#!/usr/bin/env python
"""Headline benchmark: GPT pretraining train-step throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": tokens/sec/chip, "unit": ..., "vs_baseline": ...}

vs_baseline = achieved MFU / 0.35 (BASELINE.json north-star: GPT-3 1.3B
pretraining at >=35% MFU on v5e). Falls back to smaller GPT configs if the
1.3B Adam state can't fit the chip.
"""
import json
import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def chip_peak_flops():
    """bf16 peak FLOP/s for the attached chip."""
    import jax
    kind = jax.devices()[0].device_kind.lower()
    if "v5 lite" in kind or "v5e" in kind or "lite" in kind:
        return 197e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v4" in kind:
        return 275e12
    if "v6" in kind:
        return 918e12
    return 197e12


def run_config(cfg_name, batch_size, seq_len, steps=10):
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed import build_mesh
    from paddle_tpu.distributed.trainer import Trainer
    from paddle_tpu.models import gpt as gpt_mod
    from paddle_tpu.models import GPT, GPTPretrainingCriterion

    cfg = getattr(gpt_mod, cfg_name)(max_seq_len=seq_len)
    paddle.seed(0)
    build_mesh(dp=1)
    log(f"building {cfg_name}: {cfg.num_params()/1e6:.0f}M params, "
        f"batch={batch_size} seq={seq_len}")
    model = GPT(cfg)
    model.bfloat16()
    crit = GPTPretrainingCriterion()
    opt = paddle.optimizer.AdamW(
        learning_rate=2e-4, weight_decay=0.1,
        grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0),
        accumulator_dtype="bfloat16")

    def loss_fn(m, batch):
        logits = m(paddle.to_tensor(batch["input_ids"]))
        return crit(logits, paddle.to_tensor(batch["labels"]))

    trainer = Trainer(model, opt, loss_fn)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch_size, seq_len + 1))
    batch = {"input_ids": ids[:, :-1].astype("int32"),
             "labels": ids[:, 1:].astype("int32")}

    t0 = time.time()
    loss = trainer.step(batch)
    float(loss)
    log(f"compile+first step: {time.time()-t0:.1f}s, loss={float(loss):.3f}")
    float(trainer.step(batch))  # warm

    t0 = time.time()
    for _ in range(steps):
        loss = trainer.step(batch)
    float(loss)  # sync
    dt = (time.time() - t0) / steps
    tokens_per_sec = batch_size * seq_len / dt
    n_params = cfg.num_params()
    flops_per_token = 6 * n_params  # fwd+bwd heuristic
    mfu = flops_per_token * tokens_per_sec / chip_peak_flops()
    log(f"{cfg_name}: {dt*1e3:.1f} ms/step, {tokens_per_sec:.0f} tok/s, MFU={mfu:.3f}")
    return tokens_per_sec, mfu, n_params


def main():
    attempts = [
        ("gpt_1p3b", 8, 1024),
        ("gpt_1p3b", 4, 1024),
        ("gpt_760m", 8, 1024),
        ("gpt_350m", 16, 1024),
        ("gpt_125m", 16, 1024),
    ]
    last_err = None
    for cfg_name, bs, seq in attempts:
        try:
            tok_s, mfu, n_params = run_config(cfg_name, bs, seq)
            print(json.dumps({
                "metric": f"{cfg_name}_train_tokens_per_sec_per_chip",
                "value": round(tok_s, 1),
                "unit": "tokens/s/chip",
                "vs_baseline": round(mfu / 0.35, 4),
                "mfu": round(mfu, 4),
                "params": n_params,
                "batch": bs, "seq": seq,
            }))
            return
        except Exception as e:  # OOM or tunnel issues → try smaller
            last_err = e
            log(f"{cfg_name} failed: {type(e).__name__}: {str(e)[:300]}")
    print(json.dumps({"metric": "gpt_train_tokens_per_sec_per_chip",
                      "value": 0.0, "unit": "tokens/s/chip",
                      "vs_baseline": 0.0, "error": str(last_err)[:200]}))


if __name__ == "__main__":
    main()
