#!/usr/bin/env python
"""Headline benchmark: GPT pretraining train-step throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": tokens/sec/chip, "unit": ..., "vs_baseline": ...}

vs_baseline = achieved MFU / 0.35 (BASELINE.json north-star: GPT-3 1.3B
pretraining at >=35% MFU on v5e). Falls back to smaller GPT configs if the
1.3B Adam state can't fit the chip.
"""
import json
import os
import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _stage(batch, trainer=None):
    """device_put once, outside the timed loop: steady-state training keeps
    batches device-resident via the input pipeline's async prefetch
    (io.DeviceLoader); timing a synchronous 77MB host->device copy per step
    would measure the dev tunnel, not the chip. With a trainer given the
    batch lands with the trainer's OWN GSPMD batch sharding (the layout its
    step pins via in_shardings), so the timed loop dispatches with zero
    copies and zero reshards — exactly what DeviceLoader feeds in
    production."""
    if trainer is not None:
        placed, _, _ = trainer.place_batch(batch)
        return placed
    import jax.numpy as jnp
    return {k: jnp.asarray(v) for k, v in batch.items()}


# Best-so-far JSON line for the hard-exit watchdog: if the process must be
# killed mid-wedge, the driver still gets the results banked up to that
# point rather than nothing. main() updates this as configs complete.
_PARTIAL = None


def _publish_partial(d):
    global _PARTIAL
    _PARTIAL = d


def _default_result():
    return {"metric": "gpt_train_tokens_per_sec_per_chip", "value": 0.0,
            "unit": "tokens/s/chip", "vs_baseline": 0.0}


def _alarm(seconds, label):
    """Mid-run hang guard, two layers. The init watchdog catches a tunnel
    that is dead at startup, but a tunnel that wedges MID-RUN leaves device
    syncs blocked forever (observed: gpt bs8 compiled, first step ran, then
    the 10-step measure loop never returned).

    Layer 1 — SIGALRM raising TimeoutError: works when the main thread is
    executing Python bytecode (dispatch loops, host-side work).
    Layer 2 — a backup watchdog THREAD at seconds+60: CPython only delivers
    the signal-handler exception when bytecode next runs, and a wedged jax
    sync is a C call that never returns, so the alarm alone can sail past a
    real wedge. The thread prints the best-so-far JSON line (_PARTIAL) with
    the error attached and hard-exits — the driver gets a parseable line
    either way.

    Nesting-safe: re-arms the enclosing guard's remaining time on exit.
    Signal layer is skipped off the main thread (signal restriction); the
    thread layer still applies."""
    import contextlib
    import json as _json
    import signal
    import threading

    @contextlib.contextmanager
    def guard():
        def hard_exit():
            import os
            out = dict(_PARTIAL) if _PARTIAL else _default_result()
            out["error"] = (f"{label} hard-wedged >{seconds + 60}s "
                            "(device sync never returned)")
            log(f"bench hard-exit: {out['error']}")
            print(_json.dumps(out), flush=True)
            os._exit(3)

        backup = threading.Timer(seconds + 60, hard_exit)
        backup.daemon = True
        backup.start()
        on_main = threading.current_thread() is threading.main_thread()
        old_handler = prev_remaining = None
        t0 = time.time()
        if on_main:
            def handler(signum, frame):
                raise TimeoutError(
                    f"{label} exceeded {seconds}s (TPU wedged mid-run?)")

            old_handler = signal.signal(signal.SIGALRM, handler)
            prev_remaining = signal.alarm(seconds)
        try:
            yield
        finally:
            backup.cancel()
            if on_main:
                signal.alarm(0)
                signal.signal(signal.SIGALRM, old_handler)
                if prev_remaining:  # restore the enclosing guard's budget
                    signal.alarm(max(1, int(prev_remaining -
                                            (time.time() - t0))))

    return guard()


def _measure(trainer, batch, steps, label):
    """Shared timing harness: compile+first step, one warm step, timed loop
    (async dispatch, single trailing sync). Returns seconds/step."""
    batch = _stage(batch, trainer)   # mesh-sharded, matches step in_shardings
    t0 = time.time()
    with _alarm(600, f"{label} compile+first step"):
        loss = trainer.step(batch)
        float(loss)
    log(f"{label} compile+first step: {time.time()-t0:.1f}s, loss={float(loss):.3f}")
    with _alarm(300, f"{label} measure loop"):
        float(trainer.step(batch))  # warm
        t0 = time.time()
        for _ in range(steps):
            loss = trainer.step(batch)
        float(loss)  # sync
    return (time.time() - t0) / steps


def _static_hbm(trainer, batch):
    """Static per-device peak-HBM estimate of the REAL compiled step
    (Memory Doctor liveness over the traced jaxpr, shardings + donation
    captured) — banked next to the measured throughput so a perf run
    also records how close the config sits to the HBM ceiling. Pure
    host-side tracing: no extra compile, no device work."""
    try:
        from paddle_tpu.analysis import estimate_jaxpr_memory
        program = trainer.analysis_program(batch)
        est = estimate_jaxpr_memory(program.jaxpr,
                                    arg_infos=program.arg_infos)
        log(f"static per-device peak HBM: {est.peak_bytes / 2**30:.2f} "
            f"GiB (args {est.args_bytes / 2**30:.2f}, donated credit "
            f"{est.donated_bytes / 2**30:.2f})")
        return est.peak_bytes
    except Exception as e:
        log(f"static memory estimate failed: "
            f"{type(e).__name__}: {str(e)[:200]}")
        return 0


def _fwd_flops(trainer, batch):
    """Executed FLOPs of ONE forward pass (XLA cost analysis of the traced
    loss computation): the roofline denominator for configs like detection
    or routed-MoE where a 6N params heuristic misstates the compute. Train
    step ≈ 3x forward (fwd + ~2x bwd)."""
    import jax

    from paddle_tpu.distributed.trainer import batch_to_arrays, make_compute_loss
    try:
        cl = make_compute_loss(trainer.model, trainer.loss_fn)
        lowered = jax.jit(cl).lower(trainer.params, trainer.consts,
                                    batch_to_arrays(batch))
        cost = lowered.compile().cost_analysis()
        if isinstance(cost, list):
            cost = cost[0]
        return float(cost.get("flops", 0.0))
    except Exception as e:
        log(f"fwd flops analysis failed: {type(e).__name__}: {str(e)[:200]}")
        return 0.0


def chip_peak_flops():
    """bf16 peak FLOP/s for the attached chip (table now lives in
    cost_model.CHIP_SPECS — one source for MFU, decode rooflines AND
    the autotuner's step-time model)."""
    from paddle_tpu.cost_model import chip_spec
    return chip_spec().peak_flops


def chip_hbm_bw():
    """HBM bytes/s for the attached chip (decode is bandwidth-bound).
    Same cost_model.CHIP_SPECS row as chip_peak_flops."""
    from paddle_tpu.cost_model import chip_spec
    return chip_spec().hbm_bw


def decode_roofline_tok_s(cfg, batch, avg_ctx, quant=None, kv_bytes=2):
    """Decode tokens/s ceiling from HBM bytes moved per step: every step
    reads ALL weights plus each sequence's KV cache up to its current
    length. tok/s_max = BW * batch / bytes_step. This is the honest
    denominator for decode (not MFU — the MXU idles).

    a8w8/w4a16 quantize only the per-block linears (qkv/proj/fc1/fc2)
    at 1 and 0.5 bytes/param; embeddings, position table, layernorms and
    the tied lm_head read at bf16 width (per-channel scales are a few KB
    — ignored)."""
    n = cfg.num_params()
    if quant in ("a8w8", "w4a16"):
        h, f = cfg.hidden_size, cfg.ffn_hidden
        lin = cfg.num_layers * (4 * h * h + 2 * h * f)
        per = 1 if quant == "a8w8" else 0.5
        w_bytes = lin * per + (n - lin) * 2
    else:
        w_bytes = n * 2
    kv = batch * cfg.num_layers * 2 * avg_ctx * cfg.hidden_size * kv_bytes
    return chip_hbm_bw() * batch / (w_bytes + kv)


def run_config(cfg_name, batch_size, seq_len, steps=10, remat_policy="full",
               grad_accum=1):
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed import build_mesh
    from paddle_tpu.distributed.trainer import Trainer
    from paddle_tpu.models import gpt as gpt_mod
    from paddle_tpu.models import GPT, GPTPretrainingCriterion

    cfg = getattr(gpt_mod, cfg_name)(max_seq_len=seq_len,
                                     remat_policy=remat_policy)
    paddle.seed(0)
    build_mesh(dp=1)
    log(f"building {cfg_name}: {cfg.num_params()/1e6:.0f}M params, "
        f"batch={batch_size} seq={seq_len}"
        + (f" accum={grad_accum}" if grad_accum > 1 else ""))
    model = GPT(cfg)
    model.bfloat16()
    crit = GPTPretrainingCriterion()
    opt = paddle.optimizer.AdamW(
        learning_rate=2e-4, weight_decay=0.1,
        grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0),
        accumulator_dtype="bfloat16")

    def loss_fn(m, batch):
        logits = m(paddle.to_tensor(batch["input_ids"]))
        return crit(logits, paddle.to_tensor(batch["labels"]))

    trainer = Trainer(model, opt, loss_fn, grad_accum_steps=grad_accum)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch_size, seq_len + 1))
    batch = {"input_ids": ids[:, :-1].astype("int32"),
             "labels": ids[:, 1:].astype("int32")}
    static_hbm = _static_hbm(trainer, batch)
    dt = _measure(trainer, batch, steps, cfg_name)   # _measure stages
    tokens_per_sec = batch_size * seq_len / dt
    n_params = cfg.num_params()
    flops_per_token = 6 * n_params  # fwd+bwd heuristic
    mfu = flops_per_token * tokens_per_sec / chip_peak_flops()
    log(f"{cfg_name}: {dt*1e3:.1f} ms/step, {tokens_per_sec:.0f} tok/s, MFU={mfu:.3f}")
    return tokens_per_sec, mfu, n_params, static_hbm


def run_resnet50(batch_size=128, steps=10):
    """BASELINE.json config 1: ResNet-50 train step, imgs/sec/chip."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed import build_mesh
    from paddle_tpu.distributed.trainer import Trainer

    paddle.seed(0)
    build_mesh(dp=1)
    # NHWC: the TPU-native layout (channels on the lane dim) — NCHW makes
    # XLA materialize transposes around every conv
    model = paddle.vision.models.resnet50(num_classes=1000, data_format="NHWC")
    model.bfloat16()
    model.train()
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    weight_decay=1e-4)

    def loss_fn(m, batch):
        logits = m(paddle.to_tensor(batch["image"]))
        return paddle.nn.functional.cross_entropy(
            logits, paddle.to_tensor(batch["label"]))

    trainer = Trainer(model, opt, loss_fn)
    rng = np.random.RandomState(0)
    batch = {"image": rng.randn(batch_size, 224, 224, 3).astype("float32"),
             "label": rng.randint(0, 1000, (batch_size,)).astype("int64")}
    dt = _measure(trainer, batch, steps, "resnet50")
    imgs_s = batch_size / dt
    # ~4.09e9 MACs fwd at 224^2 -> 8.2 GFLOP fwd, x3 for train
    mfu = 3 * 8.2e9 * imgs_s / chip_peak_flops()
    log(f"resnet50: {dt*1e3:.1f} ms/step, {imgs_s:.0f} imgs/s, MFU={mfu:.3f}")
    return imgs_s, mfu


def run_bert_base(batch_size=32, seq_len=512, steps=10):
    """BASELINE.json config 2: BERT-base MLM+NSP pretraining, seqs/sec/chip."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed import build_mesh
    from paddle_tpu.distributed.trainer import Trainer
    from paddle_tpu.models.bert import (
        BertForPretraining,
        BertPretrainingCriterion,
        bert_base,
    )

    paddle.seed(0)
    build_mesh(dp=1)
    cfg = bert_base(dtype="bfloat16")
    model = BertForPretraining(cfg)
    model.bfloat16()
    model.train()
    crit = BertPretrainingCriterion(cfg.vocab_size)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                                 accumulator_dtype="bfloat16")

    def loss_fn(m, batch):
        mlm_logits, nsp_logits = m(paddle.to_tensor(batch["input_ids"]),
                                   attention_mask=paddle.to_tensor(batch["attention_mask"]))
        return crit(mlm_logits, nsp_logits,
                    paddle.to_tensor(batch["mlm_labels"]),
                    paddle.to_tensor(batch["nsp_labels"]))

    trainer = Trainer(model, opt, loss_fn)
    rng = np.random.RandomState(0)
    labels = rng.randint(0, cfg.vocab_size, (batch_size, seq_len))
    labels[rng.rand(batch_size, seq_len) > 0.15] = -100  # MLM masking rate
    # ~12% padding per sequence: masked flash attention is the measured path
    lengths = rng.randint(int(seq_len * 0.75), seq_len + 1, (batch_size,))
    attn_mask = (np.arange(seq_len)[None, :] < lengths[:, None])
    batch = {"input_ids": rng.randint(0, cfg.vocab_size,
                                      (batch_size, seq_len)).astype("int32"),
             "attention_mask": attn_mask.astype("int32"),  # [B, L]: model expands
             "mlm_labels": labels.astype("int32"),
             "nsp_labels": rng.randint(0, 2, (batch_size,)).astype("int64")}
    dt = _measure(trainer, batch, steps, "bert_base")
    seqs_s = batch_size / dt
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    mfu = 6 * n_params * seqs_s * seq_len / chip_peak_flops()
    log(f"bert_base: {dt*1e3:.1f} ms/step, {seqs_s:.1f} seqs/s, MFU={mfu:.3f}")
    return seqs_s, mfu


def run_yolov3(batch_size=16, size=320, steps=10):
    """BASELINE.json config 4: PP-OCR/detection family — YOLOv3-DarkNet53
    train step, imgs/sec/chip."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed import build_mesh
    from paddle_tpu.distributed.trainer import Trainer
    from paddle_tpu.vision.models import yolov3_darknet53

    paddle.seed(0)
    build_mesh(dp=1)
    model = yolov3_darknet53(num_classes=80, data_format="NHWC")
    model.bfloat16()
    model.train()
    opt = paddle.optimizer.Momentum(learning_rate=0.01, momentum=0.9,
                                    weight_decay=5e-4)

    def loss_fn(m, b):
        outs = m(paddle.to_tensor(b["image"]))
        return m.loss(outs, paddle.to_tensor(b["gt_box"]),
                      paddle.to_tensor(b["gt_label"]))

    trainer = Trainer(model, opt, loss_fn)
    rng = np.random.RandomState(0)
    nb = 8
    batch = {"image": rng.randn(batch_size, size, size, 3).astype("float32"),
             "gt_box": np.clip(rng.rand(batch_size, nb, 4) * 0.5 + 0.1, 0, 1)
             .astype("float32"),
             "gt_label": rng.randint(0, 80, (batch_size, nb)).astype("int32")}
    fwd = _fwd_flops(trainer, batch)
    dt = _measure(trainer, batch, steps, "yolov3")
    imgs_s = batch_size / dt
    # roofline: measured fwd FLOPs x3 for train (bwd ~2x fwd)
    mfu = 3 * fwd / batch_size * imgs_s / chip_peak_flops() if fwd else 0.0
    log(f"yolov3: {dt*1e3:.1f} ms/step, {imgs_s:.0f} imgs/s, MFU={mfu:.3f} "
        f"(fwd {fwd/batch_size/1e9:.1f} GFLOP/img)")
    return imgs_s, mfu


def run_crnn(batch_size=64, width=320, steps=10):
    """BASELINE.json config 4, OCR half — CRNN recognition (CTC) train
    step at PP-OCR's 32xW crop shape, imgs/sec/chip."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed import build_mesh
    from paddle_tpu.distributed.trainer import Trainer
    from paddle_tpu.vision.models import CRNN

    paddle.seed(0)
    build_mesh(dp=1)
    model = CRNN(num_classes=97, data_format="NHWC")
    model.bfloat16()
    model.train()
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                accumulator_dtype="bfloat16")

    def loss_fn(m, b):
        logits = m(paddle.to_tensor(b["image"]))
        return m.loss(logits, paddle.to_tensor(b["label"]),
                      paddle.to_tensor(b["length"]))

    trainer = Trainer(model, opt, loss_fn)
    rng = np.random.RandomState(0)
    # CTC needs T (=width/4 columns) comfortably above the label length
    max_len = max(2, min(24, width // 16))
    lens = rng.randint(max(1, max_len // 4), max_len + 1, batch_size)
    labels = rng.randint(1, 97, (batch_size, max_len))
    labels *= (np.arange(max_len)[None, :] < lens[:, None])
    batch = {
        "image": rng.randn(batch_size, 32, width, 3).astype("float32"),
        "label": labels.astype("int32"),
        "length": lens.astype("int32")}
    fwd = _fwd_flops(trainer, batch)
    dt = _measure(trainer, batch, steps, "crnn")
    imgs_s = batch_size / dt
    mfu = 3 * fwd / batch_size * imgs_s / chip_peak_flops() if fwd else 0.0
    log(f"crnn: {dt*1e3:.1f} ms/step, {imgs_s:.0f} imgs/s, MFU={mfu:.3f} "
        f"(fwd {fwd/batch_size/1e9:.2f} GFLOP/img)")
    return imgs_s, mfu


def run_gpt_moe(batch_size=8, seq_len=1024, steps=10, gate=None):
    """BASELINE.json config 5: GPT-MoE (top-2 routed experts), tokens/s/chip.
    Single-chip: measures the dispatch/combine einsums + expert FFs; the ep
    mesh path is validated by dryrun_multichip and tests/test_moe.py.
    Gate family selectable via arg or PADDLE_TPU_MOE_GATE=topk|switch|gshard."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed import build_mesh
    from paddle_tpu.distributed.trainer import Trainer
    from paddle_tpu.models import GPTMoE, GPTPretrainingCriterion
    from paddle_tpu.models.moe import gpt_moe_small

    paddle.seed(0)
    build_mesh(dp=1)
    gate = gate or os.environ.get("PADDLE_TPU_MOE_GATE", "topk")
    cfg = gpt_moe_small(max_seq_len=seq_len, gate=gate)
    model = GPTMoE(cfg)
    model.bfloat16()
    crit = GPTPretrainingCriterion()
    opt = paddle.optimizer.AdamW(learning_rate=2e-4,
                                 accumulator_dtype="bfloat16")

    def loss_fn(m, b):
        logits = m(paddle.to_tensor(b["input_ids"]))
        return crit(logits, paddle.to_tensor(b["labels"])) + m.aux_loss()

    trainer = Trainer(model, opt, loss_fn)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch_size, seq_len + 1))
    batch = {"input_ids": ids[:, :-1].astype("int32"),
             "labels": ids[:, 1:].astype("int32")}
    dt = _measure(trainer, batch, steps, "gpt_moe")
    tok_s = batch_size * seq_len / dt
    # roofline on ACTIVATED params (top_k of E experts): 6N_active per token
    n_active = cfg.num_active_params()
    mfu = 6 * n_active * tok_s / chip_peak_flops()
    log(f"gpt_moe: {dt*1e3:.1f} ms/step, {tok_s:.0f} tok/s, MFU={mfu:.3f} "
        f"({n_active/1e6:.0f}M active / {cfg.num_params()/1e6:.0f}M total)")
    return tok_s, mfu


def run_decode(batch=8, prompt_len=128, gen=128, quant=None):
    """Serving decode throughput: continuous-batching greedy decode over
    the paged-KV Pallas kernel (GPT-1.3B bf16, falls back to 350M/125M if
    the chip can't hold it). Reported as generated tokens/sec/chip."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed import build_mesh
    from paddle_tpu.models import GPT, gpt_125m, gpt_350m, gpt_1p3b
    from paddle_tpu.serving import ContinuousBatchingEngine, PagedGPTDecoder

    paddle.seed(0)
    build_mesh(dp=1)
    rng = np.random.RandomState(0)
    last_err = None
    import os
    models = (gpt_1p3b, gpt_350m, gpt_125m)
    if os.environ.get("PADDLE_TPU_BENCH_SMOKE"):
        from paddle_tpu.models import gpt_tiny
        models = (gpt_tiny,)
        batch, prompt_len, gen = 2, 16, 8
    for mk in models:
        try:
            cfg = mk(max_seq_len=max(512, prompt_len + gen))
            model = GPT(cfg)
            model.bfloat16()
            model.eval()
            page_size = 32
            pages_per_seq = (prompt_len + gen + page_size - 1) // page_size
            dec = PagedGPTDecoder(
                model, num_pages=batch * pages_per_seq + 2,
                page_size=page_size, max_batch=batch, quant=quant,
                use_kernel=True)

            def run_batch(step_times=None):
                eng = ContinuousBatchingEngine(dec, max_new_tokens=gen)
                for _ in range(batch):
                    eng.submit(rng.randint(
                        0, cfg.vocab_size, prompt_len).astype(np.int32))
                return eng.run(step_times=step_times), eng

            t0 = time.time()
            run_batch()              # compile prefill bucket + decode step
            log(f"decode[{mk.__name__}] compile+first batch: "
                f"{time.time()-t0:.1f}s")
            steps = []
            t0 = time.time()
            outs, eng = run_batch(steps)
            dt = time.time() - t0
            n_tok = sum(len(v) for v in outs.values())
            tok_s = n_tok / dt
            # HBM roofline at the mean context length of the run
            ceil = decode_roofline_tok_s(cfg, batch, prompt_len + gen / 2,
                                         quant=quant)
            # per-token p50/p99 come from ServeStats (wall per emitted
            # token). The first step_times entry contains the full-batch
            # prefill — orders of magnitude more work than a decode
            # tick — so it's reported separately, not in the
            # percentiles; on the multi-step path that first sync also
            # spans the first K-tick horizon (the engine overlaps fetch
            # with the next dispatch), hence "first_sync" not
            # "admission"
            summary = eng.stats.summary()
            lat = {
                "p50_ms": summary.get("token_p50_ms", 0.0),
                "p99_ms": summary.get("token_p99_ms", 0.0),
                "first_sync_ms": round(steps[0] * 1e3, 2),
            }
            log(f"decode[{mk.__name__}{'/' + quant if quant else ''}]: "
                f"{n_tok} tokens in {dt:.2f}s = {tok_s:.0f} tok/s "
                f"({tok_s / ceil:.0%} of {ceil:.0f} tok/s HBM roofline; "
                f"per-token p50 {lat['p50_ms']}ms p99 {lat['p99_ms']}ms; "
                f"K={eng.k_max}, "
                f"{summary['host_syncs_per_token']:.3f} host syncs/token; "
                f"batch={batch}, prompt={prompt_len}, gen={gen})")
            return {"tok_s": tok_s, "model": mk.__name__,
                    "vs_roofline": round(tok_s / ceil, 4),
                    "roofline_tok_s": round(ceil, 1), "latency": lat,
                    "k_max": eng.k_max,
                    "host_syncs_per_token":
                        summary["host_syncs_per_token"]}
        except TimeoutError:
            # the _alarm wrapping this whole call fired: one-shot, so the
            # fallback model would run unguarded — propagate instead. Null
            # the HBM-pinning locals first: the raised traceback keeps this
            # frame alive, and a still-referenced 1.3B model would OOM the
            # caller's next quant variant.
            model = dec = run_batch = cfg = eng = None
            import gc
            gc.collect()
            raise
        except Exception as e:
            last_err = f"{type(e).__name__}: {str(e)[:200]}"
            log(f"decode {mk.__name__} failed: {last_err}")
            # the failed attempt's weights/pages must be freed BEFORE the
            # smaller model allocates, or the fallback OOMs too
            model = dec = run_batch = cfg = eng = None
            del e
            import gc
            gc.collect()
    raise RuntimeError(last_err or "decode bench failed")


def run_prefix_cache(n_requests=24, prompt_len=44, gen=4, zipf_a=1.2):
    """Prefix-cache serving scenario: requests draw a shared prompt
    template from a Zipf distribution (the real-fleet shape: a few
    system prompts / few-shot templates dominate) and append a private
    suffix. Sweeps the template pool size — unique prompts (hit rate 0)
    up to one universal template — on ONE decoder (compiles shared
    across scenarios; each scenario gets a fresh engine + cache) and
    reports achieved hit rate vs TTFT and prefill FLOPs. Requests run
    sequentially so TTFT is per-request clean. CPU-runnable (tiny GPT):
    the committed evidence is the CURVE — TTFT and prefill FLOPs
    decreasing monotonically with hit rate — not the absolute ms."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed import build_mesh
    from paddle_tpu.models import GPT, gpt_tiny
    from paddle_tpu.serving import (ContinuousBatchingEngine,
                                    PagedGPTDecoder, PrefixCache)

    paddle.seed(0)
    build_mesh(dp=1)
    cfg = gpt_tiny(max_seq_len=max(128, prompt_len + gen + 16),
                   dtype="float32", remat=False)
    model = GPT(cfg)
    model.eval()
    page_size = 16
    pages_per_seq = (prompt_len + gen + page_size - 1) // page_size
    dec = PagedGPTDecoder(model, num_pages=8 * pages_per_seq + 2,
                          page_size=page_size, max_batch=2)
    fpt = 2 * cfg.num_params()       # matmul FLOPs per prefill token
    # block-aligned shared prefix + PARTIAL-block private suffix (a
    # partial trailing block is never cacheable, so unique suffixes
    # can't pollute the cache and the max hit rate approaches 1)
    prefix_len = (prompt_len // page_size) * page_size
    if prefix_len >= prompt_len:
        prefix_len -= page_size
    suffix_len = prompt_len - prefix_len
    rng = np.random.RandomState(0)

    def scenario(n_templates):
        cache = PrefixCache(page_size, salt=dec.cache_fingerprint())
        eng = ContinuousBatchingEngine(dec, max_new_tokens=gen,
                                       prefix_cache=cache)
        templates = [rng.randint(0, cfg.vocab_size, prefix_len).tolist()
                     for _ in range(max(n_templates, 1))]
        total_prompt = 0
        for _ in range(n_requests):
            if n_templates == 0:     # no sharing: every prefix unique
                prefix = rng.randint(0, cfg.vocab_size,
                                     prefix_len).tolist()
            else:
                z = min(int(rng.zipf(zipf_a)), len(templates)) - 1
                prefix = templates[z]
            suffix = rng.randint(0, cfg.vocab_size, suffix_len).tolist()
            eng.submit(np.asarray(prefix + suffix, np.int32))
            eng.run()                # sequential: clean per-request TTFT
            total_prompt += prompt_len
        s = eng.stats
        computed = total_prompt - s.prefix_tokens_saved
        return {"templates": n_templates,
                "hit_rate": round(s.prefix_hit_rate, 4),
                # MEAN, not p50: TTFT = miss_frac * t_full +
                # hit_frac * t_suffix, so the mean tracks the hit rate
                # structurally; p50 collapses to the hit path as soon
                # as hits pass 50% and stops moving
                "ttft_ms": round(float(np.mean(s.ttft_s)) * 1e3, 2),
                "ttft_p50_ms": round(
                    float(np.percentile(s.ttft_s, 50)) * 1e3, 2),
                "prefill_flops": int(computed * fpt),
                "prefill_flops_saved": int(s.prefix_tokens_saved * fpt),
                "prefix_tokens_saved": int(s.prefix_tokens_saved),
                "evictions": s.prefix_evictions,
                "cow": s.prefix_cow}

    scenario(1)                      # warm every bucket compile
    rows = sorted((scenario(n) for n in (0, 8, 2, 1)),
                  key=lambda r: r["hit_rate"])
    for r in rows:
        log(f"prefix[{r['templates']} templates]: hit_rate "
            f"{r['hit_rate']:.2f}, ttft mean {r['ttft_ms']}ms "
            f"(p50 {r['ttft_p50_ms']}ms), "
            f"prefill {r['prefill_flops']:.3g} FLOPs "
            f"(saved {r['prefill_flops_saved']:.3g}; "
            f"{r['evictions']} evictions)")
        print(json.dumps({"metric": "gpt_prefill_ttft_vs_hit_rate",
                          "value": r["ttft_ms"], "unit": "ms",
                          **r}), flush=True)
    best = rows[-1]
    print(json.dumps({"metric": "gpt_prefill_flops_saved",
                      "value": best["prefill_flops_saved"],
                      "unit": "FLOPs",
                      "hit_rate": best["hit_rate"],
                      "ttft_ms": best["ttft_ms"],
                      "n_requests": n_requests,
                      "prompt_len": prompt_len}), flush=True)
    return rows


def run_kv_tier(n_requests=48, prompt_len=44, gen=4, zipf_s=0.7,
                n_templates=12):
    """Tiered-KV serving scenario: the SAME Zipf shared-template
    workload as run_prefix_cache, but with a template working set that
    does NOT fit the page pool — the failure mode production fleets
    hit at scale. Three measured runs:

      fits   — a pool big enough to park every template (the
               reference hit rate: only first-touch misses),
      cliff  — a small pool, no tier: eviction at the HBM cliff
               destroys parked templates and the hit rate collapses,
      tiered — the SAME small pool + a HostKVTier: evictions demote
               to host RAM and later admissions RESTORE, so the hit
               rate stays within 10% of `fits` (the acceptance bar).

    All three emit byte-identical streams (asserted — pool size, tier
    and spills never change a token). The restore policy is pinned
    "restore" here: the auto policy prices tiny-model recompute
    cheaper than the PCIe wire (correctly — the decision flips with
    model scale, unit-tested in tests/test_kv_tier.py), and the CPU
    bench's claim is the no-cliff hit-rate curve, not the pricing."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed import build_mesh
    from paddle_tpu.models import GPT, gpt_tiny
    from paddle_tpu.serving import (ContinuousBatchingEngine, HostKVTier,
                                    PagedGPTDecoder, PrefixCache)

    paddle.seed(0)
    build_mesh(dp=1)
    cfg = gpt_tiny(max_seq_len=max(128, prompt_len + gen + 16),
                   dtype="float32", remat=False)
    model = GPT(cfg)
    model.eval()
    page_size = 16
    pages_per_seq = (prompt_len + gen + page_size - 1) // page_size
    prefix_len = (prompt_len // page_size) * page_size
    if prefix_len >= prompt_len:
        prefix_len -= page_size
    suffix_len = prompt_len - prefix_len
    blocks_per_template = prefix_len // page_size
    # fits: every template parks + one active request; small: ~3
    # templates' worth of parked pages — the working set is >3x it
    fits_pages = n_templates * blocks_per_template + pages_per_seq + 2
    small_pages = 2 * blocks_per_template + pages_per_seq + 2
    rng0 = np.random.RandomState(0)
    templates = [rng0.randint(0, cfg.vocab_size, prefix_len).tolist()
                 for _ in range(n_templates)]

    # explicit Zipf(s) weights over the template ranks (rng.zipf with
    # a near 1 degenerates under the clamp — most draws exceed the
    # pool and pile onto one index): s=0.7 is the flat-ish head/tail
    # mix where the whole working set stays live — the regime where a
    # small pool's LRU actually thrashes
    probs = np.array([1.0 / (i + 1) ** zipf_s
                      for i in range(n_templates)])
    probs /= probs.sum()

    def workload():
        rng = np.random.RandomState(1)
        for _ in range(n_requests):
            z = int(rng.choice(n_templates, p=probs))
            suffix = rng.randint(0, cfg.vocab_size, suffix_len).tolist()
            yield templates[z] + suffix

    def scenario(num_pages, tier=None, policy="auto"):
        dec = PagedGPTDecoder(model, num_pages=num_pages,
                              page_size=page_size, max_batch=2)
        cache = PrefixCache(page_size, salt=dec.cache_fingerprint(),
                            tier=tier)
        eng = ContinuousBatchingEngine(dec, max_new_tokens=gen,
                                       prefix_cache=cache,
                                       tier_policy=policy)
        outs = []
        for prompt in workload():
            rid = eng.submit(np.asarray(prompt, np.int32))
            outs.append(eng.run()[rid])   # sequential: clean TTFT
        assert eng.audit_pages() == [], "page ledger audit failed"
        s = eng.stats
        return {"num_pages": num_pages,
                "hit_rate": round(s.prefix_hit_rate, 4),
                "ttft_ms": round(float(np.mean(s.ttft_s)) * 1e3, 2),
                "evictions": s.prefix_evictions,
                "tier_spills": s.tier_spills,
                "tier_restores": s.tier_restores,
                "tier_recomputes": s.tier_recomputes,
                "host_tier_bytes": s.host_tier_bytes}, outs

    fits, out_f = scenario(fits_pages)
    cliff, out_c = scenario(small_pages)
    tiered, out_t = scenario(small_pages, tier=HostKVTier(),
                             policy="restore")
    # pool size, eviction and the tier never change a token
    assert out_f == out_c == out_t, "streams diverged across tiers"
    for name, r in (("fits", fits), ("cliff", cliff),
                    ("tiered", tiered)):
        log(f"kv_tier[{name}]: pool {r['num_pages']} pages, hit_rate "
            f"{r['hit_rate']:.3f}, ttft mean {r['ttft_ms']}ms, "
            f"{r['evictions']} evictions, {r['tier_spills']} spills / "
            f"{r['tier_restores']} restores")
    row = {"metric": "gpt_prefix_hit_rate_tiered",
           "value": tiered["hit_rate"], "unit": "hit_rate",
           "fits_hit_rate": fits["hit_rate"],
           "cliff_hit_rate": cliff["hit_rate"],
           "tier_spills": tiered["tier_spills"],
           "tier_restores": tiered["tier_restores"],
           "host_tier_bytes": tiered["host_tier_bytes"],
           "n_requests": n_requests, "n_templates": n_templates,
           "small_pool_pages": small_pages, "fits_pool_pages": fits_pages,
           "streams_equal": True,
           # the acceptance bar: no eviction cliff with the tier on
           "within_10pct_of_fits":
               bool(tiered["hit_rate"] >= 0.9 * fits["hit_rate"])}
    print(json.dumps(row), flush=True)
    return {"fits": fits, "cliff": cliff, "tiered": tiered, **row}


def run_fleet(n_replicas=3, n_requests=48, n_templates=8, template_len=32,
              suffix_len=12, gen=32, zipf_s=0.7, waves=5):
    """Fleet serving scenario (serving.fleet): the Zipf shared-template
    workload from run_kv_tier, served by a FleetRouter over N engine
    replicas that share ONE host KV tier. Three measured fleets:

      N=1        — a single replica: the per-core reference rate,
      N   (seq)  — N replicas drained round-robin: pure routing and
                   shared-tier overhead, no thread concurrency,
      N   (par)  — N replicas on threads: the production topology.

    All three emit byte-identical streams (asserted — the router's
    global rid order makes fleet size invisible to the bytes). Each
    fleet serves `waves` identical request waves and the LAST wave is
    the timed one: each replica owns its own jit cache and sees ~1/N
    of the traffic, so rare ragged shapes compile stragglers for
    several waves — timing an early wave measures XLA, not serving.

    The scaling bar is honest about the host: ideal aggregate rate is
    tok_s_1 x min(N, cpu_cores) — on a 1-core box N replicas time-
    slice one core and the ideal is flat, while on an N-core box it
    is linear. The acceptance bar is >=0.8x that ideal.

    The page pool is sized at the HBM cliff for ONE replica: the
    single engine can't park the whole template working set, so it
    spills to the shared tier and restores on re-admission (the tier
    stats prove the tier leg ran). The fleet's prefix-affinity
    routing splits the working set N ways, each replica's share fits,
    and the cliff disappears — the second fleet-scale effect beyond
    raw throughput. The restore policy is pinned (see run_kv_tier on
    why auto correctly recomputes at toy scale)."""
    import tempfile

    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed import build_mesh
    from paddle_tpu.models import GPT, gpt_tiny
    from paddle_tpu.serving import (FleetRouter, PagedGPTDecoder,
                                    PrefixCache, SharedHostKVTier,
                                    TenantEngine)

    paddle.seed(0)
    build_mesh(dp=1)
    cfg = gpt_tiny(max_seq_len=max(128, template_len + suffix_len + gen),
                   dtype="float32", remat=False)
    model = GPT(cfg)
    model.eval()
    page_size = 16
    rng0 = np.random.RandomState(0)
    templates = [rng0.randint(0, cfg.vocab_size, template_len).tolist()
                 for _ in range(n_templates)]
    probs = np.array([1.0 / (i + 1) ** zipf_s
                      for i in range(n_templates)])
    probs /= probs.sum()

    def wave(seed):
        rng = np.random.RandomState(seed)
        out = []
        for _ in range(n_requests):
            z = int(rng.choice(n_templates, p=probs))
            suffix = rng.randint(0, cfg.vocab_size, suffix_len).tolist()
            out.append(templates[z] + suffix)
        return out

    def build_fleet(n):
        tier_dir = tempfile.mkdtemp(prefix="bench_fleet_tier_")
        engines = []
        for _ in range(n):
            dec = PagedGPTDecoder(model, num_pages=24,
                                  page_size=page_size, max_batch=4)
            tier = SharedHostKVTier(tier_dir, capacity_bytes=64 << 20,
                                    fingerprint=dec)
            cache = PrefixCache(page_size, salt=dec.cache_fingerprint(),
                                tier=tier)
            engines.append(TenantEngine(dec, max_new_tokens=gen,
                                        prefix_cache=cache,
                                        tier_policy="restore"))
        return FleetRouter(engines)

    def scenario(n, parallel):
        r = build_fleet(n)
        toks = dt = 0
        streams = None
        for w in range(waves):
            gids = [r.submit(p) for p in wave(1 + w)]
            t0 = time.perf_counter()
            out = r.run(parallel=parallel)
            dt = time.perf_counter() - t0
            toks = sum(len(out[g]) for g in gids)
            streams = [out[g] for g in gids]
        s = r.merged_stats().summary()
        tier = r.engines[0].cache.tier
        res = {"replicas": n, "parallel": parallel,
               "tok_s": round(toks / dt, 1),
               "wave_s": round(dt, 3),
               "hit_rate": round(s.get("prefix_hit_rate", 0.0), 4),
               "tier_spills": s.get("tier_spills", 0),
               "tier_restores": s.get("tier_restores", 0),
               "tier_entries": tier.n_entries,
               "tier_bytes": tier.bytes_used}
        return res, streams

    one, out_1 = scenario(1, parallel=False)
    seq, out_s = scenario(n_replicas, parallel=False)
    par, out_p = scenario(n_replicas, parallel=True)
    # fleet size, drain order and threading never change a token
    assert out_1 == out_s == out_p, "streams diverged across fleet sizes"
    cores = os.cpu_count() or 1
    ideal = one["tok_s"] * min(n_replicas, cores)
    eff = par["tok_s"] / ideal if ideal else 0.0
    for name, r in (("1", one), (f"{n_replicas}seq", seq),
                    (f"{n_replicas}par", par)):
        log(f"fleet[{name}]: {r['tok_s']} tok/s steady wave "
            f"({r['wave_s']}s), hit_rate {r['hit_rate']:.3f}, "
            f"{r['tier_spills']} spills / {r['tier_restores']} "
            f"restores, shared tier {r['tier_entries']} entries / "
            f"{r['tier_bytes']}B")
    log(f"fleet: scaling {par['tok_s']:.0f} / ideal {ideal:.0f} "
        f"(tok_s_1 x min({n_replicas}, {cores} cores)) = {eff:.2f}x")
    row = {"metric": "gpt_fleet_tokens_per_sec", "value": par["tok_s"],
           "unit": "tokens/s", "replicas": n_replicas,
           "tok_s_1": one["tok_s"], "tok_s_n_seq": seq["tok_s"],
           "cores": cores, "ideal_tok_s": round(ideal, 1),
           "scaling_efficiency": round(eff, 3),
           "hit_rate": par["hit_rate"],
           "hit_rate_1": one["hit_rate"],
           "tier_restores_1": one["tier_restores"],
           "shared_tier_entries_1": one["tier_entries"],
           "n_requests": n_requests, "waves": waves,
           "streams_equal": True,
           "linear_at_0_8": bool(eff >= 0.8)}
    print(json.dumps(row), flush=True)
    return {"one": one, "seq": seq, "par": par, **row}


def run_multi_tenant(n_throughput=16, n_latency=5, prompt_len=24,
                     lat_prompt_len=36, gen=16, n_adapters=3):
    """Bursty multi-tenant serving scenario (serving.tenancy): a
    throughput-tier FLOOD (n_throughput requests from batch tenants,
    rotating over n_adapters LoRA variants on shared base weights)
    saturates a small pool, while latency-tier chat requests arrive
    MID-STREAM at deterministic points in the token stream. Two
    engines serve the identical workload:

      blind  — the class-blind `ContinuousBatchingEngine`: every
               request FIFOs through the same queue, so a latency
               arrival waits out the backlog (its TTFT tail IS the
               flood drain time),
      tenant — the `TenantEngine`: latency requests admit ahead of the
               backlog, preempt throughput victims by page-spill when
               the pool is full (pages park in the prefix cache,
               victims resume byte-identically), and horizons compose
               per class (`TenantScheduler`).

    The headline is latency-tier TTFT p99 under the flood — the
    acceptance bar is >= 2x better than the class-blind engine at
    comparable aggregate tokens/s (>= 0.85x; the tenant engine does
    the same total work plus preemption overhead). Every request's
    stream is asserted byte-identical across the two engines (the
    preempted-and-resumed victims included), and the page ledger
    (slot_adapters rows included) audits clean. TTFT is measured
    client-side (submit -> first token observed at a sync), so both
    engines are scored by the same clock."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed import build_mesh
    from paddle_tpu.models import GPT, gpt_tiny
    from paddle_tpu.serving import (SLO_LATENCY, SLO_THROUGHPUT,
                                    ContinuousBatchingEngine,
                                    PagedGPTDecoder, PrefixCache,
                                    TenantEngine, make_lora_bank)

    paddle.seed(0)
    build_mesh(dp=1)
    cfg = gpt_tiny(max_seq_len=max(128, lat_prompt_len + gen + 16),
                   dtype="float32", remat=False)
    model = GPT(cfg)
    model.eval()
    page_size = 16
    bank = make_lora_bank(cfg, n_adapters, rank=4, seed=9)
    rng = np.random.RandomState(1)
    V = cfg.vocab_size
    tp_prompts = [rng.randint(0, V, prompt_len).tolist()
                  for _ in range(n_throughput)]
    lat_prompts = [rng.randint(0, V, lat_prompt_len).tolist()
                   for _ in range(n_latency)]
    tp_adapters = [1 + i % n_adapters for i in range(n_throughput)]
    # latency arrivals at deterministic TOKEN-COUNT points spread over
    # the flood's drain — the same thresholds drive both engines, so
    # the burst pattern is identical
    approx_total = (n_throughput + n_latency) * gen
    arrive_at = [int(approx_total * (i + 1) / (n_latency + 2))
                 for i in range(n_latency)]
    # 2 slots x 2-page throughput requests fill a 7-page pool; a
    # 3-page latency arrival must preempt
    num_pages = 7

    def scenario(tenant_aware):
        dec = PagedGPTDecoder(model, num_pages=num_pages,
                              page_size=page_size, max_batch=2)
        dec.attach_adapters(bank)
        cache = PrefixCache(page_size, salt=dec.cache_fingerprint())
        cls = TenantEngine if tenant_aware else ContinuousBatchingEngine
        eng = cls(dec, max_new_tokens=gen, prefix_cache=cache)
        rids = []
        for i, p in enumerate(tp_prompts):
            kw = (dict(tenant=f"batch{i % 2}", slo=SLO_THROUGHPUT)
                  if tenant_aware else {})
            rids.append(eng.submit(np.asarray(p, np.int32),
                                   adapter=tp_adapters[i], **kw))
        lat_rids = []
        state = {"submit_t": {}, "ttft": {}, "next": 0}

        def on_sync(e):
            now = time.perf_counter()
            while state["next"] < n_latency and \
                    e.stats.tokens >= arrive_at[state["next"]]:
                j = state["next"]
                kw = (dict(tenant="chat", slo=SLO_LATENCY)
                      if tenant_aware else {})
                r = e.submit(np.asarray(lat_prompts[j], np.int32),
                             **kw)
                lat_rids.append(r)
                state["submit_t"][r] = now
                state["next"] += 1
            for r, t0 in state["submit_t"].items():
                if r not in state["ttft"] and e._outputs.get(r):
                    state["ttft"][r] = now - t0

        t0 = time.perf_counter()
        outs = eng.run(on_sync=on_sync)
        wall = time.perf_counter() - t0
        assert eng.audit_pages() == [], "page ledger audit failed"
        assert len(state["ttft"]) == n_latency, \
            "a latency request never produced a token"
        ttfts = [state["ttft"][r] for r in lat_rids]
        res = {"lat_ttft_p50_ms":
               round(float(np.percentile(ttfts, 50)) * 1e3, 2),
               "lat_ttft_p99_ms":
               round(float(np.percentile(ttfts, 99)) * 1e3, 2),
               "agg_tok_s": round(eng.stats.tokens / wall, 1),
               "preemptions": eng.stats.preemptions,
               "resumes": eng.stats.resumes}
        if tenant_aware:
            res["tenancy"] = eng.tenancy_summary()
        streams = [outs[r] for r in rids] + [outs[r] for r in lat_rids]
        return res, streams

    blind, out_b = scenario(False)
    tenant, out_t = scenario(True)
    # classes, preemption and resume never change a token
    assert out_b == out_t, "streams diverged blind vs tenant-aware"
    assert tenant["preemptions"] > 0, \
        "flood never forced a preemption — scenario too gentle"
    speedup = blind["lat_ttft_p99_ms"] / max(tenant["lat_ttft_p99_ms"],
                                             1e-9)
    for name, r in (("blind", blind), ("tenant", tenant)):
        log(f"multi_tenant[{name}]: latency-tier ttft p99 "
            f"{r['lat_ttft_p99_ms']}ms (p50 {r['lat_ttft_p50_ms']}ms), "
            f"{r['agg_tok_s']} tok/s aggregate, "
            f"{r['preemptions']} preemptions")
    row = {"metric": "gpt_decode_mt_p99_ms",
           "value": tenant["lat_ttft_p99_ms"], "unit": "ms",
           "blind_p99_ms": blind["lat_ttft_p99_ms"],
           "p99_speedup": round(speedup, 2),
           "agg_tok_s_ratio": round(tenant["agg_tok_s"] /
                                    max(blind["agg_tok_s"], 1e-9), 3),
           "preemptions": tenant["preemptions"],
           "resumes": tenant["resumes"],
           "n_throughput": n_throughput, "n_latency": n_latency,
           "n_adapters": n_adapters,
           "tenancy": tenant["tenancy"],
           "streams_equal": True,
           # the acceptance bar: >=2x latency-tier p99 at comparable
           # aggregate throughput
           "meets_2x_bar": bool(speedup >= 2.0)}
    print(json.dumps(row), flush=True)
    return {"blind": blind, "tenant": tenant, **row}


def run_ragged_stall(gen=48, long_prompt=448, chunk=16, k_max=2):
    """Long-prompt-arrival serving scenario: decode p99 per-token
    latency of an ALREADY-RUNNING slot while a long prompt streams in.
    The dispatch-separate baseline admits the prompt with ONE
    host-blocking prefill — the running slot's next tokens wait out
    the whole forward (the stall the ROADMAP calls the biggest lever
    on throughput-under-load). The ragged engine admits it as
    token-budgeted chunks INSIDE the decode horizon, so the running
    slot pays at most one slightly-longer tick per chunk. CPU-runnable:
    the committed evidence is the RATIO — post-arrival decode p99
    improving >= 1.5x — not the absolute ms. Latency is measured
    client-side (token arrival gaps via run(on_sync=...)), so the
    baseline's stall cannot hide behind ServeStats' prefill exclusion;
    every compiled program is warmed on the SHARED decoder before the
    measured runs, so the ratio compares steady-state schedules, not
    one-time XLA compiles.

    Operating point: a 4-layer/256-hidden GPT (prompt-token compute
    must dominate CPU per-tick dispatch overhead or the ratio measures
    graph-launch noise), K=2 horizons and 16-token chunks — the
    per-token stall bound is ~(L/K)/w, and K also sizes the shared
    horizon-granularity tail both engines pay, so small K both
    concentrates the baseline's stall and shrinks the ragged floor."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed import build_mesh
    from paddle_tpu.models import GPT, gpt_tiny
    from paddle_tpu.serving import ContinuousBatchingEngine, PagedGPTDecoder

    paddle.seed(0)
    build_mesh(dp=1)
    cfg = gpt_tiny(hidden_size=256, num_layers=4, num_heads=8,
                   max_seq_len=long_prompt + gen + 64, dtype="float32",
                   remat=False)
    model = GPT(cfg)
    model.eval()
    page_size = 32
    rng = np.random.RandomState(0)
    streamer = rng.randint(0, cfg.vocab_size, 8).astype(np.int32)
    long_ids = rng.randint(0, cfg.vocab_size, long_prompt).astype(np.int32)
    pages = (long_prompt + gen + 8 + gen) // page_size + 4

    # ONE decoder shared by every scenario run: compiled programs are
    # per-decoder-instance (jitted bound partials), so warmup only
    # warms the measured runs if they reuse the same instance (the
    # run_prefix_cache discipline) — otherwise the mixed-horizon /
    # suffix-prefill compiles land INSIDE the post-arrival latency
    # window and the committed ratio compares compile times
    dec = PagedGPTDecoder(model, num_pages=pages + 2,
                          page_size=page_size, max_batch=2)

    def scenario(ragged, trace=None):
        eng = ContinuousBatchingEngine(dec, max_new_tokens=gen,
                                       k_max=k_max, ragged=ragged,
                                       chunk_tokens=chunk, trace=trace)
        rid = eng.submit(streamer)
        state = {"submit_t": None, "events": []}

        def on_sync(e):
            now = time.perf_counter()
            state["events"].append((now, len(e._outputs.get(rid, []))))
            if state["submit_t"] is None and \
                    len(e._outputs.get(rid, [])) >= gen // 4:
                e.submit(long_ids)       # the long prompt arrives NOW,
                state["submit_t"] = now  # mid-stream of the other slot

        outs = eng.run(on_sync=on_sync)
        assert len(outs[rid]) == gen and state["submit_t"] is not None
        lats = []
        prev = None
        for t, n in state["events"]:
            if prev is not None and n > prev[1] and t > state["submit_t"]:
                lats.extend([(t - prev[0]) / (n - prev[1])] * (n - prev[1]))
            prev = (t, n)
        return ({"p50_ms": round(float(np.percentile(lats, 50)) * 1e3, 3),
                 "p99_ms": round(float(np.percentile(lats, 99)) * 1e3, 3)},
                eng)

    scenario(True)                       # warm every compile
    scenario(False)
    ragged, eng_r = scenario(True)
    base, eng_b = scenario(False)
    improvement = base["p99_ms"] / max(ragged["p99_ms"], 1e-9)
    row = {"baseline_p99_ms": base["p99_ms"],
           "baseline_p50_ms": base["p50_ms"],
           "ragged_p99_ms": ragged["p99_ms"],
           "ragged_p50_ms": ragged["p50_ms"],
           "p99_improvement": round(improvement, 2),
           "long_prompt": long_prompt, "chunk_tokens": chunk,
           "k_max": k_max,
           # the other half of the claim: the ragged engine paid ZERO
           # host-blocking prefill syncs; the baseline stalled
           "baseline_prefill_stall_syncs":
               eng_b.stats.prefill_stall_syncs,
           "ragged_prefill_stall_syncs":
               eng_r.stats.prefill_stall_syncs,
           "ragged_prefill_chunks": eng_r.stats.prefill_chunks}
    log(f"ragged_stall: post-arrival decode p99 {base['p99_ms']}ms -> "
        f"{ragged['p99_ms']}ms ({improvement:.2f}x) with a "
        f"{long_prompt}-token prompt arriving mid-stream "
        f"(chunk={chunk}, K={k_max}; baseline stalls: "
        f"{eng_b.stats.prefill_stall_syncs}, ragged: 0)")
    print(json.dumps({"metric": "gpt_decode_stall_p99_ms",
                      "value": ragged["p99_ms"], "unit": "ms",
                      **row}), flush=True)
    # PADDLE_TPU_BENCH_TRACE=/path.json: replay the ragged scenario
    # once more with a flight recorder attached (AFTER the measured
    # runs — the committed ratio stays untraced) and export the
    # chrome-trace timeline + the roofline-drift ledger. On CPU the
    # drift ratio is dominated by the host gap (predictions price the
    # target chip); on-chip this line is the mispricing detector
    # (docs/observability.md).
    trace_path = os.environ.get("PADDLE_TPU_BENCH_TRACE")
    if trace_path:
        from paddle_tpu.serving import FlightRecorder, export_chrome_trace
        rec = FlightRecorder()
        scenario(True, trace=rec)
        export_chrome_trace(trace_path, recorders=rec)
        drift = rec.drift_report()
        # worst departure in EITHER direction (the analyzer's
        # worst_ratio convention): overpriced shapes must not read as
        # near-clean just because their ratio sits below 1
        worst = max((max(d["ratio"], 1.0 / d["ratio"])
                     for d in drift if d["ratio"] > 0), default=0.0)
        # drifting shapes whose measured tick sits INSIDE the serial
        # sum of the priced legs are a SERIALIZED schedule, not a
        # mispriced leg (the ROOFLINE-DRIFT verdict split — the fix is
        # the schedule pass / COLL-SERIALIZED, not re-fitting inputs)
        n_serialized = sum(1 for d in drift
                           if d.get("verdict") == "serialized")
        log(f"ragged_stall: flight trace -> {trace_path} "
            f"({len(rec.events)} events, worst drift {worst:.1f}x, "
            f"{n_serialized} serialized shape(s))")
        print(json.dumps({"metric": "serving_roofline_drift",
                          "value": round(worst, 2),
                          "unit": "measured_over_predicted",
                          "shapes": len(drift),
                          "serialized_shapes": n_serialized,
                          "trace_events": len(rec.events),
                          "path": trace_path}), flush=True)
    return row


def run_ragged_pad(gen=40, long_prompt=224, chunk=16, k_max=2,
                   streamers=15):
    """Mixed-horizon PACKED-vs-DENSE layout A/B: pad fraction, CPU
    wall-clock and compiled-variant count of the same workload run
    through the packed [total_new_tokens] token-stream dispatch and
    the dense [S, w] window twin (`packed=False`). The workload is the
    packed layout's motivating shape: many decode rows sharing
    horizons with one long chunking prompt — on the dense layout every
    decode row pays w-1 padded window columns per mixed tick (S*w
    dispatched for ~S-1+w real tokens), on the packed layout the tick
    pays its pow2 total-token bucket. Two short odd-length prompts
    arrive late so the dense path re-buckets on the (S, w) grid (extra
    compiled variants) while the packed path's totals collapse into
    existing buckets (w rides as a traced scalar).

    Streams are byte-identical between the two engines (the layout
    twin invariant, test-pinned); this scenario banks the THREE
    layout claims: pad fraction drops >= 3x, wall-clock no worse,
    compiled-variant count (jit cache entries) strictly lower."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed import build_mesh
    from paddle_tpu.models import GPT, gpt_tiny
    from paddle_tpu.serving import ContinuousBatchingEngine, PagedGPTDecoder

    paddle.seed(0)
    build_mesh(dp=1)
    S = streamers + 1
    cfg = gpt_tiny(hidden_size=256, num_layers=4, num_heads=8,
                   max_seq_len=long_prompt + gen + 64, dtype="float32",
                   remat=False)
    model = GPT(cfg)
    model.eval()
    page_size = 32
    rng = np.random.RandomState(0)
    stream_ids = [rng.randint(0, cfg.vocab_size, 2).astype(np.int32)
                  for _ in range(2 * streamers)]
    long_ids = rng.randint(0, cfg.vocab_size, long_prompt).astype(np.int32)
    odd_ids = [rng.randint(0, cfg.vocab_size, n).astype(np.int32)
               for n in (5, 3)]
    per_seq = (long_prompt + gen) // page_size + 2
    pages = S * ((8 + gen) // page_size + 2) + per_seq + 8

    # ONE decoder shared by every run (the run_ragged_stall compile
    # discipline: jit memos are per-instance, so only a shared
    # instance lets the warm-up runs warm the measured runs)
    dec = PagedGPTDecoder(model, num_pages=pages + 2,
                          page_size=page_size, max_batch=S)

    def scenario(packed):
        eng = ContinuousBatchingEngine(dec, max_new_tokens=gen,
                                       k_max=k_max, ragged=True,
                                       chunk_tokens=chunk, packed=packed)
        # one slot stays FREE so later odd-length arrivals admit (and
        # chunk) at different times — each distinct suffix cover is a
        # fresh (S, w) bucket for the dense grid, while the packed
        # totals keep collapsing into the same pow2 buckets
        rids = [eng.submit(ids) for ids in stream_ids[:streamers - 1]]
        state = {"sent": 0}

        def on_sync(e):
            n = len(e._outputs.get(rids[0], []))
            # the long prompt lands mid-stream; the odd short prompts
            # arrive later (staggered); a SECOND streamer wave keeps
            # the batch full while the long prompt drains its decode
            # budget (a near-empty batch pads both layouts alike — a
            # production engine at load is the comparison that matters)
            if state["sent"] == 0 and n >= gen // 4:
                e.submit(long_ids)
                state["sent"] = 1
            elif state["sent"] == 1 and n >= 3 * gen // 4:
                e.submit(odd_ids[0])
                state["sent"] = 2
            elif state["sent"] == 2 and n >= 3 * gen // 4 + 4:
                e.submit(odd_ids[1])
                state["sent"] = 3
            elif state["sent"] == 3 and n >= gen - 2:
                # wave 2 rides into the slots wave 1 frees (an
                # overflow request would drain ALONE at the end —
                # padding both layouts alike); sized so the admission's
                # token total stays inside the mixed horizons' pow2
                # bucket
                for ids in stream_ids[streamers:2 * streamers - 4]:
                    e.submit(ids)
                state["sent"] = 4

        t0 = time.perf_counter()
        outs = eng.run(on_sync=on_sync)
        wall = time.perf_counter() - t0
        assert state["sent"] == 4 and len(outs) == 2 * streamers - 2
        return ({"pad_fraction": round(eng.stats.pad_fraction, 4),
                 "tokens_dispatched": eng.stats.tokens_dispatched,
                 "tokens_padded": eng.stats.tokens_padded,
                 "wall_s": round(wall, 3)}, outs)

    def jit_entries(memos):
        return sum(fn._cache_size() for memo in memos
                   for fn in memo.values())

    scenario(True)                       # warm every packed compile
    scenario(False)                      # ... and every dense one
    packed, outs_p = scenario(True)
    dense, outs_d = scenario(False)
    assert outs_p == outs_d, "packed/dense twin streams diverged"
    # compiled-variant count per layout: the decoder memos are the jit
    # objects, their internal cache entries count per-shape variants
    # (table-width buckets included) — the (S, w) grid vs total-token
    # buckets claim, measured
    packed_entries = jit_entries([dec._packeds])
    dense_entries = jit_entries([dec._raggeds])
    drop = dense["pad_fraction"] / max(packed["pad_fraction"], 1e-9)
    row = {"packed_pad_fraction": packed["pad_fraction"],
           "dense_pad_fraction": dense["pad_fraction"],
           "pad_drop_x": round(drop, 2),
           "packed_tokens_dispatched": packed["tokens_dispatched"],
           "dense_tokens_dispatched": dense["tokens_dispatched"],
           "packed_wall_s": packed["wall_s"],
           "dense_wall_s": dense["wall_s"],
           "packed_jit_entries": packed_entries,
           "dense_jit_entries": dense_entries,
           "slots": S, "long_prompt": long_prompt,
           "chunk_tokens": chunk, "k_max": k_max}
    log(f"ragged_pad: pad fraction {dense['pad_fraction']:.3f} dense -> "
        f"{packed['pad_fraction']:.3f} packed ({drop:.1f}x less padding; "
        f"{dense['tokens_dispatched']} -> {packed['tokens_dispatched']} "
        f"positions dispatched), wall {dense['wall_s']}s -> "
        f"{packed['wall_s']}s, jit entries {dense_entries} -> "
        f"{packed_entries}")
    print(json.dumps({"metric": "gpt_ragged_pad_fraction",
                      "value": packed["pad_fraction"],
                      "unit": "padded/dispatched", **row}), flush=True)
    return row


def run_decode_capacity(model_scale="gpt_1p3b", gen=24, p99_batch=8):
    """Concurrent-slot capacity at a fixed per-token p99: bf16 vs int8
    vs int4 KV pool.  Decode is HBM-bound, so at a per-token latency
    SLO the admissible slot count is set by how many KV byte-streams
    fit under the tick budget: slots = (p99·BW − weight_bytes) /
    ctx·kv_bytes_tok.
    The SLO is anchored at the BF16 pool's tick with `p99_batch` slots
    at avg_ctx = max_seq/2 (the KV-bound operating point — each slot's
    prefix, not the weights, dominates the stream), so the bf16 column
    reads back ~p99_batch, the int8 column shows the capacity the
    halved KV stream buys, and the int4 column what the nibble-packed
    pool (0.5 B/elem + per-group scales) banks on top under the SAME
    SLO.  Priced on the v5e chip spec
    (`PagedGPTDecoder.step_hbm_bytes(batch=...)` — deterministic,
    CPU-runnable); the measured half runs all three pools through a
    real tiny-GPT engine for tokens/s (CPU numbers carry dispatch
    overhead, the committed evidence is the SLOTS ratio like the other
    serving scenarios' ratios)."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.cost_model import chip_spec
    from paddle_tpu.distributed import build_mesh
    from paddle_tpu.models import GPT, gpt_tiny
    from paddle_tpu.models import gpt as gpt_mod
    from paddle_tpu.serving import ContinuousBatchingEngine, PagedGPTDecoder
    from paddle_tpu.serving.decoder import pool_token_bytes

    paddle.seed(0)
    build_mesh(dp=1)
    # the PRICED half needs only shapes: the decoder's own byte model
    # (serving.decoder.pool_token_bytes — the ONE definition behind
    # step_hbm_bytes/kv_token_bytes) applied to the big config, so the
    # bench prices exactly what the decoder would report without
    # building a 1.3B model on the host
    cfg_big = getattr(gpt_mod, model_scale)(max_seq_len=2048)
    cfg = gpt_tiny(max_seq_len=128, dtype="float32", remat=False)
    model = GPT(cfg)
    model.eval()
    chip = chip_spec()
    avg_ctx = cfg_big.max_seq_len // 2
    w_bytes = cfg_big.num_params() * 2   # bf16 weights (the a8w8/w4a16
    # weight legs compose orthogonally; the KV pool is this scenario)
    kv16 = cfg_big.num_layers * avg_ctx * pool_token_bytes(cfg_big)
    kv8 = cfg_big.num_layers * avg_ctx * pool_token_bytes(
        cfg_big, kv_quant="int8")
    kv4 = cfg_big.num_layers * avg_ctx * pool_token_bytes(
        cfg_big, kv_quant="int4")
    # the fixed SLO: the bf16 pool's tick with p99_batch slots. Slots
    # are recovered in INTEGER byte arithmetic (a float divide/multiply
    # round-trip through p99_s can floor the bf16 column to
    # p99_batch-1 and silently flatter the ratio); p99_s is reporting
    # only.
    budget_bytes = w_bytes + p99_batch * kv16
    p99_s = budget_bytes / chip.hbm_bw
    slots = {"bf16": (budget_bytes - w_bytes) // kv16,
             "int8": (budget_bytes - w_bytes) // kv8,
             "int4": (budget_bytes - w_bytes) // kv4}
    assert slots["bf16"] == p99_batch
    ratio = slots["int8"] / max(slots["bf16"], 1)
    ratio4 = slots["int4"] / max(slots["bf16"], 1)
    dec16 = PagedGPTDecoder(model, num_pages=32, page_size=16,
                            max_batch=2)
    dec8 = PagedGPTDecoder(model, num_pages=32, page_size=16,
                           max_batch=2, kv_quant="int8")
    dec4 = PagedGPTDecoder(model, num_pages=32, page_size=16,
                           max_batch=2, kv_quant="int4")

    # measured half: all pools through a real engine (tiny GPT, CPU)
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, 12).astype(np.int32)
               for _ in range(4)]
    tok_s = {}
    for name, dec in (("bf16", dec16), ("int8", dec8), ("int4", dec4)):
        def run_once():
            eng = ContinuousBatchingEngine(dec, max_new_tokens=gen,
                                           k_max=8)
            for p in prompts:
                eng.submit(p)
            t0 = time.time()
            outs = eng.run()
            dt = time.time() - t0
            return sum(len(v) for v in outs.values()) / dt, eng
        run_once()                       # warm the compiles
        tok_s[name], _ = run_once()
    row = {"slots_bf16": slots["bf16"], "slots_int8": slots["int8"],
           "slots_int4": slots["int4"],
           "slots_ratio": round(ratio, 2),
           "slots_ratio_int4": round(ratio4, 2),
           "p99_budget_ms": round(p99_s * 1e3, 3),
           "avg_ctx": avg_ctx, "model": model_scale,
           # KV bytes one context token costs across ALL layers (the
           # ServeStats.kv_bytes_per_token view at cfg_big shapes)
           "kv_bytes_per_token_bf16": kv16 // avg_ctx,
           "kv_bytes_per_token_int8": kv8 // avg_ctx,
           "kv_bytes_per_token_int4": kv4 // avg_ctx,
           # measured on the tiny-GPT engines only — keep tiny-scale
           # stats (pool bytes, resident slots) OUT of this row: every
           # other field describes cfg_big shapes, and mixing scales
           # invites misreading (debug.serving_stats() has them live)
           "measured_tok_s_bf16": round(tok_s["bf16"], 1),
           "measured_tok_s_int8": round(tok_s["int8"], 1),
           "measured_tok_s_int4": round(tok_s["int4"], 1)}
    log(f"decode_capacity[{model_scale}]: {slots['bf16']} -> "
        f"{slots['int8']} -> {slots['int4']} slots ({ratio:.2f}x / "
        f"{ratio4:.2f}x) at p99 "
        f"{p99_s*1e3:.2f} ms, avg_ctx={avg_ctx} (KV "
        f"{row['kv_bytes_per_token_bf16']} -> "
        f"{row['kv_bytes_per_token_int8']} -> "
        f"{row['kv_bytes_per_token_int4']} B/token; measured tiny-GPT "
        f"{tok_s['bf16']:.0f} vs {tok_s['int8']:.0f} vs "
        f"{tok_s['int4']:.0f} tok/s on this host)")
    print(json.dumps({"metric": "gpt_decode_capacity",
                      "value": slots["int4"], "unit": "slots",
                      **row}), flush=True)
    return row


def run_train_multi(steps=48, n=None):
    """Multi-step TRAINING throughput: the per-step Trainer.step loop vs
    the fused `step_multi` scan (N steps, one dispatch, losses drained at
    horizon boundaries) on the same config and batches. The training twin
    of run_decode's K-tick story — reported as train steps/sec with the
    horizon N and achieved host syncs/step attached."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.cost_model import train_horizon
    from paddle_tpu.distributed import build_mesh
    from paddle_tpu.distributed.trainer import LossBuffer, Trainer
    from paddle_tpu.models import (GPT, GPTPretrainingCriterion, gpt_125m,
                                   gpt_tiny)

    smoke = bool(os.environ.get("PADDLE_TPU_BENCH_SMOKE")) or \
        _on_cpu_backend()
    mk = gpt_tiny if smoke else gpt_125m
    bs, seq = (2, 64) if smoke else (8, 512)
    paddle.seed(0)
    build_mesh(dp=1)
    cfg = mk(max_seq_len=seq, remat=False)
    crit = GPTPretrainingCriterion()

    def loss_fn(m, b):
        return crit(m(paddle.to_tensor(b["input_ids"])),
                    paddle.to_tensor(b["labels"]))

    def make_trainer():
        paddle.seed(0)
        m = GPT(cfg)
        if not smoke:
            m.bfloat16()
        return Trainer(m, paddle.optimizer.AdamW(learning_rate=3e-4),
                       loss_fn)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (bs, seq + 1)).astype(np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}

    # per-step loop: dispatch `steps` steps, one trailing drain
    tr = make_trainer()
    t0 = time.time()
    with _alarm(600, "train_multi compile per-step"):
        float(tr.step(batch))
    log(f"train_multi[{mk.__name__}] per-step compile: {time.time()-t0:.1f}s")
    with _alarm(300, "train_multi per-step measure"):
        buf = LossBuffer(drain_every=steps + 1)
        t0 = time.time()
        for _ in range(steps):
            buf.append(tr.step(batch))
        buf.drain()
        dt_per = (time.time() - t0) / steps

    # fused horizon: one dispatch per N steps, drain per horizon
    if n is None:
        # measured per-step time is the honest upper bound of the step
        # roofline here (the CPU "tick" IS mostly host overhead); the
        # priced horizon caps at 32 like decode
        n = train_horizon(dt_per)
        n = max(2, min(int(n), 8))
    tr2 = make_trainer()
    horizon = [batch] * n
    t0 = time.time()
    with _alarm(600, "train_multi compile fused"):
        np.asarray(tr2.step_multi(horizon))
    log(f"train_multi[{mk.__name__}] fused N={n} compile: "
        f"{time.time()-t0:.1f}s")
    with _alarm(300, "train_multi fused measure"):
        buf2 = LossBuffer(drain_every=n)      # one real sync per horizon
        t0 = time.time()
        for _ in range(steps // n):
            buf2.append(tr2.step_multi(horizon))
        buf2.drain()
        dt_multi = (time.time() - t0) / (steps // n * n)
    syncs_per_step = buf2.fetches / max(steps // n * n, 1)
    log(f"train_multi[{mk.__name__}]: per-step {dt_per*1e3:.2f} ms/step "
        f"vs fused N={n} {dt_multi*1e3:.2f} ms/step = "
        f"{dt_per/dt_multi:.2f}x ({syncs_per_step:.3f} host syncs/step; "
        f"bs={bs}, seq={seq})")
    return {"steps_per_sec": 1.0 / dt_multi, "model": mk.__name__,
            "multi_step": int(n),
            "host_syncs_per_step": round(syncs_per_step, 4),
            "speedup_vs_per_step": round(dt_per / dt_multi, 3),
            "per_step_ms": round(dt_per * 1e3, 3),
            "fused_step_ms": round(dt_multi * 1e3, 3)}


def run_speculative(batch=4, prompt_len=64, gen=64, k=4):
    """Speculative decode WALL-CLOCK speedup vs plain continuous
    batching, same prompts. Zero-egress means no trained checkpoint
    pair, so agreement is CONSTRUCTED: the target's tail blocks are
    zeroed to residual passthrough (their matmuls still run — full
    target cost) and the draft is the live prefix, so greedy draft ==
    greedy target and acceptance is total. This measures the mechanical
    ceiling at the given target/draft depth ratio; real-model speedup =
    ceiling scaled by the actual agreement rate."""
    import os

    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed import build_mesh
    from paddle_tpu.models import GPT, gpt_350m, gpt_tiny
    from paddle_tpu.serving import (ContinuousBatchingEngine,
                                    PagedGPTDecoder, SpeculativeEngine)

    smoke = bool(os.environ.get("PADDLE_TPU_BENCH_SMOKE")) or \
        _on_cpu_backend()
    mk = gpt_tiny if smoke else gpt_350m
    if smoke:
        batch, prompt_len, gen = 2, 16, 16
    paddle.seed(0)
    build_mesh(dp=1)
    cfg = mk(max_seq_len=max(256, prompt_len + gen + k + 8))
    target = GPT(cfg)
    draft_layers = max(1, cfg.num_layers // 4)
    # tail blocks -> residual passthrough: proj/fc2 zeroed, cost intact
    for block in list(target.blocks)[draft_layers:]:
        for lin in (block.proj, block.fc2):
            lin.weight._value = jnp.zeros_like(lin.weight._value)
            lin.bias._value = jnp.zeros_like(lin.bias._value)
    dcfg = mk(max_seq_len=cfg.max_seq_len)
    dcfg.num_layers = draft_layers
    draft = GPT(dcfg)
    tstate = target.state_dict()
    draft.set_state_dict({k2: tstate[k2] for k2 in
                          draft.state_dict() if k2 in tstate})
    for m in (target, draft):
        if not smoke:
            m.bfloat16()
        m.eval()
    page_size = 16
    pages = (prompt_len + gen + k + page_size - 1) // page_size
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, prompt_len).astype(np.int32)
               for _ in range(batch)]

    def make_dec(m):
        return PagedGPTDecoder(m, num_pages=batch * pages + 2,
                               page_size=page_size, max_batch=batch)

    def timed(build):
        eng = build()
        for p in prompts:
            eng.submit(p)
        eng.run()                    # compile
        eng = build()
        for p in prompts:
            eng.submit(p)
        t0 = time.perf_counter()
        out = eng.run()
        return time.perf_counter() - t0, out

    dt_plain, out_plain = timed(
        lambda: ContinuousBatchingEngine(make_dec(target),
                                         max_new_tokens=gen))
    dt_spec, out_spec = timed(
        lambda: SpeculativeEngine(make_dec(target), make_dec(draft),
                                  max_new_tokens=gen, k=k))
    assert out_plain == out_spec, \
        "speculative greedy output diverged from target-only decode"
    speedup = dt_plain / dt_spec
    log(f"speculative[{mk.__name__}] k={k} "
        f"draft={draft_layers}/{cfg.num_layers} layers: "
        f"plain {dt_plain:.2f}s vs spec {dt_spec:.2f}s = "
        f"{speedup:.2f}x wall-clock (full-agreement ceiling)")
    return {"wallclock_speedup": round(speedup, 3), "k": k,
            "model": mk.__name__,
            "draft_layers": draft_layers, "target_layers": cfg.num_layers,
            "mode": "constructed full-agreement ceiling",
            "plain_s": round(dt_plain, 3), "spec_s": round(dt_spec, 3)}


def _on_cpu_backend():
    import jax
    try:
        return jax.devices()[0].platform == "cpu"
    except Exception:
        return True


def _device_watchdog(timeout_s=None, attempts=None, backoff_s=45):
    """Probe jax backend init in a subprocess: a dead TPU tunnel HANGS
    jax.devices() forever, which would leave the driver with no JSON at
    all. Returns None if healthy, else an error string.

    Failure modes differ: a probe that ERRORS (nonzero exit) may be a
    transient flap — retry with backoff; a probe that HANGS to its
    timeout means the tunnel is down, and r5 burned 4x45s retries plus
    a 150s hang each before reaching the cached-campaign fallback — so
    a hang on ANY probe short-circuits immediately (error exits, which
    really are transient flaps, keep the retry budget). Budgets are
    env-tunable: PADDLE_TPU_BENCH_PROBE_TIMEOUT (seconds per probe,
    default 150) and PADDLE_TPU_BENCH_PROBE_ATTEMPTS (error-retry
    budget, default 4; set 1 for single-probe runs)."""
    import subprocess
    import time as _time
    def _env_int(name, default, lo=1):
        # a malformed env ("90s") must not crash bench before the
        # watchdog's JSON fallback it exists to guarantee
        try:
            return max(lo, int(os.environ.get(name, default)))
        except ValueError:
            log(f"ignoring malformed {name}={os.environ[name]!r}; "
                f"using {default}")
            return default
    if timeout_s is None:
        timeout_s = _env_int("PADDLE_TPU_BENCH_PROBE_TIMEOUT", 150)
    if attempts is None:
        attempts = _env_int("PADDLE_TPU_BENCH_PROBE_ATTEMPTS", 4)
    code = "import jax; d = jax.devices(); print(d[0].platform)"
    err = None
    for i in range(attempts):
        if i:
            log(f"device probe retry {i + 1}/{attempts} in {backoff_s}s: {err}")
            _time.sleep(backoff_s)
        try:
            p = subprocess.run([sys.executable, "-c", code],
                               capture_output=True, text=True,
                               timeout=timeout_s)
            if p.returncode == 0:
                return None
            err = f"device init failed: {(p.stderr or '')[-200:]}"
        except subprocess.TimeoutExpired:
            err = f"device init hung >{timeout_s}s (TPU tunnel down?)"
            # a hang is a down tunnel, not a flap — no matter which
            # probe it lands on (an error-exit flap followed by a hang
            # would otherwise still burn the remaining retry budget):
            # skip straight to the cached-campaign fallback instead of
            # ~11 min of retries that will hang the same way
            which = "first probe" if i == 0 else f"probe {i + 1} hang"
            return f"{err} [fast-fail on {which}]"
    return f"{err} [after {attempts} attempts]"



def _record_failure(extras, key, label, e):
    """Log + record a stage failure, then drop every reference to the
    exception: its traceback pins the failed run's frames (trainer params,
    KV pages) in HBM, which would OOM the next stage's allocation."""
    msg = f"{type(e).__name__}: {str(e)[:300]}"
    log(f"{label} bench failed: {msg}")
    extras[key] = msg[:160]
    # the caller's `except ... as e` binding still exists until its block
    # exits, so `del e` here can't free anything — cut the traceback (and
    # any chained exception's) off the object itself
    e.__traceback__ = None
    if e.__context__ is not None:
        e.__context__.__traceback__ = None
    del e
    import gc
    gc.collect()


def _cached_campaign(path="perf_campaign_results.jsonl", per_config=3):
    """Latest successful on-chip trials per config from the perf-campaign
    log, plus the file's mtime as provenance. Used only when the device is
    unreachable at bench time: the headline value stays 0.0 (these are not
    this run's numbers), but the evidence of what the chip did during the
    last tunnel window rides along for the record."""
    try:
        st = os.stat(path)
        best = {}
        with open(path) as f:
            for line in f:
                try:
                    d = json.loads(line)
                except ValueError:
                    continue
                cfg = d.get("config", "")
                if "error" in d or cfg.endswith("_stage_done") or not cfg:
                    continue
                best.setdefault(cfg, []).append(d)
        if not best:
            return None
        def pick(trials):
            # a sweep records many variants under one config; keep the
            # strongest (by mfu when present), not merely the most recent
            if any("mfu" in t for t in trials):
                trials = sorted(trials, key=lambda t: t.get("mfu", -1.0),
                                reverse=True)
                return trials[:per_config]
            return trials[-per_config:]

        return {
            "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                         time.gmtime(st.st_mtime)),
            "results": {cfg: pick(trials)
                        for cfg, trials in best.items()},
        }
    except OSError:
        return None


def main():
    only = sys.argv[1] if len(sys.argv) > 1 else None

    def _on_term(signum, frame):
        # external timeout (tunnel_watch runs bench under `timeout 3600`):
        # per-stage alarm budgets can sum past it on a semi-wedged tunnel,
        # so flush whatever is banked instead of dying JSON-less
        out = dict(_PARTIAL) if _PARTIAL else _default_result()
        out["error"] = "SIGTERM (external timeout) — partial results"
        log(f"bench: {out['error']}")
        print(json.dumps(out), flush=True)
        os._exit(4)

    import signal as _signal
    import threading as _threading
    if _threading.current_thread() is _threading.main_thread():
        _signal.signal(_signal.SIGTERM, _on_term)
    err = _device_watchdog()
    if err is not None:
        log(f"bench aborted: {err}")
        out = {**_default_result(), "error": err}
        cached = _cached_campaign()
        if cached:
            # value stays 0.0 — these are NOT this run's numbers, just the
            # latest on-chip evidence (examples/perf_campaign.py appends to
            # perf_campaign_results.jsonl whenever a tunnel window opens)
            out["cached_campaign"] = cached
        print(json.dumps(out))
        return
    # each group: variants of the same headline config, BEST FIRST (the
    # campaign already established the ordering: 0.641 bs6/dots > 0.623
    # bs4/dots > 0.540 bs8/full; bs8/dots exceeds what the compiler can
    # schedule).  The first variant that runs IS the group's answer —
    # re-measuring the known-slower variants only adds ~2 more compiles
    # of wedge exposure on a flaky tunnel (see r4: wedged mid-measure).
    groups = [
        [("gpt_1p3b", 6, 1024, "dots"),
         ("gpt_1p3b", 4, 1024, "dots"),
         ("gpt_1p3b", 8, 1024, "full")],
        [("gpt_1p3b", 4, 1024, "full")],
        [("gpt_760m", 8, 1024, "full")],
        [("gpt_350m", 16, 1024, "full")],
        [("gpt_125m", 16, 1024, "full")],
    ]
    # PADDLE_TPU_BENCH_ADVISE=1: let the static remat/microbatch
    # advisor (paddle_tpu.analysis.autotune — host-side tracing only,
    # no device work) reorder the headline group before any compiles.
    # Off by default because the hand ordering above IS measured truth;
    # the advisor is for fresh configs the grid never tried.
    if os.environ.get("PADDLE_TPU_BENCH_ADVISE") == "1":
        try:
            from paddle_tpu.analysis.autotune import rank_gpt_candidates
            seqs = {(n, bs, rp): s for n, bs, s, rp in groups[0]}
            if len(set(seqs.values())) != 1:
                # the probe prices ONE seq; a mixed-seq group would be
                # silently re-priced at the wrong length — keep the
                # measured hand ordering instead
                raise ValueError(
                    f"mixed seq lengths {sorted(set(seqs.values()))}")
            grid = [(n, bs, rp, 1) for n, bs, _s, rp in groups[0]]
            ranked = rank_gpt_candidates(grid, seq=next(iter(seqs.values())),
                                         top=len(grid), log=log)
            groups[0] = [(n, bs, seqs[(n, bs, rp)], rp)
                         for n, bs, rp, _a in ranked]
            log(f"advisor reordered headline group: {groups[0]}")
        except Exception as e:
            log(f"advisor failed ({type(e).__name__}: {str(e)[:160]}); "
                "keeping measured ordering")
    result, last_err = None, None
    if only in (None, "gpt"):
        for group in groups:
            for cfg_name, bs, seq, rp in group:
                try:
                    with _alarm(900, f"{cfg_name} bs{bs}/{rp}"):
                        tok_s, mfu, n_params, static_hbm = run_config(
                            cfg_name, bs, seq, remat_policy=rp)
                except Exception as e:  # OOM or tunnel issues → try smaller
                    # keep only the STRING: holding the exception pins its
                    # traceback frames, which pin the failed Trainer's params
                    # and opt state in HBM — every later attempt then OOMs
                    last_err = f"{type(e).__name__}: {str(e)[:200]}"
                    log(f"{cfg_name}/{rp} failed: {last_err}")
                    del e
                    import gc
                    gc.collect()
                    continue
                result = {
                    "metric": f"{cfg_name}_train_tokens_per_sec_per_chip",
                    "value": round(tok_s, 1),
                    "unit": "tokens/s/chip",
                    "vs_baseline": round(mfu / 0.35, 4),
                    "mfu": round(mfu, 4),
                    "params": n_params,
                    "batch": bs, "seq": seq, "remat": rp,
                    "static_peak_hbm_per_device_bytes": static_hbm,
                }
                break               # best-first: first success is the answer
            if result is not None:
                _publish_partial(result)
                break
    if result is None:
        if only in (None, "gpt"):   # real failure of the headline config
            result = _default_result()
            if last_err is not None:
                result["error"] = last_err
        else:                       # gpt intentionally skipped via CLI filter
            result = {"metric": f"bench_only_{only}", "value": 0.0,
                      "unit": "see extras", "vs_baseline": 0.0}
    # secondary BASELINE.json configs ride along in the same JSON line
    _publish_partial(result)
    extras = {}
    result["extras"] = extras  # live reference: hard-exit sees each banked stage
    if only in (None, "resnet"):
        try:
            with _alarm(900, "resnet50"):
                imgs_s, mfu = run_resnet50()
            extras["resnet50_imgs_per_sec_per_chip"] = round(imgs_s, 1)
            extras["resnet50_mfu"] = round(mfu, 4)
        except Exception as e:
            _record_failure(extras, "resnet50_error", "resnet50", e)
    if only in (None, "bert"):
        try:
            with _alarm(900, "bert_base"):
                seqs_s, mfu = run_bert_base()
            extras["bert_base_seqs_per_sec_per_chip"] = round(seqs_s, 2)
            extras["bert_base_mfu"] = round(mfu, 4)
        except Exception as e:
            _record_failure(extras, "bert_base_error", "bert", e)
    if only in (None, "yolo"):
        try:
            with _alarm(900, "yolov3"):
                imgs_s, mfu = run_yolov3()
            extras["yolov3_imgs_per_sec_per_chip"] = round(imgs_s, 1)
            extras["yolov3_mfu"] = round(mfu, 4)
        except Exception as e:
            _record_failure(extras, "yolov3_error", "yolov3", e)
    if only in (None, "yolo", "ocr"):
        try:
            with _alarm(600, "crnn"):
                imgs_s, mfu = run_crnn()
            extras["crnn_imgs_per_sec_per_chip"] = round(imgs_s, 1)
            extras["crnn_mfu"] = round(mfu, 4)
        except Exception as e:
            _record_failure(extras, "crnn_error", "crnn", e)
    if only in (None, "moe"):
        try:
            with _alarm(900, "gpt_moe"):
                tok_s, mfu = run_gpt_moe()
            extras["gpt_moe_tokens_per_sec_per_chip"] = round(tok_s, 1)
            extras["gpt_moe_mfu"] = round(mfu, 4)
        except Exception as e:
            _record_failure(extras, "gpt_moe_error", "moe", e)
    if only in (None, "train_multi"):
        try:
            with _alarm(900, "train_multi"):
                r = run_train_multi()
            extras["train_multi_steps_per_sec"] = round(r["steps_per_sec"], 2)
            extras["train_multi_n"] = r["multi_step"]
            extras["train_multi_speedup"] = r["speedup_vs_per_step"]
            # the multi-step training headline: fused-scan step
            # throughput + how rarely the host interposes
            print(json.dumps({
                "metric": "gpt_train_steps_per_sec",
                "value": round(r["steps_per_sec"], 2),
                "unit": "steps/s/chip",
                "model": r["model"], "multi_step": r["multi_step"],
                "host_syncs_per_step": r["host_syncs_per_step"],
                "speedup_vs_per_step": r["speedup_vs_per_step"]}),
                flush=True)
        except Exception as e:
            _record_failure(extras, "train_multi_error", "train_multi", e)
    if only in (None, "decode"):
        for q in (None, "a8w8", "w4a16"):
            pfx = "decode" + (f"_{q}" if q else "")
            try:
                with _alarm(900, pfx):
                    r = run_decode(quant=q)
                extras[f"{pfx}_tokens_per_sec_per_chip"] = \
                    round(r["tok_s"], 1)
                extras[f"{pfx}_model"] = r["model"]
                extras[f"{pfx}_vs_hbm_roofline"] = r["vs_roofline"]
                extras[f"{pfx}_roofline_tok_s"] = r["roofline_tok_s"]
                extras[f"{pfx}_token_latency_ms"] = r["latency"]
                if q is None:
                    # the multi-step serving headline: fused-engine
                    # decode throughput + how rarely the host interposes
                    print(json.dumps({
                        "metric": "gpt_decode_tokens_per_sec",
                        "value": round(r["tok_s"], 1),
                        "unit": "tokens/s/chip",
                        "model": r["model"], "k_max": r["k_max"],
                        "host_syncs_per_token":
                            round(r["host_syncs_per_token"], 4),
                        "vs_hbm_roofline": r["vs_roofline"]}),
                        flush=True)
            except Exception as e:
                _record_failure(extras, f"{pfx}_error", pfx, e)
        try:
            with _alarm(900, "speculative"):
                extras["speculative"] = run_speculative()
        except Exception as e:
            _record_failure(extras, "speculative_error", "speculative", e)
    if only in (None, "decode", "capacity"):
        try:
            with _alarm(600, "decode_capacity"):
                extras["decode_capacity"] = run_decode_capacity()
        except Exception as e:
            _record_failure(extras, "decode_capacity_error", "capacity", e)
    if only in (None, "decode", "prefix"):
        try:
            with _alarm(600, "prefix_cache"):
                extras["prefix_cache"] = run_prefix_cache()
        except Exception as e:
            _record_failure(extras, "prefix_cache_error", "prefix", e)
        try:
            with _alarm(600, "kv_tier"):
                extras["kv_tier"] = run_kv_tier()
        except Exception as e:
            _record_failure(extras, "kv_tier_error", "kv_tier", e)
    if only in (None, "decode", "fleet"):
        try:
            with _alarm(600, "fleet"):
                extras["fleet"] = run_fleet()
        except Exception as e:
            _record_failure(extras, "fleet_error", "fleet", e)
    if only in (None, "decode", "tenancy"):
        try:
            with _alarm(600, "multi_tenant"):
                extras["multi_tenant"] = run_multi_tenant()
        except Exception as e:
            _record_failure(extras, "multi_tenant_error", "tenancy", e)
    if only in (None, "decode", "ragged"):
        try:
            with _alarm(600, "ragged_stall"):
                extras["ragged_stall"] = run_ragged_stall()
        except Exception as e:
            _record_failure(extras, "ragged_stall_error", "ragged", e)
        try:
            with _alarm(600, "ragged_pad"):
                extras["ragged_pad"] = run_ragged_pad()
        except Exception as e:
            _record_failure(extras, "ragged_pad_error", "ragged", e)
    if not extras:
        result.pop("extras", None)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
