"""Signal ops — reference python/paddle/signal.py (stft/istft/frame/overlap_add)."""
import jax.numpy as jnp
import numpy as np

from .framework.core import Tensor, apply_op

__all__ = ["frame", "overlap_add", "stft", "istft"]


def frame(x, frame_length, hop_length, axis=-1, name=None):
    def _f(v):
        n = v.shape[axis]
        num = 1 + (n - frame_length) // hop_length
        starts = np.arange(num) * hop_length
        moved = jnp.moveaxis(v, axis, -1)
        frames = jnp.stack([moved[..., s:s + frame_length] for s in starts], axis=-1)
        # paddle: frames on axis=-2 → [..., frame_length, num_frames]
        return jnp.moveaxis(frames, (-2, -1), (-2, -1)) if axis in (-1, v.ndim - 1) \
            else jnp.moveaxis(frames, -1, axis)
    return apply_op(_f, x)


def overlap_add(x, hop_length, axis=-1, name=None):
    def _f(v):
        # [..., frame_length, num_frames]
        fl, num = v.shape[-2], v.shape[-1]
        n = (num - 1) * hop_length + fl
        out = jnp.zeros(v.shape[:-2] + (n,), v.dtype)
        for i in range(num):
            out = out.at[..., i * hop_length: i * hop_length + fl].add(v[..., i])
        return out
    return apply_op(_f, x)


def stft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
         pad_mode="reflect", normalized=False, onesided=True, name=None):
    hop = hop_length or n_fft // 4
    wl = win_length or n_fft

    def _f(v, *rest):
        w = rest[0] if rest else jnp.ones((wl,), jnp.float32)
        if wl < n_fft:
            pad = (n_fft - wl) // 2
            w = jnp.pad(w, (pad, n_fft - wl - pad))
        sig = v
        if center:
            sig = jnp.pad(sig, [(0, 0)] * (sig.ndim - 1) + [(n_fft // 2, n_fft // 2)],
                          mode="reflect" if pad_mode == "reflect" else "constant")
        n = sig.shape[-1]
        num = 1 + (n - n_fft) // hop
        frames = jnp.stack([sig[..., s * hop: s * hop + n_fft] for s in range(num)], axis=-2)
        frames = frames * w
        spec = jnp.fft.rfft(frames, axis=-1) if onesided else jnp.fft.fft(frames, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        return jnp.swapaxes(spec, -1, -2)  # [..., freq, frames]
    args = (x,) + ((window,) if window is not None else ())
    return apply_op(_f, *args)


def istft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
          normalized=False, onesided=True, length=None, return_complex=False, name=None):
    hop = hop_length or n_fft // 4
    wl = win_length or n_fft

    def _f(spec, *rest):
        w = rest[0] if rest else jnp.ones((wl,), jnp.float32)
        if wl < n_fft:
            pad = (n_fft - wl) // 2
            w = jnp.pad(w, (pad, n_fft - wl - pad))
        frames_fd = jnp.swapaxes(spec, -1, -2)  # [..., frames, freq]
        if normalized:
            frames_fd = frames_fd * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        frames = jnp.fft.irfft(frames_fd, n=n_fft, axis=-1) if onesided \
            else jnp.real(jnp.fft.ifft(frames_fd, axis=-1))
        frames = frames * w
        num = frames.shape[-2]
        n = (num - 1) * hop + n_fft
        out = jnp.zeros(frames.shape[:-2] + (n,), frames.dtype)
        wsum = jnp.zeros((n,), frames.dtype)
        for i in range(num):
            out = out.at[..., i * hop: i * hop + n_fft].add(frames[..., i, :])
            wsum = wsum.at[i * hop: i * hop + n_fft].add(w * w)
        out = out / jnp.maximum(wsum, 1e-10)
        if center:
            out = out[..., n_fft // 2: n - n_fft // 2]
        if length is not None:
            out = out[..., :length]
        return out
    args = (x,) + ((window,) if window is not None else ())
    return apply_op(_f, *args)
