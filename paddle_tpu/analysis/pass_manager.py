"""Pass manager for the Graph Doctor (TPU-MLIR-style pass pipeline,
arxiv 2210.15016): a catalog of registered analyzers, each a pure
function of (LoweredProgram | python callable, AnalysisContext) ->
Findings, run in registration order and merged into one Report.

Two analyzer kinds:
  * ``graph``  — consumes the lowered StableHLO/jaxpr program;
  * ``source`` — consumes the *python* function pre-tracing (the
    dy2static AST linter), catching hazards the graph can't show
    because conversion already erased or mangled them.
"""
from dataclasses import dataclass, field

from .findings import Report

__all__ = ["Analyzer", "AnalysisContext", "PassManager",
           "register_analyzer", "get_analyzer", "default_catalog"]

_REGISTRY = {}   # name -> Analyzer subclass (insertion-ordered)


def register_analyzer(cls):
    """Class decorator: adds the analyzer to the default catalog under
    its ``name`` attribute."""
    if not getattr(cls, "name", None):
        raise ValueError(f"{cls.__name__} needs a `name` attribute")
    _REGISTRY[cls.name] = cls
    return cls


def get_analyzer(name):
    try:
        return _REGISTRY[name]()
    except KeyError:
        raise KeyError(f"no analyzer {name!r}; known: {sorted(_REGISTRY)}")


def default_catalog():
    """Registered analyzer names, registration-ordered. propagation
    imports BEFORE memory/sharding on purpose: those passes consume the
    fixed-point result the PropagationAnalyzer stashes on ctx.extra, so
    it must run (= register) first."""
    from . import analyzers as _a     # noqa: F401  (registers graph passes)
    from . import propagation as _p   # noqa: F401  (registers propagation)
    from . import memory as _m        # noqa: F401  (registers memory pass)
    from . import sharding as _s      # noqa: F401  (registers sharding pass)
    from . import ast_lint as _l      # noqa: F401  (registers source pass)
    from . import determinism as _d   # noqa: F401  (registers determinism)
    from . import threads as _t       # noqa: F401  (registers thread lint)
    return list(_REGISTRY)


@dataclass
class AnalysisContext:
    """Everything an analyzer may consult beyond the program itself.
    All fields optional: a default-constructed context runs every pass
    in reporting mode (metrics, no expectations)."""
    name: str = "program"
    # dtype policy: "bfloat16"/"float16" activates the f32-upcast rule
    policy_dtype: str = None
    # "NHWC" makes activation transposes errors (the r2 layout pin)
    data_format: str = None
    # regexes for activation transposes that are by-design (s2d pack,
    # sequence-major flip, head-output NCHW boundary, ...)
    allowed_activation_transposes: tuple = ()
    # predicate(HloOp) -> True to exempt an f32 matmul (MoE router)
    f32_dot_allow: object = None
    # op name -> exact expected count (architecture contract)
    expected_counts: dict = None
    # committed lint manifest dict (see manifest.py) for drift checks
    manifest: dict = None
    # mesh axis -> size, for collective accounting
    mesh_axes: dict = None
    # False => any collective op is an error (single-device program)
    expect_collectives: bool = None
    # extra custom_call targets that are known device-side (Pallas etc.)
    host_callback_allow: tuple = ()
    # committed memory manifest (manifest.load_memory_manifest) for the
    # peak-HBM / wire-byte regression gates
    memory_manifest: dict = None
    # relative drift allowed against the memory manifest before the
    # memory/sharding passes turn it into an ERROR
    memory_tolerance: float = 0.10
    # per-device HBM budget; peak above it is MEM-OVER-BUDGET
    hbm_budget_bytes: int = None
    # replicated tensors at/above this size trip the sharding rules
    replicated_bytes_threshold: int = 1 << 20
    # regexes for by-design mid-program reshards (MoE all_to_all dispatch)
    allowed_resharding: tuple = ()
    # COLL-SERIALIZED bar: a critical-path collective must have at
    # least this fraction of its wire time coverable by
    # concurrently-schedulable compute (analysis/schedule.py)
    schedule_hide_frac: float = 0.5
    # free-form knobs for user analyzers
    extra: dict = field(default_factory=dict)


class Analyzer:
    """Base class. Subclasses set `name`, `kind` ("graph"|"source") and
    implement run(target, context) -> iterable of Finding (or None).
    Metrics go through report.metrics[self.name] = {...} via
    `self.metrics` captured per run by the PassManager."""
    name = None
    kind = "graph"

    def run(self, target, context):  # pragma: no cover - interface
        raise NotImplementedError


class PassManager:
    def __init__(self, analyzers=None):
        if analyzers is None:
            analyzers = default_catalog()
        self.analyzers = [a if isinstance(a, Analyzer) else get_analyzer(a)
                          for a in analyzers]

    def _run_kind(self, kind, target, context):
        context = context or AnalysisContext()
        if kind == "graph" and context.mesh_axes is None:
            # default the collective accounting to the live global mesh
            # so every entry point (CLI, diagnose, jit lint, gate) gets
            # per-axis attribution without hand-wiring
            try:
                from ..distributed import mesh_axis_sizes
                context.mesh_axes = mesh_axis_sizes()
            except Exception:
                pass
        report = Report()
        for a in self.analyzers:
            if a.kind != kind:
                continue
            a.metrics = {}
            found = a.run(target, context) or ()
            for f in found:
                if not f.analyzer:
                    f.analyzer = a.name
                if f.location is None:
                    f.location = context.name
                report.add(f)
            if a.metrics:
                report.metrics[a.name] = a.metrics
        return report

    def run(self, program, context=None):
        """Run graph analyzers over a LoweredProgram."""
        return self._run_kind("graph", program, context)

    def run_source(self, fn, context=None):
        """Run source analyzers over a python function (or source str)."""
        return self._run_kind("source", fn, context)

    def run_layer(self, model, *example_arrays, context=None):
        """Lower a Layer on CPU and run the full catalog: source passes
        over its forward, graph passes over the lowered program."""
        from .lowering import lower_layer
        context = context or AnalysisContext(name=type(model).__name__)
        report = self.run_source(
            getattr(type(model), "forward", None) or model, context)
        program = lower_layer(model, *example_arrays, name=context.name)
        report.extend(self.run(program, context))
        return report
