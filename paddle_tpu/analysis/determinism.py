"""Determinism Doctor — static proof obligations for the serving
runtime's byte-identical-stream invariant.

Every serving feature since the paged decoder landed — prefix-cache
CoW, tiered spill/restore, preemption-and-resume, multi-LoRA, packed
ragged horizons — is safe only because of two purity facts the fuzz
tests enforce dynamically:

  * a KV page's bytes are a pure function of (request, position);
  * a sampled stream is a pure function of (seed, rid, position).

This pass proves the *index side* of those facts statically with a
taint-provenance dataflow over the lowered jaxpr (recursing into
scan/while/cond/pjit bodies the way schedule.py and propagation.py
walk them).  Every value is classified against a provenance lattice:

  request-intrinsic   "rid"      sampling-key ids / request ids
                      "position" sequence positions, lengths, starts
                      "prompt"   the request's own token bytes
                      "seed"     explicit seed/key arguments
  layout-tainted      "iota"     batch order / slot index / tick index
                                 (anything minted by an iota)
                      "table"    page-table row order and row routing
  request-extrinsic   "draft"    a speculative draft model's proposals
  constant            {}         consts, params, config scalars

Taints are seeded from the serving capture's `ArgInfo` names/roles and
propagated forward through every equation (union of operand taints)
with ONE deliberate exemption: `select_n` drops its *predicate* taint
and unions only the branch taints.  That is what keeps the committed
programs green through the scratch routing they all share —
`where(done, scratch_page, pids)` routes frozen rows to the reserved
scratch page, and the *routing decision* (batch-composition-dependent)
never contaminates the *canonical index* a live row writes to.

Rules (catalog rows in docs/static_analysis.md):

  KV-WRITE-NONCANONICAL  a scatter into a pool-role buffer whose page
                         index does not route through the page TABLE
                         (or a constant scratch page), or whose
                         in-page offset carries no POSITION
                         provenance — a resume/restore/CoW replay
                         would reproduce different bytes.  Also fires
                         when the written *values* carry "draft"
                         provenance: the speculative verify window
                         writes draft-model bytes into real pages
                         before acceptance (the documented expected
                         red; commit-on-accept must turn it green).
  RNG-KEY-TAINT          an RNG eqn whose key derivation folds in
                         anything beyond (seed, rid, position) — the
                         sampled stream would depend on batch
                         composition or table layout.
  SCATTER-WRITE-OVERLAP  two scatters into the SAME pool buffer
                         within one loop/tick body whose index sets
                         cannot be proven disjoint (disjoint static
                         windows, same-page disjoint offsets, or
                         distinct row-id provenance through the same
                         table) — the device-side write-write race
                         the scratch routing exists to prevent.
  DONATE-HOST-ALIAS      a donated argument (or a pure view of one)
                         is returned as an output — the host may
                         still hold the donated buffer while XLA
                         reuses it (the PR-4/PR-13 segfault class).

`DeterminismAnalyzer` wires the walk into the Graph Doctor catalog;
metrics feed determinism_manifests/<config>.json for the serving
PROGRAM configs (see manifest.py / baseline.DETERMINISM_CONFIGS).
"""
import re
from dataclasses import dataclass, field
from itertools import combinations

from .findings import Finding, Severity
from .memory import (_SCATTER_PRIMS, _eqn_source, _is_var, _sub_jaxprs,
                     kv_cache_infos)
from .pass_manager import Analyzer, register_analyzer

__all__ = ["DeterminismResult", "analyze_determinism",
           "DeterminismAnalyzer", "REQUEST_TAGS", "LAYOUT_TAGS",
           "RNG_ALLOWED_TAGS"]

# the provenance lattice's named classes
REQUEST_TAGS = frozenset({"seed", "rid", "position", "prompt"})
LAYOUT_TAGS = frozenset({"iota", "table"})
# a sampled stream must be a pure function of (seed, rid, position)
RNG_ALLOWED_TAGS = frozenset({"seed", "rid", "position"})

# arg-name (last path component) -> lattice class.  First match wins;
# args matching nothing get a private "arg:<name>" tag so foreign
# provenance is never silently laundered into "constant".
_TAG_PATTERNS = (
    ("rid", re.compile(r"^(kids?|rids?|request(_ids?)?)$")),
    ("position", re.compile(
        r"^(lens?|pos|positions?|starts?|true_len|sample_pos|last_idx|"
        r"remaining|pend_n)$")),
    ("prompt", re.compile(r"^(tokens?|ptok|ids|pend|prompts?|eos)$")),
    ("seed", re.compile(r"^(seeds?|keys?|rng(_keys?)?)$")),
    ("table", re.compile(r"^(tables?|rows?)$")),
    ("draft", re.compile(r"^(draft(_tokens?)?|proposals?)$")),
)

# every primitive of the PRNG lowering families (old-style threefry and
# typed-key random_*): the key-taint rule inspects all of them, so a
# forbidden fold is caught whichever layer it enters at
_RNG_PRIMS = frozenset({
    "threefry2x32", "random_bits", "random_fold_in", "random_seed",
    "random_wrap", "random_unwrap", "random_gamma", "random_clone"})

# shape-only ops a pool buffer's identity survives (buffer roots)
_VIEW_PRIMS = frozenset({
    "reshape", "transpose", "squeeze", "copy", "broadcast_in_dim",
    "convert_element_type"})
# byte-preserving views only: the donation-alias chain
_ALIAS_PRIMS = frozenset({"reshape", "transpose", "squeeze", "copy"})
# wrappers stripped when chasing an index operand to its producer
_STRIP_PRIMS = frozenset({
    "reshape", "broadcast_in_dim", "convert_element_type", "squeeze",
    "copy"})

_EMPTY = frozenset()
_MAX_LOOP_SWEEPS = 16


def _unclosed(j):
    return j.jaxpr if hasattr(j, "jaxpr") else j


@dataclass
class _WriteSite:
    """One scatter into a pool-role buffer."""
    eqn: object
    idx: int
    source: str
    root: str                    # pool buffer name (arg name)
    group: int                   # id() of the enclosing jaxpr body


@dataclass
class DeterminismResult:
    findings: list = field(default_factory=list)
    metrics: dict = field(default_factory=dict)

    def by_rule(self, rule_id):
        return [f for f in self.findings if f.rule_id == rule_id]

    def summary(self):
        m = self.metrics
        return (f"{m.get('n_pool_writes', 0)} pool write(s) "
                f"({m.get('n_canonical_writes', 0)} canonical), "
                f"{m.get('n_rng_sites', 0)} rng site(s), "
                f"{m.get('n_overlap_pairs', 0)} overlap pair(s) "
                f"({m.get('n_proven_disjoint', 0)} proven disjoint), "
                f"{len(self.findings)} finding(s)")


class _TaintEngine:
    """Forward taint/range/buffer-identity dataflow over one jaxpr,
    monotone in the taint lattice (sets only grow), so the scan/while
    carry fixed points terminate."""

    def __init__(self):
        self.taints = {}         # var -> set of tags
        self.roots = {}          # var -> pool buffer name
        self.alias = {}          # var -> donated arg name (view chain)
        self.ranges = {}         # var -> (lo, hi) static int range
        self.defs = {}           # var -> defining eqn
        self.writes = {}         # id(eqn) -> _WriteSite (insertion order)
        self.rng_sites = {}      # id(eqn) -> (eqn, source)
        self.eqn_ids = set()

    # ---------------------------------------------------- lattice ops

    def taint(self, v):
        if not _is_var(v):
            return _EMPTY
        return self.taints.get(v, _EMPTY)

    def _add(self, v, tags):
        if not _is_var(v) or not tags:
            return False
        cur = self.taints.get(v)
        if cur is None:
            self.taints[v] = set(tags)
            return True
        if tags <= cur:
            return False
        cur |= tags
        return True

    def _set_root(self, v, root):
        if not _is_var(v) or root is None or v in self.roots:
            return False
        self.roots[v] = root
        return True

    def _set_alias(self, v, name):
        if not _is_var(v) or name is None or v in self.alias:
            return False
        self.alias[v] = name
        return True

    def rangeof(self, v):
        if not _is_var(v):
            val = getattr(v, "val", None)
            try:
                iv = int(val)
                return (iv, iv)
            except (TypeError, ValueError, OverflowError):
                return None
        return self.ranges.get(v)

    def _set_range(self, v, r):
        # write-once: ranges are not monotone (a carry feedback would
        # widen forever), so the first — pre-feedback — value sticks
        if r is None or not _is_var(v) or v in self.ranges:
            return False
        self.ranges[v] = (int(r[0]), int(r[1]))
        return True

    # ------------------------------------------------------ the sweep

    def sweep(self, jx):
        changed = False
        for idx, eqn in enumerate(jx.eqns):
            changed |= self._transfer(jx, idx, eqn)
        return changed

    def _transfer(self, jx, idx, eqn):
        prim = eqn.primitive.name
        self.eqn_ids.add(id(eqn))
        for o in eqn.outvars:
            if _is_var(o):
                self.defs.setdefault(o, eqn)
        if prim == "scan":
            return self._scan(eqn)
        if prim == "while":
            return self._while(eqn)
        if prim == "cond":
            return self._cond(eqn)
        subs = _sub_jaxprs(eqn)
        if subs:
            return self._call(eqn, subs)

        ins = [self.taint(v) for v in eqn.invars]
        if prim == "select_n" and len(ins) > 1:
            # the predicate picks WHICH branch's bytes flow, it never
            # writes bytes itself: scratch routing / freeze masks stay
            # out of the canonical-index provenance (documented
            # approximation — a data-dependent SELECT of two indexes
            # is judged by the indexes, not the mask)
            out = set().union(*ins[1:])
        elif prim == "iota":
            out = {"iota"}
        else:
            out = set().union(*ins) if ins else set()

        changed = False
        for o in eqn.outvars:
            changed |= self._add(o, out)

        if prim in _RNG_PRIMS:
            self.rng_sites[id(eqn)] = (eqn, _eqn_source(eqn, idx))

        if prim in _SCATTER_PRIMS and eqn.invars and \
                _is_var(eqn.invars[0]):
            root = self.roots.get(eqn.invars[0])
            if root is not None:
                for o in eqn.outvars:
                    changed |= self._set_root(o, root)
                self.writes.setdefault(
                    id(eqn),
                    _WriteSite(eqn, idx, _eqn_source(eqn, idx), root,
                               id(jx)))
        elif prim in _VIEW_PRIMS and eqn.invars and \
                _is_var(eqn.invars[0]) and len(eqn.outvars) == 1:
            changed |= self._set_root(eqn.outvars[0],
                                      self.roots.get(eqn.invars[0]))
            if prim in _ALIAS_PRIMS:
                changed |= self._set_alias(eqn.outvars[0],
                                           self.alias.get(eqn.invars[0]))

        self._range_transfer(prim, eqn)
        return changed

    # ------------------------------------------------ static ranges

    def _range_transfer(self, prim, eqn):
        o = eqn.outvars[0] if eqn.outvars else None
        if o is None or not _is_var(o):
            return
        if prim == "iota":
            shape = eqn.params.get("shape") or getattr(
                getattr(o, "aval", None), "shape", None)
            d = int(eqn.params.get("dimension", 0) or 0)
            if shape and d < len(shape):
                self._set_range(o, (0, max(int(shape[d]) - 1, 0)))
            return
        rs = [self.rangeof(v) for v in eqn.invars]
        if prim == "add" and len(rs) == 2 and all(rs):
            self._set_range(o, (rs[0][0] + rs[1][0],
                                rs[0][1] + rs[1][1]))
        elif prim == "sub" and len(rs) == 2 and all(rs):
            self._set_range(o, (rs[0][0] - rs[1][1],
                                rs[0][1] - rs[1][0]))
        elif prim == "mul" and len(rs) == 2 and all(rs):
            cs = [a * b for a in rs[0] for b in rs[1]]
            self._set_range(o, (min(cs), max(cs)))
        elif prim == "min" and len(rs) == 2 and all(rs):
            self._set_range(o, (min(rs[0][0], rs[1][0]),
                                min(rs[0][1], rs[1][1])))
        elif prim == "max" and len(rs) == 2 and all(rs):
            self._set_range(o, (max(rs[0][0], rs[1][0]),
                                max(rs[0][1], rs[1][1])))
        elif prim == "rem" and len(rs) == 2 and all(rs) and \
                rs[1][0] == rs[1][1] and rs[1][0] > 0 and rs[0][0] >= 0:
            self._set_range(o, (0, min(rs[0][1], rs[1][0] - 1)))
        elif prim == "div" and len(rs) == 2 and all(rs) and \
                rs[1][0] == rs[1][1] and rs[1][0] > 0 and rs[0][0] >= 0:
            n = rs[1][0]
            self._set_range(o, (rs[0][0] // n, rs[0][1] // n))
        elif prim == "clamp" and len(rs) == 3 and all(rs):
            lo, x, hi = rs
            self._set_range(o, (max(lo[0], min(x[0], hi[1])),
                                max(lo[0], min(x[1], hi[1]))))
        elif prim == "concatenate" and rs and all(rs):
            self._set_range(o, (min(r[0] for r in rs),
                                max(r[1] for r in rs)))
        elif prim in ("lt", "le", "gt", "ge") and len(rs) == 2 and \
                all(rs):
            # statically-decided comparisons collapse the `.at[]`
            # negative-index normalization (select_n(lt(i, 0), i,
            # i + n)) back to the live branch
            (alo, ahi), (blo, bhi) = rs
            swap = prim in ("gt", "ge")
            if swap:
                (alo, ahi), (blo, bhi) = (blo, bhi), (alo, ahi)
            strict = prim in ("lt", "gt")
            if (ahi < blo) if strict else (ahi <= blo):
                self._set_range(o, (1, 1))
            elif (alo >= bhi) if strict else (alo > bhi):
                self._set_range(o, (0, 0))
            else:
                self._set_range(o, (0, 1))
        elif prim == "select_n" and len(rs) > 1:
            if rs[0] == (0, 0) and rs[1] is not None:
                self._set_range(o, rs[1])
            elif rs[0] == (1, 1) and len(rs) > 2 and rs[2] is not None:
                self._set_range(o, rs[2])
            elif all(rs[1:]):
                self._set_range(o, (min(r[0] for r in rs[1:]),
                                    max(r[1] for r in rs[1:])))
        elif prim in _STRIP_PRIMS or prim == "transpose":
            if rs and rs[0]:
                self._set_range(o, rs[0])

    # ----------------------------------------------- call boundaries

    def _map_in(self, outer, inner, carry_range=True, with_alias=False):
        changed = self._add(inner, self.taint(outer))
        if _is_var(outer):
            changed |= self._set_root(inner, self.roots.get(outer))
            if with_alias:
                changed |= self._set_alias(inner,
                                           self.alias.get(outer))
        if carry_range:
            changed |= self._set_range(inner, self.rangeof(outer))
        return changed

    def _map_out(self, inner, outer, with_alias=False,
                 carry_range=True):
        changed = self._add(outer, self.taint(inner))
        if _is_var(inner):
            changed |= self._set_root(outer, self.roots.get(inner))
            if with_alias:
                changed |= self._set_alias(outer,
                                           self.alias.get(inner))
            if carry_range:
                changed |= self._set_range(outer, self.rangeof(inner))
        return changed

    def _fixpoint(self, body, feedback):
        """Sweep `body` until the taint state stops changing, feeding
        carry outvars back into carry invars between sweeps."""
        changed = False
        for _ in range(_MAX_LOOP_SWEEPS):
            c = self.sweep(body)
            for src, dst in feedback:
                c |= self._add(dst, self.taint(src))
                if _is_var(src):
                    c |= self._set_root(dst, self.roots.get(src))
            changed |= c
            if not c:
                break
        return changed

    def _scan(self, eqn):
        body = _unclosed(eqn.params["jaxpr"])
        nc = int(eqn.params.get("num_consts", 0))
        ncar = int(eqn.params.get("num_carry", 0))
        ivs = list(eqn.invars)
        changed = False
        for i, iv in enumerate(ivs):
            if i >= len(body.invars):
                break
            # carry ranges are not stable across ticks (lens += 1);
            # consts and xs slices keep theirs
            changed |= self._map_in(
                iv, body.invars[i],
                carry_range=not (nc <= i < nc + ncar),
                with_alias=nc <= i < nc + ncar)
        feedback = [(body.outvars[i], body.invars[nc + i])
                    for i in range(ncar)
                    if i < len(body.outvars)
                    and nc + i < len(body.invars)]
        changed |= self._fixpoint(body, feedback)
        for i, ov in enumerate(eqn.outvars):
            if i >= len(body.outvars):
                break
            changed |= self._map_out(body.outvars[i], ov,
                                     with_alias=i < ncar,
                                     carry_range=i >= ncar)
        return changed

    def _while(self, eqn):
        cn = int(eqn.params.get("cond_nconsts", 0))
        bn = int(eqn.params.get("body_nconsts", 0))
        cond = _unclosed(eqn.params["cond_jaxpr"])
        body = _unclosed(eqn.params["body_jaxpr"])
        ivs = list(eqn.invars)
        changed = False
        for i in range(min(cn, len(cond.invars))):
            changed |= self._map_in(ivs[i], cond.invars[i])
        for i in range(min(bn, len(body.invars))):
            changed |= self._map_in(ivs[cn + i], body.invars[i])
        ncar = len(ivs) - cn - bn
        for i in range(ncar):
            ov = ivs[cn + bn + i]
            if bn + i < len(body.invars):
                changed |= self._map_in(ov, body.invars[bn + i],
                                        carry_range=False,
                                        with_alias=True)
            if cn + i < len(cond.invars):
                changed |= self._map_in(ov, cond.invars[cn + i],
                                        carry_range=False)
        feedback = [(body.outvars[i], body.invars[bn + i])
                    for i in range(min(ncar, len(body.outvars)))
                    if bn + i < len(body.invars)]
        changed |= self._fixpoint(body, feedback)
        changed |= self.sweep(cond)
        for i, ov in enumerate(eqn.outvars):
            if i < len(body.outvars):
                changed |= self._map_out(body.outvars[i], ov,
                                         with_alias=True,
                                         carry_range=False)
        return changed

    def _cond(self, eqn):
        branches = [_unclosed(b)
                    for b in eqn.params.get("branches", ())]
        ivs = list(eqn.invars)[1:]          # drop the branch index
        changed = False
        for br in branches:
            for ov, bv in zip(ivs, br.invars):
                changed |= self._map_in(ov, bv)
            changed |= self.sweep(br)
        for i, ov in enumerate(eqn.outvars):
            tags = set()
            for br in branches:
                if i < len(br.outvars):
                    tags |= self.taint(br.outvars[i])
                    changed |= self._set_root(
                        ov, self.roots.get(br.outvars[i])
                        if _is_var(br.outvars[i]) else None)
            changed |= self._add(ov, tags)
        return changed

    def _call(self, eqn, subs):
        changed = False
        for sub in subs:
            if len(sub.invars) == len(eqn.invars) and \
                    len(sub.outvars) == len(eqn.outvars):
                for ov, bv in zip(eqn.invars, sub.invars):
                    changed |= self._map_in(ov, bv, with_alias=True)
                changed |= self.sweep(sub)
                for bv, ov in zip(sub.outvars, eqn.outvars):
                    changed |= self._map_out(bv, ov, with_alias=True)
            else:
                changed |= self.sweep(sub)
        return changed

    # ------------------------------------------- index introspection

    def strip(self, v):
        for _ in range(32):
            if not _is_var(v):
                return v
            e = self.defs.get(v)
            if e is None or e.primitive.name not in _STRIP_PRIMS or \
                    not e.invars or not _is_var(e.invars[0]):
                return v
            v = e.invars[0]
        return v

    def index_components(self, idx_var):
        """The per-operand-dim index columns of a scatter's indices
        operand, when it is structurally a `concatenate` of broadcast
        columns (the `.at[pids, offs].set` lowering); None otherwise.
        Column order follows `scatter_dims_to_operand_dims`, so for
        pool buffers column 0 is the PAGE id and the last column the
        in-page OFFSET."""
        v = self.strip(idx_var)
        e = self.defs.get(v) if _is_var(v) else None
        if e is not None and e.primitive.name == "concatenate":
            return [self.strip(iv) for iv in e.invars]
        return None


# ------------------------------------------------------------ seeding


def _arg_tag(name):
    base = (name or "").split("/")[-1].split(".")[-1].lower()
    for tag, pat in _TAG_PATTERNS:
        if pat.match(base):
            return tag
    return f"arg:{base}" if base else None


def _seed(program):
    """(jaxpr, engine, donated) — taints from ArgInfo names/roles, pool
    buffer roots from `kv_cache_infos` (ONE cache definition shared
    with the memory pass), donation aliases, and integer const
    ranges."""
    import numpy as np
    jxc = program.jaxpr
    jx = _unclosed(jxc)
    infos = list(getattr(program, "arg_infos", None) or [])
    cache_ids = {id(i) for i in kv_cache_infos(infos)}
    eng = _TaintEngine()
    donated = []
    for k, v in enumerate(jx.invars):
        info = infos[k] if k < len(infos) else None
        if info is None:
            continue
        if getattr(info, "donated", False):
            name = info.name or f"arg{k}"
            donated.append(name)
            eng._set_alias(v, name)
        if id(info) in cache_ids:
            eng._set_root(v, info.name or f"arg{k}")
        elif info.role not in ("param", "opt_state", "gt_state",
                               "const", "lr"):
            tag = _arg_tag(info.name) or f"arg:{k}"
            eng._add(v, {tag})
    consts = list(getattr(jxc, "consts", None) or [])
    for cv, cval in zip(jx.constvars, consts):
        try:
            a = np.asarray(cval)
            if a.dtype.kind in "iu" and 0 < a.size <= (1 << 22):
                eng._set_range(cv, (int(a.min()), int(a.max())))
        except Exception:
            pass
    return jx, eng, donated


# ------------------------------------------------------- rule checks


def _const_only(tags):
    """Purely constant-derived: no request, layout, or foreign arg
    provenance at all (the scratch-page literal qualifies; an iota
    does not — it mints the "iota" tag)."""
    return not tags


def _check_kv_write(eng, site, findings):
    """KV-WRITE-NONCANONICAL for one pool scatter.  Returns True when
    the write is canonical."""
    eqn = site.eqn
    idx_op = eqn.invars[1] if len(eqn.invars) > 1 else None
    upd_op = eqn.invars[2] if len(eqn.invars) > 2 else None
    problems = []
    comps = eng.index_components(idx_op) if idx_op is not None else None
    if comps and len(comps) >= 2:
        page_t = eng.taint(comps[0])
        off_t = eng.taint(comps[-1])
        if "table" not in page_t and not _const_only(page_t):
            problems.append(
                f"page index carries {sorted(page_t)} without routing "
                "through the page table (or a constant scratch page)")
        if "position" not in off_t and not _const_only(off_t):
            problems.append(
                f"in-page offset carries {sorted(off_t)} with no "
                "POSITION provenance")
    elif idx_op is not None:
        t = eng.taint(idx_op)
        if not _const_only(t) and \
                not ("table" in t and "position" in t):
            problems.append(
                f"write index carries {sorted(t)} — canonical pool "
                "indexing derives the page from the TABLE and the "
                "offset from the POSITION")
    if upd_op is not None and "draft" in eng.taint(upd_op):
        problems.append(
            "written values carry DRAFT provenance: speculative "
            "proposals land in real pages before acceptance (the "
            "verify-window expected red — commit-on-accept turns "
            "this green)")
    for p in problems:
        findings.append(Finding(
            "KV-WRITE-NONCANONICAL", Severity.ERROR,
            f"{site.source} writes pool buffer '{site.root}' but {p} "
            "— a resume/restore/CoW replay of this request would "
            "reproduce different page bytes",
            op=site.source,
            suggested_fix="derive the page id from the request's page "
            "table row and the offset from its sequence position; "
            "route masked/frozen rows to the reserved scratch page "
            "instead of folding layout into the index"))
    return not problems


def _ranges_disjoint(a, b):
    return a is not None and b is not None and \
        (a[1] < b[0] or b[1] < a[0])


def _page_operand(eng, site):
    eqn = site.eqn
    idx_op = eqn.invars[1] if len(eqn.invars) > 1 else None
    if idx_op is None:
        return None
    comps = eng.index_components(idx_op)
    return comps[0] if comps else eng.strip(idx_op)


def _offset_operand(eng, site):
    comps = eng.index_components(site.eqn.invars[1]) \
        if len(site.eqn.invars) > 1 else None
    return comps[-1] if comps and len(comps) >= 2 else None


def _proven_disjoint(eng, a, b):
    """Three provers, any one suffices:
    (1) disjoint static page windows; (2) the same page-id vector with
    disjoint static offsets; (3) distinct row-id provenance — both
    page ids gathered from the SAME table with disjoint static gather
    windows."""
    pa, pb = _page_operand(eng, a), _page_operand(eng, b)
    if pa is None or pb is None:
        return False
    if _ranges_disjoint(eng.rangeof(pa), eng.rangeof(pb)):
        return True
    if pa is pb:
        oa, ob = _offset_operand(eng, a), _offset_operand(eng, b)
        if oa is not None and ob is not None and \
                _ranges_disjoint(eng.rangeof(oa), eng.rangeof(ob)):
            return True
    ga = eng.defs.get(pa) if _is_var(pa) else None
    gb = eng.defs.get(pb) if _is_var(pb) else None
    if ga is not None and gb is not None and \
            ga.primitive.name == "gather" and \
            gb.primitive.name == "gather" and \
            len(ga.invars) > 1 and len(gb.invars) > 1 and \
            ga.invars[0] is gb.invars[0]:
        ra = eng.rangeof(eng.strip(ga.invars[1]))
        rb = eng.rangeof(eng.strip(gb.invars[1]))
        if _ranges_disjoint(ra, rb):
            return True
    return False


# ------------------------------------------------------- entry point


def analyze_determinism(program, ctx=None):
    """Run the full determinism dataflow over one `LoweredProgram` and
    evaluate every rule.  Deterministic: one cached CPU trace walks to
    the same fixed point on every machine."""
    jx, eng, donated = _seed(program)
    for _ in range(_MAX_LOOP_SWEEPS):
        if not eng.sweep(jx):
            break

    res = DeterminismResult()
    findings = res.findings

    # rule 1: canonical pool writes (+ the draft-value expected red)
    n_canonical = 0
    sites = list(eng.writes.values())
    for site in sites:
        if _check_kv_write(eng, site, findings):
            n_canonical += 1

    # rule 2: RNG key provenance
    for eqn, source in eng.rng_sites.values():
        tags = set()
        for v in eqn.invars:
            tags |= eng.taint(v)
        extra = tags - RNG_ALLOWED_TAGS
        if extra:
            findings.append(Finding(
                "RNG-KEY-TAINT", Severity.ERROR,
                f"{source} folds {sorted(extra)} into a sampling key "
                "— the stream would depend on batch composition or "
                "table layout, not only on (seed, rid, position)",
                op=source,
                suggested_fix="derive every per-request key as "
                "fold_in(fold_in(PRNGKey(seed), rid), position); "
                "never fold slot indexes, batch order, or table rows"))

    # rule 3: write-write overlap inside one loop/tick body
    groups = {}
    for site in sites:
        groups.setdefault((site.root, site.group), []).append(site)
    n_pairs = n_proven = 0
    for (root, _gid), group in sorted(
            groups.items(), key=lambda kv: (kv[0][0], kv[0][1])):
        for a, b in combinations(group, 2):
            n_pairs += 1
            if _proven_disjoint(eng, a, b):
                n_proven += 1
                continue
            findings.append(Finding(
                "SCATTER-WRITE-OVERLAP", Severity.ERROR,
                f"two scatters into pool buffer '{root}' in one body "
                f"({a.source} and {b.source}) have index sets that "
                "cannot be proven disjoint — a device-side "
                "write-write race; which bytes land is "
                "schedule-dependent",
                op=f"{a.source} / {b.source}",
                suggested_fix="give each writer its own page window, "
                "route one side to the scratch page, or key both "
                "through disjoint rows of the page table"))

    # rule 4: donated buffer aliased straight to an output
    n_alias = 0
    for ov in jx.outvars:
        if _is_var(ov) and ov in eng.alias:
            n_alias += 1
            findings.append(Finding(
                "DONATE-HOST-ALIAS", Severity.ERROR,
                f"donated argument '{eng.alias[ov]}' is returned as "
                "an output without an intervening defining write — "
                "the host still holds the donated buffer while XLA "
                "reuses it (the PR-4/PR-13 segfault class)",
                op=eng.alias[ov],
                suggested_fix="drop the donation for pass-through "
                "leaves, or materialize the output with an actual "
                "update (scatter/dynamic_update_slice) so XLA emits "
                "a fresh buffer"))

    findings.sort(key=lambda f: (f.rule_id, f.op or "", f.message))
    rules = {}
    for f in findings:
        rules[f.rule_id] = rules.get(f.rule_id, 0) + 1
    res.metrics = {
        "n_eqns": len(eng.eqn_ids),
        "n_pool_buffers": len({s.root for s in sites})
        if sites else len(kv_cache_infos(
            list(getattr(program, "arg_infos", None) or []))),
        "n_pool_writes": len(sites),
        "n_canonical_writes": n_canonical,
        "n_rng_sites": len(eng.rng_sites),
        "n_overlap_pairs": n_pairs,
        "n_proven_disjoint": n_proven,
        "n_donated_args": len(donated),
        "n_alias_outputs": n_alias,
        "rules": rules,
    }
    return res


@register_analyzer
class DeterminismAnalyzer(Analyzer):
    """Determinism Doctor graph pass: taint-provenance dataflow +
    KV-WRITE-NONCANONICAL / RNG-KEY-TAINT / SCATTER-WRITE-OVERLAP /
    DONATE-HOST-ALIAS (rule docs in the module docstring and
    docs/static_analysis.md).  Metrics feed
    determinism_manifests/<config>.json for the serving PROGRAM
    configs."""
    name = "determinism"

    def run(self, program, ctx):
        if getattr(program, "jaxpr", None) is None:
            self.metrics = {"available": False}
            return []
        res = analyze_determinism(program, ctx)
        self.metrics = {"available": True, **res.metrics}
        return res.findings
