"""GSPMD sharding lint — flags the silent ways a distributed program
wastes HBM or wire bandwidth, from the captured argument shardings
(`LoweredProgram.arg_infos`) plus the lowered collectives.

Rules (docs/static_analysis.md):
  SHARD-REPLICATED-BIG      a tensor above the size threshold is fully
                            replicated while the mesh has model-sharding
                            axes — every device pays full price
  SHARD-OPT-STATE-UNSHARDED optimizer state replicated under a ZeRO/
                            fsdp config whose params ARE sharded (the
                            classic silent 2-3x HBM leak: slots must
                            inherit the param sharding)
  SHARD-MID-PROGRAM-RESHARD collective_permute / all_to_all in the
                            step — a spec mismatch made GSPMD move the
                            tensor mid-program (exempt by-design ones,
                            e.g. MoE dispatch, via
                            ctx.allowed_resharding regexes)
  SHARD-WIRE-REGRESSION     total analytic wire bytes (cost_model ring
                            formulas) drifted above the committed memory
                            manifest beyond tolerance — a collective got
                            bigger or a new one appeared
  SHARD-UNKNOWN-PAYLOAD     a collective whose payload can't be sized
                            from the HLO types (symbolic dims) — the
                            wire accounting under-reports
  SHARD-PROP-DIVERGENCE     the fixed-point propagation pass
                            (analysis/propagation.py) disagrees with a
                            sharding_constraint pin or a lowered
                            mhlo.sharding annotation — GSPMD inserts an
                            implicit reshard (or silent replication)
                            the HBM/wire pricing missed
  SHARD-LOOP-CARRY-RESHARD  a scan/while carry whose body OUTPUT spec
                            mismatches its carry INPUT spec — a
                            reshard on every loop iteration, inside
                            the hot loop

Metrics: replicated big-tensor count/bytes, per-role shard coverage,
the cost-model wire-byte total the memory manifest pins, and the
propagation pass's divergence/agreement counters.
"""
import re

from .findings import Finding, Severity
from .pass_manager import Analyzer, register_analyzer

__all__ = ["ShardingAnalyzer", "RESHARD_OPS", "SHARDING_AXES"]

# collectives GSPMD inserts when producer/consumer specs disagree
RESHARD_OPS = ("collective_permute", "all_to_all")

# mesh axes that shard MODEL state (dp replicates params by design, so
# it never triggers the replication rules on its own)
SHARDING_AXES = ("fsdp", "tp", "sp", "ep")


@register_analyzer
class ShardingAnalyzer(Analyzer):
    name = "sharding"

    def run(self, program, ctx):
        from ..cost_model import collective_wire_bytes
        from .lowering import tensor_type_bytes

        findings = []
        infos = getattr(program, "arg_infos", None) or []
        mesh_axes = ctx.mesh_axes or {}
        sharding_size = 1
        for a in SHARDING_AXES:
            sharding_size *= int(mesh_axes.get(a, 1))
        n_devices = 1
        for s in mesh_axes.values():
            n_devices *= int(s)

        threshold = ctx.replicated_bytes_threshold
        replicated = [i for i in infos
                      if i.shard_count <= 1 and i.bytes >= threshold]
        sharded_param_shapes = {tuple(i.shape) for i in infos
                                if i.role == "param" and i.shard_count > 1}
        if sharding_size > 1:
            for info in replicated:
                if info.role == "opt_state":
                    continue   # covered by the dedicated rule below
                sev = (Severity.ERROR if info.role == "param"
                       and mesh_axes.get("fsdp", 1) > 1
                       else Severity.WARNING)
                findings.append(Finding(
                    "SHARD-REPLICATED-BIG", sev,
                    f"{info.role} `{info.name}` ({info.bytes} bytes, "
                    f"shape {list(info.shape)}) is replicated on all "
                    f"{n_devices} devices under a model-sharding mesh "
                    f"{dict(mesh_axes)}",
                    suggested_fix="give it a partition_spec (or let the "
                    "fsdp planner shard it: check min_fsdp_numel and "
                    "dim divisibility)"))
        # ZeRO promise: optimizer slots inherit the param sharding. A
        # replicated slot whose same-shape param IS sharded broke it.
        for info in infos:
            if info.role != "opt_state" or info.shard_count > 1 or \
                    info.bytes < threshold:
                continue
            if tuple(info.shape) in sharded_param_shapes or \
                    mesh_axes.get("fsdp", 1) > 1:
                findings.append(Finding(
                    "SHARD-OPT-STATE-UNSHARDED", Severity.ERROR,
                    f"optimizer state `{info.name}` ({info.bytes} bytes) "
                    "is replicated while the mesh shards parameters — "
                    "ZeRO semantics lost, every device holds the full "
                    "slot",
                    suggested_fix="init slots with zeros_like under jit "
                    "so they inherit the param sharding, or device_put "
                    "them with the param's NamedSharding"))

        allowed = [re.compile(p) for p in ctx.allowed_resharding]
        n_reshards = 0
        for op in program.ops_named(*RESHARD_OPS):
            if any(p.search(op.line) for p in allowed):
                continue
            n_reshards += 1
            findings.append(Finding(
                "SHARD-MID-PROGRAM-RESHARD", Severity.WARNING,
                f"{op.name} moves data mid-program — producer and "
                "consumer shardings disagree, so GSPMD inserted a "
                "reshard on the step's critical path", op=op.line,
                suggested_fix="align the sharding_constraint specs on "
                "both sides (distributed.sharding_utils.constraint), or "
                "exempt a by-design dispatch via "
                "context.allowed_resharding"))

        # analytic wire volume (ring formulas) — the collective budget
        # the memory manifest pins
        total_wire = 0
        n_unknown = 0
        from .analyzers import COLLECTIVE_OPS
        for op in program.ops_named(*COLLECTIVE_OPS):
            group, _ = op.replica_group_size()
            payload = max(op.operand_bytes(),
                          sum(tensor_type_bytes(t)
                              for t in op.result_types))
            if payload == 0 and (group or 1) > 1:
                n_unknown += 1
                findings.append(Finding(
                    "SHARD-UNKNOWN-PAYLOAD", Severity.INFO,
                    f"{op.name} payload could not be sized from the "
                    "HLO types — wire accounting under-reports",
                    op=op.line))
            total_wire += collective_wire_bytes(op.name, payload,
                                                group or 1)
        committed = (ctx.memory_manifest or {}).get("collectives", {})
        want_wire = committed.get("total_wire_bytes")
        tol = ctx.memory_tolerance
        if want_wire is not None and \
                total_wire > max(want_wire * (1 + tol), want_wire + 1024):
            findings.append(Finding(
                "SHARD-WIRE-REGRESSION", Severity.ERROR,
                f"analytic collective wire bytes {total_wire} exceed "
                f"the committed manifest's {want_wire} by more than "
                f"{tol:.0%} — a collective grew or a new one appeared",
                suggested_fix="diff the collectives against the "
                "manifest (python -m paddle_tpu.analysis --memory) and "
                "regenerate if intentional"))

        # propagation cross-check lints: the fixed-point pass
        # (registered before this one) stashed its result on ctx
        from .propagation import result_for
        prop = result_for(program, ctx)
        n_prop_div = n_loop_reshard = 0
        if prop is not None:
            for d in prop.divergences:
                n_prop_div += 1
                findings.append(Finding(
                    "SHARD-PROP-DIVERGENCE", Severity.WARNING,
                    f"static propagation says {d['propagated']} at "
                    f"{d['source']} but the pinned/lowered sharding is "
                    f"{d['annotated']} — GSPMD resolves the mismatch "
                    "with an implicit reshard (or silent replication) "
                    "the HBM/wire pricing missed",
                    suggested_fix="align the producer's spec with the "
                    "constraint (or fix the constraint): the upstream "
                    "with_sharding_constraint / in_shardings and this "
                    "pin must agree, or the move is priced on the "
                    "step's critical path"))
            for r in prop.loop_reshards:
                n_loop_reshard += 1
                findings.append(Finding(
                    "SHARD-LOOP-CARRY-RESHARD", Severity.WARNING,
                    f"loop carry #{r['carry']} at {r['source']} enters "
                    f"the body as {r['in']} but leaves as {r['out']} — "
                    "GSPMD reshards the carry on EVERY iteration, "
                    "inside the hot loop",
                    suggested_fix="make the body produce the carry in "
                    "its input spec (move the with_sharding_constraint "
                    "out of the loop, or constrain the carry init to "
                    "the body's output spec)"))

        self.metrics = {
            "n_args": len(infos),
            "n_replicated_big": len(replicated),
            "replicated_big_bytes": sum(i.bytes for i in replicated),
            "n_mid_program_reshards": n_reshards,
            "total_wire_bytes": total_wire,
            "sharded_by_role": self._role_coverage(infos),
            "n_prop_divergences": n_prop_div,
            "n_loop_carry_reshards": n_loop_reshard,
            "prop_agreement_rate": (round(prop.agreement_rate, 4)
                                    if prop is not None else None),
        }
        return findings

    @staticmethod
    def _role_coverage(infos):
        """{role: [sharded_leaves, total_leaves]} — quick coverage view."""
        cov = {}
        for i in infos:
            role = i.role or "input"
            n_sharded, n_total = cov.get(role, (0, 0))
            cov[role] = (n_sharded + (1 if i.shard_count > 1 else 0),
                         n_total + 1)
        return {k: list(v) for k, v in sorted(cov.items())}
