"""Static per-device HBM estimation — a jaxpr-order liveness analysis.

The estimator walks the closed jaxpr in equation order and tracks the
set of live buffers: non-donated arguments and constants live for the
whole execution (XLA cannot reuse caller-owned buffers), donated
arguments free at their last use (the buffer is recycled into outputs —
exactly the Trainer's params/opt-state donation), intermediates live
from definition to last use, program outputs to the end.  Each buffer's
per-device cost is its global size divided by its sharding's shard
count (replicated tensors cost full size on EVERY device).  Equations
carrying sub-jaxprs (pjit, scan, while, cond, remat, custom_vjp)
contribute their own recursive transient peak on top of the outer live
set, so inner temporaries aren't silently dropped.

The peak is attributed to the top-k live buffers at the peak program
point with their defining ops — the "what do I shard/remat/donate to
fit" answer, produced on CPU before a chip sees the program
(liveness-as-a-pass after TPU-MLIR, arxiv 2210.15016; the memory half
of MPK-style per-program planning, arxiv 2512.22219).

Cross-check: on jaxlibs whose `compiled.memory_analysis()` works on
CPU, `cpu_calibrated=True` reproduces the XLA CPU buffer model (no
native bf16 MXU there: sub-f32 floats widen to f32 temporaries, and
dot operands get materialized f32 conversion copies) so the estimate
lands within the lint gate's tolerance of XLA's own number.  Manifests
and TPU advice always use the native-width (uncalibrated) estimate.
"""
import re
from dataclasses import dataclass, field

from .findings import Finding, Severity
from .pass_manager import Analyzer, register_analyzer

__all__ = ["MemoryAnalyzer", "MemoryEstimate", "estimate_jaxpr_memory",
           "propagate_shard_counts", "audit_page_ledger",
           "PageRefcountAnalyzer"]

# arg names that identify decode-loop KV-cache state when the capture
# didn't assign an explicit role="cache" (serving front doors do)
_KV_CACHE_RE = re.compile(r"(^|[/.])(k|v|kv)?_?(cache|pages)(s)?([/.]|$)",
                          re.IGNORECASE)


def kv_cache_infos(arg_infos):
    """The args that count as decode-loop KV-cache state: explicit
    role="cache", or cache-looking names on args that aren't
    params/optimizer slots. ONE definition shared by
    MEM-NO-DONATION-KVCACHE and SERVE-HOST-SYNC-DECODE, so the two
    rules can never disagree about what the cache is."""
    return [i for i in arg_infos
            if i.role == "cache"
            or (i.role not in ("param", "opt_state", "gt_state")
                and _KV_CACHE_RE.search(i.name or ""))]

# primitives whose sub-f32 operands XLA CPU materializes as f32 copies
# (no native bf16 matmul path on the host; convolutions lower through a
# different path that fuses the widening and shows no copy)
_CPU_WIDENED_MXU = ("dot_general",)

# ops small enough that attributing the peak to them is noise
_ATTRIBUTION_MIN_BYTES = 1024


def _aval_bytes(aval, widen_sub_f32=False):
    """Byte size of one abstract value; 0 when shape/dtype is unknown.
    `widen_sub_f32` models XLA CPU's f32 compute width for bf16/f16."""
    import numpy as np
    try:
        import jax.numpy as jnp
        itemsize = aval.dtype.itemsize
        if widen_sub_f32 and itemsize < 4 and \
                jnp.issubdtype(aval.dtype, jnp.floating):
            itemsize = 4
        return int(np.prod(aval.shape, dtype=np.int64)) * itemsize
    except Exception:
        return 0


def _sub_jaxprs(eqn):
    """All Jaxprs hiding in an eqn's params (pjit/scan/while/cond/...)."""
    found = []
    for v in eqn.params.values():
        vs = v if isinstance(v, (list, tuple)) else (v,)
        for x in vs:
            tn = type(x).__name__
            if tn == "ClosedJaxpr":
                found.append(x.jaxpr)
            elif tn == "Jaxpr":
                found.append(x)
    return found


def _is_var(v):
    return type(v).__name__ != "Literal"


@dataclass
class LiveBuffer:
    """One buffer in the live set at the peak point."""
    op: str                      # defining primitive ("argument" for invars)
    name: str                    # arg name / "eqn12:dot_general output"
    bytes: int                   # global size
    device_bytes: int            # bytes / shard_count
    shard_count: int = 1
    role: str = None             # arg role when the buffer is an argument

    def to_dict(self):
        d = {"op": self.op, "name": self.name, "bytes": self.bytes,
             "device_bytes": self.device_bytes,
             "shard_count": self.shard_count}
        if self.role:
            d["role"] = self.role
        return d


@dataclass
class MemoryEstimate:
    """Static per-device HBM footprint of one lowered program."""
    peak_bytes: int = 0          # per-device peak live bytes
    args_bytes: int = 0          # per-device resident arguments
    out_bytes: int = 0           # per-device program outputs
    temp_peak_bytes: int = 0     # peak minus always-resident args
    donated_bytes: int = 0       # per-device donated-arg bytes (credit)
    peak_eqn: int = -1           # eqn index where the peak occurs
    peak_op: str = ""            # primitive at the peak point
    top: list = field(default_factory=list)   # top-k LiveBuffers at peak
    cpu_calibrated: bool = False
    n_hosts: int = 1             # hosts the mesh spans (1 = single host)
    host_peak_bytes: int = 0     # distinct bytes resident per host at peak
    host_args_bytes: int = 0     # distinct argument bytes per host

    def to_dict(self):
        d = {"peak_bytes": self.peak_bytes,
             "args_bytes": self.args_bytes,
             "out_bytes": self.out_bytes,
             "temp_peak_bytes": self.temp_peak_bytes,
             "donated_bytes": self.donated_bytes,
             "peak_eqn": self.peak_eqn, "peak_op": self.peak_op,
             "top_live": [b.to_dict() for b in self.top]}
        if self.n_hosts > 1:
            d["per_host"] = {"n_hosts": self.n_hosts,
                             "peak_bytes": self.host_peak_bytes,
                             "args_bytes": self.host_args_bytes}
        return d

    def __str__(self):
        gib = 1024.0 ** 3
        resident = self.args_bytes - self.donated_bytes
        lines = [f"per-device peak: {self.peak_bytes / gib:.4f} GiB = "
                 f"resident args {resident / gib:.4f} + working set "
                 f"{self.temp_peak_bytes / gib:.4f} (donation frees "
                 f"{self.donated_bytes / gib:.4f})"]
        if self.n_hosts > 1:
            lines.append(
                f"per-host peak ({self.n_hosts} hosts): "
                f"{self.host_peak_bytes / gib:.4f} GiB distinct bytes "
                f"(args {self.host_args_bytes / gib:.4f}) — dp shards "
                "replicated within a host are counted once")
        for b in self.top:
            lines.append(f"  {b.device_bytes:>12d} B  {b.op:<16} {b.name}")
        return "\n".join(lines)


def _eqn_source(eqn, idx):
    """Short human label for an eqn's output buffer."""
    prim = eqn.primitive.name
    try:
        from jax._src import source_info_util
        frame = source_info_util.user_frame(eqn.source_info)
        if frame is not None:
            import os
            return (f"{prim} @ {os.path.basename(frame.file_name)}:"
                    f"{frame.start_line}")
    except Exception:
        pass
    return f"{prim} #eqn{idx}"


def _inner_transient(jx, widen, memo):
    """Transient extra bytes an eqn's sub-jaxpr adds on top of the outer
    live set (its own peak minus its invars, which are already counted
    as live operands outside)."""
    key = id(jx)
    if key not in memo:
        peak, _, _ = _walk(jx, arg_counts=None, donated=(), widen=widen,
                           pin_invars=False, memo=memo)
        inb = sum(_aval_bytes(v.aval) for v in jx.invars)
        memo[key] = max(0, peak - inb)
    return memo[key]


def _walk(jx, arg_counts, donated, widen, pin_invars, memo, top_k=0,
          arg_infos=None, last_use_override=None, extra_after=None,
          var_counts=None, count_cap=None):
    """Liveness walk of one jaxpr. Returns (peak, peak_eqn_idx,
    top_buffers_at_peak).

    `last_use_override` ({var: eqn_idx}) truncates live ranges — the
    remat advisor's what-if replay drops checkpointed intermediates by
    ending them at their last FORWARD use. `extra_after` ((idx, bytes))
    adds a flat byte bump to every program point past idx — the
    advisor's model of one segment's recompute working set during the
    backward. Output vars are never truncated.

    `var_counts` ({var: shard_count}, typically
    `propagation.PropagationResult.counts`) overrides the inline
    forward propagation per var where present: the fixed-point pass
    sees constraint pins and consumer-implied specs this single
    forward sweep can't, so its counts are used when available and the
    inline `_eqn_out_shard` result is the documented conservative
    fallback for vars the pass left unknown.

    `count_cap` clamps every shard count to at most this value — the
    per-host accounting's knob: divided by min(count, n_hosts), a
    buffer's contribution is its distinct bytes per host."""
    last_use = {}
    for i, eqn in enumerate(jx.eqns):
        for v in eqn.invars:
            if _is_var(v):
                last_use[v] = i
    n = len(jx.eqns)
    for v in jx.outvars:
        if _is_var(v):
            last_use[v] = n
    if last_use_override:
        for v, idx in last_use_override.items():
            if last_use.get(v, n) < n:
                last_use[v] = idx
    bump_after, bump = extra_after if extra_after else (n + 1, 0)
    invars = list(jx.invars)
    if pin_invars:
        # non-donated arguments + baked constants are caller-owned: XLA
        # keeps them resident for the whole execution
        for k, v in enumerate(invars):
            if not (donated and k < len(donated) and donated[k]):
                last_use[v] = n
        for v in jx.constvars:
            last_use[v] = n

    counts = {}          # var -> shard count (propagated)
    dimmap = {}          # var -> per-dim shard counts (None = unknown)
    live = {}            # var -> (device_bytes, LiveBuffer)
    for k, v in enumerate(invars):
        if arg_infos and k < len(arg_infos):
            dimmap[v] = getattr(arg_infos[k], "dim_shards", None)
        if v not in last_use:
            continue
        cnt = arg_counts[k] if arg_counts and k < len(arg_counts) else 1
        if count_cap:
            cnt = min(max(cnt, 1), count_cap)
        counts[v] = cnt
        info = (arg_infos[k] if arg_infos and k < len(arg_infos) else None)
        gb = _aval_bytes(v.aval)
        live[v] = (gb // max(cnt, 1), LiveBuffer(
            op="argument",
            name=info.name if info else f"arg{k}",
            bytes=gb, device_bytes=gb // max(cnt, 1), shard_count=cnt,
            role=info.role if info else None))
    for v in jx.constvars:
        if v in last_use:
            gb = _aval_bytes(v.aval)
            live[v] = (gb, LiveBuffer(op="constant", name="const",
                                      bytes=gb, device_bytes=gb))

    cur = sum(b for b, _ in live.values())
    peak, peak_idx, peak_top = cur, -1, list(live.values())
    for i, eqn in enumerate(jx.eqns):
        inner = 0
        for sj in _sub_jaxprs(eqn):
            inner = max(inner, _inner_transient(sj, widen, memo))
        if widen and eqn.primitive.name in _CPU_WIDENED_MXU:
            # XLA CPU materializes f32 conversion copies of sub-f32
            # dot operands (bf16 has no host MXU path)
            for v in eqn.invars:
                if _is_var(v):
                    w = _aval_bytes(v.aval, widen_sub_f32=True)
                    if w > _aval_bytes(v.aval):
                        inner += w
        # sharding propagation: an op's result is at best as sharded as
        # its most-sharded operand (GSPMD propagates along data paths;
        # a reduction to scalar only shrinks the buffer, so the error
        # is bounded by the tiny result) — refined by _eqn_out_shard
        # where per-dim counts are known (contracted dot_general dims
        # drop their sharding instead of leaking into the output)
        ivs = [v for v in eqn.invars if _is_var(v)]
        out_count, out_dims = _eqn_out_shard(
            eqn, [counts.get(v, 1) for v in ivs],
            [dimmap.get(v) for v in ivs])
        for v in eqn.outvars:
            dimmap[v] = out_dims
            if v in last_use:
                cnt = (var_counts[v]
                       if var_counts is not None and v in var_counts
                       else out_count)
                if count_cap:
                    cnt = min(max(cnt, 1), count_cap)
                counts[v] = cnt
                gb = _aval_bytes(v.aval, widen_sub_f32=widen)
                db = gb // max(cnt, 1)
                live[v] = (db, LiveBuffer(
                    op=eqn.primitive.name, name=_eqn_source(eqn, i),
                    bytes=gb, device_bytes=db, shard_count=cnt))
                cur += db
        extra = bump if i > bump_after else 0
        if cur + inner + extra > peak:
            peak, peak_idx = cur + inner + extra, i
            peak_top = list(live.values())
        for v in list(eqn.invars) + list(eqn.outvars):
            if _is_var(v) and last_use.get(v) == i and v in live:
                cur -= live.pop(v)[0]
    top = []
    if top_k:
        top = sorted((b for _, b in peak_top
                      if b.device_bytes >= _ATTRIBUTION_MIN_BYTES),
                     key=lambda b: -b.device_bytes)[:top_k]
    return peak, peak_idx, top


def _reshape_dim_shards(in_shape, in_dims, out_shape):
    """Per-dim shard counts across a reshape, or None when the mapping
    isn't clean. Contiguous dim groups with equal element products map
    onto each other (the standard reshape factorization); a group's
    shard factor is the product of the factors of its FULLY-SHARDED
    major prefix (every dim before the first partially-sharded one
    contributes — merging dims sharded whole keeps a contiguous
    row-major split) plus at most one trailing partial factor, and is
    peeled onto the group's output dims major-first, WHOLE DIMS at a
    time: an output dim is either covered entirely by the split (its
    full size divides the remaining factor) or carries the remainder
    when that divides it — so a 4-way factor lands on (2, 2, ...) as
    (2, 2) and on (8, ...) as (4,), while a peel that would make one
    shard straddle a tile boundary (neither divides) returns None.
    Also None: a factor on a MINOR input dim (a partially-sharded or
    unsharded non-unit dim more major than it in the group) — a
    row-major merge turns minor-dim sharding into a STRIDED pattern of
    the merged dim, so pinning the factor anywhere would silently
    migrate shard knowledge to the wrong dimension — an
    anti-conservative per-device underestimate, the exact failure the
    conservative cap exists to prevent."""
    n, m = len(in_shape), len(out_shape)
    out = []
    i = j = 0
    while i < n and j < m:
        gi, gj = [i], [j]
        pi, pj = int(in_shape[i]), int(out_shape[j])
        i += 1
        j += 1
        while pi != pj:
            if pi < pj:
                if i >= n:
                    return None
                pi *= int(in_shape[i])
                gi.append(i)
                i += 1
            else:
                if j >= m:
                    return None
                pj *= int(out_shape[j])
                gj.append(j)
                j += 1
        factor = 1
        whole_prefix = True                  # fully-sharded so far?
        for g in gi:                         # major -> minor
            f = int(in_dims[g])
            sh = int(in_shape[g])
            if f > 1:
                if not whole_prefix:         # factor on a minor dim:
                    return None              # strided, unrepresentable
                factor *= f
                if f != sh:                  # partial split ends the
                    whole_prefix = False     # mergeable prefix
            elif sh > 1:
                whole_prefix = False
        group = [1] * len(gj)
        f = factor
        for pos, g in enumerate(gj):         # peel major-first
            if f == 1:
                break
            od = int(out_shape[g])
            if f >= od:
                if f % od:
                    return None              # shard straddles the tile
                group[pos] = od
                f //= od
            else:
                if od % f:
                    return None
                group[pos] = f
                f = 1
        if f != 1:
            return None
        out.extend(group)
    # trailing size-1 dims on either side carry no sharding
    while i < n:
        if int(in_shape[i]) != 1 or int(in_dims[i]) != 1:
            return None
        i += 1
    while j < m:
        if int(out_shape[j]) != 1:
            return None
        out.append(1)
        j += 1
    return tuple(out)


# the reduce family whose output drops shard factors on reduced dims
# (argmax/argmin carry `axes` params exactly like lax.reduce_* eqns)
_REDUCE_PRIMS = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_and", "reduce_or", "reduce_xor", "argmax", "argmin"})

# the scatter family (x.at[idx].set/add/... lowerings): output shape ==
# operand shape, and the operand's dim sharding threads EXCEPT on the
# dynamically indexed dims
_SCATTER_PRIMS = frozenset({
    "scatter", "scatter-add", "scatter-mul", "scatter-min",
    "scatter-max"})


def _eqn_out_shard(eqn, in_counts, in_dims):
    """Shard propagation for one eqn's outputs: (total_count, per-dim
    counts or None). The default heuristic — a result is at best as
    sharded as its most-sharded operand — is refined where per-DIM
    shard counts are known (seeded from ArgInfo.dim_shards):

    * `dot_general` respects contracted dims: sharding on a contracted
      axis does NOT survive into the output (GSPMD all-reduces the
      partial products; the result is replicated over that mesh axis),
      so a tensor-parallel intermediate stops inheriting
      max(operand counts) blindly. Output dims follow the dot layout
      (batch, lhs free, rhs free).
    * the reduce family (`reduce_sum`/`reduce_max`/... and
      `argmax`/`argmin`) drops shard factors on REDUCED dims — a
      reduction over a sharded axis all-reduces the per-shard partials
      (reduce_sum is a contraction against ones), so the output is
      replicated over that mesh axis; kept dims thread through.
    * `reshape` tracks split/merge dims: a sharded dim's factor follows
      its contiguous factor group into the output when divisibility
      holds (`_reshape_dim_shards`), falling back to the conservative
      cap otherwise — so dp/tp knowledge survives the [B, S, H·D] <->
      [B·S, H, D] style reshapes between attention matmuls.
    * `concatenate` / `pad` / `slice` thread factors through UNTOUCHED
      dims and drop them on the structural ones: the concat dim (pieces
      land at per-operand offsets), padded dims (offsets shift), and
      statically under-sliced or strided dims (the kept span crosses
      shard boundaries) — while a dim every operand agrees on, or one
      taken whole at stride 1, keeps its factor. This is what lets
      dp/tp knowledge survive KV-cache style concat-and-slice chains.
    * `gather` / `dynamic_slice` drop shard factors on DYNAMICALLY
      indexed dims (start_index_map / runtime slice starts): rows read
      from dynamic positions admit no static split, so the result is
      at best replicated on that mesh axis — while dims taken whole
      (full slice size, not index-addressed) thread their factor, the
      exact mirror of the scatter rule's write side. Capped at the
      most-sharded operand like every slice above.
    * shape-preserving ops (elementwise chains) inherit the matching
      operand's dim vector, `transpose` permutes it — so dim knowledge
      survives between matmuls instead of dying at the first add/ln.
    """
    name = eqn.primitive.name
    try:
        if name == "dot_general" and len(in_dims) >= 2 and \
                in_dims[0] is not None and in_dims[1] is not None:
            (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
            ld, rd = in_dims[0], in_dims[1]
            batch = [ld[i] for i in lb]
            lfree = [ld[i] for i in range(len(ld))
                     if i not in set(lc) | set(lb)]
            rfree = [rd[i] for i in range(len(rd))
                     if i not in set(rc) | set(rb)]
            dims = tuple(batch + lfree + rfree)
            total = 1
            for d in dims:
                total *= int(d)
            # per-dim counts carry no mesh-axis identity, so the cross
            # product of lhs/rhs free-dim factors can claim more shards
            # than devices exist (both operands sharded on the SAME
            # axis forces GSPMD to reshard one of them). Cap at the
            # most-sharded operand — never claim finer sharding than
            # any input actually had (under-counting shards
            # OVERestimates memory, the safe direction for the gates).
            cap = max(in_counts) if in_counts else 1
            if total > cap:
                return cap, None
            return max(total, 1), dims
        if name in _REDUCE_PRIMS and in_dims and in_dims[0] is not None:
            axes = eqn.params.get("axes")
            if axes is not None:
                ld = in_dims[0]
                # a reduced dim's shard factor does NOT survive: GSPMD
                # all-reduces the per-shard partials over that mesh
                # axis and the result is replicated on it (the exact
                # dot_general contracted-dim rule, applied to the
                # reduce family — reduce_sum IS a contraction against
                # ones). Kept dims thread through unchanged.
                dims = tuple(d for i, d in enumerate(ld)
                             if i not in set(axes))
                total = 1
                for d in dims:
                    total *= int(d)
                cap = max(in_counts) if in_counts else 1
                if total > cap:       # no axis identity: never claim
                    return cap, None  # finer sharding than any input
                return max(total, 1), dims
        if name == "dynamic_slice" and in_dims and \
                in_dims[0] is not None:
            ss = eqn.params.get("slice_sizes")
            ivs0 = [v for v in eqn.invars if _is_var(v)]
            in_shape = tuple(getattr(ivs0[0].aval, "shape", ()))
            if ss is not None and len(ss) == len(in_dims[0]) == \
                    len(in_shape):
                ld = in_dims[0]
                # a dim sliced at a DYNAMIC start loses its factor —
                # the start index is a runtime value, so GSPMD cannot
                # keep a static split over the sliced span without
                # resharding (the scatter indexed-dim rule, read side);
                # a dim taken WHOLE (slice size == operand dim) is
                # statically the identity and threads its factor
                dims = tuple(int(d) if int(ss[i]) == int(in_shape[i])
                             else 1 for i, d in enumerate(ld))
                total = 1
                for d in dims:
                    total *= int(d)
                cap = max(in_counts) if in_counts else 1
                if total > cap:       # no axis identity: never claim
                    return cap, None  # finer sharding than any input
                return max(total, 1), dims
        if name == "concatenate" and in_dims and \
                all(d is not None for d in in_dims) and in_dims:
            axis = eqn.params.get("dimension")
            if axis is not None and all(len(d) == len(in_dims[0])
                                        for d in in_dims):
                # the concat dim loses its factor: pieces land at
                # per-operand offsets, so no single static split of
                # the merged dim covers them without resharding; a
                # NON-concat dim threads only when every operand
                # agrees on its factor (a mixed-factor dim would make
                # the output's split operand-dependent)
                dims = tuple(
                    1 if (i == axis or len({int(d[i])
                                            for d in in_dims}) != 1)
                    else int(in_dims[0][i])
                    for i in range(len(in_dims[0])))
                total = 1
                for d in dims:
                    total *= int(d)
                cap = max(in_counts) if in_counts else 1
                if total > cap:       # no axis identity: never claim
                    return cap, None  # finer sharding than any input
                return max(total, 1), dims
        if name == "pad" and in_dims and in_dims[0] is not None:
            pc = eqn.params.get("padding_config")
            if pc is not None and len(pc) == len(in_dims[0]):
                # a PADDED dim loses its factor: low/high/interior
                # padding shifts element offsets, so the input's
                # even split no longer lands on shard boundaries;
                # untouched dims thread through
                dims = tuple(
                    1 if any(int(x) != 0 for x in pc[i])
                    else int(d) for i, d in enumerate(in_dims[0]))
                total = 1
                for d in dims:
                    total *= int(d)
                cap = max(in_counts) if in_counts else 1
                if total > cap:
                    return cap, None
                return max(total, 1), dims
        if name == "slice" and in_dims and in_dims[0] is not None:
            starts = eqn.params.get("start_indices")
            limits = eqn.params.get("limit_indices")
            strides = eqn.params.get("strides")
            ivs0 = [v for v in eqn.invars if _is_var(v)]
            in_shape = tuple(getattr(ivs0[0].aval, "shape", ()))
            if starts is not None and limits is not None and \
                    len(starts) == len(in_dims[0]) == len(in_shape):
                # a STATICALLY sliced dim (taken below full size, or
                # strided) loses its factor — the kept span crosses
                # shard boundaries at static but non-aligned offsets,
                # which GSPMD resolves by resharding; a dim taken
                # WHOLE at stride 1 is the identity and threads (the
                # static mirror of the dynamic_slice rule above)
                dims = tuple(
                    int(d) if (int(starts[i]) == 0 and
                               int(limits[i]) == int(in_shape[i]) and
                               (strides is None or
                                int(strides[i]) == 1))
                    else 1 for i, d in enumerate(in_dims[0]))
                total = 1
                for d in dims:
                    total *= int(d)
                cap = max(in_counts) if in_counts else 1
                if total > cap:
                    return cap, None
                return max(total, 1), dims
        if name == "gather" and in_dims and in_dims[0] is not None:
            dn = eqn.params.get("dimension_numbers")
            ss = eqn.params.get("slice_sizes")
            ivs0 = [v for v in eqn.invars if _is_var(v)]
            in_shape = tuple(getattr(ivs0[0].aval, "shape", ()))
            out_shape = tuple(getattr(eqn.outvars[0].aval, "shape", ()))
            if dn is not None and ss is not None and \
                    len(in_dims[0]) == len(in_shape) == len(ss):
                ld = in_dims[0]
                dropped = set(getattr(dn, "collapsed_slice_dims",
                                      ()) or ()) | \
                    set(getattr(dn, "operand_batching_dims", ()) or ())
                offset = tuple(getattr(dn, "offset_dims", ()) or ())
                kept = [d for d in range(len(ld)) if d not in dropped]
                if len(offset) == len(kept):
                    indexed = set(getattr(dn, "start_index_map",
                                          ()) or ())
                    # offset output dims map in order onto the
                    # non-collapsed operand dims: a dim addressed by
                    # the gather indices (start_index_map) or sliced
                    # below full size loses its factor — rows land at
                    # DYNAMIC positions, no static split survives (the
                    # scatter rule's read side); whole untouched dims
                    # thread. Batch dims (from the indices operand)
                    # stay at 1 — conservative, the safe direction.
                    dims = [1] * len(out_shape)
                    for pos, d in zip(offset, kept):
                        if 0 <= pos < len(dims) and d not in indexed \
                                and int(ss[d]) == int(in_shape[d]):
                            dims[pos] = int(ld[d])
                    dims = tuple(dims)
                    total = 1
                    for d in dims:
                        total *= int(d)
                    cap = max(in_counts) if in_counts else 1
                    if total > cap:   # no axis identity: never claim
                        return cap, None
                    return max(total, 1), dims
        if name in _SCATTER_PRIMS and in_dims and in_dims[0] is not None:
            dn = eqn.params.get("dimension_numbers")
            if dn is not None:
                ld = in_dims[0]          # operand: output shape == its
                # dims addressed by the scatter indices lose their
                # factor: updates land at DYNAMIC positions along those
                # dims, so GSPMD cannot keep a static split without
                # resharding — the result is at best replicated on that
                # mesh axis (the dot/reduce contracted-dim rule applied
                # to indexed dims). Window dims thread from the operand.
                upd = set(getattr(dn, "scatter_dims_to_operand_dims",
                                  ()) or ()) | \
                    set(getattr(dn, "inserted_window_dims", ()) or ())
                dims = tuple(1 if i in upd else int(d)
                             for i, d in enumerate(ld))
                total = 1
                for d in dims:
                    total *= int(d)
                cap = max(in_counts) if in_counts else 1
                if total > cap:      # no axis identity: never claim
                    return cap, None  # finer sharding than any input
                return max(total, 1), dims
        if name == "transpose" and in_dims and in_dims[0] is not None:
            perm = eqn.params.get("permutation")
            if perm is not None and len(perm) == len(in_dims[0]):
                dims = tuple(in_dims[0][p] for p in perm)
                return max(in_counts) if in_counts else 1, dims
        if name == "reshape" and in_dims and in_dims[0] is not None:
            ivs = [v for v in eqn.invars if _is_var(v)]
            in_shape = tuple(getattr(ivs[0].aval, "shape", ()))
            if len(in_dims[0]) == len(in_shape):
                dims = _reshape_dim_shards(
                    in_shape, in_dims[0],
                    tuple(getattr(eqn.outvars[0].aval, "shape", ())))
                if dims is not None:
                    return max(in_counts) if in_counts else 1, dims
        out_shape = tuple(getattr(eqn.outvars[0].aval, "shape", ()))
        best, best_dims = (max(in_counts) if in_counts else 1), None
        for cnt, dims, v in zip(in_counts, in_dims,
                                [v for v in eqn.invars if _is_var(v)]):
            if dims is not None and cnt == best and \
                    tuple(getattr(v.aval, "shape", ())) == out_shape:
                best_dims = dims
                break
        return best, best_dims
    except Exception:
        return (max(in_counts) if in_counts else 1), None


def propagate_shard_counts(jx, arg_counts=None, arg_dims=None):
    """{var: shard_count} over one jaxpr. Since v2 this is a thin
    wrapper over the fixed-point pass (`propagation.propagate_shardings`
    — forward AND backward sweeps, constraint-eqn seeding, scan/while/
    pjit body recursion): where the fixed point pinned a concrete
    per-dim spec, its product wins; everywhere else the count comes
    from the same single forward sweep of `_eqn_out_shard` as v1
    (max-operand heuristic with conservative caps) — on a program with
    no mid-graph pins and no backward-reachable specs the two are
    identical, so this stays the documented conservative fallback. The
    remat advisor prices dropped/saved residuals per device with it.
    `arg_dims` optionally seeds per-dim shard counts per invar (aligned
    with `arg_counts`; `lowering.ArgInfo.dim_shards` supplies them)."""
    from .propagation import propagate_shardings
    return propagate_shardings(jx, arg_counts=arg_counts,
                               arg_dims=arg_dims).counts


def estimate_jaxpr_memory(closed_jaxpr, arg_infos=None, top_k=8,
                          cpu_calibrated=False, last_use_override=None,
                          extra_after=None, var_counts=None, n_hosts=1):
    """Static per-device HBM estimate of one closed jaxpr.

    `arg_infos`: optional list of `lowering.ArgInfo` aligned with the
    flattened invars — supplies shard counts (per-device division),
    donation flags (donated args free at last use), and names for the
    peak attribution. Without it every arg is assumed replicated and
    non-donated (the single-device forward-program case).

    `last_use_override`/`extra_after` thread through to the liveness
    walk — the remat advisor's what-if replay (remat_advisor.py) re-runs
    the SAME walk with checkpointed intermediates dropped and one
    segment's recompute working set added past the fwd/bwd boundary.

    `var_counts`: optional fixed-point shard counts
    (`propagation.PropagationResult.counts`) overriding the walk's
    inline forward propagation per var — the MemoryAnalyzer passes the
    propagation pass's result so pricing sees mid-graph constraint pins;
    without it the walk's own sweep is the conservative fallback.

    `n_hosts` > 1 prices the dp-over-hosts view too: the SAME liveness
    walk re-run with every shard count clamped to
    `min(shard_count, n_hosts)`, so a buffer's contribution is its
    DISTINCT bytes per host — replicated buffers (and dp shards
    replicated across a host's local devices, host-major device order
    as `build_mesh` lays out) count once per host, buffers sharded at
    least n_hosts ways count 1/n_hosts. That is the per-host
    checkpoint/offload footprint, not n_local_devices x per-device HBM
    (which is just a multiplication the caller can do). Surfaced as
    `host_peak_bytes` / `host_args_bytes` on the estimate.
    """
    jx = closed_jaxpr.jaxpr if hasattr(closed_jaxpr, "jaxpr") else closed_jaxpr
    infos = arg_infos or []
    arg_counts = [i.shard_count for i in infos] or None
    donated = [i.donated for i in infos]
    memo = {}
    peak, peak_idx, top = _walk(
        jx, arg_counts=arg_counts, donated=donated, widen=cpu_calibrated,
        pin_invars=True, memo=memo, top_k=top_k, arg_infos=infos,
        last_use_override=last_use_override, extra_after=extra_after,
        var_counts=var_counts)

    def _arg_db(k, v):
        cnt = arg_counts[k] if arg_counts and k < len(arg_counts) else 1
        return _aval_bytes(v.aval) // max(cnt, 1)

    args_bytes = sum(_arg_db(k, v) for k, v in enumerate(jx.invars))
    out_bytes = 0
    for v in jx.outvars:
        if _is_var(v):
            cnt = 1  # conservative: treat outputs as replicated w/o info
            out_bytes += _aval_bytes(v.aval, widen_sub_f32=cpu_calibrated) \
                // cnt
    donated_bytes = sum(_arg_db(k, v) for k, v in enumerate(jx.invars)
                        if k < len(donated) and donated[k])
    est = MemoryEstimate(
        peak_bytes=peak, args_bytes=args_bytes, out_bytes=out_bytes,
        temp_peak_bytes=max(0, peak - (args_bytes - donated_bytes)),
        donated_bytes=donated_bytes, peak_eqn=peak_idx,
        peak_op=(jx.eqns[peak_idx].primitive.name
                 if 0 <= peak_idx < len(jx.eqns) else ""),
        top=top, cpu_calibrated=cpu_calibrated)
    if n_hosts > 1:
        # same walk, every shard count clamped to the host count: a
        # buffer sharded fewer than n_hosts ways is (partly) replicated
        # across hosts and costs global/min(cnt, n_hosts) distinct
        # bytes on each
        hpeak, _, _ = _walk(
            jx, arg_counts=arg_counts, donated=donated,
            widen=cpu_calibrated, pin_invars=True, memo={},
            arg_infos=infos, last_use_override=last_use_override,
            extra_after=extra_after, var_counts=var_counts,
            count_cap=int(n_hosts))
        est.n_hosts = int(n_hosts)
        est.host_peak_bytes = hpeak
        est.host_args_bytes = sum(
            _aval_bytes(v.aval) // min(
                max(arg_counts[k] if arg_counts and k < len(arg_counts)
                    else 1, 1), int(n_hosts))
            for k, v in enumerate(jx.invars))
    return est


@register_analyzer
class MemoryAnalyzer(Analyzer):
    """Per-device peak-HBM pass: liveness estimate + regression gate.

    Findings:
      MEM-PEAK-REGRESSION  ERROR    fresh peak exceeds the committed
                                    memory manifest beyond tolerance
      MEM-PEAK-IMPROVED    INFO     peak dropped below tolerance — the
                                    manifest is stale, regenerate it
      MEM-OVER-BUDGET      ERROR    peak exceeds ctx.hbm_budget_bytes
      MEM-NO-DONATION      WARNING  params+opt state bigger than the
                                    donation credit — train-step args
                                    are not donated, doubling resident
                                    state
      MEM-NO-DONATION-KVCACHE WARNING  decode-loop program whose KV
                                    cache is not donated — the cache is
                                    the carried state in inference (the
                                    params are read-only there), so a
                                    non-donated cache copies the whole
                                    KV store every decode step
    Metrics feed memory_manifests/<config>.json (peak, breakdown, top-k
    attribution)."""
    name = "memory"

    def run(self, program, ctx):
        if getattr(program, "jaxpr", None) is None:
            self.metrics = {"available": False}
            return []
        # the fixed-point pass ran just before this one (registration
        # order) and stashed its result; result_for recomputes when the
        # pass manager was bypassed or the program changed underneath
        from .propagation import result_for
        prop = result_for(program, ctx)
        n_hosts = 1
        for h in (ctx.extra.get("axis_host_counts") or {}).values():
            n_hosts *= max(int(h), 1)
        est = estimate_jaxpr_memory(
            program.jaxpr, arg_infos=getattr(program, "arg_infos", None),
            top_k=ctx.extra.get("memory_top_k", 8),
            var_counts=prop.counts if prop is not None else None,
            n_hosts=n_hosts)
        self.metrics = {"available": True, **est.to_dict()}
        findings = []
        committed = (ctx.memory_manifest or {})
        want = committed.get("per_device_peak_bytes")
        tol = ctx.memory_tolerance
        if want:
            if est.peak_bytes > want * (1 + tol):
                findings.append(Finding(
                    "MEM-PEAK-REGRESSION", Severity.ERROR,
                    f"per-device peak HBM {est.peak_bytes} exceeds the "
                    f"committed manifest's {want} by more than "
                    f"{tol:.0%} — the step no longer fits the same "
                    "chip headroom",
                    suggested_fix="shard or remat the top live tensors "
                    "(debug.memory_report), or regenerate manifests if "
                    "the growth is intentional: python -m "
                    "paddle_tpu.analysis --write-manifests"))
            elif est.peak_bytes < want * (1 - tol):
                findings.append(Finding(
                    "MEM-PEAK-IMPROVED", Severity.INFO,
                    f"per-device peak HBM {est.peak_bytes} is more than "
                    f"{tol:.0%} below the committed {want} — regenerate "
                    "the manifest to bank the improvement"))
        budget = ctx.hbm_budget_bytes
        if budget and est.peak_bytes > budget:
            findings.append(Finding(
                "MEM-OVER-BUDGET", Severity.ERROR,
                f"per-device peak HBM {est.peak_bytes} exceeds the "
                f"budget {budget}",
                suggested_fix="raise fsdp sharding, enable remat, or "
                "shrink the per-device batch"))
        infos = getattr(program, "arg_infos", None) or []
        state_bytes = sum(i.device_bytes for i in infos
                          if i.role in ("param", "opt_state"))
        if state_bytes and not any(i.donated for i in infos
                                   if i.role in ("param", "opt_state")):
            if ctx.extra.get("expect_donation", True) and \
                    any(i.role == "opt_state" for i in infos):
                findings.append(Finding(
                    "MEM-NO-DONATION", Severity.WARNING,
                    f"{state_bytes} bytes of params/opt-state are not "
                    "donated — the step holds two copies of the model "
                    "state in HBM",
                    suggested_fix="donate params/opt state into the "
                    "compiled step (Trainer(donate=True))"))
        # decode-loop variant: in inference the carried state is the KV
        # cache, not params — jit.save/serving paths never donate params
        # (correctly: they're read-only across steps), but a non-donated
        # cache double-buffers the whole KV store on every step
        cache_infos = kv_cache_infos(infos)
        # per-ARG, not any(): k_pages donated with v_pages forgotten
        # still double-buffers half the store
        undonated = [i for i in cache_infos if not i.donated]
        undonated_bytes = sum(i.device_bytes for i in undonated)
        if undonated_bytes and ctx.extra.get("expect_donation", True):
            names = ", ".join(sorted(i.name or "?" for i in undonated)[:4])
            findings.append(Finding(
                "MEM-NO-DONATION-KVCACHE", Severity.WARNING,
                f"{undonated_bytes} bytes of KV-cache state ({names}) "
                "are not donated into the decode step — XLA must "
                "allocate a second full cache for the updated pages "
                "every step",
                suggested_fix="donate the cache buffers "
                "(jax.jit(step, donate_argnums=...) on the k/v page "
                "arguments, as serving.PagedGPTDecoder does)"))
        return findings


# ------------------------------------------------- shared-pool refcounts


def audit_page_ledger(ledger):
    """MEM-PAGE-REFCOUNT invariant audit of a serving engine's page
    ledger (`ContinuousBatchingEngine.page_ledger()`): with a shared
    (prefix-cached) KV pool, every allocatable page must be owned
    EXACTLY once — on the free list, XOR held by slot(s) under a
    covering cache refcount, XOR parked (refcount 0) in the cache's
    LRU.  Double-frees, leaks, refcount drift and writes-into-shared
    hazards all surface as findings.  Returns a list of Finding
    (empty = consistent)."""
    findings = []

    def bad(msg, fix=None):
        findings.append(Finding("MEM-PAGE-REFCOUNT", Severity.ERROR, msg,
                                analyzer="page-refcount",
                                suggested_fix=fix))

    num_pages = int(ledger.get("num_pages", 0))
    scratch = ledger.get("scratch")
    free = list(ledger.get("free", []))
    slots = {int(s): list(p)
             for s, p in (ledger.get("slots") or {}).items()}
    shared = {int(s): set(p)
              for s, p in (ledger.get("shared") or {}).items()}
    cache = {int(p): dict(e)
             for p, e in (ledger.get("cache") or {}).items()}

    seen = set()
    for p in free:
        if p in seen:
            bad(f"page {p} appears twice in the free list (double free)")
        seen.add(p)
        if scratch is not None and p == scratch:
            bad("the reserved scratch page is on the free list")

    holders = {}                         # page -> [slots holding it]
    for s, pages in slots.items():
        for p in pages:
            holders.setdefault(p, []).append(s)
    # multi-LoRA rows (serving.tenancy): per-slot adapter salts — a
    # page shared across slots whose salts DIFFER means one variant is
    # reading another's KV bytes (the adapter's low-rank delta is part
    # of every write, so cross-variant bytes are simply wrong). The
    # engine prevents this by folding `adapter_salt` into the chain
    # keys; the audit proves it held on the live ledger.
    slot_adapters = {int(s): dict(e) for s, e in
                     (ledger.get("slot_adapters") or {}).items()}
    for p, hs in holders.items():
        if len(hs) > 1 and (p not in cache
                            or int(cache[p].get("refs", 0)) < len(hs)):
            bad(f"page {p} is held by slots {sorted(hs)} without a "
                "covering cache refcount (unaccounted aliasing)",
                fix="mount shared pages through the prefix cache so "
                "refcounts track every holder")
        if len(hs) > 1 and slot_adapters:
            salts = {slot_adapters.get(s, {}).get("salt", "")
                     for s in hs}
            if len(salts) > 1:
                bad(f"page {p} is shared by slots {sorted(hs)} with "
                    f"DIFFERENT adapter fingerprints — a LoRA "
                    "variant is aliasing another variant's KV bytes",
                    fix="fold the request's adapter_salt into the "
                    "prefix-cache chain keys (PrefixCache.block_keys"
                    "(ids, extra_salt=...)) so cross-variant prompts "
                    "never match the same entries")
    for p in seen:
        if p in holders:
            bad(f"page {p} is both free and held by slot(s) "
                f"{sorted(holders[p])} (double free)")
        if p in cache:
            bad(f"page {p} is both free and cache-tracked (double free: "
                "eviction must unmap before returning a page)")

    mounts = {}                          # page -> shared-mount count
    for s, sh in shared.items():
        for p in sh:
            mounts[p] = mounts.get(p, 0) + 1
            if p not in (slots.get(s) or []):
                bad(f"slot {s} marks page {p} shared but does not hold "
                    "it")
            if p not in cache:
                bad(f"slot {s} holds page {p} as shared but the cache "
                    "does not track it")
    for p, e in cache.items():
        refs = int(e.get("refs", 0))
        if refs < 0:
            bad(f"page {p} has negative refcount {refs} (double "
                "release)")
        m = mounts.get(p, 0)
        if refs != m:
            bad(f"page {p} refcount {refs} != {m} mounting slot(s) "
                "(refcount drift — the page would be freed too early "
                "or never)")
        if refs == 0 and p in holders:
            # a parked page is by definition held by NOBODY: a slot
            # still mapping it means a reference was dropped without
            # decref — eviction would hand a live-mapped page to the
            # free list and a later prefill would corrupt the slot's KV
            bad(f"page {p} is parked (refcount 0) but still held by "
                f"slot(s) {sorted(holders[p])} (reference dropped "
                "without decref)")

    owned = set(free) | set(holders) | set(cache)
    for p in range(num_pages):
        if scratch is not None and p == scratch:
            continue
        if p not in owned:
            bad(f"page {p} is unreachable: not free, not slot-held, "
                "not cached (leak)")

    # host-tier rows (tiered KV, serving.kv_tier): a spilled entry is
    # keyed by chain key and owns NO device page — unless it was
    # restored, in which case its device-twin backref must point at a
    # live cache-tracked page. A twin on the free list means the
    # unmount bookkeeping was dropped: a reader could mount the host
    # entry's "device copy" while the free list hands the same page to
    # a prefill (the spill-tier double-free).
    host = {str(k): dict(e)
            for k, e in (ledger.get("host") or {}).items()}
    free_set = set(free)
    for key, e in host.items():
        p = e.get("page")
        if p is None:
            continue
        p = int(p)
        if p in free_set:
            bad(f"host entry {key[:12]} is both host-resident and "
                f"device-free: its device twin (page {p}) sits on the "
                "free list — the unmount/spill bookkeeping dropped the "
                "backref and a later prefill would overwrite a page "
                "the tier still advertises as mounted",
                fix="clear the tier's device-twin backref "
                "(HostKVTier.note_unmounted) in the same eviction that "
                "frees the page")
        elif p not in cache:
            bad(f"host entry {key[:12]} records device twin page {p} "
                "but the cache does not track that page (stale "
                "restore backref)")
    return findings


def audit_kv_scale_planes(decoder, pages):
    """MEM-PAGE-REFCOUNT scale-plane consistency audit of a quantized
    KV pool: for every page in `pages` (slot-held or cache-tracked),
    any position holding nonzero quantized bytes must carry a nonzero
    write-time scale.  The write path stores bytes and scale together
    (`serving.decoder._kv_set`) and the floor scale is positive even
    for an all-zero vector, so a written position ALWAYS has scale > 0
    — a zero scale under live bytes means some copy path (typically a
    copy-on-write that moved page bytes but not the scale plane) split
    the two, and the page dequantizes to garbage.  int8 pools carry
    one scale per (layer, pos); int4 pools (uint8 nibble payload) one
    per (layer, pos, group) — there the check demands EVERY group
    scale positive at a written position, since the write quantizes
    all groups together.  Reads the pool from device; audit-time only,
    never on the serving hot path.  Returns Finding list (empty =
    consistent)."""
    import numpy as np
    findings = []
    k_pool, v_pool = decoder.k_pages, decoder.v_pages
    if not isinstance(k_pool, tuple):
        return findings                  # unquantized pool: nothing to check
    for name, (page_arr, scale_arr) in (("k", k_pool), ("v", v_pool)):
        pg = np.asarray(page_arr)
        sc = np.asarray(scale_arr)
        for p in pages:
            if pg.dtype == np.uint8:
                # int4: payload [L, ps, PB], scales [L, ps, G]
                wrote = np.abs(pg[:, p].astype(np.int32)).max(axis=-1) > 0
                orphan = wrote & (sc[:, p].min(axis=-1) <= 0.0)
            else:
                # [L, ps]: any head/dim byte live at (layer, position)?
                wrote = np.abs(pg[:, p].astype(np.int32)).max(
                    axis=(-2, -1)) > 0
                orphan = wrote & (sc[:, p] <= 0.0)
            if orphan.any():
                ls, ps_ = np.nonzero(orphan)
                findings.append(Finding(
                    "MEM-PAGE-REFCOUNT", Severity.ERROR,
                    f"{name}-page {p} holds quantized bytes without "
                    f"write-time scales at (layer, pos) "
                    f"{list(zip(ls.tolist(), ps_.tolist()))[:4]}"
                    f"{'...' if orphan.sum() > 4 else ''} — a copy "
                    "moved the page bytes but not the scale plane; "
                    "the page dequantizes to garbage",
                    analyzer="page-refcount",
                    suggested_fix="copy pages through "
                    "PagedGPTDecoder.copy_page (it tree-maps bytes "
                    "AND scale rows together); never copy pool leaves "
                    "individually"))
    return findings


@register_analyzer
class PageRefcountAnalyzer(Analyzer):
    """MEM-PAGE-REFCOUNT: ownership audit of the shared (prefix-cached)
    KV page pool. Runs only when `ctx.extra["page_ledger"]` carries an
    engine ledger — the `gpt_decode_prefix` PROGRAM config commits one
    captured from a real shared-prefix workload, so the CI gate proves
    on every run that refcounted sharing frees every page exactly once
    (the one-horizon-delayed-retirement discipline extended to shared
    pages). Planted-defect tests corrupt a ledger to prove double-free
    / leak / refcount-drift detection."""
    name = "page-refcount"

    def run(self, program, ctx):
        ledger = ctx.extra.get("page_ledger")
        if not ledger:
            self.metrics = {"checked": False}
            return []
        cache = ledger.get("cache") or {}
        host = ledger.get("host") or {}
        self.metrics = {
            "checked": True,
            "n_pages": int(ledger.get("num_pages", 0)),
            "n_free": len(ledger.get("free", [])),
            "n_held": sum(len(p)
                          for p in (ledger.get("slots") or {}).values()),
            "n_cached": len(cache),
            "n_parked": sum(1 for e in cache.values()
                            if not e.get("refs")),
            "refcount_total": sum(int(e.get("refs", 0))
                                  for e in cache.values()),
            # tiered-KV host rows: spilled entries + their bytes (the
            # warm set that survived the HBM cliff)
            "n_host": len(host),
            "host_bytes": sum(int(e.get("bytes", 0))
                              for e in host.values()),
        }
        return audit_page_ledger(ledger)
