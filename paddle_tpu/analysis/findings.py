"""Structured findings — the unit of output of every Graph Doctor
analyzer (pass-pipeline design after TPU-MLIR, arxiv 2210.15016: each
pass consumes the lowered program and emits diagnostics instead of
mutating it).

A Finding carries a stable rule id (documented in
docs/static_analysis.md), a severity, the offending op/source location,
and a suggested fix — enough for the CI gate to print an actionable
line and for lint manifests to diff across commits.
"""
import enum
from dataclasses import dataclass, field

__all__ = ["Severity", "Finding", "Report"]


class Severity(enum.IntEnum):
    # ordered so max() over a report gives the gate outcome
    INFO = 0
    WARNING = 1
    ERROR = 2

    def __str__(self):
        return self.name


@dataclass
class Finding:
    rule_id: str                 # e.g. "LAYOUT-ACT-TRANSPOSE"
    severity: Severity
    message: str
    analyzer: str = ""           # registry name of the emitting pass
    op: str = None               # offending op line (HLO) or AST snippet
    location: str = None         # "line 123" / "file.py:45" / model name
    suggested_fix: str = None

    def to_dict(self):
        d = {"rule_id": self.rule_id, "severity": str(self.severity),
             "message": self.message, "analyzer": self.analyzer}
        for k in ("op", "location", "suggested_fix"):
            v = getattr(self, k)
            if v is not None:
                d[k] = v
        return d

    def __str__(self):
        loc = f" [{self.location}]" if self.location else ""
        fix = f"\n      fix: {self.suggested_fix}" if self.suggested_fix else ""
        return f"{self.severity:<7} {self.rule_id}{loc}: {self.message}{fix}"


@dataclass
class Report:
    """Ordered findings from one pass-manager run, plus per-analyzer
    metrics (op counts, payload bytes) that manifests persist even when
    no finding fires."""
    findings: list = field(default_factory=list)
    metrics: dict = field(default_factory=dict)

    def add(self, finding):
        self.findings.append(finding)

    def extend(self, other):
        self.findings.extend(other.findings)
        for k, v in other.metrics.items():
            self.metrics.setdefault(k, v)

    def by_rule(self, rule_id):
        return [f for f in self.findings if f.rule_id == rule_id]

    def by_severity(self, severity):
        return [f for f in self.findings if f.severity >= severity]

    @property
    def errors(self):
        return self.by_severity(Severity.ERROR)

    @property
    def warnings(self):
        return [f for f in self.findings if f.severity == Severity.WARNING]

    @property
    def max_severity(self):
        if not self.findings:
            return None
        return max(f.severity for f in self.findings)

    def to_dict(self):
        return {"findings": [f.to_dict() for f in self.findings],
                "metrics": self.metrics}

    def __str__(self):
        if not self.findings:
            return "clean (0 findings)"
        return "\n".join(str(f) for f in self.findings)

    def __bool__(self):
        # truthy when anything fired — `if report:` reads naturally
        return bool(self.findings)
