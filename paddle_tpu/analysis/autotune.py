"""Static (microbatch, remat) autotuner — pick the training config
before anything compiles.

The bench campaign used to find GPT-1.3B's operating point (bs=6,
remat=dots, 0.64 MFU) by compiling and timing every (batch, policy)
combination — minutes of wall clock per candidate on a flaky tunnel.
This module replaces the brute force with static search:

  1. trace the trainer's REAL step once per candidate microbatch with
     remat disabled (CPU tracing, no compile, no device);
  2. replay every candidate remat policy over that trace
     (remat_advisor.py): per-device peak + recompute FLOPs per policy —
     per-device division uses the fixed-point propagated shard counts
     (analysis/propagation.py) where the lowering pinned per-dim specs,
     the v1 max-operand heuristic elsewhere;
  3. price each (microbatch, policy) with the roofline step-time model
     (cost_model.roofline_step_time): max(compute, HBM, wire) seconds;
  4. prune everything over the HBM budget, rank the rest by predicted
     throughput.

Front doors: `debug.autotune(trainer, batch, hbm_budget=...)`,
`Trainer.suggest_config(batch)`, the CLI
(`python -m paddle_tpu.analysis --autotune`), and
`rank_gpt_candidates` (examples/perf_campaign.py measures only the
advisor's top-2 unless --exhaustive).
"""
import gc
from contextlib import contextmanager
from dataclasses import dataclass, field

__all__ = ["CandidateEstimate", "AutotuneReport", "autotune",
           "autotune_layer", "rank_gpt_candidates", "DEFAULT_POLICIES"]

DEFAULT_POLICIES = ("none", "full", "dots", "dots_with_no_batch_dims")


@dataclass
class CandidateEstimate:
    """One (microbatch, remat policy[, grad accum]) grid point."""
    batch: int
    policy: str
    accum: int
    peak_bytes: int
    feasible: bool
    step_s: float
    bound: str                   # compute | hbm | wire
    throughput: float            # items/s (tokens/s when tokens known)
    unit: str
    flops: int
    recompute_pct: float
    advice: str
    n_chunks: int = 1            # collective-matmul decomposition pick

    def to_dict(self):
        return {"batch": self.batch, "policy": self.policy,
                "accum": self.accum, "peak_bytes": self.peak_bytes,
                "feasible": self.feasible,
                "predicted_step_us": round(self.step_s * 1e6, 3),
                "bound": self.bound,
                "throughput": round(self.throughput, 1),
                "unit": self.unit,
                "recompute_pct": round(self.recompute_pct, 2),
                "n_chunks": self.n_chunks}


@dataclass
class AutotuneReport:
    """Ranked candidates (feasible first, fastest first), the advice
    lines per policy, and the budget that pruned the rest."""
    name: str
    candidates: list
    hbm_budget: int
    chip: str
    advice: list = field(default_factory=list)

    @property
    def best(self):
        for c in self.candidates:
            if c.feasible:
                return c
        return None

    @property
    def top(self):
        return [c for c in self.candidates if c.feasible]

    def __str__(self):
        gib = 1024.0 ** 3
        lines = [f"== autotune: {self.name} (chip {self.chip}, HBM "
                 f"budget {self.hbm_budget / gib:.1f} GiB) =="]
        hdr = (f"{'bs':>4} {'policy':<24} {'accum':>5} {'peak GiB':>9} "
               f"{'step ms':>8} {'bound':>7} {'pred':>10} {'fit':>4}")
        lines.append(hdr)
        for c in self.candidates:
            lines.append(
                f"{c.batch:>4} {c.policy:<24} {c.accum:>5} "
                f"{c.peak_bytes / gib:>9.2f} {c.step_s * 1e3:>8.2f} "
                f"{c.bound:>7} {c.throughput:>10.0f} "
                f"{'ok' if c.feasible else 'OOM':>4}")
        for line in self.advice:
            lines.append("  " + line)
        return "\n".join(lines)


@contextmanager
def _remat_disabled(model):
    """Trace-time switch: flips cfg.remat off so the traced step is the
    no-remat baseline the replay needs. Models without a remat config
    (ResNet & co) pass through untouched."""
    cfg = getattr(model, "cfg", None)
    if cfg is None or not hasattr(cfg, "remat"):
        yield
        return
    old = cfg.remat
    cfg.remat = False
    try:
        yield
    finally:
        cfg.remat = old


def _noremat_program(trainer, batch):
    """Trace the trainer's specialized step with remat disabled, WITHOUT
    poisoning the trainer's compiled-step cache: the placed-step map is
    swapped out for the trace (fresh closures, so jax's trace cache
    can't serve a stale no-remat jaxpr to a later remat'd trace)."""
    saved_steps = trainer._placed_steps
    trainer._placed_steps = {}
    try:
        with _remat_disabled(trainer.model):
            return trainer.analysis_program(batch)
    finally:
        trainer._placed_steps = saved_steps


def _resize_batch(batch, bs):
    """Tile/slice every leaf's leading dim to `bs` (host-side numpy)."""
    import numpy as np
    import jax

    def fix(v):
        a = np.asarray(v)
        if a.ndim == 0:
            return a
        if a.shape[0] == bs:
            return a
        reps = -(-bs // a.shape[0])          # ceil
        return np.concatenate([a] * reps, axis=0)[:bs]
    return jax.tree_util.tree_map(fix, batch)


def _segments_of(model, default=1):
    cfg = getattr(model, "cfg", None)
    n = getattr(cfg, "num_layers", None)
    if n:
        return int(n)
    blocks = getattr(model, "blocks", None)
    try:
        return max(len(blocks), 1)
    except TypeError:
        return default


def _leading_dim(batch):
    """Batch size = leading dim of the first NON-SCALAR leaf (scalar
    leaves, e.g. a loss weight, carry no batch dim — _resize_batch
    passes them through untouched for the same reason)."""
    import numpy as np
    import jax
    for leaf in jax.tree_util.tree_leaves(batch):
        a = np.asarray(leaf)
        if a.ndim:
            return int(a.shape[0])
    return 1


def _batch_items(batch, tokens_per_item=None):
    """(count, unit) for throughput: tokens when a [B, L] integer leaf
    exists (LM batches), else leading-dim items."""
    import numpy as np
    import jax
    leaves = jax.tree_util.tree_leaves(batch)
    b = _leading_dim(batch)
    if tokens_per_item:
        return b * tokens_per_item, "tokens/s"
    for leaf in leaves:
        a = np.asarray(leaf)
        if a.ndim == 2 and a.dtype.kind in "iu" and a.shape[1] > 1:
            return b * int(a.shape[1]), "tokens/s"
    return b, "items/s"


def _wire_bytes(program, mesh=None):
    """(ici, dcn) analytic wire bytes of the program's collectives,
    DCN-priced when a mesh axis spans hosts."""
    from ..cost_model import (axis_host_count, collective_wire_split)
    from .analyzers import COLLECTIVE_OPS
    from .lowering import tensor_type_bytes
    hosts = 1
    if mesh is not None:
        try:
            hosts = max(axis_host_count(mesh, a) for a in mesh.axis_names)
        except (ValueError, TypeError):
            hosts = 1
    ici = dcn = 0
    for op in program.ops_named(*COLLECTIVE_OPS):
        group, _ = op.replica_group_size()
        payload = max(op.operand_bytes(),
                      sum(tensor_type_bytes(t) for t in op.result_types))
        split = collective_wire_split(op.name, payload, group or 1,
                                      host_count=hosts)
        ici += split["ici"]
        dcn += split["dcn"]
    return ici, dcn


def _state_bytes(arg_infos):
    infos = arg_infos or []
    state = sum(i.device_bytes for i in infos
                if i.role in ("param", "opt_state", "gt_state", "const"))
    batch = sum(i.device_bytes for i in infos if i.role == "batch")
    params = sum(i.device_bytes for i in infos if i.role == "param")
    bshard = max([i.shard_count for i in infos if i.role == "batch"]
                 or [1])
    return state, batch, params, bshard


def _price(whatif, state_b, batch_b, params_b, items, unit, chip,
           ici_b=0, dcn_b=0, accum=1, batch_shard=1, overlap_frac=1.0):
    """Roofline-price one replayed policy, PER DEVICE: the replayed
    peak and byte counts are already per-device (shard-count division),
    so the compute leg divides the batch-proportional FLOPs by the
    batch's shard count too (data parallelism splits the fwd/bwd work;
    the optimizer epilogue runs on every device's own shard of state
    and is priced once). Throughput stays GLOBAL items per step. With
    grad accumulation the fwd/bwd repeats `accum` times before one
    epilogue, and a float32 params-shaped gradient accumulator joins
    the peak.

    `overlap_frac` is the schedule pass's wire-hiding fraction
    (`analysis.schedule.estimate_schedule(...).overlap_frac`): the
    step is priced through `roofline_step_time_overlap`, so a program
    whose lowered schedule SERIALIZES its collectives ranks by the
    time it will actually run at, not the full-overlap floor. With no
    wire (every single-device candidate, including the gpt_1p3b probe
    grid) the price is bit-identical to the old max() — rankings
    can't move."""
    from ..cost_model import roofline_step_time_overlap
    opt_flops = 12 * max(params_b // 2, 1)   # ~12 flops/param epilogue
    micro_flops = max(whatif.step_flops + whatif.recompute_flops
                      - opt_flops, 0) // max(batch_shard, 1)
    flops = accum * micro_flops + opt_flops
    act_b = 2 * (whatif.saved_bytes + whatif.boundary_bytes
                 + whatif.dropped_bytes)
    hbm = 2 * state_b + accum * (batch_b + act_b)
    peak = whatif.peak_bytes
    if accum > 1:
        peak += 2 * params_b      # f32 grad accumulator (params are bf16)
    rt = roofline_step_time_overlap(flops, hbm, ici_b * accum,
                                    dcn_b * accum, chip=chip,
                                    overlap_frac=overlap_frac)
    return peak, flops, rt, accum * items / max(rt.step_s, 1e-12)


def _rank_key(c):
    """Feasible first, fastest first; ties (HBM-bound small models make
    policies indistinguishable on time) break toward the least
    recompute, then the smallest peak."""
    return (not c.feasible, -c.throughput, c.recompute_pct, c.peak_bytes)


def autotune(trainer, batch, hbm_budget=None, batch_sizes=None,
             policies=DEFAULT_POLICIES, chip=None, segments=None,
             tokens_per_item=None, print_report=False):
    """Static config search over (microbatch, remat policy) for a
    Trainer: one no-remat trace per batch size, a what-if liveness
    replay per policy, roofline pricing, HBM-budget pruning, and a
    ranked table. No compile, no device execution.

    Returns an AutotuneReport; `report.best` is the config to measure
    first, `report.advice` the per-policy "moves the peak from X to Y
    at +Z% recompute FLOPs" lines for the example batch size."""
    from ..cost_model import chip_spec
    from .remat_advisor import advise_remat

    chip = chip_spec(chip) if not hasattr(chip, "peak_flops") else chip
    budget = int(hbm_budget or chip.hbm_bytes)
    segments = segments or _segments_of(trainer.model)
    b0 = _leading_dim(batch)
    if batch_sizes is None:
        batch_sizes = sorted({max(1, b0 // 2), b0, b0 * 2})

    # advice lines quote the example batch's size when it is in the
    # grid, else the first traced size — .advice must never be empty
    # just because batch_sizes excluded b0
    advice_bs = b0 if b0 in batch_sizes else batch_sizes[0]
    candidates, advice = [], []
    for bs in batch_sizes:
        resized = _resize_batch(batch, bs)
        program = _noremat_program(trainer, resized)
        items, unit = _batch_items(resized, tokens_per_item)
        state_b, batch_b, params_b, bshard = _state_bytes(
            program.arg_infos)
        ici_b, dcn_b = _wire_bytes(program, getattr(trainer, "mesh", None))
        # overlap-aware wire leg: a program WITH collectives prices at
        # the schedule pass's hiding fraction (a serialized psum can't
        # hide behind the MXU); wire-free candidates skip the DAG walk
        # — their price is bit-identical either way
        overlap_frac = 1.0
        if ici_b or dcn_b:
            from .schedule import estimate_schedule
            mesh = getattr(trainer, "mesh", None)
            overlap_frac = estimate_schedule(
                program, chip=chip,
                mesh_axes=(dict(mesh.shape) if mesh is not None
                           else None)).overlap_frac
        for w in advise_remat(program, policies=policies,
                              segments=segments):
            peak, flops, rt, thr = _price(
                w, state_b, batch_b, params_b, items, unit, chip,
                ici_b, dcn_b, batch_shard=bshard,
                overlap_frac=overlap_frac)
            # n_chunks is picked the way microbatch is — feasible-
            # fastest through the chunked-overlap leg: the chip time
            # (max of MXU and HBM legs) is what chunk t+1's matmul can
            # hide chunk t's transfer behind. Wire-free candidates
            # stay at the bulk n=1 (nothing to decompose).
            n_best = 1
            if rt.wire_s > 0.0:
                from ..cost_model import best_n_chunks
                n_best, ct = best_n_chunks(max(rt.compute_s, rt.hbm_s),
                                           rt.wire_s)
                if bs == advice_bs:
                    advice.append(
                        f"[{w.policy}] chunked overlap: n_chunks="
                        f"{n_best} hides {ct.overlap_frac:.0%} of the "
                        f"{rt.wire_s * 1e3:.2f} ms wire "
                        f"(bulk step {ct.serial_s * 1e3:.2f} ms -> "
                        f"{ct.step_s * 1e3:.2f} ms)")
            candidates.append(CandidateEstimate(
                batch=bs, policy=w.policy, accum=1, peak_bytes=peak,
                feasible=peak <= budget, step_s=rt.step_s,
                bound=rt.bound, throughput=thr, unit=unit, flops=flops,
                recompute_pct=w.recompute_pct, advice=w.advice,
                n_chunks=n_best))
            if bs == advice_bs:
                advice.append(w.advice)
        del program
        gc.collect()

    candidates.sort(key=_rank_key)
    report = AutotuneReport(
        name=type(trainer.model).__name__, candidates=candidates,
        hbm_budget=budget, chip=chip.name, advice=advice)
    if print_report:
        print(report)
    return report


def autotune_layer(model, *example_arrays, policies=DEFAULT_POLICIES,
                   segments=None, chip="v5e", name=None,
                   hbm_budget=None):
    """Remat advice for a bare Layer (no Trainer): traces
    value_and_grad of a synthetic mean-square loss over the forward —
    the policy-ranking backbone the BASELINE tuning manifests pin.
    Deterministic: fixed chip, no live-device dependence."""
    import jax
    import jax.numpy as jnp
    from ..framework.core import Tensor
    from ..nn.layer_base import (buffer_pytree, functional_call,
                                 state_pytree)
    from ..cost_model import chip_spec
    from .lowering import LoweredProgram, tree_arg_infos
    from .remat_advisor import advise_remat

    chip = chip_spec(chip) if not hasattr(chip, "peak_flops") else chip
    budget = int(hbm_budget or chip.hbm_bytes)
    segments = segments or _segments_of(model)
    params = state_pytree(model)
    params.update(buffer_pytree(model))

    def objective(p, *args):
        with _remat_disabled(model):
            with functional_call(model, p):
                out = model(*[Tensor(a) for a in args])
        leaves = jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(
                lambda t: t._value if isinstance(t, Tensor) else t, out,
                is_leaf=lambda t: isinstance(t, Tensor)))
        loss = sum(jnp.mean(jnp.square(l.astype(jnp.float32)))
                   for l in leaves if hasattr(l, "dtype"))
        return loss

    with _remat_disabled(model):
        traced = jax.jit(jax.value_and_grad(objective)).trace(
            params, *example_arrays)
    infos = tree_arg_infos(params, "param")
    for i, a in enumerate(example_arrays):
        infos += tree_arg_infos(a, "input", prefix=f"input{i}")
    program = LoweredProgram(traced.lower().as_text(),
                             jaxpr=traced.jaxpr,
                             name=name or type(model).__name__,
                             arg_infos=infos)
    whatifs = advise_remat(program, policies=policies, segments=segments)
    items, unit = _batch_items(list(example_arrays))
    import numpy as np
    leaves = jax.tree_util.tree_leaves(list(example_arrays))
    b0 = int(np.asarray(leaves[0]).shape[0]) if leaves else 1
    state_b, _, params_b, _bshard = _state_bytes(program.arg_infos)
    batch_b = sum(i.device_bytes for i in program.arg_infos
                  if i.role == "input")
    candidates = []
    for w in whatifs:
        peak, flops, rt, thr = _price(w, state_b, batch_b, params_b,
                                      items, unit, chip)
        candidates.append(CandidateEstimate(
            batch=b0,
            policy=w.policy, accum=1, peak_bytes=peak,
            feasible=peak <= budget, step_s=rt.step_s,
            bound=rt.bound, throughput=thr, unit=unit, flops=flops,
            recompute_pct=w.recompute_pct, advice=w.advice))
    candidates.sort(key=_rank_key)
    return AutotuneReport(
        name=name or type(model).__name__, candidates=candidates,
        hbm_budget=budget, chip=chip.name,
        advice=[w.advice for w in whatifs])


# ------------------------------------------------- GPT grid ranking

def rank_gpt_candidates(grid, seq=1024, top=2, probe_layers=(2, 3),
                        chip=None, hbm_budget=None, log=None):
    """Rank a bench-style GPT grid [(cfg_name, bs, remat, accum), ...]
    statically and return the top-`top` entries (advisor order).

    Tracing the full 1.3B model would materialize >2 GB of params just
    to build a jaxpr, so the advisor probes a depth-truncated twin at
    `probe_layers` (two points) and extrapolates peak/FLOPs linearly in
    layer count — every per-block quantity (params, optimizer slots,
    saved/dropped residuals, block FLOPs) is exactly linear in L, and
    the embedding/head/loss ends cancel in the two-point difference.
    Runs entirely on the host: build + trace + replay, no compile."""
    import numpy as np

    from ..cost_model import chip_spec
    from .remat_advisor import BENCH_POLICY_NAMES, replay_remat

    chip = chip_spec(chip) if not hasattr(chip, "peak_flops") else chip
    budget = int(hbm_budget or chip.hbm_bytes)
    names = {g[0] for g in grid}
    if len(names) != 1:
        raise ValueError(f"rank_gpt_candidates wants one config family, "
                         f"got {sorted(names)}")
    cfg_name = names.pop()
    policies = sorted({BENCH_POLICY_NAMES.get(g[2], g[2]) for g in grid})
    micro_bss = sorted({g[1] // max(g[3], 1) for g in grid})

    import paddle_tpu as paddle
    from paddle_tpu.distributed import build_mesh
    from paddle_tpu.distributed.mesh import get_mesh, set_mesh
    from paddle_tpu.distributed.trainer import Trainer
    from paddle_tpu.framework.random import get_rng_state, set_rng_state
    from paddle_tpu.models import GPT, GPTPretrainingCriterion
    from paddle_tpu.models import gpt as gpt_mod

    # probe[(L, mb, policy)] -> (peak, step_flops+recompute, whatif)
    probe = {}
    full_L = None
    state_by_L, params_by_L = {}, {}
    import jax
    # the probes pin the global mesh and reseed the global RNG; both are
    # process-wide state a caller may be mid-use of — restore on exit
    saved_mesh = get_mesh(create_default=False)
    saved_rng = get_rng_state()
    try:
        for L in probe_layers:
            cfg = getattr(gpt_mod, cfg_name)(max_seq_len=seq, remat=False)
            full_L = cfg.num_layers
            cfg.num_layers = L
            paddle.seed(0)
            # probes price ONE chip (the bench/campaign unit), so the mesh
            # is pinned to a single device — on dev hosts with a virtual
            # multi-device CPU platform, the default mesh would silently
            # shard some probe batches and skew the extrapolation
            build_mesh(dp=1, devices=jax.devices()[:1])
            model = GPT(cfg)
            model.bfloat16()
            crit = GPTPretrainingCriterion()
            opt = paddle.optimizer.AdamW(
                learning_rate=2e-4, weight_decay=0.1,
                grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0),
                accumulator_dtype="bfloat16")

            def loss_fn(m, b):
                logits = m(paddle.to_tensor(b["input_ids"]))
                return crit(logits, paddle.to_tensor(b["labels"]))

            trainer = Trainer(model, opt, loss_fn)
            rng = np.random.RandomState(0)
            for mb in micro_bss:
                ids = rng.randint(0, cfg.vocab_size, (mb, seq + 1))
                batch = {"input_ids": ids[:, :-1].astype("int32"),
                         "labels": ids[:, 1:].astype("int32")}
                program = _noremat_program(trainer, batch)
                state_b, batch_b, params_b, _bs = _state_bytes(
                    program.arg_infos)
                state_by_L[L], params_by_L[L] = state_b, params_b
                for pol in policies:
                    w = replay_remat(program, pol,
                                     arg_infos=program.arg_infos,
                                     segments=L)
                    probe[(L, mb, pol)] = (w, batch_b)
                del program
            del trainer, model, opt
            gc.collect()
    finally:
        set_mesh(saved_mesh)
        set_rng_state(saved_rng)

    L0, L1 = probe_layers
    span = L1 - L0

    def lerp(a, b):
        return int(a + (full_L - L0) * (b - a) / span)

    scored = []
    for entry in grid:
        _, bs, rp, accum = entry
        pol = BENCH_POLICY_NAMES.get(rp, rp)
        mb = bs // max(accum, 1)
        w0, batch_b = probe[(L0, mb, pol)]
        w1, _ = probe[(L1, mb, pol)]
        # extrapolate each replayed FIELD linearly in depth, then price
        # the synthetic full-depth what-if through the SAME `_price` the
        # trainer autotuner uses — the 12-flops/param epilogue, the f32
        # grad-merge accumulator and the activation-traffic legs exist
        # in exactly one place (the wire legs stay 0 by design: the
        # probes are pinned single-device)
        from .remat_advisor import RematWhatIf
        w = RematWhatIf(
            policy=pol,
            peak_bytes=lerp(w0.peak_bytes, w1.peak_bytes),
            base_peak_bytes=lerp(w0.base_peak_bytes, w1.base_peak_bytes),
            saved_bytes=lerp(w0.saved_bytes, w1.saved_bytes),
            boundary_bytes=lerp(w0.boundary_bytes, w1.boundary_bytes),
            dropped_bytes=lerp(w0.dropped_bytes, w1.dropped_bytes),
            bump_bytes=lerp(w0.bump_bytes, w1.bump_bytes),
            recompute_flops=lerp(w0.recompute_flops, w1.recompute_flops),
            step_flops=lerp(w0.step_flops, w1.step_flops),
            segments=full_L)
        state_b = lerp(state_by_L[L0], state_by_L[L1])
        params_b = lerp(params_by_L[L0], params_by_L[L1])
        peak, _flops, rt, tok_s = _price(
            w, state_b, batch_b, params_b, mb * seq, "tokens/s", chip,
            accum=accum)
        scored.append((entry, peak, peak <= budget, tok_s))
        if log:
            log(f"advisor {entry}: peak {peak / 2**30:.2f} GiB "
                f"{'ok' if peak <= budget else 'OOM'}, "
                f"predicted {tok_s:.0f} tok/s ({rt.bound}-bound)")
    scored.sort(key=lambda s: (not s[2], -s[3]))
    return [s[0] for s in scored[:top]]
