"""Schedule Doctor — overlap-aware critical-path analysis of one
lowered program, and the COLL-SERIALIZED lint.

`cost_model.roofline_step_time` prices a step as max(compute, HBM,
wire): the analytic floor that assumes XLA fully overlaps the compute
stream with the collective stream.  The LOWERED program often cannot
overlap them — a tensor-parallel psum that consumes the block's only
matmul has nothing to hide behind, and the step runs at the SERIAL sum
instead (the gap T3 closes by decomposing collectives into per-chunk
ops interleaved with the matmuls that produce them, arxiv 2401.16677).
This pass makes that gap measurable before a chip sees the program
(compiler-level schedule verification after TPU-MLIR, arxiv
2210.15016):

1. build the operand/result dependency DAG over the jaxpr, recursing
   into scan/while/pjit sub-jaxprs the way `memory.py`'s liveness walk
   does (a scan body's nodes are priced once and scaled by the trip
   count; source lines survive, so a scan-body collective attributes
   to the line that wrote it);
2. price every node with the existing legs — `cost_model.eqn_flops`
   for compute, operand+result bytes for the HBM stream (each compute
   node costs max(flops leg, HBM leg): its own tiny roofline), and
   `collective_wire_bytes`/`collective_wire_split` for collectives
   (group sizes from the analysis context's mesh axes; DCN-spanning
   hops priced at DCN bandwidth);
3. run a two-resource list schedule — ONE compute stream, ONE
   collective stream, critical-path-rank priority — which yields the
   critical path with per-op attribution, an overlap-aware predicted
   step time bracketed by construction
   (max(compute, wire) <= overlap <= compute + wire), and the fraction
   of wire time the schedule actually hides.

The COLL-SERIALIZED rule fires (ERROR) when a collective sits on the
critical path and the compute that COULD run concurrently (neither its
ancestor nor its descendant) cannot hide at least
`ctx.schedule_hide_frac` of its wire time — the exact program shape
the ROADMAP's decomposed-collective work must fix, caught statically.

`ScheduleEstimate.overlap_frac` feeds `autotune._price`
(`cost_model.roofline_step_time_overlap`), and the serial/overlap pair
feeds the flight recorder's predicted-tick band so the ROOFLINE-DRIFT
ledger can tell a mispriced leg from a serialized schedule.
"""
import heapq
from dataclasses import dataclass, field

from .findings import Finding, Severity
# ONE set of jaxpr-walk helpers, shared with the memory pass (the two
# passes must agree on what a var/sub-jaxpr/byte/op-label is — a fix
# to either walk reaches both)
from .memory import _aval_bytes, _is_var, _sub_jaxprs
from .pass_manager import Analyzer, register_analyzer

__all__ = ["ScheduleNode", "ScheduleEstimate", "estimate_schedule",
           "ScheduleAnalyzer", "COLLECTIVE_PRIMS"]

# jaxpr primitives that lower to a collective on the wire (the jaxpr
# vocabulary of analyzers.COLLECTIVE_OPS; cost_model._COLLECTIVE_ALIASES
# maps them onto the ring formulas)
COLLECTIVE_PRIMS = frozenset({
    "psum", "pmax", "pmin", "ppermute", "pshuffle", "psum_scatter",
    "pbroadcast", "all_gather", "all_gather_invariant", "all_to_all",
    "reduce_scatter", "pgather"})

# sub-jaxpr-carrying primitives whose body repeats: scan multiplies its
# body cost by the trip count; while bodies price ONE iteration (the
# trip count is dynamic — decode loops carry their own k elsewhere)
_ATTRIBUTION_MIN_S = 1e-12


def _eqn_source(eqn):
    """`prim @ file.py:line` label — the per-op attribution unit (same
    rendering as memory.py's peak attribution, so the two passes agree
    on what an op is called; memory's variant appends an eqn index the
    flattened DAG doesn't have, so the fallback here is the bare
    primitive name)."""
    prim = eqn.primitive.name
    try:
        from jax._src import source_info_util
        frame = source_info_util.user_frame(eqn.source_info)
        if frame is not None:
            import os
            return (f"{prim} @ {os.path.basename(frame.file_name)}:"
                    f"{frame.start_line}")
    except Exception:
        pass
    return prim


@dataclass
class ScheduleNode:
    """One schedulable op of the flattened program DAG."""
    idx: int
    op: str                      # primitive name
    source: str                  # "psum @ gpt.py:123"
    stream: str                  # "compute" | "collective"
    cost_s: float                # duration on its stream (trip-scaled)
    flops: int = 0
    hbm_bytes: int = 0
    wire_bytes: int = 0          # ici + dcn (collectives only)
    dcn_bytes: int = 0           # the DCN share of wire_bytes
    preds: set = field(default_factory=set)
    start_s: float = 0.0
    end_s: float = 0.0
    critical: bool = False

    def to_dict(self):
        d = {"op": self.op, "source": self.source, "stream": self.stream,
             "cost_us": round(self.cost_s * 1e6, 3)}
        if self.wire_bytes:
            d["wire_bytes"] = self.wire_bytes
        return d


@dataclass
class ScheduleEstimate:
    """Two-stream schedule of one lowered program.

    The three step times bracket by construction:
      ``ideal_step_s``   = max(compute_s, wire_s) — streams fully
                           overlapped, today's roofline max();
      ``overlap_step_s`` = the list schedule's makespan under the real
                           dependencies (clamped into the bracket);
      ``serial_step_s``  = compute_s + wire_s — nothing overlaps.
    ``overlap_frac`` is the fraction of wire time the schedule hides
    under compute (1.0 when there is no wire): the knob
    `cost_model.roofline_step_time_overlap` consumes."""
    n_nodes: int = 0
    n_collectives: int = 0
    flops: int = 0
    hbm_bytes: int = 0
    wire_ici_bytes: int = 0
    wire_dcn_bytes: int = 0
    compute_s: float = 0.0       # compute-stream busy time
    wire_s: float = 0.0          # collective-stream busy time
    overlap_step_s: float = 0.0
    chip: str = "v5e"
    critical_path: list = field(default_factory=list)   # ScheduleNodes
    serialized: list = field(default_factory=list)
    # [(node, hideable_s, hidden_frac)] — COLL-SERIALIZED evidence

    @property
    def ideal_step_s(self):
        return max(self.compute_s, self.wire_s)

    @property
    def serial_step_s(self):
        return self.compute_s + self.wire_s

    @property
    def hidden_wire_s(self):
        return self.serial_step_s - self.overlap_step_s

    @property
    def exposed_wire_s(self):
        return self.wire_s - self.hidden_wire_s

    @property
    def overlap_frac(self):
        """Fraction of wire time the schedule hides under compute —
        1.0 with no wire at all (nothing to hide: the overlap-aware
        price collapses to the roofline max)."""
        if self.wire_s <= 0:
            return 1.0
        return max(0.0, min(1.0, self.hidden_wire_s / self.wire_s))

    def to_dict(self):
        return {"n_nodes": self.n_nodes,
                "n_collectives": self.n_collectives,
                "flops": self.flops, "hbm_bytes": self.hbm_bytes,
                "wire_ici_bytes": self.wire_ici_bytes,
                "wire_dcn_bytes": self.wire_dcn_bytes,
                "compute_us": round(self.compute_s * 1e6, 3),
                "wire_us": round(self.wire_s * 1e6, 3),
                "ideal_step_us": round(self.ideal_step_s * 1e6, 3),
                "overlap_step_us": round(self.overlap_step_s * 1e6, 3),
                "serial_step_us": round(self.serial_step_s * 1e6, 3),
                "overlap_frac": round(self.overlap_frac, 4),
                "n_serialized_collectives": len(self.serialized),
                "critical_path": [n.to_dict()
                                  for n in self.critical_path]}

    def __str__(self):
        lines = [f"step: overlap {self.overlap_step_s * 1e6:.1f} us "
                 f"(roofline max {self.ideal_step_s * 1e6:.1f}, serial "
                 f"{self.serial_step_s * 1e6:.1f}) — "
                 f"{self.overlap_frac:.0%} of "
                 f"{self.wire_s * 1e6:.1f} us wire hidden, "
                 f"{self.n_collectives} collective(s) / "
                 f"{self.n_nodes} node(s)"]
        for n in self.critical_path[:16]:
            mark = "  << SERIALIZED" if any(
                s[0] is n for s in self.serialized) else ""
            lines.append(f"  {n.cost_s * 1e6:>10.2f} us "
                         f"{n.stream:<10} {n.source}{mark}")
        return "\n".join(lines)


def _collective_axes(eqn):
    """Named mesh axes of one collective eqn ('axes' on psum & friends,
    'axis_name' on ppermute/all_gather/all_to_all). Positional axes
    (ints) carry no name and are skipped — their size is baked into
    the aval and the group can't be recovered without the trace."""
    axes = eqn.params.get("axes", None)
    if axes is None:
        axes = eqn.params.get("axis_name", None)
    if axes is None:
        return ()
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    return tuple(a for a in axes if isinstance(a, str))


def _collective_group(eqn, mesh_axes):
    """Participant count of one collective: the product of its named
    axes' sizes (ctx.mesh_axes), or the explicit axis_index_groups row
    length when present. 1 = degenerate (XLA folds it to a copy)."""
    groups = eqn.params.get("axis_index_groups")
    if groups:
        try:
            return max(len(groups[0]), 1)
        except (TypeError, IndexError):
            pass
    n = 1
    for a in _collective_axes(eqn):
        n *= int((mesh_axes or {}).get(a, 1))
    return n


def _walk(jx, nodes, entry, scale, ctx):
    """Flatten one (sub-)jaxpr into `nodes`. `entry` is the pred-id set
    every node with a free (invar/const) operand inherits — for a
    sub-jaxpr, the producers of the carrying eqn's operands, so the
    region hammocks between its operands and its consumers. Returns the
    producer-id sets of the jaxpr's outvars (the region's sinks)."""
    chip, mxu_eff, mesh_axes, hosts = (ctx["chip"], ctx["mxu_eff"],
                                       ctx["mesh_axes"], ctx["hosts"])
    from ..cost_model import (collective_wire_split, eqn_flops)
    producers = {}

    def prods(v):
        return producers.get(v, entry)

    for eqn in jx.eqns:
        preds = set()
        for v in eqn.invars:
            if _is_var(v):
                preds |= prods(v)
        name = eqn.primitive.name
        subs = _sub_jaxprs(eqn)
        if subs and name not in COLLECTIVE_PRIMS:
            trip = int(eqn.params.get("length", 1)) if name == "scan" \
                else 1
            if name == "cond" and len(subs) > 1:
                # mutually exclusive branches: exactly ONE executes, so
                # price the most expensive (the eqn_flops rule) — a
                # summed walk would inflate compute_s AND overcount the
                # untaken branch as COLL-SERIALIZED-hideable compute
                def branch_cost(sj):
                    tmp = []
                    _walk(sj, tmp, frozenset(), 1, ctx)
                    return sum(n.cost_s for n in tmp)
                subs = [max(subs, key=branch_cost)]
            sinks = set()
            for sj in subs:
                sinks |= _walk(sj, nodes, frozenset(preds),
                               scale * trip, ctx)
            out = frozenset(sinks or preds)
            for v in eqn.outvars:
                producers[v] = out
            continue
        out_bytes = sum(_aval_bytes(v.aval) for v in eqn.outvars)
        in_bytes = sum(_aval_bytes(v.aval) for v in eqn.invars
                       if _is_var(v))
        idx = len(nodes)
        if name in COLLECTIVE_PRIMS:
            group = _collective_group(eqn, mesh_axes)
            payload = max(in_bytes, out_bytes)
            h = max((hosts.get(a, 1) for a in _collective_axes(eqn)),
                    default=1)
            split = collective_wire_split(name, payload, group,
                                          host_count=h)
            cost = (split["ici"] / chip.ici_bw
                    + split["dcn"] / chip.dcn_bw) * scale
            node = ScheduleNode(
                idx=idx, op=name, source=_eqn_source(eqn),
                # a degenerate group's collective folds to a copy: it
                # has no wire leg and must not occupy (or ever flag)
                # the collective stream
                stream="collective" if cost > 0 else "compute",
                cost_s=cost,
                hbm_bytes=(in_bytes + out_bytes) * scale,
                wire_bytes=(split["ici"] + split["dcn"]) * scale,
                dcn_bytes=split["dcn"] * scale,
                preds=set(preds))
        else:
            flops = eqn_flops(eqn)
            cost = max(flops / (chip.peak_flops * mxu_eff),
                       (in_bytes + out_bytes) / chip.hbm_bw) * scale
            node = ScheduleNode(
                idx=idx, op=name, source=_eqn_source(eqn),
                stream="compute", cost_s=cost, flops=flops * scale,
                hbm_bytes=(in_bytes + out_bytes) * scale,
                preds=set(preds))
        nodes.append(node)
        me = frozenset((idx,))
        for v in eqn.outvars:
            producers[v] = me
    sinks = set()
    for v in jx.outvars:
        if _is_var(v):
            sinks |= prods(v)
    return sinks


def _list_schedule(nodes):
    """Two-resource list schedule: each node starts at
    max(stream free, preds' ends); ready nodes are picked by
    downstream-WIRE release first (a compute node whose chain feeds a
    collective goes ahead of an equal-stream chain that doesn't —
    releasing wire early is free for the compute stream's busy time
    and lets the collective stream run concurrently, exactly what a
    latency-hiding scheduler does), then critical-path rank (longest
    downward path). Deterministic (wire-release, rank, then index).
    Returns the makespan."""
    succs = [[] for _ in nodes]
    n_preds = [0] * len(nodes)
    for n in nodes:
        n_preds[n.idx] = len(n.preds)
        for p in n.preds:
            succs[p].append(n.idx)
    # downward ranks via reverse topological order (nodes are appended
    # in a valid topological order by construction)
    rank = [0.0] * len(nodes)
    wire_down = [0.0] * len(nodes)
    for n in reversed(nodes):
        down = max((rank[s] for s in succs[n.idx]), default=0.0)
        rank[n.idx] = n.cost_s + down
        own_wire = n.cost_s if n.stream == "collective" else 0.0
        wire_down[n.idx] = own_wire + max(
            (wire_down[s] for s in succs[n.idx]), default=0.0)

    def key(i):
        return (-wire_down[i], -rank[i], i)

    free = {"compute": 0.0, "collective": 0.0}
    ready = [key(n.idx) for n in nodes if not n.preds]
    heapq.heapify(ready)
    remaining = [n_preds[i] for i in range(len(nodes))]
    makespan = 0.0
    while ready:
        i = heapq.heappop(ready)[2]
        n = nodes[i]
        earliest = max((nodes[p].end_s for p in n.preds), default=0.0)
        n.start_s = max(free[n.stream], earliest)
        n.end_s = n.start_s + n.cost_s
        free[n.stream] = n.end_s
        makespan = max(makespan, n.end_s)
        for s in succs[i]:
            remaining[s] -= 1
            if remaining[s] == 0:
                heapq.heappush(ready, key(s))
    return makespan


def _critical_path(nodes):
    """Walk back from the last-finishing node: the chain of nodes whose
    end time gates each successor's start (preferring a dependency
    pred; falling back to the same-stream neighbor that the stream
    waited on). Marks and returns the path in program order."""
    if not nodes:
        return []
    by_stream_end = {}
    for n in nodes:
        by_stream_end.setdefault(n.stream, []).append(n)
    for ns in by_stream_end.values():
        ns.sort(key=lambda n: n.end_s)
    last = max(nodes, key=lambda n: (n.end_s, n.idx))
    path = []
    cur = last
    eps = 1e-15
    while cur is not None:
        cur.critical = True
        path.append(cur)
        if cur.start_s <= eps:
            break
        nxt = None
        for p in cur.preds:
            if abs(nodes[p].end_s - cur.start_s) <= eps:
                nxt = nodes[p]
                break
        if nxt is None:
            # the stream (not a dependency) gated this start: the
            # previous node on the same stream ended exactly here
            import bisect
            ns = by_stream_end[cur.stream]
            k = bisect.bisect_right([n.end_s for n in ns],
                                    cur.start_s + eps) - 1
            while k >= 0 and (ns[k] is cur or ns[k].end_s > cur.start_s
                              + eps):
                k -= 1
            nxt = ns[k] if k >= 0 and \
                abs(ns[k].end_s - cur.start_s) <= eps else None
        if nxt is None:
            # a pred ended earlier but is still the binding constraint
            # (float drift): take the latest-ending pred
            nxt = max((nodes[p] for p in cur.preds),
                      key=lambda n: n.end_s, default=None)
        cur = nxt
    path.reverse()
    return path


def _ancestor_masks(nodes):
    """Per-node ancestor sets as int bitmasks (node idx -> bit)."""
    masks = [0] * len(nodes)
    for n in nodes:                      # topological order
        m = 0
        for p in n.preds:
            m |= masks[p] | (1 << p)
        masks[n.idx] = m
    return masks


def estimate_schedule(program, mesh_axes=None, axis_host_counts=None,
                      chip="v5e", mxu_efficiency=0.65, hide_frac=0.5,
                      top_k=24):
    """Overlap-aware schedule estimate of one lowered program (a
    `LoweredProgram` or anything with `.jaxpr`, or a closed jaxpr).

    `mesh_axes` sizes the collective groups ({axis: size}; the pass
    manager defaults it to the live mesh), `axis_host_counts` marks
    DCN-spanning axes ({axis: hosts}). `chip` defaults to the fixed
    v5e spec so committed manifests are deterministic. `hide_frac` is
    the COLL-SERIALIZED bar: a critical-path collective whose
    concurrently-schedulable compute covers less than this fraction of
    its wire time is serialized."""
    from ..cost_model import chip_spec
    jx = getattr(program, "jaxpr", program)
    jx = jx.jaxpr if hasattr(jx, "jaxpr") else jx
    chip = chip if hasattr(chip, "peak_flops") else chip_spec(chip)
    ctx = {"chip": chip, "mxu_eff": float(mxu_efficiency),
           "mesh_axes": dict(mesh_axes or {}),
           "hosts": dict(axis_host_counts or {})}
    nodes = []
    _walk(jx, nodes, frozenset(), 1, ctx)
    est = ScheduleEstimate(n_nodes=len(nodes), chip=chip.name)
    if not nodes:
        return est
    for n in nodes:
        if n.stream == "collective":
            est.n_collectives += 1
            est.wire_s += n.cost_s
        else:
            est.compute_s += n.cost_s
        est.flops += n.flops
        est.hbm_bytes += n.hbm_bytes
        est.wire_ici_bytes += n.wire_bytes - n.dcn_bytes
        est.wire_dcn_bytes += n.dcn_bytes
    makespan = _list_schedule(nodes)
    # the bracket holds for any work-conserving schedule; clamping
    # makes it definitional, so float drift can never leak out of
    # [max, sum] into the manifests or the autotuner
    est.overlap_step_s = min(max(makespan, est.ideal_step_s),
                             est.serial_step_s)
    path = _critical_path(nodes)
    est.critical_path = [n for n in path
                         if n.cost_s >= _ATTRIBUTION_MIN_S]
    est.critical_path.sort(key=lambda n: -n.cost_s)
    est.critical_path = est.critical_path[:top_k]
    # COLL-SERIALIZED evidence: for each critical-path collective, the
    # compute neither upstream nor downstream of it — the work a
    # latency-hiding schedule COULD run during the wire transfer
    crit_colls = [n for n in path
                  if n.stream == "collective" and n.wire_bytes > 0]
    if crit_colls:
        masks = _ancestor_masks(nodes)
        for c in crit_colls:
            cbit = 1 << c.idx
            hideable = sum(
                n.cost_s for n in nodes
                if n.stream == "compute"
                and not (masks[c.idx] >> n.idx) & 1      # not ancestor
                and not masks[n.idx] & cbit)             # not descendant
            frac = hideable / c.cost_s if c.cost_s > 0 else 1.0
            if frac < hide_frac:
                est.serialized.append((c, hideable, frac))
    return est


@register_analyzer
class ScheduleAnalyzer(Analyzer):
    """Overlap-aware schedule pass + the COLL-SERIALIZED rule.

    Findings:
      COLL-SERIALIZED  ERROR  a collective sits on the two-stream
                              schedule's critical path with less
                              concurrently-schedulable compute than
                              `ctx.schedule_hide_frac` of its wire
                              time — the lowered program SERIALIZES
                              the wire behind the MXU, so the real
                              step runs at the serial sum while every
                              roofline consumer (autotuner horizon,
                              capacity pricing) still believes the
                              max().

    Metrics feed schedule_manifests/<config>.json (overlap/serial/ideal
    step time, overlap fraction, critical-path attribution) for the
    five BASELINE configs and the fused gpt_train_multi capture; the
    pricing chip is pinned to v5e like the tuning manifests, so a CPU
    and a TPU checkout agree byte-for-byte."""
    name = "schedule"

    def run(self, program, ctx):
        if getattr(program, "jaxpr", None) is None:
            self.metrics = {"available": False}
            return []
        est = estimate_schedule(
            program, mesh_axes=ctx.mesh_axes,
            axis_host_counts=ctx.extra.get("axis_host_counts"),
            hide_frac=ctx.schedule_hide_frac,
            chip=ctx.extra.get("schedule_chip", "v5e"))
        self.metrics = {"available": True, **est.to_dict()}
        findings = []
        for node, hideable, frac in est.serialized:
            findings.append(Finding(
                "COLL-SERIALIZED", Severity.ERROR,
                f"{node.source} ({node.wire_bytes} wire bytes, "
                f"{node.cost_s * 1e6:.2f} us) sits on the critical "
                f"path with only {hideable * 1e6:.2f} us of "
                f"concurrently-schedulable compute "
                f"({frac:.0%} of its wire time, bar "
                f"{ctx.schedule_hide_frac:.0%}) — the schedule "
                "serializes the wire behind the MXU and the step runs "
                "toward the serial sum "
                f"({est.serial_step_s * 1e6:.1f} us) instead of the "
                f"roofline max ({est.ideal_step_s * 1e6:.1f} us)",
                op=node.source,
                suggested_fix="decompose the collective into per-chunk "
                "ops interleaved with the matmuls that produce them "
                "(shard_map + ppermute ring), or reorder independent "
                "compute next to it so the latency-hiding scheduler "
                "has something to overlap"))
        return findings
