"""Graph analyzer catalog — each pass reads the lowered StableHLO and
emits Findings with stable rule ids (docs/static_analysis.md).

These generalize the graph pins that previously lived as inline regexes
in tests/test_hlo_regression.py: layout (the r2 NHWC win), dtype (bf16
at the MXU boundary), host transfers, graph shape vs a committed
manifest, and collective accounting cross-checked against cost_model's
analytic wire-bytes model (fine-grained compute/collective split after
T3, arxiv 2401.16677).
"""
import re

from .findings import Finding, Severity
from .pass_manager import Analyzer, register_analyzer

__all__ = ["LayoutAnalyzer", "DtypeAnalyzer", "HostTransferAnalyzer",
           "GraphShapeAnalyzer", "CollectiveAnalyzer", "ServingAnalyzer",
           "PrefillStallAnalyzer", "TrainingAnalyzer", "KvQuantAnalyzer",
           "RooflineDriftAnalyzer", "COLLECTIVE_OPS", "MXU_OPS"]

MXU_OPS = ("dot_general", "convolution")
COLLECTIVE_OPS = ("all_reduce", "all_gather", "all_to_all",
                  "reduce_scatter", "collective_permute",
                  "collective_broadcast")


@register_analyzer
class LayoutAnalyzer(Analyzer):
    """Activation transposes inside conv/matmul bodies.

    Weight-layout transposes applied directly to parameters (`%argN`)
    fold into XLA's free parameter-layout assignment and are counted but
    never flagged. Everything else is HBM traffic NHWC exists to avoid
    (~15x measured on NCHW ResNet-50): flagged ERROR under a pinned
    data_format, WARNING otherwise. By-design transposes (s2d input
    pack, sequence-major flip, API-boundary NCHW heads) are exempted via
    context.allowed_activation_transposes regexes and reported INFO."""
    name = "layout"

    def run(self, program, ctx):
        transposes = program.ops_named("transpose")
        act = program.activation_transposes()
        allowed_pats = [re.compile(p)
                        for p in ctx.allowed_activation_transposes]
        findings = []
        n_allowed = 0
        for op in act:
            if any(p.search(op.line) for p in allowed_pats):
                n_allowed += 1
                continue
            sev = (Severity.ERROR if ctx.data_format == "NHWC"
                   else Severity.WARNING)
            findings.append(Finding(
                "LAYOUT-ACT-TRANSPOSE", sev,
                "activation transpose in the lowered graph — layout "
                "left the TPU-preferred minor-to-major order "
                f"(~{max(op.operand_bytes(), 1)} bytes of HBM traffic "
                "per call)",
                op=op.line,
                suggested_fix="keep data_format=NHWC end to end; the "
                "usual breakers are concat/upsample/reshape between "
                "convs, or an NCHW-assuming head"))
        if n_allowed:
            findings.append(Finding(
                "LAYOUT-ALLOWED-TRANSPOSE", Severity.INFO,
                f"{n_allowed} by-design activation transpose(s) "
                "(exempted by context)"))
        self.metrics = {"n_transposes": len(transposes),
                        "n_weight_transposes": len(transposes) - len(act),
                        "n_activation_transposes": len(act),
                        "n_allowed_activation_transposes": n_allowed}
        return findings


@register_analyzer
class DtypeAnalyzer(Analyzer):
    """f32 upcasts of matmul/conv OPERANDS under a bf16/amp policy.

    f32 inputs halve the MXU rate; f32 accumulation on the output side
    is free and numerically right, so only operand types are checked.
    context.f32_dot_allow exempts by-design f32 matmuls (MoE router
    logits). f64 anywhere is flagged regardless of policy."""
    name = "dtype"

    def run(self, program, ctx):
        findings = []
        mxu = program.ops_named(*MXU_OPS)
        n_f32 = 0
        low = ctx.policy_dtype in ("bfloat16", "float16")
        for op in mxu:
            elems = [t.split("x")[-1] for t in op.operand_types]
            if "f64" in elems:
                findings.append(Finding(
                    "DTYPE-F64-OPERAND", Severity.ERROR,
                    f"f64 operand on {op.name} (no TPU f64 MXU path)",
                    op=op.line))
                continue
            if not low:
                continue
            if "f32" in elems:
                if ctx.f32_dot_allow is not None and ctx.f32_dot_allow(op):
                    findings.append(Finding(
                        "DTYPE-F32-ALLOWED", Severity.INFO,
                        f"by-design f32 {op.name} (exempted)",
                        op=op.line))
                    continue
                n_f32 += 1
                findings.append(Finding(
                    "DTYPE-F32-MATMUL", Severity.ERROR,
                    f"f32 operand on {op.name} under {ctx.policy_dtype} "
                    "policy — halves the MXU rate",
                    op=op.line,
                    suggested_fix="cast the activation down at the op "
                    "boundary (amp_compute_cast / model.bfloat16()); "
                    "keep f32 only on the accumulation output"))
        self.metrics = {"n_mxu_ops": len(mxu), "n_f32_mxu_ops": n_f32,
                        "policy_dtype": ctx.policy_dtype}
        return findings


@register_analyzer
class KvQuantAnalyzer(Analyzer):
    """Quantized-KV-pool discipline for serving programs (context
    extra["kv_quant"] set, e.g. the `gpt_decode_kv8` PROGRAM config).
    Two rules:

    DTYPE-KV-SCALE-WIDTH — every floating cache argument (the per-token
    scale planes riding next to the int8 page bytes) must be exactly
    f32: f64 doubles the metadata byte stream and has no TPU path, and
    a sub-f32 plane quantizes the scales themselves (the write-time
    amax discipline prices 4 bytes/token/layer per plane, no more, no
    less).

    DTYPE-KV-DEQUANT-HBM — the dequantized pool must never materialize
    in HBM: the whole point of the int8 pool is that the decode tick
    streams int8 bytes + scale planes, with dequant happening on the
    page-sized working set inside the attention body
    (`ops.ragged_paged_attention._page_update`). A stablehlo `convert`
    whose int8 operand is at least one full pool tensor
    (extra["kv_pool_block_elems"] elements, the per-layer
    [P, ps, H, D] block) re-inflates the stream to bf16/f32 width and
    erases the capacity win before the first page is read."""
    name = "kv-quant"

    def run(self, program, ctx):
        quant = ctx.extra.get("kv_quant")
        if not quant:
            self.metrics = {"checked": False}
            return []
        findings = []
        from .memory import kv_cache_infos
        cache = kv_cache_infos(getattr(program, "arg_infos", None) or [])
        n_scales = n_bad_scales = 0
        for info in cache:
            dt = str(info.dtype)
            if "float" not in dt:            # ("bfloat16" matches too)
                continue                     # int8 page bytes
            n_scales += 1
            if dt not in ("float32", "f32"):
                n_bad_scales += 1
                findings.append(Finding(
                    "DTYPE-KV-SCALE-WIDTH", Severity.ERROR,
                    f"KV scale plane {info.name} is {dt}, not float32 "
                    "— f64 doubles the per-token metadata bytes (and "
                    "has no TPU path); narrower floats quantize the "
                    "scales themselves",
                    suggested_fix="store write-time scales as f32 "
                    "(serving.decoder._quantize_kv does)"))
        thresh = int(ctx.extra.get("kv_pool_block_elems") or 0)
        n_dequant = 0
        if thresh:
            from .lowering import tensor_type_bytes
            for op in program.ops_named("convert"):
                src = (op.operand_types or [""])[0]
                if not re.search(r"(?:^|x)i8>?\s*$", src):
                    continue
                dst = (op.result_types or [""])[0]
                if not re.search(r"(?:^|x)(f32|f64|bf16|f16)>?\s*$", dst):
                    continue
                # i8 itemsize is 1, so bytes == element count
                if tensor_type_bytes(src) >= thresh:
                    n_dequant += 1
                    findings.append(Finding(
                        "DTYPE-KV-DEQUANT-HBM", Severity.ERROR,
                        f"full-pool dequantization materialized in HBM "
                        f"({tensor_type_bytes(src)} int8 elements "
                        "converted to a wide float tensor) — the int8 "
                        "pool's halved byte stream is erased before "
                        "the attention reads a single page",
                        op=op.line,
                        suggested_fix="dequantize inside the shared "
                        "per-page update "
                        "(ops.ragged_paged_attention._page_update); "
                        "the pool must stay int8 end to end"))
        self.metrics = {"checked": True, "kv_quant": quant,
                        "n_cache_args": len(cache),
                        "n_scale_planes": n_scales,
                        "n_bad_scale_planes": n_bad_scales,
                        "n_pool_dequants": n_dequant}
        return findings


# custom_call targets that move data to/from the host or re-enter python
_HOST_TARGET_RE = re.compile(
    r"@([\w.]*(?:callback|CallbackTo|host_to_device|device_to_host)[\w.]*)")


def _host_transfer_ops(program, ctx):
    """ONE detector for host traffic inside a compiled program, shared
    by the HOST-* rules and the SERVE-HOST-SYNC-DECODE serving gate (a
    new callback pattern or allowlist rule added here reaches both).
    Returns (callbacks, data_ops): non-allowlisted host custom_calls as
    (op, target) pairs, and raw infeed/outfeed/send/recv ops."""
    callbacks = []
    allow = tuple(ctx.host_callback_allow) + _device_custom_calls()
    for op in program.ops_named("custom_call"):
        m = _HOST_TARGET_RE.search(op.line)
        if not m:
            continue
        target = m.group(1)
        if any(a in target for a in allow):
            continue
        callbacks.append((op, target))
    return callbacks, list(program.ops_named("infeed", "outfeed",
                                             "send", "recv"))


@register_analyzer
class HostTransferAnalyzer(Analyzer):
    """Device<->host transfers hiding inside a jit region: python
    callbacks (io_callback/debug.print left in a model), infeed/outfeed,
    send/recv. Each one serializes the step against the host and kills
    async dispatch — on TPU that's a full pipeline bubble per call."""
    name = "host-transfer"

    def run(self, program, ctx):
        findings = []
        callbacks, data_ops = _host_transfer_ops(program, ctx)
        for op, target in callbacks:
            findings.append(Finding(
                "HOST-CALLBACK", Severity.ERROR,
                f"host python callback `{target}` inside the jit region",
                op=op.line,
                suggested_fix="move the callback out of the compiled "
                "step (log post-step from host) or switch to an "
                "in-graph equivalent (debug.check_numerics)"))
        for op in data_ops:
            if op.name in ("infeed", "outfeed"):
                findings.append(Finding(
                    "HOST-INFEED", Severity.ERROR,
                    f"{op.name} op in the jit region (host data "
                    "dependency per step)", op=op.line))
            else:
                findings.append(Finding(
                    "HOST-SENDRECV", Severity.WARNING,
                    f"{op.name} op in the jit region", op=op.line))
        self.metrics = {
            "n_custom_calls": program.count("custom_call"),
            "n_host_callbacks": len(callbacks),
        }
        return findings


def _device_custom_calls():
    """Known device-side custom_call target fragments (Pallas kernels,
    sharding annotations) that must not be mistaken for host traffic."""
    try:
        from ..ops import DEVICE_CUSTOM_CALL_TARGETS
        return tuple(DEVICE_CUSTOM_CALL_TARGETS)
    except Exception:   # keep the analyzer usable mid-bootstrap
        return ("Sharding", "tpu_custom_call")


# the op families a manifest pins: MXU work, layout traffic, control
# flow, collectives, and escape hatches. Elementwise noise is excluded
# so a fusion-neutral refactor doesn't churn manifests.
MANIFEST_OPS = ("dot_general", "convolution", "transpose", "while",
                "custom_call", "reduce", "sort", "scatter", "gather",
                "iota", "rng_bit_generator") + COLLECTIVE_OPS


@register_analyzer
class GraphShapeAnalyzer(Analyzer):
    """Op-count contract: exact expected counts (the architecture's
    signature — 53 convs in ResNet-50, 6 dots/block + lm_head in GPT)
    and drift against a committed lint manifest. A duplicate forward,
    double-remat, or lost fusion shows up here as a count change and is
    reviewed in-diff instead of discovered on-chip."""
    name = "graph-shape"

    def run(self, program, ctx):
        hist = program.op_histogram
        counts = {op: hist.get(op, 0) for op in MANIFEST_OPS
                  if hist.get(op, 0)}
        self.metrics = {"op_counts": counts}
        findings = []
        for op, want in (ctx.expected_counts or {}).items():
            got = hist.get(op, 0)
            if got != want:
                findings.append(Finding(
                    "GRAPH-OPCOUNT-DRIFT", Severity.ERROR,
                    f"{op} count changed: {got} != expected {want} — "
                    "graph structure shifted; re-derive and update the "
                    "contract if intentional", ))
        committed = (ctx.manifest or {}).get("op_counts")
        if committed is not None:
            deltas = {op: (committed.get(op, 0), counts.get(op, 0))
                      for op in set(committed) | set(counts)
                      if committed.get(op, 0) != counts.get(op, 0)}
            if deltas:
                for op, (want, got) in sorted(deltas.items()):
                    sev = Severity.ERROR
                    msg = (f"manifest drift: {op} {want} -> {got}")
                    if op in MXU_OPS and want and got >= 2 * want:
                        findings.append(Finding(
                            "GRAPH-DOUBLE-FORWARD", Severity.ERROR,
                            f"{op} count doubled vs manifest ({want} -> "
                            f"{got}): duplicate forward or broken remat "
                            "policy (a third body copy blows HBM at "
                            "1.3B scale)"))
                    findings.append(Finding(
                        "GRAPH-MANIFEST-DRIFT", sev, msg,
                        suggested_fix="python -m paddle_tpu.analysis "
                        "--write-manifests (then review the diff)"))
        return findings


@register_analyzer
class ServingAnalyzer(Analyzer):
    """SERVE-HOST-SYNC-DECODE: a fused serving decode program (the
    `PagedGPTDecoder.decode_multi` loop, context
    extra["serving_decode"]=True) must be fully device-resident — zero
    per-tick host transfers (a callback/infeed inside the K-tick scan
    pays a host round-trip PER TOKEN, exactly the cost the fused loop
    exists to kill) — and must keep the KV-cache donation the per-tick
    step has (composes with MEM-NO-DONATION-KVCACHE: that rule warns on
    any decode program; here an undonated cache in the HOT fused loop
    is an ERROR, since every horizon would copy the whole paged store).
    Metrics record the device-loop count so manifests pin that the K
    ticks really lower to one while loop, not K unrolled dispatches."""
    name = "serving"

    def run(self, program, ctx):
        if not ctx.extra.get("serving_decode"):
            self.metrics = {"checked": False}
            return []
        findings = []
        callbacks, data_ops = _host_transfer_ops(program, ctx)
        n_host = len(callbacks) + len(data_ops)
        for op, target in callbacks:
            findings.append(Finding(
                "SERVE-HOST-SYNC-DECODE", Severity.ERROR,
                f"host transfer `{target}` inside the fused decode "
                "loop — every tick re-interposes the host, the exact "
                "per-token round-trip decode_multi exists to eliminate",
                op=op.line,
                suggested_fix="move the callback out of the decode "
                "step; telemetry belongs at horizon sync points "
                "(ServeStats), not inside the compiled loop"))
        for op in data_ops:
            findings.append(Finding(
                "SERVE-HOST-SYNC-DECODE", Severity.ERROR,
                f"{op.name} op inside the fused decode loop (host data "
                "dependency per tick)", op=op.line))
        from .memory import kv_cache_infos
        cache = kv_cache_infos(getattr(program, "arg_infos", None) or [])
        undonated = [i for i in cache if not i.donated]
        if undonated:
            names = ", ".join(sorted(i.name or "?" for i in undonated)[:4])
            findings.append(Finding(
                "SERVE-HOST-SYNC-DECODE", Severity.ERROR,
                f"KV-cache state ({names}) is not donated into the "
                "fused decode loop — every K-tick horizon would copy "
                "the whole paged store",
                suggested_fix="jit with donate_argnums on the k/v page "
                "arguments (serving.PagedGPTDecoder.decode_multi does)"))
        self.metrics = {"checked": True,
                        "n_host_transfers": n_host,
                        "n_device_loops": program.count("while"),
                        "cache_donated": not undonated,
                        "n_cache_args": len(cache)}
        return findings


@register_analyzer
class PrefillStallAnalyzer(Analyzer):
    """SERVE-PREFILL-STALL: no host-blocking prefill dispatch on the
    decode critical path. Runs only when `ctx.extra["serve_schedule"]`
    carries an engine scheduling trace
    (`ContinuousBatchingEngine.serve_schedule()` — the MEM-PAGE-REFCOUNT
    ledger pattern applied to scheduling decisions): each event is
    either a "prefill_sync" (a blocking prefill dispatch, recording how
    many decode slots sat stalled behind it) or a "horizon" (one ragged
    mixed K-tick dispatch with its decode/prefill row mix). A
    prefill_sync with `decode_active > 0` is the stall the ragged
    scheduler exists to eliminate — one long prompt freezing every
    decoding slot for a whole monolithic prefill — and is an ERROR.
    The committed `gpt_decode_ragged` PROGRAM config re-audits a trace
    captured from a real long-prompt-mid-stream workload on every CI
    run; planted-defect tests corrupt a trace to prove detection.
    Metrics pin the chunked-admission shape (mixed horizons present,
    zero stalls) through the committed manifests."""
    name = "prefill-stall"

    def run(self, program, ctx):
        events = ctx.extra.get("serve_schedule")
        if not events:
            self.metrics = {"checked": False}
            return []
        findings = []
        n_stall = n_prefill_sync = n_mixed = n_horizon = 0
        chunk_rows = 0
        for ev in events:
            kind = ev.get("kind")
            if kind == "prefill_sync":
                n_prefill_sync += 1
                active = int(ev.get("decode_active", 0))
                if active > 0:
                    n_stall += 1
                    findings.append(Finding(
                        "SERVE-PREFILL-STALL", Severity.ERROR,
                        f"host-blocking prefill dispatch ({ev.get('rows', '?')} "
                        f"row(s)) on the decode critical path stalled "
                        f"{active} running decode slot(s) — one long "
                        "prompt freezes every decoding slot for its "
                        "whole prefill",
                        suggested_fix="admit prompts as token-budgeted "
                        "chunks inside the decode horizon "
                        "(ContinuousBatchingEngine ragged scheduling / "
                        "serving.RaggedScheduler) instead of a "
                        "monolithic prefill sync"))
            elif kind == "horizon":
                n_horizon += 1
                if ev.get("prefill_rows"):
                    n_mixed += 1
                    chunk_rows += int(ev["prefill_rows"])
        self.metrics = {"checked": True,
                        "n_events": len(events),
                        "n_prefill_syncs": n_prefill_sync,
                        "n_stalled_prefill_syncs": n_stall,
                        "n_horizons": n_horizon,
                        "n_mixed_horizons": n_mixed,
                        "n_prefill_rows": chunk_rows}
        return findings


@register_analyzer
class RooflineDriftAnalyzer(Analyzer):
    """ROOFLINE-DRIFT: the scheduler's priced tick time must track the
    measured one. Runs only when `ctx.extra["roofline_drift"]` carries
    a flight-recorder drift report
    (`serving.trace.FlightRecorder.drift_report()` — the
    serve_schedule/page_ledger pattern applied to timing): one entry
    per dispatch shape with its rolling mean predicted and measured
    horizon seconds. A shape whose measured/predicted ratio exceeds
    `ctx.extra["drift_factor"]` (default 3.0) is MISPRICED — the
    roofline's max(compute, HBM, wire) no longer describes the
    dispatch, so every schedule priced from it (horizon K, chunk
    budget W, capacity slots) silently errs; an ERROR. A ratio below
    1/factor (overpriced — the model leaves real capacity on the
    table) is a WARNING. Shapes with fewer than
    `ctx.extra["drift_min_samples"]` (default 3) samples are skipped:
    a single cold tick is noise, not drift. Planted-defect tests feed
    a deliberately mispriced dispatch; on-chip runs audit the real
    recorder (CPU dev boxes drift by construction — the prediction
    prices the target chip — so CI uses planted reports, not live CPU
    timings)."""
    name = "roofline-drift"

    def run(self, program, ctx):
        report = ctx.extra.get("roofline_drift")
        if not report:
            self.metrics = {"checked": False}
            return []
        factor = float(ctx.extra.get("drift_factor") or 3.0)
        raw_min = ctx.extra.get("drift_min_samples")
        # None check, not truthiness: an explicit 0 means "audit every
        # shape, cold single ticks included"
        min_n = 3 if raw_min is None else int(raw_min)
        findings = []
        n_checked = n_over = n_under = n_serialized = 0
        worst = 1.0
        for entry in report:
            pred = float(entry.get("predicted_s") or 0.0)
            meas = float(entry.get("measured_s") or 0.0)
            n = int(entry.get("n") or 0)
            if pred <= 0 or n < min_n:
                continue
            n_checked += 1
            ratio = meas / pred
            shape = "x".join(str(s) for s in (entry.get("shape") or []))
            worst = max(worst, ratio, 1.0 / ratio if ratio > 0 else 1.0)
            if ratio > factor:
                n_over += 1
                # the serial-prediction band (ticks stamped with
                # predicted_serial_s) splits the over-drift verdict:
                # measured INSIDE the serial sum = the legs are priced
                # right but the schedule never overlapped them — a
                # COLL-SERIALIZED problem, not a pricing one
                serial = float(entry.get("predicted_serial_s") or 0.0)
                if serial > 0 and meas / serial <= factor:
                    n_serialized += 1
                    findings.append(Finding(
                        "ROOFLINE-DRIFT", Severity.ERROR,
                        f"dispatch shape [{shape}] measured "
                        f"{meas * 1e3:.3f} ms vs priced "
                        f"{pred * 1e3:.3f} ms ({ratio:.1f}x over), but "
                        f"WITHIN the serial sum of the priced legs "
                        f"({serial * 1e3:.3f} ms) — the schedule "
                        "SERIALIZES streams the roofline assumed "
                        f"overlapped (factor {factor:g}, n={n}); the "
                        "pricing inputs are fine",
                        suggested_fix="run the schedule pass "
                        "(debug.schedule_report / COLL-SERIALIZED) and "
                        "overlap the serialized collective — do NOT "
                        "re-fit step_hbm_bytes/flops_per_token, they "
                        "reproduce the measurement already"))
                    continue
                findings.append(Finding(
                    "ROOFLINE-DRIFT", Severity.ERROR,
                    f"dispatch shape [{shape}] measured {meas * 1e3:.3f} "
                    f"ms vs priced {pred * 1e3:.3f} ms — {ratio:.1f}x "
                    f"over the roofline (factor {factor:g}, n={n}): the "
                    "cost model underprices this shape, so every "
                    "schedule derived from it (horizon K, chunk budget, "
                    "capacity) errs silently",
                    suggested_fix="re-fit the pricing inputs for this "
                    "shape (step_hbm_bytes / flops_per_token / "
                    "measured_host_sync_s, chip spec) or exclude the "
                    "pollution source from the measured window"))
            elif ratio < 1.0 / factor:
                n_under += 1
                under = 1.0 / ratio if ratio > 0 else float("inf")
                findings.append(Finding(
                    "ROOFLINE-DRIFT", Severity.WARNING,
                    f"dispatch shape [{shape}] measured {meas * 1e3:.3f} "
                    f"ms vs priced {pred * 1e3:.3f} ms — "
                    f"{under:.1f}x UNDER the roofline (n={n}): "
                    "the model overprices this shape and leaves "
                    "schedulable capacity unused"))
        self.metrics = {"checked": True, "n_shapes": len(report),
                        "n_checked": n_checked, "n_over": n_over,
                        "n_under": n_under,
                        "n_serialized": n_serialized,
                        "worst_ratio": round(worst, 3),
                        "factor": factor}
        return findings


@register_analyzer
class TrainingAnalyzer(Analyzer):
    """HOST-SYNC-TRAIN: a fused multi-step TRAINING program (the
    `Trainer.step_multi` scan, context extra["train_multi"]=True) must
    be fully device-resident — zero host transfers inside the N-tick
    scan (a callback/infeed in the body pays a host round-trip PER
    STEP, exactly the dispatch cost the fused loop exists to kill), a
    DONATED carry (params/opt-state/grad-transform-state/consts thread
    through the scan; an undonated carry double-buffers the whole model
    state every horizon), and a real `stablehlo.while` (N ticks lowered
    to one device loop, not N unrolled step bodies — an unrolled
    horizon compiles N× slower and re-pays dispatch per tick on some
    backends). The serving twin is SERVE-HOST-SYNC-DECODE; both rules
    share `_host_transfer_ops`, so a new callback pattern reaches
    training and serving alike. Metrics pin the device-loop count and
    carry donation through the committed manifests."""
    name = "training"

    #: arg roles that form the fused scan's carried state
    CARRY_ROLES = ("param", "opt_state", "gt_state", "const")

    def run(self, program, ctx):
        if not ctx.extra.get("train_multi"):
            self.metrics = {"checked": False}
            return []
        findings = []
        callbacks, data_ops = _host_transfer_ops(program, ctx)
        n_host = len(callbacks) + len(data_ops)
        for op, target in callbacks:
            findings.append(Finding(
                "HOST-SYNC-TRAIN", Severity.ERROR,
                f"host transfer `{target}` inside the fused train scan "
                "— every tick re-interposes the host, the per-step "
                "round-trip step_multi exists to eliminate",
                op=op.line,
                suggested_fix="move the callback out of the step body; "
                "metrics/logging belong at horizon boundaries "
                "(LossBuffer drains), not inside the compiled loop"))
        for op in data_ops:
            findings.append(Finding(
                "HOST-SYNC-TRAIN", Severity.ERROR,
                f"{op.name} op inside the fused train scan (host data "
                "dependency per step)", op=op.line))
        carry = [i for i in (getattr(program, "arg_infos", None) or [])
                 if i.role in self.CARRY_ROLES]
        undonated = [i for i in carry if not i.donated]
        if undonated:
            names = ", ".join(sorted(i.name or "?" for i in undonated)[:4])
            findings.append(Finding(
                "HOST-SYNC-TRAIN", Severity.ERROR,
                f"scan carry state ({names}, ...) is not donated into "
                "the fused train loop — every horizon would keep two "
                "resident copies of params/opt-state",
                suggested_fix="Trainer(donate=True) (the default) "
                "threads the carry through donate_argnums"))
        n_loops = program.count("while")
        if carry and n_loops == 0:
            findings.append(Finding(
                "HOST-SYNC-TRAIN", Severity.ERROR,
                "the N train ticks did not lower to a device loop (no "
                "stablehlo.while): the horizon unrolled into N step "
                "bodies",
                suggested_fix="keep the horizon in ONE lax.scan "
                "(Trainer._build_multi); unrolled bodies blow compile "
                "time and code size linearly in N"))
        self.metrics = {"checked": True,
                        "n_host_transfers": n_host,
                        "n_device_loops": n_loops,
                        "carry_donated": not undonated,
                        "n_carry_args": len(carry)}
        return findings


def _attribute_mesh_axis(mesh_axes, group_size, groups):
    """Mesh axis a collective's replica groups run along, or None."""
    if not mesh_axes or not group_size or group_size <= 1:
        return None
    names = list(mesh_axes)
    sizes = [mesh_axes[n] for n in names]
    first = groups[0] if groups else None
    if first and len(first) == group_size:
        stride = 1
        for i in range(len(names) - 1, -1, -1):
            if sizes[i] == group_size:
                expect = [first[0] + k * stride
                          for k in range(group_size)]
                if list(first) == expect:
                    return names[i]
            stride *= sizes[i]
    matches = [n for n, s in mesh_axes.items() if s == group_size]
    return matches[0] if len(matches) == 1 else None


@register_analyzer
class CollectiveAnalyzer(Analyzer):
    """Collective count + payload bytes per op, cross-checked against
    cost_model's analytic wire-bytes (ring algorithms). Flags
    collectives in programs pinned single-device, and latency-bound
    tiny-payload collectives that should be bucketed."""
    name = "collective"

    # below this payload a ring all-reduce is latency- not bandwidth-
    # bound on ICI — many of these means gradient bucketing is off
    TINY_PAYLOAD = 16 * 1024

    def run(self, program, ctx):
        from ..cost_model import collective_wire_bytes
        findings = []
        entries = []
        for op in program.ops_named(*COLLECTIVE_OPS):
            payload = op.operand_bytes()
            group, n_groups = op.replica_group_size()
            # the ring model wants the FULL payload: for all_gather the
            # operand is the 1/n shard and the result is the gathered
            # array (the reverse for reduce_scatter), so max() of the
            # two sides is the full payload for every collective kind
            from .lowering import tensor_type_bytes
            full = max(payload,
                       sum(tensor_type_bytes(t) for t in op.result_types))
            wire = collective_wire_bytes(op.name, full, group or 1)
            entries.append({"op": op.name, "payload_bytes": payload,
                            "group_size": group, "num_groups": n_groups,
                            "wire_bytes": wire, "line": op.line_no})
            if ctx.expect_collectives is False:
                findings.append(Finding(
                    "COLL-UNEXPECTED", Severity.ERROR,
                    f"{op.name} in a program pinned single-device "
                    f"({payload} payload bytes)", op=op.line))
            elif payload and payload < self.TINY_PAYLOAD:
                findings.append(Finding(
                    "COLL-TINY-PAYLOAD", Severity.WARNING,
                    f"{op.name} with {payload}-byte payload is latency-"
                    "bound", op=op.line,
                    suggested_fix="bucket gradients (grad merge / "
                    "fused allreduce) so payloads amortize ring latency"))
        per_axis = {}
        if ctx.mesh_axes:
            # attribute each collective to a mesh axis (the T3-style
            # split): primary signal is the device-id STRIDE of its
            # replica groups (row-major mesh ⇒ axis i groups step by
            # the product of later axis sizes), which disambiguates
            # equal-sized axes; size matching is the fallback
            groups_by_line = {op.line_no: op.replica_groups()
                              for op in program.ops_named(*COLLECTIVE_OPS)}
            for e in entries:
                e["mesh_axis"] = _attribute_mesh_axis(
                    ctx.mesh_axes, e["group_size"],
                    groups_by_line.get(e["line"]))
                axis = e["mesh_axis"]
                if axis:
                    acc = per_axis.setdefault(
                        axis, {"count": 0, "payload_bytes": 0,
                               "wire_bytes": 0})
                    acc["count"] += 1
                    acc["payload_bytes"] += e["payload_bytes"]
                    acc["wire_bytes"] += e["wire_bytes"]
        self.metrics = {
            "n_collectives": len(entries),
            "collectives": entries,
            "total_payload_bytes": sum(e["payload_bytes"]
                                       for e in entries),
            "total_wire_bytes": sum(e["wire_bytes"] for e in entries),
        }
        if per_axis:
            self.metrics["per_mesh_axis"] = per_axis
        return findings
