"""What-if remat replay — concrete remat advice from the liveness walk.

The Memory Doctor (memory.py) names the top live tensors at the peak;
this module answers the follow-up question: *which remat policy moves
the peak where, at what recompute cost* — statically, from ONE no-remat
trace, before anything compiles.

Mechanics (a replay of memory.py's jaxpr-order liveness pass):

  1. Find the fwd/bwd boundary of a grad/train-step jaxpr: the eqn
     defining the loss (the earliest-defined scalar-float output).
     Residuals are the values defined at-or-before the boundary with a
     use after it — exactly what autodiff saves for the backward.
  2. Segment the forward into `segments` checkpoint regions (per-layer
     checkpoint granularity). Cut points target equal droppable bytes
     but snap to local minima of forward-crossing bytes — real block
     boundaries are where almost nothing is live across, so the cuts
     recover the layer structure from a flat jaxpr.
  3. For a candidate policy, classify each residual: *saved* (the
     policy's saveable predicate holds — e.g. dot_general outputs under
     "dots"), *boundary* (a forward use in a later segment: the next
     segment's checkpoint input, always saved), or *dropped* (truncated
     at its last forward use — the liveness walk then frees it in the
     forward, exactly what jax.checkpoint does).
  4. Re-run the liveness walk with those truncated ranges plus a flat
     "recompute working set" bump past the boundary: the largest
     segment's dropped bytes, which rematerialize during that segment's
     backward. The replayed peak is the what-if per-device peak.
  5. Recompute FLOPs = analytic FLOPs (cost_model.eqn_flops) of every
     non-saveable forward eqn — the extra forward the backward pays.
     For "full" that's the whole forward (~+33% of the 3x fwd step);
     for "dots" only the cheap elementwise tail.

Validated against real lowerings: tests/test_remat_advisor.py lowers
the same block stack with and without jax.checkpoint(policy=...) and
pins the replayed peak within 20% of the measured liveness peak of the
actually-rematted program.
"""
from dataclasses import dataclass, field

from .memory import (_aval_bytes, _is_var, estimate_jaxpr_memory,
                     propagate_shard_counts)

__all__ = ["RematWhatIf", "REMAT_POLICIES", "BENCH_POLICY_NAMES",
           "find_boundary", "saveable_predicate", "replay_remat",
           "advise_remat"]

# policy name -> one-line description (the saveable predicates live in
# saveable_predicate; aliases below). "none" is the no-remat baseline.
REMAT_POLICIES = {
    "none": "save every residual (no remat)",
    "full": "nothing_saveable: recompute the whole segment in backward",
    "dots": "dots_saveable: save every dot_general output",
    "dots_with_no_batch_dims": "save dot outputs without batch dims "
                               "(projections, not attention scores)",
}

_ALIASES = {
    "nothing_saveable": "full",
    "dots_saveable": "dots",
    "dots_no_batch": "dots_with_no_batch_dims",
    "dots_with_no_batch_dims_saveable": "dots_with_no_batch_dims",
    "everything_saveable": "none",
}

# bench.py / GPTConfig.remat_policy vocabulary -> advisor policy names
# (the model's 'dots' maps to jax dots_with_no_batch_dims_saveable —
# see models/gpt._remat_policy)
BENCH_POLICY_NAMES = {
    "full": "full",
    "dots": "dots_with_no_batch_dims",
    "none": "none",
}


def canonical_policy(name):
    name = _ALIASES.get(name, name)
    if name not in REMAT_POLICIES:
        raise KeyError(f"unknown remat policy {name!r}; known: "
                       f"{sorted(REMAT_POLICIES)} (+aliases "
                       f"{sorted(_ALIASES)})")
    return name


def saveable_predicate(policy):
    """eqn -> bool: would `policy` save this eqn's outputs as residuals
    instead of recomputing them in the backward."""
    policy = canonical_policy(policy)
    if policy == "none":
        return lambda eqn: True
    if policy == "full":
        return lambda eqn: False
    if policy == "dots":
        return lambda eqn: eqn.primitive.name == "dot_general"

    def no_batch_dots(eqn):
        if eqn.primitive.name != "dot_general":
            return False
        (_, _), (lb, _rb) = eqn.params["dimension_numbers"]
        return not lb
    return no_batch_dots


def find_boundary(jx):
    """Eqn index of the fwd/bwd boundary: where the loss value is
    defined. Scans the outputs for scalar floating values and takes the
    earliest-defined one (value_and_grad puts the loss first, the
    Trainer step puts it last; grads/opt-state outputs are all defined
    later). Falls back to the midpoint when no scalar output exists."""
    import jax.numpy as jnp
    defs = {}
    for i, eqn in enumerate(jx.eqns):
        for v in eqn.outvars:
            defs[v] = i
    cands = []
    for v in jx.outvars:
        if not _is_var(v) or v not in defs:
            continue
        aval = v.aval
        try:
            if aval.shape == () and jnp.issubdtype(aval.dtype, jnp.floating):
                cands.append(defs[v])
        except Exception:
            continue
    return min(cands) if cands else len(jx.eqns) // 2


@dataclass
class RematWhatIf:
    """One policy's replayed outcome on one program."""
    policy: str
    peak_bytes: int              # replayed per-device peak under policy
    base_peak_bytes: int         # measured peak of the no-remat program
    saved_bytes: int             # residuals the policy keeps (per device)
    boundary_bytes: int          # segment-crossing checkpoints (kept)
    dropped_bytes: int           # residuals dropped + recomputed
    bump_bytes: int              # modeled recompute working set
    recompute_flops: int         # extra fwd FLOPs the backward pays
    step_flops: int              # analytic FLOPs of the no-remat step
    segments: int
    top: list = field(default_factory=list)   # top live buffers at peak

    @property
    def recompute_pct(self):
        """Recompute as % of the full (no-remat) step's FLOPs."""
        if not self.step_flops:
            return 0.0
        return 100.0 * self.recompute_flops / self.step_flops

    @property
    def advice(self):
        gib = 1024.0 ** 3
        return (f"remat={self.policy}: peak "
                f"{self.base_peak_bytes / gib:.2f} GiB → "
                f"{self.peak_bytes / gib:.2f} GiB per device, "
                f"+{self.recompute_pct:.1f}% recompute FLOPs")

    def to_dict(self):
        return {"policy": self.policy, "peak_bytes": self.peak_bytes,
                "saved_bytes": self.saved_bytes,
                "boundary_bytes": self.boundary_bytes,
                "dropped_bytes": self.dropped_bytes,
                "recompute_flops": self.recompute_flops,
                "recompute_pct": round(self.recompute_pct, 2)}


def _collect(jx):
    """(defs, uses, n): def eqn per var, sorted use indices per var
    (program outputs use at n)."""
    n = len(jx.eqns)
    defs, uses = {}, {}
    for i, eqn in enumerate(jx.eqns):
        for v in eqn.invars:
            if _is_var(v):
                uses.setdefault(v, []).append(i)
        for v in eqn.outvars:
            defs[v] = i
    for v in jx.outvars:
        if _is_var(v):
            uses.setdefault(v, []).append(n)
    return defs, uses, n


def _segment_cuts(jx, defs, uses, boundary, droppable, segments):
    """Cut the forward [0, boundary] into `segments` chunks: targets at
    equal cumulative droppable bytes, each snapped to the nearby eqn
    index where the fewest forward-live bytes cross — liveness minima
    are the real block boundaries."""
    total = sum(droppable.values())
    # boundary 0 means the whole forward is one eqn (e.g. a nested-jit
    # call collapsed to a single pjit) — nothing to cut, and the snap
    # window below would be an empty range
    if segments <= 1 or not total or boundary < 1:
        return []
    # fwd-crossing bytes at each cut position c: def < c <= last fwd use
    delta = [0] * (boundary + 3)
    for v, d in defs.items():
        if d > boundary:
            continue
        fwd = [u for u in uses.get(v, []) if u <= boundary]
        if not fwd or max(fwd) <= d:
            continue
        b = _aval_bytes(v.aval)
        if b >= 1024:
            delta[d + 1] += b
            delta[max(fwd) + 1] -= b
    crossing, acc = [0] * (boundary + 2), 0
    for i in range(boundary + 2):
        acc += delta[i]
        crossing[i] = acc
    ideal, accd, k = [], 0, 1
    for i in range(boundary + 1):
        accd += droppable.get(i, 0)
        while k < segments and accd >= total * k / segments:
            ideal.append(i + 1)
            k += 1
    win = max(2, (boundary + 1) // (3 * segments))
    cuts = set()
    for t in ideal:
        lo, hi = max(1, t - win), min(boundary, t + win)
        cuts.add(min(range(lo, hi + 1),
                     key=lambda i: (crossing[i], abs(i - t))))
    return sorted(cuts)


@dataclass
class _ReplayBase:
    """Everything about a no-remat program that is the SAME for every
    candidate policy: the def/use walk, the fwd/bwd boundary, the
    propagated shard counts, the residual list, the base liveness peak,
    the total step FLOPs and the per-eqn forward FLOPs. `advise_remat`
    computes it once and hands it to every `replay_remat` call — the
    policy loop used to redo this walk per policy (~2x advisor host
    time on GPT-sized jaxprs)."""
    jx: object
    arg_infos: object
    defs: dict
    uses: dict
    boundary: int
    counts: dict
    residuals: list              # (var, def_idx, last_fwd_use)
    base_peak_bytes: int
    step_flops: int
    fwd_eqn_flops: list          # analytic FLOPs of eqns [0..boundary]


def _prepare_replay(program_or_jaxpr, arg_infos=None, boundary=None):
    """The policy-independent half of the what-if replay."""
    from ..cost_model import eqn_flops, jaxpr_flops
    program = program_or_jaxpr
    jx = getattr(program, "jaxpr", program)
    if arg_infos is None:
        arg_infos = getattr(program, "arg_infos", None)
    jx = jx.jaxpr if hasattr(jx, "jaxpr") else jx
    defs, uses, _n = _collect(jx)
    if boundary is None:
        boundary = find_boundary(jx)
    # fixed-point counts (analysis/propagation.py): per-dim specs where
    # the lowering pinned them, v1 heuristic everywhere else — so the
    # per-device residual pricing sees the same shards the memory pass
    # prices
    counts = propagate_shard_counts(
        jx, [i.shard_count for i in arg_infos] if arg_infos else None,
        arg_dims=([getattr(i, "dim_shards", None) for i in arg_infos]
                  if arg_infos else None))
    residuals = []
    for v, d in defs.items():
        us = uses.get(v, [])
        if d <= boundary and us and max(us) > boundary:
            fwd = [u for u in us if u <= boundary]
            residuals.append((v, d, max(fwd) if fwd else d))
    base = estimate_jaxpr_memory(jx, arg_infos=arg_infos, top_k=0,
                                 var_counts=counts)
    return _ReplayBase(
        jx=jx, arg_infos=arg_infos, defs=defs, uses=uses,
        boundary=boundary, counts=counts, residuals=residuals,
        base_peak_bytes=base.peak_bytes, step_flops=jaxpr_flops(jx),
        fwd_eqn_flops=[eqn_flops(e) for e in jx.eqns[:boundary + 1]])


def replay_remat(program_or_jaxpr, policy, arg_infos=None, segments=1,
                 boundary=None, top_k=4, base=None):
    """What-if liveness replay of one remat policy over a NO-remat
    grad/train-step program. Returns a RematWhatIf.

    The program must have been traced with checkpointing disabled (the
    autotuner's front doors arrange that); replaying over an
    already-rematted jaxpr would discount the same residuals twice.
    `base` is an optional precomputed `_prepare_replay` result —
    `advise_remat` shares one across its whole policy sweep."""
    if base is None:
        base = _prepare_replay(program_or_jaxpr, arg_infos=arg_infos,
                               boundary=boundary)
    jx, boundary = base.jx, base.boundary
    policy = canonical_policy(policy)
    save = saveable_predicate(policy)
    segments = max(int(segments or 1), 1)
    counts = base.counts

    def dev_bytes(v):
        return _aval_bytes(v.aval) // max(counts.get(v, 1), 1)

    droppable = {}
    for v, d, _ in base.residuals:
        if policy != "none" and not save(jx.eqns[d]):
            droppable[d] = droppable.get(d, 0) + dev_bytes(v)
    cuts = _segment_cuts(jx, base.defs, base.uses, boundary, droppable,
                         segments)

    def chunk_of(i):
        c = 0
        for cp in cuts:
            if i >= cp:
                c += 1
        return c

    overrides = {}
    seg_drop = [0] * (len(cuts) + 1)
    saved_b = bound_b = drop_b = 0
    for v, d, last_fwd in base.residuals:
        b = dev_bytes(v)
        if policy == "none" or save(jx.eqns[d]):
            saved_b += b
            continue
        if chunk_of(last_fwd) > chunk_of(d):
            bound_b += b           # next segment's checkpoint input
            continue
        overrides[v] = last_fwd
        drop_b += b
        seg_drop[chunk_of(d)] += b
    bump = max(seg_drop) if policy != "none" else 0

    est = estimate_jaxpr_memory(jx, arg_infos=base.arg_infos,
                                top_k=top_k,
                                last_use_override=overrides,
                                extra_after=(boundary, bump),
                                var_counts=counts)

    recompute = 0
    if policy != "none":
        recompute = sum(f for f, eqn in
                        zip(base.fwd_eqn_flops, jx.eqns)
                        if not save(eqn))

    return RematWhatIf(
        policy=policy, peak_bytes=est.peak_bytes,
        base_peak_bytes=base.base_peak_bytes, saved_bytes=saved_b,
        boundary_bytes=bound_b, dropped_bytes=drop_b, bump_bytes=bump,
        recompute_flops=recompute, step_flops=base.step_flops,
        segments=len(cuts) + 1, top=est.top)


def advise_remat(program, policies=None, arg_infos=None, segments=1,
                 boundary=None):
    """Replay every candidate policy over one no-remat program; returns
    RematWhatIf results sorted by replayed peak (smallest first). Each
    carries the `.advice` line the autotuner and CLI print:

        remat=dots: peak 12.4 GiB -> 7.9 GiB per device, +3.2% recompute FLOPs

    The base walk (defs/uses, boundary, shard counts, residuals, base
    peak, per-eqn forward FLOPs) is computed ONCE and shared across the
    policy sweep."""
    policies = policies or list(REMAT_POLICIES)
    base = _prepare_replay(program, arg_infos=arg_infos,
                           boundary=boundary)
    out = [replay_remat(program, p, segments=segments, base=base)
           for p in policies]
    return sorted(out, key=lambda r: r.peak_bytes)
