"""Lowering front-end for the Graph Doctor: turn any nn.Layer or jitted
callable into a `LoweredProgram` — pre-optimization StableHLO text plus
the closed jaxpr — on the CPU platform (chip-independent; no TPU or
tunnel needed), then give analyzers a cheap structured view of the ops.

The parser is deliberately line-oriented: StableHLO's pretty printer
emits one op per line except for region-carrying generic ops
(all_reduce, reduce, sort, ...), whose type signature lands on the
closing `}) : (...) -> ...` line — those are stitched by brace
balancing. This matches (and replaces) the regex counting the old
tests/test_hlo_regression.py did inline.
"""
import re
from collections import Counter
from dataclasses import dataclass, field

__all__ = ["ArgInfo", "HloOp", "LoweredProgram", "lower_layer",
           "lower_callable", "tensor_type_bytes", "sharding_shard_count",
           "sharding_dim_counts", "spec_dim_axes", "sharding_dim_axes",
           "tree_arg_infos",
           "parse_hlo_sharding", "harvest_hlo_shardings"]

_OP_RE = re.compile(r'"?stablehlo\.([a-zA-Z0-9_]+)"?')
_TENSOR_RE = re.compile(r"tensor<([^>]*)>")
_WEIGHT_TRANSPOSE_RE = re.compile(r"transpose %arg\d+, dims = ")

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2,
    "f8E4M3FN": 1, "f8E5M2": 1,
    "i64": 8, "ui64": 8, "i32": 4, "ui32": 4,
    "i16": 2, "ui16": 2, "i8": 1, "ui8": 1, "i1": 1,
    "c64": 8, "c128": 16,
}


def tensor_type_bytes(type_str):
    """Byte size of one `tensor<2x4xf32>`-style type string (0 when the
    element type is unknown or a dim is symbolic)."""
    m = _TENSOR_RE.search(type_str)
    body = m.group(1) if m else type_str
    parts = body.split("x")
    elem = parts[-1]
    n = 1
    for d in parts[:-1]:
        if not d.isdigit():
            return 0
        n *= int(d)
    return n * _DTYPE_BYTES.get(elem, 0)


@dataclass
class ArgInfo:
    """Per-argument metadata of a lowered program's flattened calling
    convention (one entry per %arg of the main function, jaxpr invar
    order). Carries the sharding/donation facts the memory & sharding
    passes need but the HLO text alone can't recover: what the arg IS
    (param vs optimizer slot vs batch), how many shards its sharding
    splits it into, and whether the buffer is donated."""
    name: str                    # pytree path, e.g. "params/fc.weight"
    role: str                    # param|opt_state|gt_state|const|lr|batch|input
    shape: tuple = ()
    dtype: str = ""
    bytes: int = 0               # global (unsharded) size
    spec: tuple = None           # PartitionSpec entries, None when unknown
    shard_count: int = 1         # devices one shard of this arg lands on
    dim_shards: tuple = None     # per-dim shard counts, None when unknown
    donated: bool = False

    @property
    def device_bytes(self):
        """Per-device footprint: global bytes split over the shard count
        (replicated args cost their full size on EVERY device)."""
        return self.bytes // max(self.shard_count, 1)


def sharding_shard_count(sharding):
    """How many ways a NamedSharding/PositionalSharding splits a value
    (1 = fully replicated). Robust to plain specs and None."""
    if sharding is None:
        return 1
    mesh = getattr(sharding, "mesh", None)
    spec = getattr(sharding, "spec", None)
    if mesh is None or spec is None:
        return max(int(getattr(sharding, "num_devices", 1) or 1), 1)
    count = 1
    for entry in spec:
        if entry is None:
            continue
        axes = entry if isinstance(entry, (tuple, list)) else (entry,)
        for a in axes:
            count *= int(mesh.shape.get(a, 1))
    return max(count, 1)


def sharding_dim_counts(sharding, ndim):
    """Per-DIMENSION shard counts of a NamedSharding over an
    `ndim`-rank value, or None when unknown. Feeds the memory pass's
    dim-aware propagation (`memory._eqn_out_shard`): knowing WHICH dim
    carries the sharding lets contracted `dot_general` dims drop their
    factor instead of leaking it into the output."""
    if sharding is None or ndim is None:
        return None
    mesh = getattr(sharding, "mesh", None)
    spec = getattr(sharding, "spec", None)
    if mesh is None or spec is None:
        return None
    dims = [1] * int(ndim)
    for i, entry in enumerate(spec):
        if i >= len(dims) or entry is None:
            continue
        axes = entry if isinstance(entry, (tuple, list)) else (entry,)
        for a in axes:
            dims[i] *= int(mesh.shape.get(a, 1))
    return tuple(dims)


def spec_dim_axes(spec, ndim):
    """Per-dim mesh-axis NAMES from PartitionSpec entries over an
    `ndim`-rank value: a tuple of tuples of axis-name strings (empty
    tuple = the dim is unsharded), or None when the spec itself is
    unknown. The identity half of `sharding_dim_counts` — knowing a
    dim is split 2-ways says how many shards, knowing it is split over
    "dp" says WHICH 2-way split, so two specs naming distinct axes are
    known to compose (their count product is exact, not a cap)."""
    if spec is None or ndim is None:
        return None
    out = [()] * int(ndim)
    for i, entry in enumerate(spec):
        if i >= int(ndim) or entry is None:
            continue
        axes = entry if isinstance(entry, (tuple, list)) else (entry,)
        out[i] = tuple(str(a) for a in axes if a is not None)
    return tuple(out)


def sharding_dim_axes(sharding, ndim):
    """`spec_dim_axes` lifted off a NamedSharding (constraint eqns carry
    one in params["sharding"]); None for shardings without a spec."""
    spec = getattr(sharding, "spec", None)
    if spec is None:
        return None
    return spec_dim_axes(tuple(spec), ndim)


_MHLO_SHARDING_RE = re.compile(r'mhlo\.sharding\s*=\s*"([^"]*)"')
_HLO_TILE_RE = re.compile(r"devices=\[([0-9,]+)\]")
_HLO_SUBGROUP_RE = re.compile(r"last_tile_dims=\{([^}]*)\}")


def parse_hlo_sharding(sharding_str, rank):
    """Per-dim shard counts from an HLO sharding string over a
    `rank`-dim value, or None when unknown/unrepresentable.

    Handles the forms XLA emits in `mhlo.sharding` attrs:
    `{replicated}` and `{maximal device=k}` (one full copy per device
    -> all-ones), `{devices=[2,2]0,1,2,3}` (V1 explicit device list)
    and `{devices=[2,2]<=[4]}` (V2 iota, incl. transposed
    `<=[2,2]T(1,0)` reshapes — the device ASSIGNMENT is irrelevant to
    per-dim counts, only the tile shape matters), with trailing
    replication (`last_tile_dim_replicate`) or subgroup dims
    (`last_tile_dims={...}`) stripped off the tile shape. `{manual}`
    and sdy-dialect attrs return None (counted as unmapped by the
    propagation cross-check)."""
    if sharding_str is None or rank is None:
        return None
    body = sharding_str.strip()
    if body.startswith("{") and body.endswith("}"):
        body = body[1:-1].strip()
    if body.startswith("replicated") or body.startswith("maximal"):
        return (1,) * int(rank)
    m = _HLO_TILE_RE.match(body)
    if m is None:
        return None
    tile = [int(x) for x in m.group(1).split(",") if x]
    sub = _HLO_SUBGROUP_RE.search(body)
    if sub is not None:
        k = len([p for p in sub.group(1).split(",") if p.strip()])
        tile = tile[:len(tile) - k] if k else tile
    elif "last_tile_dim_replicate" in body:
        tile = tile[:-1]
    if len(tile) != int(rank):
        return None
    return tuple(tile)


def harvest_hlo_shardings(text):
    """The per-tensor sharding annotations XLA actually lowered into a
    StableHLO module: `{"args": {argno: raw_string}, "constraints":
    [raw_string_or_None, ...]}`.

    * entry args: `mhlo.sharding` attrs on the `@main` signature
      (paren-balanced, so tensor types and nested attrs don't confuse
      the split);
    * constraints: every `stablehlo.custom_call @Sharding` — the
      lowered form of a `sharding_constraint` eqn — in document order.
      The propagation cross-check matches them to depth-first jaxpr
      eqn order, which coincides for inlined bodies (scan/while lower
      into the same function); constraints inside out-of-line private
      funcs that XLA reordered are caught by the rank sanity check and
      counted unmapped rather than mismatched.

    Raw strings are returned unparsed (sdy attrs included) —
    `parse_hlo_sharding` decides representability."""
    args = {}
    m = re.search(r"@main\s*\(", text)
    if m is not None:
        i, depth, start = m.end(), 1, m.end()
        while i < len(text) and depth:
            c = text[i]
            if c == "(":
                depth += 1
            elif c == ")":
                depth -= 1
            i += 1
        sig = text[start:i - 1]
        arg_marks = list(re.finditer(r"%arg(\d+):", sig))
        for j, am in enumerate(arg_marks):
            seg_end = (arg_marks[j + 1].start()
                       if j + 1 < len(arg_marks) else len(sig))
            sm = _MHLO_SHARDING_RE.search(sig[am.end():seg_end])
            if sm is not None:
                args[int(am.group(1))] = sm.group(1)
    constraints = []
    for line in text.splitlines():
        if "custom_call" in line and "@Sharding" in line:
            sm = _MHLO_SHARDING_RE.search(line)
            constraints.append(sm.group(1) if sm is not None else None)
    return {"args": args, "constraints": constraints}


@dataclass
class HloOp:
    """One stablehlo op occurrence (nested region ops included, matching
    whole-text regex-count semantics)."""
    name: str                    # "dot_general", "all_reduce", ...
    line_no: int                 # 1-based line in the module text
    line: str                    # the op's first line, stripped
    operand_types: list = field(default_factory=list)
    result_types: list = field(default_factory=list)
    attrs: str = ""              # full text slice incl. closing sig line

    @property
    def is_weight_transpose(self):
        """A transpose applied directly to a parameter argument (OIHW->
        HWIO and friends): folds into XLA's free parameter-layout
        assignment, so layout lint must not count it as activation
        traffic. NOTE: textual heuristic only — a program that knows
        which %arg ids are model INPUTS (LoweredProgram.input_arg_ids)
        refines this via LoweredProgram.is_weight_transpose, since an
        input-image transpose is exactly the layout bug to catch."""
        return (self.name == "transpose"
                and _WEIGHT_TRANSPOSE_RE.search(self.line) is not None)

    def arg_operand_id(self):
        """The N of a direct `%argN` first operand, or None."""
        m = re.search(r"transpose %arg(\d+)\b", self.line)
        return int(m.group(1)) if m else None

    def operand_bytes(self):
        return sum(tensor_type_bytes(t) for t in self.operand_types)

    def replica_group_size(self):
        """(group_size, num_groups) from a replica_groups attr, or
        (None, None) when absent."""
        m = re.search(r"replica_groups\s*=\s*dense<(\[\[.*?\]\]|\[\]|"
                      r"[0-9]+)>\s*:\s*tensor<(\d+)x(\d+)", self.attrs,
                      re.S)
        if not m:
            return None, None
        return int(m.group(3)), int(m.group(2))

    def replica_groups(self):
        """The replica_groups device-id lists, e.g. [[0, 2], [1, 3]],
        or None when absent (lets the collective analyzer attribute a
        group to a mesh AXIS by id stride, not just by size — two axes
        of equal size are otherwise indistinguishable)."""
        m = re.search(r"replica_groups\s*=\s*dense<(\[\[.*?\]\])>",
                      self.attrs, re.S)
        if not m:
            return None
        try:
            import json
            return json.loads(m.group(1).replace(" ", "")
                              .replace("\n", ""))
        except ValueError:
            return None


def _split_signature(line):
    """Parse the trailing ` : (operands) -> results` / ` : type` section
    of a one-line op. Returns (operand_types, result_types)."""
    idx = line.rfind(" : ")
    if idx < 0:
        return [], []
    sig = line[idx + 3:]
    if "->" in sig:
        left, right = sig.split("->", 1)
        return _TENSOR_RE.findall(left), _TENSOR_RE.findall(right)
    tys = _TENSOR_RE.findall(sig)
    # shorthand form: operand and result share the type
    return list(tys), list(tys)


def parse_hlo_ops(text):
    """All stablehlo op occurrences in a module's textual form.
    `stablehlo.return` is skipped (region plumbing, not computation)."""
    lines = text.splitlines()
    ops = []
    for i, raw in enumerate(lines):
        m = _OP_RE.search(raw)
        if m is None:
            continue
        name = m.group(1)
        if name == "return":
            continue
        line = raw.strip()
        attrs = line
        if f'"stablehlo.{name}"' in raw:
            # generic (quoted) form: a region op whose type signature is
            # on the closing `}) : ...` line — stitch by brace balance
            depth = raw.count("{") - raw.count("}")
            j = i
            while depth > 0 and j + 1 < len(lines):
                j += 1
                depth += lines[j].count("{") - lines[j].count("}")
            attrs = "\n".join(lines[i:j + 1])
            sig_line = lines[j] if j > i else raw
            operand_types, result_types = _split_signature(sig_line)
        else:
            operand_types, result_types = _split_signature(line)
        ops.append(HloOp(name=name, line_no=i + 1, line=line,
                         operand_types=operand_types,
                         result_types=result_types, attrs=attrs))
    return ops


def tree_arg_infos(tree, role, prefix="", donated=False, shardings=None):
    """Flatten one pytree argument into ArgInfo entries (jaxpr invar
    order). `shardings` is an optional parallel pytree of shardings; a
    leaf's shard count comes from it (or from the value's own committed
    .sharding when absent)."""
    import jax
    import numpy as np
    leaves_p = jax.tree_util.tree_flatten_with_path(tree)[0]
    sh_leaves = (jax.tree_util.tree_leaves(
        shardings, is_leaf=lambda x: x is None)
        if shardings is not None else [None] * len(leaves_p))
    infos = []
    for (path, leaf), sh in zip(leaves_p, sh_leaves):
        name = jax.tree_util.keystr(path).strip("[]'\"").replace(
            "']['", "/").replace("][", "/") or role
        if prefix:
            name = f"{prefix}/{name}" if name != role else prefix
        if sh is None:
            sh = getattr(leaf, "sharding", None)
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = getattr(leaf, "dtype", None)
        itemsize = getattr(dtype, "itemsize", np.dtype(type(leaf)).itemsize
                           if np.isscalar(leaf) else 0)
        spec = getattr(sh, "spec", None)
        infos.append(ArgInfo(
            name=name, role=role, shape=shape,
            dtype=str(dtype) if dtype is not None else "",
            bytes=int(np.prod(shape, dtype=np.int64)) * int(itemsize or 0),
            spec=tuple(spec) if spec is not None else None,
            shard_count=sharding_shard_count(sh),
            dim_shards=sharding_dim_counts(sh, len(shape)),
            donated=donated))
    return infos


class LoweredProgram:
    """StableHLO text + jaxpr of one lowered callable, with a parsed op
    view. `jaxpr` is produced from the same single trace as the HLO (no
    double tracing). `arg_infos`, when given, aligns one ArgInfo with
    each flattened jaxpr invar (sharding + donation capture)."""

    def __init__(self, text, jaxpr=None, name="program", platform="cpu",
                 input_arg_ids=None, arg_infos=None):
        self.text = text
        self.jaxpr = jaxpr
        self.name = name
        self.platform = platform
        # %arg indices of the main function that are model INPUTS (vs
        # parameters/buffers); None when unknown (raw-text programs)
        self.input_arg_ids = (None if input_arg_ids is None
                              else frozenset(input_arg_ids))
        self.arg_infos = arg_infos
        self.ops = parse_hlo_ops(text)

    def is_weight_transpose(self, op):
        """Argument transposes are free parameter-layout moves ONLY for
        parameter args — a transpose of an INPUT arg is real activation
        traffic (the NHWC-defeating bug itself)."""
        if not op.is_weight_transpose:
            return False
        if self.input_arg_ids is None:
            return True
        return op.arg_operand_id() not in self.input_arg_ids

    def ops_named(self, *names):
        wanted = set(names)
        return [op for op in self.ops if op.name in wanted]

    def count(self, op_name):
        return sum(1 for op in self.ops if op.name == op_name)

    @property
    def op_histogram(self):
        return Counter(op.name for op in self.ops)

    def activation_transposes(self):
        return [op for op in self.ops
                if op.name == "transpose"
                and not self.is_weight_transpose(op)]

    def __repr__(self):
        return (f"LoweredProgram({self.name!r}, {len(self.ops)} ops, "
                f"{len(self.text.splitlines())} lines)")


def _untensor(tree):
    from ..framework.core import Tensor
    import jax
    return jax.tree_util.tree_map(
        lambda t: t._value if isinstance(t, Tensor) else t, tree,
        is_leaf=lambda t: isinstance(t, Tensor))


def lower_callable(fn, *example_args, name="program", input_arg_ids=None,
                   arg_infos=None, in_shardings=None):
    """Trace `fn` once; return StableHLO + jaxpr as a LoweredProgram.
    `in_shardings` (a per-arg tuple of sharding pytrees, None entries =
    unspecified) threads into `jax.jit` so the lowered text carries real
    `mhlo.sharding` annotations, and seeds the auto-built ArgInfos'
    dim_shards — the propagation pass's cross-check needs both sides."""
    import jax
    jitted = (jax.jit(fn, in_shardings=in_shardings)
              if in_shardings is not None else jax.jit(fn))
    traced = jitted.trace(*example_args)
    if arg_infos is None:
        arg_infos = []
        shardings = (in_shardings if in_shardings is not None
                     else [None] * len(example_args))
        for i, (a, sh) in enumerate(zip(example_args, shardings)):
            arg_infos.extend(tree_arg_infos(a, "input", prefix=f"arg{i}",
                                            shardings=sh))
    return LoweredProgram(traced.lower().as_text(), jaxpr=traced.jaxpr,
                          name=name, input_arg_ids=input_arg_ids,
                          arg_infos=arg_infos)


def lower_layer(model, *example_arrays, name=None):
    """Lower a Layer's forward (functional form: params/buffers as
    arguments) at the given example inputs — the same pure-call shape
    the Trainer and jit.save use, so lint sees the graph that ships."""
    from ..framework.core import Tensor
    from ..nn.layer_base import (buffer_pytree, functional_call,
                                 state_pytree)
    params = state_pytree(model)
    params.update(buffer_pytree(model))

    def pure(p, *args):
        with functional_call(model, p):
            out = model(*[Tensor(a) for a in args])
        return _untensor(out)

    # flattened calling convention: params-dict leaves first, then the
    # example arrays — so the inputs are the TRAILING %arg ids, letting
    # the layout analyzer tell a free param-layout transpose from an
    # input-activation transpose
    import jax
    n_params = len(jax.tree_util.tree_leaves(params))
    n_inputs = len(jax.tree_util.tree_leaves(list(example_arrays)))
    infos = tree_arg_infos(params, "param")
    for i, a in enumerate(example_arrays):
        infos.extend(tree_arg_infos(a, "input", prefix=f"input{i}"))
    return lower_callable(
        pure, params, *example_arrays,
        name=name or type(model).__name__,
        input_arg_ids=range(n_params, n_params + n_inputs),
        arg_infos=infos)
