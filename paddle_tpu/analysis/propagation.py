"""Sharding propagation v2 — a fixed-point GSPMD-style dataflow pass.

The memory pass, remat advisor, autotuner and the SHARD-* lints all
price tensors per device, which requires knowing each tensor's shard
count. v1 was a single forward sweep seeded from ARG specs only
(`memory._eqn_out_shard` applied eqn by eqn): intermediates whose
sharding is pinned mid-program (`with_sharding_constraint`) or implied
only by a CONSUMER (a dot whose other operand is sharded, a transpose
feeding an annotated output) fell back to the max-operand guess.

v2 runs the same per-primitive transfer rules to a FIXED POINT, in both
directions, over the whole jaxpr including scan/while/pjit/cond bodies
(the recursion mirrors `analysis/schedule.py`):

* **Seeding.** Three sources, in decreasing authority: per-dim counts
  from `ArgInfo.dim_shards` (what the caller committed to);
  `sharding_constraint` equations, whose `sharding` param IS the spec
  GSPMD will honor (their outputs are pinned — and the lowered
  StableHLO's `mhlo.sharding` annotations cross-check both, see
  `lowering.harvest_hlo_shardings`); and optional `out_dims`
  (out_shardings). An arg known to be UNSHARDED (shard_count == 1 with
  no dim vector) seeds as exactly replicated — `(1,) * rank` is a real
  spec, not an unknown — which is what makes the committed
  single-device configs fully exact under this pass.
* **Fixed point.** A monotone lattice per var: unknown -> one concrete
  per-dim count vector, first write wins, no downgrades. Forward
  transfer is `memory._eqn_out_shard` (the rule list stays in ONE
  place); backward transfer inverts the structural rules (transpose
  permutation, reshape factor groups, dot_general batch/free dims,
  same-shape elementwise) so a downstream pin reaches upstream
  producers. Each sweep only fills unknowns, so the pass converges in
  at most O(longest def-use chain) sweeps and is hard-capped at
  `max_iters`.
* **No backward transfer through `sharding_constraint`.** The
  constraint REPLACES the spec; propagating it onto its input would
  erase exactly the disagreement SHARD-PROP-DIVERGENCE exists to
  report (the implicit reshard GSPMD inserts to honor the pin).
* **Fallback.** Vars still unknown after the fixed point price through
  the v1 heuristic unchanged (max-operand count, conservative caps):
  under-counting shards OVERestimates per-device bytes, the safe
  direction for every gate that consumes this pass.

The result also carries the two lint feeds: `divergences` (propagated
spec vs constraint/lowered annotation — SHARD-PROP-DIVERGENCE) and
`loop_reshards` (scan/while body whose carry output spec mismatches its
carry input — a per-iteration reshard inside the hot loop,
SHARD-LOOP-CARRY-RESHARD). Cross-checking the static pass against the
stage below it is the TPU-MLIR verification discipline (arxiv
2210.15016); per-op true shardings as the basis for overlap pricing is
the T3 prerequisite (arxiv 2401.16677).

NOTE: this module must not import `.memory` at module scope — analyzer
registration order (propagation before memory, so MemoryAnalyzer can
consume the stashed result) is set by import order in
`default_catalog`/`__init__`, and a top-level import here would flip
it. All memory helpers are imported lazily inside functions.
"""
from dataclasses import dataclass, field

from .pass_manager import Analyzer, register_analyzer

__all__ = ["PropagationResult", "propagate_shardings",
           "PropagationAnalyzer"]

_MAX_ITERS = 64


def _prod(dims):
    total = 1
    for d in dims:
        total *= int(d)
    return max(total, 1)


def _rank(v):
    return len(getattr(v.aval, "shape", ()) or ())


def _unclosed(jx):
    return jx.jaxpr if hasattr(jx, "jaxpr") else jx


@dataclass
class PropagationResult:
    """Outcome of one fixed-point propagation over a jaxpr."""
    dims: dict = field(default_factory=dict)    # var -> per-dim counts
    counts: dict = field(default_factory=dict)  # var -> total shard count
    # var -> per-dim mesh-axis NAMES (tuple of tuples of strings):
    # seeded from entry args with a known PartitionSpec and from
    # sharding_constraint outputs, then propagated forward through the
    # structural eqn-rule slice (`_propagate_axes`: elementwise
    # inherit, transpose permute, dot_general batch+free with
    # contracted-drop) so derived vars keep their identity too
    axes: dict = field(default_factory=dict)
    divergences: list = field(default_factory=list)
    loop_reshards: list = field(default_factory=list)
    n_vars: int = 0              # all vars (args, consts, eqn outputs)
    n_exact: int = 0             # vars with a concrete per-dim spec
    n_constraints: int = 0       # sharding_constraint eqns seen
    n_annotated: int = 0         # lowered-HLO annotations cross-checked
    n_agree: int = 0             # annotations matching the static spec
    n_diverge: int = 0           # annotations contradicting it
    n_unmapped: int = 0          # annotations we could not parse/map
    iterations: int = 0
    converged: bool = True
    jaxpr_id: int = 0            # id() of the analyzed jaxpr (reuse guard)

    @property
    def n_fallback(self):
        return self.n_vars - self.n_exact

    @property
    def agreement_rate(self):
        """Exact-match rate over lowered annotations; 1.0 by convention
        when the module carries none (single-device programs)."""
        if not self.n_annotated:
            return 1.0
        return self.n_agree / self.n_annotated

    def summary(self):
        return {
            "n_vars": self.n_vars,
            "n_exact": self.n_exact,
            "n_fallback": self.n_fallback,
            "n_constraints": self.n_constraints,
            "n_annotated": self.n_annotated,
            "n_agree": self.n_agree,
            "n_diverge": self.n_diverge,
            "n_unmapped": self.n_unmapped,
            "agreement_rate": round(self.agreement_rate, 4),
            "n_axis_identified": len(self.axes),
            "n_divergences": len(self.divergences),
            "n_loop_carry_reshards": len(self.loop_reshards),
            "iterations": self.iterations,
            "converged": self.converged,
        }


def _constraint_dims(eqn):
    """The per-dim counts a sharding_constraint eqn pins, or None when
    the sharding object carries no NamedSharding mesh/spec."""
    from .lowering import sharding_dim_counts
    sharding = eqn.params.get("sharding")
    return sharding_dim_counts(sharding, _rank(eqn.outvars[0]))


def _set(dims, v, spec):
    """Monotone write: fill an unknown var with a concrete spec (rank
    checked); never overwrite. Returns True when something changed."""
    from .memory import _is_var
    if spec is None or not _is_var(v) or v in dims:
        return False
    if len(spec) != _rank(v):
        return False
    dims[v] = tuple(int(d) for d in spec)
    return True


def _link(dims, a, b, both=False):
    """Copy a known spec across an equal-value boundary (call operand ->
    body invar, body outvar -> call result). `both` also lifts the
    inner spec back out — safe only where the two vars really alias the
    same value (1:1 inlined calls, loop consts), NOT for loop carries
    (the body sees the steady-state spec, the outer init may differ —
    that difference is the SHARD-LOOP-CARRY-RESHARD signal)."""
    from .memory import _is_var
    changed = False
    da = dims.get(a) if _is_var(a) else None
    if da is not None:
        changed |= _set(dims, b, da)
    if both:
        db = dims.get(b) if _is_var(b) else None
        if db is not None:
            changed |= _set(dims, a, db)
    return changed


def _sweep(jx, dims):
    """One forward + one backward pass over a jaxpr (recursing into sub
    jaxprs). The caller iterates to the global fixed point."""
    changed = _forward_sweep(jx, dims)
    changed |= _backward_sweep(jx, dims)
    return changed


def _forward_sweep(jx, dims):
    from .memory import _eqn_out_shard, _is_var, _sub_jaxprs
    changed = False
    for eqn in jx.eqns:
        name = eqn.primitive.name
        if name == "sharding_constraint":
            changed |= _set(dims, eqn.outvars[0], _constraint_dims(eqn))
            continue
        if _sub_jaxprs(eqn):
            changed |= _propagate_sub(eqn, dims)
            continue
        ivs = [v for v in eqn.invars if _is_var(v)]
        in_dims = [dims.get(v) for v in ivs]
        in_counts = [_prod(d) if d is not None else 1 for d in in_dims]
        out_count, out_dims = _eqn_out_shard(eqn, in_counts, in_dims)
        if out_dims is not None:
            for v in eqn.outvars:
                changed |= _set(dims, v, out_dims)
    return changed


def _backward_sweep(jx, dims):
    """Invert the structural transfer rules: a known OUTPUT spec fills
    unknown inputs. Constraint eqns are never walked through (see module
    docstring); sub-jaxpr eqns were handled by the forward recursion."""
    from .memory import (_is_var, _reshape_dim_shards, _sub_jaxprs)
    changed = False
    for eqn in reversed(jx.eqns):
        name = eqn.primitive.name
        if name == "sharding_constraint" or _sub_jaxprs(eqn):
            continue
        if len(eqn.outvars) != 1:
            continue
        ov = eqn.outvars[0]
        od = dims.get(ov)
        if od is None:
            continue
        out_shape = tuple(getattr(ov.aval, "shape", ()))
        if name == "transpose":
            perm = eqn.params.get("permutation")
            iv = eqn.invars[0]
            if perm is not None and len(perm) == len(od):
                ind = [1] * len(od)
                for i, p in enumerate(perm):
                    ind[int(p)] = int(od[i])
                changed |= _set(dims, iv, ind)
            continue
        if name == "reshape":
            iv = eqn.invars[0]
            if _is_var(iv):
                in_shape = tuple(getattr(iv.aval, "shape", ()))
                try:
                    d = _reshape_dim_shards(out_shape, od, in_shape)
                except Exception:
                    d = None
                changed |= _set(dims, iv, d)
            continue
        if name == "dot_general":
            changed |= _backward_dot(eqn, od, dims)
            continue
        # elementwise default: the output spec holds for every
        # same-shaped operand (GSPMD propagates through elementwise ops
        # unchanged in both directions)
        for iv in eqn.invars:
            if _is_var(iv) and \
                    tuple(getattr(iv.aval, "shape", ())) == out_shape:
                changed |= _set(dims, iv, od)
    return changed


def _backward_dot(eqn, od, dims):
    """dot_general output layout is (batch, lhs free, rhs free): map
    those factors back onto operand dims; contracted dims seed as
    UNSHARDED (1) — conservative: if they were in fact sharded we
    under-count shards, which overestimates per-device bytes."""
    from .memory import _is_var
    changed = False
    try:
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    except Exception:
        return False
    ivs = [v for v in eqn.invars if _is_var(v)]
    if len(ivs) != 2:
        return False
    lhs, rhs = ivs
    lrank, rrank = _rank(lhs), _rank(rhs)
    lfree = [i for i in range(lrank) if i not in set(lc) | set(lb)]
    rfree = [i for i in range(rrank) if i not in set(rc) | set(rb)]
    nb = len(lb)
    if len(od) != nb + len(lfree) + len(rfree):
        return False
    ld = [1] * lrank
    for i, p in enumerate(lb):
        ld[int(p)] = int(od[i])
    for i, p in enumerate(lfree):
        ld[int(p)] = int(od[nb + i])
    changed |= _set(dims, lhs, ld)
    rd = [1] * rrank
    for i, p in enumerate(rb):
        rd[int(p)] = int(od[i])
    for i, p in enumerate(rfree):
        rd[int(p)] = int(od[nb + len(lfree) + i])
    changed |= _set(dims, rhs, rd)
    return changed


def _propagate_sub(eqn, dims):
    """Map specs across a call boundary and sweep the body once. scan /
    while get their loop-aware operand split; everything else (pjit,
    remat, custom_vjp/jvp, cond) maps 1:1 where arity matches."""
    from .memory import _is_var, _sub_jaxprs
    name = eqn.primitive.name
    changed = False
    if name == "scan":
        body = _unclosed(eqn.params["jaxpr"])
        nc = int(eqn.params.get("num_consts", 0))
        ncar = int(eqn.params.get("num_carry", 0))
        ivs = list(eqn.invars)
        for i in range(min(nc, len(ivs))):
            changed |= _link(dims, ivs[i], body.invars[i], both=True)
        for i in range(nc, min(nc + ncar, len(ivs))):
            changed |= _link(dims, ivs[i], body.invars[i])
        # xs operands carry a leading scan dim the body never sees; the
        # split is only clean when that dim is unsharded
        for i in range(nc + ncar, len(ivs)):
            if not _is_var(ivs[i]):
                continue
            od = dims.get(ivs[i])
            if od is not None and len(od) >= 1 and int(od[0]) == 1:
                changed |= _set(dims, body.invars[i], od[1:])
        changed |= _sweep(body, dims)
        outs = list(eqn.outvars)
        for i in range(min(ncar, len(outs))):
            changed |= _link(dims, body.outvars[i], outs[i])
        for i in range(ncar, len(outs)):
            bd = dims.get(body.outvars[i]) \
                if i < len(body.outvars) else None
            if bd is not None:
                changed |= _set(dims, outs[i], (1,) + tuple(bd))
            od = dims.get(outs[i]) if _is_var(outs[i]) else None
            if od is not None and len(od) >= 1 and int(od[0]) == 1 and \
                    i < len(body.outvars):
                changed |= _set(dims, body.outvars[i], od[1:])
        return changed
    if name == "while":
        cn = int(eqn.params.get("cond_nconsts", 0))
        bn = int(eqn.params.get("body_nconsts", 0))
        cond = _unclosed(eqn.params["cond_jaxpr"])
        body = _unclosed(eqn.params["body_jaxpr"])
        ivs = list(eqn.invars)
        for i in range(min(cn, len(cond.invars))):
            changed |= _link(dims, ivs[i], cond.invars[i], both=True)
        for i in range(min(bn, len(body.invars))):
            changed |= _link(dims, ivs[cn + i], body.invars[i],
                             both=True)
        ncar = len(ivs) - cn - bn
        for i in range(ncar):
            ov = ivs[cn + bn + i]
            if bn + i < len(body.invars):
                changed |= _link(dims, ov, body.invars[bn + i])
            if cn + i < len(cond.invars):
                changed |= _link(dims, ov, cond.invars[cn + i])
        changed |= _sweep(cond, dims)
        changed |= _sweep(body, dims)
        for i in range(min(ncar, len(eqn.outvars), len(body.outvars))):
            changed |= _link(dims, body.outvars[i], eqn.outvars[i])
        return changed
    if name == "cond":
        branches = [_unclosed(b) for b in eqn.params.get("branches", ())]
        ivs = list(eqn.invars)[1:]          # drop the predicate
        for br in branches:
            for ov, bv in zip(ivs, br.invars):
                changed |= _link(dims, ov, bv)
            changed |= _sweep(br, dims)
        # an output spec is only known when every branch agrees
        for i, ov in enumerate(eqn.outvars):
            specs = [dims.get(br.outvars[i]) for br in branches
                     if i < len(br.outvars)]
            if specs and all(s is not None for s in specs) and \
                    len({tuple(s) for s in specs}) == 1:
                changed |= _set(dims, ov, specs[0])
        return changed
    # generic 1:1 call (pjit, remat, custom_jvp/vjp, checkpoint): map
    # any sub-jaxpr whose arity matches the eqn exactly
    for sub in _sub_jaxprs(eqn):
        if len(sub.invars) == len(eqn.invars) and \
                len(sub.outvars) == len(eqn.outvars):
            for ov, bv in zip(eqn.invars, sub.invars):
                changed |= _link(dims, ov, bv, both=True)
            changed |= _sweep(sub, dims)
            for bv, ov in zip(sub.outvars, eqn.outvars):
                changed |= _link(dims, bv, ov, both=True)
        else:
            changed |= _sweep(sub, dims)
    return changed


def _report(jx, dims, res):
    """Post-fixpoint walk: coverage counters, constraint divergences,
    loop-carry reshards. Recursive over sub-jaxprs."""
    from .memory import _eqn_source, _is_var, _sub_jaxprs
    for v in list(jx.invars) + list(jx.constvars):
        res.n_vars += 1
        if v in dims:
            res.n_exact += 1
    for idx, eqn in enumerate(jx.eqns):
        for v in eqn.outvars:
            res.n_vars += 1
            if v in dims:
                res.n_exact += 1
        name = eqn.primitive.name
        if name == "sharding_constraint":
            res.n_constraints += 1
            want = _constraint_dims(eqn)
            ivs = [v for v in eqn.invars if _is_var(v)]
            got = dims.get(ivs[0]) if ivs else None
            if want is not None and got is not None and \
                    tuple(got) != tuple(want):
                res.divergences.append({
                    "source": _eqn_source(eqn, idx),
                    "annotated": [int(d) for d in want],
                    "propagated": [int(d) for d in got]})
        elif name == "scan":
            body = _unclosed(eqn.params["jaxpr"])
            nc = int(eqn.params.get("num_consts", 0))
            ncar = int(eqn.params.get("num_carry", 0))
            for i in range(ncar):
                if nc + i >= len(body.invars) or i >= len(body.outvars):
                    continue
                din = dims.get(body.invars[nc + i])
                dout = dims.get(body.outvars[i])
                if din is not None and dout is not None and \
                        tuple(din) != tuple(dout):
                    res.loop_reshards.append({
                        "source": _eqn_source(eqn, idx), "carry": i,
                        "in": [int(d) for d in din],
                        "out": [int(d) for d in dout]})
        elif name == "while":
            body = _unclosed(eqn.params["body_jaxpr"])
            bn = int(eqn.params.get("body_nconsts", 0))
            for i in range(len(body.outvars)):
                if bn + i >= len(body.invars):
                    continue
                din = dims.get(body.invars[bn + i])
                dout = dims.get(body.outvars[i])
                if din is not None and dout is not None and \
                        tuple(din) != tuple(dout):
                    res.loop_reshards.append({
                        "source": _eqn_source(eqn, idx), "carry": i,
                        "in": [int(d) for d in din],
                        "out": [int(d) for d in dout]})
        for sub in _sub_jaxprs(eqn):
            _report(sub, dims, res)


def _axes_distinct(axes, v):
    """True when `v` carries a per-dim axis-identity spec whose named
    axes are all DISTINCT — the dim-count product is then exact (no two
    dims can be splitting the same mesh axis), so the no-identity caps
    below do not apply."""
    a = axes.get(v) if axes else None
    if a is None:
        return False
    named = [n for dim in a for n in dim]
    return len(named) == len(set(named))


def _axis_sizes(axes, dims):
    """{mesh axis name: size}, recovered from vars carrying BOTH an
    axis identity and a per-dim count spec: a dim split over exactly
    one named axis splits that many ways, so the count IS the axis
    size (PartitionSpec semantics — "dp" means the whole dp axis).
    First observation wins; multi-name dims are skipped (their count
    is a product this inversion cannot decompose)."""
    sizes = {}
    for v, a in axes.items():
        d = dims.get(v)
        if d is None or len(d) != len(a):
            continue
        for names, cnt in zip(a, d):
            if len(names) == 1:
                sizes.setdefault(names[0], int(cnt))
    return sizes


def _axes_product(axes, v, sizes):
    """The shard count an axis identity PROVES: the product of the
    named axes' sizes, for a var whose axes are distinct and all
    sized. None when the identity is missing, conflicted, or names an
    axis no seed sized — callers fall back to the caps."""
    a = axes.get(v) if axes else None
    if a is None or not _axes_distinct(axes, v):
        return None
    total = 1
    for dim in a:
        for n in dim:
            if n not in sizes:
                return None
            total *= int(sizes[n])
    return max(total, 1)


def _final_counts(jx, dims, arg_counts, axes=None):
    """{var: total shard count} over the TOP-LEVEL jaxpr: the product of
    the fixed-point per-dim spec where known, the v1 forward heuristic
    (`_eqn_out_shard` with conservative caps) where not — byte-for-byte
    the old `propagate_shard_counts` on a program with no mid-graph
    pins.

    `axes` (PropagationResult.axes) lifts the caps where it can: a var
    whose per-dim AXIS NAMES are known and distinct takes its dim-spec
    product verbatim — the identity proves the product is the real
    shard count, not an over-claim. DERIVED axis-identified vars (the
    `_propagate_axes` eqn-rule slice) often have NO dim spec at all —
    the dims sweep capped e.g. a dp x tp dot at its most-sharded
    operand and recorded nothing — so their count comes from the
    identity directly: the product of the named axes' sizes
    (`_axes_product` over `_axis_sizes` recovered from the seeds)."""
    from .memory import _eqn_out_shard, _is_var
    sizes = _axis_sizes(axes, dims) if axes else {}
    counts = {}
    for k, v in enumerate(jx.invars):
        d = dims.get(v)
        cnt = _prod(d) if d is not None else None
        if arg_counts and k < len(arg_counts) and \
                not _axes_distinct(axes, v):
            # per-dim counts carry no mesh-axis identity, so a dim-spec
            # product can over-claim vs the arg's actual shard count —
            # keep the v1 cap (min = fewer shards = per-device bytes
            # OVERestimated, the safe direction). Axis-identified vars
            # skip it: their product is exact by construction.
            cnt = arg_counts[k] if cnt is None else min(cnt, arg_counts[k])
        counts[v] = cnt if cnt is not None else 1
    for eqn in jx.eqns:
        ivs = [v for v in eqn.invars if _is_var(v)]
        in_counts = [counts.get(v, 1) for v in ivs]
        out, _ = _eqn_out_shard(eqn, in_counts, [dims.get(v) for v in ivs])
        # the same no-axis-identity cap v1 applied: an output never
        # claims finer sharding than its most-sharded operand — except
        # a constraint-pinned output whose distinct axis names prove
        # the finer sharding is real (a deliberate mid-graph reshard)
        cap = max(in_counts, default=1)
        for v in eqn.outvars:
            d = dims.get(v)
            ap = _axes_product(axes, v, sizes)
            if d is None:
                counts[v] = ap if ap is not None else out
            elif _axes_distinct(axes, v):
                counts[v] = ap if ap is not None else _prod(d)
            else:
                counts[v] = min(_prod(d), cap)
    return counts


def _seed_axes(jx, arg_infos):
    """{var: per-dim axis names} — the mesh-axis IDENTITY first slice.
    Only vars whose identity is stated outright are recorded: entry
    args carrying a PartitionSpec (ArgInfo.spec) and every
    sharding_constraint output (NamedSharding in params), recursively.
    Counts say how many ways a dim splits; axes say over WHICH mesh
    axis — the fact `_final_counts` needs to trust a dim product
    outright instead of capping it (two dims splitting "dp" and "tp"
    compose to dp x tp shards; two dims that might both be "dp" do
    not)."""
    from .lowering import sharding_dim_axes, spec_dim_axes
    from .memory import _sub_jaxprs
    axes = {}
    for k, v in enumerate(jx.invars):
        info = arg_infos[k] if arg_infos and k < len(arg_infos) else None
        a = spec_dim_axes(getattr(info, "spec", None), _rank(v))
        if a is not None:
            axes[v] = a

    def _collect(sub):
        for eqn in sub.eqns:
            if eqn.primitive.name == "sharding_constraint":
                a = sharding_dim_axes(eqn.params.get("sharding"),
                                      _rank(eqn.outvars[0]))
                if a is not None:
                    axes[eqn.outvars[0]] = a
            for s in _sub_jaxprs(eqn):
                _collect(s)

    _collect(jx)
    return axes


# shape-preserving prims whose output is computed position-by-position
# from same-shape operands: the output splits exactly the way every
# operand splits, so mesh-axis identity carries through verbatim
_ELEMENTWISE_PRIMS = frozenset({
    "add", "sub", "mul", "div", "rem", "pow", "max", "min", "atan2",
    "nextafter", "and", "or", "xor", "not", "neg", "sign", "abs",
    "exp", "exp2", "expm1", "log", "log1p", "tanh", "logistic", "erf",
    "erfc", "erf_inv", "sqrt", "rsqrt", "cbrt", "square", "sin", "cos",
    "tan", "asin", "acos", "atan", "sinh", "cosh", "floor", "ceil",
    "round", "is_finite", "integer_pow", "convert_element_type",
    "bitcast_convert_type", "real", "imag", "conj", "clamp",
    "shift_left", "shift_right_logical", "shift_right_arithmetic",
    "eq", "ne", "lt", "le", "gt", "ge", "select_n", "copy",
    "stop_gradient", "reduce_precision"})


def _axes_of(axes, v):
    """Axis spec of one eqn operand. Literals and consts are
    REPLICATED — a concrete all-empty spec, not an unknown — so a
    `x * 2.0` chain doesn't break the identity at every literal."""
    from .memory import _is_var
    if not _is_var(v):
        return ((),) * _rank(v)
    return axes.get(v)


def _set_axes(axes, v, spec):
    """Monotone write, mirroring `_set`: first identity wins, rank
    checked, never overwrite."""
    from .memory import _is_var
    if spec is None or not _is_var(v) or v in axes:
        return False
    if len(spec) != _rank(v):
        return False
    axes[v] = tuple(tuple(a) for a in spec)
    return True


def _propagate_axes(jx, axes, max_iters=_MAX_ITERS):
    """Mesh-axis IDENTITY propagation, eqn-rule slice: forward-only,
    monotone, run to a fixed point over the top-level jaxpr after
    `_seed_axes`. Three structural rules — the ones whose output
    identity is forced by the input identity with no mesh knowledge:

    * same-shape elementwise: the output inherits its operands' axes
      when every same-shape operand's identity is KNOWN and they all
      AGREE (conflict or an unknown operand -> skip: the unknown side
      might be sharded over a different axis, and guessing here would
      let `_final_counts` lift a cap it must not);
    * `transpose`: the per-dim names permute with the dims;
    * `dot_general`: batch and free dims thread through in output
      order (batch, lhs free, rhs free); CONTRACTED dims drop — the
      partial products are all-reduced over those axes, so the result
      carries no split (and hence no identity) there.

    Everything else (reshape factor groups, gather/scatter, reductions,
    sub-jaxpr bodies) stays out of this slice: their outputs simply
    keep no identity and `_final_counts` falls back to the
    conservative caps, the safe direction."""
    from .memory import _sub_jaxprs
    for _ in range(max_iters):
        changed = False
        for eqn in jx.eqns:
            name = eqn.primitive.name
            if name == "sharding_constraint" or _sub_jaxprs(eqn) or \
                    len(eqn.outvars) != 1:
                continue
            ov = eqn.outvars[0]
            if ov in axes:
                continue
            if name == "transpose":
                ia = _axes_of(axes, eqn.invars[0])
                perm = eqn.params.get("permutation")
                if ia is not None and perm is not None and \
                        len(perm) == len(ia):
                    changed |= _set_axes(
                        axes, ov, tuple(ia[int(p)] for p in perm))
                continue
            if name == "dot_general":
                la = _axes_of(axes, eqn.invars[0])
                ra = _axes_of(axes, eqn.invars[1])
                if la is None or ra is None:
                    continue
                (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
                batch = [la[int(i)] for i in lb]
                lfree = [la[i] for i in range(len(la))
                         if i not in set(lc) | set(lb)]
                rfree = [ra[i] for i in range(len(ra))
                         if i not in set(rc) | set(rb)]
                changed |= _set_axes(axes, ov,
                                     tuple(batch + lfree + rfree))
                continue
            if name not in _ELEMENTWISE_PRIMS:
                continue
            out_shape = tuple(getattr(ov.aval, "shape", ()))
            specs, known = [], True
            for v in eqn.invars:
                shp = tuple(getattr(getattr(v, "aval", None),
                                    "shape", ()) or ())
                if shp != out_shape or shp == ():
                    continue      # scalars don't constrain the split
                a = _axes_of(axes, v)
                if a is None:
                    known = False
                    break
                specs.append(a)
            if known and specs and all(s == specs[0]
                                       for s in specs[1:]):
                changed |= _set_axes(axes, ov, specs[0])
        if not changed:
            return


def _cross_check_hlo(text, jx, dims, res):
    """Cross-check the static fixed point against what XLA actually
    lowered: `mhlo.sharding` annotations on the module's entry args and
    on `@Sharding` custom_calls (the lowered form of every
    `sharding_constraint` eqn, matched in depth-first eqn order)."""
    from .lowering import harvest_hlo_shardings, parse_hlo_sharding
    from .memory import _is_var, _sub_jaxprs
    harvested = harvest_hlo_shardings(text)
    for n, raw in sorted(harvested["args"].items()):
        if n >= len(jx.invars):
            res.n_unmapped += 1
            continue
        v = jx.invars[n]
        want = parse_hlo_sharding(raw, _rank(v))
        if want is None:
            res.n_unmapped += 1
            continue
        res.n_annotated += 1
        got = dims.get(v)
        if got is None:
            # fallback var: conservative direction, neither agreement
            # nor divergence — it drags the rate down, as it should
            continue
        if tuple(got) == tuple(want):
            res.n_agree += 1
        else:
            res.n_diverge += 1
            res.divergences.append({
                "source": f"%arg{n}",
                "annotated": [int(d) for d in want],
                "propagated": [int(d) for d in got]})

    ceqns = []

    def _collect(sub_jx):
        from .memory import _eqn_source
        for idx, eqn in enumerate(sub_jx.eqns):
            if eqn.primitive.name == "sharding_constraint":
                ceqns.append((eqn, _eqn_source(eqn, idx)))
            for sub in _sub_jaxprs(eqn):
                _collect(sub)

    _collect(jx)
    anns = harvested["constraints"]
    res.n_unmapped += abs(len(anns) - len(ceqns))
    for raw, (eqn, src) in zip(anns, ceqns):
        want = parse_hlo_sharding(raw, _rank(eqn.outvars[0]))
        have = _constraint_dims(eqn)
        if want is None or have is None:
            res.n_unmapped += 1
            continue
        res.n_annotated += 1
        if tuple(want) == tuple(have):
            res.n_agree += 1
        else:
            res.n_diverge += 1
            res.divergences.append({
                "source": src,
                "annotated": [int(d) for d in want],
                "propagated": [int(d) for d in have]})


def propagate_shardings(program_or_jaxpr, arg_infos=None, arg_counts=None,
                        arg_dims=None, out_dims=None,
                        max_iters=_MAX_ITERS):
    """Run the fixed-point propagation over a LoweredProgram or (closed)
    jaxpr. Returns a PropagationResult.

    Seeds: `arg_dims` (or `arg_infos[k].dim_shards`) per invar, with
    shard_count==1 args pinned to exactly-replicated; every
    `sharding_constraint` eqn's output; optional `out_dims` per program
    outvar (out_shardings). When the program carries StableHLO text the
    lowered `mhlo.sharding` annotations are cross-checked into the
    agreement counters and divergence list."""
    program = program_or_jaxpr
    jx = getattr(program, "jaxpr", None)
    if jx is None:
        jx = program
        program = None
    if arg_infos is None and program is not None:
        arg_infos = getattr(program, "arg_infos", None)
    jx = _unclosed(jx)
    if arg_counts is None and arg_infos:
        arg_counts = [i.shard_count for i in arg_infos]
    if arg_dims is None and arg_infos:
        arg_dims = [getattr(i, "dim_shards", None) for i in arg_infos]

    dims = {}
    for k, v in enumerate(jx.invars):
        d = arg_dims[k] if arg_dims and k < len(arg_dims) else None
        cnt = arg_counts[k] if arg_counts and k < len(arg_counts) else 1
        if d is not None:
            _set(dims, v, d)
        elif cnt <= 1:
            # unsharded is a concrete spec, not an unknown
            _set(dims, v, (1,) * _rank(v))
    for v in jx.constvars:
        # baked constants are replicated onto every device
        _set(dims, v, (1,) * _rank(v))
    if out_dims:
        for v, d in zip(jx.outvars, out_dims):
            _set(dims, v, d)

    iterations, converged = 0, False
    while iterations < max_iters:
        iterations += 1
        if not _sweep(jx, dims):
            converged = True
            break

    res = PropagationResult(dims=dims, iterations=iterations,
                            converged=converged, jaxpr_id=id(jx))
    _report(jx, dims, res)
    res.axes = _seed_axes(jx, arg_infos)
    _propagate_axes(jx, res.axes)
    res.counts = _final_counts(jx, dims, arg_counts, axes=res.axes)
    text = getattr(program, "text", None) if program is not None else None
    if text:
        _cross_check_hlo(text, jx, dims, res)
    return res


def result_for(program, ctx=None):
    """The propagation result for `program`: the one PropagationAnalyzer
    stashed on `ctx.extra` when it matches this program's jaxpr,
    computed on demand otherwise (the passes can run standalone)."""
    jx = getattr(program, "jaxpr", None)
    if jx is None:
        return None
    cached = (ctx.extra.get("propagation_result")
              if ctx is not None and getattr(ctx, "extra", None) is not None
              else None)
    if cached is not None and cached.jaxpr_id == id(_unclosed(jx)):
        return cached
    return propagate_shardings(program)


@register_analyzer
class PropagationAnalyzer(Analyzer):
    """Sharding-propagation pass: runs the fixed point once per program
    and stashes the result on `ctx.extra["propagation_result"]` for the
    memory and sharding passes (it registers BEFORE them — import order
    in `default_catalog`). Emits no findings itself: the divergence and
    loop-reshard lints live in `sharding.ShardingAnalyzer`, next to the
    other SHARD-* rules. Metrics feed
    propagation_manifests/<config>.json."""
    name = "propagation"

    def run(self, program, ctx):
        if getattr(program, "jaxpr", None) is None:
            self.metrics = {"available": False}
            return []
        res = propagate_shardings(program)
        ctx.extra["propagation_result"] = res
        self.metrics = {"available": True, **res.summary()}
        return []
