"""Thread-discipline lint over the serving/IO host runtime — the
Determinism Doctor's host-side leg (graph-side: determinism.py).

The device-side taint pass can prove a pool write canonical, but the
HOST decides which requests enter which tick: a racy prefetch worker,
an unlocked HostKVTier LRU, or a FlightRecorder hook mutated from two
threads reorders *admissions*, and byte-identical pages no longer mean
byte-identical streams.  Before the cross-process HostKVTier (ROADMAP)
multiplies the thread count, this lint walks every class in
`paddle_tpu/serving/` + `paddle_tpu/io/` and checks the lock
discipline statically, extending the PR-1 dy2static AST-linter idiom
(ast walk, findings with file:line, zero imports of the target).

Model (deliberately conservative about *sides*, precise about
*paths*):

  * a class is THREADED when it spawns `threading.Thread(target=
    self.<m>)`: `<m>` and everything it calls is the WORKER side;
    every other method (minus `__init__`, which runs before the
    thread is published) and everything *it* calls is the MAIN side.
    Classes that spawn no threads produce NO findings — single-
    threaded user code can't false-positive (the r5 fuzz-corpus bar).
  * accesses are keyed by full attribute PATH (`self._stats.batches`,
    not `self._stats`): the prefetch iterator's worker and consumer
    legally own different fields of one stats object.
  * attributes initialised from `Queue`/`Event`/`Lock`/`Condition`/
    `Semaphore`/`threading.local` are thread-safe by construction and
    exempt.

Rules:

  SERVE-UNLOCKED-SHARED  one attribute path is WRITTEN from both
                         sides and at least one of those writes is
                         not inside a `with self.<lock>` block — an
                         unsynchronized write-write on shared
                         mutable state.
  SERVE-LOCK-ORDER       two lock attributes are acquired in opposite
                         nesting orders by different methods — the
                         classic ABBA deadlock once both sides run.
"""
import ast
import os

from .findings import Finding, Severity
from .pass_manager import Analyzer, register_analyzer

__all__ = ["ThreadDisciplineAnalyzer", "lint_thread_discipline",
           "lint_module_source", "default_thread_lint_paths"]

_THREADSAFE_CTORS = frozenset({
    "Queue", "LifoQueue", "PriorityQueue", "SimpleQueue", "Event",
    "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore",
    "Barrier", "local"})
_LOCK_CTORS = frozenset({"Lock", "RLock", "Condition"})
# method calls that mutate their receiver in place
_MUTATORS = frozenset({
    "append", "extend", "insert", "remove", "pop", "popleft",
    "popitem", "clear", "update", "add", "discard", "setdefault",
    "move_to_end", "appendleft", "sort", "reverse"})


def _call_ctor_name(node):
    """`Queue` for `queue.Queue(...)` / `Queue(...)`, else None."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _self_attr_path(node):
    """('_stats', 'batches') for `self._stats.batches`, None when the
    chain is not rooted at `self` (subscripts terminate the path at
    the base attribute: `self._live[i]` -> ('_live',))."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    while isinstance(node, ast.Subscript):
        node = node.value
        if isinstance(node, ast.Attribute):
            parts = []
            while isinstance(node, ast.Attribute):
                parts.append(node.attr)
                node = node.value
    if isinstance(node, ast.Name) and node.id == "self" and parts:
        return tuple(reversed(parts))
    return None


class _MethodScan(ast.NodeVisitor):
    """Per-method facts: self-attr writes (with the lock-attr context
    each occurred under), self-method calls, and nested lock orders."""

    def __init__(self, lock_attrs):
        self.lock_attrs = lock_attrs
        self.writes = []         # (path, lineno, frozenset(held locks))
        self.calls = set()       # self.<m>() method names
        self.lock_pairs = []     # (outer, inner, lineno)
        self._held = []

    # ---- lock context

    def visit_With(self, node):
        acquired = []
        for item in node.items:
            p = None
            ce = item.context_expr
            if isinstance(ce, ast.Attribute):
                p = _self_attr_path(ce)
            elif isinstance(ce, ast.Call) and \
                    isinstance(ce.func, ast.Attribute):
                # `with self._lock.acquire_timeout(...)`-style wrappers
                p = _self_attr_path(ce.func.value)
            if p and len(p) == 1 and p[0] in self.lock_attrs:
                for outer in self._held:
                    self.lock_pairs.append((outer, p[0], node.lineno))
                acquired.append(p[0])
                self._held.append(p[0])
        self.generic_visit(node)
        for _ in acquired:
            self._held.pop()

    # ---- writes

    def _record_write(self, target, lineno):
        p = _self_attr_path(target)
        if p is not None:
            self.writes.append((p, lineno, frozenset(self._held)))

    def visit_Assign(self, node):
        for t in node.targets:
            self._record_write(t, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._record_write(node.target, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._record_write(node.target, node.lineno)
        self.generic_visit(node)

    def visit_Call(self, node):
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr in _MUTATORS:
                p = _self_attr_path(f.value)
                if p is not None:
                    self.writes.append(
                        (p, node.lineno, frozenset(self._held)))
            elif isinstance(f.value, ast.Name) and \
                    f.value.id == "self":
                self.calls.add(f.attr)
        self.generic_visit(node)


def _thread_targets(cls_node):
    """Names of methods passed as `threading.Thread(target=self.<m>)`
    anywhere in the class body."""
    targets = set()
    for node in ast.walk(cls_node):
        if isinstance(node, ast.Call) and \
                _call_ctor_name(node) == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    p = _self_attr_path(kw.value)
                    if p and len(p) == 1:
                        targets.add(p[0])
    return targets


def _closure(roots, calls):
    seen = set(roots)
    frontier = list(roots)
    while frontier:
        m = frontier.pop()
        for callee in calls.get(m, ()):
            if callee in calls and callee not in seen:
                seen.add(callee)
                frontier.append(callee)
    return seen


def _conflicts(a, b):
    """Two attr paths alias when one is a prefix of the other."""
    n = min(len(a), len(b))
    return a[:n] == b[:n]


def _lint_class(cls_node, filename, findings):
    """Run both rules over one ClassDef.  Returns per-class metric
    counters (threaded?, shared paths, lock attrs)."""
    methods = {n.name: n for n in cls_node.body
               if isinstance(n, (ast.FunctionDef,
                                 ast.AsyncFunctionDef))}
    # attr typing from constructor-looking assignments anywhere
    lock_attrs, safe_attrs = set(), set()
    for node in ast.walk(cls_node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            p = _self_attr_path(node.targets[0])
            ctor = _call_ctor_name(node.value)
            if p and len(p) == 1 and ctor in _THREADSAFE_CTORS:
                safe_attrs.add(p[0])
                if ctor in _LOCK_CTORS:
                    lock_attrs.add(p[0])

    workers = _thread_targets(cls_node) & set(methods)
    stats = {"threaded": bool(workers), "n_lock_attrs": len(lock_attrs),
             "n_shared_paths": 0}
    scans = {}
    for name, node in methods.items():
        s = _MethodScan(lock_attrs)
        for stmt in node.body:
            s.visit(stmt)
        scans[name] = s
    calls = {name: s.calls for name, s in scans.items()}

    # SERVE-LOCK-ORDER needs no worker: inconsistent nesting is a
    # hazard the moment any caller threads (and the committed runtime
    # is about to)
    order = {}                   # (A, B) -> first lineno
    for name, s in scans.items():
        for a, b, line in s.lock_pairs:
            order.setdefault((a, b), (name, line))
    for (a, b), (name, line) in sorted(order.items()):
        if a != b and (b, a) in order and (a, b) < (b, a):
            oname, oline = order[(b, a)]
            findings.append(Finding(
                "SERVE-LOCK-ORDER", Severity.ERROR,
                f"class {cls_node.name} acquires lock '{a}' then "
                f"'{b}' in {name} (line {line}) but '{b}' then '{a}' "
                f"in {oname} (line {oline}) — opposite nesting "
                "orders deadlock once both run concurrently",
                op=f"{cls_node.name}.{name}",
                location=f"{os.path.basename(filename)}:{line}",
                suggested_fix="pick one global acquisition order for "
                "the class's locks and make every method follow it"))

    if not workers:
        return stats

    worker_side = _closure(workers, calls)
    main_roots = (set(methods) - worker_side) - {"__init__"}
    main_side = _closure(main_roots, calls)

    def side_writes(side):
        out = {}
        for m in sorted(side):
            for p, line, held in scans[m].writes:
                if p[0] in safe_attrs or p[0] in lock_attrs:
                    continue
                out.setdefault(p, []).append((m, line, held))
        return out

    ww, mw = side_writes(worker_side), side_writes(main_side)
    flagged = set()
    for wp in sorted(ww):
        for mp in sorted(mw):
            if not _conflicts(wp, mp):
                continue
            key = min(wp, mp)
            if key in flagged:
                continue
            accesses = ww[wp] + mw[mp]
            held_everywhere = frozenset.intersection(
                *[h for _, _, h in accesses])
            stats["n_shared_paths"] += 1
            if held_everywhere:
                continue          # one common lock guards every write
            flagged.add(key)
            attr = "self." + ".".join(key)
            sides = ", ".join(
                f"{m} line {ln}" + (" [unlocked]" if not h else "")
                for m, ln, h in accesses[:4])
            findings.append(Finding(
                "SERVE-UNLOCKED-SHARED", Severity.ERROR,
                f"class {cls_node.name} writes {attr} from both the "
                f"worker thread and the main thread with no common "
                f"lock held ({sides}) — an unsynchronized write-write "
                "on shared mutable state; admission order becomes "
                "schedule-dependent",
                op=f"{cls_node.name}: {attr}",
                location=(f"{os.path.basename(filename)}:"
                          f"{accesses[0][1]}"),
                suggested_fix="guard every write with one owning "
                "`with self.<lock>:` block, or hand the value across "
                "threads through the Queue instead of a shared "
                "attribute"))
    return stats


def lint_module_source(src, filename="<module>"):
    """Lint one module's SOURCE TEXT.  Returns (findings, stats) —
    the entry the fuzz-corpus tests drive directly."""
    findings = []
    stats = {"n_classes": 0, "n_threaded_classes": 0,
             "n_shared_paths": 0, "n_lock_attrs": 0}
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return findings, stats
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            stats["n_classes"] += 1
            cs = _lint_class(node, filename, findings)
            stats["n_threaded_classes"] += int(cs["threaded"])
            stats["n_shared_paths"] += cs["n_shared_paths"]
            stats["n_lock_attrs"] += cs["n_lock_attrs"]
    return findings, stats


def default_thread_lint_paths():
    """The serving-runtime surface the lint audits: every module of
    `paddle_tpu/serving/` and `paddle_tpu/io/`."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = []
    for sub in ("serving", "io"):
        d = os.path.join(pkg, sub)
        if os.path.isdir(d):
            out.extend(sorted(
                os.path.join(d, f) for f in os.listdir(d)
                if f.endswith(".py")))
    return out


def lint_thread_discipline(paths=None):
    """Lint every module in `paths` (default: serving/ + io/).
    Returns (findings, metrics) — deterministic: files sorted, classes
    in file order."""
    findings = []
    metrics = {"n_files": 0, "n_classes": 0, "n_threaded_classes": 0,
               "n_shared_paths": 0, "n_lock_attrs": 0}
    for path in (paths if paths is not None
                 else default_thread_lint_paths()):
        try:
            with open(path) as f:
                src = f.read()
        except OSError:
            continue
        metrics["n_files"] += 1
        found, stats = lint_module_source(src, filename=path)
        findings.extend(found)
        for k, v in stats.items():
            metrics[k] += v
    rules = {}
    for f in findings:
        rules[f.rule_id] = rules.get(f.rule_id, 0) + 1
    metrics["rules"] = rules
    return findings, metrics


@register_analyzer
class ThreadDisciplineAnalyzer(Analyzer):
    """Host-side Determinism Doctor leg: SERVE-UNLOCKED-SHARED +
    SERVE-LOCK-ORDER over the serving/IO runtime modules.  A `source`
    analyzer that audits the REPO surface rather than the passed
    target, so it only runs when the context opts in
    (`ctx.extra["thread_lint"]` or a serving capture's
    `serving_decode`) — layer lints stay unaffected."""
    name = "threads"
    kind = "source"

    def run(self, target, ctx):
        extra = getattr(ctx, "extra", None) or {}
        if not (extra.get("thread_lint") or extra.get("serving_decode")):
            self.metrics = {"available": False}
            return []
        paths = extra.get("thread_lint_paths")
        findings, metrics = lint_thread_discipline(paths)
        self.metrics = {"available": True, **metrics}
        return findings
