"""Per-model lint & memory manifests — the committed, diffable face of
the Graph Doctor (same role as perf_evidence.json for the analytic perf
model: regenerate, diff, review).

`lint_manifests/<config>.json` pins each BASELINE config's op counts,
collective accounting, and finding summary. The graph-shape analyzer
treats the committed manifest as the baseline: any drift is an ERROR
until the manifest is regenerated and the diff reviewed.

`memory_manifests/<config>.json` pins the static per-device HBM
estimate (liveness peak, breakdown, top-k attribution) and the analytic
collective wire budget. The memory/sharding passes gate fresh runs
against it; `manifest_drift` powers the CLI's `--check` mode (stale
manifests fail CI instead of silently re-baselining the lint)."""
import json
import os

__all__ = ["manifest_dir", "manifest_path", "load_manifest",
           "build_manifest", "write_manifest",
           "memory_manifest_dir", "memory_manifest_path",
           "load_memory_manifest", "build_memory_manifest",
           "write_memory_manifest", "manifest_drift",
           "tuning_manifest_dir", "tuning_manifest_path",
           "load_tuning_manifest", "build_tuning_manifest",
           "write_tuning_manifest",
           "schedule_manifest_dir", "schedule_manifest_path",
           "load_schedule_manifest", "build_schedule_manifest",
           "write_schedule_manifest",
           "propagation_manifest_dir", "propagation_manifest_path",
           "load_propagation_manifest", "build_propagation_manifest",
           "write_propagation_manifest",
           "determinism_manifest_dir", "determinism_manifest_path",
           "load_determinism_manifest", "build_determinism_manifest",
           "write_determinism_manifest"]

_SCHEMA = 1
_MEMORY_SCHEMA = 1
_TUNING_SCHEMA = 1
_SCHEDULE_SCHEMA = 1
_PROPAGATION_SCHEMA = 1
_DETERMINISM_SCHEMA = 1


def manifest_dir():
    """Repo-root lint_manifests/ (next to perf_evidence.json)."""
    here = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(os.path.dirname(here))
    return os.path.join(repo, "lint_manifests")


def manifest_path(name):
    return os.path.join(manifest_dir(), f"{name}.json")


def load_manifest(name):
    """The committed manifest dict, or None when not yet committed."""
    try:
        with open(manifest_path(name)) as f:
            return json.load(f)
    except FileNotFoundError:
        return None


def build_manifest(name, program, report):
    """Manifest dict from one pass-manager run (deterministic: sorted
    keys, no timestamps — a re-run on an unchanged graph must produce a
    byte-identical file)."""
    counts = report.metrics.get("graph-shape", {}).get("op_counts", {})
    coll = report.metrics.get("collective", {})
    by_rule = {}
    for f in report.findings:
        by_rule[f.rule_id] = by_rule.get(f.rule_id, 0) + 1
    return {
        "schema": _SCHEMA,
        "model": name,
        "op_counts": {k: counts[k] for k in sorted(counts)},
        "collectives": {
            "count": coll.get("n_collectives", 0),
            "total_payload_bytes": coll.get("total_payload_bytes", 0),
            "total_wire_bytes": coll.get("total_wire_bytes", 0),
        },
        "findings_by_rule": {k: by_rule[k] for k in sorted(by_rule)},
        "max_severity": (str(report.max_severity)
                         if report.findings else None),
        "note": "regenerate: python -m paddle_tpu.analysis "
                "--write-manifests",
    }


def write_manifest(name, program, report):
    os.makedirs(manifest_dir(), exist_ok=True)
    data = build_manifest(name, program, report)
    with open(manifest_path(name), "w") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    return data


# ---------------------------------------------------------------- memory


def memory_manifest_dir():
    """Repo-root memory_manifests/ (next to lint_manifests/)."""
    here = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(os.path.dirname(here))
    return os.path.join(repo, "memory_manifests")


def memory_manifest_path(name):
    return os.path.join(memory_manifest_dir(), f"{name}.json")


def load_memory_manifest(name):
    """The committed memory manifest dict, or None when not committed."""
    try:
        with open(memory_manifest_path(name)) as f:
            return json.load(f)
    except FileNotFoundError:
        return None


def build_memory_manifest(name, report):
    """Memory manifest dict from one pass-manager run (deterministic:
    sorted keys, no timestamps, native dtype widths — platform
    independent, so a TPU and a CPU checkout agree byte-for-byte)."""
    mem = report.metrics.get("memory", {})
    sh = report.metrics.get("sharding", {})
    return {
        "schema": _MEMORY_SCHEMA,
        "model": name,
        "per_device_peak_bytes": mem.get("peak_bytes", 0),
        "args_bytes": mem.get("args_bytes", 0),
        "output_bytes": mem.get("out_bytes", 0),
        "temp_peak_bytes": mem.get("temp_peak_bytes", 0),
        "donated_bytes": mem.get("donated_bytes", 0),
        "top_live": [
            {"op": b.get("op"), "name": b.get("name"),
             "device_bytes": b.get("device_bytes")}
            for b in mem.get("top_live", [])],
        "replication": {
            "n_replicated_big": sh.get("n_replicated_big", 0),
            "replicated_big_bytes": sh.get("replicated_big_bytes", 0),
        },
        "collectives": {
            "total_wire_bytes": sh.get("total_wire_bytes", 0),
            "n_mid_program_reshards": sh.get("n_mid_program_reshards", 0),
        },
        "note": "regenerate: python -m paddle_tpu.analysis "
                "--write-manifests",
        # dp-over-hosts captures only: the distinct-bytes-per-host
        # block (absent keeps single-host manifests byte-stable)
        **({"per_host": mem["per_host"]} if mem.get("per_host") else {}),
    }


def write_memory_manifest(name, report):
    os.makedirs(memory_manifest_dir(), exist_ok=True)
    data = build_memory_manifest(name, report)
    with open(memory_manifest_path(name), "w") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    return data


# ---------------------------------------------------------------- tuning


def tuning_manifest_dir():
    """Repo-root tuning_manifests/ (next to memory_manifests/)."""
    here = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(os.path.dirname(here))
    return os.path.join(repo, "tuning_manifests")


def tuning_manifest_path(name):
    return os.path.join(tuning_manifest_dir(), f"{name}.json")


def load_tuning_manifest(name):
    """The committed tuning manifest dict, or None when not committed."""
    try:
        with open(tuning_manifest_path(name)) as f:
            return json.load(f)
    except FileNotFoundError:
        return None


def build_tuning_manifest(name, report):
    """Tuning manifest dict from one `autotune_layer` report
    (analysis/autotune.py): per-policy what-if peaks, recompute %, and
    the advisor's ranking. Deterministic — the replay runs over one
    seeded CPU trace and the roofline prices against a FIXED chip spec
    (v5e), so a TPU and a CPU checkout agree byte-for-byte."""
    return {
        "schema": _TUNING_SCHEMA,
        "model": name,
        "chip": report.chip,
        "hbm_budget_bytes": report.hbm_budget,
        "policies": {
            c.policy: {
                "peak_bytes": c.peak_bytes,
                "recompute_pct": round(c.recompute_pct, 2),
                "predicted_step_us": round(c.step_s * 1e6, 3),
                "bound": c.bound,
                "feasible": c.feasible,
            } for c in report.candidates},
        "ranked": [c.policy for c in report.candidates],
        "best": report.best.policy if report.best else None,
        "note": "regenerate: python -m paddle_tpu.analysis "
                "--write-manifests",
    }


def write_tuning_manifest(name, report):
    os.makedirs(tuning_manifest_dir(), exist_ok=True)
    data = build_tuning_manifest(name, report)
    with open(tuning_manifest_path(name), "w") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    return data


# -------------------------------------------------------------- schedule


def schedule_manifest_dir():
    """Repo-root schedule_manifests/ (next to tuning_manifests/)."""
    here = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(os.path.dirname(here))
    return os.path.join(repo, "schedule_manifests")


def schedule_manifest_path(name):
    return os.path.join(schedule_manifest_dir(), f"{name}.json")


def load_schedule_manifest(name):
    """The committed schedule manifest dict, or None when absent."""
    try:
        with open(schedule_manifest_path(name)) as f:
            return json.load(f)
    except FileNotFoundError:
        return None


def build_schedule_manifest(name, report):
    """Schedule manifest dict from one pass-manager run
    (analysis/schedule.py metrics): the overlap-aware/serial/roofline
    step-time bracket, the wire-hiding fraction, and the critical-path
    attribution. Deterministic — node pricing runs over the cached CPU
    trace against the FIXED v5e spec (the tuning-manifest discipline),
    so a TPU and a CPU checkout agree byte-for-byte."""
    sch = report.metrics.get("schedule", {})
    return {
        "schema": _SCHEDULE_SCHEMA,
        "model": name,
        "chip": "v5e",
        "n_nodes": sch.get("n_nodes", 0),
        "n_collectives": sch.get("n_collectives", 0),
        "n_serialized_collectives": sch.get(
            "n_serialized_collectives", 0),
        "wire": {"ici_bytes": sch.get("wire_ici_bytes", 0),
                 "dcn_bytes": sch.get("wire_dcn_bytes", 0)},
        "ideal_step_us": sch.get("ideal_step_us", 0),
        "overlap_step_us": sch.get("overlap_step_us", 0),
        "serial_step_us": sch.get("serial_step_us", 0),
        "overlap_frac": sch.get("overlap_frac", 1.0),
        "critical_path": sch.get("critical_path", []),
        "note": "regenerate: python -m paddle_tpu.analysis "
                "--write-manifests",
    }


def write_schedule_manifest(name, report):
    os.makedirs(schedule_manifest_dir(), exist_ok=True)
    data = build_schedule_manifest(name, report)
    with open(schedule_manifest_path(name), "w") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    return data


# ----------------------------------------------------------- propagation


def propagation_manifest_dir():
    """Repo-root propagation_manifests/ (next to schedule_manifests/)."""
    here = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(os.path.dirname(here))
    return os.path.join(repo, "propagation_manifests")


def propagation_manifest_path(name):
    return os.path.join(propagation_manifest_dir(), f"{name}.json")


def load_propagation_manifest(name):
    """The committed propagation manifest dict, or None when absent."""
    try:
        with open(propagation_manifest_path(name)) as f:
            return json.load(f)
    except FileNotFoundError:
        return None


def build_propagation_manifest(name, report):
    """Propagation manifest dict from one pass-manager run
    (analysis/propagation.py metrics): fixed-point coverage (exact vs
    conservative-fallback vars), the XLA cross-check's agreement
    counters, and the two lint feeds' counts. Deterministic — the
    fixed point over one cached CPU trace converges to the same specs
    on every machine, so a TPU and a CPU checkout agree
    byte-for-byte."""
    prop = report.metrics.get("propagation", {})
    return {
        "schema": _PROPAGATION_SCHEMA,
        "model": name,
        "n_vars": prop.get("n_vars", 0),
        "n_exact": prop.get("n_exact", 0),
        "n_fallback": prop.get("n_fallback", 0),
        "n_constraints": prop.get("n_constraints", 0),
        "annotations": {
            "n_annotated": prop.get("n_annotated", 0),
            "n_agree": prop.get("n_agree", 0),
            "n_diverge": prop.get("n_diverge", 0),
            "n_unmapped": prop.get("n_unmapped", 0),
            "agreement_rate": prop.get("agreement_rate", 1.0),
        },
        "n_divergences": prop.get("n_divergences", 0),
        "n_loop_carry_reshards": prop.get("n_loop_carry_reshards", 0),
        "iterations": prop.get("iterations", 0),
        "converged": prop.get("converged", True),
        "note": "regenerate: python -m paddle_tpu.analysis "
                "--write-manifests",
    }


def write_propagation_manifest(name, report):
    os.makedirs(propagation_manifest_dir(), exist_ok=True)
    data = build_propagation_manifest(name, report)
    with open(propagation_manifest_path(name), "w") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    return data


# ----------------------------------------------------------- determinism


def determinism_manifest_dir():
    """Repo-root determinism_manifests/ (next to schedule_manifests/)."""
    here = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(os.path.dirname(here))
    return os.path.join(repo, "determinism_manifests")


def determinism_manifest_path(name):
    return os.path.join(determinism_manifest_dir(), f"{name}.json")


def load_determinism_manifest(name):
    """The committed determinism manifest dict, or None when absent."""
    try:
        with open(determinism_manifest_path(name)) as f:
            return json.load(f)
    except FileNotFoundError:
        return None


def build_determinism_manifest(name, report):
    """Determinism manifest dict from one pass-manager run: the graph
    leg's taint/write/race coverage (analysis/determinism.py metrics)
    plus the host leg's thread-discipline counters
    (analysis/threads.py).  Committed GREEN for every serving PROGRAM
    config — the one expected red (the SpeculativeEngine verify
    window) is a separate, uncommitted program pinned red by
    tests/test_determinism_lint.py.  Deterministic: the taint fixed
    point walks one cached CPU trace and the thread lint walks the
    checked-in sources, so every machine agrees byte-for-byte."""
    det = report.metrics.get("determinism", {})
    thr = report.metrics.get("threads", {})
    fnd = [f for f in report.findings
           if f.analyzer in ("determinism", "threads")]
    rules = dict(det.get("rules", {}))
    for k, v in thr.get("rules", {}).items():
        rules[k] = rules.get(k, 0) + v
    return {
        "schema": _DETERMINISM_SCHEMA,
        "model": name,
        "graph": {
            "n_eqns": det.get("n_eqns", 0),
            "n_pool_buffers": det.get("n_pool_buffers", 0),
            "n_pool_writes": det.get("n_pool_writes", 0),
            "n_canonical_writes": det.get("n_canonical_writes", 0),
            "n_rng_sites": det.get("n_rng_sites", 0),
            "n_overlap_pairs": det.get("n_overlap_pairs", 0),
            "n_proven_disjoint": det.get("n_proven_disjoint", 0),
            "n_donated_args": det.get("n_donated_args", 0),
            "n_alias_outputs": det.get("n_alias_outputs", 0),
        },
        "threads": {
            "n_files": thr.get("n_files", 0),
            "n_classes": thr.get("n_classes", 0),
            "n_threaded_classes": thr.get("n_threaded_classes", 0),
            "n_shared_paths": thr.get("n_shared_paths", 0),
            "n_lock_attrs": thr.get("n_lock_attrs", 0),
        },
        "rules": rules,
        "n_findings": len(fnd),
        "max_severity": (str(max(f.severity for f in fnd))
                         if fnd else None),
        "note": "regenerate: python -m paddle_tpu.analysis "
                "--write-manifests",
    }


def write_determinism_manifest(name, report):
    os.makedirs(determinism_manifest_dir(), exist_ok=True)
    data = build_determinism_manifest(name, report)
    with open(determinism_manifest_path(name), "w") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    return data


def manifest_drift(fresh, committed, path=""):
    """Recursive diff of a regenerated manifest dict vs the committed
    one. Returns ["path: committed -> fresh", ...] — empty means the
    committed file is current. The CLI's --check mode fails CI on any
    entry, so stale manifests can't silently re-baseline the lint."""
    if committed is None and isinstance(fresh, dict):
        # a manifest is always a dict, so a None here is the missing
        # FILE — a None VALUE (e.g. max_severity on a clean model)
        # falls through to the scalar compare below
        return [f"{path or '<manifest>'}: missing committed file"]
    if isinstance(fresh, dict) and isinstance(committed, dict):
        out = []
        for k in sorted(set(fresh) | set(committed)):
            sub = f"{path}.{k}" if path else str(k)
            if k not in fresh:
                out.append(f"{sub}: {committed[k]!r} -> <gone>")
            elif k not in committed:
                out.append(f"{sub}: <absent> -> {fresh[k]!r}")
            else:
                out.extend(manifest_drift(fresh[k], committed[k], sub))
        return out
    if isinstance(fresh, list) and isinstance(committed, list):
        if fresh != committed:
            return [f"{path}: list changed ({len(committed)} -> "
                    f"{len(fresh)} entries)"]
        return []
    if fresh != committed:
        return [f"{path}: {committed!r} -> {fresh!r}"]
    return []
