"""Per-model lint manifests — the committed, diffable face of the
Graph Doctor (same role as perf_evidence.json for the analytic perf
model: regenerate, diff, review).

`lint_manifests/<config>.json` pins each BASELINE config's op counts,
collective accounting, and finding summary. The graph-shape analyzer
treats the committed manifest as the baseline: any drift is an ERROR
until the manifest is regenerated and the diff reviewed.
"""
import json
import os

__all__ = ["manifest_dir", "manifest_path", "load_manifest",
           "build_manifest", "write_manifest"]

_SCHEMA = 1


def manifest_dir():
    """Repo-root lint_manifests/ (next to perf_evidence.json)."""
    here = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(os.path.dirname(here))
    return os.path.join(repo, "lint_manifests")


def manifest_path(name):
    return os.path.join(manifest_dir(), f"{name}.json")


def load_manifest(name):
    """The committed manifest dict, or None when not yet committed."""
    try:
        with open(manifest_path(name)) as f:
            return json.load(f)
    except FileNotFoundError:
        return None


def build_manifest(name, program, report):
    """Manifest dict from one pass-manager run (deterministic: sorted
    keys, no timestamps — a re-run on an unchanged graph must produce a
    byte-identical file)."""
    counts = report.metrics.get("graph-shape", {}).get("op_counts", {})
    coll = report.metrics.get("collective", {})
    by_rule = {}
    for f in report.findings:
        by_rule[f.rule_id] = by_rule.get(f.rule_id, 0) + 1
    return {
        "schema": _SCHEMA,
        "model": name,
        "op_counts": {k: counts[k] for k in sorted(counts)},
        "collectives": {
            "count": coll.get("n_collectives", 0),
            "total_payload_bytes": coll.get("total_payload_bytes", 0),
            "total_wire_bytes": coll.get("total_wire_bytes", 0),
        },
        "findings_by_rule": {k: by_rule[k] for k in sorted(by_rule)},
        "max_severity": (str(report.max_severity)
                         if report.findings else None),
        "note": "regenerate: python -m paddle_tpu.analysis "
                "--write-manifests",
    }


def write_manifest(name, program, report):
    os.makedirs(manifest_dir(), exist_ok=True)
    data = build_manifest(name, program, report)
    with open(manifest_path(name), "w") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    return data
