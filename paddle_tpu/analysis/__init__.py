"""paddle_tpu.analysis — the Graph Doctor: chip-independent static
analysis of lowered programs (StableHLO + jaxpr on the CPU platform)
and of python-side dy2static hazards, run as a pass catalog that emits
structured Findings before a model ever reaches a chip.

Three front doors:
  * ``paddle.jit.to_static(fn, lint=True)`` — lint at conversion/first
    compile, warnings surfaced inline;
  * ``python -m paddle_tpu.analysis [config ...]`` — CLI over the five
    BASELINE configs (or any ``module:builder`` spec), writing the
    committed per-model lint manifests;
  * the pytest gate (tests/test_graph_lint.py, ``lint_graphs`` marker)
    — every BASELINE config must lint clean against its committed
    manifest in the standard tier-1 sweep.

See docs/static_analysis.md for the rule catalog and how to add an
analyzer.
"""
from .findings import Finding, Report, Severity  # noqa: F401
from .lowering import (LoweredProgram, lower_callable,  # noqa: F401
                       lower_layer, tensor_type_bytes)
from .pass_manager import (AnalysisContext, Analyzer,  # noqa: F401
                           PassManager, default_catalog, get_analyzer,
                           register_analyzer)
from . import analyzers  # noqa: F401  (registers the graph passes)
# propagation registers BEFORE memory/sharding: those passes consume
# the fixed-point result it stashes on ctx.extra, so it must run first
from . import propagation as _propagation  # noqa: F401
from . import memory as _memory  # noqa: F401  (registers the memory pass)
from . import sharding as _sharding  # noqa: F401  (registers sharding pass)
from . import schedule as _schedule  # noqa: F401 (registers schedule pass)
from . import determinism as _determinism  # noqa: F401 (determinism pass)
from . import threads as _threads  # noqa: F401 (thread-discipline lint)
from .analyzers import COLLECTIVE_OPS, MXU_OPS  # noqa: F401
from .ast_lint import lint_function  # noqa: F401
from .lowering import ArgInfo, sharding_shard_count  # noqa: F401
from .manifest import (build_manifest, load_manifest,  # noqa: F401
                       manifest_path, write_manifest,
                       build_memory_manifest, load_memory_manifest,
                       manifest_drift, memory_manifest_path,
                       write_memory_manifest,
                       build_tuning_manifest, load_tuning_manifest,
                       tuning_manifest_path, write_tuning_manifest,
                       build_schedule_manifest, load_schedule_manifest,
                       schedule_manifest_path, write_schedule_manifest,
                       build_propagation_manifest,
                       load_propagation_manifest,
                       propagation_manifest_path,
                       write_propagation_manifest,
                       build_determinism_manifest,
                       load_determinism_manifest,
                       determinism_manifest_path,
                       write_determinism_manifest)
from .determinism import (DeterminismResult,  # noqa: F401
                          analyze_determinism)
from .threads import lint_thread_discipline  # noqa: F401
from .memory import (MemoryEstimate, audit_page_ledger,  # noqa: F401
                     estimate_jaxpr_memory, propagate_shard_counts)
from .propagation import (PropagationResult,  # noqa: F401
                          propagate_shardings)
from .schedule import (ScheduleEstimate, ScheduleNode,  # noqa: F401
                       estimate_schedule)
from .remat_advisor import (REMAT_POLICIES, RematWhatIf,  # noqa: F401
                            advise_remat, replay_remat)
from .autotune import (AutotuneReport, CandidateEstimate,  # noqa: F401
                       autotune, autotune_layer, rank_gpt_candidates)

__all__ = [
    "Finding", "Report", "Severity",
    "LoweredProgram", "lower_callable", "lower_layer",
    "ArgInfo", "sharding_shard_count",
    "AnalysisContext", "Analyzer", "PassManager", "default_catalog",
    "get_analyzer", "register_analyzer",
    "lint_function", "analyze", "analyze_layer",
    "build_manifest", "load_manifest", "manifest_path", "write_manifest",
    "build_memory_manifest", "load_memory_manifest", "manifest_drift",
    "memory_manifest_path", "write_memory_manifest",
    "build_tuning_manifest", "load_tuning_manifest",
    "tuning_manifest_path", "write_tuning_manifest",
    "build_schedule_manifest", "load_schedule_manifest",
    "schedule_manifest_path", "write_schedule_manifest",
    "build_propagation_manifest", "load_propagation_manifest",
    "propagation_manifest_path", "write_propagation_manifest",
    "build_determinism_manifest", "load_determinism_manifest",
    "determinism_manifest_path", "write_determinism_manifest",
    "DeterminismResult", "analyze_determinism",
    "lint_thread_discipline",
    "MemoryEstimate", "estimate_jaxpr_memory", "propagate_shard_counts",
    "PropagationResult", "propagate_shardings",
    "audit_page_ledger",
    "ScheduleEstimate", "ScheduleNode", "estimate_schedule",
    "REMAT_POLICIES", "RematWhatIf", "advise_remat", "replay_remat",
    "AutotuneReport", "CandidateEstimate", "autotune", "autotune_layer",
    "rank_gpt_candidates",
    "BASELINE_CONFIGS",
]


def analyze_layer(model, *example_arrays, context=None, analyzers=None):
    """One-call Graph Doctor: lower `model` at the example inputs and
    run the full catalog. Returns a Report."""
    return PassManager(analyzers).run_layer(model, *example_arrays,
                                            context=context)


def analyze(fn, *example_args, context=None, analyzers=None):
    """Analyze a jittable callable (already functional — no Layer
    plumbing). Every argument of a plain callable is an INPUT, so all
    %arg ids are input ids: a transpose applied directly to an input
    is activation traffic, not a free weight-layout move."""
    import jax
    pm = PassManager(analyzers)
    context = context or AnalysisContext(
        name=getattr(fn, "__name__", "program"))
    report = pm.run_source(fn, context)
    n_in = len(jax.tree_util.tree_leaves(list(example_args)))
    program = lower_callable(fn, *example_args, name=context.name,
                             input_arg_ids=range(n_in))
    report.extend(pm.run(program, context))
    return report


def __getattr__(name):
    # BASELINE_CONFIGS builds models on import; keep it lazy so
    # `import paddle_tpu.analysis` stays cheap
    if name == "BASELINE_CONFIGS":
        from .baseline import BASELINE_CONFIGS
        return BASELINE_CONFIGS
    raise AttributeError(name)
