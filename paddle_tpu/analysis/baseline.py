"""The five BASELINE configs (BASELINE.json) as lintable model specs.

Each entry builds a tiny, CPU-lowerable stand-in for a headline
workload (same architecture family, same graph invariants, shrunk
shapes) plus the AnalysisContext carrying its contracts: data format,
dtype policy, by-design transpose exemptions, f32 exemptions, and
expected op counts published by the model modules themselves
(GRAPH_CONTRACT / graph_contract next to each architecture).

Lowerings are cached per config for the process lifetime — the pytest
lint gate and the CLI share one trace per model.
"""
import jax.numpy as jnp

from .pass_manager import AnalysisContext

__all__ = ["BASELINE_CONFIGS", "PROGRAM_CONFIGS", "SCHEDULE_CONFIGS",
           "DETERMINISM_CONFIGS", "build_config", "lowered_program",
           "forward_fn", "tuning_report"]

_CACHE = {}   # name -> (LoweredProgram, AnalysisContext, forward fn)
_TUNING_CACHE = {}   # name -> AutotuneReport (autotune.autotune_layer)

# the ragged paged attention's by-design reorders (one body behind
# decode ticks, chunked prefill and the mixed horizon — see
# ops/ragged_paged_attention.py): the page-gather layout move
# [n,MP,ps,H,D] -> per-page [MP][n,H,ps,D] and the q/out head-major
# flip. Shared by every serving PROGRAM config.
RAGGED_ATTENTION_TRANSPOSES = (r"dims = \[1, 0, 3, 2, 4\]",
                               r"dims = \[0, 2, 1, 3\]")


def _fresh():
    import paddle_tpu as paddle
    from paddle_tpu.distributed import build_mesh
    paddle.seed(0)
    build_mesh(dp=1)
    return paddle


def _resnet50():
    paddle = _fresh()
    from paddle_tpu.vision.models import resnet
    model = paddle.vision.models.resnet50(num_classes=10,
                                          data_format="NHWC")
    model.bfloat16()
    model.eval()
    x = jnp.zeros((2, 64, 64, 3), jnp.bfloat16)
    ctx = AnalysisContext(
        name="resnet50", policy_dtype="bfloat16", data_format="NHWC",
        expected_counts=dict(resnet.GRAPH_CONTRACT),
        expect_collectives=False)
    return model, (x,), ctx


def _bert_base():
    paddle = _fresh()
    from paddle_tpu.models import bert as bert_mod
    cfg = bert_mod.bert_base(dtype="bfloat16")
    cfg.num_layers = 2          # graph shape per layer is what matters
    model = bert_mod.BertModel(cfg)
    model.bfloat16()
    model.train()               # dropout ACTIVE — that's the pin
    ids = jnp.zeros((2, 64), jnp.int32)
    from paddle_tpu.models.gpt import ATTENTION_TRANSPOSES
    ctx = AnalysisContext(
        name="bert_base", policy_dtype="bfloat16",
        allowed_activation_transposes=ATTENTION_TRANSPOSES,
        expected_counts=bert_mod.graph_contract(cfg),
        expect_collectives=False)
    return model, (ids,), ctx


def _gpt():
    paddle = _fresh()
    from paddle_tpu.models import GPT, gpt_tiny
    from paddle_tpu.models import gpt as gpt_mod
    cfg = gpt_tiny(dtype="bfloat16", remat=False)
    model = GPT(cfg)
    model.bfloat16()
    model.eval()
    ids = jnp.zeros((2, 32), jnp.int32)
    ctx = AnalysisContext(
        name="gpt", policy_dtype="bfloat16",
        allowed_activation_transposes=gpt_mod.ATTENTION_TRANSPOSES,
        expected_counts=gpt_mod.graph_contract(cfg),
        expect_collectives=False)
    return model, (ids,), ctx


def _ppocr_crnn():
    paddle = _fresh()
    from paddle_tpu.vision.models import CRNN
    from paddle_tpu.vision.models import ocr as ocr_mod
    model = CRNN(num_classes=97, data_format="NHWC")
    model.bfloat16()
    model.eval()
    x = jnp.zeros((2, 32, 64, 3), jnp.bfloat16)
    ctx = AnalysisContext(
        name="ppocr_crnn", policy_dtype="bfloat16", data_format="NHWC",
        # the single by-design [B,W',C]->[W',B,C] sequence-major flip
        allowed_activation_transposes=(
            r"dims = \[1, 0, 2\]",),
        expected_counts=dict(ocr_mod.GRAPH_CONTRACT),
        expect_collectives=False)
    return model, (x,), ctx


def _gpt_moe():
    paddle = _fresh()
    from paddle_tpu.models import GPTMoE
    from paddle_tpu.models import moe as moe_mod
    cfg = moe_mod.gpt_moe_tiny(dtype="bfloat16")
    model = GPTMoE(cfg)
    model.bfloat16()
    model.eval()
    ids = jnp.zeros((2, 32), jnp.int32)
    from paddle_tpu.models.gpt import ATTENTION_TRANSPOSES
    ctx = AnalysisContext(
        name="gpt_moe", policy_dtype="bfloat16",
        allowed_activation_transposes=ATTENTION_TRANSPOSES,
        f32_dot_allow=moe_mod.router_f32_allow(cfg),
        expect_collectives=False)
    return model, (ids,), ctx


# config name -> builder() -> (model, example_arrays, AnalysisContext)
BASELINE_CONFIGS = {
    "resnet50": _resnet50,        # ResNet-50 imgs/sec (vision config)
    "bert_base": _bert_base,      # ERNIE/BERT encoder config
    "gpt": _gpt,                  # GPT-3 1.3B pretraining family
    "ppocr_crnn": _ppocr_crnn,    # PP-OCR conv+RNN config
    "gpt_moe": _gpt_moe,          # GPT-MoE expert-parallel config
}


def _gpt_decode():
    """The SERVING config: the fused multi-step decode loop
    (PagedGPTDecoder.decode_multi, K=4 device-resident ticks) captured
    via analysis_program(k=4) — not an nn.Layer forward, so it lives in
    PROGRAM_CONFIGS (no tuning manifest: there is nothing to remat in a
    decode tick). The SERVE-HOST-SYNC-DECODE rule gates it: zero host
    transfers inside the loop, KV-cache donation kept."""
    paddle = _fresh()
    from paddle_tpu.models import GPT, gpt_tiny
    from paddle_tpu.models import gpt as gpt_mod
    from paddle_tpu.serving import PagedGPTDecoder
    cfg = gpt_tiny(max_seq_len=64, dtype="float32", remat=False)
    model = GPT(cfg)
    model.eval()
    dec = PagedGPTDecoder(model, num_pages=16, page_size=16, max_batch=2)
    program = dec.analysis_program(k=4)
    ctx = AnalysisContext(
        name="gpt_decode",
        # the ragged attention's gather/head reorders ride with the
        # dense model's by-design attention transposes
        allowed_activation_transposes=gpt_mod.ATTENTION_TRANSPOSES
        + RAGGED_ATTENTION_TRANSPOSES,
        expect_collectives=False,
        extra={"serving_decode": True})
    return program, ctx, PagedGPTDecoder._decode_multi_step


def _gpt_train_multi():
    """The fused multi-step TRAINING config: `Trainer.step_multi`'s
    N=4 scan over a leading-stacked batch (donated params/opt-state/
    consts carry, [N] lr vector, unfetched [N] loss output) captured
    via `Trainer.analysis_program(batch, n=4)` — a PROGRAM config like
    gpt_decode (the capture is a whole train step, not a Layer
    forward; no tuning manifest — the remat advisor already covers the
    single-step twin). The HOST-SYNC-TRAIN rule gates it: zero host
    transfers inside the scan, donated carry, a real device loop."""
    paddle = _fresh()
    from paddle_tpu.distributed.trainer import Trainer
    from paddle_tpu.models import GPT, GPTPretrainingCriterion, gpt_tiny
    from paddle_tpu.models import gpt as gpt_mod
    cfg = gpt_tiny(max_seq_len=32, dtype="float32", remat=False)
    model = GPT(cfg)
    model.train()
    crit = GPTPretrainingCriterion()

    def loss_fn(m, batch):
        logits = m(paddle.to_tensor(batch["input_ids"]))
        return crit(logits, paddle.to_tensor(batch["labels"]))

    opt = paddle.optimizer.AdamW(learning_rate=1e-3)
    trainer = Trainer(model, opt, loss_fn)
    batch = {"input_ids": jnp.zeros((2, 32), jnp.int32),
             "labels": jnp.zeros((2, 32), jnp.int32)}
    program = trainer.analysis_program(batch, n=4)
    ctx = AnalysisContext(
        name="gpt_train_multi",
        # backward pass: the weight-grad matmul (x^T . dy) flips one
        # 2-D operand — by-design in every train step, rides with the
        # dense model's attention transposes
        allowed_activation_transposes=gpt_mod.ATTENTION_TRANSPOSES
        + (r"dims = \[1, 0\] : \(tensor<\d+x\d+xf32>\)",),
        expect_collectives=False,
        extra={"train_multi": True})
    return program, ctx, Trainer._build_multi


def _gpt_decode_prefix():
    """The PREFIX-CACHE serving config: the PACKED suffix-prefill
    program (`PagedGPTDecoder._prefill_packed_step` — one flat token
    stream for a whole admission batch, bucketed by total token count;
    W=16 sizes the trace's bucket) captured via
    `analysis_program(prefix_w=16)`, plus a page LEDGER committed from
    a real TIERED shared-prefix workload: a full-hit copy-on-write, a
    pool-pressure eviction that SPILLS to a `HostKVTier`, and a
    host-only chain RESTORED back into the pool — so the committed
    ledger carries host-tier rows (a restored entry with its
    device-twin backref and a host-only spilled entry) next to the
    parked/shared device rows.  Gated by SERVE-HOST-SYNC-DECODE (zero
    host transfers, donated KV pool — the chunked prefill is part of
    the serving hot path) and by MEM-PAGE-REFCOUNT (the ledger audit:
    refcounted sharing frees every page exactly once, and a host
    entry's device twin is never on the free list)."""
    import numpy as np
    paddle = _fresh()
    from paddle_tpu.models import GPT, gpt_tiny
    from paddle_tpu.models import gpt as gpt_mod
    from paddle_tpu.serving import (ContinuousBatchingEngine,
                                    HostKVTier, PagedGPTDecoder,
                                    PrefixCache)
    cfg = gpt_tiny(max_seq_len=64, dtype="float32", remat=False)
    model = GPT(cfg)
    model.eval()
    # 3 allocatable pages: each request needs 2, each base block parks
    # 1 — the third distinct base forces an eviction (spill), and
    # re-referencing the first base restores its host-only chain
    dec = PagedGPTDecoder(model, num_pages=4, page_size=16, max_batch=2)
    eng = ContinuousBatchingEngine(
        dec, max_new_tokens=4, k_max=2, tier_policy="restore",
        prefix_cache=PrefixCache(16, salt=dec.cache_fingerprint(),
                                 tier=HostKVTier()))
    b1 = list(range(1, 17))              # full shareable blocks
    b2 = list(range(31, 47))
    b3 = list(range(51, 67))
    for prompt in (b1 + [21, 22, 23],    # miss + insert
                   b1,                   # FULL hit -> copy-on-write
                   b2 + [24],            # second template parks
                   b3 + [25],            # pressure: evicts+SPILLS b1
                   b1 + [26]):           # host-only chain -> RESTORE
        eng.submit(np.asarray(prompt, np.int32))
        eng.run()
    assert eng.stats.tier_spills and eng.stats.tier_restores, \
        "tiered ledger workload lost its spill/restore shape"
    program = dec.analysis_program(prefix_w=16)
    ctx = AnalysisContext(
        name="gpt_decode_prefix",
        # the chunked body's ragged-attention reorders ride with the
        # dense model's by-design attention transposes (same exemptions
        # as gpt_decode — one shared body)
        allowed_activation_transposes=gpt_mod.ATTENTION_TRANSPOSES
        + RAGGED_ATTENTION_TRANSPOSES,
        expect_collectives=False,
        extra={"serving_decode": True,
               "page_ledger": eng.page_ledger()})
    return program, ctx, PagedGPTDecoder._prefill_packed_step


def _gpt_decode_ragged():
    """The RAGGED serving config: the PACKED mixed chunked-prefill +
    decode horizon program (`PagedGPTDecoder._packed_multi_step`, K=4
    ticks over the flat [total_new_tokens] stream — the pow2 bucket of
    one w=8 chunk row next to S-1 decode rows; the per-row chunk cap w
    rides as a traced input) captured via `analysis_program(ragged=(4,
    8))`, plus a SCHEDULING TRACE committed from a real
    long-prompt-arrives-mid-stream workload (a short request decoding
    while a 40-token prompt streams into the same horizons as chunks).
    Gated by SERVE-HOST-SYNC-DECODE (zero host transfers inside the
    fused mixed scan, donated KV pool, a real device loop) and by
    SERVE-PREFILL-STALL (the trace must contain NO host-blocking
    prefill dispatch while decode slots run — the stall the ragged
    scheduler deletes)."""
    import numpy as np
    paddle = _fresh()
    from paddle_tpu.models import GPT, gpt_tiny
    from paddle_tpu.models import gpt as gpt_mod
    from paddle_tpu.serving import ContinuousBatchingEngine, PagedGPTDecoder
    cfg = gpt_tiny(max_seq_len=64, dtype="float32", remat=False)
    model = GPT(cfg)
    model.eval()
    dec = PagedGPTDecoder(model, num_pages=16, page_size=16, max_batch=2)
    eng = ContinuousBatchingEngine(dec, max_new_tokens=6, k_max=4,
                                   chunk_tokens=8)
    eng.submit(np.arange(1, 6, dtype=np.int32))          # short, decodes
    eng.submit(np.arange(1, 41, dtype=np.int32))         # long, chunks in
    eng.run()
    program = dec.analysis_program(ragged=(4, 8))
    ctx = AnalysisContext(
        name="gpt_decode_ragged",
        # the ragged page-scan attention's gather/head reorders ride
        # with the dense model's by-design attention transposes
        allowed_activation_transposes=gpt_mod.ATTENTION_TRANSPOSES
        + RAGGED_ATTENTION_TRANSPOSES,
        expect_collectives=False,
        extra={"serving_decode": True,
               "serve_schedule": eng.serve_schedule()})
    return program, ctx, PagedGPTDecoder._packed_multi_step


def _gpt_decode_kv8():
    """The INT8-KV serving config: the fused K=4 decode loop over an
    int8 KV pool with per-token f32 scale planes (`kv_quant="int8"` —
    the pool's byte stream behind the decode roofline halves, which is
    what `step_hbm_bytes`/`decode_horizon` re-price). Gated by the
    serving rules gpt_decode carries (SERVE-HOST-SYNC-DECODE: zero host
    transfers, donated pool — now FOUR cache leaves: pages + scale
    planes for K and V), by the new kv-quant rules
    (DTYPE-KV-SCALE-WIDTH: scale planes exactly f32;
    DTYPE-KV-DEQUANT-HBM: no full-pool dequantization materialized in
    HBM — dequant stays inside the shared per-page attention update),
    and by MEM-PAGE-REFCOUNT over a page ledger committed from a real
    shared-prefix int8 workload including a full-hit copy-on-write
    (CoW moves page bytes AND scale rows together)."""
    import numpy as np
    paddle = _fresh()
    from paddle_tpu.models import GPT, gpt_tiny
    from paddle_tpu.models import gpt as gpt_mod
    from paddle_tpu.serving import (ContinuousBatchingEngine,
                                    PagedGPTDecoder, PrefixCache)
    cfg = gpt_tiny(max_seq_len=64, dtype="float32", remat=False)
    model = GPT(cfg)
    model.eval()
    dec = PagedGPTDecoder(model, num_pages=16, page_size=16, max_batch=2,
                          kv_quant="int8")
    eng = ContinuousBatchingEngine(
        dec, max_new_tokens=4, k_max=2,
        prefix_cache=PrefixCache(16, salt=dec.cache_fingerprint()))
    base = list(range(1, 17))            # one full shareable block
    for tail in ([21, 22, 23], []):      # miss+insert, then a FULL hit
        eng.submit(np.asarray(base + tail, np.int32))
        eng.run()
    program = dec.analysis_program(k=4)
    ctx = AnalysisContext(
        name="gpt_decode_kv8",
        # the shared ragged-attention reorders, plus the int8 pool's
        # per-page scale-plane gather layout move [n,MP,ps]->[MP,n,ps]
        allowed_activation_transposes=gpt_mod.ATTENTION_TRANSPOSES
        + RAGGED_ATTENTION_TRANSPOSES + (r"dims = \[1, 0, 2\]",),
        expect_collectives=False,
        extra={"serving_decode": True,
               "kv_quant": "int8",
               # one per-layer [P, ps, H, D] pool tensor: a convert of
               # this many int8 elements to a wide float IS the
               # dequantized pool landing in HBM
               "kv_pool_block_elems": (dec.num_pages * dec.page_size *
                                       cfg.num_heads * cfg.head_dim),
               "page_ledger": eng.page_ledger()})
    return program, ctx, PagedGPTDecoder._decode_multi_step


def _gpt_decode_kv4():
    """The INT4-KV serving config: the fused K=4 decode loop over a
    nibble-packed int4 pool with per-GROUP f32 scale planes
    (`kv_quant="int4"` — uint8 pages [L,P,ps,PB] + scales [L,P,ps,G];
    the pool's byte stream behind the decode roofline drops ~4x vs
    bf16). Same gate set as gpt_decode_kv8, re-proven on the packed
    layout: SERVE-HOST-SYNC-DECODE (zero host transfers, four donated
    cache leaves), DTYPE-KV-SCALE-WIDTH (group-scale planes exactly
    f32), DTYPE-KV-DEQUANT-HBM (the nibble unpack's int8->f32 convert
    stays per-page inside the shared attention update — a full-pool
    dequant materialized in HBM is the defect), and MEM-PAGE-REFCOUNT
    over a page ledger committed from a real shared-prefix int4
    workload including a full-hit copy-on-write (CoW moves nibble
    bytes AND group-scale rows together)."""
    import numpy as np
    paddle = _fresh()
    from paddle_tpu.models import GPT, gpt_tiny
    from paddle_tpu.models import gpt as gpt_mod
    from paddle_tpu.serving import (ContinuousBatchingEngine,
                                    PagedGPTDecoder, PrefixCache)
    cfg = gpt_tiny(max_seq_len=64, dtype="float32", remat=False)
    model = GPT(cfg)
    model.eval()
    dec = PagedGPTDecoder(model, num_pages=16, page_size=16, max_batch=2,
                          kv_quant="int4")
    eng = ContinuousBatchingEngine(
        dec, max_new_tokens=4, k_max=2,
        prefix_cache=PrefixCache(16, salt=dec.cache_fingerprint()))
    base = list(range(1, 17))            # one full shareable block
    for tail in ([21, 22, 23], []):      # miss+insert, then a FULL hit
        eng.submit(np.asarray(base + tail, np.int32))
        eng.run()
    program = dec.analysis_program(k=4)
    ctx = AnalysisContext(
        name="gpt_decode_kv4",
        # the shared ragged-attention reorders, plus the int4 pool's
        # page gathers: packed nibbles and group scales are rank-4
        # [n,MP,ps,X] -> [MP,n,ps,X] layout moves (X = PB or G)
        allowed_activation_transposes=gpt_mod.ATTENTION_TRANSPOSES
        + RAGGED_ATTENTION_TRANSPOSES + (r"dims = \[1, 0, 2, 3\]",),
        expect_collectives=False,
        extra={"serving_decode": True,
               "kv_quant": "int4",
               # one per-layer [P, ps, H, D] pool's worth of ELEMENTS:
               # the packed payload holds 2*PB >= H*D nibbles per
               # token, so a convert of this many unpacked elements to
               # a wide float IS the dequantized pool landing in HBM
               # (legit per-page converts stay n*ps*2*PB — far under)
               "kv_pool_block_elems": (dec.num_pages * dec.page_size *
                                       cfg.num_heads * cfg.head_dim),
               "page_ledger": eng.page_ledger()})
    return program, ctx, PagedGPTDecoder._decode_multi_step


def _gpt_decode_mt():
    """The MULTI-TENANT serving config (serving.tenancy): the PACKED
    mixed horizon program WITH the multi-LoRA adapter gather —
    `_packed_multi_step` over a decoder carrying an attached 2-adapter
    bank, so the trace includes the per-token low-rank delta
    (`_lora_delta`) and the `aids` input — captured via
    `analysis_program(ragged=(4, 8))`, plus a page LEDGER and a
    scheduling trace committed from a REAL preempting multi-tenant
    workload: two throughput-tier requests on different adapters fill
    both slots, a latency-tier request arrives mid-stream, preempts a
    victim by page-spill (its blocks park in the prefix cache), and
    the ledger is captured at a sync where the preemption has landed
    and slots are live — so the committed ledger carries
    `slot_adapters` rows (the MEM-PAGE-REFCOUNT cross-variant
    aliasing check runs against real data) next to the parked victim
    blocks. Gated by SERVE-HOST-SYNC-DECODE (zero host transfers in
    the adapter-gather scan, donated KV pool), SERVE-PREFILL-STALL
    (preemption must not reintroduce a blocking prefill), and
    MEM-PAGE-REFCOUNT."""
    import numpy as np
    paddle = _fresh()
    from paddle_tpu.models import GPT, gpt_tiny
    from paddle_tpu.models import gpt as gpt_mod
    from paddle_tpu.serving import (SLO_LATENCY, SLO_THROUGHPUT,
                                    PagedGPTDecoder, PrefixCache,
                                    TenantEngine, make_lora_bank)
    cfg = gpt_tiny(max_seq_len=64, dtype="float32", remat=False)
    model = GPT(cfg)
    model.eval()
    # 6 allocatable pages: two 2-page throughput requests occupy both
    # slots, the 3-page latency arrival can only be served by
    # preempting a victim (slot exhaustion + page pressure)
    dec = PagedGPTDecoder(model, num_pages=7, page_size=16, max_batch=2)
    dec.attach_adapters(make_lora_bank(cfg, 2, rank=4, seed=5))
    eng = TenantEngine(
        dec, max_new_tokens=6, k_max=2,
        prefix_cache=PrefixCache(16, salt=dec.cache_fingerprint()))
    rng = np.random.RandomState(3)
    V = cfg.vocab_size
    lat_prompt = rng.randint(0, V, 36).astype(np.int32)
    eng.submit(rng.randint(0, V, 20).astype(np.int32), tenant="batch",
               slo=SLO_THROUGHPUT, adapter=1)
    eng.submit(rng.randint(0, V, 20).astype(np.int32), tenant="batch",
               slo=SLO_THROUGHPUT, adapter=2)
    cap = {}

    def on_sync(e):
        if "lat" not in cap and e.stats.tokens >= 2:
            cap["lat"] = e.submit(lat_prompt, tenant="chat",
                                  slo=SLO_LATENCY)
        if "ledger" not in cap and e.stats.preemptions and \
                any(r is not None for r in e._slot_req):
            cap["ledger"] = e.page_ledger()

    eng.run(on_sync=on_sync)
    assert eng.stats.preemptions and eng.stats.resumes, \
        "multi-tenant ledger workload lost its preemption shape"
    assert cap.get("ledger") and cap["ledger"]["slot_adapters"], \
        "ledger capture missed the live multi-adapter window"
    program = dec.analysis_program(ragged=(4, 8))
    ctx = AnalysisContext(
        name="gpt_decode_mt",
        # the shared ragged-attention reorders ride with the dense
        # model's by-design attention transposes (same body as
        # gpt_decode_ragged; the adapter gather adds none)
        allowed_activation_transposes=gpt_mod.ATTENTION_TRANSPOSES
        + RAGGED_ATTENTION_TRANSPOSES,
        expect_collectives=False,
        extra={"serving_decode": True,
               "page_ledger": cap["ledger"],
               "serve_schedule": eng.serve_schedule()})
    return program, ctx, PagedGPTDecoder._packed_multi_step


def _gpt_decode_fleet():
    """The FLEET serving config (serving.fleet): the ragged mixed
    horizon program served through a `FleetRouter` over TWO engine
    replicas sharing ONE file-backed `SharedHostKVTier`, captured with
    a page LEDGER from a replica whose pool overflowed into the
    shared tier mid-run — so the committed ledger's `host` rows are
    SHARED-tier rows (`"page": None`: a cross-process tier holds no
    device-twin backrefs, the audit must accept ownerless host
    entries) next to live slots. The workload is real fleet churn:
    three 2-block templates route by prefix affinity (pigeonhole
    lands >=2 on one replica), the 6-allocatable-page pool can't park
    both next to active slots, evictions spill to the shared tier,
    and a second admission round restores from it (asserted). Gated
    by SERVE-HOST-SYNC-DECODE, SERVE-PREFILL-STALL and
    MEM-PAGE-REFCOUNT like every serving capture; its determinism
    manifest additionally pins the fleet thread/lock discipline
    (analysis.threads covers serving/fleet.py)."""
    import tempfile

    import numpy as np
    paddle = _fresh()
    from paddle_tpu.models import GPT, gpt_tiny
    from paddle_tpu.models import gpt as gpt_mod
    from paddle_tpu.serving import (FleetRouter, PagedGPTDecoder,
                                    PrefixCache, SharedHostKVTier,
                                    TenantEngine)
    cfg = gpt_tiny(max_seq_len=64, dtype="float32", remat=False)
    model = GPT(cfg)
    model.eval()
    tier_dir = tempfile.mkdtemp(prefix="gpt_decode_fleet_tier_")
    engines = []
    for _ in range(2):
        dec = PagedGPTDecoder(model, num_pages=7, page_size=16,
                              max_batch=2)
        tier = SharedHostKVTier(tier_dir, fingerprint=dec)
        engines.append(TenantEngine(
            dec, max_new_tokens=6, k_max=2, tier_policy="restore",
            prefix_cache=PrefixCache(16, salt=dec.cache_fingerprint(),
                                     tier=tier)))
    router = FleetRouter(engines)
    rng = np.random.RandomState(3)
    V = cfg.vocab_size
    templates = [rng.randint(0, V, 32).tolist() for _ in range(3)]

    def round_of(seed):
        # two requests per template: the home replica of a doubled-up
        # template must evict parked blocks to admit the second wave,
        # which is what pushes them through the shared tier
        r = np.random.RandomState(seed)
        return [t + r.randint(0, V, 4).tolist()
                for t in templates for _ in range(2)]

    for p in round_of(11):
        router.submit(np.asarray(p, np.int32))
    cap = {}

    def on_sync(rt, i, eng):
        # the live window: this replica has spilled into the shared
        # tier AND still holds slots — the committed ledger carries
        # shared host rows next to live ownership
        if "ledger" not in cap and eng.stats.tier_spills and \
                any(r is not None for r in eng._slot_req):
            cap["ledger"] = eng.page_ledger()
            cap["schedule"] = eng.serve_schedule()
            cap["replica"] = i

    router.run(on_sync=on_sync, parallel=False)
    for p in round_of(12):                # re-admission: restores
        router.submit(np.asarray(p, np.int32))
    router.run(on_sync=on_sync, parallel=False)
    tier = engines[0].cache.tier
    merged = router.merged_stats()
    assert tier.n_entries and merged.tier_spills, \
        "fleet ledger workload lost its shared-tier spill shape"
    assert merged.tier_restores and not merged.tier_recomputes, \
        "fleet re-admission round did not restore from the shared tier"
    assert cap.get("ledger") and cap["ledger"].get("host"), \
        "ledger capture missed the live shared-tier window"
    dec = engines[cap["replica"]].d
    program = dec.analysis_program(ragged=(4, 8))
    ctx = AnalysisContext(
        name="gpt_decode_fleet",
        allowed_activation_transposes=gpt_mod.ATTENTION_TRANSPOSES
        + RAGGED_ATTENTION_TRANSPOSES,
        expect_collectives=False,
        extra={"serving_decode": True,
               "page_ledger": cap["ledger"],
               "serve_schedule": cap["schedule"],
               "fleet": {"replicas": 2,
                         "tier_entries": tier.n_entries,
                         "tier_bytes": tier.bytes_used,
                         "tier_restores": int(merged.tier_restores)}})
    return program, ctx, PagedGPTDecoder._packed_multi_step


TP_OVERLAP_SIZES = dict(B=2, L=512, H=1024, F=4096, head_dim=64)
TP_OVERLAP_AXIS = 4


def _tp_overlap_block(x, wqkv, wproj, w1, w2, n_chunks=4, impl="ring"):
    """Per-device body of ONE tensor-parallel GPT block — the two
    convicted row-parallel sites (attention proj, fc2) go through
    `ops.overlap.chunked_matmul_all_reduce`, so the capture carries the
    REAL decomposed ring the Schedule Doctor prices: per-chunk matmul
    tiles interleaved with single-hop collective_permutes instead of
    one bulk psum at the end.  `impl="bulk"` is the serial twin the
    COLL-SERIALIZED red test captures."""
    import jax
    from ..ops.overlap import chunked_matmul_all_reduce
    hd = TP_OVERLAP_SIZES["head_dim"]
    B, L, _ = x.shape
    qkv = x @ wqkv                          # column-parallel: local
    q, k, v = jnp.split(qkv, 3, axis=-1)
    hp = q.shape[-1] // hd                  # this device's heads

    def heads(t):
        return t.reshape(B, L, hp, hd).transpose(0, 2, 1, 3)
    q, k, v = heads(q), heads(k), heads(v)
    s = jax.nn.softmax((q @ k.transpose(0, 1, 3, 2)) / hd ** 0.5,
                       axis=-1)
    a = (s @ v).transpose(0, 2, 1, 3).reshape(B, L, hp * hd)
    y = chunked_matmul_all_reduce(a, wproj, "tp", n_chunks=n_chunks,
                                  impl=impl)
    h = jax.nn.gelu(y @ w1)                 # column-parallel: local
    return chunked_matmul_all_reduce(h, w2, "tp", n_chunks=n_chunks,
                                     impl=impl)


def gpt_tp_overlap_program(impl="ring", n_chunks=4):
    """LoweredProgram of the shard_map'd tp block above (tp=4 over the
    first 4 local devices; B=2 L=512 H=1024 F=4096 bf16 puts the MXU
    leg at ~2x the wire leg, so a hiding schedule has headroom). Also
    the front door for the bulk serial twin the red/green schedule
    test A/Bs against — same trace, impl flipped."""
    import functools

    import jax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from ..distributed.mesh import build_mesh, compat_shard_map
    from .lowering import LoweredProgram, tree_arg_infos
    if len(jax.devices()) < TP_OVERLAP_AXIS:
        raise RuntimeError(
            f"gpt_tp_overlap needs {TP_OVERLAP_AXIS} local devices for "
            "its tp mesh — run under XLA_FLAGS="
            "--xla_force_host_platform_device_count=8 (the test env "
            "default)")
    _fresh()
    mesh = build_mesh(tp=TP_OVERLAP_AXIS,
                      devices=jax.devices()[:TP_OVERLAP_AXIS])
    sz = TP_OVERLAP_SIZES
    B, L, H, F = sz["B"], sz["L"], sz["H"], sz["F"]
    args = {"x": jnp.zeros((B, L, H), jnp.bfloat16),
            "wqkv": jnp.zeros((H, 3 * H), jnp.bfloat16),
            "wproj": jnp.zeros((H, H), jnp.bfloat16),
            "w1": jnp.zeros((H, F), jnp.bfloat16),
            "w2": jnp.zeros((F, H), jnp.bfloat16)}
    specs = {"x": P(), "wqkv": P(None, "tp"), "wproj": P("tp", None),
             "w1": P(None, "tp"), "w2": P("tp", None)}
    body = functools.partial(_tp_overlap_block, n_chunks=n_chunks,
                             impl=impl)
    f = compat_shard_map(body, mesh,
                         in_specs=tuple(specs[k] for k in args),
                         out_specs=P(), axis_names={"tp"}, check=False)
    shardings = tuple(NamedSharding(mesh, specs[k]) for k in args)
    traced = jax.jit(f, in_shardings=shardings).trace(*args.values())
    infos = []
    for (name, a), sh in zip(args.items(), shardings):
        role = "batch" if name == "x" else "param"
        infos += tree_arg_infos(a, role, prefix=name, shardings=sh)
    return LoweredProgram(traced.lower().as_text(), jaxpr=traced.jaxpr,
                          name=f"gpt_tp_overlap_{impl}",
                          arg_infos=infos)


def _gpt_tp_overlap():
    """The OVERLAPPED tensor-parallel config: the shard_map'd GPT block
    whose two row-parallel matmuls ride the chunked collective-matmul
    ring (ops/overlap.py) — the program PR 17's tentpole exists to
    produce. Its committed schedule manifest pins the wire-hiding
    fraction the bulk twin can't reach (the twin's two psums sit alone
    on the critical path: COLL-SERIALIZED red), and the collective/
    sharding passes account the per-chunk permutes' wire honestly."""
    from paddle_tpu.models import gpt as gpt_mod
    program = gpt_tp_overlap_program(impl="ring", n_chunks=4)
    program.name = "gpt_tp_overlap"
    ctx = AnalysisContext(
        name="gpt_tp_overlap",
        # the attention head split/merge transposes are the dense
        # model's by-design moves
        allowed_activation_transposes=gpt_mod.ATTENTION_TRANSPOSES,
        expect_collectives=True,
        mesh_axes={"tp": TP_OVERLAP_AXIS},
        # the ring IS made of collective_permutes by design — they are
        # the decomposed transfer, not a GSPMD spec-mismatch reshard
        allowed_resharding=(r"collective_permute",),
        # the block activations ([B,L,H] bf16, ~2 MiB) replicate across
        # tp by design (sequence stays whole); only model state is tp-
        # sharded here, so lift the replication bar above them
        replicated_bytes_threshold=8 << 20,
        extra={"tp_overlap": True})
    return program, ctx, _tp_overlap_block


# configs whose builder yields a READY LoweredProgram (serving decode
# loops and other non-Layer captures): builder() ->
# (LoweredProgram, AnalysisContext, source_fn). They ride the same
# lint/memory manifest + CI plumbing as BASELINE_CONFIGS but skip the
# tuning manifests (no grad program to replay).
PROGRAM_CONFIGS = {
    "gpt_decode": _gpt_decode,       # fused multi-step serving decode
    "gpt_decode_prefix": _gpt_decode_prefix,   # chunked prefix-cache prefill
    "gpt_decode_ragged": _gpt_decode_ragged,   # mixed chunked-prefill+decode
    "gpt_decode_kv8": _gpt_decode_kv8,         # int8 KV pool decode loop
    "gpt_decode_kv4": _gpt_decode_kv4,         # int4 nibble-packed KV pool
    "gpt_decode_mt": _gpt_decode_mt,           # multi-tenant + multi-LoRA
    "gpt_decode_fleet": _gpt_decode_fleet,     # fleet + shared host KV tier
    "gpt_train_multi": _gpt_train_multi,   # fused multi-step train scan
    "gpt_tp_overlap": _gpt_tp_overlap,     # chunked collective-matmul tp block
}

# configs whose schedule manifest is committed (schedule_manifests/):
# the five BASELINE model forwards plus the fused train scan — the
# programs whose step time the overlap-aware roofline prices — plus
# gpt_decode_mt: the one serving capture with a schedule manifest (the
# multi-tenant horizon is the program whose composition the tenancy
# scheduler prices, so its critical-path/overlap numbers are pinned
# even though a decode tick carries no collective to hide). The other
# serving captures stay excluded: their schedule estimate adds
# nothing the memory manifests don't already pin.
# ... plus gpt_tp_overlap: the chunked collective-matmul capture whose
# wire-hiding fraction IS the number the manifest exists to pin (the
# one SCHEDULE config with a real collective stream).
SCHEDULE_CONFIGS = tuple(BASELINE_CONFIGS) + ("gpt_train_multi",
                                              "gpt_decode_mt",
                                              "gpt_tp_overlap")

# configs whose determinism manifest is committed
# (determinism_manifests/): every SERVING capture — the programs whose
# byte-identical-stream invariant the Determinism Doctor proves
# statically (taint-canonical pool writes, clean RNG key derivation,
# no unprovable scatter overlap, no donated-alias outputs, and the
# host-side thread/lock discipline).  The training and tp-overlap
# captures stay excluded: no pool buffers, nothing for the pass to
# pin.  The SpeculativeEngine verify window is deliberately NOT here:
# it is the documented expected red (tests/test_determinism_lint.py
# pins it red until commit-on-accept lands).
DETERMINISM_CONFIGS = ("gpt_decode", "gpt_decode_prefix",
                       "gpt_decode_ragged", "gpt_decode_kv8",
                       "gpt_decode_kv4", "gpt_decode_mt",
                       "gpt_decode_fleet")


def build_config(name):
    try:
        builder = BASELINE_CONFIGS[name]
    except KeyError:
        raise KeyError(f"unknown BASELINE config {name!r}; known: "
                       f"{sorted(BASELINE_CONFIGS)}")
    return builder()


def lowered_program(name):
    """(LoweredProgram, AnalysisContext, forward fn) for a BASELINE or
    PROGRAM config — lowered once per process (the lint gate's time
    budget rides on this cache). The context is a fresh copy per call:
    consumers set run-local fields on it (manifest, mesh_axes) and a
    shared instance would leak one run's manifest into the next —
    e.g. baking transition-run DRIFT findings into a regenerated
    manifest."""
    import dataclasses
    if name not in _CACHE:
        if name in PROGRAM_CONFIGS:
            _CACHE[name] = PROGRAM_CONFIGS[name]()
        else:
            from .lowering import lower_layer
            model, examples, ctx = build_config(name)
            program = lower_layer(model, *examples, name=name)
            _CACHE[name] = (program, ctx, type(model).forward)
    program, ctx, fwd = _CACHE[name]
    return program, dataclasses.replace(ctx), fwd


def forward_fn(name):
    return lowered_program(name)[2]


def tuning_report(name):
    """The remat advisor's AutotuneReport for a BASELINE config —
    what-if peak + recompute per policy over a fresh seeded grad trace,
    roofline-priced against the fixed v5e spec (deterministic: this is
    what tuning_manifests/<name>.json pins). Cached per process like
    the lowerings."""
    if name not in _TUNING_CACHE:
        from .autotune import autotune_layer
        model, examples, ctx = build_config(name)
        _TUNING_CACHE[name] = autotune_layer(model, *examples,
                                             chip="v5e", name=name)
    return _TUNING_CACHE[name]
