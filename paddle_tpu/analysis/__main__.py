"""CLI: ``python -m paddle_tpu.analysis [config ...] [options]``

Runs the full analyzer catalog over BASELINE configs (default: all
five) or any custom ``module.path:builder`` spec whose builder returns
``(model, example_arrays[, AnalysisContext])``. Prints findings, checks
drift against committed lint manifests, and with --write-manifests
regenerates them.

Exit code: 0 clean / manifest-matching, 1 any ERROR finding (the CI
gate), 2 usage problems.
"""
import argparse
import importlib
import json
import sys


def _run_spec(spec, write, as_json, no_manifest):
    from . import (AnalysisContext, PassManager, load_manifest,
                   lower_layer, write_manifest)
    from .baseline import BASELINE_CONFIGS, lowered_program

    pm = PassManager()
    if spec in BASELINE_CONFIGS:
        program, ctx, fwd = lowered_program(spec)
    else:
        if ":" not in spec:
            raise SystemExit(
                f"unknown config {spec!r} (known: "
                f"{', '.join(sorted(BASELINE_CONFIGS))}) and not a "
                "module:builder spec")
        mod_name, attr = spec.split(":", 1)
        builder = getattr(importlib.import_module(mod_name), attr)
        built = builder()
        model, examples = built[0], built[1]
        ctx = (built[2] if len(built) > 2
               else AnalysisContext(name=attr))
        program = lower_layer(model, *examples, name=ctx.name)
        fwd = type(model).forward
    if not no_manifest and not write:
        # regeneration must be idempotent: checking the OLD manifest
        # while writing the new one would bake transition-run DRIFT
        # findings into the fresh manifest
        ctx.manifest = load_manifest(ctx.name)
    report = pm.run_source(fwd, ctx)
    report.extend(pm.run(program, ctx))
    if write:
        data = write_manifest(ctx.name, program, report)
        print(f"wrote {ctx.name} manifest "
              f"({sum(data['op_counts'].values())} pinned ops)")
    if as_json:
        print(json.dumps({ctx.name: report.to_dict()}, indent=1,
                         sort_keys=True))
    else:
        print(f"== {ctx.name} ==")
        print(report if report else "clean (0 findings)")
        gs = report.metrics.get("graph-shape", {}).get("op_counts", {})
        if gs:
            print("   ops: " + ", ".join(f"{k}={v}"
                                         for k, v in sorted(gs.items())))
    return report


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="Graph Doctor: static-analyze lowered programs on "
                    "CPU (no TPU needed)")
    parser.add_argument("configs", nargs="*", default=[],
                        help="BASELINE config names (default: all) or "
                             "module.path:builder specs")
    parser.add_argument("--list", action="store_true",
                        help="list BASELINE configs and analyzers")
    parser.add_argument("--write-manifests", action="store_true",
                        help="regenerate lint_manifests/<config>.json")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable findings")
    parser.add_argument("--no-manifest-check", action="store_true",
                        help="skip drift checks against committed "
                             "manifests")
    parser.add_argument("--fail-on", choices=("error", "warning",
                                              "never"),
                        default="error",
                        help="severity that makes the exit code "
                             "nonzero (default: error)")
    args = parser.parse_args(argv)

    from . import Severity, default_catalog
    from .baseline import BASELINE_CONFIGS

    if args.list:
        print("BASELINE configs: " + ", ".join(sorted(BASELINE_CONFIGS)))
        print("analyzers: " + ", ".join(default_catalog()))
        return 0

    names = args.configs or list(BASELINE_CONFIGS)
    worst = None
    for name in names:
        report = _run_spec(name, args.write_manifests, args.json,
                           args.no_manifest_check)
        sev = report.max_severity
        if sev is not None and (worst is None or sev > worst):
            worst = sev
    if args.fail_on == "never" or worst is None:
        return 0
    gate = (Severity.ERROR if args.fail_on == "error"
            else Severity.WARNING)
    return 1 if worst >= gate else 0


if __name__ == "__main__":
    sys.exit(main())
