"""CLI: ``python -m paddle_tpu.analysis [config ...] [options]``

Runs the full analyzer catalog over BASELINE configs (default: all
five, plus the PROGRAM configs — ready-made LoweredPrograms like the
``gpt_decode`` fused serving loop) or any custom ``module.path:builder``
spec whose builder returns
``(model, example_arrays[, AnalysisContext])``. Prints findings, checks
drift against committed lint AND memory manifests, and with
--write-manifests regenerates both. ``--memory`` adds the per-device
HBM breakdown (peak, args/transient split, top live tensors);
``--autotune`` prints the remat advisor's what-if table (per-policy
peak, recompute FLOPs, roofline step time — tuning_manifests/*.json
pins it); ``--schedule`` prints the overlap-aware schedule breakdown
(critical path, wire-hiding fraction, COLL-SERIALIZED evidence —
schedule_manifests/*.json pins it); ``--propagation`` prints the
GSPMD fixed-point pass summary (exact/fallback coverage, XLA
annotation agreement, divergences — propagation_manifests/*.json pins
it); ``--determinism`` prints the Determinism Doctor summary
(canonical pool writes, RNG key provenance, scatter-overlap proofs,
thread-discipline counters — determinism_manifests/*.json pins it for
the serving configs); ``--check`` regenerates every committed
manifest in-memory (lint, memory, tuning, schedule, propagation AND
determinism) and fails on any drift — the CI answer to stale
manifests.

Exit code: 0 clean / manifest-matching, 1 any ERROR finding or drift
(the CI gate), 2 usage problems.
"""
import argparse
import importlib
import json
import os
import sys

# The multi-device capture configs (gpt_tp_overlap's tp=4 mesh) need
# virtual host devices; mirror tests/conftest.py so the bare CLI and
# the CI `--check` gate see the same meshes as the tier-1 suite. XLA
# only reads the flag at backend init, which hasn't happened yet at
# CLI start even though the package import pulled in jax.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()


def _build_spec(spec):
    """(program, ctx, fwd, built) for a BASELINE/PROGRAM name or a
    module:builder spec. `built` is the custom spec's (model, examples)
    so later stages (--autotune) reuse the SAME build instead of
    calling the builder a second time; None for named configs (their
    builds are process-cached in baseline.py)."""
    from . import AnalysisContext, lower_layer
    from .baseline import (BASELINE_CONFIGS, PROGRAM_CONFIGS,
                           lowered_program)
    if spec in BASELINE_CONFIGS or spec in PROGRAM_CONFIGS:
        return lowered_program(spec) + (None,)
    if ":" not in spec:
        raise SystemExit(
            f"unknown config {spec!r} (known: "
            f"{', '.join(sorted(BASELINE_CONFIGS) + sorted(PROGRAM_CONFIGS))}"
            ") and not a module:builder spec")
    mod_name, attr = spec.split(":", 1)
    builder = getattr(importlib.import_module(mod_name), attr)
    built = builder()
    model, examples = built[0], built[1]
    ctx = built[2] if len(built) > 2 else AnalysisContext(name=attr)
    program = lower_layer(model, *examples, name=ctx.name)
    return program, ctx, type(model).forward, (model, examples, ctx)


def _run_spec(spec, write, as_json, no_manifest, show_memory,
              show_autotune=False, show_schedule=False,
              show_propagation=False, show_determinism=False):
    from . import (PassManager, load_manifest, load_memory_manifest,
                   write_determinism_manifest, write_manifest,
                   write_memory_manifest, write_propagation_manifest,
                   write_schedule_manifest, write_tuning_manifest)
    from .baseline import (BASELINE_CONFIGS, DETERMINISM_CONFIGS,
                           SCHEDULE_CONFIGS)

    pm = PassManager()
    program, ctx, fwd, built = _build_spec(spec)
    if not no_manifest and not write:
        # regeneration must be idempotent: checking the OLD manifest
        # while writing the new one would bake transition-run DRIFT
        # findings into the fresh manifest
        ctx.manifest = load_manifest(ctx.name)
        ctx.memory_manifest = load_memory_manifest(ctx.name)
    report = pm.run_source(fwd, ctx)
    report.extend(pm.run(program, ctx))
    if write:
        data = write_manifest(ctx.name, program, report)
        mem = write_memory_manifest(ctx.name, report)
        prop = write_propagation_manifest(ctx.name, report)
        msg = (f"wrote {ctx.name} manifests "
               f"({sum(data['op_counts'].values())} pinned ops, "
               f"{mem['per_device_peak_bytes']} peak bytes, "
               f"prop {prop['n_exact']}/{prop['n_vars']} exact")
        if spec in SCHEDULE_CONFIGS:
            sch = write_schedule_manifest(ctx.name, report)
            msg += (f", overlap step {sch['overlap_step_us']} us "
                    f"(frac {sch['overlap_frac']})")
        if spec in DETERMINISM_CONFIGS:
            det = write_determinism_manifest(ctx.name, report)
            msg += (f", determinism "
                    f"{det['graph']['n_canonical_writes']}/"
                    f"{det['graph']['n_pool_writes']} canonical writes")
        if spec in BASELINE_CONFIGS:
            tun = write_tuning_manifest(ctx.name, _tuning_report(spec))
            msg += f", best remat={tun['best']}"
        print(msg + ")")
    if as_json:
        print(json.dumps({ctx.name: report.to_dict()}, indent=1,
                         sort_keys=True))
    else:
        print(f"== {ctx.name} ==")
        print(report if report else "clean (0 findings)")
        gs = report.metrics.get("graph-shape", {}).get("op_counts", {})
        if gs:
            print("   ops: " + ", ".join(f"{k}={v}"
                                         for k, v in sorted(gs.items())))
        if show_memory:
            _print_memory(report)
        if show_schedule:
            _print_schedule(report)
        if show_propagation:
            _print_propagation(report)
        if show_determinism:
            _print_determinism(report)
        if show_autotune:
            from .baseline import PROGRAM_CONFIGS
            if spec in PROGRAM_CONFIGS:
                print(f"(no tuning report for program config {spec}: "
                      "a decode loop has no grad step to remat)")
            else:
                print(_tuning_report(spec, built=built))
    return report


def _tuning_report(spec, built=None):
    """AutotuneReport for a BASELINE name (cached) or a module:builder
    spec. Custom specs pass their ALREADY-BUILT (model, examples[, ctx])
    through `built` so lint and tuning share one model build — without
    it the CLI used to call the user's builder twice."""
    from .baseline import BASELINE_CONFIGS, tuning_report
    if spec in BASELINE_CONFIGS:
        return tuning_report(spec)
    from . import autotune_layer
    _, attr = spec.split(":", 1)
    if built is None:
        mod_name, attr = spec.split(":", 1)
        built = getattr(importlib.import_module(mod_name), attr)()
    return autotune_layer(built[0], *built[1], name=attr)


def _print_memory(report):
    mem = report.metrics.get("memory", {})
    if not mem.get("available"):
        print("   memory: no jaxpr available")
        return
    mib = 1024.0 ** 2
    print(f"   memory: per-device peak {mem['peak_bytes'] / mib:.2f} MiB"
          f" (args {mem['args_bytes'] / mib:.2f} + transient "
          f"{mem['temp_peak_bytes'] / mib:.2f}; donated "
          f"{mem['donated_bytes'] / mib:.2f})")
    for b in mem.get("top_live", []):
        print(f"     {b['device_bytes']:>12d} B  {b['op']:<14} "
              f"{b['name']}")
    sh = report.metrics.get("sharding", {})
    if sh:
        print(f"   sharding: {sh.get('n_replicated_big', 0)} big "
              f"replicated tensor(s), wire "
              f"{sh.get('total_wire_bytes', 0)} B, "
              f"{sh.get('n_mid_program_reshards', 0)} mid-program "
              "reshard(s)")


def _print_schedule(report):
    sch = report.metrics.get("schedule", {})
    if not sch.get("available"):
        print("   schedule: no jaxpr available")
        return
    print(f"   schedule: overlap step {sch['overlap_step_us']} us "
          f"(roofline max {sch['ideal_step_us']}, serial "
          f"{sch['serial_step_us']}) — overlap_frac "
          f"{sch['overlap_frac']}, {sch['n_collectives']} "
          f"collective(s), {sch['n_serialized_collectives']} "
          "serialized")
    for n in sch.get("critical_path", [])[:8]:
        print(f"     {n['cost_us']:>10.2f} us {n['stream']:<10} "
              f"{n['source']}")


def _print_propagation(report):
    prop = report.metrics.get("propagation", {})
    if not prop.get("available"):
        print("   propagation: no jaxpr available")
        return
    print(f"   propagation: {prop['n_exact']}/{prop['n_vars']} vars "
          f"exact ({prop['n_fallback']} heuristic fallback), "
          f"{prop['n_constraints']} constraint pin(s), converged in "
          f"{prop['iterations']} sweep(s)")
    print(f"     vs XLA: {prop['n_agree']}/{prop['n_annotated']} "
          f"annotated vars agree (rate {prop['agreement_rate']}), "
          f"{prop['n_diverge']} diverge, {prop['n_unmapped']} unmapped; "
          f"{prop['n_divergences']} divergence lint(s), "
          f"{prop['n_loop_carry_reshards']} loop-carry reshard(s)")


def _print_determinism(report):
    det = report.metrics.get("determinism", {})
    if not det.get("available"):
        print("   determinism: no jaxpr available")
        return
    print(f"   determinism: {det['n_canonical_writes']}/"
          f"{det['n_pool_writes']} pool writes canonical over "
          f"{det['n_pool_buffers']} pool buffer(s), "
          f"{det['n_rng_sites']} RNG site(s); overlap pairs "
          f"{det['n_proven_disjoint']}/{det['n_overlap_pairs']} proven "
          f"disjoint; {det['n_alias_outputs']} alias output(s) of "
          f"{det['n_donated_args']} donated arg(s)")
    th = report.metrics.get("threads", {})
    if th.get("available"):
        print(f"   threads: {th['n_threaded_classes']}/"
              f"{th['n_classes']} classes threaded across "
              f"{th['n_files']} file(s), {th['n_shared_paths']} "
              f"unlocked shared path(s), {th['n_lock_attrs']} "
              "lock attr(s)")
    rules = dict(det.get("rules", ()))
    rules.update(th.get("rules", ()))
    fired = {k: v for k, v in sorted(rules.items()) if v}
    if fired:
        print("     fired: " + ", ".join(f"{k}={v}"
                                         for k, v in fired.items()))


def _check_manifests(names):
    """Regenerate every manifest in-memory (lint, memory, tuning,
    schedule AND propagation) and diff against the committed files.
    Returns the number of drifting/missing manifests (the --check CI
    mode: stale manifests fail instead of silently re-baselining)."""
    from . import (PassManager, build_determinism_manifest,
                   build_manifest, build_memory_manifest,
                   build_propagation_manifest, build_schedule_manifest,
                   build_tuning_manifest, load_determinism_manifest,
                   load_manifest, load_memory_manifest,
                   load_propagation_manifest, load_schedule_manifest,
                   load_tuning_manifest, manifest_drift)
    from .baseline import (BASELINE_CONFIGS, DETERMINISM_CONFIGS,
                           SCHEDULE_CONFIGS)

    pm = PassManager()
    n_bad = 0
    for name in names:
        program, ctx, fwd, _built = _build_spec(name)
        # no committed manifests on the context: the rebuild must see
        # exactly what --write-manifests would write
        report = pm.run_source(fwd, ctx)
        report.extend(pm.run(program, ctx))
        drift = manifest_drift(build_manifest(name, program, report),
                               load_manifest(name), path="lint")
        drift += manifest_drift(build_memory_manifest(name, report),
                                load_memory_manifest(name), path="memory")
        drift += manifest_drift(build_propagation_manifest(name, report),
                                load_propagation_manifest(name),
                                path="propagation")
        if name in SCHEDULE_CONFIGS:
            drift += manifest_drift(
                build_schedule_manifest(name, report),
                load_schedule_manifest(name), path="schedule")
        if name in DETERMINISM_CONFIGS:
            drift += manifest_drift(
                build_determinism_manifest(name, report),
                load_determinism_manifest(name), path="determinism")
        if name in BASELINE_CONFIGS:
            drift += manifest_drift(
                build_tuning_manifest(name, _tuning_report(name)),
                load_tuning_manifest(name), path="tuning")
        if drift:
            n_bad += 1
            print(f"== {name}: STALE ==")
            for line in drift:
                print(f"   {line}")
        else:
            print(f"== {name}: manifests current ==")
    if n_bad:
        print(f"{n_bad} config(s) drifted — regenerate with "
              "python -m paddle_tpu.analysis --write-manifests "
              "and review the diff")
    return n_bad


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="Graph Doctor: static-analyze lowered programs on "
                    "CPU (no TPU needed)")
    parser.add_argument("configs", nargs="*", default=[],
                        help="BASELINE config names (default: all) or "
                             "module.path:builder specs")
    parser.add_argument("--list", action="store_true",
                        help="list BASELINE configs and analyzers")
    parser.add_argument("--write-manifests", action="store_true",
                        help="regenerate lint_manifests/, "
                             "memory_manifests/ and "
                             "propagation_manifests/<config>.json")
    parser.add_argument("--check", action="store_true",
                        help="regenerate all manifests in-memory and "
                             "exit non-zero on drift (CI staleness "
                             "gate); writes nothing")
    parser.add_argument("--memory", action="store_true",
                        help="print the per-device HBM breakdown "
                             "(peak, args/transient, top live tensors)")
    parser.add_argument("--schedule", action="store_true",
                        help="print the overlap-aware schedule "
                             "breakdown (critical path, wire-hiding "
                             "fraction, serialized collectives)")
    parser.add_argument("--propagation", action="store_true",
                        help="print the GSPMD fixed-point propagation "
                             "summary (exact/fallback coverage, XLA "
                             "annotation agreement, divergences)")
    parser.add_argument("--determinism", action="store_true",
                        help="print the Determinism Doctor summary "
                             "(canonical pool writes, RNG provenance, "
                             "scatter-overlap proofs, thread lint)")
    parser.add_argument("--autotune", action="store_true",
                        help="print the remat advisor's what-if table "
                             "(per-policy peak, recompute FLOPs, "
                             "roofline step time) for each config")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable findings")
    parser.add_argument("--no-manifest-check", action="store_true",
                        help="skip drift checks against committed "
                             "manifests")
    parser.add_argument("--fail-on", choices=("error", "warning",
                                              "never"),
                        default="error",
                        help="severity that makes the exit code "
                             "nonzero (default: error)")
    args = parser.parse_args(argv)

    from . import Severity, default_catalog
    from .baseline import BASELINE_CONFIGS, PROGRAM_CONFIGS

    if args.list:
        print("BASELINE configs: " + ", ".join(sorted(BASELINE_CONFIGS)))
        print("PROGRAM configs: " + ", ".join(sorted(PROGRAM_CONFIGS)))
        print("analyzers: " + ", ".join(default_catalog()))
        return 0

    names = args.configs or \
        list(BASELINE_CONFIGS) + list(PROGRAM_CONFIGS)
    if args.check:
        return 1 if _check_manifests(names) else 0
    worst = None
    for name in names:
        report = _run_spec(name, args.write_manifests, args.json,
                           args.no_manifest_check, args.memory,
                           show_autotune=args.autotune,
                           show_schedule=args.schedule,
                           show_propagation=args.propagation,
                           show_determinism=args.determinism)
        sev = report.max_severity
        if sev is not None and (worst is None or sev > worst):
            worst = sev
    if args.fail_on == "never" or worst is None:
        return 0
    gate = (Severity.ERROR if args.fail_on == "error"
            else Severity.WARNING)
    return 1 if worst >= gate else 0


if __name__ == "__main__":
    sys.exit(main())
