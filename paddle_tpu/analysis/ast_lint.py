"""Dy2static AST linter — flags python-side tracing hazards at
conversion time, BEFORE jax tracing mangles or erases them.

The rules mirror what jit/dy2static/transformer.py actually does with
each shape (it is the authority on what converts):

  D2S-TRACED-BRANCH      if/while test reads a tensor-derived value —
                         lowers to lax.cond/while_loop; both branches
                         must bind the same variables with matching
                         tensor-ness (INFO: handled, worth knowing).
  D2S-TRACED-LOOP        for over a tensor-derived iterable — lowers to
                         lax.scan with shape-static carries (INFO).
  D2S-LOOP-TARGET-LEAK   a for target read after its loop — the r5
                         fuzzer's silent-wrong-numbers class; now
                         carried correctly by the converter, but the
                         leaked value rides a scan carry seeded with a
                         placeholder, so a 0-trip traced loop reads
                         garbage (WARNING).
  D2S-EARLY-RETURN       return before the function tail — folded into
                         both-branches-return lax.cond form (INFO).
  D2S-RETURN-IN-TRY      return inside try: NOT functionalized — a
                         traced condition around it hits the jax tracer
                         error at runtime (WARNING).
  D2S-JUMP-IN-WITH-TRY   break/continue inside with/try: same (WARNING).
  D2S-LOOP-ELSE          loop with an else clause: not functionalized
                         (WARNING).
  D2S-GLOBAL-WRITE       `global` write: the whole function is left
                         unconverted (ERROR).
  D2S-NO-SOURCE          source unavailable — linter (and converter)
                         can only fall back (WARNING).
"""
import ast
import inspect
import textwrap

from .findings import Finding, Severity
from .pass_manager import Analyzer, AnalysisContext, register_analyzer
# reuse the converter's own scope/liveness machinery so the linter and
# the transform can never disagree about what "read after the loop" is
from ..jit.dy2static.transformer import (_SCOPE_NODES, _compute_tail_reads,
                                         _reads)

__all__ = ["Dy2StaticASTLinter", "lint_function"]


def _loc(node, filename, offset=0):
    line = getattr(node, "lineno", None)
    return f"{filename}:{line + offset if line is not None else '?'}"


def _snippet(node):
    try:
        return ast.unparse(node)[:120]
    except Exception:
        return type(node).__name__


def _tainted_names(fdef):
    """Names (conservatively) derived from the function's parameters —
    the values that are tracers under jit. One forward pass per
    statement list, repeated to a fixed point so `y = x + 1; z = y * 2`
    taints z."""
    a = fdef.args
    tainted = {p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)}
    # `self`/`cls` carry config attributes (data_format, num_layers...)
    # whose reads are concrete at trace time; tainting them would flag
    # every config branch in every forward as traced
    tainted -= {"self", "cls"}
    if a.vararg:
        tainted.add(a.vararg.arg)
    if a.kwarg:
        tainted.add(a.kwarg.arg)

    def expr_tainted(e):
        return bool(_reads(e) & tainted)

    changed = True
    while changed:
        changed = False
        for n in ast.walk(fdef):
            targets = None
            if isinstance(n, ast.Assign):
                targets, value = n.targets, n.value
            elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
                targets, value = [n.target], n.value
            elif isinstance(n, ast.For):
                # target tainted iff the iterable is (range(3) is not)
                targets, value = [n.target], n.iter
            else:
                continue
            if value is None or not expr_tainted(value):
                continue
            for t in targets:
                for name_node in ast.walk(t):
                    if isinstance(name_node, ast.Name) \
                            and name_node.id not in tainted:
                        tainted.add(name_node.id)
                        changed = True
    return tainted


def _is_concrete_test(test):
    """Tests that are concrete even over traced values: identity checks
    (`x is None`, `x is not None`) and isinstance() — both resolve at
    trace time, never inside the graph."""
    if isinstance(test, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
        return True
    if isinstance(test, ast.Call) and isinstance(test.func, ast.Name) \
            and test.func.id in ("isinstance", "hasattr", "callable"):
        return True
    if isinstance(test, ast.BoolOp):
        return all(_is_concrete_test(v) for v in test.values)
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _is_concrete_test(test.operand)
    return False


def _scoped_walk(fdef):
    """Walk fdef's OWN scope only — unlike ast.walk, nested function/
    class/lambda/comprehension subtrees are pruned, so a `global` or
    `return` inside a nested helper is never misattributed to the
    forward being linted (the helper gets its own conversion, and its
    own lint, when convert_call reaches it)."""
    stack = [fdef]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, _SCOPE_NODES):
                continue
            stack.append(child)


def _in_same_scope(root, kinds, stop=()):
    """Nodes of `kinds` under root without crossing nested scopes or
    `stop` statement types."""
    out = []
    stack = list(ast.iter_child_nodes(root))
    while stack:
        n = stack.pop()
        if isinstance(n, kinds):
            out.append(n)
        if isinstance(n, _SCOPE_NODES) or isinstance(n, stop):
            continue
        stack.extend(ast.iter_child_nodes(n))
    return out


@register_analyzer
class Dy2StaticASTLinter(Analyzer):
    name = "dy2static-ast"
    kind = "source"

    def run(self, target, ctx):
        fdef, filename, offset, err = _parse_target(target)
        if fdef is None:
            return [Finding("D2S-NO-SOURCE", Severity.WARNING,
                            f"source unavailable for lint: {err}")]
        findings = list(self._lint_fdef(fdef, filename, offset))
        self.metrics = {"n_rules_fired": len(findings)}
        return findings

    def _lint_fdef(self, fdef, filename, offset=0):
        tainted = _tainted_names(fdef)
        _, after_reads = _compute_tail_reads(fdef)
        for n in _scoped_walk(fdef):
            if isinstance(n, ast.Global):
                yield Finding(
                    "D2S-GLOBAL-WRITE", Severity.ERROR,
                    "`global` write: dy2static leaves this function "
                    "entirely unconverted (traced control flow in it "
                    "will hit the jax tracer error)",
                    op=_snippet(n), location=_loc(n, filename, offset),
                    suggested_fix="pass state explicitly or use a "
                    "mutable container instead of `global`")
            elif isinstance(n, (ast.If, ast.While)):
                if _reads(n.test) & tainted \
                        and not _is_concrete_test(n.test):
                    kind = "if" if isinstance(n, ast.If) else "while"
                    yield Finding(
                        "D2S-TRACED-BRANCH", Severity.INFO,
                        f"`{kind}` over a tensor-derived condition — "
                        "lowers to lax.cond/while_loop; both paths "
                        "must bind the same variables",
                        op=_snippet(n.test), location=_loc(n, filename, offset))
                if isinstance(n, ast.While) and n.orelse:
                    yield Finding(
                        "D2S-LOOP-ELSE", Severity.WARNING,
                        "while/else is not functionalized",
                        location=_loc(n, filename, offset))
            elif isinstance(n, ast.For):
                yield from self._lint_for(n, tainted, after_reads,
                                          filename, offset)
            elif isinstance(n, ast.Return):
                if n is not fdef.body[-1]:
                    yield Finding(
                        "D2S-EARLY-RETURN", Severity.INFO,
                        "early return — functionalized by folding into "
                        "a both-branches-return lax.cond",
                        op=_snippet(n), location=_loc(n, filename, offset))
            elif isinstance(n, ast.Try):
                for r in _in_same_scope(n, ast.Return):
                    yield Finding(
                        "D2S-RETURN-IN-TRY", Severity.WARNING,
                        "return inside try is not functionalized — a "
                        "traced condition around it fails at trace "
                        "time", op=_snippet(r),
                        location=_loc(r, filename, offset),
                        suggested_fix="move the return out of the "
                        "try block")
            elif isinstance(n, (ast.With, ast.AsyncWith)):
                jumps = _in_same_scope(n, (ast.Break, ast.Continue),
                                       stop=(ast.While, ast.For))
                for j in jumps:
                    yield Finding(
                        "D2S-JUMP-IN-WITH-TRY", Severity.WARNING,
                        f"{'break' if isinstance(j, ast.Break) else 'continue'}"
                        " inside a with block is not functionalized",
                        location=_loc(j, filename, offset))

    def _lint_for(self, n, tainted, after_reads, filename,
                  offset=0):
        if _reads(n.iter) & tainted:
            yield Finding(
                "D2S-TRACED-LOOP", Severity.INFO,
                "for over a tensor-derived iterable — lowers to "
                "lax.scan with shape-static carries",
                op=_snippet(n.iter), location=_loc(n, filename, offset))
        if n.orelse:
            yield Finding(
                "D2S-LOOP-ELSE", Severity.WARNING,
                "for/else is not functionalized",
                location=_loc(n, filename, offset))
        tnames = {t.id for t in ast.walk(n.target)
                  if isinstance(t, ast.Name)}
        leaked = tnames & after_reads.get(id(n), set())
        for t in sorted(leaked):
            yield Finding(
                "D2S-LOOP-TARGET-LEAK", Severity.WARNING,
                f"loop target `{t}` is read after the loop (python "
                "leaks the final value) — carried through the "
                "conversion, but a 0-trip traced loop would observe "
                "the carry's zeros placeholder",
                op=_snippet(n.target), location=_loc(n, filename, offset),
                suggested_fix=f"bind `{t}` explicitly before/after the "
                "loop if the post-loop read is intentional")


def _parse_target(target):
    """(FunctionDef, filename, line-offset, error) for a function,
    source string, or Layer class/instance (lints its forward)."""
    fn = target
    if hasattr(fn, "forward") and not isinstance(fn, str) \
            and not inspect.isfunction(fn) and not inspect.ismethod(fn):
        fn = fn.forward
    fn = getattr(fn, "__func__", fn)
    # unwrap an already-converted function back to nothing — generated
    # code has no user source; lint the wrapped original if recorded
    offset = 0
    if isinstance(fn, str):
        src, filename = fn, "<string>"
    else:
        try:
            src = textwrap.dedent(inspect.getsource(fn))
            filename = inspect.getsourcefile(fn) or "<unknown>"
            offset = getattr(getattr(fn, "__code__", None),
                             "co_firstlineno", 1) - 1
        except (OSError, TypeError) as e:
            return None, None, 0, str(e)
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return None, None, 0, str(e)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # node linenos are relative to the dedented snippet, whose
            # first line is the same line co_firstlineno points at (the
            # first decorator when present, else the def) — so `offset`
            # alone shifts to file-absolute; subtracting node.lineno
            # would double-count decorator lines
            return node, filename, offset, None
    return None, None, 0, "no function definition found"


def lint_function(fn, context=None):
    """Standalone entry: Report of dy2static hazards for one function
    (used by to_static(lint=True) and the tests)."""
    linter = Dy2StaticASTLinter()
    linter.metrics = {}
    from .findings import Report
    report = Report()
    ctx = context or AnalysisContext(name=getattr(fn, "__name__", "fn"))
    for f in linter.run(fn, ctx) or ():
        if not f.analyzer:
            f.analyzer = linter.name
        report.add(f)
    if linter.metrics:
        report.metrics[linter.name] = linter.metrics
    return report
