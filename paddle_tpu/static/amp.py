"""Reference python/paddle/static/amp/__init__.py (fluid
mixed_precision): the static-graph AMP surface, mapped onto the eager
amp module — on TPU the precision policy is applied while TRACING (the
same trace serves eager and static/jit), so `decorate`/`fp16_guard`
delegate to amp.auto_cast machinery rather than rewriting a Program.

`cast_model_to_fp16` / `cast_parameters_to_fp16` accept a Layer (the
dygraph object our static mode traces); raw fluid Programs don't exist
here.
"""
import contextlib

from ..amp import auto_cast
from ..amp import decorate as _amp_decorate

__all__ = ["decorate", "CustomOpLists", "AutoMixedPrecisionLists",
           "OptimizerWithMixedPrecision", "fp16_guard",
           "cast_model_to_fp16", "cast_parameters_to_fp16", "bf16"]


class AutoMixedPrecisionLists:
    """Reference fluid/contrib/mixed_precision/fp16_lists.py: the
    white/black op-name lists auto_cast consults."""

    def __init__(self, custom_white_list=None, custom_black_list=None,
                 custom_black_varnames=None):
        self.white_list = set(custom_white_list or [])
        self.black_list = set(custom_black_list or [])
        self.black_varnames = set(custom_black_varnames or [])


CustomOpLists = AutoMixedPrecisionLists


class OptimizerWithMixedPrecision:
    """Reference fluid OptimizerWithMixedPrecision: minimize() scales
    the loss, backprops the scaled value, unscales gradients, skips
    non-finite steps, and updates the loss scale — all through the
    eager GradScaler, which is the same machinery our trace uses."""

    def __init__(self, optimizer, scaler, amp_lists=None,
                 use_pure_fp16=False):
        self._optimizer = optimizer
        self._scaler = scaler
        self._amp_lists = amp_lists
        self._use_pure_fp16 = use_pure_fp16

    def backward(self, loss, **kw):
        scaled = self._scaler.scale(loss)
        scaled.backward()
        return []

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        self.backward(loss)
        # GradScaler.step() unscales, skips non-finite steps AND updates
        # the dynamic scale — calling update() again here would clear the
        # nan counter every step and freeze the scale
        self._scaler.step(self._optimizer)
        params = getattr(self._optimizer, "_parameter_list", None) or []
        return None, [(p, p.grad) for p in params]

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def amp_init(self, place=None, scope=None, test_program=None,
                 use_fp16_test=False):
        return None   # parameters cast at decorate time on this backend

    def get_loss_scaling(self):
        return self._scaler.get_init_loss_scaling()

    def __getattr__(self, name):
        return getattr(self._optimizer, name)


def decorate(optimizer, amp_lists=None, init_loss_scaling=2 ** 15,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.8,
             use_dynamic_loss_scaling=True, use_pure_fp16=False,
             use_fp16_guard=None, use_bf16=False):
    """Reference mixed_precision.decorate: returns an
    OptimizerWithMixedPrecision whose minimize() runs the full
    scale -> backward -> unscale -> skip-nonfinite -> rescale loop
    (dynamic scaling is disabled for bf16, like the reference's bf16
    path — bf16's exponent range needs none)."""
    from ..amp import GradScaler
    # bf16 needs no scaling at all (enable=False); fp16 with static
    # scaling keeps the CONSTANT init_loss_scaling applied+unscaled
    # (use_dynamic_loss_scaling=False), matching the reference's
    # static-scale mode — underflow protection is the whole point
    scaler = GradScaler(
        enable=not use_bf16,
        use_dynamic_loss_scaling=use_dynamic_loss_scaling,
        init_loss_scaling=init_loss_scaling,
        incr_ratio=incr_ratio, decr_ratio=decr_ratio,
        incr_every_n_steps=incr_every_n_steps,
        decr_every_n_nan_or_inf=decr_every_n_nan_or_inf)
    return OptimizerWithMixedPrecision(optimizer, scaler,
                                       amp_lists=amp_lists,
                                       use_pure_fp16=use_pure_fp16)


@contextlib.contextmanager
def fp16_guard():
    """Reference fp16_guard: marks a region to run in low precision
    under pure-fp16 mode; here it opens an O2 autocast scope."""
    with auto_cast(True, level="O2", dtype="float16"):
        yield


def cast_model_to_fp16(model, amp_lists=None, use_fp16_guard=True):
    from ..nn import Layer
    if isinstance(model, Layer):
        return model.astype("float16")
    raise TypeError(
        "cast_model_to_fp16 takes the nn.Layer the static trace runs; "
        "fluid Programs don't exist on the TPU backend")


def cast_parameters_to_fp16(place=None, program=None, scope=None,
                            to_fp16_var_names=None, model=None):
    from ..nn import Layer
    target = model if model is not None else program
    if isinstance(target, Layer):
        return target.astype("float16")
    raise TypeError(
        "cast_parameters_to_fp16 takes the nn.Layer the static trace "
        "runs (model=...); fluid Programs don't exist on the TPU backend")


class _BF16Module:
    """Reference static/amp/bf16: same decorate/guard surface at
    bfloat16 — the TPU-native dtype, where no loss scaling is needed."""

    AutoMixedPrecisionListsBF16 = AutoMixedPrecisionLists

    @staticmethod
    def decorate_bf16(optimizer, amp_lists=None, use_pure_bf16=False,
                      use_bf16_guard=None):
        return decorate(optimizer, amp_lists=amp_lists, use_bf16=True,
                        use_pure_fp16=use_pure_bf16)

    @staticmethod
    @contextlib.contextmanager
    def bf16_guard():
        with auto_cast(True, level="O2", dtype="bfloat16"):
            yield

    @staticmethod
    def cast_model_to_bf16(model, amp_lists=None, use_bf16_guard=True):
        from ..nn import Layer
        if isinstance(model, Layer):
            return model.bfloat16()
        raise TypeError("cast_model_to_bf16 takes an nn.Layer")

    @staticmethod
    def cast_parameters_to_bf16(place=None, program=None, scope=None,
                                to_bf16_var_names=None, model=None):
        from ..nn import Layer
        target = model if model is not None else program
        if isinstance(target, Layer):
            return target.bfloat16()
        raise TypeError("cast_parameters_to_bf16 takes an nn.Layer "
                        "(model=...)")


bf16 = _BF16Module()
