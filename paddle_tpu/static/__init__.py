"""paddle_tpu.static — static-graph façade (reference python/paddle/static).

The reference's Program/Executor machinery is replaced by XLA compilation:
a "Program" here is a traced, jit-compiled callable. The façade keeps the
most-used static APIs importable so reference-style scripts run.
"""
import jax

from ..framework.core import Tensor
from .input_spec import InputSpec  # noqa: F401

__all__ = ["InputSpec", "data", "Program", "Executor", "default_main_program",
           "default_startup_program", "name_scope", "py_func", "save", "load"]


def data(name, shape, dtype="float32", lod_level=0):
    return InputSpec(shape, dtype, name)


class Program:
    """Placeholder graph container; real compilation happens via jax.jit."""

    def __init__(self):
        self._ops = []

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self


_main = Program()
_startup = Program()


def default_main_program():
    return _main


def default_startup_program():
    return _startup


class Executor:
    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None):
        raise NotImplementedError(
            "paddle_tpu is eager/jit-first: wrap your computation in "
            "paddle_tpu.jit.to_static instead of Executor.run")


def name_scope(prefix=None):
    return jax.named_scope(prefix or "scope")


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    raise NotImplementedError("use paddle_tpu.autograd.PyLayer for custom ops")


def save(program, model_path, protocol=4):
    raise NotImplementedError("use paddle_tpu.jit.save")


def load(program, model_path, executor=None, var_list=None):
    raise NotImplementedError("use paddle_tpu.jit.load")
