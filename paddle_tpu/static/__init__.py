"""paddle_tpu.static — working static-graph mode over XLA.

The reference's Program/Executor machinery (python/paddle/static,
paddle/fluid/framework Program + fluid/executor.cc) is rebuilt TPU-first:
`static.data` creates SymbolicVar placeholders, every paddle op applied to
one records a deferred node (see framework.core._defer_symbolic) instead of
executing, and `Executor.run` evaluates the fetched sub-graph as ONE
jit-compiled XLA program (cached per feed signature). `optimizer.minimize`
on a symbolic loss registers a train spec: Executor.run then computes the
loss, differentiates it w.r.t. every trainable parameter captured in the
graph (jax.value_and_grad), and applies the optimizer update.

No op-by-op interpreter, no Program protobuf: XLA *is* the executor.
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..framework.dtype import dtype as _as_dtype
from ..framework.core import SymbolicVar, Tensor, _pause_tape
from .input_spec import InputSpec  # noqa: F401

__all__ = ["InputSpec", "data", "Program", "Executor", "default_main_program",
           "default_startup_program", "name_scope", "py_func", "save", "load",
           "gradients", "append_backward", "global_scope", "scope_guard",
           "cpu_places", "cuda_places"]


def data(name, shape, dtype="float32", lod_level=0):
    """Create a feed placeholder (reference python/paddle/static/input.py).

    Dims given as -1/0 are dynamic: `.shape` reports -1 (paddle semantics,
    so build-time code like reshape(x, [x.shape[0], ...]) records -1 and
    stays batch-polymorphic), while the tracing aval uses 1. Run-time shapes
    come from the actual feed arrays, so any batch size can be fed.
    """
    declared = tuple(int(s) for s in shape)
    concrete = tuple(s if s > 0 else 1 for s in declared)
    aval = jax.ShapeDtypeStruct(concrete, _as_dtype(dtype))
    var = SymbolicVar(aval, feed_name=name)
    if any(s <= 0 for s in declared):
        var._declared_shape = [s if s > 0 else -1 for s in declared]
    _main._feeds[name] = var
    return var


class Program:
    """Graph container; actual compilation happens in Executor.run."""

    def __init__(self):
        self._feeds = {}
        self._train_specs = {}   # id(loss var) -> (loss var, optimizer)

    def global_block(self):
        return self

    def clone(self, for_test=False):
        if not for_test:
            return self
        # Test clone shares the graph but drops train specs so Executor.run
        # on it never applies optimizer updates (reference Program.clone
        # strips backward/optimize ops when for_test=True).
        test = Program()
        test._feeds = self._feeds
        return test

    def all_parameters(self):
        return []


_main = Program()
_startup = Program()


def default_main_program():
    return _main


def default_startup_program():
    return _startup


def _register_minimize(loss, optimizer):
    """Called by Optimizer.minimize when the loss is symbolic."""
    _main._train_specs[id(loss)] = (loss, optimizer)


def _toposort(fetch_vars):
    """Iterative post-order over the SymbolicVar DAG.

    Returns (ordered vars, feed names in deterministic order, captured
    concrete Tensors in deterministic order).
    """
    order, feeds, consts = [], [], []
    seen_v, seen_c = set(), set()
    stack = [(v, False) for v in reversed(fetch_vars) if isinstance(v, SymbolicVar)]
    while stack:
        var, done = stack.pop()
        if done:
            order.append(var)
            continue
        if id(var) in seen_v:
            continue
        seen_v.add(id(var))
        stack.append((var, True))
        if var._feed_name is not None:
            if var._feed_name not in feeds:
                feeds.append(var._feed_name)
            continue
        if var._sym_op is None:
            raise ValueError(f"symbolic var {var.name} has no producer or feed")
        for a in var._sym_op.args:
            if isinstance(a, SymbolicVar):
                stack.append((a, False))
            elif isinstance(a, Tensor) and id(a) not in seen_c:
                seen_c.add(id(a))
                consts.append(a)
    return order, feeds, consts


def _eval_graph(fetch_vars, order, feed_map, const_map):
    """Evaluate the DAG given value maps; returns fetched raw arrays."""
    memo = {}   # id(SymbolicVar) -> array
    opmemo = {}  # id(_SymOp) -> raw multi-output
    for var in order:
        if var._feed_name is not None:
            memo[id(var)] = feed_map[var._feed_name]
            continue
        op = var._sym_op
        if id(op) in opmemo:
            out = opmemo[id(op)]
        else:
            vals = [memo[id(a)] if isinstance(a, SymbolicVar)
                    else (const_map[id(a)] if isinstance(a, Tensor) else a)
                    for a in op.args]
            out = op.fn(*vals, **op.kwargs)
            opmemo[id(op)] = out
        memo[id(var)] = out[var._out_index] if var._out_index is not None else out
    return [memo[id(v)] if isinstance(v, SymbolicVar)
            else (v._value if isinstance(v, Tensor) else jnp.asarray(v))
            for v in fetch_vars]


class Executor:
    """Compile-and-run over the symbolic graph (reference fluid/executor.py).

    Each distinct (fetch set, feed signature) compiles once; repeated run()
    calls hit the jit cache — the static-mode analogue of the reference's
    ParallelExecutor graph reuse.
    """

    def __init__(self, place=None):
        self.place = place
        self._cache = {}

    def run(self, program=None, feed=None, fetch_list=None, return_numpy=True):
        program = program or _main
        feed = feed or {}
        fetch_list = list(fetch_list or [])
        train = [program._train_specs[id(v)] for v in fetch_list
                 if id(v) in program._train_specs]

        order, feed_names, consts = _toposort(fetch_list)
        missing = [n for n in feed_names if n not in feed]
        if missing:
            raise ValueError(f"missing feed entries: {missing}")
        feed_vals = tuple(jnp.asarray(np.asarray(feed[n])) for n in feed_names)
        key = (tuple(id(v) for v in fetch_list),
               tuple((n, v.shape, str(v.dtype)) for n, v in zip(feed_names, feed_vals)))

        if train:
            outs = None
            for spec_i, (loss_var, opt) in enumerate(train):
                params = [p for p in (opt._parameter_list or [])
                          if not getattr(p, "stop_gradient", True)]
                if not params:  # fall back: every captured trainable tensor
                    params = [c for c in consts if not c.stop_gradient]
                param_ids = {id(p) for p in params}
                others = [c for c in consts if id(c) not in param_ids]
                if opt._parameter_list is None:
                    opt._parameter_list = params

                skey = key + (id(loss_var),)
                if skey not in self._cache:
                    def step(fvals, pvals, ovals, _params=params,
                             _others=others, _loss=loss_var):
                        cmap = {id(p): v for p, v in zip(_params, pvals)}
                        cmap.update({id(c): v for c, v in zip(_others, ovals)})
                        fmap = dict(zip(feed_names, fvals))
                        outs = _eval_graph(fetch_list, order, fmap, cmap)
                        li = fetch_list.index(_loss)
                        return jnp.sum(outs[li]), outs

                    self._cache[skey] = jax.jit(
                        jax.value_and_grad(step, argnums=1, has_aux=True))
                pvals = tuple(p._value for p in params)
                ovals = tuple(c._value for c in others)
                with _pause_tape():
                    (_, step_outs), grads = self._cache[skey](feed_vals, pvals, ovals)
                    outs = step_outs if outs is None else outs
                    for p, g in zip(params, grads):
                        p.grad = Tensor(g, stop_gradient=True) if p.grad is None \
                            else Tensor(p.grad._value + g, stop_gradient=True)
                    opt.step()
                    opt.clear_grad()
        else:
            if key not in self._cache:
                def fwd(fvals, cvals):
                    cmap = {id(c): v for c, v in zip(consts, cvals)}
                    return _eval_graph(fetch_list, order, dict(zip(feed_names, fvals)), cmap)

                self._cache[key] = jax.jit(fwd)
            cvals = tuple(c._value for c in consts)
            with _pause_tape():
                outs = self._cache[key](feed_vals, cvals)

        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]


def gradients(targets, inputs, target_gradients=None):
    """Symbolic gradients (reference python/paddle/static/gradient.py →
    fluid backward.gradients): returns d(sum targets)/d(inputs) as new
    symbolic vars evaluated through jax.grad at run time."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    order, feed_names, consts = _toposort(list(targets) + list(inputs))

    from ..framework.core import apply_op

    def grad_fn(*vals):
        n_in = len(inputs)
        in_vals, rest = vals[:n_in], vals[n_in:]

        def f(iv):
            fmap, cmap = {}, {}
            it_rest = iter(rest)
            for n in feed_names:
                fmap[n] = next(it_rest)
            for c in consts:
                cmap[id(c)] = next(it_rest)
            # substitute differentiated inputs
            sub = {id(v): x for v, x in zip(inputs, iv)}
            memo_outs = _eval_graph_sub(targets, order, fmap, cmap, sub)
            return sum(jnp.sum(o) for o in memo_outs)

        return jax.grad(f)(tuple(in_vals))

    feed_vars = [v for v in order if v._feed_name is not None]
    args = list(inputs) + [feed_vars[[v._feed_name for v in feed_vars].index(n)]
                           for n in feed_names] + list(consts)
    out = apply_op(grad_fn, *args)
    return list(out) if isinstance(out, (tuple, list)) else [out]


def _eval_graph_sub(fetch_vars, order, feed_map, const_map, substitute):
    memo, opmemo = dict(substitute), {}
    for var in order:
        if id(var) in memo:
            continue
        if var._feed_name is not None:
            memo[id(var)] = feed_map[var._feed_name]
            continue
        op = var._sym_op
        if id(op) in opmemo:
            out = opmemo[id(op)]
        else:
            vals = [memo[id(a)] if isinstance(a, SymbolicVar)
                    else (const_map[id(a)] if isinstance(a, Tensor) else a)
                    for a in op.args]
            out = op.fn(*vals, **op.kwargs)
            opmemo[id(op)] = out
        memo[id(var)] = out[var._out_index] if var._out_index is not None else out
    return [memo[id(v)] for v in fetch_vars]


def append_backward(loss, parameter_list=None):
    """API-parity shim (reference fluid/backward.py append_backward):
    gradients are generated inside Executor.run via jax.value_and_grad, so
    this only validates the loss is symbolic."""
    if not isinstance(loss, SymbolicVar):
        raise TypeError("append_backward expects a symbolic loss")
    return []


class _Scope:
    def var(self, name):
        return None

    def find_var(self, name):
        return None


_scope = _Scope()


def global_scope():
    return _scope


class scope_guard:
    def __init__(self, scope):
        self.scope = scope

    def __enter__(self):
        return self.scope

    def __exit__(self, *exc):
        return False


def cpu_places(device_count=None):
    from ..framework.device import CPUPlace
    return [CPUPlace() for _ in range(device_count or 1)]


def cuda_places(device_ids=None):
    from ..framework.device import TPUPlace
    ids = device_ids if device_ids is not None else range(len(jax.devices()))
    return [TPUPlace(i) for i in ids]


def name_scope(prefix=None):
    return jax.named_scope(prefix or "scope")


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    raise NotImplementedError("use paddle_tpu.autograd.PyLayer for custom ops")


def save(program, model_path, protocol=4):
    raise NotImplementedError("use paddle_tpu.jit.save")


def load(program, model_path, executor=None, var_list=None):
    raise NotImplementedError("use paddle_tpu.jit.load")


class program_guard:
    """Context manager scoping graph construction to a Program (reference
    python/paddle/static/__init__.py program_guard)."""

    def __init__(self, main_program, startup_program=None):
        self.main = main_program
        self.startup = startup_program

    def __enter__(self):
        global _main
        self._prev = _main
        _main = self.main
        return self.main

    def __exit__(self, *exc):
        global _main
        _main = self._prev
        return False


class _StaticNN:
    """static.nn op-style layer builders (reference python/paddle/static/nn):
    each call creates fresh parameters, like the reference's unique-named
    per-call params."""

    @staticmethod
    def fc(x, size, num_flatten_dims=1, activation=None, name=None):
        from .. import nn as dyn_nn
        from ..nn import functional as F
        in_dim = 1
        for s in x.shape[num_flatten_dims:]:
            in_dim *= abs(int(s))
        layer = dyn_nn.Linear(in_dim, size)
        out = layer(x if len(x.shape) == num_flatten_dims + 1
                    else _reshape_keep(x, num_flatten_dims, in_dim))
        if activation:
            out = getattr(F, activation)(out)
        return out

    @staticmethod
    def embedding(input, size, is_sparse=False, padding_idx=None, name=None):
        from .. import nn as dyn_nn
        layer = dyn_nn.Embedding(size[0], size[1], padding_idx=padding_idx)
        return layer(input)

    @staticmethod
    def conv2d(input, num_filters, filter_size, stride=1, padding=0,
               groups=1, name=None, act=None):
        from .. import nn as dyn_nn
        from ..nn import functional as F
        in_ch = int(input.shape[1])
        layer = dyn_nn.Conv2D(in_ch, num_filters, filter_size, stride=stride,
                              padding=padding, groups=groups)
        out = layer(input)
        if act:
            out = getattr(F, act)(out)
        return out

    @staticmethod
    def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, name=None):
        from .. import nn as dyn_nn
        from ..nn import functional as F
        ch = int(input.shape[1])
        layer = dyn_nn.BatchNorm2D(ch, momentum=momentum, epsilon=epsilon) \
            if len(input.shape) == 4 else dyn_nn.BatchNorm1D(ch, momentum=momentum,
                                                             epsilon=epsilon)
        out = layer(input)
        if act:
            out = getattr(F, act)(out)
        return out


def _reshape_keep(x, keep_dims, flat):
    from ..tensor.manipulation import reshape
    lead = [int(s) for s in x.shape[:keep_dims]]
    return reshape(x, lead + [flat])


nn = _StaticNN()
__all__ += ["program_guard", "nn"]
