"""paddle_tpu.static — working static-graph mode over XLA.

The reference's Program/Executor machinery (python/paddle/static,
paddle/fluid/framework Program + fluid/executor.cc) is rebuilt TPU-first:
`static.data` creates SymbolicVar placeholders, every paddle op applied to
one records a deferred node (see framework.core._defer_symbolic) instead of
executing, and `Executor.run` evaluates the fetched sub-graph as ONE
jit-compiled XLA program (cached per feed signature). `optimizer.minimize`
on a symbolic loss registers a train spec: Executor.run then computes the
loss, differentiates it w.r.t. every trainable parameter captured in the
graph (jax.value_and_grad), and applies the optimizer update.

No op-by-op interpreter, no Program protobuf: XLA *is* the executor.
"""
import pickle

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.dtype import dtype as _as_dtype
from ..framework.core import SymbolicVar, Tensor, _pause_tape
from .input_spec import InputSpec  # noqa: F401

__all__ = ["InputSpec", "data", "Program", "Executor", "default_main_program",
           "default_startup_program", "name_scope", "py_func", "save", "load",
           "gradients", "append_backward", "global_scope", "scope_guard",
           "cpu_places", "cuda_places"]


def data(name, shape, dtype="float32", lod_level=0):
    """Create a feed placeholder (reference python/paddle/static/input.py).

    Dims given as -1/0 are dynamic: `.shape` reports -1 (paddle semantics,
    so build-time code like reshape(x, [x.shape[0], ...]) records -1 and
    stays batch-polymorphic), while the tracing aval uses 1. Run-time shapes
    come from the actual feed arrays, so any batch size can be fed.
    """
    declared = tuple(int(s) for s in shape)
    concrete = tuple(s if s > 0 else 1 for s in declared)
    aval = jax.ShapeDtypeStruct(concrete, _as_dtype(dtype))
    var = SymbolicVar(aval, feed_name=name)
    if any(s <= 0 for s in declared):
        var._declared_shape = [s if s > 0 else -1 for s in declared]
    _main._feeds[name] = var
    return var


class Program:
    """Graph container; actual compilation happens in Executor.run."""

    def __init__(self):
        self._feeds = {}
        self._train_specs = {}   # id(loss var) -> (loss var, optimizer)
        self._params = []        # Parameters created while this is default

    def global_block(self):
        return self

    def clone(self, for_test=False):
        if not for_test:
            return self
        # Test clone shares the graph but drops train specs so Executor.run
        # on it never applies optimizer updates (reference Program.clone
        # strips backward/optimize ops when for_test=True).
        test = Program()
        test._feeds = self._feeds
        test._params = self._params
        return test

    def all_parameters(self):
        """Parameters created under static mode while this Program was the
        default (reference Program.all_parameters over persistable vars)."""
        return list(self._params)


_main = Program()
_startup = Program()


def default_main_program():
    return _main


def default_startup_program():
    return _startup


def _register_parameter(param):
    _main._params.append(param)


def _register_minimize(loss, optimizer):
    """Called by Optimizer.minimize when the loss is symbolic."""
    _main._train_specs[id(loss)] = (loss, optimizer)


def _toposort(fetch_vars):
    """Iterative post-order over the SymbolicVar DAG.

    Returns (ordered vars, feed names in deterministic order, captured
    concrete Tensors in deterministic order).
    """
    order, feeds, consts = [], [], []
    seen_v, seen_c = set(), set()
    stack = [(v, False) for v in reversed(fetch_vars) if isinstance(v, SymbolicVar)]
    while stack:
        var, done = stack.pop()
        if done:
            order.append(var)
            continue
        if id(var) in seen_v:
            continue
        seen_v.add(id(var))
        stack.append((var, True))
        if var._feed_name is not None:
            if var._feed_name not in feeds:
                feeds.append(var._feed_name)
            continue
        if var._sym_op is None:
            raise ValueError(f"symbolic var {var.name} has no producer or feed")
        for a in var._sym_op.args:
            if isinstance(a, SymbolicVar):
                stack.append((a, False))
            elif isinstance(a, Tensor) and id(a) not in seen_c:
                seen_c.add(id(a))
                consts.append(a)
    return order, feeds, consts


def _eval_graph(fetch_vars, order, feed_map, const_map):
    """Evaluate the DAG given value maps; returns fetched raw arrays."""
    memo = {}   # id(SymbolicVar) -> array
    opmemo = {}  # id(_SymOp) -> raw multi-output
    for var in order:
        if var._feed_name is not None:
            memo[id(var)] = feed_map[var._feed_name]
            continue
        op = var._sym_op
        if id(op) in opmemo:
            out = opmemo[id(op)]
        else:
            vals = [memo[id(a)] if isinstance(a, SymbolicVar)
                    else (const_map[id(a)] if isinstance(a, Tensor) else a)
                    for a in op.args]
            out = op.fn(*vals, **op.kwargs)
            opmemo[id(op)] = out
        memo[id(var)] = out[var._out_index] if var._out_index is not None else out
    return [memo[id(v)] if isinstance(v, SymbolicVar)
            else (v._value if isinstance(v, Tensor) else jnp.asarray(v))
            for v in fetch_vars]


class Executor:
    """Compile-and-run over the symbolic graph (reference fluid/executor.py).

    Each distinct (fetch set, feed signature) compiles once; repeated run()
    calls hit the jit cache — the static-mode analogue of the reference's
    ParallelExecutor graph reuse.
    """

    def __init__(self, place=None):
        self.place = place
        self._cache = {}

    def run(self, program=None, feed=None, fetch_list=None, return_numpy=True):
        if isinstance(program, CompiledProgram):
            program = program._program or _main
        if hasattr(program, "run_feed"):   # deserialized inference program
            outs = program.run_feed(feed or {})
            return [np.asarray(o) for o in outs] if return_numpy else outs
        program = program or _main
        feed = feed or {}
        fetch_list = list(fetch_list or [])
        train = [program._train_specs[id(v)] for v in fetch_list
                 if id(v) in program._train_specs]

        order, feed_names, consts = _toposort(fetch_list)
        missing = [n for n in feed_names if n not in feed]
        if missing:
            raise ValueError(f"missing feed entries: {missing}")
        feed_vals = tuple(jnp.asarray(np.asarray(feed[n])) for n in feed_names)
        key = (tuple(id(v) for v in fetch_list),
               tuple((n, v.shape, str(v.dtype)) for n, v in zip(feed_names, feed_vals)))

        if train:
            outs = None
            for spec_i, (loss_var, opt) in enumerate(train):
                params = [p for p in (opt._parameter_list or [])
                          if not getattr(p, "stop_gradient", True)]
                if not params:  # fall back: every captured trainable tensor
                    params = [c for c in consts if not c.stop_gradient]
                param_ids = {id(p) for p in params}
                others = [c for c in consts if id(c) not in param_ids]
                if opt._parameter_list is None:
                    opt._parameter_list = params

                skey = key + (id(loss_var),)
                if skey not in self._cache:
                    def step(fvals, pvals, ovals, _params=params,
                             _others=others, _loss=loss_var):
                        cmap = {id(p): v for p, v in zip(_params, pvals)}
                        cmap.update({id(c): v for c, v in zip(_others, ovals)})
                        fmap = dict(zip(feed_names, fvals))
                        outs = _eval_graph(fetch_list, order, fmap, cmap)
                        li = fetch_list.index(_loss)
                        return jnp.sum(outs[li]), outs

                    self._cache[skey] = jax.jit(
                        jax.value_and_grad(step, argnums=1, has_aux=True))
                pvals = tuple(p._value for p in params)
                ovals = tuple(c._value for c in others)
                with _pause_tape():
                    (_, step_outs), grads = self._cache[skey](feed_vals, pvals, ovals)
                    outs = step_outs if outs is None else outs
                    for p, g in zip(params, grads):
                        p.grad = Tensor(g, stop_gradient=True) if p.grad is None \
                            else Tensor(p.grad._value + g, stop_gradient=True)
                    opt.step()
                    opt.clear_grad()
        else:
            if key not in self._cache:
                def fwd(fvals, cvals):
                    cmap = {id(c): v for c, v in zip(consts, cvals)}
                    return _eval_graph(fetch_list, order, dict(zip(feed_names, fvals)), cmap)

                self._cache[key] = jax.jit(fwd)
            cvals = tuple(c._value for c in consts)
            with _pause_tape():
                outs = self._cache[key](feed_vals, cvals)

        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]


def gradients(targets, inputs, target_gradients=None):
    """Symbolic gradients (reference python/paddle/static/gradient.py →
    fluid backward.gradients): returns d(sum targets)/d(inputs) as new
    symbolic vars evaluated through jax.grad at run time."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    order, feed_names, consts = _toposort(list(targets) + list(inputs))

    from ..framework.core import apply_op

    def grad_fn(*vals):
        n_in = len(inputs)
        in_vals, rest = vals[:n_in], vals[n_in:]

        def f(iv):
            fmap, cmap = {}, {}
            it_rest = iter(rest)
            for n in feed_names:
                fmap[n] = next(it_rest)
            for c in consts:
                cmap[id(c)] = next(it_rest)
            # substitute differentiated inputs
            sub = {id(v): x for v, x in zip(inputs, iv)}
            memo_outs = _eval_graph_sub(targets, order, fmap, cmap, sub)
            return sum(jnp.sum(o) for o in memo_outs)

        return jax.grad(f)(tuple(in_vals))

    feed_vars = [v for v in order if v._feed_name is not None]
    args = list(inputs) + [feed_vars[[v._feed_name for v in feed_vars].index(n)]
                           for n in feed_names] + list(consts)
    out = apply_op(grad_fn, *args)
    return list(out) if isinstance(out, (tuple, list)) else [out]


def _eval_graph_sub(fetch_vars, order, feed_map, const_map, substitute):
    memo, opmemo = dict(substitute), {}
    for var in order:
        if id(var) in memo:
            continue
        if var._feed_name is not None:
            memo[id(var)] = feed_map[var._feed_name]
            continue
        op = var._sym_op
        if id(op) in opmemo:
            out = opmemo[id(op)]
        else:
            vals = [memo[id(a)] if isinstance(a, SymbolicVar)
                    else (const_map[id(a)] if isinstance(a, Tensor) else a)
                    for a in op.args]
            out = op.fn(*vals, **op.kwargs)
            opmemo[id(op)] = out
        memo[id(var)] = out[var._out_index] if var._out_index is not None else out
    return [memo[id(v)] for v in fetch_vars]


def append_backward(loss, parameter_list=None):
    """API-parity shim (reference fluid/backward.py append_backward):
    gradients are generated inside Executor.run via jax.value_and_grad, so
    this only validates the loss is symbolic."""
    if not isinstance(loss, SymbolicVar):
        raise TypeError("append_backward expects a symbolic loss")
    return []


class _Scope:
    def var(self, name):
        return None

    def find_var(self, name):
        return None


_scope = _Scope()


def global_scope():
    return _scope


class scope_guard:
    def __init__(self, scope):
        self.scope = scope

    def __enter__(self):
        return self.scope

    def __exit__(self, *exc):
        return False


def cpu_places(device_count=None):
    from ..framework.device import CPUPlace
    return [CPUPlace() for _ in range(device_count or 1)]


def cuda_places(device_ids=None):
    from ..framework.device import TPUPlace
    ids = device_ids if device_ids is not None else range(len(jax.devices()))
    return [TPUPlace(i) for i in ids]


def name_scope(prefix=None):
    return jax.named_scope(prefix or "scope")


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    raise NotImplementedError("use paddle_tpu.autograd.PyLayer for custom ops")


def save(program, model_path, protocol=4):
    raise NotImplementedError("use paddle_tpu.jit.save")


def load(program, model_path, executor=None, var_list=None):
    raise NotImplementedError("use paddle_tpu.jit.load")


class program_guard:
    """Context manager scoping graph construction to a Program (reference
    python/paddle/static/__init__.py program_guard)."""

    def __init__(self, main_program, startup_program=None):
        self.main = main_program
        self.startup = startup_program

    def __enter__(self):
        global _main
        self._prev = _main
        _main = self.main
        return self.main

    def __exit__(self, *exc):
        global _main
        _main = self._prev
        return False


class _StaticNN:
    """static.nn op-style layer builders (reference python/paddle/static/nn):
    each call creates fresh parameters, like the reference's unique-named
    per-call params."""

    @staticmethod
    def fc(x, size, num_flatten_dims=1, activation=None, name=None):
        from .. import nn as dyn_nn
        from ..nn import functional as F
        in_dim = 1
        for s in x.shape[num_flatten_dims:]:
            in_dim *= abs(int(s))
        layer = dyn_nn.Linear(in_dim, size)
        out = layer(x if len(x.shape) == num_flatten_dims + 1
                    else _reshape_keep(x, num_flatten_dims, in_dim))
        if activation:
            out = getattr(F, activation)(out)
        return out

    @staticmethod
    def embedding(input, size, is_sparse=False, padding_idx=None, name=None):
        from .. import nn as dyn_nn
        layer = dyn_nn.Embedding(size[0], size[1], padding_idx=padding_idx)
        return layer(input)

    @staticmethod
    def conv2d(input, num_filters, filter_size, stride=1, padding=0,
               groups=1, name=None, act=None):
        from .. import nn as dyn_nn
        from ..nn import functional as F
        in_ch = int(input.shape[1])
        layer = dyn_nn.Conv2D(in_ch, num_filters, filter_size, stride=stride,
                              padding=padding, groups=groups)
        out = layer(input)
        if act:
            out = getattr(F, act)(out)
        return out

    @staticmethod
    def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, name=None):
        from .. import nn as dyn_nn
        from ..nn import functional as F
        ch = int(input.shape[1])
        layer = dyn_nn.BatchNorm2D(ch, momentum=momentum, epsilon=epsilon) \
            if len(input.shape) == 4 else dyn_nn.BatchNorm1D(ch, momentum=momentum,
                                                             epsilon=epsilon)
        out = layer(input)
        if act:
            out = getattr(F, act)(out)
        return out


def _reshape_keep(x, keep_dims, flat):
    from ..tensor.manipulation import reshape
    lead = [int(s) for s in x.shape[:keep_dims]]
    return reshape(x, lead + [flat])


nn = _StaticNN()
__all__ += ["program_guard", "nn", "Variable", "BuildStrategy", "ExecutionStrategy",
            "IpuStrategy", "CompiledProgram", "IpuCompiledProgram", "ipu_shard_guard",
            "ParallelExecutor", "device_guard", "Print", "WeightNormParamAttr",
            "ExponentialMovingAverage", "create_global_var", "create_parameter",
            "accuracy", "auc", "xpu_places", "npu_places", "mlu_places",
            "normalize_program", "serialize_program", "serialize_persistables",
            "save_to_file", "load_from_file", "deserialize_program",
            "deserialize_persistables", "save_inference_model",
            "load_inference_model", "load_program_state", "set_program_state"]


# ---------------------------------------------------------------------------
# Program compilation / execution config façades — reference
# python/paddle/static/__init__.py. Under XLA there is exactly one build
# pipeline (trace -> StableHLO -> XLA), so these carry config for parity and
# feed the same Executor path.

Variable = SymbolicVar


class BuildStrategy:
    """reference fluid/compiler.py BuildStrategy (attribute bag)."""

    def __init__(self):
        self.enable_inplace = True
        self.memory_optimize = True
        self.fuse_all_optimizer_ops = True
        self.fuse_elewise_add_act_ops = True
        self.fuse_bn_act_ops = True
        self.reduce_strategy = 0
        self.gradient_scale_strategy = 0
        self.sync_batch_norm = False
        self.num_trainers = 1
        self.trainer_id = 0


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10
        self.num_iteration_per_run = 1


class IpuStrategy:
    def __init__(self):
        self.config = {}

    def set_graph_config(self, **kw):
        self.config.update(kw)

    def set_pipelining_config(self, **kw):
        self.config.update(kw)

    def set_precision_config(self, **kw):
        self.config.update(kw)


class CompiledProgram:
    """reference fluid/compiler.py:CompiledProgram — XLA compiles every
    program; this wrapper only carries the strategy."""

    def __init__(self, program, build_strategy=None):
        self._program = program
        self._build_strategy = build_strategy or BuildStrategy()

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None, places=None):
        return self

    def __getattr__(self, item):
        return getattr(object.__getattribute__(self, "_program"), item)


class IpuCompiledProgram(CompiledProgram):
    def __init__(self, program=None, ipu_strategy=None, scope=None):
        super().__init__(program)
        self._ipu_strategy = ipu_strategy


class ipu_shard_guard:
    def __init__(self, index=-1, stage=-1):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class ParallelExecutor:
    """Legacy multi-card executor — GSPMD replaces graph replication; runs the
    plain Executor underneath."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None, build_strategy=None,
                 num_trainers=1, trainer_id=0, scope=None):
        self._exe = Executor()
        self._program = main_program

    def run(self, fetch_list=None, feed=None, feed_dict=None, return_numpy=True):
        return self._exe.run(self._program, feed=feed or feed_dict,
                             fetch_list=fetch_list, return_numpy=return_numpy)


class device_guard:
    """reference static device_guard context — XLA owns placement."""

    def __init__(self, device=None):
        self.device = device

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def Print(input, first_n=-1, message=None, summarize=20, print_tensor_name=True,
          print_tensor_type=True, print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """Identity op with host-side printing (reference fluid Print op)."""
    from ..framework.core import apply_op

    def _f(v):
        jax.debug.print((message or "Var") + ": {}", v)
        return v
    return apply_op(_f, input)


from ..nn.layer_base import ParamAttr as _ParamAttr


class WeightNormParamAttr(_ParamAttr):
    """reference python/paddle/fluid/param_attr.py:WeightNormParamAttr."""

    def __init__(self, dim=None, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=False,
                 need_clip=True):
        super().__init__(name=name, initializer=initializer,
                         learning_rate=learning_rate, regularizer=regularizer,
                         trainable=trainable, need_clip=need_clip)
        self.dim = dim


class ExponentialMovingAverage:
    """EMA of trainable parameters — reference
    python/paddle/fluid/optimizer.py:ExponentialMovingAverage."""

    def __init__(self, decay=0.999, thres_steps=None, name=None,
                 parameter_list=None):
        self._decay = decay
        self._params = list(parameter_list) if parameter_list is not None else []
        self._ema = {}
        self._backup = {}
        self._step = 0

    def _param_iter(self):
        return [(id(p), p) for p in self._params]

    def update(self):
        self._step += 1
        d = min(self._decay, (1 + self._step) / (10 + self._step))
        for key, p in self._param_iter():
            cur = p._value
            prev = self._ema.get(key, cur)
            self._ema[key] = d * prev + (1 - d) * cur

    def apply(self, executor=None, need_restore=True):
        ema = self

        class _ApplyCtx:
            def __enter__(ctx):
                for key, p in ema._param_iter():
                    ema._backup[key] = p._value
                    if key in ema._ema:
                        p._value = ema._ema[key]
                return ctx

            def __exit__(ctx, *exc):
                if need_restore:
                    ema.restore()
                return False
        return _ApplyCtx()

    def restore(self, executor=None):
        for key, p in self._param_iter():
            if key in self._backup:
                p._value = self._backup.pop(key)


def create_global_var(shape, value, dtype, persistable=False, force_cpu=False,
                      name=None):
    return Tensor(jnp.full([int(s) for s in shape], value, _as_dtype(dtype)))


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..framework import create_parameter as _cp
    return _cp(shape, dtype, name=name, attr=attr, is_bias=is_bias,
               default_initializer=default_initializer)


def accuracy(input, label, k=1, correct=None, total=None):
    """Top-k accuracy op (reference python/paddle/static/nn/metric.py)."""
    from ..framework.core import apply_op

    def _f(pred, lab):
        topk = jnp.argsort(-pred, axis=-1)[..., :k]
        lab2 = lab.reshape(-1, 1)
        hit = (topk == lab2).any(axis=-1)
        return hit.mean(dtype=jnp.float32)
    return apply_op(_f, input, label)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1, slide_steps=1):
    """Batch AUC (reference static.auc). Returns (auc, [stat placeholders])."""
    from ..framework.core import apply_op

    def _f(pred, lab):
        score = pred[:, 1] if pred.ndim == 2 and pred.shape[1] == 2 else pred.reshape(-1)
        lab_ = lab.reshape(-1)
        order = jnp.argsort(score)
        ranks = jnp.empty_like(order).at[order].set(jnp.arange(1, score.shape[0] + 1))
        npos = jnp.sum(lab_ == 1)
        nneg = jnp.sum(lab_ == 0)
        rank_sum = jnp.sum(jnp.where(lab_ == 1, ranks, 0))
        return ((rank_sum - npos * (npos + 1) / 2.0)
                / jnp.maximum(npos * nneg, 1)).astype(jnp.float32)
    a = apply_op(_f, input, label)
    return a, [a]


def xpu_places(device_ids=None):
    return cuda_places(device_ids)


def npu_places(device_ids=None):
    return cuda_places(device_ids)


def mlu_places(device_ids=None):
    return cuda_places(device_ids)


# --- inference program serialization (jax.export-backed) -------------------

class _LoadedProgram(Program):
    """Deserialized inference program: a callable XLA artifact + metadata."""

    def __init__(self, exported, feed_names, n_fetch):
        super().__init__()
        self._exported = exported
        self._feed_names = list(feed_names)
        self._n_fetch = n_fetch

    def run_feed(self, feed):
        args = [jnp.asarray(np.asarray(feed[n])) for n in self._feed_names]
        out = self._exported.call(*args)
        return list(out) if isinstance(out, (tuple, list)) else [out]


def normalize_program(program, feed_vars, fetch_vars):
    """Attach feed/fetch info to the program (reference prunes + normalizes;
    our traced graphs are already minimal)."""
    program._norm_feed = [v._feed_name for v in feed_vars]
    program._norm_fetch = list(fetch_vars)
    return program


def _build_inference_fn(feed_vars, fetch_vars):
    order, feed_names, consts = _toposort(list(fetch_vars))
    const_map = {id(c): c._value for c in consts}
    names = [v._feed_name for v in feed_vars]

    def fn(*args):
        fmap = dict(zip(names, args))
        return tuple(_eval_graph(list(fetch_vars), order, fmap, const_map))
    examples = [jnp.zeros(v._value.shape, v._value.dtype) for v in feed_vars]
    return fn, names, examples


def serialize_program(feed_vars, fetch_vars, **kwargs):
    """Serialize the traced inference graph via jax.export (StableHLO bytes)."""
    from jax import export as jexport
    fn, names, examples = _build_inference_fn(feed_vars, fetch_vars)
    exported = jexport.export(jax.jit(fn))(*examples)
    blob = exported.serialize()
    header = pickle.dumps({"feed_names": names, "n_fetch": len(fetch_vars)})
    return len(header).to_bytes(8, "little") + header + bytes(blob)


def serialize_persistables(feed_vars, fetch_vars, **kwargs):
    _, _, consts = _toposort(list(fetch_vars))
    state = {f"const_{i}": np.asarray(c._value) for i, c in enumerate(consts)}
    return pickle.dumps(state)


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def deserialize_program(data):
    from jax import export as jexport
    hlen = int.from_bytes(data[:8], "little")
    meta = pickle.loads(data[8:8 + hlen])
    exported = jexport.deserialize(bytearray(data[8 + hlen:]))
    return _LoadedProgram(exported, meta["feed_names"], meta["n_fetch"])


def deserialize_persistables(program, data, executor=None):
    return pickle.loads(data)


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         **kwargs):
    """reference python/paddle/static/io.py:save_inference_model — emits
    {path}.pdmodel (serialized XLA artifact) + {path}.pdiparams."""
    import os as _os
    _os.makedirs(_os.path.dirname(path_prefix) or ".", exist_ok=True)
    save_to_file(path_prefix + ".pdmodel", serialize_program(feed_vars, fetch_vars))
    save_to_file(path_prefix + ".pdiparams",
                 serialize_persistables(feed_vars, fetch_vars))


def load_inference_model(path_prefix, executor=None, **kwargs):
    prog = deserialize_program(load_from_file(path_prefix + ".pdmodel"))
    fetch_handles = list(range(prog._n_fetch))
    return [prog, prog._feed_names, fetch_handles]


def load_program_state(model_path, var_list=None):
    with open(model_path + ".pdiparams", "rb") as f:
        return pickle.load(f)


def set_program_state(program, state_dict):
    program._loaded_state = dict(state_dict)


# --- static.nn op-style builders (reference python/paddle/static/nn) --------

def _static_nn_extend():
    from .. import nn as dyn_nn
    from ..nn import functional as F
    from ..framework.core import apply_op as _apply_op

    def conv2d_transpose(input, num_filters, filter_size=None, output_size=None,
                         stride=1, padding=0, groups=1, dilation=1, act=None,
                         name=None):
        in_ch = int(input.shape[1])
        layer = dyn_nn.Conv2DTranspose(in_ch, num_filters, filter_size,
                                       stride=stride, padding=padding,
                                       groups=groups, dilation=dilation)
        out = layer(input)
        return getattr(F, act)(out) if act else out

    def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
               groups=1, act=None, name=None):
        in_ch = int(input.shape[1])
        layer = dyn_nn.Conv3D(in_ch, num_filters, filter_size, stride=stride,
                              padding=padding, dilation=dilation, groups=groups)
        out = layer(input)
        return getattr(F, act)(out) if act else out

    def conv3d_transpose(input, num_filters, filter_size=None, output_size=None,
                         stride=1, padding=0, groups=1, dilation=1, act=None,
                         name=None):
        in_ch = int(input.shape[1])
        layer = dyn_nn.Conv3DTranspose(in_ch, num_filters, filter_size,
                                       stride=stride, padding=padding,
                                       groups=groups, dilation=dilation)
        out = layer(input)
        return getattr(F, act)(out) if act else out

    def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
                   epsilon=1e-5, act=None, name=None):
        shape = [int(s) for s in input.shape[begin_norm_axis:]]
        layer = dyn_nn.LayerNorm(shape, epsilon=epsilon,
                                 weight_attr=None if scale else False,
                                 bias_attr=None if shift else False)
        out = layer(input)
        return getattr(F, act)(out) if act else out

    def group_norm(input, groups, epsilon=1e-5, act=None, name=None,
                   param_attr=None, bias_attr=None, data_layout="NCHW"):
        ch = int(input.shape[1])
        layer = dyn_nn.GroupNorm(groups, ch, epsilon=epsilon)
        out = layer(input)
        return getattr(F, act)(out) if act else out

    def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                      name=None):
        ch = int(input.shape[1])
        cls = dyn_nn.InstanceNorm2D if len(input.shape) == 4 else dyn_nn.InstanceNorm1D
        return cls(ch, epsilon=epsilon)(input)

    def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
                  data_layout="NCHW", in_place=False, name=None,
                  moving_mean_name=None, moving_variance_name=None,
                  do_model_average_for_mean_and_var=True, slot_dim=-1,
                  sync_stats=False, summary_decay_rate=0.9999999, enable_scale_and_shift=False):
        def _f(v):
            mean = v.mean(axis=0, keepdims=True)
            var = v.var(axis=0, keepdims=True)
            return (v - mean) / jnp.sqrt(var + epsilon)
        out = _apply_op(_f, input)
        return getattr(F, act)(out) if act else out

    def prelu(x, mode="all", param_attr=None, data_format="NCHW", name=None):
        if mode == "all":
            n = 1
        elif mode == "channel":
            n = int(x.shape[1])
        else:
            n = int(np.prod([int(s) for s in x.shape[1:]]))
        layer = dyn_nn.PReLU(num_parameters=n, data_format=data_format)
        return layer(x)

    def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
        layer = dyn_nn.SpectralNorm(
            [int(s) for s in weight.shape], dim=dim, power_iters=power_iters, eps=eps)
        return layer(weight)

    def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                      padding=0, dilation=1, groups=1, deformable_groups=1,
                      im2col_step=1, param_attr=None, bias_attr=None,
                      modulated=True, name=None):
        from ..vision.ops import DeformConv2D as _DC
        ks = (filter_size, filter_size) if isinstance(filter_size, int) \
            else tuple(filter_size)
        layer = _DC(int(x.shape[1]), num_filters, ks, stride=stride,
                    padding=padding, dilation=dilation,
                    deformable_groups=deformable_groups, groups=groups,
                    bias_attr=bias_attr)
        return layer(x, offset, mask if modulated else None)

    def bilinear_tensor_product(x, y, size, act=None, name=None,
                                param_attr=None, bias_attr=None):
        layer = dyn_nn.Bilinear(int(x.shape[-1]), int(y.shape[-1]), size)
        out = layer(x, y)
        return getattr(F, act)(out) if act else out

    def crf_decoding(input, param_attr=None, label=None, length=None):
        from ..text import viterbi_decode
        raise NotImplementedError(
            "use paddle_tpu.text.ViterbiDecoder (lax.scan CRF decode)")

    def row_conv(input, future_context_size, param_attr=None, act=None):
        """Lookahead row convolution (reference fluid row_conv op):
        out[t] = sum_{k=0..K} w[k] * in[t+k]."""
        k = future_context_size + 1
        d = int(input.shape[-1])
        from ..framework.core import Parameter
        from ..framework.random import next_key
        w = Parameter(jax.random.normal(next_key(), (k, d)) * 0.1)

        def _f(v, wv):
            pad = jnp.pad(v, [(0, 0), (0, k - 1), (0, 0)])
            out = sum(pad[:, i:i + v.shape[1]] * wv[i] for i in range(k))
            return out
        out = _apply_op(_f, input, w)
        return getattr(F, act)(out) if act else out

    def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
            bias_attr=None, num_neg_samples=None, name=None, sampler="uniform",
            custom_dist=None, seed=0, is_sparse=False):
        """Noise-contrastive estimation loss (reference fluid nce op),
        uniform negative sampling."""
        from ..framework.core import Parameter
        from ..framework.random import next_key
        d = int(input.shape[-1])
        k = num_neg_samples or 10
        w = Parameter(jax.random.normal(next_key(), (num_total_classes, d)) * 0.01)
        b = Parameter(jnp.zeros((num_total_classes,)))

        def _f(x, lab, wv, bv):
            n = x.shape[0]
            lab = lab.reshape(-1).astype(jnp.int32)
            pos_logit = jnp.einsum("nd,nd->n", x, wv[lab]) + bv[lab]
            neg_ids = jax.random.randint(jax.random.PRNGKey(seed), (n, k),
                                         0, num_total_classes)
            neg_logit = jnp.einsum("nd,nkd->nk", x, wv[neg_ids]) + bv[neg_ids]
            loss = jax.nn.softplus(-pos_logit) + \
                jnp.sum(jax.nn.softplus(neg_logit), axis=1)
            return loss.reshape(-1, 1)
        return _apply_op(_f, input, label, w, b)

    def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                       min_ratio=None, max_ratio=None, min_sizes=None,
                       max_sizes=None, steps=None, step_w=None, step_h=None,
                       offset=0.5, variance=[0.1, 0.1, 0.2, 0.2], flip=True,
                       clip=False, kernel_size=1, pad=0, stride=1, name=None,
                       min_max_aspect_ratios_order=False):
        """SSD detection head (reference fluid multi_box_head): per-feature-map
        loc/conf convs + prior boxes."""
        n_in = len(inputs)
        if min_sizes is None:
            step = int(np.floor((max_ratio - min_ratio) / max(n_in - 2, 1)))
            min_sizes, max_sizes = [], []
            for r in range(min_ratio, max_ratio + 1, step):
                min_sizes.append(base_size * r / 100.0)
                max_sizes.append(base_size * (r + step) / 100.0)
            min_sizes = [base_size * 0.1] + min_sizes
            max_sizes = [base_size * 0.2] + max_sizes
        locs, confs, priors, vars_ = [], [], [], []
        img_h = int(image.shape[2])
        img_w = int(image.shape[3])
        for i, x in enumerate(inputs):
            ar = aspect_ratios[i] if isinstance(aspect_ratios[i], (list, tuple)) \
                else [aspect_ratios[i]]
            n_prior = 2 + len(ar) * (2 if flip else 1)
            loc = _StaticNN.conv2d(x, n_prior * 4, kernel_size, stride=stride,
                                   padding=pad)
            conf = _StaticNN.conv2d(x, n_prior * num_classes, kernel_size,
                                    stride=stride, padding=pad)
            fh, fw = int(x.shape[2]), int(x.shape[3])
            # prior boxes for this feature map
            smin, smax = min_sizes[i], max_sizes[i]
            widths, heights = [smin, float(np.sqrt(smin * smax))], \
                [smin, float(np.sqrt(smin * smax))]
            for a in ar:
                widths += [smin * float(np.sqrt(a))] + ([smin / float(np.sqrt(a))] if flip else [])
                heights += [smin / float(np.sqrt(a))] + ([smin * float(np.sqrt(a))] if flip else [])
            sw = step_w or img_w / fw
            sh = step_h or img_h / fh
            cy, cx = np.meshgrid((np.arange(fh) + offset) * sh,
                                 (np.arange(fw) + offset) * sw, indexing="ij")
            boxes = []
            for w_, h_ in zip(widths, heights):
                x1 = (cx - w_ / 2) / img_w
                y1 = (cy - h_ / 2) / img_h
                x2 = (cx + w_ / 2) / img_w
                y2 = (cy + h_ / 2) / img_h
                boxes.append(np.stack([x1, y1, x2, y2], -1))
            pb = np.stack(boxes, 2).reshape(-1, 4)
            if clip:
                pb = np.clip(pb, 0, 1)
            priors.append(pb.astype(np.float32))
            vars_.append(np.tile(np.asarray(variance, np.float32), (pb.shape[0], 1)))
            from ..tensor.manipulation import reshape, transpose
            locs.append(reshape(transpose(loc, [0, 2, 3, 1]), [int(loc.shape[0]), -1, 4]))
            confs.append(reshape(transpose(conf, [0, 2, 3, 1]),
                                 [int(conf.shape[0]), -1, num_classes]))
        from ..tensor.manipulation import concat
        mbox_loc = concat(locs, axis=1)
        mbox_conf = concat(confs, axis=1)
        prior_boxes = Tensor(jnp.asarray(np.concatenate(priors)))
        box_vars = Tensor(jnp.asarray(np.concatenate(vars_)))
        return mbox_loc, mbox_conf, prior_boxes, box_vars

    # control flow (host-evaluated: dygraph semantics; inside jit use
    # paddle_tpu's lax-backed cond/while wrappers)
    def cond(pred, true_fn=None, false_fn=None, name=None):
        p = bool(np.asarray(pred._value if isinstance(pred, Tensor) else pred))
        if p:
            return true_fn() if true_fn else None
        return false_fn() if false_fn else None

    def case(pred_fn_pairs, default=None, name=None):
        for pred, fn in pred_fn_pairs:
            if bool(np.asarray(pred._value if isinstance(pred, Tensor) else pred)):
                return fn()
        return default() if default else None

    def switch_case(branch_index, branch_fns, default=None, name=None):
        idx = int(np.asarray(branch_index._value if isinstance(branch_index, Tensor)
                             else branch_index))
        fns = dict(branch_fns) if not isinstance(branch_fns, dict) else branch_fns
        if idx in fns:
            return fns[idx]()
        return default() if default else None

    def while_loop(cond_fn, body, loop_vars, is_test=False, name=None):
        vars_ = list(loop_vars)
        while True:
            c = cond_fn(*vars_)
            if not bool(np.asarray(c._value if isinstance(c, Tensor) else c)):
                break
            out = body(*vars_)
            vars_ = list(out) if isinstance(out, (list, tuple)) else [out]
        return vars_

    def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
        xs = x if isinstance(x, (list, tuple)) else [x]
        res = func(*[np.asarray(v._value) for v in xs])
        return Tensor(jnp.asarray(res))

    def sparse_embedding(input, size, padding_idx=None, is_test=False,
                        entry=None, param_attr=None, dtype="float32"):
        return _StaticNN.embedding(input, size, is_sparse=True,
                                   padding_idx=padding_idx)

    # sequence ops: LoD-era API; here inputs are dense (B, T, ...) tensors
    # (the padded form paddle 2.x prefers anyway).
    def sequence_softmax(input, use_cudnn=False, name=None):
        return F.softmax(input, axis=1)

    def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                      padding=True, padding_start=None, bias_attr=None,
                      param_attr=None, act=None, name=None):
        d = int(input.shape[-1])
        layer = dyn_nn.Conv1D(d, num_filters, filter_size,
                              stride=filter_stride,
                              padding=(filter_size // 2) if padding else 0,
                              data_format="NLC")
        out = layer(input)
        return getattr(F, act)(out) if act else out

    def sequence_pool(input, pool_type="average", is_test=False, pad_value=0.0):
        from ..framework.core import apply_op as _ap
        ops = {"average": jnp.mean, "sum": jnp.sum, "max": jnp.max,
               "min": jnp.min, "sqrt": None, "last": None, "first": None}
        pt = pool_type.lower()

        def _f(v):
            if pt == "last":
                return v[:, -1]
            if pt == "first":
                return v[:, 0]
            if pt == "sqrt":
                return jnp.sum(v, axis=1) / jnp.sqrt(jnp.asarray(v.shape[1], v.dtype))
            return ops[pt](v, axis=1)
        return _ap(_f, input)

    def sequence_concat(input, name=None):
        from ..tensor.manipulation import concat as _cat
        return _cat(list(input), axis=1)

    def sequence_first_step(input):
        return sequence_pool(input, "first")

    def sequence_last_step(input):
        return sequence_pool(input, "last")

    def sequence_slice(input, offset, length, name=None):
        from ..framework.core import apply_op as _ap

        def _f(v, off, ln):
            off0 = int(np.asarray(off).reshape(-1)[0])
            ln0 = int(np.asarray(ln).reshape(-1)[0])
            return jax.lax.dynamic_slice_in_dim(v, off0, ln0, axis=1)
        return _ap(_f, input, offset, length)

    def sequence_expand(x, y, ref_level=-1, name=None):
        from ..framework.core import apply_op as _ap
        return _ap(lambda a, b: jnp.repeat(a, b.shape[1] // max(a.shape[1], 1),
                                           axis=1), x, y)

    def sequence_expand_as(x, y, name=None):
        return sequence_expand(x, y)

    def sequence_pad(x, pad_value, maxlen=None, name=None):
        from ..framework.core import apply_op as _ap

        def _f(v, pv):
            tgt = maxlen or v.shape[1]
            if tgt <= v.shape[1]:
                return v[:, :tgt], jnp.full((v.shape[0],), v.shape[1], jnp.int32)
            padded = jnp.pad(v, [(0, 0), (0, tgt - v.shape[1])] +
                             [(0, 0)] * (v.ndim - 2),
                             constant_values=np.asarray(pv).item())
            return padded, jnp.full((v.shape[0],), v.shape[1], jnp.int32)
        return _ap(_f, x, pad_value)

    def sequence_unpad(x, length, name=None):
        from ..framework.core import apply_op as _ap

        def _f(v, ln):
            keep = int(np.asarray(ln).max())
            return v[:, :keep]
        return _ap(_f, x, length)

    def sequence_reshape(input, new_dim):
        from ..tensor.manipulation import reshape as _rs
        b = int(input.shape[0])
        return _rs(input, [b, -1, new_dim])

    def sequence_scatter(input, index, updates, name=None):
        from ..framework.core import apply_op as _ap

        def _f(v, i, u):
            return v.at[:, i.reshape(-1)].add(u)
        return _ap(_f, input, index, updates)

    def sequence_enumerate(input, win_size, pad_value=0, name=None):
        from ..framework.core import apply_op as _ap

        def _f(v):
            t = v.shape[1]
            outs = []
            for k in range(win_size):
                shifted = jnp.pad(v[:, k:], [(0, 0), (0, k)],
                                  constant_values=pad_value)
                outs.append(shifted)
            return jnp.stack(outs, axis=-1)
        return _ap(_f, input)

    def sequence_reverse(x, name=None):
        from ..framework.core import apply_op as _ap
        return _ap(lambda v: jnp.flip(v, axis=1), x)

    for k, v in list(locals().items()):
        if callable(v) and not k.startswith("_"):
            setattr(_StaticNN, k, staticmethod(v))


_static_nn_extend()


from . import amp  # noqa: F401,E402  (reference static.amp surface)
from . import sparsity  # noqa: F401,E402  (reference static.sparsity surface)
