"""InputSpec — reference python/paddle/static/input.py."""
import jax.numpy as jnp

from ..framework.core import Tensor

__all__ = ["InputSpec"]


class InputSpec:
    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = jnp.dtype(dtype) if dtype is not None else None
        self.name = name

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(ndarray.shape, ndarray.dtype, name)

    def batch(self, batch_size):
        return InputSpec((batch_size,) + self.shape, self.dtype, self.name)

    def unbatch(self):
        return InputSpec(self.shape[1:], self.dtype, self.name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"

    def example_array(self, batch=1):
        """Concrete zeros array at this spec's shape — dynamic (None/-1)
        dims materialize as `batch`. Shared by jit.save's export path
        and the Graph Doctor CLI (lint a model straight from its
        InputSpec without hand-built examples)."""
        shape = [batch if (s is None or s < 0) else int(s)
                 for s in self.shape]
        return jnp.zeros(shape, self.dtype or jnp.float32)
