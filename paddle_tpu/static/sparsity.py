"""Reference python/paddle/static/sparsity/__init__.py — the static ASP
surface re-exports the same five functions as incubate.asp (the
reference routes both through fluid.contrib.sparsity)."""
from ..incubate.asp import (calculate_density, decorate, prune_model,
                            reset_excluded_layers, set_excluded_layers)

__all__ = ["calculate_density", "decorate", "prune_model",
           "set_excluded_layers", "reset_excluded_layers"]
