"""Continuous-batching decode engine over the paged KV cache.

Reference role: the fluid inference API's batched decode serving path
(paddle/fluid/inference/api/paddle_inference_api.h + PaddleNLP FasterGPT
decoding).  TPU-native design:

- ONE compiled decode step for a fixed slot count: [max_batch] tokens in,
  [max_batch] next tokens out (greedy, or seeded temperature/top-k/top-p
  sampling).  Slots hold independent sequences at different lengths;
  position/page state rides in arrays, so admission and retirement never
  recompile.
- KV lives in paged pools [L, P, page_size, H, D] (ops/paged_attention).
  Decode attention gathers each slot's pages (optionally via the
  scalar-prefetch Pallas kernel); page allocation is host-side.
- Prefill is a second compiled program per prompt-length bucket
  (powers of two) writing the prompt's K/V straight into the pages.
- quant="a8w8": per-(layer, out-channel) int8 weights with dynamic
  per-row int8 activations — matmuls run int8xint8->int32 on the MXU
  (same recipe as quantization.QuantizedLinearA8W8).
- quant="w4a16": weight-only int4 (ops/w4_matmul.py): nibbles unpack in
  VMEM, bf16 activations — half the weight HBM traffic of a8w8.

The engine applies to GPT-family models (uniform pre-LN blocks); weights
are extracted once into stacked per-layer arrays and the model object is
no longer needed — pair with jit.load-style artifacts for serving.
"""
import functools
import math

import jax
import jax.numpy as jnp
import numpy as np

from .framework.core import Tensor

__all__ = ["PagedGPTDecoder", "ContinuousBatchingEngine",
           "SpeculativeEngine"]


def _ln(x, w, b):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, -1, keepdims=True)
    var = jnp.var(x32, -1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + 1e-5) * w + b).astype(x.dtype)


def _quantize_w(w):
    """Per-out-channel symmetric int8 via the shared quantization recipe
    (quantization.quantize_weight) — one implementation so serving a8w8
    can't drift from QuantizedLinearA8W8/PTQ."""
    from .quantization import quantize_weight
    q, scale = quantize_weight(w, axis=0)
    return q, scale.reshape(-1)


def _spec_accept(p_rows, q_rows, drafts, rng):
    """Rejection-sampling acceptance for ONE slot (Leviathan et al.):
    p_rows [n+1, V] target probs — row j is the target's conditional
    AFTER the tokens preceding draft j (row 0 judges drafts[0]),
    q_rows [n, V] draft probs, drafts [n] proposed tokens.  Accept draft
    j with prob min(1, p_j(d)/q_j(d)); on rejection emit a sample from
    norm(max(p_j - q_j, 0)); if every draft is accepted emit a fresh
    sample from the last target row.  The emitted tokens are distributed
    EXACTLY as target-only sampling (unit-tested by Monte Carlo).
    Returns (n_accepted, final_token)."""
    n = len(drafts)
    for j in range(n):
        d = int(drafts[j])
        q = q_rows[j, d]
        p = p_rows[j, d]
        if q <= 0.0 or rng.random() >= min(1.0, p / q):
            resid = np.maximum(p_rows[j] - q_rows[j], 0.0)
            tot = resid.sum()
            if tot <= 1e-12:       # p==q everywhere: any target sample
                resid, tot = p_rows[j], p_rows[j].sum()
            return j, int(rng.choice(len(resid), p=resid / tot))
    row = p_rows[n]
    return n, int(rng.choice(len(row), p=row / row.sum()))


def _sample_tokens(logits, sampling, keys):
    """Per-slot next-token choice: greedy, or seeded temperature/top-k/
    top-p sampling (keys: [S] per-slot PRNG keys — slot-stable draws no
    matter how the batch is composed; the mask itself is shared with
    generate() via models.generation.mask_logits)."""
    if sampling is None:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    from .models.generation import mask_logits
    temperature, top_k, top_p = sampling
    masked = mask_logits(logits, temperature, top_k, top_p)
    return jax.vmap(jax.random.categorical)(keys, masked).astype(jnp.int32)


def _mm_heads(x, w, b, quant):
    """x [S, h] @ head-major qkv weight [h, 3, H, D] -> [S, 3, H, D]."""
    if not quant:
        return (jnp.einsum("sh,htnd->stnd", x, w.astype(x.dtype))
                + b.astype(x.dtype))
    if quant == "w4a16":
        from .ops.w4_matmul import w4_matmul
        packed, sw = w             # [h/2, 3, H, D] packed, [3, H, D]
        out = w4_matmul(x, packed.reshape(packed.shape[0], -1),
                        sw.reshape(-1), x.shape[-1])
        return out.reshape(x.shape[0], *b.shape) + b.astype(x.dtype)
    qw, sw = w                     # [h,3,H,D] int8, [3,H,D] f32
    sx = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1,
                 keepdims=True) / 127.0
    sx = jnp.maximum(sx, 1e-8)
    xq = jnp.clip(jnp.round(x.astype(jnp.float32) / sx), -127,
                  127).astype(jnp.int8)
    acc = jax.lax.dot_general(xq, qw, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)
    return (acc.astype(jnp.float32) * sx[:, :, None, None] * sw
            + b).astype(x.dtype)


def _mm(x, w, b, quant):
    """x [..., in] @ w -> [..., out].  Float path, weight-only int4
    (W4A16: Pallas in-VMEM dequant), or dynamic-A8 x W8 int8 MXU
    matmul with per-row activation scales."""
    if not quant:
        return (x @ w.astype(x.dtype) + b.astype(x.dtype)).astype(x.dtype)
    if quant == "w4a16":
        from .ops.w4_matmul import w4_matmul
        out = w4_matmul(x, w[0], w[1], x.shape[-1])
        return (out + b.astype(x.dtype)).astype(x.dtype)
    qw, sw = w
    sx = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True) / 127.0
    sx = jnp.maximum(sx, 1e-8)
    xq = jnp.clip(jnp.round(x.astype(jnp.float32) / sx), -127, 127).astype(jnp.int8)
    acc = jax.lax.dot_general(xq, qw, (((xq.ndim - 1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)
    return (acc.astype(jnp.float32) * sx * sw + b).astype(x.dtype)


class PagedGPTDecoder:
    """Stacked-weight GPT decode executor over paged KV pools."""

    def __init__(self, model, num_pages=128, page_size=16, max_batch=8,
                 max_pages_per_seq=None, quant=None, use_kernel=False,
                 dtype=None, temperature=0.0, top_k=0, top_p=1.0, seed=0,
                 mesh=None):
        cfg = model.cfg
        self.cfg = cfg
        self.page_size = page_size
        self.num_pages = num_pages
        self.max_batch = max_batch
        self.max_pages = max_pages_per_seq or \
            (cfg.max_seq_len + page_size - 1) // page_size
        self.quant = quant
        self.use_kernel = use_kernel
        assert quant in (None, "a8w8", "w4a16"), quant
        # temperature 0 = greedy (reference decode convention)
        self.sampling = None if not temperature else \
            (float(temperature), int(top_k), float(top_p))
        self.seed = int(seed)
        self._draws = 0
        dtype = dtype or jnp.dtype(cfg.dtype)

        state = {k: np.asarray(v._value)
                 for k, v in model.state_dict().items()}
        L = cfg.num_layers

        def stack(fmt):
            return jnp.asarray(
                np.stack([state[fmt.format(i)] for i in range(L)]))

        H, D = cfg.num_heads, cfg.head_dim
        w = {
            "ln1_w": stack("blocks.{}.ln1.weight"),
            "ln1_b": stack("blocks.{}.ln1.bias"),
            # head-major qkv layout [L, h, 3, H, D]: under tp the shard
            # axis is the HEAD dim, which propagates cleanly through the
            # per-head attention and the head-sharded KV pages (a flat
            # [h, 3h] out-dim shard mixes q/k/v columns and costs an
            # all-gather per layer)
            "qkv_w": stack("blocks.{}.qkv.weight").reshape(
                cfg.num_layers, cfg.hidden_size, 3, H, D),
            "qkv_b": stack("blocks.{}.qkv.bias").reshape(
                cfg.num_layers, 3, H, D),
            "proj_w": stack("blocks.{}.proj.weight"),
            "proj_b": stack("blocks.{}.proj.bias"),
            "ln2_w": stack("blocks.{}.ln2.weight"),
            "ln2_b": stack("blocks.{}.ln2.bias"),
            "fc1_w": stack("blocks.{}.fc1.weight"),
            "fc1_b": stack("blocks.{}.fc1.bias"),
            "fc2_w": stack("blocks.{}.fc2.weight"),
            "fc2_b": stack("blocks.{}.fc2.bias"),
        }
        if quant:
            if quant == "w4a16":
                from .ops.w4_matmul import quantize_w4 as quantizer
            else:
                quantizer = _quantize_w
            for k in ("qkv_w", "proj_w", "fc1_w", "fc2_w"):
                v = w[k]
                shp = v.shape
                if v.ndim > 3:          # qkv head-major: flatten to 2-D
                    v = v.reshape(shp[0], shp[1], -1)
                q, s = jax.vmap(quantizer)(v)
                # restore the head-major rank (w4's packed in-dim is
                # h/2) so _shard_for_tp's specs apply to both quant
                # modes exactly as to fp; the scan slices tuples
                # leaf-wise per layer
                w[k] = (q.reshape((shp[0], q.shape[1]) + shp[2:]),
                        s.reshape((shp[0],) + shp[2:]))
        self.weights = w
        self.wte = jnp.asarray(state["wte.weight"])
        self.wpe = jnp.asarray(state["wpe.weight"])
        self.ln_f_w = jnp.asarray(state["ln_f.weight"])
        self.ln_f_b = jnp.asarray(state["ln_f.bias"])
        self.lm_head = jnp.asarray(
            state.get("lm_head.weight", state["wte.weight"].T))

        H, D = cfg.num_heads, cfg.head_dim
        self.k_pages = jnp.zeros((L, num_pages, page_size, H, D), dtype)
        self.v_pages = jnp.zeros((L, num_pages, page_size, H, D), dtype)

        # tensor-parallel serving: shard the 3h/ffn/head dims of the
        # stacked weights and the HEAD dim of the KV pages over 'tp';
        # GSPMD inserts the all-reduces after proj/ffn2 — the Megatron
        # decode layout, no code changes in the step function
        self.mesh = mesh
        if mesh is None:
            from .distributed.mesh import get_mesh
            m = get_mesh(create_default=False)
            if m is not None and m.shape.get("tp", 1) > 1:
                self.mesh = m
        if self.mesh is not None:
            self._shard_for_tp()

        self._decode = jax.jit(self._decode_step, donate_argnums=(1, 2))
        self._verify = None   # jitted lazily (speculative decoding only)
        self._probs = None    # jitted lazily (sampled speculation)
        self._prefills = {}   # padded length -> jitted prefill

    def _probs_of(self, logits):
        """softmax over the decoder's sampling mask (the distribution its
        sampled tokens are actually drawn from)."""
        if self._probs is None:
            from .models.generation import mask_logits
            if self.sampling:
                t, tk, tp = self.sampling
                self._probs = jax.jit(lambda lg: jax.nn.softmax(
                    mask_logits(lg, t, tk, tp), axis=-1))
            else:
                self._probs = jax.jit(
                    lambda lg: jax.nn.softmax(lg, axis=-1))
        return np.asarray(self._probs(logits))

    def _shard_for_tp(self):
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        mesh = self.mesh
        tp = mesh.shape.get("tp", 1)
        if self.cfg.num_heads % tp:
            raise ValueError(
                f"num_heads {self.cfg.num_heads} must divide over "
                f"tp={tp} for tensor-parallel serving")
        if self.cfg.ffn_hidden % tp:
            raise ValueError(
                f"ffn_hidden {self.cfg.ffn_hidden} must divide over "
                f"tp={tp} for tensor-parallel serving")

        def put(v, *spec):
            return jax.device_put(v, NamedSharding(mesh, P(*spec)))

        w = self.weights

        def put_w(key, *spec):
            if isinstance(w[key], tuple):      # a8w8 (q, per-out scale)
                q, s = w[key]
                w[key] = (put(q, *spec), put(s, spec[0], *spec[2:]))
            else:
                w[key] = put(w[key], *spec)

        # column-parallel qkv (HEAD axis — aligns with the per-head
        # attention and the head-sharded pages, no reshard) and fc1;
        # row-parallel proj/fc2; biases follow their out dims
        put_w("qkv_w", None, None, None, "tp", None)
        w["qkv_b"] = put(w["qkv_b"], None, None, "tp", None)
        put_w("proj_w", None, "tp", None)
        put_w("fc1_w", None, None, "tp")
        w["fc1_b"] = put(w["fc1_b"], None, "tp")
        put_w("fc2_w", None, "tp", None)
        self.wte = put(self.wte, None, None)
        if self.lm_head.shape[-1] % tp == 0:
            self.lm_head = put(self.lm_head, None, "tp")
        else:
            # odd vocab (e.g. 50257): keep the head replicated rather
            # than fail — logits are [S, V] and small at decode batch
            self.lm_head = put(self.lm_head, None, None)
        # KV pages: heads sharded — each tp shard holds its heads' pages
        self.k_pages = put(self.k_pages, None, None, None, "tp", None)
        self.v_pages = put(self.v_pages, None, None, None, "tp", None)

    # -- compiled programs -------------------------------------------------

    def _decode_step(self, weights, k_pages, v_pages, tokens, lens, table,
                     draw):
        """tokens [S], lens [S] (tokens already counted, i.e. position of
        the incoming token), table [S, max_pages], draw (sampling round
        counter for per-slot keys) -> (next [S], logits [S, V], k_pages,
        v_pages)."""
        cfg, ps = self.cfg, self.page_size
        H, D = cfg.num_heads, cfg.head_dim
        S = tokens.shape[0]
        x = (self.wte[tokens] +
             self.wpe[jnp.clip(lens, 0, cfg.max_seq_len - 1)]
             ).astype(self.k_pages.dtype)                      # [S, h]
        pids = jnp.take_along_axis(table, (lens // ps)[:, None],
                                   axis=1)[:, 0]                # [S]
        offs = lens % ps
        quant = self.quant

        def layer(x, wkv):
            wl, kp, vp = wkv
            y = _ln(x, wl["ln1_w"], wl["ln1_b"])
            qkv = _mm_heads(y, wl["qkv_w"], wl["qkv_b"], quant)  # [S,3,H,D]
            q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
            kp = kp.at[pids, offs].set(k.astype(kp.dtype))
            vp = vp.at[pids, offs].set(v.astype(vp.dtype))
            from .ops.paged_attention import paged_attention
            attn = paged_attention(q[:, None], kp, vp, table, lens + 1,
                                   use_kernel=self.use_kernel)  # [S,1,H,D]
            x = x + _mm(attn.reshape(S, H * D), wl["proj_w"], wl["proj_b"],
                        quant)
            y = _ln(x, wl["ln2_w"], wl["ln2_b"])
            h = jax.nn.gelu(_mm(y, wl["fc1_w"], wl["fc1_b"], quant),
                            approximate=True)
            x = x + _mm(h, wl["fc2_w"], wl["fc2_b"], quant)
            return x, (kp, vp)

        x, (k_pages, v_pages) = jax.lax.scan(
            layer, x, (weights, k_pages, v_pages))
        x = _ln(x, self.ln_f_w, self.ln_f_b)
        logits = x.astype(jnp.float32) @ self.lm_head.astype(jnp.float32)
        keys = None
        if self.sampling is not None:
            base = jax.random.fold_in(jax.random.PRNGKey(self.seed), draw)
            keys = jax.vmap(lambda s: jax.random.fold_in(base, s))(
                jnp.arange(S))
        nxt = _sample_tokens(logits, self.sampling, keys)
        return nxt, logits, k_pages, v_pages

    def _verify_step(self, weights, k_pages, v_pages, tokens, lens, table):
        """Speculative verify: tokens [S, W] (last accepted token + the
        draft proposals) are consumed in ONE forward — KV written at
        positions lens..lens+W-1, causal attention against the paged
        prefix — returning the target's greedy choice after every
        position ([S, W] argmaxes). Rejected positions need no cleanup:
        lens is the source of truth and stale entries are overwritten."""
        cfg, ps = self.cfg, self.page_size
        H, D = cfg.num_heads, cfg.head_dim
        S, W = tokens.shape
        pos = lens[:, None] + jnp.arange(W)[None, :]            # [S, W]
        x = (self.wte[tokens] +
             self.wpe[jnp.clip(pos, 0, cfg.max_seq_len - 1)]
             ).astype(self.k_pages.dtype)                       # [S, W, h]
        MP = table.shape[1]
        # margin guard: window positions past the table's capacity (the
        # engine admits with a +k margin, so only pathological callers
        # get here) write to the reserved scratch page, never to a
        # clamped REAL page of the sequence
        in_range = pos < MP * ps
        pids = jnp.take_along_axis(table, jnp.minimum(pos // ps, MP - 1),
                                   axis=1)                      # [S, W]
        pids = jnp.where(in_range, pids, self.num_pages - 1)
        offs = pos % ps
        quant = self.quant

        def layer(x, wkv):
            wl, kp, vp = wkv
            y = _ln(x, wl["ln1_w"], wl["ln1_b"])
            xf = y.reshape(S * W, -1)
            qkv = _mm_heads(xf, wl["qkv_w"], wl["qkv_b"], quant)
            qkv = qkv.reshape(S, W, 3, H, D)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            kp = kp.at[pids, offs].set(k.astype(kp.dtype))
            vp = vp.at[pids, offs].set(v.astype(vp.dtype))
            # gather each slot's pages and attend with per-row causality
            kg = kp[table].reshape(S, MP * ps, H, D)            # [S, T, H, D]
            vg = vp[table].reshape(S, MP * ps, H, D)
            scale = 1.0 / float(np.sqrt(D))
            s = jnp.einsum("swhd,sthd->shwt", q.astype(jnp.float32),
                           kg.astype(jnp.float32)) * scale
            kpos = jnp.arange(MP * ps)[None, None, None, :]
            s = jnp.where(kpos <= pos[:, None, :, None], s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            attn = jnp.einsum("shwt,sthd->swhd", p,
                              vg.astype(jnp.float32)).astype(x.dtype)
            o = _mm(attn.reshape(S * W, H * D), wl["proj_w"],
                    wl["proj_b"], quant).reshape(S, W, -1)
            x = x + o
            y = _ln(x, wl["ln2_w"], wl["ln2_b"])
            yf = y.reshape(S * W, -1)
            h = jax.nn.gelu(_mm(yf, wl["fc1_w"], wl["fc1_b"], quant),
                            approximate=True)
            x = x + _mm(h, wl["fc2_w"], wl["fc2_b"],
                        quant).reshape(S, W, -1)
            return x, (kp, vp)

        x, (k_pages, v_pages) = jax.lax.scan(
            layer, x, (weights, k_pages, v_pages))
        x = _ln(x, self.ln_f_w, self.ln_f_b)
        logits = x.astype(jnp.float32) @ self.lm_head.astype(jnp.float32)
        return (jnp.argmax(logits, axis=-1).astype(jnp.int32), logits,
                k_pages, v_pages)

    def verify(self, tokens, lens, table, return_probs=False):
        """Batched speculative verify (see _verify_step)."""
        if self._verify is None:
            self._verify = jax.jit(self._verify_step,
                                   donate_argnums=(1, 2))
        out, logits, self.k_pages, self.v_pages = self._verify(
            self.weights, self.k_pages, self.v_pages,
            jnp.asarray(tokens, jnp.int32), jnp.asarray(lens, jnp.int32),
            jnp.asarray(table, jnp.int32))
        if return_probs:
            return np.asarray(out), self._probs_of(logits)
        return np.asarray(out)

    def _prefill_fn(self, Lp, n):
        """Per-(length-bucket, batch-bucket) compiled prefill: n padded
        sequences at once. Writes prompt KV into each sequence's pages
        and returns the n first tokens."""
        cfg, ps = self.cfg, self.page_size
        H, D = cfg.num_heads, cfg.head_dim
        n_pg = Lp // ps
        quant = self.quant

        def run(weights, k_pages, v_pages, ids, true_len, page_ids, draw):
            x = (self.wte[ids] + self.wpe[jnp.arange(Lp)][None]
                 ).astype(k_pages.dtype)                     # [n, Lp, h]

            def layer(x, wkv):
                wl, kp, vp = wkv
                y = _ln(x, wl["ln1_w"], wl["ln1_b"])
                qkv = _mm_heads(y.reshape(n * Lp, -1), wl["qkv_w"],
                                wl["qkv_b"], quant).reshape(n, Lp, 3, H, D)
                q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
                # Pallas flash kernel when backend/tiling allow, jnp
                # reference otherwise (one shared gate + fallback).
                # Padded-key masking is unnecessary: causal rows < true_len
                # never see cols >= true_len, padded rows' garbage stays
                # row-local, and only row true_len-1 feeds the logits.
                from .ops.attention import flash_raw_or_reference
                attn = flash_raw_or_reference(
                    q, k, v, causal=True, scale=1.0 / math.sqrt(D))
                x = x + _mm(attn.reshape(n * Lp, H * D).astype(x.dtype),
                            wl["proj_w"], wl["proj_b"],
                            quant).reshape(n, Lp, -1)
                y = _ln(x, wl["ln2_w"], wl["ln2_b"])
                h = jax.nn.gelu(
                    _mm(y.reshape(n * Lp, -1), wl["fc1_w"], wl["fc1_b"],
                        quant), approximate=True)
                x = x + _mm(h, wl["fc2_w"], wl["fc2_b"],
                            quant).reshape(n, Lp, -1)
                # page writes: static page count, dynamic page ids; the
                # requests' page sets are disjoint (scratch excepted)
                kpg = k.reshape(n, n_pg, ps, H, D).astype(kp.dtype)
                vpg = v.reshape(n, n_pg, ps, H, D).astype(vp.dtype)
                kp = kp.at[page_ids].set(kpg)
                vp = vp.at[page_ids].set(vpg)
                return x, (kp, vp)

            x, (k_pages, v_pages) = jax.lax.scan(
                layer, x, (weights, k_pages, v_pages))
            x = _ln(x, self.ln_f_w, self.ln_f_b)
            last = jnp.take_along_axis(
                x, (true_len - 1)[:, None, None].astype(jnp.int32),
                axis=1)[:, 0]                                # [n, h]
            logits = last.astype(jnp.float32) @ \
                self.lm_head.astype(jnp.float32)
            keys = None
            if self.sampling is not None:
                base = jax.random.fold_in(jax.random.PRNGKey(self.seed),
                                          draw)
                keys = jax.vmap(lambda s: jax.random.fold_in(base, s))(
                    jnp.arange(n))
            return _sample_tokens(logits, self.sampling, keys), \
                k_pages, v_pages

        return jax.jit(run, donate_argnums=(1, 2))

    # -- host-side API -----------------------------------------------------

    def prefill(self, ids, page_ids):
        """Run one prompt through the model, writing KV into `page_ids`;
        returns the next token (greedy, or sampled per the decoder's
        temperature/top_k/top_p config)."""
        return self.prefill_batch([(ids, page_ids)])[0]

    def prefill_batch(self, requests):
        """Prefill several prompts, batching same-length-bucket groups
        into single forwards. requests: [(ids, page_ids), ...]; returns
        the first generated token per request (in order)."""
        ps = self.page_size
        results = [None] * len(requests)
        groups = {}
        for i, (ids, page_ids) in enumerate(requests):
            ids = np.asarray(ids, np.int32)
            Lp = max(ps, ps * (2 ** math.ceil(
                math.log2(max(1, (len(ids) + ps - 1) // ps)))))
            groups.setdefault(Lp, []).append((i, ids, page_ids))
        for Lp, group in groups.items():
            n_pg = Lp // ps
            while group:
                # batch-bucket to powers of two (bounded compile count)
                nb = 1
                while nb * 2 <= len(group) and nb * 2 <= self.max_batch:
                    nb *= 2
                chunk, group = group[:nb], group[nb:]
                pad = np.zeros((nb, Lp), np.int32)
                tl = np.ones(nb, np.int32)
                pg = np.full((nb, n_pg), self.num_pages - 1, np.int32)
                for r, (i, ids, page_ids) in enumerate(chunk):
                    pad[r, :len(ids)] = ids
                    tl[r] = len(ids)
                    k = min(len(page_ids), n_pg)
                    pg[r, :k] = page_ids[:k]   # rest stays on scratch
                key = (Lp, nb)
                if key not in self._prefills:
                    self._prefills[key] = self._prefill_fn(Lp, nb)
                self._draws += 1
                nxt, self.k_pages, self.v_pages = self._prefills[key](
                    self.weights, self.k_pages, self.v_pages,
                    jnp.asarray(pad), jnp.asarray(tl), jnp.asarray(pg),
                    jnp.asarray(self._draws, jnp.int32))
                nxt = np.asarray(nxt)
                for r, (i, _, _) in enumerate(chunk):
                    results[i] = int(nxt[r])
        return results

    def analysis_program(self, donate=True):
        """Graph Doctor view of the compiled decode step: one fresh
        trace of `_decode_step` with per-argument role capture —
        weights/embeddings are `param` (read-only across steps, NOT
        donated: that's correct for inference), the K/V page pools are
        `cache` with donated=True matching the real donate_argnums=(1,2)
        (the cache is the decode loop's carried state — an undonated
        cache is the MEM-NO-DONATION-KVCACHE lint), tokens/lens/table/
        draw are `input`. `donate=False` traces the defective variant
        the planted-defect test lints."""
        from .analysis.lowering import LoweredProgram, tree_arg_infos

        S = self.max_batch
        tokens = jnp.zeros((S,), jnp.int32)
        lens = jnp.zeros((S,), jnp.int32)
        table = jnp.zeros((S, self.max_pages), jnp.int32)
        draw = jnp.zeros((), jnp.int32)
        fn = jax.jit(self._decode_step,
                     donate_argnums=(1, 2) if donate else ())
        traced = fn.trace(self.weights, self.k_pages, self.v_pages,
                          tokens, lens, table, draw)
        infos = tree_arg_infos(self.weights, "param")
        infos += tree_arg_infos(self.k_pages, "cache", prefix="k_pages",
                                donated=donate)
        infos += tree_arg_infos(self.v_pages, "cache", prefix="v_pages",
                                donated=donate)
        for nm, v in (("tokens", tokens), ("lens", lens),
                      ("table", table), ("draw", draw)):
            infos += tree_arg_infos(v, "input", prefix=nm)
        return LoweredProgram(traced.lower().as_text(),
                              jaxpr=traced.jaxpr, name="decode_step",
                              arg_infos=infos)

    def decode(self, tokens, lens, table, return_probs=False):
        """One decode step for all slots (greedy, or the configured
        sampling with deterministic per-(seed, round, slot) keys).
        return_probs additionally yields the [S, V] distribution the
        token was drawn from (speculative acceptance needs it)."""
        self._draws += 1
        nxt, logits, self.k_pages, self.v_pages = self._decode(
            self.weights, self.k_pages, self.v_pages,
            jnp.asarray(tokens, jnp.int32), jnp.asarray(lens, jnp.int32),
            jnp.asarray(table, jnp.int32),
            jnp.asarray(self._draws, jnp.int32))
        if return_probs:
            return nxt, self._probs_of(logits)
        return nxt


class ContinuousBatchingEngine:
    """Slot-based continuous batching: requests are admitted into free
    slots as soon as capacity allows (iteration-level scheduling), decode
    runs one compiled step for ALL active slots, finished sequences free
    their pages immediately."""

    def __init__(self, decoder: PagedGPTDecoder, eos_token_id=None,
                 max_new_tokens=64):
        if max_new_tokens < 1:
            raise ValueError(
                "max_new_tokens must be >= 1 (the prefill forward always "
                f"produces one token), got {max_new_tokens}")
        self.d = decoder
        self.eos = eos_token_id
        self.max_new = max_new_tokens
        # page 0..num_pages-2 allocatable; last page reserved as scratch
        self._free = list(range(decoder.num_pages - 2, -1, -1))
        S = decoder.max_batch
        self._slot_req = [None] * S          # request id per slot
        self._slot_pages = [[] for _ in range(S)]
        # int32 end to end: decode() feeds these to the kernel as int32,
        # so int64 here would insert a convert_element_type every tick
        self._lens = np.zeros(S, np.int32)
        self._tokens = np.zeros(S, np.int32)
        self._table_cache = None             # rebuilt on admit/retire only
        self._queue = []                     # (req_id, ids)
        self._outputs = {}                   # req_id -> [generated ids]
        self._next_id = 0
        self.steps = 0

    def submit(self, prompt_ids):
        rid = self._next_id
        self._next_id += 1
        ids = [int(t) for t in np.asarray(
            prompt_ids._value if isinstance(prompt_ids, Tensor)
            else prompt_ids).reshape(-1)]
        total = len(ids) + self.max_new
        need = self._pages_for(total)
        if need > min(self.d.max_pages, self.d.num_pages - 1):
            raise ValueError(
                f"request needs {need} pages (prompt {len(ids)} + "
                f"max_new {self.max_new} tokens) but the pool allows "
                f"{min(self.d.max_pages, self.d.num_pages - 1)}")
        if total > self.d.cfg.max_seq_len:
            raise ValueError(
                f"prompt {len(ids)} + max_new {self.max_new} tokens "
                f"exceeds the model's max_seq_len "
                f"{self.d.cfg.max_seq_len} (positions past it have no "
                "embedding)")
        self._queue.append((rid, ids))
        return rid

    def _pages_for(self, n_tokens):
        return (n_tokens + self.d.page_size - 1) // self.d.page_size

    def _admit(self):
        # gather every admittable request first: same-length-bucket
        # prompts then prefill as ONE batched forward (iteration-level
        # batching applies to prefill too, not just decode). Pages freed
        # by EOS-at-prefill become available from the NEXT step's pass.
        admitted = self._gather_admissions()
        if not admitted:
            return
        self._table_cache = None
        firsts = self.d.prefill_batch(
            [(ids, pages) for _, _, ids, pages in admitted])
        self._extra_prefill(admitted)
        for (slot, rid, ids, pages), first in zip(admitted, firsts):
            self._outputs[rid] = [first]
            if (self.eos is not None and first == self.eos) \
                    or self.max_new <= 1:
                # finished at prefill: never occupy a decode slot
                self._retire(slot)
                continue
            self._lens[slot] = len(ids)
            self._tokens[slot] = first
            self._after_admit(slot, len(ids))

    def _gather_admissions(self):
        admitted = []
        for slot in range(self.d.max_batch):
            if self._slot_req[slot] is not None or not self._queue:
                continue
            rid, ids = self._queue[0]
            need = self._pages_for(len(ids) + self.max_new)
            if need > len(self._free) or need > self.d.max_pages:
                break                        # head-of-line: wait for pages
            self._queue.pop(0)
            pages = [self._free.pop() for _ in range(need)]
            self._slot_req[slot] = rid
            self._slot_pages[slot] = pages
            admitted.append((slot, rid, ids, pages))
        return admitted

    def _extra_prefill(self, admitted):
        pass                                 # SpeculativeEngine: draft

    def _after_admit(self, slot, prompt_len):
        pass                                 # SpeculativeEngine: _dlens

    def _retire(self, slot):
        self._free.extend(self._slot_pages[slot])
        self._slot_req[slot] = None
        self._slot_pages[slot] = []
        self._lens[slot] = 0
        self._tokens[slot] = 0
        self._table_cache = None

    def _table(self, pages_per_slot, decoder):
        """Page table with inactive/unused entries routed to the reserved
        scratch page (their masked, discarded KV writes must never land
        in allocatable pages)."""
        t = np.full((decoder.max_batch, decoder.max_pages),
                    decoder.num_pages - 1, np.int32)
        for s, pg in enumerate(pages_per_slot):
            if pg:
                t[s, :len(pg)] = pg
        return t

    def step(self):
        """Admit + one decode tick. Returns number of active slots."""
        self._admit()
        active = [s for s in range(self.d.max_batch)
                  if self._slot_req[s] is not None]
        if not active:
            return 0
        if self._table_cache is None:        # slots changed since last tick
            self._table_cache = self._table(self._slot_pages, self.d)
        nxt = np.asarray(self.d.decode(self._tokens, self._lens,
                                       self._table_cache))
        self.steps += 1
        for s in active:
            rid = self._slot_req[s]
            tok = int(nxt[s])
            self._outputs[rid].append(tok)
            self._lens[s] += 1
            self._tokens[s] = tok
            done = (self.eos is not None and tok == self.eos) or \
                len(self._outputs[rid]) >= self.max_new
            if done:
                self._retire(s)
        return len(active)

    def run(self, step_times=None):
        """Drain the queue; returns {request_id: generated token list}.
        `step_times`, if given, receives each step's wall seconds (the
        public hook benches use for per-token latency percentiles)."""
        import time as _time
        while self._queue or any(r is not None for r in self._slot_req):
            if step_times is None:
                self.step()
            else:
                t0 = _time.perf_counter()
                self.step()
                step_times.append(_time.perf_counter() - t0)
        return dict(self._outputs)


class SpeculativeEngine(ContinuousBatchingEngine):
    """Speculative decoding over the paged engine: a small DRAFT model
    proposes k tokens with k cheap decode ticks; the TARGET model scores
    all of them in ONE verify forward. Greedy configs accept the longest
    matching prefix (+ the target's token at the first mismatch) —
    output is EXACTLY the target's greedy decode; sampled configs (same
    temperature/top-k/top-p on both decoders) use rejection-sampling
    acceptance (_spec_accept), so emitted tokens are distributed exactly
    as target-only sampling. Either way: up to k-times fewer target
    forwards. Paged KV makes rollback free: `lens` is the source of
    truth, rejected positions are simply overwritten.

    Acceptance is capped at k-1 drafts so the draft cache (which holds
    proposals d1..d_{k-1}) never falls behind; when all k drafts match,
    the capped path still emits exactly d1..dk.
    """

    def __init__(self, decoder, draft_decoder, eos_token_id=None,
                 max_new_tokens=64, k=4):
        if decoder.sampling != draft_decoder.sampling:
            raise ValueError(
                "speculative decoding needs the SAME sampling config on "
                "target and draft (acceptance compares their masked "
                f"distributions): {decoder.sampling} vs "
                f"{draft_decoder.sampling}")
        if draft_decoder.max_batch != decoder.max_batch or \
                draft_decoder.page_size != decoder.page_size:
            raise ValueError("draft/target max_batch and page_size must match")
        super().__init__(decoder, eos_token_id, max_new_tokens)
        self.draft = draft_decoder
        self.k = int(k)
        self._draft_free = list(range(draft_decoder.num_pages - 2, -1, -1))
        self._draft_pages = [[] for _ in range(decoder.max_batch)]
        self._dlens = np.zeros(decoder.max_batch, np.int32)
        self.target_calls = 0

    def submit(self, prompt_ids):
        """Same as the base, with a +k margin: a verify window can write
        up to k positions past the final accepted length."""
        ids = np.asarray(prompt_ids._value if isinstance(prompt_ids, Tensor)
                         else prompt_ids).reshape(-1)
        total = len(ids) + self.max_new + self.k
        need = self._pages_for(total)
        limit = min(self.d.max_pages, self.draft.max_pages,
                    self.d.num_pages - 1, self.draft.num_pages - 1)
        if need > limit:
            raise ValueError(
                f"request needs {need} pages (prompt {len(ids)} + max_new "
                f"{self.max_new} + speculation margin {self.k}) but the "
                f"pools allow {limit}")
        if total > min(self.d.cfg.max_seq_len, self.draft.cfg.max_seq_len):
            raise ValueError(
                f"prompt {len(ids)} + max_new {self.max_new} + margin "
                f"{self.k} exceeds max_seq_len "
                f"{min(self.d.cfg.max_seq_len, self.draft.cfg.max_seq_len)}")
        rid = self._next_id
        self._next_id += 1
        self._queue.append((rid, [int(t) for t in ids]))
        return rid

    def _gather_admissions(self):
        admitted = []
        for slot in range(self.d.max_batch):
            if self._slot_req[slot] is not None or not self._queue:
                continue
            rid, ids = self._queue[0]
            # +k margin: a verify window may write up to k positions past
            # the final accepted length
            need = self._pages_for(len(ids) + self.max_new + self.k)
            if need > len(self._free) or need > len(self._draft_free) \
                    or need > self.d.max_pages \
                    or need > self.draft.max_pages:
                break
            self._queue.pop(0)
            pages = [self._free.pop() for _ in range(need)]
            dpages = [self._draft_free.pop() for _ in range(need)]
            self._slot_req[slot] = rid
            self._slot_pages[slot] = pages
            self._draft_pages[slot] = dpages
            admitted.append((slot, rid, ids, pages))
        return admitted

    def _extra_prefill(self, admitted):
        self.draft.prefill_batch(           # draft's guesses discarded
            [(ids, self._draft_pages[slot])
             for slot, _, ids, _ in admitted])

    def _after_admit(self, slot, prompt_len):
        self._dlens[slot] = prompt_len

    def _retire(self, slot):
        self._draft_free.extend(self._draft_pages[slot])
        self._draft_pages[slot] = []
        self._dlens[slot] = 0
        super()._retire(slot)

    def step(self):
        self._admit()
        active = [s for s in range(self.d.max_batch)
                  if self._slot_req[s] is not None]
        if not active:
            return 0
        k = self.k
        if self._table_cache is None:        # slots changed since last tick
            self._table_cache = (self._table(self._slot_pages, self.d),
                                 self._table(self._draft_pages, self.draft))
        ttable, dtable = self._table_cache

        sampled = self.d.sampling is not None

        # draft proposes k tokens (k cheap ticks over all slots)
        proposals = np.zeros((self.d.max_batch, k), np.int32)
        qrows = None
        d_in = self._tokens.copy()
        dlens = self._dlens.copy()
        for j in range(k):
            if sampled and j < k - 1:
                # the k-th draft's distribution is never judged
                # (acceptance is capped at k-1): skip its transfer
                nxt, qp = self.draft.decode(d_in, dlens, dtable,
                                            return_probs=True)
                if qrows is None:
                    qrows = np.zeros((self.d.max_batch, k - 1,
                                      qp.shape[-1]))
                qrows[:, j] = qp
                nxt = np.asarray(nxt)
            else:
                nxt = np.asarray(self.draft.decode(d_in, dlens, dtable))
            proposals[:, j] = nxt
            dlens = dlens + 1
            d_in = nxt.astype(np.int32)

        # target verifies [cur, d1..dk] in one forward
        window = np.concatenate(
            [self._tokens[:, None], proposals[:, :k]], axis=1)  # [S, k+1]
        if sampled:
            tgt, prows = self.d.verify(window, self._lens, ttable,
                                       return_probs=True)
        else:
            tgt = self.d.verify(window, self._lens, ttable)     # [S, k+1]
        self.target_calls += 1
        self.steps += 1

        for s in active:
            rid = self._slot_req[s]
            if sampled:
                rng = np.random.default_rng(
                    (self.d.seed * 1000003 + self.target_calls) * 4093 + s)
                a, tok = _spec_accept(
                    prows[s, :k],
                    qrows[s] if qrows is not None else
                    np.zeros((0, prows.shape[-1])),
                    proposals[s, :k - 1], rng)
                emitted = [int(t) for t in proposals[s, :a]] + [tok]
            else:
                a = 0
                while a < k - 1 and proposals[s, a] == tgt[s, a]:
                    a += 1
                emitted = [int(t) for t in proposals[s, :a]] + \
                    [int(tgt[s, a])]
            L = int(self._lens[s])
            self._lens[s] = L + a + 1
            self._dlens[s] = L + a + 1
            self._tokens[s] = emitted[-1]
            done = False
            for t in emitted:
                self._outputs[rid].append(t)
                if (self.eos is not None and t == self.eos) or \
                        len(self._outputs[rid]) >= self.max_new:
                    done = True      # tokens speculated past the stop
                    break            # point are simply never appended
            if done:
                self._retire(s)
        return len(active)
