"""Continuous-batching decode engine over the paged KV cache.

Reference role: the fluid inference API's batched decode serving path
(paddle/fluid/inference/api/paddle_inference_api.h + PaddleNLP FasterGPT
decoding).  TPU-native design:

- ONE compiled decode step for a fixed slot count: [max_batch] tokens in,
  [max_batch] next tokens out (greedy, or seeded temperature/top-k/top-p
  sampling).  Slots hold independent sequences at different lengths;
  position/page state rides in arrays, so admission and retirement never
  recompile.
- KV lives in paged pools [L, P, page_size, H, D] (ops/paged_attention).
  Decode attention gathers each slot's pages (optionally via the
  scalar-prefetch Pallas kernel); page allocation is host-side.
- Prefill is a second compiled program per prompt-length bucket
  (powers of two) writing the prompt's K/V straight into the pages.
- Multi-step decode: `decode_multi` fuses K decode ticks into ONE
  compiled `lax.scan` — sampled tokens feed back on device, per-slot
  done masks (EOS or token budget) freeze finished slots (their `lens`
  stop and their K/V writes route to the reserved scratch page) — so
  the engine syncs the host once per K tokens instead of once per
  token (the host-interposed round-trip is the decode throughput
  killer once the kernel is fast; cf. Ragged Paged Attention,
  arXiv 2604.15464, and T3's overlap analysis, arXiv 2401.16677).
  `ContinuousBatchingEngine.run()` schedules horizons of
  `k = min(K_max, smallest remaining budget)` ticks and overlaps each
  block's host fetch with the NEXT block's dispatch (one-horizon-
  delayed retirement); `cost_model.decode_horizon` prices the default
  K from the chip's tick roofline vs the measured host sync cost.
- quant="a8w8": per-(layer, out-channel) int8 weights with dynamic
  per-row int8 activations — matmuls run int8xint8->int32 on the MXU
  (same recipe as quantization.QuantizedLinearA8W8).
- quant="w4a16": weight-only int4 (ops/w4_matmul.py): nibbles unpack in
  VMEM, bf16 activations — half the weight HBM traffic of a8w8.

The engine applies to GPT-family models (uniform pre-LN blocks); weights
are extracted once into stacked per-layer arrays and the model object is
no longer needed — pair with jit.load-style artifacts for serving.
"""
import collections
import functools
import math
import time
import weakref
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from .framework.core import Tensor

__all__ = ["PagedGPTDecoder", "ContinuousBatchingEngine",
           "SpeculativeEngine", "ServeStats", "serving_stats"]


# every live engine, for debug.serving_stats() (mirrors the prefetcher
# registry in io/prefetch.py: observability without plumbing handles)
_ENGINES = weakref.WeakSet()


# sample window of the per-token / queue-wait / occupancy percentiles:
# counters run forever, distributions cover the most recent samples so
# a long-lived engine's telemetry stays O(1) memory and O(window) to
# summarize
_STATS_WINDOW = 4096


@dataclass
class ServeStats:
    """Serving telemetry of one engine: how often the host interposes
    on the decode loop and what the client observes. `decode_syncs` is
    the number under optimization — the per-tick engine pays one host
    sync per generated token; the multi-step engine one per K.
    Counters are lifetime totals; the latency/occupancy distributions
    are bounded sliding windows (last `_STATS_WINDOW` samples)."""
    engine: str = ""
    k_max: int = 1
    requests: int = 0            # submitted
    completed: int = 0           # retired with output
    tokens: int = 0              # generated tokens (prefill's included)
    ticks: int = 0               # device decode ticks dispatched
    decode_syncs: int = 0        # host fetches of decode results
    prefill_syncs: int = 0       # host-blocking prefill rounds
    queue_wait_s: collections.deque = field(      # submit -> admit
        default_factory=lambda: collections.deque(maxlen=_STATS_WINDOW))
    occupancy: collections.deque = field(         # active/slots per block
        default_factory=lambda: collections.deque(maxlen=_STATS_WINDOW))
    token_time_s: collections.deque = field(
        # wall per token, steady-state decode syncs only (syncs that
        # contained a prefill are excluded, or p99 becomes a prefill
        # number)
        default_factory=lambda: collections.deque(maxlen=_STATS_WINDOW))

    @property
    def host_syncs_per_token(self):
        return self.decode_syncs / self.tokens if self.tokens else 0.0

    def summary(self):
        d = {"engine": self.engine, "k_max": self.k_max,
             "requests": self.requests, "completed": self.completed,
             "tokens": self.tokens, "ticks": self.ticks,
             "decode_syncs": self.decode_syncs,
             "prefill_syncs": self.prefill_syncs,
             "host_syncs_per_token": round(self.host_syncs_per_token, 4)}
        if self.occupancy:
            d["mean_slot_occupancy"] = round(
                float(np.mean(self.occupancy)), 4)
        if self.queue_wait_s:
            d["queue_wait_p50_ms"] = round(
                float(np.percentile(self.queue_wait_s, 50)) * 1e3, 3)
        if self.token_time_s:
            tot = float(np.sum(self.token_time_s))
            d["tokens_per_sec"] = round(len(self.token_time_s) / tot, 1) \
                if tot else 0.0
            d["token_p50_ms"] = round(
                float(np.percentile(self.token_time_s, 50)) * 1e3, 3)
            d["token_p99_ms"] = round(
                float(np.percentile(self.token_time_s, 99)) * 1e3, 3)
        return d


def serving_stats():
    """ServeStats summaries of every live engine (debug.serving_stats
    front door)."""
    return [e.stats.summary() for e in list(_ENGINES)]


# decode_multi's result bundle: device arrays — the engine feeds
# tokens/lens/done/remaining straight into the next horizon's call and
# fetches tokens_block/done_before only at sync points
MultiDecodeOut = collections.namedtuple(
    "MultiDecodeOut", ["tokens_block", "done_before", "tokens", "lens",
                       "done", "remaining", "logits_block"])


def _ln(x, w, b):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, -1, keepdims=True)
    var = jnp.var(x32, -1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + 1e-5) * w + b).astype(x.dtype)


def _quantize_w(w):
    """Per-out-channel symmetric int8 via the shared quantization recipe
    (quantization.quantize_weight) — one implementation so serving a8w8
    can't drift from QuantizedLinearA8W8/PTQ."""
    from .quantization import quantize_weight
    q, scale = quantize_weight(w, axis=0)
    return q, scale.reshape(-1)


def _spec_accept(p_rows, q_rows, drafts, rng):
    """Rejection-sampling acceptance for ONE slot (Leviathan et al.):
    p_rows [n+1, V] target probs — row j is the target's conditional
    AFTER the tokens preceding draft j (row 0 judges drafts[0]),
    q_rows [n, V] draft probs, drafts [n] proposed tokens.  Accept draft
    j with prob min(1, p_j(d)/q_j(d)); on rejection emit a sample from
    norm(max(p_j - q_j, 0)); if every draft is accepted emit a fresh
    sample from the last target row.  The emitted tokens are distributed
    EXACTLY as target-only sampling (unit-tested by Monte Carlo).
    Returns (n_accepted, final_token)."""
    n = len(drafts)
    for j in range(n):
        d = int(drafts[j])
        q = q_rows[j, d]
        p = p_rows[j, d]
        if q <= 0.0 or rng.random() >= min(1.0, p / q):
            resid = np.maximum(p_rows[j] - q_rows[j], 0.0)
            tot = resid.sum()
            if tot <= 1e-12:       # p==q everywhere: any target sample
                resid, tot = p_rows[j], p_rows[j].sum()
            return j, int(rng.choice(len(resid), p=resid / tot))
    row = p_rows[n]
    return n, int(rng.choice(len(row), p=row / row.sum()))


def _sample_tokens(logits, sampling, keys):
    """Per-slot next-token choice: greedy, or seeded temperature/top-k/
    top-p sampling (keys: [S] per-slot PRNG keys derived from
    (seed, request id, position) — see PagedGPTDecoder._pos_keys — so a
    request's draws don't depend on batch composition or scheduling;
    the mask itself is shared with generate() via
    models.generation.mask_logits)."""
    if sampling is None:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    from .models.generation import mask_logits
    temperature, top_k, top_p = sampling
    masked = mask_logits(logits, temperature, top_k, top_p)
    return jax.vmap(jax.random.categorical)(keys, masked).astype(jnp.int32)


def _mm_heads(x, w, b, quant):
    """x [S, h] @ head-major qkv weight [h, 3, H, D] -> [S, 3, H, D]."""
    if not quant:
        return (jnp.einsum("sh,htnd->stnd", x, w.astype(x.dtype))
                + b.astype(x.dtype))
    if quant == "w4a16":
        from .ops.w4_matmul import w4_matmul
        packed, sw = w             # [h/2, 3, H, D] packed, [3, H, D]
        out = w4_matmul(x, packed.reshape(packed.shape[0], -1),
                        sw.reshape(-1), x.shape[-1])
        return out.reshape(x.shape[0], *b.shape) + b.astype(x.dtype)
    qw, sw = w                     # [h,3,H,D] int8, [3,H,D] f32
    sx = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1,
                 keepdims=True) / 127.0
    sx = jnp.maximum(sx, 1e-8)
    xq = jnp.clip(jnp.round(x.astype(jnp.float32) / sx), -127,
                  127).astype(jnp.int8)
    acc = jax.lax.dot_general(xq, qw, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)
    return (acc.astype(jnp.float32) * sx[:, :, None, None] * sw
            + b).astype(x.dtype)


def _mm(x, w, b, quant):
    """x [..., in] @ w -> [..., out].  Float path, weight-only int4
    (W4A16: Pallas in-VMEM dequant), or dynamic-A8 x W8 int8 MXU
    matmul with per-row activation scales."""
    if not quant:
        return (x @ w.astype(x.dtype) + b.astype(x.dtype)).astype(x.dtype)
    if quant == "w4a16":
        from .ops.w4_matmul import w4_matmul
        out = w4_matmul(x, w[0], w[1], x.shape[-1])
        return (out + b.astype(x.dtype)).astype(x.dtype)
    qw, sw = w
    sx = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True) / 127.0
    sx = jnp.maximum(sx, 1e-8)
    xq = jnp.clip(jnp.round(x.astype(jnp.float32) / sx), -127, 127).astype(jnp.int8)
    acc = jax.lax.dot_general(xq, qw, (((xq.ndim - 1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)
    return (acc.astype(jnp.float32) * sx * sw + b).astype(x.dtype)


class PagedGPTDecoder:
    """Stacked-weight GPT decode executor over paged KV pools."""

    def __init__(self, model, num_pages=128, page_size=16, max_batch=8,
                 max_pages_per_seq=None, quant=None, use_kernel=False,
                 dtype=None, temperature=0.0, top_k=0, top_p=1.0, seed=0,
                 mesh=None):
        cfg = model.cfg
        self.cfg = cfg
        self.page_size = page_size
        self.num_pages = num_pages
        self.max_batch = max_batch
        self.max_pages = max_pages_per_seq or \
            (cfg.max_seq_len + page_size - 1) // page_size
        self.quant = quant
        self.use_kernel = use_kernel
        assert quant in (None, "a8w8", "w4a16"), quant
        # temperature 0 = greedy (reference decode convention)
        self.sampling = None if not temperature else \
            (float(temperature), int(top_k), float(top_p))
        self.seed = int(seed)
        self._draws = 0
        dtype = dtype or jnp.dtype(cfg.dtype)

        state = {k: np.asarray(v._value)
                 for k, v in model.state_dict().items()}
        L = cfg.num_layers

        def stack(fmt):
            return jnp.asarray(
                np.stack([state[fmt.format(i)] for i in range(L)]))

        H, D = cfg.num_heads, cfg.head_dim
        w = {
            "ln1_w": stack("blocks.{}.ln1.weight"),
            "ln1_b": stack("blocks.{}.ln1.bias"),
            # head-major qkv layout [L, h, 3, H, D]: under tp the shard
            # axis is the HEAD dim, which propagates cleanly through the
            # per-head attention and the head-sharded KV pages (a flat
            # [h, 3h] out-dim shard mixes q/k/v columns and costs an
            # all-gather per layer)
            "qkv_w": stack("blocks.{}.qkv.weight").reshape(
                cfg.num_layers, cfg.hidden_size, 3, H, D),
            "qkv_b": stack("blocks.{}.qkv.bias").reshape(
                cfg.num_layers, 3, H, D),
            "proj_w": stack("blocks.{}.proj.weight"),
            "proj_b": stack("blocks.{}.proj.bias"),
            "ln2_w": stack("blocks.{}.ln2.weight"),
            "ln2_b": stack("blocks.{}.ln2.bias"),
            "fc1_w": stack("blocks.{}.fc1.weight"),
            "fc1_b": stack("blocks.{}.fc1.bias"),
            "fc2_w": stack("blocks.{}.fc2.weight"),
            "fc2_b": stack("blocks.{}.fc2.bias"),
        }
        if quant:
            if quant == "w4a16":
                from .ops.w4_matmul import quantize_w4 as quantizer
            else:
                quantizer = _quantize_w
            for k in ("qkv_w", "proj_w", "fc1_w", "fc2_w"):
                v = w[k]
                shp = v.shape
                if v.ndim > 3:          # qkv head-major: flatten to 2-D
                    v = v.reshape(shp[0], shp[1], -1)
                q, s = jax.vmap(quantizer)(v)
                # restore the head-major rank (w4's packed in-dim is
                # h/2) so _shard_for_tp's specs apply to both quant
                # modes exactly as to fp; the scan slices tuples
                # leaf-wise per layer
                w[k] = (q.reshape((shp[0], q.shape[1]) + shp[2:]),
                        s.reshape((shp[0],) + shp[2:]))
        self.weights = w
        self.wte = jnp.asarray(state["wte.weight"])
        self.wpe = jnp.asarray(state["wpe.weight"])
        self.ln_f_w = jnp.asarray(state["ln_f.weight"])
        self.ln_f_b = jnp.asarray(state["ln_f.bias"])
        self.lm_head = jnp.asarray(
            state.get("lm_head.weight", state["wte.weight"].T))

        H, D = cfg.num_heads, cfg.head_dim
        self.k_pages = jnp.zeros((L, num_pages, page_size, H, D), dtype)
        self.v_pages = jnp.zeros((L, num_pages, page_size, H, D), dtype)

        # tensor-parallel serving: shard the 3h/ffn/head dims of the
        # stacked weights and the HEAD dim of the KV pages over 'tp';
        # GSPMD inserts the all-reduces after proj/ffn2 — the Megatron
        # decode layout, no code changes in the step function
        self.mesh = mesh
        if mesh is None:
            from .distributed.mesh import get_mesh
            m = get_mesh(create_default=False)
            if m is not None and m.shape.get("tp", 1) > 1:
                self.mesh = m
        if self.mesh is not None:
            self._shard_for_tp()

        self._decode = jax.jit(self._decode_step, donate_argnums=(1, 2))
        self._multis = {}     # (k, return_logits) -> jitted fused loop
        self._verify = None   # jitted lazily (speculative decoding only)
        self._probs = None    # jitted lazily (sampled speculation)
        self._prefills = {}   # padded length -> jitted prefill

    def _probs_of(self, logits):
        """softmax over the decoder's sampling mask (the distribution its
        sampled tokens are actually drawn from)."""
        if self._probs is None:
            from .models.generation import mask_logits
            if self.sampling:
                t, tk, tp = self.sampling
                self._probs = jax.jit(lambda lg: jax.nn.softmax(
                    mask_logits(lg, t, tk, tp), axis=-1))
            else:
                self._probs = jax.jit(
                    lambda lg: jax.nn.softmax(lg, axis=-1))
        return np.asarray(self._probs(logits))

    def _shard_for_tp(self):
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        mesh = self.mesh
        tp = mesh.shape.get("tp", 1)
        if self.cfg.num_heads % tp:
            raise ValueError(
                f"num_heads {self.cfg.num_heads} must divide over "
                f"tp={tp} for tensor-parallel serving")
        if self.cfg.ffn_hidden % tp:
            raise ValueError(
                f"ffn_hidden {self.cfg.ffn_hidden} must divide over "
                f"tp={tp} for tensor-parallel serving")

        def put(v, *spec):
            return jax.device_put(v, NamedSharding(mesh, P(*spec)))

        w = self.weights

        def put_w(key, *spec):
            if isinstance(w[key], tuple):      # a8w8 (q, per-out scale)
                q, s = w[key]
                w[key] = (put(q, *spec), put(s, spec[0], *spec[2:]))
            else:
                w[key] = put(w[key], *spec)

        # column-parallel qkv (HEAD axis — aligns with the per-head
        # attention and the head-sharded pages, no reshard) and fc1;
        # row-parallel proj/fc2; biases follow their out dims
        put_w("qkv_w", None, None, None, "tp", None)
        w["qkv_b"] = put(w["qkv_b"], None, None, "tp", None)
        put_w("proj_w", None, "tp", None)
        put_w("fc1_w", None, None, "tp")
        w["fc1_b"] = put(w["fc1_b"], None, "tp")
        put_w("fc2_w", None, "tp", None)
        self.wte = put(self.wte, None, None)
        if self.lm_head.shape[-1] % tp == 0:
            self.lm_head = put(self.lm_head, None, "tp")
        else:
            # odd vocab (e.g. 50257): keep the head replicated rather
            # than fail — logits are [S, V] and small at decode batch
            self.lm_head = put(self.lm_head, None, None)
        # KV pages: heads sharded — each tp shard holds its heads' pages
        self.k_pages = put(self.k_pages, None, None, None, "tp", None)
        self.v_pages = put(self.v_pages, None, None, None, "tp", None)

    # -- compiled programs -------------------------------------------------

    def _forward_tokens(self, weights, k_pages, v_pages, tokens, lens,
                        table, pids, offs):
        """Shared single-position forward over all slots: embed `tokens`
        at position `lens`, write K/V at (pids, offs) — callers route
        frozen slots' pids to the reserved scratch page — and attend
        over each slot's pages. Returns (logits [S, V], k_pages,
        v_pages). Both the per-tick step and every tick of the fused
        multi-step scan run THIS body, so they cannot drift."""
        cfg = self.cfg
        H, D = cfg.num_heads, cfg.head_dim
        S = tokens.shape[0]
        x = (self.wte[tokens] +
             self.wpe[jnp.clip(lens, 0, cfg.max_seq_len - 1)]
             ).astype(k_pages.dtype)                           # [S, h]
        quant = self.quant

        def layer(x, wkv):
            wl, kp, vp = wkv
            y = _ln(x, wl["ln1_w"], wl["ln1_b"])
            qkv = _mm_heads(y, wl["qkv_w"], wl["qkv_b"], quant)  # [S,3,H,D]
            q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
            kp = kp.at[pids, offs].set(k.astype(kp.dtype))
            vp = vp.at[pids, offs].set(v.astype(vp.dtype))
            from .ops.paged_attention import paged_attention
            attn = paged_attention(q[:, None], kp, vp, table, lens + 1,
                                   use_kernel=self.use_kernel)  # [S,1,H,D]
            x = x + _mm(attn.reshape(S, H * D), wl["proj_w"], wl["proj_b"],
                        quant)
            y = _ln(x, wl["ln2_w"], wl["ln2_b"])
            h = jax.nn.gelu(_mm(y, wl["fc1_w"], wl["fc1_b"], quant),
                            approximate=True)
            x = x + _mm(h, wl["fc2_w"], wl["fc2_b"], quant)
            return x, (kp, vp)

        x, (k_pages, v_pages) = jax.lax.scan(
            layer, x, (weights, k_pages, v_pages))
        x = _ln(x, self.ln_f_w, self.ln_f_b)
        logits = x.astype(jnp.float32) @ self.lm_head.astype(jnp.float32)
        return logits, k_pages, v_pages

    def _pos_keys(self, kids, pos):
        """Per-slot PRNG keys from (seed, kid, position): draws depend
        only on the decoder seed, the request identity (`kids` — the
        engine passes the request id; direct callers default to the
        slot index) and the position of the token being consumed.
        NOTHING about scheduling enters the key, so the same request
        sampled through the per-tick loop, the fused multi-step loop,
        or any admission/batch composition draws the same tokens."""
        base = jax.random.PRNGKey(self.seed)
        return jax.vmap(lambda kid, p: jax.random.fold_in(
            jax.random.fold_in(base, kid), p))(kids, pos)

    def _decode_step(self, weights, k_pages, v_pages, tokens, lens, table,
                     kids):
        """tokens [S], lens [S] (tokens already counted, i.e. position of
        the incoming token), table [S, max_pages], kids [S] (sampling
        key ids, see _pos_keys) -> (next [S], logits [S, V], k_pages,
        v_pages)."""
        ps = self.page_size
        pids = jnp.take_along_axis(table, (lens // ps)[:, None],
                                   axis=1)[:, 0]                # [S]
        offs = lens % ps
        logits, k_pages, v_pages = self._forward_tokens(
            weights, k_pages, v_pages, tokens, lens, table, pids, offs)
        keys = None
        if self.sampling is not None:
            keys = self._pos_keys(kids, lens)
        nxt = _sample_tokens(logits, self.sampling, keys)
        return nxt, logits, k_pages, v_pages

    def _decode_multi_step(self, weights, k_pages, v_pages, tokens, lens,
                           table, kids, done, remaining, eos, *, k,
                           return_logits=False):
        """K fused decode ticks inside ONE compiled program (lax.scan):
        each tick's sampled token feeds the next tick on device, so the
        host syncs once per K tokens instead of once per token.

        tokens/lens/table/kids as in `_decode_step`. Tick j draws with
        the (seed, kid, lens+j) key — exactly the keys the per-tick
        loop would use at those positions, so fused and per-tick decode
        emit byte-identical streams. `done` [S] bool freezes a slot
        from tick 0 (inactive or already finished); a slot also freezes
        itself after emitting its first `eos` (pass -1 for none) or
        after `remaining` [S] tokens (its budget). Frozen slots' `lens`
        stop advancing and their K/V writes route to the reserved
        scratch page, so the pages stay exactly as the per-tick engine
        would leave them.

        Returns (block [k, S] emitted tokens, done_before [k, S] — True
        where the slot was already frozen, i.e. the token is filler —
        final tokens/lens/done/remaining, k_pages, v_pages[, logits
        [k, S, V] when return_logits])."""
        ps = self.page_size
        scratch = self.num_pages - 1

        def tick(carry, _):
            tokens, lens, done, remaining, kp, vp = carry
            pids = jnp.take_along_axis(table, (lens // ps)[:, None],
                                       axis=1)[:, 0]
            pids = jnp.where(done, scratch, pids)
            offs = lens % ps
            logits, kp, vp = self._forward_tokens(
                weights, kp, vp, tokens, lens, table, pids, offs)
            keys = None
            if self.sampling is not None:
                keys = self._pos_keys(kids, lens)
            nxt = _sample_tokens(logits, self.sampling, keys)
            nxt = jnp.where(done, tokens, nxt)
            rem = jnp.where(done, remaining, remaining - 1)
            new_done = done | (nxt == eos) | (rem <= 0)
            new_lens = jnp.where(done, lens, lens + 1)
            out = (nxt, done, logits) if return_logits else (nxt, done)
            return (nxt, new_lens, new_done, rem, kp, vp), out

        carry = (tokens, lens, done, remaining, k_pages, v_pages)
        carry, outs = jax.lax.scan(tick, carry, jnp.arange(k))
        tokens, lens, done, remaining, k_pages, v_pages = carry
        ret = (outs[0], outs[1], tokens, lens, done, remaining,
               k_pages, v_pages)
        if return_logits:
            ret += (outs[2],)
        return ret

    def _verify_step(self, weights, k_pages, v_pages, tokens, lens, table):
        """Speculative verify: tokens [S, W] (last accepted token + the
        draft proposals) are consumed in ONE forward — KV written at
        positions lens..lens+W-1, causal attention against the paged
        prefix — returning the target's greedy choice after every
        position ([S, W] argmaxes). Rejected positions need no cleanup:
        lens is the source of truth and stale entries are overwritten."""
        cfg, ps = self.cfg, self.page_size
        H, D = cfg.num_heads, cfg.head_dim
        S, W = tokens.shape
        pos = lens[:, None] + jnp.arange(W)[None, :]            # [S, W]
        x = (self.wte[tokens] +
             self.wpe[jnp.clip(pos, 0, cfg.max_seq_len - 1)]
             ).astype(self.k_pages.dtype)                       # [S, W, h]
        MP = table.shape[1]
        # margin guard: window positions past the table's capacity (the
        # engine admits with a +k margin, so only pathological callers
        # get here) write to the reserved scratch page, never to a
        # clamped REAL page of the sequence
        in_range = pos < MP * ps
        pids = jnp.take_along_axis(table, jnp.minimum(pos // ps, MP - 1),
                                   axis=1)                      # [S, W]
        pids = jnp.where(in_range, pids, self.num_pages - 1)
        offs = pos % ps
        quant = self.quant

        def layer(x, wkv):
            wl, kp, vp = wkv
            y = _ln(x, wl["ln1_w"], wl["ln1_b"])
            xf = y.reshape(S * W, -1)
            qkv = _mm_heads(xf, wl["qkv_w"], wl["qkv_b"], quant)
            qkv = qkv.reshape(S, W, 3, H, D)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            kp = kp.at[pids, offs].set(k.astype(kp.dtype))
            vp = vp.at[pids, offs].set(v.astype(vp.dtype))
            # gather each slot's pages and attend with per-row causality
            kg = kp[table].reshape(S, MP * ps, H, D)            # [S, T, H, D]
            vg = vp[table].reshape(S, MP * ps, H, D)
            scale = 1.0 / float(np.sqrt(D))
            s = jnp.einsum("swhd,sthd->shwt", q.astype(jnp.float32),
                           kg.astype(jnp.float32)) * scale
            kpos = jnp.arange(MP * ps)[None, None, None, :]
            s = jnp.where(kpos <= pos[:, None, :, None], s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            attn = jnp.einsum("shwt,sthd->swhd", p,
                              vg.astype(jnp.float32)).astype(x.dtype)
            o = _mm(attn.reshape(S * W, H * D), wl["proj_w"],
                    wl["proj_b"], quant).reshape(S, W, -1)
            x = x + o
            y = _ln(x, wl["ln2_w"], wl["ln2_b"])
            yf = y.reshape(S * W, -1)
            h = jax.nn.gelu(_mm(yf, wl["fc1_w"], wl["fc1_b"], quant),
                            approximate=True)
            x = x + _mm(h, wl["fc2_w"], wl["fc2_b"],
                        quant).reshape(S, W, -1)
            return x, (kp, vp)

        x, (k_pages, v_pages) = jax.lax.scan(
            layer, x, (weights, k_pages, v_pages))
        x = _ln(x, self.ln_f_w, self.ln_f_b)
        logits = x.astype(jnp.float32) @ self.lm_head.astype(jnp.float32)
        return (jnp.argmax(logits, axis=-1).astype(jnp.int32), logits,
                k_pages, v_pages)

    def verify(self, tokens, lens, table, return_probs=False):
        """Batched speculative verify (see _verify_step)."""
        if self._verify is None:
            self._verify = jax.jit(self._verify_step,
                                   donate_argnums=(1, 2))
        out, logits, self.k_pages, self.v_pages = self._verify(
            self.weights, self.k_pages, self.v_pages,
            jnp.asarray(tokens, jnp.int32), jnp.asarray(lens, jnp.int32),
            jnp.asarray(table, jnp.int32))
        if return_probs:
            return np.asarray(out), self._probs_of(logits)
        return np.asarray(out)

    def _prefill_fn(self, Lp, n):
        """Per-(length-bucket, batch-bucket) compiled prefill: n padded
        sequences at once. Writes prompt KV into each sequence's pages
        and returns the n first tokens."""
        cfg, ps = self.cfg, self.page_size
        H, D = cfg.num_heads, cfg.head_dim
        n_pg = Lp // ps
        quant = self.quant

        def run(weights, k_pages, v_pages, ids, true_len, page_ids, kids):
            x = (self.wte[ids] + self.wpe[jnp.arange(Lp)][None]
                 ).astype(k_pages.dtype)                     # [n, Lp, h]

            def layer(x, wkv):
                wl, kp, vp = wkv
                y = _ln(x, wl["ln1_w"], wl["ln1_b"])
                qkv = _mm_heads(y.reshape(n * Lp, -1), wl["qkv_w"],
                                wl["qkv_b"], quant).reshape(n, Lp, 3, H, D)
                q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
                # Pallas flash kernel when backend/tiling allow, jnp
                # reference otherwise (one shared gate + fallback).
                # Padded-key masking is unnecessary: causal rows < true_len
                # never see cols >= true_len, padded rows' garbage stays
                # row-local, and only row true_len-1 feeds the logits.
                from .ops.attention import flash_raw_or_reference
                attn = flash_raw_or_reference(
                    q, k, v, causal=True, scale=1.0 / math.sqrt(D))
                x = x + _mm(attn.reshape(n * Lp, H * D).astype(x.dtype),
                            wl["proj_w"], wl["proj_b"],
                            quant).reshape(n, Lp, -1)
                y = _ln(x, wl["ln2_w"], wl["ln2_b"])
                h = jax.nn.gelu(
                    _mm(y.reshape(n * Lp, -1), wl["fc1_w"], wl["fc1_b"],
                        quant), approximate=True)
                x = x + _mm(h, wl["fc2_w"], wl["fc2_b"],
                            quant).reshape(n, Lp, -1)
                # page writes: static page count, dynamic page ids; the
                # requests' page sets are disjoint (scratch excepted)
                kpg = k.reshape(n, n_pg, ps, H, D).astype(kp.dtype)
                vpg = v.reshape(n, n_pg, ps, H, D).astype(vp.dtype)
                kp = kp.at[page_ids].set(kpg)
                vp = vp.at[page_ids].set(vpg)
                return x, (kp, vp)

            x, (k_pages, v_pages) = jax.lax.scan(
                layer, x, (weights, k_pages, v_pages))
            x = _ln(x, self.ln_f_w, self.ln_f_b)
            last = jnp.take_along_axis(
                x, (true_len - 1)[:, None, None].astype(jnp.int32),
                axis=1)[:, 0]                                # [n, h]
            logits = last.astype(jnp.float32) @ \
                self.lm_head.astype(jnp.float32)
            keys = None
            if self.sampling is not None:
                # same (seed, kid, position) key walk as decode: the
                # prompt's last token sits at true_len-1, so the first
                # generated token draws with that position — whatever
                # chunk/bucket the request was prefilled in
                keys = self._pos_keys(kids, true_len - 1)
            return _sample_tokens(logits, self.sampling, keys), \
                k_pages, v_pages

        return jax.jit(run, donate_argnums=(1, 2))

    # -- host-side API -----------------------------------------------------

    def prefill(self, ids, page_ids, kid=None):
        """Run one prompt through the model, writing KV into `page_ids`;
        returns the next token (greedy, or sampled per the decoder's
        temperature/top_k/top_p config)."""
        return self.prefill_batch([(ids, page_ids)],
                                  kids=None if kid is None else [kid])[0]

    def prefill_batch(self, requests, kids=None):
        """Prefill several prompts, batching same-length-bucket groups
        into single forwards. requests: [(ids, page_ids), ...]; returns
        the first generated token per request (in order). `kids` are
        the per-request sampling key ids (see _pos_keys; the engine
        passes request ids — default: the request's index in this
        call)."""
        ps = self.page_size
        results = [None] * len(requests)
        if kids is None:
            kids = list(range(len(requests)))
        groups = {}
        for i, (ids, page_ids) in enumerate(requests):
            ids = np.asarray(ids, np.int32)
            Lp = max(ps, ps * (2 ** math.ceil(
                math.log2(max(1, (len(ids) + ps - 1) // ps)))))
            groups.setdefault(Lp, []).append((i, ids, page_ids))
        for Lp, group in groups.items():
            n_pg = Lp // ps
            while group:
                # batch-bucket to powers of two (bounded compile count)
                nb = 1
                while nb * 2 <= len(group) and nb * 2 <= self.max_batch:
                    nb *= 2
                chunk, group = group[:nb], group[nb:]
                pad = np.zeros((nb, Lp), np.int32)
                tl = np.ones(nb, np.int32)
                pg = np.full((nb, n_pg), self.num_pages - 1, np.int32)
                kd = np.zeros(nb, np.int32)
                for r, (i, ids, page_ids) in enumerate(chunk):
                    pad[r, :len(ids)] = ids
                    tl[r] = len(ids)
                    kd[r] = kids[i]
                    k = min(len(page_ids), n_pg)
                    pg[r, :k] = page_ids[:k]   # rest stays on scratch
                key = (Lp, nb)
                if key not in self._prefills:
                    self._prefills[key] = self._prefill_fn(Lp, nb)
                self._draws += 1
                nxt, self.k_pages, self.v_pages = self._prefills[key](
                    self.weights, self.k_pages, self.v_pages,
                    jnp.asarray(pad), jnp.asarray(tl), jnp.asarray(pg),
                    jnp.asarray(kd))
                nxt = np.asarray(nxt)
                for r, (i, _, _) in enumerate(chunk):
                    results[i] = int(nxt[r])
        return results

    def analysis_program(self, donate=True, k=None):
        """Graph Doctor view of the compiled decode program: one fresh
        trace with per-argument role capture — weights/embeddings are
        `param` (read-only across steps, NOT donated: that's correct
        for inference), the K/V page pools are `cache` with
        donated=True matching the real donate_argnums=(1,2) (the cache
        is the decode loop's carried state — an undonated cache is the
        MEM-NO-DONATION-KVCACHE lint), everything else is `input`.

        With `k` the FUSED multi-step program (`_decode_multi_step`, K
        device-resident ticks in one lax.scan) is traced instead of the
        single tick — the SERVE-HOST-SYNC-DECODE rule checks it for
        host transfers and kept cache donation. `donate=False` traces
        the defective variant the planted-defect tests lint."""
        from .analysis.lowering import LoweredProgram, tree_arg_infos

        S = self.max_batch
        tokens = jnp.zeros((S,), jnp.int32)
        lens = jnp.zeros((S,), jnp.int32)
        table = jnp.zeros((S, self.max_pages), jnp.int32)
        kids = jnp.arange(S, dtype=jnp.int32)
        inputs = [("tokens", tokens), ("lens", lens), ("table", table),
                  ("kids", kids)]
        if k:
            done = jnp.zeros((S,), bool)
            remaining = jnp.full((S,), int(k), jnp.int32)
            eos = jnp.asarray(-1, jnp.int32)
            inputs += [("done", done), ("remaining", remaining),
                       ("eos", eos)]
            fn = jax.jit(functools.partial(self._decode_multi_step,
                                           k=int(k)),
                         donate_argnums=(1, 2) if donate else ())
            traced = fn.trace(self.weights, self.k_pages, self.v_pages,
                              tokens, lens, table, kids, done, remaining,
                              eos)
            name = f"decode_multi_k{int(k)}"
        else:
            fn = jax.jit(self._decode_step,
                         donate_argnums=(1, 2) if donate else ())
            traced = fn.trace(self.weights, self.k_pages, self.v_pages,
                              tokens, lens, table, kids)
            name = "decode_step"
        infos = tree_arg_infos(self.weights, "param")
        infos += tree_arg_infos(self.k_pages, "cache", prefix="k_pages",
                                donated=donate)
        infos += tree_arg_infos(self.v_pages, "cache", prefix="v_pages",
                                donated=donate)
        for nm, v in inputs:
            infos += tree_arg_infos(v, "input", prefix=nm)
        return LoweredProgram(traced.lower().as_text(),
                              jaxpr=traced.jaxpr, name=name,
                              arg_infos=infos)

    def step_hbm_bytes(self, avg_ctx=None):
        """HBM bytes ONE decode tick moves: every weight byte plus each
        slot's KV prefix at `avg_ctx` (default: half the model's max
        sequence). The numerator of the decode tick roofline —
        `cost_model.decode_horizon` prices the default multi-step K
        from it; bench.decode_roofline_tok_s is the tok/s view of the
        same bytes model."""
        cfg = self.cfg
        n = cfg.num_params()
        per = {"a8w8": 1.0, "w4a16": 0.5}.get(self.quant)
        if per is not None:
            h, f = cfg.hidden_size, cfg.ffn_hidden
            lin = cfg.num_layers * (4 * h * h + 2 * h * f)
            w_bytes = lin * per + (n - lin) * 2
        else:
            w_bytes = n * 2
        if avg_ctx is None:
            avg_ctx = max(cfg.max_seq_len // 2, 1)
        kv = (self.max_batch * cfg.num_layers * 2 * avg_ctx *
              cfg.num_heads * cfg.head_dim *
              jnp.dtype(self.k_pages.dtype).itemsize)
        return int(w_bytes + kv)

    def _kids_or_default(self, kids):
        if kids is None:
            return np.arange(self.max_batch, dtype=np.int32)
        return np.asarray(kids, np.int32)

    def decode(self, tokens, lens, table, kids=None, return_probs=False):
        """One decode step for all slots (greedy, or the configured
        sampling with deterministic per-(seed, kid, position) keys —
        kid defaults to the slot index; the engine passes request ids
        so a request's draws are scheduling-independent).
        return_probs additionally yields the [S, V] distribution the
        token was drawn from (speculative acceptance needs it)."""
        self._draws += 1
        nxt, logits, self.k_pages, self.v_pages = self._decode(
            self.weights, self.k_pages, self.v_pages,
            jnp.asarray(tokens, jnp.int32), jnp.asarray(lens, jnp.int32),
            jnp.asarray(table, jnp.int32),
            jnp.asarray(self._kids_or_default(kids)))
        if return_probs:
            return nxt, self._probs_of(logits)
        return nxt

    def decode_multi(self, tokens, lens, table, k, kids=None, done=None,
                     remaining=None, eos=None, return_logits=False):
        """Run `k` decode ticks device-resident: ONE dispatch, zero
        intermediate host syncs (see `_decode_multi_step`). Jitted per
        (k, return_logits); the engine buckets k to powers of two so
        the compile count stays bounded like the prefill buckets.

        All inputs/outputs may stay on device: the engine feeds the
        returned tokens/lens/done/remaining straight into the next
        horizon's call and fetches tokens_block/done_before only at
        sync points. `kids` are per-slot sampling key ids (see
        `_pos_keys`; default slot index), `done` marks slots frozen
        from tick 0 (default none), `remaining` per-slot token budgets
        (default unlimited), `eos` the stop token (default none).
        Returns a MultiDecodeOut;
        `logits_block` is None unless return_logits (speculation wants
        the draft's distributions)."""
        k = int(k)
        S = self.max_batch
        key = (k, bool(return_logits))
        fn = self._multis.get(key)
        if fn is None:
            fn = jax.jit(
                functools.partial(self._decode_multi_step, k=k,
                                  return_logits=bool(return_logits)),
                donate_argnums=(1, 2))
            self._multis[key] = fn
        if done is None:
            done = np.zeros(S, bool)
        if remaining is None:
            remaining = np.full(S, np.iinfo(np.int32).max // 2, np.int32)
        self._draws += k             # dispatch telemetry, not key state
        out = fn(self.weights, self.k_pages, self.v_pages,
                 jnp.asarray(tokens, jnp.int32),
                 jnp.asarray(lens, jnp.int32),
                 jnp.asarray(table, jnp.int32),
                 jnp.asarray(self._kids_or_default(kids)),
                 jnp.asarray(done, bool),
                 jnp.asarray(remaining, jnp.int32),
                 jnp.asarray(-1 if eos is None else int(eos), jnp.int32))
        self.k_pages, self.v_pages = out[6], out[7]
        return MultiDecodeOut(out[0], out[1], out[2], out[3], out[4],
                              out[5], out[8] if return_logits else None)


class ContinuousBatchingEngine:
    """Slot-based continuous batching: requests are admitted into free
    slots as soon as capacity allows (iteration-level scheduling), decode
    runs one compiled step for ALL active slots, finished sequences free
    their pages.

    By default `run()` schedules in HORIZONS: blocks of
    `k = min(k_max, smallest remaining budget)` device-resident decode
    ticks (`PagedGPTDecoder.decode_multi`), with the host syncing only
    at block boundaries for admission/retirement/output append, and each
    block's fetch overlapped against the NEXT block's dispatch
    (one-horizon-delayed retirement: a slot finishing inside block N
    stays frozen on device through block N+1 — its writes route to the
    scratch page — and its pages are freed exactly once, when block N is
    processed). `k_max` defaults to `cost_model.decode_horizon`'s priced
    answer; `k_max=1` selects the legacy per-tick loop (`step()` is the
    per-tick API either way)."""

    def __init__(self, decoder: PagedGPTDecoder, eos_token_id=None,
                 max_new_tokens=64, k_max=None, host_sync_s=None):
        if max_new_tokens < 1:
            raise ValueError(
                "max_new_tokens must be >= 1 (the prefill forward always "
                f"produces one token), got {max_new_tokens}")
        self.d = decoder
        self.eos = eos_token_id
        self.max_new = max_new_tokens
        # page 0..num_pages-2 allocatable; last page reserved as scratch
        self._free = list(range(decoder.num_pages - 2, -1, -1))
        S = decoder.max_batch
        self._slot_req = [None] * S          # request id per slot
        self._slot_pages = [[] for _ in range(S)]
        # int32 end to end: decode() feeds these to the kernel as int32,
        # so int64 here would insert a convert_element_type every tick
        self._lens = np.zeros(S, np.int32)
        self._tokens = np.zeros(S, np.int32)
        self._kids = np.zeros(S, np.int32)   # request id per slot: the
        # sampling key id, so a request's draws are independent of
        # which slot/batch/schedule served it
        self._table_cache = None             # rebuilt on admit/retire only
        self._queue = []                     # (req_id, ids)
        self._outputs = {}                   # req_id -> [generated ids]
        self._next_id = 0
        self.steps = 0
        if k_max is None:
            from .cost_model import decode_horizon
            k_max = decode_horizon(decoder.step_hbm_bytes(),
                                   host_sync_s=host_sync_s)
        self.k_max = max(1, int(k_max))
        self.stats = ServeStats(engine=type(self).__name__,
                                k_max=self.k_max)
        self._submit_t = {}                  # rid -> submit wall time
        _ENGINES.add(self)

    def submit(self, prompt_ids):
        ids = [int(t) for t in np.asarray(
            prompt_ids._value if isinstance(prompt_ids, Tensor)
            else prompt_ids).reshape(-1)]
        total = len(ids) + self.max_new
        need = self._pages_for(total)
        if need > min(self.d.max_pages, self.d.num_pages - 1):
            raise ValueError(
                f"request needs {need} pages (prompt {len(ids)} + "
                f"max_new {self.max_new} tokens) but the pool allows "
                f"{min(self.d.max_pages, self.d.num_pages - 1)}")
        if total > self.d.cfg.max_seq_len:
            raise ValueError(
                f"prompt {len(ids)} + max_new {self.max_new} tokens "
                f"exceeds the model's max_seq_len "
                f"{self.d.cfg.max_seq_len} (positions past it have no "
                "embedding)")
        return self._register_request(ids)

    def _register_request(self, ids):
        """Queue a VALIDATED request: rid allocation, queue-wait stamp,
        stats — one implementation for both engines' submit()s, and
        called only after validation so a rejected submission can't
        skew stats.requests or leak a _submit_t entry."""
        rid = self._next_id
        self._next_id += 1
        self._submit_t[rid] = time.perf_counter()
        self.stats.requests += 1
        self._queue.append((rid, ids))
        return rid

    def _pages_for(self, n_tokens):
        return (n_tokens + self.d.page_size - 1) // self.d.page_size

    def _admit(self):
        # gather every admittable request first: same-length-bucket
        # prompts then prefill as ONE batched forward (iteration-level
        # batching applies to prefill too, not just decode). Pages freed
        # by EOS-at-prefill become available from the NEXT step's pass.
        # Returns the slots that entered decode (the multi-step run loop
        # merges exactly those into its device carry).
        admitted = self._gather_admissions()
        if not admitted:
            return []
        now = time.perf_counter()
        for _, rid, _, _ in admitted:
            t0 = self._submit_t.pop(rid, None)
            if t0 is not None:
                self.stats.queue_wait_s.append(now - t0)
        self._table_cache = None
        firsts = self.d.prefill_batch(
            [(ids, pages) for _, _, ids, pages in admitted],
            kids=[rid for _, rid, _, _ in admitted])
        self.stats.prefill_syncs += 1
        self._extra_prefill(admitted)
        live = []
        for (slot, rid, ids, pages), first in zip(admitted, firsts):
            self._outputs[rid] = [first]
            self.stats.tokens += 1
            if (self.eos is not None and first == self.eos) \
                    or self.max_new <= 1:
                # finished at prefill: never occupy a decode slot
                self._retire(slot)
                continue
            self._lens[slot] = len(ids)
            self._tokens[slot] = first
            self._kids[slot] = rid
            self._after_admit(slot, len(ids))
            live.append(slot)
        return live

    def _gather_admissions(self):
        admitted = []
        for slot in range(self.d.max_batch):
            if self._slot_req[slot] is not None or not self._queue:
                continue
            rid, ids = self._queue[0]
            need = self._pages_for(len(ids) + self.max_new)
            if need > len(self._free) or need > self.d.max_pages:
                break                        # head-of-line: wait for pages
            self._queue.pop(0)
            pages = [self._free.pop() for _ in range(need)]
            self._slot_req[slot] = rid
            self._slot_pages[slot] = pages
            admitted.append((slot, rid, ids, pages))
        return admitted

    def _extra_prefill(self, admitted):
        pass                                 # SpeculativeEngine: draft

    def _after_admit(self, slot, prompt_len):
        pass                                 # SpeculativeEngine: _dlens

    def _retire(self, slot):
        self._free.extend(self._slot_pages[slot])
        self._slot_req[slot] = None
        self._slot_pages[slot] = []
        self._lens[slot] = 0
        self._tokens[slot] = 0
        self._table_cache = None
        self.stats.completed += 1

    def _table(self, pages_per_slot, decoder):
        """Page table with inactive/unused entries routed to the reserved
        scratch page (their masked, discarded KV writes must never land
        in allocatable pages)."""
        t = np.full((decoder.max_batch, decoder.max_pages),
                    decoder.num_pages - 1, np.int32)
        for s, pg in enumerate(pages_per_slot):
            if pg:
                t[s, :len(pg)] = pg
        return t

    def step(self):
        """Admit + one decode tick. Returns number of active slots."""
        self._admit()
        active = [s for s in range(self.d.max_batch)
                  if self._slot_req[s] is not None]
        if not active:
            return 0
        if self._table_cache is None:        # slots changed since last tick
            self._table_cache = self._table(self._slot_pages, self.d)
        nxt = np.asarray(self.d.decode(self._tokens, self._lens,
                                       self._table_cache,
                                       kids=self._kids))
        self.steps += 1
        self.stats.ticks += 1
        self.stats.decode_syncs += 1
        self.stats.occupancy.append(len(active) / self.d.max_batch)
        for s in active:
            rid = self._slot_req[s]
            tok = int(nxt[s])
            self._outputs[rid].append(tok)
            self.stats.tokens += 1
            self._lens[s] += 1
            self._tokens[s] = tok
            done = (self.eos is not None and tok == self.eos) or \
                len(self._outputs[rid]) >= self.max_new
            if done:
                self._retire(s)
        return len(active)

    def run(self, step_times=None):
        """Drain the queue; returns {request_id: generated token list}.
        `step_times`, if given, receives wall seconds per host sync —
        per decode tick on the per-tick path (k_max=1), per K-tick
        horizon on the multi-step path (use `self.stats` for per-token
        percentiles either way)."""
        if self.k_max <= 1:
            return self._run_per_tick(step_times)
        return self._run_multi(step_times)

    def _run_per_tick(self, step_times=None):
        """Legacy loop: one compiled tick, one host sync per token."""
        while self._queue or any(r is not None for r in self._slot_req):
            t0 = time.perf_counter()
            before = self.stats.tokens
            before_p = self.stats.prefill_syncs
            self.step()
            dt = time.perf_counter() - t0
            if step_times is not None:
                step_times.append(dt)
            n = self.stats.tokens - before
            # token_time_s is the STEADY-STATE decode latency: a sync
            # that contained a prefill is dominated by it (orders of
            # magnitude more work than a tick) and would turn p99 into
            # a prefill number — keep it out of the percentiles
            if n and self.stats.prefill_syncs == before_p:
                self.stats.token_time_s.extend([dt / n] * n)
        return dict(self._outputs)

    def _budget_left(self, slot):
        """Tokens this slot may still emit (host view, excludes ticks
        already dispatched but not yet processed)."""
        return self.max_new - len(self._outputs[self._slot_req[slot]])

    def _horizon(self, slots, inflight):
        """Largest power-of-two tick count ≤ k_max that fits every
        dispatchable slot's remaining budget (powers of two bound the
        decode_multi compile count, like the prefill buckets)."""
        rem = min(self._budget_left(s) - inflight[s] for s in slots)
        k = 1
        while k * 2 <= min(rem, self.k_max):
            k *= 2
        return k

    def _merge_carry(self, carry, admitted):
        """Device-resident decode state for the next horizon. The carry
        never round-trips through the host: newly admitted slots are
        scattered into the in-flight arrays with device ops."""
        S = self.d.max_batch
        if carry is None:
            done = np.array([r is None for r in self._slot_req])
            rem = np.array([self._budget_left(s) if self._slot_req[s]
                            is not None else 0 for s in range(S)],
                           np.int32)
            return (jnp.asarray(self._tokens), jnp.asarray(self._lens),
                    jnp.asarray(done), jnp.asarray(rem))
        if not admitted:
            return carry
        tokens, lens, done, rem = carry
        idx = jnp.asarray(admitted, jnp.int32)
        tokens = tokens.at[idx].set(jnp.asarray(self._tokens[admitted]))
        lens = lens.at[idx].set(jnp.asarray(self._lens[admitted]))
        done = done.at[idx].set(False)
        rem = rem.at[idx].set(jnp.asarray(
            [self._budget_left(s) for s in admitted], jnp.int32))
        return tokens, lens, done, rem

    def _process_block(self, meta, inflight, step_times,
                       prefilled_since=False):
        """Fetch + bookkeep one finished horizon. Called AFTER the next
        horizon is dispatched, so the device→host wait overlaps it."""
        block_d, done_before_d, k, rids, t0, had_prefill = meta
        block = np.asarray(block_d)
        done_before = np.asarray(done_before_d)
        self.stats.decode_syncs += 1
        emitted = 0
        for s, rid in rids.items():
            inflight[s] = max(0, inflight[s] - k)
            if self._slot_req[s] != rid:
                continue
            for j in range(k):
                if done_before[j, s]:
                    break
                tok = int(block[j, s])
                self._outputs[rid].append(tok)
                self.stats.tokens += 1
                emitted += 1
                self._lens[s] += 1
                self._tokens[s] = tok
                if (self.eos is not None and tok == self.eos) or \
                        len(self._outputs[rid]) >= self.max_new:
                    self._retire(s)
                    break
        dt = time.perf_counter() - t0
        if step_times is not None:
            step_times.append(dt)
        # steady-state decode latency only: the block's dt window spans
        # its dispatch iteration AND the next iteration up to this
        # call, so a prefill in either (had_prefill at dispatch,
        # prefilled_since at processing) would make p99 a prefill
        # number — exclude such blocks from the percentiles (see
        # _run_per_tick)
        if emitted and not had_prefill and not prefilled_since:
            self.stats.token_time_s.extend([dt / emitted] * emitted)

    def _run_multi(self, step_times=None):
        """Horizon-scheduled drain: dispatch a K-tick device-resident
        block, then process the PREVIOUS block while the new one runs.
        Retirement is one horizon delayed — a slot that finishes inside
        block N stays frozen on device through block N+1 (done mask
        carried on device; its K/V writes route to the scratch page)
        and its pages are freed exactly once, when block N's results
        land on the host."""
        S = self.d.max_batch
        pending = None               # the in-flight horizon's meta
        carry = None                 # device (tokens, lens, done, rem)
        inflight = [0] * S           # dispatched-not-yet-processed ticks
        while (self._queue or pending is not None
               or any(r is not None for r in self._slot_req)):
            t0 = time.perf_counter()
            admitted = self._admit()
            carry = self._merge_carry(carry, admitted)
            # invariant: for a live non-admitted slot, the device-side
            # `remaining` equals budget_left - inflight exactly (both
            # count init budget minus dispatched ticks), so a slot
            # excluded here is always already frozen on device — its
            # ticks in another slot's block are filler, never lost
            # tokens
            disp = [s for s in range(S) if self._slot_req[s] is not None
                    and self._budget_left(s) - inflight[s] > 0]
            meta = None
            if disp:
                k = self._horizon(disp, inflight)
                if self._table_cache is None:
                    self._table_cache = self._table(self._slot_pages,
                                                    self.d)
                tokens_d, lens_d, done_d, rem_d = carry
                out = self.d.decode_multi(
                    tokens_d, lens_d, self._table_cache, k,
                    kids=self._kids, done=done_d, remaining=rem_d,
                    eos=self.eos)
                carry = (out.tokens, out.lens, out.done, out.remaining)
                self.steps += k
                self.stats.ticks += k
                self.stats.occupancy.append(len(disp) / S)
                for s in disp:
                    inflight[s] += k
                meta = (out.tokens_block, out.done_before, k,
                        {s: self._slot_req[s] for s in disp}, t0,
                        bool(admitted))
            if pending is not None:
                self._process_block(pending, inflight, step_times,
                                    prefilled_since=bool(admitted))
            pending = meta
        return dict(self._outputs)


class SpeculativeEngine(ContinuousBatchingEngine):
    """Speculative decoding over the paged engine: a small DRAFT model
    proposes k tokens with k cheap decode ticks; the TARGET model scores
    all of them in ONE verify forward. Greedy configs accept the longest
    matching prefix (+ the target's token at the first mismatch) —
    output is EXACTLY the target's greedy decode; sampled configs (same
    temperature/top-k/top-p on both decoders) use rejection-sampling
    acceptance (_spec_accept), so emitted tokens are distributed exactly
    as target-only sampling. Either way: up to k-times fewer target
    forwards. Paged KV makes rollback free: `lens` is the source of
    truth, rejected positions are simply overwritten.

    Acceptance is capped at k-1 drafts so the draft cache (which holds
    proposals d1..d_{k-1}) never falls behind; when all k drafts match,
    the capped path still emits exactly d1..dk.
    """

    def __init__(self, decoder, draft_decoder, eos_token_id=None,
                 max_new_tokens=64, k=4):
        if decoder.sampling != draft_decoder.sampling:
            raise ValueError(
                "speculative decoding needs the SAME sampling config on "
                "target and draft (acceptance compares their masked "
                f"distributions): {decoder.sampling} vs "
                f"{draft_decoder.sampling}")
        if draft_decoder.max_batch != decoder.max_batch or \
                draft_decoder.page_size != decoder.page_size:
            raise ValueError("draft/target max_batch and page_size must match")
        # k_max=1: the verify cadence IS this engine's horizon — each
        # step() already moves a k-token window; the draft's ticks are
        # device-resident via decode_multi below
        super().__init__(decoder, eos_token_id, max_new_tokens, k_max=1)
        self.draft = draft_decoder
        self.k = int(k)
        self._draft_free = list(range(draft_decoder.num_pages - 2, -1, -1))
        self._draft_pages = [[] for _ in range(decoder.max_batch)]
        self._dlens = np.zeros(decoder.max_batch, np.int32)
        self.target_calls = 0

    def submit(self, prompt_ids):
        """Same as the base, with a +k margin: a verify window can write
        up to k positions past the final accepted length."""
        ids = np.asarray(prompt_ids._value if isinstance(prompt_ids, Tensor)
                         else prompt_ids).reshape(-1)
        total = len(ids) + self.max_new + self.k
        need = self._pages_for(total)
        limit = min(self.d.max_pages, self.draft.max_pages,
                    self.d.num_pages - 1, self.draft.num_pages - 1)
        if need > limit:
            raise ValueError(
                f"request needs {need} pages (prompt {len(ids)} + max_new "
                f"{self.max_new} + speculation margin {self.k}) but the "
                f"pools allow {limit}")
        if total > min(self.d.cfg.max_seq_len, self.draft.cfg.max_seq_len):
            raise ValueError(
                f"prompt {len(ids)} + max_new {self.max_new} + margin "
                f"{self.k} exceeds max_seq_len "
                f"{min(self.d.cfg.max_seq_len, self.draft.cfg.max_seq_len)}")
        return self._register_request([int(t) for t in ids])

    def _gather_admissions(self):
        admitted = []
        for slot in range(self.d.max_batch):
            if self._slot_req[slot] is not None or not self._queue:
                continue
            rid, ids = self._queue[0]
            # +k margin: a verify window may write up to k positions past
            # the final accepted length
            need = self._pages_for(len(ids) + self.max_new + self.k)
            if need > len(self._free) or need > len(self._draft_free) \
                    or need > self.d.max_pages \
                    or need > self.draft.max_pages:
                break
            self._queue.pop(0)
            pages = [self._free.pop() for _ in range(need)]
            dpages = [self._draft_free.pop() for _ in range(need)]
            self._slot_req[slot] = rid
            self._slot_pages[slot] = pages
            self._draft_pages[slot] = dpages
            admitted.append((slot, rid, ids, pages))
        return admitted

    def _extra_prefill(self, admitted):
        self.draft.prefill_batch(           # draft's guesses discarded
            [(ids, self._draft_pages[slot])
             for slot, _, ids, _ in admitted],
            kids=[rid for _, rid, _, _ in admitted])

    def _after_admit(self, slot, prompt_len):
        self._dlens[slot] = prompt_len

    def _retire(self, slot):
        self._draft_free.extend(self._draft_pages[slot])
        self._draft_pages[slot] = []
        self._dlens[slot] = 0
        super()._retire(slot)

    def step(self):
        self._admit()
        active = [s for s in range(self.d.max_batch)
                  if self._slot_req[s] is not None]
        if not active:
            return 0
        k = self.k
        if self._table_cache is None:        # slots changed since last tick
            self._table_cache = (self._table(self._slot_pages, self.d),
                                 self._table(self._draft_pages, self.draft))
        ttable, dtable = self._table_cache

        sampled = self.d.sampling is not None

        # draft proposes k tokens: K DEVICE-RESIDENT ticks in ONE
        # compiled loop (decode_multi) — the proposal chain feeds back
        # on device, so the k cheap ticks cost one dispatch + one fetch
        # instead of k host round-trips
        qrows = None
        out = self.draft.decode_multi(self._tokens, self._dlens, dtable,
                                      k, kids=self._kids,
                                      return_logits=sampled)
        proposals = np.asarray(out.tokens_block).T.astype(np.int32)
        if sampled and k > 1:
            # the k-th draft's distribution is never judged (acceptance
            # is capped at k-1): skip its transfer
            qp = self.draft._probs_of(out.logits_block[:k - 1])
            qrows = np.moveaxis(qp, 0, 1)          # [S, k-1, V]
        self.stats.ticks += k
        self.stats.decode_syncs += 1

        # target verifies [cur, d1..dk] in one forward
        window = np.concatenate(
            [self._tokens[:, None], proposals[:, :k]], axis=1)  # [S, k+1]
        if sampled:
            tgt, prows = self.d.verify(window, self._lens, ttable,
                                       return_probs=True)
        else:
            tgt = self.d.verify(window, self._lens, ttable)     # [S, k+1]
        self.target_calls += 1
        self.steps += 1
        self.stats.ticks += 1
        self.stats.decode_syncs += 1
        self.stats.occupancy.append(len(active) / self.d.max_batch)

        for s in active:
            rid = self._slot_req[s]
            if sampled:
                rng = np.random.default_rng(
                    (self.d.seed * 1000003 + self.target_calls) * 4093 + s)
                a, tok = _spec_accept(
                    prows[s, :k],
                    qrows[s] if qrows is not None else
                    np.zeros((0, prows.shape[-1])),
                    proposals[s, :k - 1], rng)
                emitted = [int(t) for t in proposals[s, :a]] + [tok]
            else:
                a = 0
                while a < k - 1 and proposals[s, a] == tgt[s, a]:
                    a += 1
                emitted = [int(t) for t in proposals[s, :a]] + \
                    [int(tgt[s, a])]
            L = int(self._lens[s])
            self._lens[s] = L + a + 1
            self._dlens[s] = L + a + 1
            self._tokens[s] = emitted[-1]
            done = False
            for t in emitted:
                self._outputs[rid].append(t)
                self.stats.tokens += 1
                if (self.eos is not None and t == self.eos) or \
                        len(self._outputs[rid]) >= self.max_new:
                    done = True      # tokens speculated past the stop
                    break            # point are simply never appended
            if done:
                self._retire(s)
        return len(active)
