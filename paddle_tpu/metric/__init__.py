"""Metrics — reference python/paddle/metric/metrics.py."""
import numpy as np

from ..framework.core import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _np(x):
    return np.asarray(x._value) if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None, *args, **kwargs):
        super().__init__()
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        idx = np.argsort(-pred_np, axis=-1)[..., : self.maxk]
        # reference metrics.py Accuracy.compute: a trailing dim of 1 means
        # INDEX labels [N, ..., 1]; only wider trailing dims are one-hot.
        # (ndim alone misclassifies [N, 1] int labels as one-hot and
        # argmax turns every label into class 0.)
        if label_np.ndim == pred_np.ndim:
            if label_np.shape[-1] == 1:
                label_np = label_np[..., 0]
            else:
                label_np = np.argmax(label_np, axis=-1)
        correct = (idx == label_np[..., None]).astype(np.float32)
        return Tensor(correct)

    def update(self, correct, *args):
        c = _np(correct)
        num = c.shape[0]
        accs = []
        for k in self.topk:
            acc_k = c[..., :k].sum(-1).mean()
            accs.append(acc_k)
            self.total[self.topk.index(k)] += c[..., :k].sum()
        self.count += num
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = 0

    def accumulate(self):
        res = [t / max(self.count, 1) for t in self.total]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc", *args, **kwargs):
        super().__init__()
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2:
            p = p[:, 1]
        l = _np(labels).reshape(-1)
        bins = np.minimum((p * self.num_thresholds).astype(np.int64), self.num_thresholds - 1)
        pos = l.astype(bool)
        self._stat_pos += np.bincount(bins[pos], minlength=self.num_thresholds)
        self._stat_neg += np.bincount(bins[~pos], minlength=self.num_thresholds)

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds, np.int64)

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if not tot_pos or not tot_neg:
            return 0.0
        # trapezoid over descending threshold, from the (0,0) origin —
        # reference metrics.py Auc.accumulate starts tot_pos/tot_neg at 0
        # so the first bucket contributes a triangle too
        tp = np.concatenate([[0], np.cumsum(self._stat_pos[::-1])])
        fp = np.concatenate([[0], np.cumsum(self._stat_neg[::-1])])
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        return float(np.trapezoid(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    import jax.numpy as jnp

    from ..framework.core import apply_op

    def _f(p, l):
        idx = jnp.argsort(-p, axis=-1)[..., :k]
        ll = l if l.ndim == p.ndim - 1 else l[..., 0]
        good = jnp.any(idx == ll[..., None], axis=-1)
        return jnp.mean(good.astype(jnp.float32))
    return apply_op(_f, input, label)
