"""paddle_tpu — a TPU-native deep learning framework with the API surface of
PaddlePaddle (reference: /root/reference, a Paddle v2.3 fork).

Not a port: compute lowers to XLA via jax/jnp/pallas; distribution is GSPMD
over jax.sharding meshes; eager mode is XLA-eager with a lightweight autograd
tape; the performance path compiles whole train steps with jax.jit.
"""
__version__ = "0.1.0"

# Honor an explicit JAX_PLATFORMS=cpu request BEFORE any backend init: the
# axon TPU-tunnel plugin (when present on this box) force-selects
# jax_platforms="axon,cpu" at registration, so a user asking for CPU would
# still block on the shared (and sometimes down) tunnel the moment
# jax.devices() runs. Dropping the factory is what tests/conftest.py does;
# doing it here makes `JAX_PLATFORMS=cpu python examples/...` work too.
import os as _os

if _os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
    try:
        import jax as _jax
        import jax._src.xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
        _jax.config.update("jax_platforms", "cpu")
    except Exception as _e:  # private jax API moved — fail LOUD, not silent:
        # swallowing this would reproduce the exact tunnel-block the guard
        # exists to prevent, with zero diagnostic
        import sys as _sys

        print(f"paddle_tpu: could not honor JAX_PLATFORMS=cpu "
              f"({type(_e).__name__}: {_e}); the axon TPU plugin may still "
              f"grab the tunnel", file=_sys.stderr)
        del _sys
del _os

from . import autograd, framework, tensor
from .framework import (  # noqa: F401
    CPUPlace,
    CUDAPlace,
    Parameter,
    Tensor,
    TPUPlace,
    bfloat16,
    bool,  # noqa: A004
    complex64,
    complex128,
    disable_static,
    dtype,
    enable_grad,
    float16,
    float32,
    float64,
    get_default_dtype,
    get_device,
    get_rng_state,
    in_dynamic_mode,
    int8,
    int16,
    int32,
    int64,
    is_grad_enabled,
    no_grad,
    seed,
    set_default_dtype,
    set_device,
    set_rng_state,
    uint8,
)
from .framework import (  # noqa: F401
    create_parameter,
    enable_static,
    in_dygraph_mode,
    set_grad_enabled,
    set_printoptions,
)
from .framework.device import CUDAPinnedPlace, NPUPlace  # noqa: F401
from .framework.random import get_rng_state as get_cuda_rng_state  # noqa: F401
from .framework.random import set_rng_state as set_cuda_rng_state  # noqa: F401
from .framework.core import to_tensor  # noqa: F401
from .tensor import *  # noqa: F401,F403
from .autograd import grad  # noqa: F401

# subpackages (gate lets the core be imported standalone during bring-up)
import os as _os

if _os.environ.get("PADDLE_TPU_CORE_ONLY") != "1":
    from . import nn  # noqa: F401,E402
    from . import optimizer  # noqa: F401,E402
    from . import distributed  # noqa: F401,E402
    from . import io  # noqa: F401,E402
    from . import metric  # noqa: F401,E402
    from . import amp  # noqa: F401,E402
    from . import vision  # noqa: F401,E402
    from . import jit  # noqa: F401,E402
    from . import static  # noqa: F401,E402
    from . import distribution  # noqa: F401,E402
    from . import incubate  # noqa: F401,E402
    from .hapi.model import Model  # noqa: F401,E402
    from .framework.io import load, save  # noqa: F401,E402
    from . import fft  # noqa: F401,E402
    from . import signal  # noqa: F401,E402
    from . import sparse  # noqa: F401,E402
    from . import device  # noqa: F401,E402
    from . import regularizer  # noqa: F401,E402
    from . import profiler  # noqa: F401,E402
    from . import linalg  # noqa: F401,E402
    from . import text  # noqa: F401,E402
    from . import hub  # noqa: F401,E402
    from . import debug  # noqa: F401,E402
    from . import models  # noqa: F401,E402
    from . import utils  # noqa: F401,E402
    from .hapi import callbacks  # noqa: F401,E402
    from . import compat  # noqa: F401,E402
    from . import cost_model  # noqa: F401,E402
    from . import dataset  # noqa: F401,E402
    from . import reader  # noqa: F401,E402
    from . import sysconfig  # noqa: F401,E402
    from . import inference  # noqa: F401,E402
    from . import onnx  # noqa: F401,E402
    from . import autograd as _autograd_ns  # noqa: F401,E402
    from .device import (  # noqa: F401,E402
        CustomPlace,
        IPUPlace,
        MLUPlace,
        XPUPlace,
        get_cudnn_version,
        is_compiled_with_cinn,
        is_compiled_with_cuda,
        is_compiled_with_ipu,
        is_compiled_with_mlu,
        is_compiled_with_npu,
        is_compiled_with_rocm,
        is_compiled_with_tpu,
        is_compiled_with_xpu,
    )
    from .nn.layer_base import ParamAttr  # noqa: F401,E402
    from .distributed.parallel import DataParallel  # noqa: F401,E402

    flatten = tensor.manipulation.flatten  # keep function (not module) at top level


def monkey_patch_math_varbase():
    """Tensor operators are patched at import (reference patches lazily)."""
    return None


def monkey_patch_variable():
    return None


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Count the forward FLOPs of a model (reference python/paddle/hapi/
    dynamic_flops.py). Uses jax's cost analysis on the traced forward — the
    XLA-native answer rather than per-layer hooks."""
    import numpy as _np
    import jax as _jax
    from .framework.core import Tensor as _T

    x = _np.zeros(input_size, _np.float32)

    def fwd(v):
        out = net(_T(v))
        return out._value if isinstance(out, _T) else out

    try:
        lowered = _jax.jit(fwd).lower(x)
        cost = lowered.compile().cost_analysis()
        if isinstance(cost, list):
            cost = cost[0]
        total = int(cost.get("flops", 0))
    except Exception:
        total = 0
    if print_detail:
        print(f"Total FLOPs: {total}")
    return total


def batch(reader, batch_size, drop_last=False):
    """Legacy reader combinator (reference python/paddle/batch.py)."""
    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batched


def get_flags(flags):
    names = flags if isinstance(flags, (list, tuple)) else [flags]
    return {n: _FLAGS.get(n) for n in names}


def set_flags(flags):
    _FLAGS.update(flags)


_FLAGS = {}


def disable_signal_handler():
    pass


version = type("version", (), {"full_version": __version__,
                               "commit": "tpu-native", "istaged": True})


def summary(net, input_size=None, dtypes=None, input=None):
    from .hapi.model_summary import summary as _summary
    return _summary(net, input_size, dtypes, input)
