"""BERT / ERNIE encoder family — role parity with PaddleNLP's
bert/ernie modeling (the reference's ERNIE-3.0 / BERT-base benchmark
config). Encoder blocks ride the same fused attention + fused LayerNorm
paths as GPT; tp partition specs on the projections.
"""
import dataclasses

import jax.numpy as jnp

from .. import nn
from ..framework.core import Tensor
from ..nn import functional as F

__all__ = ["BertConfig", "BertModel", "BertForPretraining",
           "BertForSequenceClassification", "BertPretrainingCriterion",
           "ErnieConfig", "ErnieModel", "ErnieForSequenceClassification",
           "bert_base", "bert_large", "bert_tiny"]


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    pad_token_id: int = 0
    dtype: str = "float32"


class BertEmbeddings(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        init = nn.ParamAttr(initializer=nn.initializer.Normal(0.0, 0.02))
        self.word_embeddings = nn.Embedding(cfg.vocab_size, cfg.hidden_size,
                                            padding_idx=cfg.pad_token_id,
                                            weight_attr=init)
        self.position_embeddings = nn.Embedding(cfg.max_position_embeddings,
                                                cfg.hidden_size, weight_attr=init)
        self.token_type_embeddings = nn.Embedding(cfg.type_vocab_size,
                                                  cfg.hidden_size, weight_attr=init)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size, epsilon=1e-12)
        self.dropout = nn.Dropout(cfg.hidden_dropout)

    def forward(self, input_ids, token_type_ids=None):
        from ..tensor.creation import arange, zeros_like
        L = input_ids.shape[1]
        pos = arange(L, dtype="int32")
        if token_type_ids is None:
            token_type_ids = zeros_like(input_ids)
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(pos)
             + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(x))


class BertModel(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = BertEmbeddings(cfg)
        enc_layer = nn.TransformerEncoderLayer(
            cfg.hidden_size, cfg.num_heads, cfg.intermediate_size,
            dropout=cfg.hidden_dropout, activation="gelu",
            attn_dropout=cfg.attention_dropout, act_dropout=0.0)
        self.encoder = nn.TransformerEncoder(enc_layer, cfg.num_layers)
        self.pooler = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        if cfg.dtype in ("bfloat16", "float16"):
            self.astype(cfg.dtype)   # config-driven PARAM cast


    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        if attention_mask is not None:
            # [B, L] 1/0 → additive [B, 1, 1, L]
            from ..framework.core import apply_op
            attention_mask = apply_op(
                lambda m: ((1.0 - m.astype(jnp.float32)) * -1e4)[:, None, None, :],
                attention_mask)
        x = self.embeddings(input_ids, token_type_ids)
        seq = self.encoder(x, attention_mask)
        pooled = F.tanh(self.pooler(seq[:, 0]))
        return seq, pooled


class BertForPretraining(nn.Layer):
    """MLM + NSP heads (tied MLM decoder)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.bert = BertModel(cfg)
        self.cfg = cfg
        self.mlm_transform = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.mlm_norm = nn.LayerNorm(cfg.hidden_size, epsilon=1e-12)
        self.mlm_bias = self.create_parameter(
            [cfg.vocab_size], is_bias=True)
        self.nsp = nn.Linear(cfg.hidden_size, 2)
        if cfg.dtype in ("bfloat16", "float16"):
            self.astype(cfg.dtype)   # heads follow the config dtype too

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        h = self.mlm_norm(F.gelu(self.mlm_transform(seq)))
        from ..framework.core import apply_op
        import jax
        mlm_logits = apply_op(
            lambda hv, e, b: jax.lax.dot_general(
                hv, e, (((2,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) + b,
            h, self.bert.embeddings.word_embeddings.weight, self.mlm_bias)
        nsp_logits = self.nsp(pooled)
        return mlm_logits, nsp_logits


class BertPretrainingCriterion(nn.Layer):
    def __init__(self, vocab_size):
        super().__init__()
        self.vocab_size = vocab_size

    def forward(self, mlm_logits, nsp_logits, mlm_labels, nsp_labels):
        from ..tensor.manipulation import reshape
        mlm = F.cross_entropy(reshape(mlm_logits, [-1, self.vocab_size]),
                              reshape(mlm_labels, [-1]), ignore_index=-100)
        nsp = F.cross_entropy(nsp_logits, nsp_labels)
        return mlm + nsp


class BertForSequenceClassification(nn.Layer):
    def __init__(self, cfg: BertConfig, num_classes=2, dropout=None):
        super().__init__()
        self.bert = BertModel(cfg)
        self.dropout = nn.Dropout(dropout if dropout is not None else cfg.hidden_dropout)
        self.classifier = nn.Linear(cfg.hidden_size, num_classes)
        if cfg.dtype in ("bfloat16", "float16"):
            self.astype(cfg.dtype)   # heads follow the config dtype too

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        return self.classifier(self.dropout(pooled))


# ERNIE shares the architecture; config defaults differ (role parity with
# PaddleNLP ernie-3.0 which the reference benches)
ErnieConfig = BertConfig
ErnieModel = BertModel
ErnieForSequenceClassification = BertForSequenceClassification


def bert_tiny(**kw):
    base = dict(vocab_size=1024, hidden_size=128, num_layers=2, num_heads=4,
                intermediate_size=256, max_position_embeddings=128)
    base.update(kw)
    return BertConfig(**base)


def bert_base(**kw):
    return BertConfig(**kw)


def bert_large(**kw):
    base = dict(hidden_size=1024, num_layers=24, num_heads=16, intermediate_size=4096)
    base.update(kw)
    return BertConfig(**base)


def graph_contract(cfg):
    """Graph Doctor contract (paddle_tpu.analysis): the encoder's
    dot_general budget — qkv/proj/fc1/fc2 + 2 attention matmuls per
    layer, pooler + embedding matmul excluded (model-level extras vary
    by head) — plus the counter-hash dropout pin: tensor-wide
    rng_bit_generator must never appear (threefry inside an encoder
    step costs more than the matmuls it regularizes)."""
    return {"rng_bit_generator": 0}
