"""MoE gate family — reference
python/paddle/incubate/distributed/models/moe/gate/{naive,switch,gshard}_gate.py.

TPU-native framing: a gate is a pure ROUTING POLICY over the router
logits — it owns no parameters (the router projection lives in MoEMLP)
and is expressed as jit-traceable transforms so the whole dispatch stays
one XLA program:

* `NaiveTopKGate`  — plain top-k (k rounds of argmax), uniform keep.
* `SwitchGate`     — top-1; during training the raw scores get additive
  uniform noise in [1-eps, 1+eps] (reference switch_gate.py:49-52).
* `GShardGate`     — top-2; the SECOND expert is kept with probability
  min(1, 2*g2) per token (reference gshard_gate.py random_routing +
  distributed/models/moe/utils.py:_random_routing — drop when
  2*g2 < u).

The k-round selection/capacity loop itself lives in models/moe.py
(`_moe_dispatch`); gates plug in via two hooks:
  select_logits(logits, key, train)  -> logits used for argmax selection
  keep_round(k, gate_val, key, train) -> per-token keep mask or None
"""
import jax
import jax.numpy as jnp

__all__ = ["NaiveTopKGate", "SwitchGate", "GShardGate", "make_gate"]


class NaiveTopKGate:
    """Plain top-k routing (reference naive_gate.py)."""

    name = "topk"

    def __init__(self, top_k=2):
        self.top_k = int(top_k)

    @property
    def normalize_combine(self):
        """Renormalize combine weights over the selected experts. False
        for top-1: the renormalized weight degenerates to 1, killing the
        router's task-loss gradient (Switch scales by the raw prob)."""
        return self.top_k > 1

    def select_logits(self, logits, key, train):
        return logits

    def keep_round(self, k, gate_val, key, train):
        return None


class SwitchGate(NaiveTopKGate):
    """Top-1 routing with training-time jitter (reference
    switch_gate.py: `noise = rand*2*eps + 1 - eps; score += noise`)."""

    name = "switch"

    def __init__(self, switch_eps=0.1):
        super().__init__(top_k=1)
        self.switch_eps = float(switch_eps)

    def select_logits(self, logits, key, train):
        if not train:
            return logits
        noise = jax.random.uniform(
            key, logits.shape, jnp.float32,
            1.0 - self.switch_eps, 1.0 + self.switch_eps)
        return logits + noise


class GShardGate(NaiveTopKGate):
    """Top-2 with random second-expert routing (reference
    gshard_gate.py): token i's 2nd expert is dropped when
    2 * g2_i < uniform_i — i.e. kept with probability min(1, 2*g2)."""

    name = "gshard"

    def __init__(self, random_routing=True):
        super().__init__(top_k=2)
        self.random_routing = random_routing

    def keep_round(self, k, gate_val, key, train):
        # training-time regularizer only: inference stays deterministic
        if k == 0 or not self.random_routing or not train:
            return None
        u = jax.random.uniform(key, gate_val.shape, jnp.float32)
        return (2.0 * gate_val) >= u


def make_gate(gate, cfg):
    """Gate factory: `gate` is a policy instance or one of
    "topk" | "switch" | "gshard" (config knobs taken from MoEConfig)."""
    if not isinstance(gate, str):
        return gate
    if gate == "topk":
        return NaiveTopKGate(top_k=cfg.top_k)
    if gate == "switch":
        return SwitchGate(switch_eps=cfg.switch_eps)
    if gate == "gshard":
        return GShardGate(random_routing=cfg.random_routing)
    raise ValueError(
        f"unknown MoE gate {gate!r}: expected 'topk', 'switch', 'gshard' "
        "or a gate policy instance")
