"""Autoregressive generation — role parity with PaddleNLP's
generation_utils (greedy / sampling / top-k / top-p) on the reference side.

TPU-first: prefill is one batched forward that fills the KV cache; the decode
loop is a single lax.scan over steps (one compiled program, static shapes),
sampling with explicit PRNG keys.
"""
import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..nn.layer_base import buffer_pytree, functional_call, state_pytree

__all__ = ["generate"]


def mask_logits(logits, temperature, top_k, top_p):
    """Temperature/top-k/nucleus filtering — the ONE implementation of
    the sampling mask (generate() and serving.py both use it, so they
    can't drift)."""
    logits = logits.astype(jnp.float32) / temperature
    if top_k and top_k > 0:
        kth = jnp.sort(logits, axis=-1)[..., -top_k][..., None]
        logits = jnp.where(logits < kth, -1e30, logits)
    if top_p and top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        cutoff_idx = jnp.sum(cum < top_p, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -1e30, logits)
    return logits


def _sample(logits, key, temperature, top_k, top_p):
    if temperature == 0.0:
        return jnp.argmax(logits.astype(jnp.float32), axis=-1)
    return jax.random.categorical(
        key, mask_logits(logits, temperature, top_k, top_p), axis=-1)


def generate(model, input_ids, max_new_tokens=32, temperature=1.0, top_k=0,
             top_p=1.0, eos_token_id=None, seed=0):
    """Returns [B, L_in + max_new_tokens] token ids (greedy when
    temperature=0). The full prefill+decode runs as two compiled programs."""
    ids = input_ids._value if isinstance(input_ids, Tensor) else jnp.asarray(input_ids)
    ids = ids.astype(jnp.int32)
    B, L_in = ids.shape
    max_len = L_in + max_new_tokens
    assert max_len <= model.cfg.max_seq_len, "exceeds model max_seq_len"

    params = state_pytree(model)
    params.update(buffer_pytree(model))
    model.eval()

    def prefill(params, ids):
        with functional_call(model, params):
            cache = model.init_cache(B, max_len)
            logits, cache = model(Tensor(ids), cache=cache, pos=0)
        lv = logits._value if isinstance(logits, Tensor) else logits
        return lv[:, -1], cache

    def decode(params, cache, first_tok, key):
        def step(carry, _):
            cache, tok, p, key = carry
            key, sub = jax.random.split(key)
            with functional_call(model, params):
                logits, cache = model(Tensor(tok[:, None]), cache=cache, pos=p)
            lv = (logits._value if isinstance(logits, Tensor) else logits)[:, -1]
            nxt = _sample(lv, sub, temperature, top_k, top_p).astype(jnp.int32)
            return (cache, nxt, p + 1, key), nxt

        key, sub = jax.random.split(key)
        (_, _, _, _), toks = jax.lax.scan(
            step, (cache, first_tok, jnp.asarray(L_in, jnp.int32), key),
            None, length=max_new_tokens - 1)
        return toks

    last_logits, cache = jax.jit(prefill)(params, ids)
    key = jax.random.PRNGKey(seed)
    key, sub = jax.random.split(key)
    first_tok = _sample(last_logits, sub, temperature, top_k, top_p).astype(jnp.int32)
    if max_new_tokens == 1:
        out = jnp.concatenate([ids, first_tok[:, None]], axis=1)
        return Tensor(out)
    toks = jax.jit(decode)(params, cache, first_tok, key)
    out = jnp.concatenate([ids, first_tok[:, None], jnp.swapaxes(toks, 0, 1)], axis=1)
    if eos_token_id is not None:
        # mask everything after the first EOS with EOS (post-hoc, host-side)
        gen = out[:, L_in:]
        hit = jnp.cumsum((gen == eos_token_id).astype(jnp.int32), axis=1) > 0
        prev_hit = jnp.pad(hit[:, :-1], ((0, 0), (1, 0)))
        gen = jnp.where(prev_hit, eos_token_id, gen)
        out = jnp.concatenate([out[:, :L_in], gen], axis=1)
    return Tensor(out)
