"""Autoregressive generation — role parity with PaddleNLP's
generation_utils (greedy / sampling / top-k / top-p) on the reference side.

TPU-first: prefill is one batched forward that fills the KV cache; the decode
loop is a single lax.scan over steps (one compiled program, static shapes),
sampling with explicit PRNG keys.
"""
import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..nn.layer_base import buffer_pytree, functional_call, state_pytree

__all__ = ["generate", "beam_search"]


def mask_logits(logits, temperature, top_k, top_p):
    """Temperature/top-k/nucleus filtering — the ONE implementation of
    the sampling mask (generate() and the serving package both use it, so they
    can't drift)."""
    logits = logits.astype(jnp.float32) / temperature
    if top_k and top_k > 0:
        kth = jnp.sort(logits, axis=-1)[..., -top_k][..., None]
        logits = jnp.where(logits < kth, -1e30, logits)
    if top_p and top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        cutoff_idx = jnp.sum(cum < top_p, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -1e30, logits)
    return logits


def _sample(logits, key, temperature, top_k, top_p):
    if temperature == 0.0:
        return jnp.argmax(logits.astype(jnp.float32), axis=-1)
    return jax.random.categorical(
        key, mask_logits(logits, temperature, top_k, top_p), axis=-1)


def _make_prefill(model, B, max_len):
    """ONE prefill recipe for greedy and beam paths (cache init + batched
    forward + last-position logits)."""
    def prefill(params, ids):
        with functional_call(model, params):
            cache = model.init_cache(B, max_len)
            logits, cache = model(Tensor(ids), cache=cache, pos=0)
        lv = logits._value if isinstance(logits, Tensor) else logits
        return lv[:, -1], cache
    return prefill


def generate(model, input_ids, max_new_tokens=32, temperature=1.0, top_k=0,
             top_p=1.0, eos_token_id=None, seed=0, num_beams=1,
             length_penalty=0.0):
    """Returns [B, L_in + max_new_tokens] token ids (greedy when
    temperature=0). The full prefill+decode runs as two compiled programs.
    num_beams>1 switches to beam search (PaddleNLP generation_utils
    decode_strategy='beam_search' role): one lax.scan where each step
    expands KxV candidates, keeps the top K, and REORDERS the KV cache
    to follow the surviving beams; finished beams are frozen on EOS.
    Final selection divides scores by len**length_penalty (0 = raw
    log-prob, PaddleNLP's default)."""
    if num_beams > 1:
        assert temperature in (0.0, 1.0) and not top_k \
            and top_p in (0, 1.0), \
            "beam search explores by score, not sampling: leave " \
            "temperature/top_k/top_p at defaults"
        out, _scores = _beam_search(model, input_ids, max_new_tokens,
                                    num_beams, eos_token_id,
                                    length_penalty)
        return out
    ids = input_ids._value if isinstance(input_ids, Tensor) else jnp.asarray(input_ids)
    ids = ids.astype(jnp.int32)
    B, L_in = ids.shape
    max_len = L_in + max_new_tokens
    assert max_len <= model.cfg.max_seq_len, "exceeds model max_seq_len"

    params = state_pytree(model)
    params.update(buffer_pytree(model))
    model.eval()
    prefill = _make_prefill(model, B, max_len)

    def decode(params, cache, first_tok, key):
        def step(carry, _):
            cache, tok, p, key = carry
            key, sub = jax.random.split(key)
            with functional_call(model, params):
                logits, cache = model(Tensor(tok[:, None]), cache=cache, pos=p)
            lv = (logits._value if isinstance(logits, Tensor) else logits)[:, -1]
            nxt = _sample(lv, sub, temperature, top_k, top_p).astype(jnp.int32)
            return (cache, nxt, p + 1, key), nxt

        key, sub = jax.random.split(key)
        (_, _, _, _), toks = jax.lax.scan(
            step, (cache, first_tok, jnp.asarray(L_in, jnp.int32), key),
            None, length=max_new_tokens - 1)
        return toks

    last_logits, cache = jax.jit(prefill)(params, ids)
    key = jax.random.PRNGKey(seed)
    key, sub = jax.random.split(key)
    first_tok = _sample(last_logits, sub, temperature, top_k, top_p).astype(jnp.int32)
    if max_new_tokens == 1:
        out = jnp.concatenate([ids, first_tok[:, None]], axis=1)
        return Tensor(out)
    toks = jax.jit(decode)(params, cache, first_tok, key)
    out = jnp.concatenate([ids, first_tok[:, None], jnp.swapaxes(toks, 0, 1)], axis=1)
    if eos_token_id is not None:
        # mask everything after the first EOS with EOS (post-hoc, host-side)
        gen = out[:, L_in:]
        hit = jnp.cumsum((gen == eos_token_id).astype(jnp.int32), axis=1) > 0
        prev_hit = jnp.pad(hit[:, :-1], ((0, 0), (1, 0)))
        gen = jnp.where(prev_hit, eos_token_id, gen)
        out = jnp.concatenate([out[:, :L_in], gen], axis=1)
    return Tensor(out)


def _beam_search(model, input_ids, max_new_tokens, num_beams,
                 eos_token_id, length_penalty):
    ids = input_ids._value if isinstance(input_ids, Tensor) \
        else jnp.asarray(input_ids)
    ids = ids.astype(jnp.int32)
    B, L_in = ids.shape
    K = int(num_beams)
    T = int(max_new_tokens)
    max_len = L_in + T
    assert max_len <= model.cfg.max_seq_len, "exceeds model max_seq_len"
    eos = -1 if eos_token_id is None else int(eos_token_id)
    pad = eos if eos_token_id is not None else 0

    params = state_pytree(model)
    params.update(buffer_pytree(model))
    model.eval()
    prefill = _make_prefill(model, B, max_len)

    def run(params, ids):
        last_logits, cache = prefill(params, ids)
        logp0 = jax.nn.log_softmax(last_logits.astype(jnp.float32), -1)
        scores, first_toks = jax.lax.top_k(logp0, K)      # [B, K]
        first_toks = first_toks.astype(jnp.int32)
        # beams share the prefix: replicate every cache leaf to B*K rows
        cache = jax.tree_util.tree_map(
            lambda x: jnp.repeat(x, K, axis=0), cache)
        toks = jnp.full((B, K, T), pad, jnp.int32)
        toks = toks.at[:, :, 0].set(first_toks)
        finished = (first_toks == eos)
        V = logp0.shape[-1]
        b_idx = jnp.arange(B)[:, None]

        def step(carry, t):
            cache, scores, cur, finished, toks = carry
            with functional_call(model, params):
                logits, cache = model(Tensor(cur.reshape(B * K, 1)),
                                      cache=cache, pos=L_in + t)
            lv = (logits._value if isinstance(logits, Tensor)
                  else logits)[:, -1]
            logp = jax.nn.log_softmax(lv.astype(jnp.float32), -1)
            logp = logp.reshape(B, K, V)
            # live beams expand over V; finished beams carry ONE frozen
            # candidate (their pad continuation at unchanged score)
            cand = jnp.where(finished[:, :, None], -jnp.inf,
                             scores[:, :, None] + logp)
            frozen = jnp.full((B, K, V), -jnp.inf)
            frozen = frozen.at[:, :, pad].set(
                jnp.where(finished, scores, -jnp.inf))
            cand = jnp.maximum(cand, frozen).reshape(B, K * V)
            scores, flat = jax.lax.top_k(cand, K)         # [B, K]
            beam = (flat // V).astype(jnp.int32)
            tok = (flat % V).astype(jnp.int32)
            # the surviving beams' KV history must follow them
            sel = (b_idx * K + beam).reshape(-1)          # [B*K]
            cache = jax.tree_util.tree_map(
                lambda x: jnp.take(x, sel, axis=0), cache)
            toks = toks[b_idx, beam]                      # reorder history
            finished = finished[b_idx, beam] | (tok == eos)
            toks = toks.at[:, :, t + 1].set(tok)
            return (cache, scores, tok, finished, toks), None

        if T > 1:
            (cache, scores, cur, finished, toks), _ = jax.lax.scan(
                step, (cache, scores, first_toks, finished, toks),
                jnp.arange(T - 1))
        # length = tokens up to and including the first EOS (or T)
        if eos >= 0:
            hit = jnp.cumsum((toks == eos).astype(jnp.int32), -1) > 0
            lengths = T - jnp.sum(hit, -1) + jnp.any(hit, -1)
            # canonicalize: everything after the first EOS becomes pad
            prev_hit = jnp.pad(hit[:, :, :-1], ((0, 0), (0, 0), (1, 0)))
            toks = jnp.where(prev_hit, pad, toks)
        else:
            lengths = jnp.full((B, K), T)
        norm = scores / jnp.maximum(lengths, 1).astype(
            jnp.float32) ** length_penalty
        best = jnp.argmax(norm, axis=1)                   # [B]
        best_toks = toks[jnp.arange(B), best]             # [B, T]
        best_score = norm[jnp.arange(B), best]
        return jnp.concatenate([ids, best_toks], axis=1), best_score

    out, scores = jax.jit(run)(params, ids)
    return Tensor(out), Tensor(scores)


def beam_search(model, input_ids, max_new_tokens=32, num_beams=4,
                eos_token_id=None, length_penalty=0.0):
    """Standalone beam-search entry. Returns (ids, scores) like the
    reference generate() (PaddleNLP generation_utils returns the decoded
    ids WITH their scores)."""
    return _beam_search(model, input_ids, max_new_tokens, num_beams,
                        eos_token_id, length_penalty)
