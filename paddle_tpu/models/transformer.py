"""Transformer seq2seq (machine-translation style) — SURVEY item 19.

Role parity: PaddleNLP's Transformer-base/big MT recipe (the reference's
`Transformer` benchmark family built on python/paddle/nn/layer/transformer.py).
TPU-first details: bf16-friendly embeddings + fp32 softmax/loss via the nn
stack, sinusoidal positions computed host-side once, greedy/beam decode as a
host loop over a jit-compiled step (decode is latency-bound).
"""
import math

import jax
import jax.numpy as jnp
import numpy as np

from .. import nn
from ..framework.core import Tensor, apply_op
from ..nn import functional as F
from ..nn.layer_base import Layer

__all__ = ["TransformerModel", "CrossEntropyCriterion", "transformer_base",
           "transformer_big"]


def _sinusoid_table(max_len, d_model):
    pos = np.arange(max_len)[:, None]
    i = np.arange(d_model)[None, :]
    angle = pos / np.power(10000.0, (2 * (i // 2)) / d_model)
    table = np.zeros((max_len, d_model), np.float32)
    table[:, 0::2] = np.sin(angle[:, 0::2])
    table[:, 1::2] = np.cos(angle[:, 1::2])
    return table


class TransformerModel(Layer):
    """Encoder-decoder MT transformer with tied target embedding/projection.

    src/tgt are int token ids [B, L]; pad id masks attention. Mirrors the
    reference recipe's structure (shared scale-embedding + sinusoid position,
    pre-norm off to match paddle's default post-norm layers)."""

    def __init__(self, src_vocab_size, trg_vocab_size, max_length=256,
                 num_encoder_layers=6, num_decoder_layers=6, n_head=8,
                 d_model=512, d_inner_hid=2048, dropout=0.1,
                 weight_sharing=False, bos_id=0, eos_id=1, pad_id=None):
        super().__init__()
        self.pad_id = pad_id if pad_id is not None else bos_id
        self.bos_id = bos_id
        self.eos_id = eos_id
        self.d_model = d_model
        self.src_emb = nn.Embedding(src_vocab_size, d_model)
        self.trg_emb = self.src_emb if weight_sharing else \
            nn.Embedding(trg_vocab_size, d_model)
        self.register_buffer("pos_table",
                             Tensor(jnp.asarray(_sinusoid_table(max_length, d_model))),
                             persistable=False)
        self.dropout = nn.Dropout(dropout)
        self.transformer = nn.Transformer(
            d_model=d_model, nhead=n_head,
            num_encoder_layers=num_encoder_layers,
            num_decoder_layers=num_decoder_layers,
            dim_feedforward=d_inner_hid, dropout=dropout)
        self.max_length = max_length
        self.weight_sharing = weight_sharing
        if weight_sharing and src_vocab_size != trg_vocab_size:
            raise ValueError(
                "weight_sharing requires src_vocab_size == trg_vocab_size "
                f"(got {src_vocab_size} vs {trg_vocab_size})")
        if not weight_sharing:
            self.project = nn.Linear(d_model, trg_vocab_size, bias_attr=False)

    def _embed(self, ids, emb, offset=0):
        x = emb(ids) * math.sqrt(self.d_model)
        L = ids.shape[1]
        if offset + L > self.max_length:
            raise ValueError(
                f"sequence length {offset + L} exceeds max_length "
                f"{self.max_length}; rebuild the model with a larger max_length")
        pos = Tensor(self.pos_table._value[offset:offset + L])
        return self.dropout(x + pos)

    def _masks(self, src, tgt):
        def _f(s, t):
            src_pad = (s == self.pad_id)
            # additive masks broadcast to [B, H, Lq, Lk]
            src_mask = jnp.where(src_pad[:, None, None, :], -1e9, 0.0)
            Lt = t.shape[1]
            causal = jnp.triu(jnp.full((Lt, Lt), -1e9, jnp.float32), k=1)
            tgt_mask = causal[None, None]
            mem_mask = src_mask
            return src_mask, tgt_mask, mem_mask
        return apply_op(_f, src, tgt)

    def forward(self, src_word, trg_word):
        src_mask, tgt_mask, mem_mask = self._masks(src_word, trg_word)
        enc_in = self._embed(src_word, self.src_emb)
        dec_in = self._embed(trg_word, self.trg_emb)
        out = self.transformer(enc_in, dec_in, src_mask=src_mask,
                               tgt_mask=tgt_mask, memory_mask=mem_mask)
        return self._project(out)

    def _project(self, out):
        if self.weight_sharing:
            return apply_op(
                lambda h, e: jnp.einsum("bld,vd->blv", h.astype(jnp.float32),
                                        e.astype(jnp.float32)),
                out, self.trg_emb.weight)
        return self.project(out)

    def generate(self, src_word, max_len=64):
        """Greedy decode: encode ONCE, then step the decoder with the
        incremental KV cache (nn.MultiHeadAttention.Cache) — O(1) work in the
        prefix per step."""
        b = src_word.shape[0]
        src_mask, _, mem_mask = self._masks(src_word, src_word)
        memory = self.transformer.encoder(self._embed(src_word, self.src_emb),
                                          src_mask)
        cache = self.transformer.decoder.gen_cache(memory)
        tgt = np.full((b, 1), self.bos_id, np.int32)
        finished = np.zeros(b, bool)
        last = Tensor(jnp.asarray(tgt))
        for step in range(max_len):
            dec_in = self._embed(last, self.trg_emb, offset=step)
            out, cache = self.transformer.decoder(dec_in, memory, None,
                                                  mem_mask, cache)
            logits = self._project(out)
            nxt = np.asarray(logits.numpy()[:, -1].argmax(-1)).astype(np.int32)
            nxt = np.where(finished, self.eos_id, nxt)
            tgt = np.concatenate([tgt, nxt[:, None]], axis=1)
            finished |= nxt == self.eos_id
            if finished.all():
                break
            last = Tensor(jnp.asarray(nxt[:, None]))
        return Tensor(jnp.asarray(tgt[:, 1:]))


class CrossEntropyCriterion(Layer):
    """Label-smoothed token CE ignoring pads — reference MT criterion."""

    def __init__(self, label_smooth_eps=0.1, pad_id=0):
        super().__init__()
        self.eps = label_smooth_eps
        self.pad_id = pad_id

    def forward(self, predict, label):
        """Returns (sum_cost, avg_cost, token_num) — the reference MT
        criterion's order; backprop avg_cost."""
        def _f(logits, lab):
            v = logits.shape[-1]
            lab = lab.reshape(lab.shape[0], lab.shape[1]).astype(jnp.int32)
            logsm = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            onehot = (jnp.arange(v)[None, None, :] == lab[..., None])
            # reference F.label_smooth: (1-eps)*onehot + eps/V over ALL classes
            smooth = onehot * (1.0 - self.eps) + self.eps / v
            token_loss = -jnp.sum(smooth * logsm, axis=-1)
            mask = (lab != self.pad_id).astype(jnp.float32)
            total = jnp.sum(token_loss * mask)
            tokens = jnp.maximum(jnp.sum(mask), 1.0)
            return total, total / tokens, tokens
        total, avg, tokens = apply_op(_f, predict, label)
        return total, avg, tokens


def transformer_base(src_vocab_size=32000, trg_vocab_size=32000, **kw):
    return TransformerModel(src_vocab_size, trg_vocab_size, d_model=512,
                            n_head=8, d_inner_hid=2048, **kw)


def transformer_big(src_vocab_size=32000, trg_vocab_size=32000, **kw):
    return TransformerModel(src_vocab_size, trg_vocab_size, d_model=1024,
                            n_head=16, d_inner_hid=4096, **kw)
