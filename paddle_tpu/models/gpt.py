"""GPT-3-style decoder — the flagship pretraining model.

Role parity: PaddleNLP gpt-3 recipe the reference benchmarks
(BASELINE.json: "GPT-3 1.3B tokens/sec/chip"). TPU-first design:

  * bf16 params/activations, fp32 LayerNorm + softmax + loss
  * flash attention (Pallas) on the causal path
  * per-block jax.checkpoint (remat) — activation memory ~O(L·1 block)
  * tensor parallel via GSPMD partition specs on qkv/proj/mlp/vocab
    (see distributed/fleet/meta_parallel.py for the mechanism)
  * sequence-parallel activation constraints over 'sp' when that axis >1
  * tied input/output embedding (logits = h @ E^T)
"""
import dataclasses
import math
from functools import partial

import jax
import jax.numpy as jnp

from .. import nn
from ..framework.core import Tensor, apply_op
from ..nn import functional as F
from ..nn.initializer import Constant, Normal
from ..nn.layer_base import Layer, functional_call

__all__ = ["GPTConfig", "GPT", "GPTPretrainingCriterion",
           "gpt_tiny", "gpt_125m", "gpt_350m", "gpt_760m", "gpt_1p3b"]


def _remat_policy(name):
    """Map config string -> jax.checkpoint policy. 'dots' saves matmul
    results so the backward skips recomputing the FLOPs-heavy ops (the 6N
    heuristic's extra-fwd cost) in exchange for per-layer matmul-activation
    memory."""
    if name == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    if name not in ("full", "none"):
        raise ValueError(f"remat_policy must be 'full', 'dots' or 'none', "
                         f"got {name!r}")
    return jax.checkpoint_policies.nothing_saveable


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304          # multiple of 128 → clean vocab sharding
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    ffn_hidden: int = 0              # 0 → 4*hidden
    max_seq_len: int = 1024
    dropout: float = 0.0
    sp_mode: str = "ring"            # 'ring' | 'zigzag' | 'ulysses' seq par
    #   'zigzag': load-balanced causal ring (2x less attention compute at
    #   large sp; see ops/ring_attention.py)
    dtype: str = "bfloat16"          # compute/param dtype
    remat: bool = True               # jax.checkpoint each block
    remat_policy: str = "full"       # 'full' (recompute all) | 'dots' (save
    #   matmul outputs: ~4/3 fewer flops in bwd at the cost of ~per-layer
    #   matmul-activation memory) | 'none' ≈ remat=False
    tie_embeddings: bool = True
    init_std: float = 0.02
    tp_overlap: str = "off"          # tensor-parallel collective dispatch at
    #   the two row-parallel sites (attention proj, fc2): 'off' leaves the
    #   dots to GSPMD (bulk psum, the COLL-SERIALIZED shape), 'bulk' issues
    #   the explicit shard_map psum twin, 'ring' the chunked ring-overlapped
    #   path (ops/overlap.py) — bit-identical to 'bulk' by the twin pin
    tp_overlap_chunks: int = 4       # free-dim tiles per overlapped site

    def __post_init__(self):
        if self.ffn_hidden == 0:
            self.ffn_hidden = 4 * self.hidden_size
        if self.sp_mode not in ("ring", "zigzag", "ulysses"):
            raise ValueError(f"sp_mode must be 'ring', 'zigzag' or "
                             f"'ulysses', got {self.sp_mode!r}")
        if self.tp_overlap not in ("off", "bulk", "ring"):
            raise ValueError(f"tp_overlap must be 'off', 'bulk' or "
                             f"'ring', got {self.tp_overlap!r}")

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads

    def num_params(self):
        h, L, v = self.hidden_size, self.num_layers, self.vocab_size
        per_block = 4 * h * h + 2 * h * self.ffn_hidden + 9 * h + 2 * self.ffn_hidden
        return v * h + self.max_seq_len * h + L * per_block + 2 * h


class GPTBlock(Layer):
    """Pre-LN decoder block. qkv/out and mlp projections carry 'tp'
    partition specs; with tp=1 those specs are inert."""

    def __init__(self, cfg: GPTConfig, layer_idx: int):
        super().__init__()
        h = cfg.hidden_size
        self.cfg = cfg
        init = Normal(0.0, cfg.init_std)
        # scaled init on residual-out projections (GPT-2/3 recipe)
        out_init = Normal(0.0, cfg.init_std / math.sqrt(2.0 * cfg.num_layers))
        self.ln1 = nn.LayerNorm(h)
        self.qkv = nn.Linear(h, 3 * h, weight_attr=nn.ParamAttr(initializer=init))
        self.qkv.weight.partition_spec = (None, "tp")
        self.qkv.bias.partition_spec = ("tp",)
        self.proj = nn.Linear(h, h, weight_attr=nn.ParamAttr(initializer=out_init))
        self.proj.weight.partition_spec = ("tp", None)
        self.ln2 = nn.LayerNorm(h)
        self.fc1 = nn.Linear(h, cfg.ffn_hidden, weight_attr=nn.ParamAttr(initializer=init))
        self.fc1.weight.partition_spec = (None, "tp")
        self.fc1.bias.partition_spec = ("tp",)
        self.fc2 = nn.Linear(cfg.ffn_hidden, h, weight_attr=nn.ParamAttr(initializer=out_init))
        self.fc2.weight.partition_spec = ("tp", None)

    def forward(self, x, cache=None, pos=None):
        cfg = self.cfg
        B, L = x.shape[0], x.shape[1]
        res = x
        y = self.ln1(x)
        qkv = self.qkv(y)
        from ..tensor.manipulation import reshape
        qkv = reshape(qkv, [B, L, 3, cfg.num_heads, cfg.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if cache is not None:
            attn, cache = self._attend_cached(q, k, v, cache, pos)
        else:
            from ..distributed.mesh import get_mesh
            mesh = get_mesh(create_default=False)
            if mesh is not None and mesh.shape.get("sp", 1) > 1:
                # sequence parallel over the 'sp' ICI axis: exact ring
                # attention, or Ulysses all-to-all head-resharding when
                # configured and the head count divides
                if cfg.sp_mode == "ulysses":
                    # ops/ulysses.py raises if heads don't divide 'sp' —
                    # an explicit error beats silently measuring ring
                    from ..ops.ulysses import ulysses_attention
                    attn = apply_op(
                        lambda qv, kv, vv: ulysses_attention(
                            qv, kv, vv, mesh=mesh, causal=True), q, k, v)
                else:
                    from ..ops.ring_attention import ring_attention
                    layout = ("zigzag" if cfg.sp_mode == "zigzag"
                              else "contiguous")
                    attn = apply_op(
                        lambda qv, kv, vv: ring_attention(
                            qv, kv, vv, mesh=mesh, causal=True,
                            layout=layout),
                        q, k, v)
            else:
                attn = F.scaled_dot_product_attention(q, k, v, is_causal=True,
                                                      dropout_p=cfg.dropout,
                                                      training=self.training)
        attn = reshape(attn, [B, L, cfg.hidden_size])
        x = res + self._row_parallel(self.proj, attn)
        res = x
        y = self.ln2(x)
        y = self._row_parallel(self.fc2, F.gelu(self.fc1(y),
                                                approximate=True))
        out = res + y
        return out if cache is None else (out, cache)

    def _row_parallel(self, linear, x):
        """The two convicted COLL-SERIALIZED sites: a row-parallel dot
        whose tp all-reduce GSPMD dispatches as one bulk psum nothing
        can hide behind. With cfg.tp_overlap='ring' the dot+psum goes
        through ops/overlap.py's chunked ring (per-chunk ppermutes
        overlap the neighbour chunks' dots); 'bulk' is the explicit
        shard_map psum twin (the A/B reference, bit-identical to
        'ring'); 'off' keeps the plain Linear."""
        cfg = self.cfg
        if cfg.tp_overlap != "off":
            from ..distributed.mesh import get_mesh
            mesh = get_mesh(create_default=False)
            if mesh is not None and mesh.shape.get("tp", 1) > 1:
                from ..ops.overlap import overlap_matmul_all_reduce
                impl = "ring" if cfg.tp_overlap == "ring" else "bulk"
                return apply_op(
                    lambda a, wt, b: overlap_matmul_all_reduce(
                        a, wt, axis="tp",
                        n_chunks=cfg.tp_overlap_chunks,
                        mesh=mesh, impl=impl) + b,
                    x, linear.weight, linear.bias)
        return linear(x)

    def _attend_cached(self, q, k, v, cache, pos):
        """Decode-time attention against a static KV buffer (lengths stay
        compile-time constant; validity enforced by position mask)."""
        import math as _math

        def _f(qv, kv, vv, k_buf, v_buf, p):
            k_buf = jax.lax.dynamic_update_slice(k_buf, kv.astype(k_buf.dtype),
                                                 (0, p, 0, 0))
            v_buf = jax.lax.dynamic_update_slice(v_buf, vv.astype(v_buf.dtype),
                                                 (0, p, 0, 0))
            Lq = qv.shape[1]
            Lmax = k_buf.shape[1]
            scale = 1.0 / _math.sqrt(qv.shape[-1])
            qh = jnp.swapaxes(qv, 1, 2).astype(jnp.float32) * scale
            kh = jnp.swapaxes(k_buf, 1, 2).astype(jnp.float32)
            vh = jnp.swapaxes(v_buf, 1, 2).astype(jnp.float32)
            s = qh @ jnp.swapaxes(kh, -1, -2)  # [B,H,Lq,Lmax]
            q_pos = p + jax.lax.broadcasted_iota(jnp.int32, (Lq, Lmax), 0)
            k_pos = jax.lax.broadcasted_iota(jnp.int32, (Lq, Lmax), 1)
            s = jnp.where(q_pos >= k_pos, s, -1e30)
            probs = jax.nn.softmax(s, axis=-1)
            out = jnp.swapaxes(probs @ vh, 1, 2).astype(qv.dtype)
            return out, k_buf, v_buf

        pos_v = pos._value if isinstance(pos, Tensor) else pos
        res = apply_op(lambda qv, kv, vv, kb, vb: _f(qv, kv, vv, kb, vb, pos_v),
                       q, k, v, cache[0], cache[1])
        out, k_buf, v_buf = res
        return out, (k_buf, v_buf)


class GPT(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        init = Normal(0.0, cfg.init_std)
        self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size,
                                weight_attr=nn.ParamAttr(initializer=init))
        self.wte.weight.partition_spec = ("tp", None)  # vocab-parallel
        self.wpe = nn.Embedding(cfg.max_seq_len, cfg.hidden_size,
                                weight_attr=nn.ParamAttr(initializer=init))
        self.drop = nn.Dropout(cfg.dropout)
        self.blocks = nn.LayerList([GPTBlock(cfg, i) for i in range(cfg.num_layers)])
        self.ln_f = nn.LayerNorm(cfg.hidden_size)
        if not cfg.tie_embeddings:
            self.lm_head = nn.Linear(cfg.hidden_size, cfg.vocab_size,
                                     weight_attr=nn.ParamAttr(initializer=init),
                                     bias_attr=False)
            self.lm_head.weight.partition_spec = (None, "tp")

    def _run_block(self, block, x):
        """Apply one block, optionally under jax.checkpoint: the block's
        params become explicit inputs of a pure function so XLA rematerializes
        its activations in the backward pass instead of storing them."""
        if not self.cfg.remat or self.cfg.remat_policy == "none":
            return block(x)
        names = [n for n, _ in block.named_parameters()]
        vals = [p._value for _, p in block.named_parameters()]

        @partial(jax.checkpoint, policy=_remat_policy(self.cfg.remat_policy))
        def pure_block(pvals, xv):
            with functional_call(block, dict(zip(names, pvals))):
                out = block(Tensor(xv))
            return out._value

        return apply_op(lambda xv, *pv: pure_block(list(pv), xv), x, *vals)

    def init_cache(self, batch_size, max_len):
        """Decode KV cache: per-block (k, v) buffers [B, max_len, H, D]."""
        cfg = self.cfg
        d = jnp.dtype(cfg.dtype)
        shape = (batch_size, max_len, cfg.num_heads, cfg.head_dim)
        return [(jnp.zeros(shape, d), jnp.zeros(shape, d)) for _ in self.blocks]

    def forward(self, input_ids, cache=None, pos=0):
        cfg = self.cfg
        B, L = input_ids.shape[0], input_ids.shape[1]
        from ..tensor.creation import arange
        positions = arange(L, dtype="int32") + pos if cache is not None \
            else arange(L, dtype="int32")
        x = self.wte(input_ids) + self.wpe(positions)
        x = x.astype(cfg.dtype)
        # batch over data axes, sequence over 'sp' (GSPMD inserts the
        # gather/scatter collectives around attention when sp > 1)
        from ..distributed.sharding_utils import constraint
        from ..distributed.mesh import get_mesh
        if cache is None and get_mesh(create_default=False) is not None:
            x = constraint(x, ("dp", "fsdp"), "sp", None)
        x = self.drop(x)
        if cache is not None:
            new_cache = []
            for block, c in zip(self.blocks, cache):
                x, c = block(x, cache=c, pos=pos)
                new_cache.append(c)
        else:
            for block in self.blocks:
                x = self._run_block(block, x)
        x = self.ln_f(x)
        # tied head: [B,L,H] @ [H,V] — the big MXU matmul; fp32 accum via
        # preferred_element_type to keep loss numerics honest in bf16
        if cfg.tie_embeddings:
            logits = apply_op(
                lambda h, e: jax.lax.dot_general(
                    h, e, (((2,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32),
                x, self.wte.weight)
        else:
            logits = self.lm_head(x)
        return logits if cache is None else (logits, new_cache)


class GPTPretrainingCriterion(Layer):
    """Causal LM loss (fp32), ignoring pad label -100.

    On TPU-friendly shapes the loss runs through the fused Pallas
    softmax-cross-entropy kernel (ops/fused_ops.py — one vocab pass forward,
    (softmax - onehot)·g backward without a second fp32 prob tensor);
    otherwise the jnp cross_entropy path."""

    def forward(self, logits, labels):
        V = logits.shape[-1]
        from ..tensor.manipulation import reshape
        flat = reshape(logits, [-1, V])
        flat_labels = reshape(labels, [-1])
        n = flat.shape[0]
        from ..ops.fused_ops import can_fuse_xent
        if can_fuse_xent(n, V):
            from ..framework.core import apply_op
            from ..ops.fused_ops import fused_softmax_cross_entropy

            def _f(lg, lab):
                lab = lab.astype(jnp.int32)
                valid = lab >= 0
                rows = fused_softmax_cross_entropy(lg, jnp.maximum(lab, 0))
                rows = jnp.where(valid, rows, 0.0)
                return jnp.sum(rows) / jnp.maximum(jnp.sum(valid), 1)
            return apply_op(_f, flat, flat_labels)
        return F.cross_entropy(flat, flat_labels, ignore_index=-100, reduction="mean")


def _preset(kw, **defaults):
    """Config factory body: caller kwargs override the preset's fields."""
    defaults.update(kw)
    return GPTConfig(**defaults)


def gpt_tiny(**kw):
    return _preset(kw, vocab_size=1024, hidden_size=128, num_layers=2,
                   num_heads=4, max_seq_len=256)


def gpt_125m(**kw):
    return _preset(kw, hidden_size=768, num_layers=12, num_heads=12)


def gpt_350m(**kw):
    return _preset(kw, hidden_size=1024, num_layers=24, num_heads=16)


def gpt_760m(**kw):
    return _preset(kw, hidden_size=1536, num_layers=24, num_heads=16)


def gpt_1p3b(**kw):
    return _preset(kw, hidden_size=2048, num_layers=24, num_heads=16)


# ---------------------------------------------------------------------------
# Stacked-layer GPT: scan-over-layers (fast compile) + pipeline parallelism
# ---------------------------------------------------------------------------
class GPTStacked(Layer):
    """GPT with all decoder blocks stored as STACKED parameters
    ([num_layers, ...]).

    Why: (a) lax.scan over the layer dim compiles O(1) in depth instead of
    O(L); (b) the 'pp' mesh axis shards the layer dim, and the same stacked
    layout feeds the GPipe schedule in distributed/pipeline.py — the TPU
    rendering of reference fleet meta_parallel/pipeline_parallel.py.
    Attention uses the jnp path (GSPMD-sharded); dropout is not applied
    inside stacked blocks.

    With pp_schedule="interleaved" the layer stack is stored in virtual-
    chunk schedule order (permuted once at construction, so the compiled
    step never reshards it). Checkpoints saved from such a model are in
    that order: load them only into a model built with the same pp degree
    and pp_virtual, or convert rows via layer_storage_order(). Running the
    model under a different mesh raises.
    """

    def __init__(self, cfg: GPTConfig, pp_microbatches: int = 4,
                 pp_schedule: str = "1f1b", pp_virtual: int = 2):
        super().__init__()
        self.cfg = cfg
        self.pp_microbatches = pp_microbatches
        self.pp_schedule = pp_schedule
        self.pp_virtual = pp_virtual
        h, f, L = cfg.hidden_size, cfg.ffn_hidden, cfg.num_layers
        init = Normal(0.0, cfg.init_std)
        out_init = Normal(0.0, cfg.init_std / math.sqrt(2.0 * cfg.num_layers))
        self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size,
                                weight_attr=nn.ParamAttr(initializer=init))
        self.wte.weight.partition_spec = ("tp", None)
        self.wpe = nn.Embedding(cfg.max_seq_len, cfg.hidden_size,
                                weight_attr=nn.ParamAttr(initializer=init))
        self.ln_f = nn.LayerNorm(h)

        def mk(name, shape, initializer, spec):
            p = self.create_parameter(shape, default_initializer=initializer)
            p.partition_spec = spec
            self.add_parameter(name, p)

        one, zero = Constant(1.0), Constant(0.0)
        mk("ln1_w", [L, h], one, ("pp", None))
        mk("ln1_b", [L, h], zero, ("pp", None))
        mk("qkv_w", [L, h, 3 * h], init, ("pp", None, "tp"))
        mk("qkv_b", [L, 3 * h], zero, ("pp", "tp"))
        mk("proj_w", [L, h, h], out_init, ("pp", "tp", None))
        mk("proj_b", [L, h], zero, ("pp", None))
        mk("ln2_w", [L, h], one, ("pp", None))
        mk("ln2_b", [L, h], zero, ("pp", None))
        mk("fc1_w", [L, h, f], init, ("pp", None, "tp"))
        mk("fc1_b", [L, f], zero, ("pp", "tp"))
        mk("fc2_w", [L, f, h], out_init, ("pp", "tp", None))
        mk("fc2_b", [L, h], zero, ("pp", None))

        # Interleaved schedule: store the layer stack in the device-major
        # virtual-chunk order ONCE, so the compiled step never reshards the
        # whole stack (a per-step all-to-all otherwise). state_dict() then
        # holds layers in schedule order; `layer_storage_order()` gives the
        # original-index-of-row mapping for checkpoint conversion.
        self._pp_perm = None
        self._pp_perm_stages = None
        if pp_schedule.startswith("interleaved"):
            from ..distributed.mesh import get_mesh
            from ..distributed.pipeline import _interleave_perm
            mesh = get_mesh(create_default=False)
            S = mesh.shape.get("pp", 1) if mesh is not None else 1
            if S > 1 and L % (S * pp_virtual) == 0:
                perm = _interleave_perm(L, S, pp_virtual)
                for k in self._BLOCK_KEYS:
                    p = self._parameters[k]
                    p._value = jnp.take(p._value, jnp.asarray(perm), axis=0)
                self._pp_perm = perm
                self._pp_perm_stages = S

    def layer_storage_order(self):
        """Row i of every stacked parameter holds the weights of ORIGINAL
        layer `layer_storage_order()[i]` (identity unless the interleaved
        schedule permuted storage at construction)."""
        import numpy as np
        if self._pp_perm is None:
            return np.arange(self.cfg.num_layers)
        return np.asarray(self._pp_perm)

    _BLOCK_KEYS = ("ln1_w", "ln1_b", "qkv_w", "qkv_b", "proj_w", "proj_b",
                   "ln2_w", "ln2_b", "fc1_w", "fc1_b", "fc2_w", "fc2_b")

    def _block_step(self, p, xv):
        """One decoder block on raw arrays. p: one layer's param dict."""
        cfg = self.cfg

        def ln(z, w, b):
            z32 = z.astype(jnp.float32)
            mu = jnp.mean(z32, -1, keepdims=True)
            var = jnp.mean(jnp.square(z32 - mu), -1, keepdims=True)
            return ((z32 - mu) * jax.lax.rsqrt(var + 1e-5)).astype(z.dtype) \
                * w.astype(z.dtype) + b.astype(z.dtype)

        B, L = xv.shape[0], xv.shape[1]
        y = ln(xv, p["ln1_w"], p["ln1_b"])
        qkv = y @ p["qkv_w"].astype(y.dtype) + p["qkv_b"].astype(y.dtype)
        qkv = qkv.reshape(B, L, 3, cfg.num_heads, cfg.head_dim)
        from ..ops.attention import flash_raw_or_reference
        attn = flash_raw_or_reference(qkv[:, :, 0], qkv[:, :, 1],
                                      qkv[:, :, 2], causal=True)
        attn = attn.reshape(B, L, cfg.hidden_size)
        xv = xv + attn @ p["proj_w"].astype(y.dtype) + p["proj_b"].astype(y.dtype)
        y = ln(xv, p["ln2_w"], p["ln2_b"])
        y = jax.nn.gelu(y @ p["fc1_w"].astype(y.dtype) + p["fc1_b"].astype(y.dtype),
                        approximate=True)
        return xv + y @ p["fc2_w"].astype(y.dtype) + p["fc2_b"].astype(y.dtype)

    def _stage_fn(self, params_local, xv):
        """Apply a contiguous slice of layers (scan + per-layer remat)."""
        step = self._block_step
        if self.cfg.remat and self.cfg.remat_policy != "none":
            step = jax.checkpoint(step, policy=_remat_policy(self.cfg.remat_policy))

        def body(carry, pslice):
            return step(pslice, carry), None

        out, _ = jax.lax.scan(body, xv, params_local)
        return out

    def forward(self, input_ids):
        cfg = self.cfg
        from ..tensor.creation import arange
        from ..distributed.mesh import get_mesh
        from ..distributed.pipeline import pipeline_apply

        L = input_ids.shape[1]
        pos = arange(L, dtype="int32")
        x = self.wte(input_ids) + self.wpe(pos)
        x = x.astype(cfg.dtype)
        mesh = get_mesh(create_default=False)
        if self._pp_perm is not None:
            # storage is baked in schedule order for a specific pp degree;
            # running under any other mesh would apply layers out of order
            pp_now = mesh.shape.get("pp", 1) if mesh is not None else 1
            if pp_now != self._pp_perm_stages:
                raise RuntimeError(
                    f"GPTStacked was built with interleaved layer storage "
                    f"for pp={self._pp_perm_stages}, but the current mesh "
                    f"has pp={pp_now}. Rebuild the model under the target "
                    f"mesh (see layer_storage_order() for checkpoint "
                    f"conversion).")
        stacked_names = list(self._BLOCK_KEYS)
        stacked_tensors = [self._parameters[k] for k in stacked_names]
        n_micro = self.pp_microbatches

        def run(xv, *pvals):
            stacked = dict(zip(stacked_names, pvals))
            if mesh is not None and mesh.shape.get("pp", 1) > 1:
                return pipeline_apply(self._stage_fn, stacked, xv, n_micro,
                                      mesh=mesh, schedule=self.pp_schedule,
                                      virtual=self.pp_virtual,
                                      pre_permuted=self._pp_perm is not None)
            return self._stage_fn(stacked, xv)

        x = apply_op(run, x, *stacked_tensors)
        x = self.ln_f(x)
        logits = apply_op(
            lambda h, e: jax.lax.dot_general(
                h, e, (((2,), (1,)), ((), ())), preferred_element_type=jnp.float32),
            x, self.wte.weight)
        return logits


def graph_contract(cfg):
    """Graph Doctor contract (paddle_tpu.analysis): dot_general count of
    the CPU-lowered eval forward — 4 projections per block (qkv, proj,
    fc1, fc2) + 2 attention matmuls (qk, av) per block on the reference
    attention path + the tied lm_head."""
    return {"dot_general": cfg.num_layers * 6 + 1}


# by-design activation transposes of the reference attention path: the
# [B,L,H,D]<->[B,H,L,D] head moves and the k^T flip. On TPU the Pallas
# flash kernel owns layout in-kernel; on the CPU-lowered graph these are
# the algorithm, not a layout regression (Graph Doctor exemptions).
ATTENTION_TRANSPOSES = (r"dims = \[0, 2, 1, 3\]", r"dims = \[0, 1, 3, 2\]")
