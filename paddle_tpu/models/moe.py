"""Mixture-of-Experts — reference python/paddle/incubate/distributed/models/moe
(MoELayer: gate + per-rank experts + NCCL all_to_all dispatch).

TPU-native (GShard recipe): experts live STACKED on an 'ep'-sharded leading
dim; token dispatch/combine are einsums against a capacity-bounded one-hot
dispatch tensor, so shapes stay static and XLA lowers the dispatch to
all_to_all over ICI automatically. Top-2 gating with load-balance aux loss.
"""
import dataclasses
import math

import jax
import jax.numpy as jnp

from .. import nn
from ..framework.core import Tensor, apply_op
from ..nn.initializer import Constant, Normal
from ..nn.layer_base import Layer
from .gpt import GPT, GPTBlock, GPTConfig, GPTPretrainingCriterion

__all__ = ["MoEConfig", "MoEMLP", "GPTMoE", "gpt_moe_tiny", "gpt_moe_small"]


@dataclasses.dataclass
class MoEConfig(GPTConfig):
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    moe_every: int = 2  # every Nth block gets an MoE MLP
    gate: str = "topk"  # "topk" | "switch" | "gshard" (moe_gate.py)
    switch_eps: float = 0.1       # SwitchGate training jitter
    random_routing: bool = True   # GShard random 2nd-expert drop

    def __post_init__(self):
        super().__post_init__()
        # "switch" is top-1, "gshard" top-2 by definition; keep top_k (the
        # FLOPs/capacity accounting input) consistent with the policy
        if isinstance(self.gate, str):
            if self.gate == "switch":
                self.top_k = 1
            elif self.gate == "gshard":
                self.top_k = 2
            elif self.gate != "topk":
                raise ValueError(
                    f"unknown MoE gate {self.gate!r}: "
                    "'topk', 'switch' or 'gshard'")
        else:
            # a policy instance defines its own k; keep the config's
            # FLOPs/capacity accounting in sync with actual routing
            self.top_k = int(self.gate.top_k)

    def _n_moe_blocks(self):
        return sum(1 for i in range(self.num_layers)
                   if i % self.moe_every == self.moe_every - 1)

    def _expert_mlp_params(self):
        # one expert's FF: w1 [h,f] + b1 [f] + w2 [f,h] + b2 [h]
        h, f = self.hidden_size, self.ffn_hidden
        return 2 * h * f + f + h

    def num_params(self):
        # dense equivalent + per-MoE-block gate and the (E-1) extra experts
        # replacing that block's dense MLP
        extra = self._n_moe_blocks() * (
            self.hidden_size * self.num_experts
            + (self.num_experts - 1) * self._expert_mlp_params())
        return super().num_params() + extra

    def num_active_params(self):
        """Per-token ACTIVATED parameters (backbone + gate + top_k of the E
        experts in each MoE block): the N in the 6N FLOPs/token roofline —
        routed-expert FLOPs scale with top_k, not num_experts."""
        extra = self._n_moe_blocks() * (
            self.hidden_size * self.num_experts
            + (self.top_k - 1) * self._expert_mlp_params())
        return super().num_params() + extra


def _moe_dispatch(x, gate_w, w1, b1, w2, b2, gate_policy, capacity_factor,
                  key=None, train=False):
    """x: [T, H] tokens. Returns (y [T, H], aux_loss scalar).
    Pure function — runs under jit/GSPMD; the E dim of w1/w2 is 'ep'-sharded.
    Routing policy (top-k count, selection noise, per-round random drops)
    comes from `gate_policy` (models/moe_gate.py).
    """
    T, H = x.shape
    E = w1.shape[0]
    top_k = gate_policy.top_k
    C = max(1, int(capacity_factor * T * top_k / E))
    if key is None:
        key = jax.random.key(0)
    sel_key, route_key = jax.random.split(jax.random.fold_in(key, T))

    logits = (x.astype(jnp.float32) @ gate_w.astype(jnp.float32))  # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    # selection may see jittered logits (SwitchGate); combine weights and
    # the aux loss always use the clean probabilities
    sel_probs = jax.nn.softmax(
        gate_policy.select_logits(logits, sel_key, train), axis=-1)

    # top-k selection, one expert at a time (k small)
    combine = jnp.zeros((T, E, C), jnp.float32)
    dispatch = jnp.zeros((T, E, C), bool)
    remaining = sel_probs
    # track per-expert slot usage across the k rounds
    base_count = jnp.zeros((E,), jnp.int32)
    aux_me = jnp.mean(probs, axis=0)  # mean gate prob per expert
    frac_tokens = jnp.zeros((E,), jnp.float32)
    sel_gate_sum = jnp.zeros((T,), jnp.float32)
    for k in range(top_k):
        expert = jnp.argmax(remaining, axis=-1)              # [T]
        onehot = jax.nn.one_hot(expert, E, dtype=jnp.int32)  # [T, E]
        # combine weight comes from the CLEAN probs at the chosen expert
        gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]
        # renorm denominator counts every SELECTED expert (g1+g2), before
        # random drops/capacity — post-drop sums would degenerate to 1
        sel_gate_sum = sel_gate_sum + gate
        remaining = remaining * (1.0 - onehot.astype(jnp.float32))
        extra = gate_policy.keep_round(
            k, gate, jax.random.fold_in(route_key, k), train)
        if extra is not None:
            # e.g. GShard random 2nd-expert drop: the token leaves the
            # round entirely (consumes no capacity slot)
            onehot = onehot * extra[:, None].astype(jnp.int32)
        frac_tokens = frac_tokens + jnp.mean(onehot.astype(jnp.float32), axis=0)
        # position of each token within its expert's queue this round
        pos_in_expert = jnp.cumsum(onehot, axis=0) - onehot + base_count[None, :]
        pos = jnp.sum(pos_in_expert * onehot, axis=1)        # [T]
        keep = (pos < C) & (jnp.sum(onehot, axis=1) > 0)
        slot = jax.nn.one_hot(jnp.where(keep, pos, C), C + 1, dtype=jnp.float32)[:, :C]
        contrib = onehot.astype(jnp.float32)[:, :, None] * slot[:, None, :]
        combine = combine + gate[:, None, None] * contrib
        dispatch = dispatch | (contrib > 0)
        base_count = base_count + jnp.sum(onehot * keep[:, None].astype(jnp.int32), axis=0)

    if getattr(gate_policy, "normalize_combine", top_k > 1):
        # renormalize combine weights over the selected experts (GShard
        # g1/(g1+g2) convention). Top-1 gates must NOT renormalize: the
        # weight would become a constant 1 and the router would get zero
        # task-loss gradient — Switch scales output by the raw prob.
        combine = combine / jnp.maximum(
            sel_gate_sum[:, None, None], 1e-9)

    aux = E * jnp.sum(aux_me * frac_tokens / top_k)

    expert_in = jnp.einsum("tec,th->ech", dispatch.astype(x.dtype), x)  # a2a here
    h = jax.nn.gelu(jnp.einsum("ech,ehf->ecf", expert_in, w1.astype(x.dtype))
                    + b1[:, None, :].astype(x.dtype), approximate=True)
    expert_out = jnp.einsum("ecf,efh->ech", h, w2.astype(x.dtype)) \
        + b2[:, None, :].astype(x.dtype)
    y = jnp.einsum("tec,ech->th", combine.astype(x.dtype), expert_out)  # a2a back
    return y, aux.astype(jnp.float32)


class MoEMLP(Layer):
    """Drop-in MLP replacement: top-k routed experts over the 'ep' axis."""

    def __init__(self, cfg: MoEConfig):
        super().__init__()
        self.cfg = cfg
        h, f, E = cfg.hidden_size, cfg.ffn_hidden, cfg.num_experts
        init = Normal(0.0, cfg.init_std)
        out_init = Normal(0.0, cfg.init_std / math.sqrt(2.0 * cfg.num_layers))
        self.gate_w = self.create_parameter([h, E], default_initializer=init)
        self.w1 = self.create_parameter([E, h, f], default_initializer=init)
        self.w1.partition_spec = ("ep", None, "tp")
        self.b1 = self.create_parameter([E, f], default_initializer=Constant(0.0))
        self.b1.partition_spec = ("ep", "tp")
        self.w2 = self.create_parameter([E, f, h], default_initializer=out_init)
        self.w2.partition_spec = ("ep", "tp", None)
        self.b2 = self.create_parameter([E, h], default_initializer=Constant(0.0))
        self.b2.partition_spec = ("ep", None)
        from .moe_gate import make_gate
        self.gate_policy = make_gate(cfg.gate, cfg)
        self.last_aux_loss = None

    def forward(self, x):
        cfg = self.cfg
        B, L, H = x.shape[0], x.shape[1], x.shape[2]
        from ..framework.random import next_key
        from ..tensor.manipulation import reshape
        flat = reshape(x, [B * L, H])
        policy, train = self.gate_policy, self.training
        # stochastic gates (switch jitter, gshard random routing) draw
        # from the framework's seeded key stream, like dropout does
        key = next_key() if train else None
        out = apply_op(
            lambda xv, gw, w1, b1, w2, b2: _moe_dispatch(
                xv, gw, w1, b1, w2, b2, policy, cfg.capacity_factor,
                key=key, train=train),
            flat, self.gate_w, self.w1, self.b1, self.w2, self.b2)
        y, aux = out
        self.last_aux_loss = aux
        return reshape(y, [B, L, H])


class GPTMoEBlock(GPTBlock):
    def __init__(self, cfg: MoEConfig, layer_idx: int):
        super().__init__(cfg, layer_idx)
        if layer_idx % cfg.moe_every == cfg.moe_every - 1:
            # replace dense MLP with routed experts
            del self.fc1
            del self.fc2
            self.moe = MoEMLP(cfg)
        else:
            self.moe = None

    def forward(self, x):
        from ..nn import functional as F
        from ..tensor.manipulation import reshape
        if self.moe is None:
            return super().forward(x)
        cfg = self.cfg
        B, L = x.shape[0], x.shape[1]
        res = x
        y = self.ln1(x)
        qkv = self.qkv(y)
        qkv = reshape(qkv, [B, L, 3, cfg.num_heads, cfg.head_dim])
        attn = F.scaled_dot_product_attention(
            qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2], is_causal=True,
            dropout_p=cfg.dropout, training=self.training)
        x = res + self.proj(reshape(attn, [B, L, cfg.hidden_size]))
        return x + self.moe(self.ln2(x))


class GPTMoE(GPT):
    """GPT with routed-expert MLPs every `moe_every` blocks (reference
    GPT-MoE recipe: PaddleNLP MoE + fleet expert parallel)."""

    def __init__(self, cfg: MoEConfig):
        Layer.__init__(self)
        self.cfg = cfg
        init = Normal(0.0, cfg.init_std)
        self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size,
                                weight_attr=nn.ParamAttr(initializer=init))
        self.wte.weight.partition_spec = ("tp", None)
        self.wpe = nn.Embedding(cfg.max_seq_len, cfg.hidden_size,
                                weight_attr=nn.ParamAttr(initializer=init))
        self.drop = nn.Dropout(cfg.dropout)
        self.blocks = nn.LayerList([GPTMoEBlock(cfg, i) for i in range(cfg.num_layers)])
        self.ln_f = nn.LayerNorm(cfg.hidden_size)

    def _run_block(self, block, x):
        # no per-block remat here: MoE aux losses are read back from the
        # blocks after forward, which must stay in the same trace
        return block(x)

    def aux_loss(self):
        total = None
        for b in self.blocks:
            if getattr(b, "moe", None) is not None and b.moe.last_aux_loss is not None:
                total = b.moe.last_aux_loss if total is None else total + b.moe.last_aux_loss
        if total is None:
            return Tensor(jnp.zeros((), jnp.float32))
        return total * self.cfg.aux_loss_weight


def gpt_moe_tiny(**kw):
    base = dict(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
                max_seq_len=64, dtype="float32", num_experts=4, top_k=2,
                remat=False)
    base.update(kw)
    return MoEConfig(**base)


def gpt_moe_small(**kw):
    """~350M-class dense backbone with 8 experts every 2nd block (the
    single-chip bench config; scale num_experts with the 'ep' degree)."""
    base = dict(hidden_size=1024, num_layers=12, num_heads=16,
                num_experts=8, top_k=2)
    base.update(kw)
    return MoEConfig(**base)


def router_f32_allow(cfg):
    """Graph Doctor exemption (paddle_tpu.analysis): the ROUTER keeps
    f32 by design (bf16 top-k gate logits destabilize capacity
    assignment — the reference gate computes fp32 too), so an f32
    dot_general is legal iff it is router-sized: result trailing dim ==
    num_experts. Anything bigger in f32 is a down-cast regression."""
    import re as _re

    def allow(op):
        out_ty = op.result_types[-1] if op.result_types else ""
        m = _re.match(r"((?:\d+x)*)f32", out_ty)
        if not m:
            return False
        dims = [int(d) for d in m.group(1).split("x") if d]
        return bool(dims) and dims[-1] == cfg.num_experts
    return allow
