"""paddle_tpu.models — flagship model zoo (NLP side; vision lives in
paddle_tpu.vision.models). Mirrors the PaddleNLP model recipes the reference
headline benchmarks use (GPT-3, BERT/ERNIE, GPT-MoE)."""
from .gpt import (  # noqa: F401
    GPT,
    GPTConfig,
    GPTPretrainingCriterion,
    GPTStacked,
    gpt_125m,
    gpt_350m,
    gpt_760m,
    gpt_1p3b,
    gpt_tiny,
)
from .moe import GPTMoE, MoEConfig, MoEMLP, gpt_moe_small, gpt_moe_tiny  # noqa: F401
from .bert import (  # noqa: F401
    BertConfig,
    BertForPretraining,
    BertForSequenceClassification,
    BertModel,
    BertPretrainingCriterion,
    ErnieConfig,
    ErnieForSequenceClassification,
    ErnieModel,
    bert_base,
    bert_large,
    bert_tiny,
)
from .generation import beam_search, generate  # noqa: F401
from .transformer import (  # noqa: F401
    CrossEntropyCriterion,
    TransformerModel,
    transformer_base,
    transformer_big,
)
