"""Model summary — reference python/paddle/hapi/model_summary.py."""
import numpy as np

from ..framework.core import Tensor
from ..nn.layer_base import Layer

__all__ = ["summary"]


def summary(net, input_size=None, dtypes=None, input=None):
    """Prints a per-layer table; returns {'total_params', 'trainable_params'}."""
    rows = []
    hooks = []

    def make_hook(name, layer):
        def hook(l, inputs, outputs):
            n_params = sum(p.size for p in l._parameters.values() if p is not None)
            out_shape = outputs.shape if isinstance(outputs, Tensor) else "-"
            rows.append((name, type(l).__name__, out_shape, n_params))
        return hook

    for name, layer in net.named_sublayers():
        if not layer._sub_layers:  # leaves only
            hooks.append(layer.register_forward_post_hook(make_hook(name, layer)))

    if input is not None:
        net(input)
    elif input_size is not None:
        import jax.numpy as jnp
        shape = input_size if isinstance(input_size, (list, tuple)) else [input_size]
        if isinstance(shape[0], (list, tuple)):
            xs = [Tensor(jnp.zeros(s, jnp.float32)) for s in shape]
            net(*xs)
        else:
            net(Tensor(jnp.zeros(shape, jnp.float32)))
    for h in hooks:
        h.remove()

    total = sum(p.size for p in net.parameters())
    trainable = sum(p.size for p in net.parameters() if not p.stop_gradient)
    if rows:
        w = max(len(r[0]) for r in rows) + 2
        print(f"{'Layer':<{w}}{'Type':<24}{'Output Shape':<20}{'Params':>12}")
        print("-" * (w + 56))
        for name, typ, shape, n in rows:
            print(f"{name:<{w}}{typ:<24}{str(shape):<20}{n:>12,}")
        print("-" * (w + 56))
    print(f"Total params: {total:,}\nTrainable params: {trainable:,}")
    return {"total_params": total, "trainable_params": trainable}
