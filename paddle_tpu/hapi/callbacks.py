"""High-level API callbacks — reference python/paddle/hapi/callbacks.py."""
import csv
import os
import time

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "LRScheduler",
           "EarlyStopping", "VisualDL", "CallbackList"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def fanout(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)
            return fanout
        raise AttributeError(name)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self._t0 = time.time()
        self.steps = self.params.get("steps")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = " - ".join(f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
                               for k, v in (logs or {}).items())
            total = f"/{self.steps}" if self.steps else ""
            print(f"Epoch {self.epoch}: step {step}{total} - {items}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            items = " - ".join(f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
                               for k, v in (logs or {}).items())
            print(f"Epoch {epoch} done in {dt:.1f}s - {items}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        return opt._lr_scheduler if opt is not None else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        if mode == "max" or (mode == "auto" and "acc" in monitor):
            self.better = lambda cur, best: cur > best + self.min_delta
            self.best = -float("inf")
        else:
            self.better = lambda cur, best: cur < best - self.min_delta
            self.best = float("inf")
        self.wait = 0
        self.stopped_epoch = 0

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        cur = float(cur[0] if isinstance(cur, (list, tuple)) else cur)
        if self.better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class VisualDL(Callback):
    """CSV-backed scalar logger (the VisualDL service isn't in this image)."""

    def __init__(self, log_dir="./log"):
        super().__init__()
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        self._file = None

    def on_train_begin(self, logs=None):
        self._file = open(os.path.join(self.log_dir, "scalars.csv"), "a", newline="")
        self._writer = csv.writer(self._file)

    def on_train_batch_end(self, step, logs=None):
        for k, v in (logs or {}).items():
            if isinstance(v, (int, float)):
                self._writer.writerow([time.time(), step, k, v])

    def on_train_end(self, logs=None):
        if self._file:
            self._file.close()
