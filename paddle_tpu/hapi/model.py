"""High-level Model API — reference python/paddle/hapi/model.py.

TPU-first: Model.fit compiles one whole train step (forward+loss+grads+update)
with jax.jit via the functional optimizer path, donating params/opt-state so
updates are in-place in HBM. Eager fallback keeps paddle debugging UX.
"""
import time

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..metric import Metric
from ..nn.layer_base import Layer, buffer_pytree, functional_call, state_pytree
from .callbacks import CallbackList, ProgBarLogger

__all__ = ["Model"]


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = (list(inputs) if isinstance(inputs, (list, tuple))
                        else [inputs]) if inputs is not None else None
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self.stop_training = False
        self._compiled_step = None
        self._compiled_multi = None
        # optional serving.trace.FlightRecorder: fit(multi_step=N)
        # horizons record "train" ticks on it (dead branch when None —
        # the Trainer.attach_recorder discipline, hapi rendering)
        self.flight_recorder = None
        self._rec_last_t = None

    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is None:
            self._metrics = []
        else:
            self._metrics = metrics if isinstance(metrics, (list, tuple)) else [metrics]

    # -- compiled train step -------------------------------------------------
    def _train_step_body(self):
        """The ONE single-step body shared by `_build_train_step`'s jit
        and every tick of `_build_train_multi_step`'s fused scan — the
        two paths cannot drift (`distributed.trainer._build_body`
        pattern)."""
        net = self.network
        loss_fn = self._loss
        opt = self._optimizer

        def step(params, buffers, opt_state, lr, inputs, labels):
            def compute_loss(p):
                from ..nn.layer_base import collect_buffer_updates
                with collect_buffer_updates() as sink:
                    with functional_call(net, {**p, **buffers}):
                        out = net(*inputs)
                # BN running stats recorded during the trace carry forward
                updates = {}
                if sink:
                    by_id = {id(b): name for name, b in net.named_buffers()}
                    for tid, (_, val) in sink.items():
                        if tid in by_id:
                            updates[by_id[tid]] = val
                loss = loss_fn(out, *labels)
                lv = loss._value if isinstance(loss, Tensor) else loss
                return jnp.mean(lv), (out._value if isinstance(out, Tensor) else out,
                                      updates)

            (loss_v, (out, updates)), grads = jax.value_and_grad(
                compute_loss, has_aux=True)(params)
            new_params, new_state = opt.apply_gradients_pytree(params, grads, opt_state, lr)
            return new_params, new_state, {**buffers, **updates}, loss_v, out

        return step

    @staticmethod
    def _donate_argnums():
        # Donating params/opt_state lets XLA alias the new state into the
        # old buffers — the memory win training needs on TPU. But this
        # jaxlib's ASYNC CPU client can release a donated input buffer
        # while a host read of an output aliased into it is still in
        # flight: heap corruption (segfault inside np.asarray of the
        # step's `out` during metric compute, ~1 in 3 runs of
        # tests/test_hapi_fit.py, reproduced at 2/8 on the pristine tree
        # and 0/10 with donation off). CPU runs are functional tests, not
        # memory-bound — skip donation there, keep it on real chips.
        return () if jax.default_backend() == "cpu" else (0, 2)

    def _build_train_step(self):
        return jax.jit(self._train_step_body(),
                       donate_argnums=self._donate_argnums())

    def _build_train_multi_step(self):
        """N train steps in ONE jitted lax.scan over leading-stacked
        inputs/labels and an [N] lr vector, params/buffers/opt-state
        threaded through the carry. Per-step logits are NOT carried out
        (metrics force per-step syncs and disable this path); the [N]
        loss vector returns unfetched so host contact stays at horizon
        boundaries."""
        body = self._train_step_body()

        def multi(params, buffers, opt_state, lrs, inputs, labels):
            def tick(carry, xs):
                params, buffers, opt_state = carry
                lr, ins, labs = xs
                params, opt_state, buffers, loss_v, _out = body(
                    params, buffers, opt_state, lr, list(ins), list(labs))
                return (params, buffers, opt_state), loss_v

            (params, buffers, opt_state), losses = jax.lax.scan(
                tick, (params, buffers, opt_state),
                (lrs, tuple(inputs), tuple(labels)))
            return params, opt_state, buffers, losses

        return jax.jit(multi, donate_argnums=self._donate_argnums())

    def _ensure_train_state(self):
        """Lazy one-time bootstrap of the functional training state
        (params/buffers/opt-state pytrees + the compiled single step) —
        shared by train_batch and train_batch_multi so the two paths
        can never initialize different state."""
        if self._compiled_step is None:
            net = self.network
            self._params = state_pytree(net, trainable_only=True)
            self._buffers = {k: v for k, v in {**dict(
                (n, p._value) for n, p in net.named_parameters() if p.stop_gradient),
                **buffer_pytree(net)}.items() if k not in self._params}
            self._opt_state = self._optimizer.init_state_pytree(self._params)
            self._compiled_step = self._build_train_step()

    def train_batch(self, inputs, labels=None, update=True, fetch=True):
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if isinstance(labels, (list, tuple)) else ([labels] if labels is not None else [])
        net = self.network
        net.train()
        self._ensure_train_state()
        in_vals = [self._leaf_value(x) for x in inputs]
        lab_vals = [self._leaf_value(x) for x in labels]
        lr = self._optimizer.get_lr()
        self._params, self._opt_state, self._buffers, loss_v, out = self._compiled_step(
            self._params, self._buffers, self._opt_state, lr, in_vals, lab_vals)
        # scheduler stepping belongs to the LRScheduler CALLBACK (fit
        # auto-configures one, reference hapi/callbacks.config_callbacks)
        # — stepping here too would double-advance it whenever a user
        # adds the callback explicitly, as the reference docs show
        metrics_out = []
        for m in self._metrics:
            correct = m.compute(Tensor(out), labels[0])
            m.update(correct)
            metrics_out.append(m.accumulate())
        if metrics_out:
            return float(loss_v), metrics_out
        # fetch=False: hand back the UNFETCHED device loss (async metrics
        # drain — fit's prefetch path batches the host syncs through a
        # LossBuffer instead of stalling dispatch every step)
        return float(loss_v) if fetch else loss_v

    def train_batch_multi(self, inputs_stack, labels_stack, lrs):
        """Dispatch N fused train steps (ONE compiled lax.scan) over
        leading-stacked inputs/labels ([N, B, ...] leaves) with the
        precomputed per-step `lrs` vector. Returns the UNFETCHED [N]
        device loss vector — `Model.fit(multi_step=N)` drains it at
        horizon boundaries. Scheduler stepping stays with the
        LRScheduler callback (fit precomputes `lrs` around it)."""
        net = self.network
        net.train()
        self._ensure_train_state()
        if self._compiled_multi is None:
            self._compiled_multi = self._build_train_multi_step()
        in_vals = [self._leaf_value(x) for x in inputs_stack]
        lab_vals = [self._leaf_value(x) for x in labels_stack]
        lrs = jnp.asarray(np.asarray(lrs, np.float32))
        rec = self.flight_recorder
        t0 = time.perf_counter() if rec is not None else None
        self._params, self._opt_state, self._buffers, losses = \
            self._compiled_multi(self._params, self._buffers,
                                 self._opt_state, lrs, in_vals, lab_vals)
        if rec is not None:
            # same measurement discipline as Trainer.step_multi:
            # dispatch is non-blocking, so steady-state horizon wall is
            # the dispatch-to-dispatch gap (first horizon: call wall),
            # and the tick's ts anchors at the window's START
            now = time.perf_counter()
            start = self._rec_last_t if self._rec_last_t is not None \
                else t0
            self._rec_last_t = now
            n = int(lrs.shape[0])
            rec.tick("train", ("fit", n), now - start, ts=start, k=n,
                     decode_rows=0, prefill_rows=0)
        return losses

    @staticmethod
    def _leaf_value(x):
        if isinstance(x, Tensor):
            return x._value
        if isinstance(x, jax.Array):   # device-resident (io.DeviceLoader)
            return x
        return jnp.asarray(np.asarray(x))

    @staticmethod
    def _raw_value(x):
        """Tensor -> raw array, everything else untouched (no device
        placement — shape reads and host-side stacking must not pay an
        H2D copy)."""
        return x._value if isinstance(x, Tensor) else x

    @staticmethod
    def _stack_leaves(values):
        """[per-step leaf, ...] -> one [N, ...] leaf (the shared
        io.prefetch horizon policy: device leaves stack on device,
        host leaves with numpy)."""
        from ..io.prefetch import stack_leaf_values
        return stack_leaf_values([Model._raw_value(v) for v in values])

    def _sync_params_back(self):
        if self._compiled_step is not None:
            from ..nn.layer_base import load_state_pytree
            load_state_pytree(self.network, {**self._buffers, **self._params})

    def eval_batch(self, inputs, labels=None):
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if isinstance(labels, (list, tuple)) else ([labels] if labels is not None else [])
        self._sync_params_back()
        net = self.network
        net.eval()
        out = net(*inputs)
        result = {}
        if self._loss is not None and labels:
            loss = self._loss(out, *labels)
            result["loss"] = float(loss.item() if hasattr(loss, "item") else loss)
        for m in self._metrics:
            correct = m.compute(out, labels[0])
            m.update(correct)
        return result

    def predict_batch(self, inputs):
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        self._sync_params_back()
        self.network.eval()
        return self.network(*inputs)

    # -- loops ---------------------------------------------------------------
    def _horizon_lrs(self, n, lr_cb):
        """Precompute the per-step lr vector for one fused horizon.
        Scheduler stepping is the LRScheduler CALLBACK's job and the
        callback now ticks once per HORIZON — so for by_step scheduling
        this advances the real scheduler n-1 times (ticks 1..n-1) and
        leaves the n-th step to the horizon-end callback: the scheduler
        lands exactly where n per-step batches would leave it, and
        warmup/decay boundaries mid-horizon feed the scan the same lr
        sequence the per-step loop sees."""
        opt = self._optimizer
        sched = opt._lr_scheduler if opt is not None else None
        if sched is None or lr_cb is None or not lr_cb.by_step:
            return [opt.get_lr()] * n
        lrs = [opt.get_lr()]
        for _ in range(n - 1):
            sched.step()
            lrs.append(opt.get_lr())
        return lrs

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            prefetch=False, prefetch_depth=2, multi_step=1, **kwargs):
        from ..io import DataLoader, Dataset, DeviceLoader
        loader = train_data if isinstance(train_data, (DataLoader, DeviceLoader)) \
            else DataLoader(
                train_data, batch_size=batch_size, shuffle=shuffle,
                drop_last=drop_last, num_workers=num_workers)
        # async input pipeline: device-resident sharded batches `depth`
        # ahead + loss syncs batched per log window instead of per step
        loss_buf = None
        own_device_loader = None
        if prefetch:
            from ..distributed.trainer import LossBuffer
            if not isinstance(loader, DeviceLoader):
                loader = own_device_loader = DeviceLoader(
                    loader, depth=prefetch_depth)
            if not self._metrics:   # metrics force a per-step host sync
                loss_buf = LossBuffer(drain_every=max(1, log_freq))
        # device-resident multi-step training: fuse `multi_step` train
        # steps into one compiled scan (train_batch_multi) and move
        # logging/callback/scheduler ticks to horizon boundaries.
        # Metrics force a per-step host sync (they consume per-step
        # logits), so they disable the fused path.
        multi_step = max(1, int(multi_step))
        if multi_step > 1 and self._metrics:
            import warnings
            warnings.warn("Model.fit: multi_step>1 disabled because "
                          "metrics require per-step outputs; running "
                          "per-step")
            multi_step = 1
        from .callbacks import LRScheduler
        user_cbs = list(callbacks or [])
        auto = [ProgBarLogger(log_freq, verbose)]
        # reference config_callbacks: an LRScheduler callback is always
        # present (it owns scheduler stepping); a user-provided one wins
        if not any(isinstance(c, LRScheduler) for c in user_cbs):
            auto.append(LRScheduler())
        cbs = CallbackList(auto + user_cbs)
        cbs.set_model(self)
        try:
            cbs.set_params({"epochs": epochs, "steps": len(loader)})
        except TypeError:
            cbs.set_params({"epochs": epochs, "steps": None})
        cbs.on_train_begin()
        self.stop_training = False
        try:
            lr_cb = next((c for c in cbs.callbacks
                          if isinstance(c, LRScheduler)), None)
            for epoch in range(epochs):
                cbs.on_epoch_begin(epoch)
                for m in self._metrics:
                    m.reset()
                logs = {}
                if multi_step > 1:
                    logs = self._fit_epoch_multi(loader, multi_step, cbs,
                                                 lr_cb, loss_buf)
                else:
                    for step, batch in enumerate(loader):
                        cbs.on_train_batch_begin(step)
                        inputs, labels = self._split_batch(batch)
                        res = self.train_batch(inputs, labels,
                                               fetch=loss_buf is None)
                        if isinstance(res, tuple):
                            loss, mvals = res
                            logs = {"loss": loss}
                            for m, v in zip(self._metrics, mvals):
                                names = m.name() if isinstance(m.name(), list) else [m.name()]
                                vals = v if isinstance(v, list) else [v]
                                logs.update(dict(zip(names, vals)))
                        elif loss_buf is not None:
                            # non-blocking: the device loss lands in the buffer;
                            # one host sync per drain window feeds the logs
                            loss_buf.append(res)
                            logs = {"loss": loss_buf.last
                                    if loss_buf.last is not None else float("nan")}
                        else:
                            logs = {"loss": res}
                        cbs.on_train_batch_end(step, logs)
                if loss_buf is not None:
                    logs = {"loss": loss_buf.drain()}
                cbs.on_epoch_end(epoch, logs)
                if eval_data is not None and (epoch + 1) % eval_freq == 0:
                    self.evaluate(eval_data, batch_size=batch_size, verbose=0)
                if save_dir and (epoch + 1) % save_freq == 0:
                    self.save(f"{save_dir}/{epoch}")
                if self.stop_training:
                    break
        finally:
            # close the loader fit itself created: an exception mid-epoch
            # must not strand the prefetch thread holding device batches
            if own_device_loader is not None:
                own_device_loader.close()
        cbs.on_train_end()

    def _fit_epoch_multi(self, loader, multi_step, cbs, lr_cb, loss_buf):
        """One epoch of horizon-granularity training: batches group into
        `multi_step`-deep horizons dispatched as ONE compiled scan
        (train_batch_multi), with callback/logging ticks fired once per
        horizon boundary. The final partial horizon (epoch length not a
        multiple of N) falls back to per-step `train_batch` — no fresh
        m-step scan compile for the tail."""
        logs = {}
        horizon = []        # [(step_idx, inputs, labels), ...]
        # fresh epoch: the gap back to the previous epoch's last
        # dispatch spans eval/checkpoint/callback host work, not a
        # horizon — the next tick measures its own call wall instead
        # (the Trainer.mark_recorder_idle discipline)
        self._rec_last_t = None

        def log_loss(fallback=None):
            if loss_buf is not None:
                last = loss_buf.last
                return {"loss": last if last is not None else float("nan")}
            return {"loss": fallback}

        def uniform():
            # a ragged final BATCH (drop_last=False default) can land
            # inside a full group — leaves of unequal leading shape
            # cannot stack, so such a horizon takes the per-step path.
            # Shapes are read off the RAW leaves: no device placement
            # just to measure them
            sig0 = [np.shape(self._raw_value(v))
                    for v in horizon[0][1] + horizon[0][2]]
            return all([np.shape(self._raw_value(v))
                        for v in h[1] + h[2]] == sig0 for h in horizon[1:])

        def flush():
            nonlocal logs
            if not horizon:
                return
            n = len(horizon)
            cbs.on_train_batch_begin(horizon[0][0])
            if n == multi_step and uniform():
                ins = [self._stack_leaves([h[1][i] for h in horizon])
                       for i in range(len(horizon[0][1]))]
                labs = [self._stack_leaves([h[2][i] for h in horizon])
                        for i in range(len(horizon[0][2]))]
                losses = self.train_batch_multi(
                    ins, labs, self._horizon_lrs(n, lr_cb))
                if loss_buf is not None:
                    loss_buf.append(losses)
                    logs = log_loss()
                else:
                    logs = {"loss": float(np.asarray(losses)[-1])}
            else:
                sched = (self._optimizer._lr_scheduler
                         if lr_cb is not None and lr_cb.by_step else None)
                for j, (_, ins, labs) in enumerate(horizon):
                    res = self.train_batch(ins, labs,
                                           fetch=loss_buf is None)
                    if loss_buf is not None:
                        loss_buf.append(res)
                    else:
                        logs = {"loss": res}
                    # per-step scheduler ticks for all but the last —
                    # the horizon-end callback supplies that one
                    if j < n - 1 and sched is not None:
                        sched.step()
                if loss_buf is not None:
                    logs = log_loss()
            cbs.on_train_batch_end(horizon[-1][0], logs)
            horizon.clear()

        for step, batch in enumerate(loader):
            inputs, labels = self._split_batch(batch)
            horizon.append((step, inputs, labels))
            if len(horizon) == multi_step:
                flush()
        flush()
        return logs

    def _split_batch(self, batch):
        if isinstance(batch, (list, tuple)):
            if len(batch) >= 2:
                return list(batch[:-1]), [batch[-1]]
            return [batch[0]], []
        return [batch], []

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, **kwargs):
        from ..io import DataLoader
        loader = eval_data if isinstance(eval_data, DataLoader) else DataLoader(
            eval_data, batch_size=batch_size, num_workers=num_workers)
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in loader:
            inputs, labels = self._split_batch(batch)
            res = self.eval_batch(inputs, labels)
            if "loss" in res:
                losses.append(res["loss"])
        out = {}
        if losses:
            out["loss"] = float(np.mean(losses))
        for m in self._metrics:
            names = m.name() if isinstance(m.name(), list) else [m.name()]
            vals = m.accumulate()
            vals = vals if isinstance(vals, list) else [vals]
            out.update(dict(zip(names, vals)))
        return out

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                callbacks=None, verbose=1, **kwargs):
        from ..io import DataLoader
        loader = test_data if isinstance(test_data, DataLoader) else DataLoader(
            test_data, batch_size=batch_size, num_workers=num_workers)
        outputs = []
        for batch in loader:
            inputs, _ = self._split_batch(batch)
            outputs.append(self.predict_batch(inputs))
        return outputs

    def save(self, path, training=True):
        from ..framework.io import save as fsave
        self._sync_params_back()
        if not training:
            # reference Model.save(training=False): export the INFERENCE
            # program (jit.save artifact executable without the Python
            # network) — requires the input specs given at Model(...)
            if self._inputs is None:
                raise ValueError(
                    "Model.save(training=False) exports an inference "
                    "program and needs input specs: construct the model "
                    "as Model(net, inputs=[InputSpec(...)])")
            from .. import jit
            jit.save(self.network, path, input_spec=self._inputs)
            return
        fsave(self.network.state_dict(), path + ".pdparams")
        if self._optimizer is not None:
            fsave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as fload
        state = fload(path + ".pdparams")
        self.network.set_state_dict(state)
        self._compiled_step = None  # rebuild with fresh params
        self._compiled_multi = None
        import os
        if not reset_optimizer and self._optimizer is not None and os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(fload(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        n_params = sum(p.size for p in self.network.parameters())
        trainable = sum(p.size for p in self.network.parameters() if not p.stop_gradient)
        return {"total_params": n_params, "trainable_params": trainable}
