"""AST transformation for dy2static (reference program_translator.py +
ifelse_transformer.py / loop_transformer.py / logical_transformer.py).

`convert_to_static(fn)` rewrites a function's source so Python control
flow that *might* depend on tensors is routed through the dual-path
runtime converters in convert_ops:

    if t.sum() > 0: x = x + 1        →  functionalized branch fns + _jst.convert_ifelse
    while norm(x) > eps: x = f(x)    →  cond/body fns + _jst.convert_while_loop
    for row in tensor: acc += row    →  body fn + _jst.convert_for (lax.scan)
    a and b / not a                  →  _jst.convert_and / _jst.convert_not

Concrete (non-traced) conditions keep exact Python semantics, so the
transform is safe to apply universally; traced conditions lower to
lax.cond / lax.while_loop / lax.scan.

`break`/`continue` in tensor loops are rewritten into guard flags
(break -> carried stop flag ANDed into the loop condition; continue ->
iteration-local skip flag guarding the rest of the body) and then ride
the normal if/while functionalization.

Early returns are functionalized by restructuring (_fold_early_returns):
`if c: ...return` folds its fall-through into the other branch — with or
without an existing else — until every data-dependent return covers both
branches of a lax.cond; `return` inside a loop becomes a guard flag +
value carrier + `break` (riding the break machinery), re-raised after
the loop.  A return whose VALUE is only defined under a traced loop
carry still needs a pre-loop tensor value (lax carries are shape-static)
— the converter says so explicitly.

A `with ctx: ... return e` tail rides WHOLE into its branch fn (the
context manager is never split), so returns inside with-blocks
functionalize too.

Deliberately NOT functionalized (left as plain Python, which still works
for concrete conditions and raises jax's tracer error for traced ones):
break/continue inside with/try blocks, returns inside try,
`global`/`nonlocal`, loop-`else`.
"""
import ast
import copy
import functools
import inspect
import linecache
import textwrap
import types
import weakref

from . import convert_ops as _jst_mod

_TEMPLATES = {}    # fn.__code__ -> (module_code, fdef_name, kept_decorators)
_CONVERTED = weakref.WeakKeyDictionary()   # fn -> converted fn (per closure)
_BY_CODE_KEY = "__dy2static_by_code__"  # per-module cache slot: code -> fn
_FAILED = {}       # fn.__code__ -> reason string (for diagnostics)


class _LiveGlobals(dict):
    """exec-globals that READ through to the original module namespace
    live (a snapshot would hide later rebinds of module attributes from
    converted code) while keeping definitions (the transformed function,
    _jst) out of the user's module.  Works because CPython's LOAD_GLOBAL
    takes the generic-mapping path for dict subclasses."""

    def __init__(self, base, extra):
        super().__init__(extra)
        self._base = base

    def __missing__(self, key):
        return self._base[key]


# --------------------------------------------------------------------------
# name analysis
# --------------------------------------------------------------------------

_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                ast.Lambda, ast.ListComp, ast.SetComp, ast.DictComp,
                ast.GeneratorExp)


def _walk_same_scope(node, into_loops=True):
    """Yield nodes in the same variable scope (don't descend into nested
    function/class/comprehension scopes — including when the root itself
    opens one)."""
    if isinstance(node, _SCOPE_NODES):
        return
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, _SCOPE_NODES):
            continue
        if not into_loops and isinstance(n, (ast.While, ast.For)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _stores(stmts, local_names=None):
    """Ordered simple-Name assignment targets in these statements (same
    scope): Assign/AugAssign/AnnAssign/NamedExpr/For-target/With-as.
    A subscript store (`out[i] = v`) counts as a store of its base —
    Tensor __setitem__ is a functional update that must be threaded
    through lax control flow — but ONLY when the base is a local of the
    enclosing function (`local_names`); subscript writes to globals or
    closure objects are genuine side effects and must stay untouched."""
    seen, out = set(), []

    def add(name):
        if name not in seen:
            seen.add(name)
            out.append(name)

    def targets_of(t):
        if isinstance(t, ast.Name):
            add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                targets_of(e)
        elif isinstance(t, ast.Starred):
            targets_of(t.value)
        elif isinstance(t, ast.Subscript) and isinstance(t.value, ast.Name):
            if local_names is None or t.value.id in local_names:
                add(t.value.id)

    for stmt in stmts:
        for n in [stmt] + list(_walk_same_scope(stmt)):
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    targets_of(t)
            elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
                targets_of(n.target)
            elif isinstance(n, ast.NamedExpr):
                targets_of(n.target)
            elif isinstance(n, ast.For):
                targets_of(n.target)
            elif isinstance(n, ast.withitem) and n.optional_vars is not None:
                targets_of(n.optional_vars)
            elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                add(n.name)
    # generated helpers are scoped to the statement that consumes them and
    # must never count as user variables to thread through conversions
    return [n for n in out if not n.startswith("_pt_") and n != "_jst"]


def _reads(node):
    """All Name loads under `node`, INCLUDING nested scopes (a nested def
    reads its free variables when called — conservative is correct
    here)."""
    out = set()
    nodes = [node] if isinstance(node, ast.AST) else list(node)
    for root in nodes:
        for n in ast.walk(root):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                out.add(n.id)
            elif isinstance(n, ast.AugAssign) and \
                    isinstance(n.target, ast.Name):
                # `x += e` reads x even though the target is Store ctx
                out.add(n.target.id)
    return out


def _use_before_def(stmts, candidates, local_names=None):
    """Which of `candidates` are read before they are (re)assigned when
    executing `stmts` linearly — i.e. loop-carried names.  `if`
    statements are walked branch-by-branch (a name assigned before its
    read INSIDE a branch is not use-before-def; only names defined in
    BOTH branches count as definitely-defined afterwards); other
    compound statements are approximated coarsely: reads first, then
    stores."""
    carried = set()

    def run(stmts, defined):
        for stmt in stmts:
            if isinstance(stmt, ast.If):
                for name in _reads(stmt.test):
                    if name in candidates and name not in defined:
                        carried.add(name)
                d_t = run(stmt.body, set(defined))
                d_f = run(stmt.orelse, set(defined))
                defined = defined | (d_t & d_f)
            else:
                for name in _reads(stmt):
                    if name in candidates and name not in defined:
                        carried.add(name)
                defined = defined | set(_stores([stmt], local_names))
        return defined

    run(stmts, set())
    return carried


def _contains(node, kinds, stop=()):
    """Does `node` contain any statement of `kinds` in the same
    scope/binding region (not descending into `stop` node types)?"""
    if isinstance(node, _SCOPE_NODES) or (stop and isinstance(node, stop)):
        return False
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        if isinstance(n, kinds):
            return True
        if isinstance(n, _SCOPE_NODES) or isinstance(n, stop):
            continue
        stack.extend(ast.iter_child_nodes(n))
    return False


def _has_return(stmts):
    return any(_contains_self(s, ast.Return) for s in stmts)


def _contains_self(node, kinds):
    if isinstance(node, kinds):
        return True
    return _contains(node, kinds if isinstance(kinds, tuple) else (kinds,))


def _has_loop_jump(stmts, kinds=(ast.Break, ast.Continue)):
    """break/continue bound to an ENCLOSING loop (not one inside)."""
    for s in stmts:
        if isinstance(s, kinds):
            return True
        if isinstance(s, (ast.While, ast.For)):
            continue  # binds its own break/continue
        if isinstance(s, _SCOPE_NODES):
            continue
        if _contains(s, kinds, stop=(ast.While, ast.For)):
            return True
    return False


def _assign_const(name, value):
    return ast.Assign(targets=[_name(name, ast.Store())],
                      value=ast.Constant(value=value))


def _rewrite_loop_jumps(stmts, brk, cont):
    """Rewrite break/continue bound to THIS loop into guard-flag
    assignments (reference break_continue_transformer.py plays the same
    trick with fluid fill_constant flags):

        break     ->  <brk> = True      (rest of the body guarded off)
        continue  ->  <cont> = True     (rest of THIS iteration guarded)

    Statements after an `if` that may set a flag are wrapped in
    `if not (<brk> or <cont>): ...` — the injected ifs then ride the
    normal if-functionalization, so a flag set under a TRACED condition
    becomes a lax.cond output and everything downstream masks correctly.
    Returns the rewritten statements, or None when a jump sits inside a
    construct we don't restructure (with/try)."""
    flags = [brk] + ([cont] if cont else [])

    def guard(suffix):
        test = _name(flags[0])
        for n in flags[1:]:
            test = ast.BoolOp(op=ast.Or(), values=[test, _name(n)])
        return ast.If(test=ast.UnaryOp(op=ast.Not(), operand=test),
                      body=suffix, orelse=[])

    def rw(body):
        out = []
        for i, st in enumerate(body):
            if isinstance(st, ast.Break):
                out.append(_assign_const(brk, True))
                return out                      # rest is dead code
            if isinstance(st, ast.Continue):
                out.append(_assign_const(cont, True))
                return out
            if isinstance(st, (ast.While, ast.For)) or \
                    isinstance(st, _SCOPE_NODES):
                out.append(st)                  # binds its own jumps
                continue
            if _contains(st, (ast.Break, ast.Continue),
                         stop=(ast.While, ast.For)):
                if not isinstance(st, ast.If):
                    return None                 # jump inside with/try
                t_body = rw(st.body)
                f_body = rw(st.orelse) if st.orelse else []
                if t_body is None or f_body is None:
                    return None
                out.append(ast.If(test=st.test, body=t_body,
                                  orelse=f_body))
                suffix = rw(body[i + 1:])
                if suffix is None:
                    return None
                if suffix:
                    out.append(guard(suffix))
                return out
            out.append(st)
        return out

    return rw(stmts)


def _has_scope_escape(stmts):
    for s in stmts:
        if _contains_self(s, (ast.Global, ast.Nonlocal, ast.Delete)):
            return True
    return False


def _ends_in_return(stmts):
    """Every execution path through `stmts` ends in `return`?  (tail
    return, an if whose both branches end in return, or a with whose
    body does — the with-block travels WHOLE into a branch fn, so its
    context-manager semantics are untouched)."""
    if not stmts:
        return False
    last = stmts[-1]
    if isinstance(last, ast.Return):
        return True
    if isinstance(last, ast.If) and last.orelse:
        return _ends_in_return(last.body) and _ends_in_return(last.orelse)
    if isinstance(last, ast.With):
        return _ends_in_return(last.body)
    return False


_RET_UID = iter(range(1 << 30))

# REST duplication bound: each partial-return `if` copies its suffix
# onto both branches, so k NESTED partial returns grow the tail 2^k-
# fold.  Inner folds run first and see the already-grown suffix, so a
# per-site size check bounds the cumulative blowup; an over-limit fold
# is skipped (plain-Python fallback — concrete conditions still work,
# traced ones get the tracer error, exactly the pre-fold behavior).
_FOLD_REST_LIMIT = 4000


def _ast_size(stmts):
    return sum(1 for s in stmts for _ in ast.walk(s))


def _rw_loop_returns(body, flag, val):
    """Rewrite `return e` bound directly to this loop body (not inside a
    nested loop/scope, which binds or re-folds its own) into

        <flag> = True; <val> = e; break

    — the break then rides the existing guard-flag machinery
    (_rewrite_loop_jumps), and the caller re-raises the return AFTER the
    loop from the flag.  Returns None when a return hides inside a
    construct we don't restructure (with/try)."""
    out = []
    for i, st in enumerate(body):
        if isinstance(st, ast.Return):
            out.append(_assign_const(flag, True))
            out.append(ast.Assign(
                targets=[_name(val, ast.Store())],
                value=st.value or ast.Constant(value=None)))
            out.append(ast.Break())
            return out                          # rest is dead code
        if isinstance(st, (ast.While, ast.For)) or \
                isinstance(st, _SCOPE_NODES):
            out.append(st)
            continue
        if _contains(st, ast.Return, stop=(ast.While, ast.For)):
            if not isinstance(st, ast.If):
                return None                     # return inside with/try
            t = _rw_loop_returns(st.body, flag, val)
            f = _rw_loop_returns(st.orelse, flag, val) if st.orelse else []
            if t is None or f is None:
                return None
            out.append(ast.If(test=st.test, body=t, orelse=f))
            # no guard needed on the suffix: every rewritten return path
            # ends in `break`, which truncates natively (concrete) or via
            # the break-flag guards (traced)
            rest = _rw_loop_returns(body[i + 1:], flag, val)
            if rest is None:
                return None
            out.extend(rest)
            return out
        out.append(st)
    return out


def _fold_early_returns(stmts, is_func_tail):
    """Functionalize early returns (reference return_transformer.py, by
    restructuring instead of flag-threading where possible):

    * `if c: ...return` + REST  ->  `if c: ...return else: REST` — and
      when the `if` HAS an else, REST moves onto whichever branch falls
      through, so any partial-return if/else lowers to the
      both-branches-return lax.cond form.
    * `return` inside a loop  ->  flag + value carrier + `break`
      (_rw_loop_returns), with `if <flag>: return <val>` re-raised after
      the loop — the same guard-flag trick as break/continue; the
      injected if is then folded by the rule above.

    Only statement lists whose fall-through means "function returns
    None" may have an implicit `return None` appended.  Still excluded
    (left as plain Python): returns inside with/try and loop-`else`."""
    stmts = list(stmts)
    for i, st in enumerate(stmts):
        if isinstance(st, ast.Return):
            del stmts[i + 1:]                   # anything after is dead
            return stmts
        if isinstance(st, ast.If):
            rest = stmts[i + 1:]
            st.body[:] = _fold_early_returns(st.body,
                                             is_func_tail and not rest)
            st.orelse[:] = _fold_early_returns(st.orelse,
                                               is_func_tail and not rest)
            has_ret = _has_return(st.body) or _has_return(st.orelse)
            jumps = _has_loop_jump(st.body) or _has_loop_jump(st.orelse)
            if (has_ret and not jumps and (rest or is_func_tail)
                    and _ast_size(rest) <= _FOLD_REST_LIMIT):
                # distribute REST onto every fall-through path: each
                # branch re-folds with REST appended (a branch that
                # already returns strips it as dead code), so partial /
                # nested early returns reduce to the both-branches-return
                # lax.cond form.  REST is deep-copied for the second
                # placement — later visitors mutate nodes in place.
                st.body[:] = _fold_early_returns(
                    st.body + copy.deepcopy(rest), is_func_tail)
                if is_func_tail and not _ends_in_return(st.body):
                    st.body.append(ast.Return(value=ast.Constant(value=None)))
                st.orelse[:] = _fold_early_returns(
                    st.orelse + rest, is_func_tail)
                if is_func_tail and not _ends_in_return(st.orelse):
                    st.orelse.append(
                        ast.Return(value=ast.Constant(value=None)))
                del stmts[i + 1:]
                return stmts
        elif isinstance(st, (ast.While, ast.For)):
            st.body[:] = _fold_early_returns(st.body, False)
            if (_has_return(st.body) and not st.orelse
                    and not _has_scope_escape(st.body)):
                uid = next(_RET_UID)
                flag, val = f"_retf_{uid}", f"_retv_{uid}"
                new_body = _rw_loop_returns(st.body, flag, val)
                if new_body is not None:
                    st.body[:] = new_body
                    raise_if = ast.If(
                        test=_name(flag),
                        body=[ast.Return(value=_name(val))], orelse=[])
                    spliced = (stmts[:i]
                               + [_assign_const(flag, False),
                                  _assign_const(val, None), st, raise_if]
                               + stmts[i + 1:])
                    # reprocess: the loop body is now return-free and the
                    # injected raise_if folds like any early-return if
                    return _fold_early_returns(spliced, is_func_tail)
        elif isinstance(st, ast.With):
            st.body[:] = _fold_early_returns(st.body, False)
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            st.body[:] = _fold_early_returns(st.body, True)
    return stmts


def _walk_tail(stmts, after, out, after_out):
    """Backward liveness walk: record tail-read sets for every If
    (`out[id]` = names read after the if) and loop (`out[id]` = after
    the loop + the loop's own reads, for seeding its body;
    `after_out[id]` = strictly after, which decides the loop's OWN
    carried variables)."""
    acc = set(after)
    for st in reversed(stmts):
        if isinstance(st, (ast.While, ast.For)):
            after_out[id(st)] = set(acc)
            out[id(st)] = acc | _reads(st)
            _walk_tail(st.body, out[id(st)], out, after_out)
            _walk_tail(st.orelse, acc, out, after_out)
        elif isinstance(st, ast.If):
            out[id(st)] = set(acc)
            _walk_tail(st.body, acc, out, after_out)
            _walk_tail(st.orelse, acc, out, after_out)
        elif isinstance(st, ast.With):
            _walk_tail(st.body, acc, out, after_out)
        elif isinstance(st, ast.Try):
            # an exception can fire after ANY body statement, so a
            # name read only in a handler (or finally) is still live
            # throughout the body; the else clause runs right after
            # the body, so its reads are body-live too
            fin_reads = _reads(st.finalbody)
            h_reads = fin_reads.copy()
            for h in st.handlers:
                h_reads |= _reads(h.body)
            _walk_tail(st.body, acc | h_reads | _reads(st.orelse),
                       out, after_out)
            _walk_tail(st.orelse, acc | fin_reads, out, after_out)
            for h in st.handlers:
                _walk_tail(h.body, acc | fin_reads, out, after_out)
            _walk_tail(st.finalbody, acc, out, after_out)
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _walk_tail(st.body, acc, out, after_out)
        acc |= _reads(st)
    return acc


def _compute_tail_reads(fdef):
    """For every While/For node: the names read after the loop finishes,
    including re-reads by the next iteration of any ENCLOSING loop. For
    every If node: the names read after the `if` completes (used to drop
    branch-local dead variables from the lax.cond outputs — a loop
    counter living only inside one branch must not force both branches
    to agree on its tensor-ness)."""
    out = {}
    after_out = {}

    # a nested def/lambda/genexp's FREE-variable reads are live over the
    # WHOLE function: its call/consumption position is unknowable, so
    # seeding them into the initial tail set is the only safe placement.
    # Only free variables — seeding the nested scope's own params/locals
    # would pin same-named outer branch-locals and defeat the filter.
    nested = set()
    for n in ast.walk(fdef):
        if n is fdef:
            continue
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.GeneratorExp)):
            nested |= _free_reads(n)

    _walk_tail(fdef.body, nested, out, after_out)
    return out, after_out


def _free_reads(n):
    """Name loads under a nested scope MINUS the names that scope binds
    itself (params, its own simple-Name assignments, comprehension
    targets). Subtlety in both directions: `nonlocal`/`global` targets
    are NOT local bindings (assigning them mutates the outer scope, so
    their reads/writes stay free), and a subscript store like
    `out[0] = v` binds nothing — `out` there is a Name LOAD, which the
    shallow Store-only walk below naturally leaves in the free set."""
    if isinstance(n, ast.GeneratorExp):
        bound = set()
        for comp in n.generators:
            for t in ast.walk(comp.target):
                if isinstance(t, ast.Name):
                    bound.add(t.id)
        return _reads(n) - bound
    a = n.args
    bound = {p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)}
    if a.vararg:
        bound.add(a.vararg.arg)
    if a.kwarg:
        bound.add(a.kwarg.arg)
    if isinstance(n, ast.Lambda):
        return _reads(n.body) - bound
    own, escaped = set(), set()
    stack = list(n.body)
    while stack:
        node = stack.pop()
        if isinstance(node, _SCOPE_NODES):
            # a deeper scope binds its own names — but its NAME (def g /
            # class C) is bound HERE
            name = getattr(node, "name", None)
            if name:
                own.add(name)
            continue
        if isinstance(node, (ast.Global, ast.Nonlocal)):
            escaped.update(node.names)
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            own.add(node.id)
        stack.extend(ast.iter_child_nodes(node))
    bound |= own - escaped
    return _reads(n.body) - bound


# --------------------------------------------------------------------------
# AST building helpers
# --------------------------------------------------------------------------

def _name(id_, ctx=None):
    return ast.Name(id=id_, ctx=ctx or ast.Load())


def _jst(attr):
    return ast.Attribute(value=_name("_jst"), attr=attr, ctx=ast.Load())


def _call(func, args=(), kwargs=()):
    return ast.Call(func=func, args=list(args),
                    keywords=[ast.keyword(arg=k, value=v)
                              for k, v in kwargs])


def _const_tuple(names):
    return ast.Tuple(elts=[ast.Constant(value=n) for n in names],
                     ctx=ast.Load())


def _arg_thunk(name):
    """_jst.arg(lambda: name)"""
    lam = ast.Lambda(
        args=ast.arguments(posonlyargs=[], args=[], vararg=None,
                           kwonlyargs=[], kw_defaults=[], kwarg=None,
                           defaults=[]),
        body=_name(name))
    return _call(_jst("arg"), [lam])


def _make_fn(name, params, body):
    return ast.FunctionDef(
        name=name,
        args=ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=p, annotation=None) for p in params],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
            defaults=[]),
        body=body, decorator_list=[], returns=None)


def _ret_tuple(names):
    return ast.Return(value=ast.Tuple(elts=[_name(n) for n in names],
                                      ctx=ast.Load()))


def _assign_tuple(names, value):
    return ast.Assign(
        targets=[ast.Tuple(elts=[_name(n, ast.Store()) for n in names],
                           ctx=ast.Store())],
        value=value)


# --------------------------------------------------------------------------
# the transformer
# --------------------------------------------------------------------------

class _CtrlFlowTransformer(ast.NodeTransformer):
    def __init__(self, tail_reads, self_name=None, has_class_cell=False,
                 local_names=None, after_reads=None):
        self._tail_reads = tail_reads
        self._after_reads = after_reads or {}
        self._self_name = self_name
        self._has_class_cell = has_class_cell
        self._locals = local_names
        self._n = 0

    def _uid(self):
        self._n += 1
        return self._n

    # -- calls -------------------------------------------------------------

    _SKIP_CALL_NAMES = frozenset({
        "range", "len", "super", "isinstance", "issubclass",
        "getattr", "setattr", "hasattr", "type", "locals", "globals",
        "vars", "id", "repr",
    })  # print is handled by its own convert_print rewrite

    def visit_Call(self, node):
        """Two rewrites.  (1) `super()` relies on the compiler-injected
        __class__ cell, which a recompiled def outside its class body
        doesn't get: make the arguments explicit (`super(__class__,
        self)`).  (2) every other call goes through _jst.convert_call so
        user-defined helpers get their own control-flow conversion
        (reference convert_call_func.py); library callables pass through
        untouched at runtime."""
        self.generic_visit(node)
        func = node.func
        if isinstance(func, ast.Name) and func.id == "super" \
                and not node.args and not node.keywords:
            if self._has_class_cell and self._self_name:
                node.args = [_name("__class__"), _name(self._self_name)]
            return node
        if isinstance(func, ast.Name) and func.id == "print":
            node.func = _jst("convert_print")
            return node
        if isinstance(func, ast.Name) and func.id in self._SKIP_CALL_NAMES:
            return node
        if isinstance(func, ast.Attribute) and \
                isinstance(func.value, ast.Name) and func.value.id == "_jst":
            return node
        node.func = _call(_jst("convert_call"), [func])
        return node

    # -- boolean operators -------------------------------------------------
    def visit_BoolOp(self, node):
        self.generic_visit(node)
        conv = "convert_and" if isinstance(node.op, ast.And) else "convert_or"
        thunks = [ast.Lambda(
            args=ast.arguments(posonlyargs=[], args=[], vararg=None,
                               kwonlyargs=[], kw_defaults=[], kwarg=None,
                               defaults=[]),
            body=v) for v in node.values]
        return _call(_jst(conv), thunks)

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return _call(_jst("convert_not"), [node.operand])
        return node

    # -- if ----------------------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        body_ret = _has_return(node.body)
        orelse_ret = _has_return(node.orelse)
        if body_ret or orelse_ret:
            # only the both-tails-return shape is functionalized; other
            # early-return shapes stay Python (fine for concrete preds)
            if (node.orelse and _ends_in_return(node.body)
                    and _ends_in_return(node.orelse)
                    and not _has_loop_jump(node.body)
                    and not _has_loop_jump(node.orelse)
                    and not _has_scope_escape(node.body + node.orelse)):
                uid = self._uid()
                tname, fname = f"_pt_ret_true_{uid}", f"_pt_ret_false_{uid}"
                # locals a branch reads before (re)assigning must come in
                # as PARAMETERS: assigning a name anywhere in the branch
                # fn makes it fn-local, so the closure read that zero-arg
                # fns relied on would raise UnboundLocalError (e.g. a
                # folded `x = x * 2; return x - 7` branch)
                params = sorted(
                    _use_before_def(node.body, self._locals, self._locals)
                    | _use_before_def(node.orelse, self._locals,
                                      self._locals))
                t_fn = _make_fn(tname, params, node.body)
                f_fn = _make_fn(fname, params, node.orelse)
                ret = ast.Return(value=_call(
                    _jst("convert_ifelse_ret"),
                    [node.test, _name(tname), _name(fname),
                     ast.Tuple(elts=[_arg_thunk(n) for n in params],
                               ctx=ast.Load())]))
                return [t_fn, f_fn, ret]
            return node
        if (_has_loop_jump(node.body) or _has_loop_jump(node.orelse)
                or _has_scope_escape(node.body + node.orelse)):
            return node
        mod = _stores(node.body + node.orelse, self._locals)
        tail = self._tail_reads.get(id(node))
        if tail is not None:
            # a name DEAD after the if (never read again — tail is
            # conservative about enclosing-loop back-edges, handler reads
            # and nested-def free variables) need not be a cond output:
            # dropping it lets a branch-local helper (e.g. a while
            # counter in one branch) exist without the other branch
            # having to match its tensor-ness. A name a branch reads
            # BEFORE (re)assigning must stay: `mod` doubles as the
            # helper's parameter list, and dropping it would leave an
            # unbound local inside the generated branch fn.
            carried = (_use_before_def(node.body, set(mod), self._locals)
                       | _use_before_def(node.orelse, set(mod),
                                         self._locals))
            mod = [n for n in mod if n in tail or n in carried]
        if not mod:
            return node   # side-effect-only if: nothing to functionalize
        uid = self._uid()
        tname, fname = f"_pt_true_{uid}", f"_pt_false_{uid}"
        t_fn = _make_fn(tname, mod, node.body + [_ret_tuple(mod)])
        f_fn = _make_fn(fname, mod,
                        (node.orelse or [ast.Pass()]) + [_ret_tuple(mod)])
        call = _call(_jst("convert_ifelse"),
                     [node.test, _name(tname), _name(fname),
                      ast.Tuple(elts=[_arg_thunk(n) for n in mod],
                                ctx=ast.Load()),
                      _const_tuple(mod)])
        return [t_fn, f_fn, _assign_tuple(mod, call)]

    def _rewrite_jumps(self, node):
        """break/continue -> guard flags (see _rewrite_loop_jumps).
        Mutates node.body on success and registers fresh tail-read
        entries for the injected/cloned guard ifs — without them the
        dead-variable filter is skipped and every iteration-local temp
        would be forced into the loop carry with no pre-loop value.
        Returns ([brk-init statement], brk_name) or ([], None)."""
        if (node.orelse or not _has_loop_jump(node.body)
                or _has_return(node.body)
                or _has_scope_escape(node.body)):
            return [], None
        uid = self._uid()
        brk = f"_brk_{uid}"
        cont = (f"_cont_{uid}"
                if _has_loop_jump(node.body, (ast.Continue,)) else None)
        new_body = _rewrite_loop_jumps(node.body, brk, cont)
        if new_body is None:
            return [], None
        if cont:
            new_body = [_assign_const(cont, False)] + new_body
        node.body = new_body
        # seed = after-loop reads + names the NEXT iteration reads before
        # defining (the genuinely carried set) + the flag (read by the
        # loop test / wrap guard next iteration). Seeding with ALL body
        # reads would pin defined-before-read iteration temps into every
        # guard-if's cond outputs, forcing them into the carry with no
        # pre-loop value.
        seed = (self._after_reads.get(id(node), set())
                | _use_before_def(node.body, _reads(node), self._locals)
                | {brk})
        _walk_tail(node.body, seed, self._tail_reads, self._after_reads)
        return [_assign_const(brk, False)], brk

    # -- while -------------------------------------------------------------
    def visit_While(self, node):
        tail = self._after_reads.get(id(node), set())
        prelude, brk = self._rewrite_jumps(node)
        if brk:
            # `not brk` FIRST: python's break never re-evaluates the
            # loop test after firing (it may be side-effecting or rely
            # on state the final iteration invalidated, e.g. seq[i])
            node.test = ast.BoolOp(
                op=ast.And(),
                values=[ast.UnaryOp(op=ast.Not(), operand=_name(brk)),
                        node.test])
        self.generic_visit(node)
        if (node.orelse or _has_loop_jump(node.body)
                or _has_return(node.body)
                or _has_scope_escape(node.body)):
            return prelude + [node]
        stored = _stores(node.body, self._locals)
        if not stored:
            return prelude + [node]
        carried = _use_before_def(node.body, set(stored), self._locals)
        test_reads = _reads(node.test)
        loop_vars = [n for n in stored
                     if n in carried or n in test_reads or n in tail]
        if not loop_vars:
            return prelude + [node]
        uid = self._uid()
        cname, bname = f"_pt_while_cond_{uid}", f"_pt_while_body_{uid}"
        c_fn = _make_fn(cname, loop_vars, [ast.Return(value=node.test)])
        b_fn = _make_fn(bname, loop_vars, node.body + [_ret_tuple(loop_vars)])
        call = _call(_jst("convert_while_loop"),
                     [_name(cname), _name(bname),
                      ast.Tuple(elts=[_arg_thunk(n) for n in loop_vars],
                                ctx=ast.Load()),
                      _const_tuple(loop_vars)])
        return prelude + [c_fn, b_fn, _assign_tuple(loop_vars, call)]

    # -- for ---------------------------------------------------------------
    def visit_For(self, node):
        tail = self._after_reads.get(id(node), set())
        # target shape gates BOTH the conversion and the jump rewrite (a
        # rewritten body with a dropped prelude would read an unbound
        # flag)
        if isinstance(node.target, ast.Name):
            tnames = [node.target.id]
        elif isinstance(node.target, ast.Tuple) and all(
                isinstance(e, ast.Name) for e in node.target.elts):
            tnames = [e.id for e in node.target.elts]
        else:
            tnames = None
        # python LEAKS the loop target: code after the loop (including an
        # enclosing same-name loop's tests — the nested-shadow shape)
        # reads whatever `j` holds at loop exit, INCLUDING rebinds by
        # inner same-name loops mid-iteration. When leaked, the target
        # becomes an ORDINARY carried variable: the body fn receives the
        # iterated value under a fresh `_ptt_` parameter and re-binds the
        # real name at iteration start (inside the brk guard, so a traced
        # break freezes it), letting the standard loop_vars machinery
        # thread every later rebind out.
        leak_uid = self._uid()
        leaked = [t for t in (tnames or []) if t in tail]
        fresh = {t: f"_ptt_{leak_uid}_{t}" for t in leaked}
        renames = [ast.Assign(targets=[_name(t, ast.Store())],
                              value=_name(fresh[t])) for t in leaked]
        prelude, brk = ([], None) if tnames is None \
            else self._rewrite_jumps(node)
        if brk:
            # a scan can't exit early: once <brk> is set, every remaining
            # iteration's whole body is guarded off (the concrete path
            # early-stops inside convert_for via the brk kwarg)
            wrap = ast.If(
                test=ast.UnaryOp(op=ast.Not(), operand=_name(brk)),
                body=renames + node.body, orelse=[])
            renames = []                        # consumed by the wrap
            self._tail_reads[id(wrap)] = (
                self._after_reads.get(id(node), set())
                | _use_before_def(node.body, _reads(node), self._locals)
                | {brk})
            node.body = [wrap]
        self.generic_visit(node)
        if (tnames is None or node.orelse or _has_loop_jump(node.body)
                or _has_return(node.body)
                or _has_scope_escape(node.body)):
            return prelude + [node]
        stored = [n for n in _stores(node.body, self._locals)
                  if n not in set(tnames) - set(leaked)]
        carried = _use_before_def(node.body, set(stored), self._locals)
        loop_vars = [n for n in stored if n in carried or n in tail]
        for t in leaked:
            if t not in loop_vars:
                loop_vars.append(t)
        if not loop_vars:
            return prelude + [node]
        uid = self._uid()
        it = node.iter
        if (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
                and it.func.id == "range"):
            it = _call(_jst("convert_range"), it.args)
        bname = f"_pt_for_body_{uid}"
        params = [fresh.get(t, t) for t in tnames] + loop_vars
        b_fn = _make_fn(bname, params,
                        renames + node.body + [_ret_tuple(loop_vars)])
        kwargs = [("target_arity", ast.Constant(value=len(tnames)))]
        if brk:
            kwargs.append(("brk", ast.Constant(value=brk)))
        # leaked-target SLOTS are named with the _ptlk_ prefix so the
        # traced-scan path may seed an unbound leak with a zeros
        # placeholder (_init_ret_carries) — the body overwrites it every
        # iteration, so it is unobservable for any >=1-trip scan, where
        # rejecting it would break `for k in tensor: ...; use(k)`
        slot_names = [f"_ptlk_{uid}_{n}" if n in leaked else n
                      for n in loop_vars]
        call = _call(_jst("convert_for"),
                     [it, _name(bname),
                      ast.Tuple(elts=[_arg_thunk(n) for n in loop_vars],
                                ctx=ast.Load()),
                      _const_tuple(slot_names)],
                     kwargs=kwargs)
        return prelude + [b_fn, _assign_tuple(loop_vars, call)]


# --------------------------------------------------------------------------
# entry point
# --------------------------------------------------------------------------

def convert_to_static(fn, verbose=False):
    """Rewrite `fn`'s tensor-dependent control flow onto lax primitives.
    Falls back to `fn` unchanged when the source is unavailable or the
    transform fails (trace-only to_static still works for straight-line
    code).

    The transformed TEMPLATE is cached per code object, but each distinct
    function (closure) gets its own converted function bound to its OWN
    closure cells — factory-made functions stay independent and see later
    cell mutations."""
    key = getattr(fn, "__code__", None)
    if key is None:
        return fn
    import inspect as _inspect
    if key.co_flags & (_inspect.CO_GENERATOR | _inspect.CO_COROUTINE
                       | _inspect.CO_ASYNC_GENERATOR):
        # functionalizing a body that yields would change generator
        # semantics (yields move into branch helpers): never convert
        return fn
    if key.co_filename.startswith("<dy2static"):
        return fn           # already-generated code
    try:
        hit = _CONVERTED.get(fn)
    except TypeError:       # unhashable callable
        hit = None
    if hit is None and not fn.__closure__:
        hit = fn.__globals__.get(_BY_CODE_KEY, {}).get(key)
    if hit is not None:
        return hit
    if key in _FAILED:
        return fn
    try:
        new_fn = _convert(fn)
    except Exception as e:  # pragma: no cover - diagnostics path
        _FAILED[key] = f"{type(e).__name__}: {e}"
        if verbose:
            import traceback
            traceback.print_exc()
        return fn
    try:
        _CONVERTED[fn] = new_fn
    except TypeError:
        pass
    if not fn.__closure__:
        # per-code cache so per-call function objects (nested defs) don't
        # reconvert every invocation; stored IN the globals dict so the
        # cache's lifetime is the module's (an id(globals) key could be
        # served stale after id reuse)
        fn.__globals__.setdefault(_BY_CODE_KEY, {})[key] = new_fn
    return new_fn


def conversion_error(fn):
    """Why convert_to_static fell back for this function (or None)."""
    return _FAILED.get(getattr(fn, "__code__", None))


_TO_STATIC_DECOS = ("to_static", "not_to_static")


def _build_template(fn):
    src = textwrap.dedent(inspect.getsource(fn))
    tree = ast.parse(src)
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        raise TypeError(f"not a function def: {type(fdef).__name__}")
    for n in ast.walk(fdef):
        if isinstance(n, ast.Global):
            # a converted fn executes with _LiveGlobals: `global` writes
            # would land there instead of the user's module
            raise TypeError("uses `global` writes; left unconverted")
    # strip the decorator that triggered conversion, plus binding
    # decorators (static/classmethod: the descriptor behavior lives on
    # the class attribute — convert_call always receives the plain
    # function); semantic decorators (@no_grad(), ...) keep wrapping
    kept = []
    for d in fdef.decorator_list:
        text = ast.unparse(d)
        if text in ("staticmethod", "classmethod"):
            continue
        if not any(text == t or text.endswith("." + t)
                   or text.startswith(t + "(") or ("." + t + "(") in text
                   for t in _TO_STATIC_DECOS):
            kept.append(d)
    fdef.decorator_list = kept
    fdef.body[:] = _fold_early_returns(fdef.body, True)
    tail_reads, after_reads = _compute_tail_reads(fdef)
    self_name = fdef.args.args[0].arg if fdef.args.args else None
    has_class_cell = "__class__" in fn.__code__.co_freevars
    a = fdef.args
    params = [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]
    if a.vararg:
        params.append(a.vararg.arg)
    if a.kwarg:
        params.append(a.kwarg.arg)
    # function locals = params + plain Name stores (subscript bases
    # excluded here: a name is local only if actually BOUND in scope)
    local_names = frozenset(params) | frozenset(
        _stores(fdef.body, frozenset()))
    _CtrlFlowTransformer(tail_reads, self_name, has_class_cell,
                         local_names, after_reads).visit(fdef)

    freevars = fn.__code__.co_freevars
    if freevars:
        factory = _make_fn("__dy2st_factory", list(freevars),
                           [fdef, ast.Return(value=_name(fdef.name))])
        module = ast.Module(body=[factory], type_ignores=[])
    else:
        module = ast.Module(body=[fdef], type_ignores=[])
    ast.fix_missing_locations(module)

    filename = f"<dy2static {fn.__module__}.{fn.__qualname__}>"
    code = compile(module, filename, "exec")
    # make the generated source inspectable in tracebacks
    try:
        gen_src = ast.unparse(module)
        linecache.cache[filename] = (len(gen_src), None,
                                     [l + "\n" for l in gen_src.split("\n")],
                                     filename)
    except Exception:
        pass
    return code, fdef.name, bool(kept)


def _convert(fn):
    key = fn.__code__
    if key not in _TEMPLATES:
        _TEMPLATES[key] = _build_template(fn)
    code, name, has_decorators = _TEMPLATES[key]
    glb = _LiveGlobals(fn.__globals__, {"_jst": _jst_mod})
    exec(code, glb)
    freevars = fn.__code__.co_freevars
    if freevars:
        # build once with placeholder cells, then rebind the ORIGINAL
        # cells so the converted function shares this closure's live state
        inner = glb["__dy2st_factory"](*([None] * len(freevars)))
        cellmap = dict(zip(freevars, fn.__closure__))
        if (has_decorators
                or any(n not in cellmap
                       for n in inner.__code__.co_freevars)):
            # a kept decorator wraps the inner fn (its code isn't ours to
            # rebind): fall back to snapshotting the cell contents
            new_fn = glb["__dy2st_factory"](
                *[c.cell_contents for c in fn.__closure__])
        else:
            new_fn = types.FunctionType(
                inner.__code__, glb, fn.__name__, fn.__defaults__,
                tuple(cellmap[n] for n in inner.__code__.co_freevars))
    else:
        new_fn = glb[name]
    try:
        new_fn.__defaults__ = fn.__defaults__
        new_fn.__kwdefaults__ = fn.__kwdefaults__
    except (AttributeError, TypeError):
        pass   # decorated wrapper without writable defaults
    functools.update_wrapper(new_fn, fn, updated=())
    new_fn.__dy2static__ = True
    return new_fn
