"""dy2static: dynamic-graph Python → static (traceable) conversion.

Reference python/paddle/fluid/dygraph/dygraph_to_static/: an AST rewrite
(transformer.py) routes `if`/`while`/`for`/`and`/`or`/`not` through
dual-path runtime converters (convert_ops.py) that keep Python semantics
for concrete values and lower to lax.cond / lax.while_loop / lax.scan for
traced ones — so `jit.to_static` compiles models with data-dependent
control flow instead of failing in the tracer.
"""
from .convert_ops import (
    UNDEF,
    convert_and,
    convert_call,
    convert_for,
    convert_ifelse,
    convert_ifelse_ret,
    convert_len,
    convert_not,
    convert_or,
    convert_print,
    convert_range,
    convert_while_loop,
    to_bool,
)
from .transformer import conversion_error, convert_to_static

__all__ = [
    "convert_to_static", "conversion_error", "convert_ifelse",
    "convert_ifelse_ret", "convert_while_loop", "convert_for",
    "convert_and", "convert_or", "convert_not", "convert_range",
    "convert_len", "convert_call", "convert_print", "to_bool", "UNDEF",
]
