"""Runtime converters for dy2static control flow.

Reference: python/paddle/fluid/dygraph/dygraph_to_static/convert_operators.py
(convert_ifelse / convert_while_loop / convert_logical_*), which lower
Python control flow to fluid cond/while ops.  TPU-native: the same
dual-path converters dispatch on the *runtime* type of the condition — a
concrete Python/array value keeps exact Python semantics (short-circuit,
early exit, unrolling), while a traced value lowers to `lax.cond` /
`lax.while_loop` / `lax.scan`, which is what XLA needs for data-dependent
control flow inside one compiled program.

These are the call targets the AST transformer (transformer.py) rewrites
`if` / `while` / `for` / `and` / `or` / `not` into; user code never calls
them directly.  Tensor is a registered pytree, so loop carries and branch
outputs flow through lax primitives with their wrappers intact.
"""
import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor

__all__ = [
    "UNDEF", "arg", "convert_ifelse", "convert_ifelse_ret",
    "convert_while_loop", "convert_for", "convert_and", "convert_or",
    "convert_not", "convert_range", "convert_len", "convert_call",
    "to_bool",
]

# modules whose functions are never AST-converted when called from
# converted code (library code is already trace-compatible; reference
# convert_call_func.py BUILTIN/paddle skip list)
_NO_CONVERT_PREFIXES = (
    "jax", "numpy", "paddle_tpu", "builtins", "math", "functools",
    "itertools", "operator", "collections", "typing", "np", "torch",
)


def convert_call(fn):
    """Recursive conversion entry (reference
    dygraph_to_static/convert_call_func.py): a user-defined function
    called from converted code gets its own control-flow conversion;
    library/builtin callables pass through untouched.  Conversion is
    cached per function; failures fall back to the original callable."""
    from .transformer import convert_to_static

    target = fn
    bound_self = None
    if isinstance(fn, staticmethod):
        target = fn.__func__
    elif hasattr(fn, "__func__") and hasattr(fn, "__self__"):
        bound_self = fn.__self__                # bound method
        target = fn.__func__
    if not isinstance(target, type(convert_call)):
        return fn                               # class, Layer instance, ...
    if getattr(target, "__dy2static__", False):
        return fn
    mod = getattr(target, "__module__", "") or ""
    if mod.split(".")[0] in _NO_CONVERT_PREFIXES:
        return fn
    conv = convert_to_static(target)
    if conv is target:
        return fn
    if bound_self is not None:
        return conv.__get__(bound_self)
    return conv


class _Undefined:
    """Placeholder for a name with no binding at the conversion point (a
    variable first assigned inside the converted block) — reference
    variable_trans_func.create_undefined_variable.  Loud on accidental
    use."""
    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "<undefined>"

    def __bool__(self):
        raise NameError(
            "dy2static: variable used before assignment (it is only set on "
            "one path of converted control flow)")


UNDEF = _Undefined()


def arg(thunk):
    """Evaluate `lambda: name` from generated code; unbound names become
    UNDEF instead of raising, so variables first assigned inside the block
    can still be threaded through the functionalized call."""
    try:
        return thunk()
    except NameError:       # includes UnboundLocalError and free-var errors
        return UNDEF


def _raw(v):
    return v._value if isinstance(v, Tensor) else v


def _is_traced(v):
    return isinstance(_raw(v), jax.core.Tracer)


def to_bool(pred, ctx="condition"):
    """Truthiness for the dual path: a Python bool when the value is
    concrete, a scalar bool tracer when traced."""
    p = _raw(pred)
    if isinstance(p, jax.core.Tracer):
        if getattr(p, "size", 1) != 1:
            raise ValueError(
                f"dy2static: {ctx} is an array of {p.size} elements; a "
                "branch/loop condition must be a single boolean (reduce "
                "with .any()/.all() first)")
        return jnp.reshape(p, ()).astype(bool)
    if isinstance(p, (jax.Array, np.ndarray)):
        if p.size != 1:
            raise ValueError(
                f"dy2static: {ctx} is an array of {p.size} elements; a "
                "branch/loop condition must be a single boolean (reduce "
                "with .any()/.all() first)")
        return bool(p.reshape(())) if isinstance(p, np.ndarray) else bool(p)
    return bool(p)


_DYN_LEAVES = (jax.Array, jax.core.Tracer, np.ndarray,
               bool, int, float, complex, np.generic)


def _is_dyn(v):
    """Can this value ride through a lax primitive as an operand?
    Scalars/arrays/Tensors directly; containers (list/tuple/dict) ride
    as pytrees when EVERY leaf is dynamic — Tensor is a registered
    pytree node, so lax.cond/while_loop flatten and rebuild them (both
    branches / every iteration must keep the same structure, enforced
    by the structure checks downstream)."""
    if v is UNDEF:
        return False
    if isinstance(v, (Tensor,) + _DYN_LEAVES):
        return True
    if isinstance(v, (list, tuple, dict)):
        leaves = jax.tree_util.tree_leaves(v)
        # at least one leaf must be an actual device/traced array: a
        # container of plain Python scalars (`shape = [2, 3]`) must stay
        # STATIC, or shape-like lists assigned in both branches would
        # come back as tracers and break paddle.zeros(shape)/reshape
        return (bool(leaves)
                and all(isinstance(l, _DYN_LEAVES) for l in leaves)
                and any(isinstance(l, (jax.Array, jax.core.Tracer))
                        for l in leaves))
    return False


def _split(vals):
    mask = tuple(_is_dyn(v) for v in vals)
    dyn = [v for v, m in zip(vals, mask) if m]
    stat = [v for v, m in zip(vals, mask) if not m]
    return dyn, stat, mask


def _merge(dyn, stat, mask):
    out, i, j = [], 0, 0
    for m in mask:
        if m:
            out.append(dyn[i])
            i += 1
        else:
            out.append(stat[j])
            j += 1
    return tuple(out)


def _check_same_static(name, a, b):
    name = _public_name(name)
    same = a is b
    if not same:
        try:
            same = bool(a == b)
        except Exception:
            same = False
    if not same:
        hint = ""
        if isinstance(a, list) or isinstance(b, list):
            hint = (" — to COLLECT results in a tensor-dependent loop, "
                    "preallocate a tensor and write into it "
                    "(out = paddle.zeros([n, ...]); out[i] = ...), which "
                    "lowers to a scan with stacked outputs; a growing "
                    "Python list has no static shape for XLA")
        raise TypeError(
            f"dy2static: non-tensor variable {name!r} takes different "
            f"values on the branches of tensor-dependent control flow "
            f"({a!r} vs {b!r}); only tensor/numeric values can depend on "
            f"a traced condition{hint}")


def _public_name(n):
    """Transformer-synthesized names, translated for diagnostics — the
    user never wrote `_retv_0`."""
    if isinstance(n, str):
        if n.startswith("_retv_"):
            return "return value"
        if n.startswith("_ptlk_"):
            return "loop variable " + n.split("_", 3)[-1]
        if n.startswith("_retf_"):
            return "return flag"
        if n.startswith("_brk_"):
            return "loop break flag"
        if n.startswith("_cont_"):
            return "loop continue flag"
    return n


def _public_names(names):
    return [_public_name(n) for n in names]


def _dyn_names(names, mask, dyn_vals=None):
    """Names of the dynamic operands, expanded per pytree LEAF when
    `dyn_vals` is given: error paths (_check_branch_match,
    _stable_dtypes) index by flattened-leaf position, and a container
    operand contributes several leaves — without expansion they would
    blame the wrong variable."""
    out, it = [], iter(dyn_vals if dyn_vals is not None else ())
    for n, m in zip(names, mask):
        n = _public_name(n)
        if not m:
            continue
        if dyn_vals is None:
            out.append(n)
            continue
        v = next(it, None)
        k = len(jax.tree_util.tree_leaves(v))
        if k <= 1:
            out.append(n)
        else:
            out.extend(f"{n} (leaf {j})" for j in range(k))
    return out or _public_names(names)


# --------------------------------------------------------------------------
# if / else
# --------------------------------------------------------------------------

def _placeholder_like(aval_tree):
    return jax.tree_util.tree_map(
        lambda a: jnp.zeros(a.shape, a.dtype), aval_tree)


def _fix_ret_placeholders(true_fn, false_fn, t_out, f_out, stash, names):
    """Synthetic `_retv_*` early-return carriers (transformer-generated,
    read only under their matching `_retf_*` flag) are legitimately
    None/UNDEF on the branch that doesn't return: substitute an
    unobservable zeros placeholder shaped like the returning branch's
    value so lax.cond sees matching pytrees.  Returns wrapped
    (true_fn, false_fn) or None when the mismatch involves any real
    user variable (caller raises its usual diagnostic)."""
    t_full = _merge(list(t_out), *stash["t"])
    f_full = _merge(list(f_out), *stash["f"])
    avals = {}
    for pos, nm in enumerate(names):
        if stash["t"][1][pos] == stash["f"][1][pos]:
            continue
        if not nm.startswith(("_retv_", "_ptlk_")):
            return None
        static_v = f_full[pos] if stash["t"][1][pos] else t_full[pos]
        if static_v is not None and static_v is not UNDEF:
            return None
        avals[pos] = t_full[pos] if stash["t"][1][pos] else f_full[pos]
    if not avals:
        return None

    def fix(fn):
        def wrapped(*ops):
            outs = list(fn(*ops))
            for pos, aval in avals.items():
                if outs[pos] is None or outs[pos] is UNDEF:
                    outs[pos] = _placeholder_like(aval)
            return tuple(outs)
        return wrapped
    return fix(true_fn), fix(false_fn)


def convert_ifelse(pred, true_fn, false_fn, operands, names=()):
    """`if`-statement converter.  `operands` holds the current values of
    every name either branch assigns; both fns take and return that full
    tuple (the transformer generates them that way)."""
    p = to_bool(pred, "`if` condition")
    if not isinstance(p, jax.core.Tracer):
        return (true_fn if p else false_fn)(*operands)

    dyn, stat, mask = _split(operands)
    for attempt in (0, 1):
        stash = {}

        def run(fn, tag):
            def inner(dyn_in):
                outs = fn(*_merge(list(dyn_in), stat, mask))
                nd, ns, nm = _split(outs)
                stash[tag] = (ns, nm)
                return tuple(nd)
            return inner

        # pre-check with eval_shape for readable errors (lax.cond's
        # structure errors don't mention the user's variable names)
        dyn_in = tuple(dyn)
        try:
            t_out = jax.eval_shape(run(true_fn, "t"), dyn_in)
            f_out = jax.eval_shape(run(false_fn, "f"), dyn_in)
        except TypeError as e:
            raise TypeError(
                f"dy2static: a branch of a tensor-dependent `if` assigning "
                f"{_public_names(names)} produced a non-traceable value: {e}") from None
        if stash["t"][1] != stash["f"][1]:
            fixed = (_fix_ret_placeholders(true_fn, false_fn, t_out, f_out,
                                           stash, names)
                     if attempt == 0 else None)
            if fixed is None:
                raise TypeError(
                    f"dy2static: the branches of a tensor-dependent `if` "
                    f"disagree on which of {_public_names(names)} are tensors; a "
                    "variable set in only one branch must already have a "
                    "tensor value before the `if`")
            true_fn, false_fn = fixed
            continue
        break
    _check_branch_match(t_out, f_out,
                        _dyn_names(names, stash["t"][1], list(t_out)))
    for n, a, b in zip([nm for nm, m in zip(names, stash["t"][1]) if not m],
                       stash["t"][0], stash["f"][0]):
        _check_same_static(n, a, b)

    outs = jax.lax.cond(p, run(true_fn, "t"), run(false_fn, "f"), dyn_in)
    ns, nm = stash["t"]
    return _merge(list(outs), ns, nm)


def _check_branch_match(t_out, f_out, names):
    t_flat, t_tree = jax.tree_util.tree_flatten(t_out)
    f_flat, f_tree = jax.tree_util.tree_flatten(f_out)
    if t_tree != f_tree or len(t_flat) != len(f_flat):
        raise TypeError(
            f"dy2static: the branches of a tensor-dependent `if` produce "
            f"different structures for {_public_names(names)} ({t_tree} vs {f_tree})")
    for i, (a, b) in enumerate(zip(t_flat, f_flat)):
        nm = names[i] if i < len(names) else f"value {i}"
        if tuple(a.shape) != tuple(b.shape) or a.dtype != b.dtype:
            raise TypeError(
                f"dy2static: {nm!r} is {tuple(a.shape)}/{a.dtype} on the "
                f"true branch but {tuple(b.shape)}/{b.dtype} on the false "
                "branch; both sides of a tensor-dependent `if` must "
                "produce matching tensors")


def convert_ifelse_ret(pred, true_fn, false_fn, operands=()):
    """Both-branches-return form: the converted statement returns the
    chosen branch's return value directly.  `operands` are the locals a
    branch reads before (re)assigning (UNDEF thunks for names unbound at
    the call site — using one inside the taken branch raises loudly,
    matching plain Python's UnboundLocalError timing for the not-taken
    branch's names)."""
    p = to_bool(pred, "`if` condition")
    if not isinstance(p, jax.core.Tracer):
        return (true_fn if p else false_fn)(*operands)
    t_out = jax.eval_shape(lambda: true_fn(*operands))
    f_out = jax.eval_shape(lambda: false_fn(*operands))
    _check_branch_match(t_out, f_out, ("return value",))
    return jax.lax.cond(p, lambda _: true_fn(*operands),
                        lambda _: false_fn(*operands), 0)


# --------------------------------------------------------------------------
# while / for
# --------------------------------------------------------------------------

def _stable_dtypes(body_flat, init_flat, names):
    """Fixed-point the carry dtypes (e.g. `x = x / 2` promotes an int
    carry to float): promote the initial carry until one body application
    is dtype-stable, with shape changes reported by name."""
    dtypes = [jnp.result_type(x) for x in init_flat]
    shapes = [jnp.shape(x) for x in init_flat]
    for _ in range(4):
        avals = tuple(jax.ShapeDtypeStruct(s, d)
                      for s, d in zip(shapes, dtypes))
        out = jax.eval_shape(body_flat, avals)
        for i, o in enumerate(out):
            if tuple(o.shape) != tuple(shapes[i]):
                nm = names[i] if i < len(names) else f"carry {i}"
                raise TypeError(
                    f"dy2static: loop variable {nm!r} changes shape "
                    f"{tuple(shapes[i])} -> {tuple(o.shape)} across "
                    "iterations; tensor loops need shape-stable carries "
                    "(pad or restructure the loop)")
        new = [jnp.promote_types(d, o.dtype) for d, o in zip(dtypes, out)]
        if new == dtypes:
            return dtypes
        dtypes = new
    return dtypes


def _check_no_undef(names, operands, kind):
    for n, v in zip(names, operands):
        if v is UNDEF:
            raise TypeError(
                f"dy2static: loop variable {n!r} is carried by a "
                f"tensor-dependent `{kind}` loop but has no value before "
                "it; initialize it before the loop")


def convert_while_loop(cond_fn, body_fn, operands, names=()):
    """`while` converter: operands are every name the loop carries (read
    by the condition, loop-carried in the body, or read after the loop)."""
    test = to_bool(cond_fn(*operands), "`while` condition")
    if not isinstance(test, jax.core.Tracer):
        vals = operands
        while test:
            vals = body_fn(*vals)
            test = to_bool(cond_fn(*vals), "`while` condition")
            if isinstance(test, jax.core.Tracer):
                # the condition became traced mid-flight (first iteration
                # produced a tracer): continue on the traced path
                return _traced_while(cond_fn, body_fn, vals, names)
        return vals
    return _traced_while(cond_fn, body_fn, operands, names)


def _init_ret_carries(run_body, operands, names):
    """A `_retv_*` early-return carrier entering a traced loop with no
    prior value (None/UNDEF init from the return rewrite) gets a zeros
    placeholder shaped like the value the body assigns it — reads are
    guarded by the matching `_retf_*` flag, so the placeholder is
    unobservable.  `run_body(operands)` applies one loop body (the
    while/for callers bind their iteration argument).  Real user
    variables are left alone for _check_no_undef's diagnostic."""
    pending = [i for i, (n, v) in enumerate(zip(names, operands))
               if n.startswith(("_retv_", "_ptlk_"))
               and (v is None or v is UNDEF)]
    if not pending:
        return operands
    try:
        out = jax.eval_shape(lambda: run_body(operands))
    except Exception:
        return operands
    ops = list(operands)
    for i in pending:
        if i < len(out) and out[i] is not None and out[i] is not UNDEF:
            ops[i] = _placeholder_like(out[i])
    return tuple(ops)


def _traced_while(cond_fn, body_fn, operands, names):
    operands = _init_ret_carries(lambda ops: body_fn(*ops), operands, names)
    _check_no_undef(names, operands, "while")
    dyn, stat, mask = _split(operands)
    dyn_flat, dyn_tree = jax.tree_util.tree_flatten(tuple(dyn))
    static_names = [n for n, m in zip(names, mask) if not m]

    def cond(flat):
        vals = _merge(list(jax.tree_util.tree_unflatten(dyn_tree, flat)),
                      stat, mask)
        return to_bool(cond_fn(*vals), "`while` condition")

    def body_raw(flat):
        vals = _merge(list(jax.tree_util.tree_unflatten(dyn_tree, flat)),
                      stat, mask)
        outs = body_fn(*vals)
        nd, ns, nm = _split(outs)
        if nm != mask:
            raise TypeError(
                f"dy2static: the `while` body changed which of "
                f"{_public_names(names)} are tensors; loop variables must stay "
                "tensor/numeric")
        for n, a, b in zip(static_names, stat, ns):
            _check_same_static(n, a, b)
        new_flat, new_tree = jax.tree_util.tree_flatten(tuple(nd))
        if new_tree != dyn_tree:
            raise TypeError(
                f"dy2static: the `while` body changed the structure of "
                f"loop variables {_public_names(names)}")
        return new_flat

    leaf_names = _dyn_names(names, mask, dyn)
    init_flat = [jnp.asarray(_plain(x)) for x in dyn_flat]
    dtypes = _stable_dtypes(body_raw, init_flat, leaf_names)
    init = tuple(x.astype(d) for x, d in zip(init_flat, dtypes))

    def body(flat):
        return tuple(jnp.asarray(_plain(v)).astype(d)
                     for v, d in zip(body_raw(list(flat)), dtypes))

    out_flat = jax.lax.while_loop(cond, body, init)
    return _merge(list(jax.tree_util.tree_unflatten(dyn_tree,
                                                    list(out_flat))),
                  stat, mask)


def _plain(v):
    return v._value if isinstance(v, Tensor) else v


def convert_for(iterable, body_fn, operands, names=(), target_arity=1,
                brk=None):
    """`for` converter.  A Tensor/traced iterable scans over its leading
    axis with `lax.scan`; any other iterable keeps the Python loop (which
    unrolls under jit — the natural XLA behavior for static trip
    counts).  `brk` names the transformer's break guard flag: when its
    carried value turns CONCRETELY true on the Python path, the loop
    stops early — restoring real break semantics that the guard rewrite
    alone would turn into no-op tail iterations."""
    if isinstance(iterable, _TracedRange):
        return _traced_range_for(iterable, body_fn, operands, names,
                                 target_arity)
    it = _raw(iterable)
    if not isinstance(it, jax.core.Tracer):
        brk_i = names.index(brk) if brk in names else None
        vals = operands
        for x in iterable:
            if target_arity == 1:
                vals = body_fn(x, *vals)
            else:
                vals = body_fn(*tuple(x), *vals)
            if brk_i is not None:
                flag = _raw(vals[brk_i])
                if not isinstance(flag, jax.core.Tracer) and bool(flag):
                    break
        return vals

    wrap = Tensor if isinstance(iterable, Tensor) else (lambda x: x)
    x0_probe = it[0] if it.shape[0] else it  # aval probe only
    if target_arity == 1:
        xs0 = (wrap(x0_probe),)
    else:
        xs0 = tuple(wrap(x0_probe[i]) for i in range(target_arity))
    operands = _init_ret_carries(lambda ops: body_fn(*xs0, *ops),
                                 operands, names)
    _check_no_undef(names, operands, "for")
    dyn, stat, mask = _split(operands)
    dyn_flat, dyn_tree = jax.tree_util.tree_flatten(tuple(dyn))
    static_names = [n for n, m in zip(names, mask) if not m]

    def step_raw(flat, x):
        vals = _merge(list(jax.tree_util.tree_unflatten(dyn_tree, flat)),
                      stat, mask)
        if target_arity == 1:
            xs = (wrap(x),)
        else:
            xs = tuple(wrap(x[i]) for i in range(target_arity))
        outs = body_fn(*xs, *vals)
        nd, ns, nm = _split(outs)
        if nm != mask:
            raise TypeError(
                f"dy2static: the `for` body changed which of "
                f"{_public_names(names)} are tensors; loop variables must stay "
                "tensor/numeric")
        for n, a, b in zip(static_names, stat, ns):
            _check_same_static(n, a, b)
        new_flat, new_tree = jax.tree_util.tree_flatten(tuple(nd))
        if new_tree != dyn_tree:
            raise TypeError(
                f"dy2static: the `for` body changed the structure of loop "
                f"variables {_public_names(names)}")
        return new_flat

    leaf_names = _dyn_names(names, mask, dyn)
    init_flat = [jnp.asarray(_plain(x)) for x in dyn_flat]
    dtypes = _stable_dtypes(lambda flat: step_raw(list(flat), x0_probe),
                            init_flat, leaf_names)
    init = tuple(x.astype(d) for x, d in zip(init_flat, dtypes))

    def step(flat, x):
        out = step_raw(list(flat), x)
        return tuple(jnp.asarray(_plain(v)).astype(d)
                     for v, d in zip(out, dtypes)), None

    carry, _ = jax.lax.scan(step, init, it)
    return _merge(list(jax.tree_util.tree_unflatten(dyn_tree, list(carry))),
                  stat, mask)


class _TracedRange:
    """range() with a traced bound: no concrete length exists, so the
    `for` lowers to lax.while_loop over the index instead of a scan."""

    def __init__(self, start, stop, step):
        self.start, self.stop, self.step = start, stop, step


def _traced_range_for(rng, body_fn, operands, names, target_arity):
    """`for i in range(<traced bound>)`: lax.while_loop carrying
    (index, *loop_vars)."""
    if target_arity != 1:
        raise TypeError("dy2static: cannot unpack a range() loop target")
    _check_no_undef(names, operands, "for")

    def cond_fn(i, *vals):
        step = rng.step
        fwd = jnp.logical_and(jnp.asarray(step > 0), i < rng.stop)
        bwd = jnp.logical_and(jnp.asarray(step < 0), i > rng.stop)
        return jnp.logical_or(fwd, bwd)

    def step_fn(i, *vals):
        outs = body_fn(i, *vals)
        if not isinstance(outs, tuple):
            outs = (outs,)
        return (i + rng.step,) + outs

    out = _traced_while(cond_fn, step_fn,
                        (jnp.asarray(rng.start),) + tuple(operands),
                        ("<range index>",) + tuple(names))
    return out[1:]


def convert_range(*args):
    """`range(...)` in a converted `for` header: Python range for concrete
    bounds, a while-loop marker for tensor bounds."""
    vals = [_raw(a) for a in args]
    if any(isinstance(v, jax.core.Tracer) for v in vals):
        vals = [jnp.reshape(v, ()) if isinstance(v, jax.core.Tracer)
                else int(v) for v in vals]
        if len(vals) == 1:
            return _TracedRange(0, vals[0], 1)
        if len(vals) == 2:
            return _TracedRange(vals[0], vals[1], 1)
        return _TracedRange(*vals[:3])
    return range(*[int(v) for v in vals])


def convert_len(x):
    v = _raw(x)
    if isinstance(v, (jax.Array, jax.core.Tracer, np.ndarray)):
        if v.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return v.shape[0]
    return len(x)


# --------------------------------------------------------------------------
# boolean operators
# --------------------------------------------------------------------------

def convert_and(*thunks):
    """Short-circuit `and` chain.  Python semantics for concrete values;
    a traced operand switches to elementwise logical_and (the graph
    meaning — reference convert_logical_and)."""
    val = thunks[0]()
    for t in thunks[1:]:
        if not _is_traced(val):
            if not val:
                return val
            val = t()
        else:
            val = _logical(jnp.logical_and, val, t())
    return val


def convert_or(*thunks):
    val = thunks[0]()
    for t in thunks[1:]:
        if not _is_traced(val):
            if val:
                return val
            val = t()
        else:
            val = _logical(jnp.logical_or, val, t())
    return val


def _logical(op, a, b):
    out = op(jnp.asarray(_raw(a)).astype(bool),
             jnp.asarray(_raw(b)).astype(bool))
    return Tensor(out) if isinstance(a, Tensor) or isinstance(b, Tensor) \
        else out


def convert_not(x):
    if _is_traced(x):
        out = jnp.logical_not(jnp.asarray(_raw(x)).astype(bool))
        return Tensor(out) if isinstance(x, Tensor) else out
    return not x


def convert_print(*args, **kwargs):
    """print() in converted code (reference PrintTransformer → Print op):
    traced values print at RUNTIME via jax.debug.print instead of
    dumping tracer reprs at trace time. sep/end are honored; `file`
    cannot be routed through the runtime host callback and is ignored
    on the traced path."""
    if not any(_is_traced(a) for a in args):
        return print(*args, **kwargs)
    esc = lambda s: str(s).replace("{", "{{").replace("}", "}}")
    # print(sep=None/end=None) means the defaults, not the string 'None'
    sep = kwargs.get("sep")
    sep = esc(" " if sep is None else sep)
    end = kwargs.get("end")
    end = "\n" if end is None else end
    fmt = sep.join("{}" for _ in args)
    if end != "\n":                 # debug.print terminates with newline
        fmt += esc(end)
    jax.debug.print(fmt, *[_raw(a) if _is_traced(a) else a for a in args])
