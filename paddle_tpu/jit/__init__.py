"""paddle_tpu.jit — reference python/paddle/jit (dy2static to_static, save/load).

TPU-native: to_static first routes tensor-dependent Python control flow
onto lax.cond/while_loop/scan via the dy2static AST transform (see
jit/dy2static/), then wraps the Layer/function in jax.jit over its
functional form. jit.save exports StableHLO text + weights; jit.load
restores a callable (same artifact role as the reference's saved
inference Program).
"""
import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor
from ..nn.layer_base import Layer, functional_call, state_pytree
from ..static.input_spec import InputSpec
from .dy2static import conversion_error, convert_to_static

__all__ = ["to_static", "save", "load", "not_to_static", "TranslatedLayer",
           "dy2static"]


class _StaticFunction:
    """dy2static-converted, jax.jit-compiled wrapper around a Layer or
    python function (reference dygraph_to_static.StaticFunction)."""

    def __init__(self, fn_or_layer, input_spec=None, donate_params=False,
                 lint=False):
        self._target = fn_or_layer
        self._input_spec = input_spec
        self._lint = bool(lint)
        self._lint_graph_done = False
        self.lint_report = None
        self._is_layer = isinstance(fn_or_layer, Layer)
        if self._lint:
            # source lint at CONVERSION time: hazards like a global
            # write or return-in-try are visible here and invisible in
            # the traced graph
            self.lint_report = self._run_source_lint(fn_or_layer)
        if self._is_layer:
            layer = fn_or_layer
            # convert whatever Layer.__call__ would dispatch to: an
            # instance-assigned forward wins over the class method
            inst_fwd = layer.__dict__.get("forward")
            if inst_fwd is not None and hasattr(inst_fwd, "__func__"):
                conv = convert_to_static(inst_fwd.__func__)
                bound = lambda *a, **k: conv(layer, *a, **k)  # noqa: E731
            elif inst_fwd is not None:
                bound = convert_to_static(inst_fwd)
            else:
                conv = convert_to_static(type(layer).forward)
                bound = lambda *a, **k: conv(layer, *a, **k)  # noqa: E731

            def call_converted(*inputs, **kwargs):
                # hook-wrapped dispatch of the CONVERTED forward (no
                # instance-dict swap: swapping layer.forward is not
                # reentrancy/thread safe). A subclass overriding
                # __call__ itself is bypassed here — hook semantics
                # live in Layer._dispatch, the shared path.
                return layer._dispatch(bound, *inputs, **kwargs)

            self._dygraph = call_converted

            def pure(params, buffers, *args, **kwargs):
                merged = {**params, **buffers}
                with functional_call(layer, merged):
                    out = call_converted(*args, **kwargs)
                return out
            self._jitted = jax.jit(pure)
        else:
            fn = convert_to_static(
                getattr(fn_or_layer, "__func__", fn_or_layer))
            if hasattr(fn_or_layer, "__self__"):   # bound method
                bound_self = fn_or_layer.__self__
                conv = fn

                def fn(*args, **kwargs):
                    return conv(bound_self, *args, **kwargs)
            self._dygraph = fn
            self._jitted = jax.jit(fn)

    def __call__(self, *args, **kwargs):
        if not ProgramTranslator.enable_to_static:
            # dygraph fallback (still control-flow converted, not jitted)
            return self._dygraph(*args, **kwargs)
        if self._is_layer:
            layer = self._target
            params = state_pytree(layer)
            from ..nn.layer_base import buffer_pytree
            bufs = buffer_pytree(layer)
            if self._lint and not self._lint_graph_done:
                # flatten order is (params, bufs, *inputs): the model
                # inputs are the trailing %arg ids, which the layout
                # analyzer needs to tell an input-activation transpose
                # from a free parameter-layout one
                n_fixed = len(jax.tree_util.tree_leaves((params, bufs)))
                n_in = len(jax.tree_util.tree_leaves((args, kwargs)))
                self._run_graph_lint(
                    range(n_fixed, n_fixed + n_in),
                    params, bufs, *args, **kwargs)
            return self._jitted(params, bufs, *args, **kwargs)
        if self._lint and not self._lint_graph_done:
            n_in = len(jax.tree_util.tree_leaves((args, kwargs)))
            self._run_graph_lint(range(n_in), *args, **kwargs)
        return self._jitted(*args, **kwargs)

    def _run_source_lint(self, fn_or_layer):
        from ..analysis.ast_lint import lint_function
        target = fn_or_layer
        if isinstance(fn_or_layer, Layer):
            target = (fn_or_layer.__dict__.get("forward")
                      or type(fn_or_layer).forward)
        report = lint_function(target)
        self._warn_lint(report, "dy2static lint")
        return report

    def _run_graph_lint(self, input_arg_ids, *jit_args, **jit_kwargs):
        """Graph Doctor over the program about to run: one extra trace
        (lint=True is an explicit opt-in), findings merged into
        self.lint_report and surfaced as warnings."""
        self._lint_graph_done = True
        from ..analysis import (AnalysisContext, LoweredProgram,
                                PassManager)
        try:
            text = self._jitted.lower(*jit_args, **jit_kwargs).as_text()
        except Exception as e:   # lint must never break the real call
            import warnings
            warnings.warn(f"graph lint skipped (lowering failed: {e})")
            return
        name = getattr(self._target, "__name__",
                       type(self._target).__name__)
        ctx = AnalysisContext(name=name,
                              policy_dtype=self._guess_policy())
        program = LoweredProgram(text, name=name,
                                 input_arg_ids=input_arg_ids)
        report = PassManager().run(program, ctx)
        if self.lint_report is None:
            self.lint_report = report
        else:
            self.lint_report.extend(report)
        self._warn_lint(report, "graph lint")

    def _guess_policy(self):
        if self._is_layer:
            import jax.numpy as jnp
            for p in self._target.parameters():
                if p._value.dtype == jnp.bfloat16:
                    return "bfloat16"
        return None

    @staticmethod
    def _warn_lint(report, what):
        from ..analysis import Severity
        import warnings
        worth = [f for f in report.findings
                 if f.severity >= Severity.WARNING]
        if worth:
            warnings.warn(
                f"{what}: {len(worth)} finding(s):\n"
                + "\n".join(str(f) for f in worth))

    @property
    def forward(self):
        return self


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, lint=False, **kwargs):
    """`lint=True` runs the Graph Doctor (paddle_tpu.analysis): the
    dy2static AST linter at conversion time, plus the full graph pass
    catalog on the first compiled call. Findings land on the returned
    object's `.lint_report`; WARNING+ ones also surface as python
    warnings."""
    if function is None:
        def deco(fn):
            return _StaticFunction(fn, input_spec, lint=lint)
        return deco
    return _StaticFunction(function, input_spec, lint=lint)


def not_to_static(fn):
    return fn


def _example_from_spec(spec):
    return spec.example_array(batch=1)


def _symbolic_args(specs):
    """InputSpec list -> ShapeDtypeStruct args where every None/-1 dim is
    a distinct export symbol, so the saved program accepts ANY size there
    (paddle's dynamic-batch convention) instead of specializing to 1."""
    from jax import export as jax_export
    scope = jax_export.SymbolicScope()
    args, n = [], 0
    for spec in specs:
        dims = []
        for s in spec.shape:
            if s is None or s < 0:
                dims.append(jax_export.symbolic_shape(f"d{n}",
                                                      scope=scope)[0])
                n += 1
            else:
                dims.append(int(s))
        args.append(jax.ShapeDtypeStruct(tuple(dims),
                                         jnp.dtype(spec.dtype or "float32")))
    return args, n


def save(layer, path, input_spec=None, **configs):
    """Exports {path}.pdiparams (weights pickle) + {path}.pdmodel (meta)
    + {path}.stablehlo.mlir (inspectable IR) + {path}.jaxprog (executable
    jax.export artifact: the serialized program runs WITHOUT the Python
    Layer — reference jit.save inference-program role,
    paddle/fluid/inference/api/paddle_inference_api.h)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    converted = None
    if isinstance(layer, _StaticFunction):
        converted = layer._dygraph     # control-flow-converted forward
        layer = layer._target
    state = {k: np.asarray(v._value) for k, v in layer.state_dict().items()}
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump(state, f)
    meta = {"class": type(layer).__name__}
    if input_spec:
        specs = [s if isinstance(s, InputSpec) else InputSpec.from_tensor(s)
                 for s in input_spec]
        meta["input_spec"] = [(list(s.shape), str(s.dtype)) for s in specs]
        try:
            params = state_pytree(layer)
            from ..nn.layer_base import buffer_pytree
            bufs = buffer_pytree(layer)
            meta["param_names"] = sorted(params)
            meta["buffer_names"] = sorted(bufs)

            if converted is None and isinstance(layer, Layer):
                # convert so tensor-dependent control flow exports via lax
                converted = _StaticFunction(layer)._dygraph
            fwd_call = converted if converted is not None else layer

            def pure(params, buffers, *args):
                with functional_call(layer, {**params, **buffers}):
                    out = fwd_call(*args)
                return out._value if isinstance(out, Tensor) else out
            examples = [_example_from_spec(s) for s in specs]
            from jax import export as jax_export
            sym_args, n_sym = _symbolic_args(specs)
            try:
                exp = jax_export.export(jax.jit(pure))(params, bufs,
                                                       *sym_args)
            except Exception as sym_err:
                if n_sym:
                    # an op in the model doesn't support shape polymorphism:
                    # fall back to a static program at the example shapes
                    meta["symbolic_export_error"] = str(sym_err)[:500]
                    meta["static_shapes"] = True
                    exp = jax_export.export(jax.jit(pure))(params, bufs,
                                                           *examples)
                else:
                    raise
            with open(path + ".jaxprog", "wb") as f:
                f.write(exp.serialize())
            # inspectable IR straight from the exported artifact (a
            # separate .lower() would trace the model a second time)
            with open(path + ".stablehlo.mlir", "w") as f:
                f.write(str(exp.mlir_module()))
        except Exception as e:  # export is best-effort; weights always saved
            meta["export_error"] = str(e)
            try:
                # the executable program failed, but the inspectable IR
                # may still lower — keep the .stablehlo.mlir promise
                lowered = jax.jit(pure).lower(params, bufs, *examples)
                with open(path + ".stablehlo.mlir", "w") as f:
                    f.write(lowered.as_text())
            except Exception:
                pass
    with open(path + ".pdmodel", "wb") as f:
        pickle.dump(meta, f)


class TranslatedLayer(Layer):
    """Loaded inference artifact (reference fluid/dygraph/io.py:
    TranslatedLayer).  When the .jaxprog executable program is present,
    forward() RUNS it — no Python Layer rebuild needed (the saved weights
    feed the program's parameter arguments)."""

    def __init__(self, state, meta, program=None, load_error=None):
        super().__init__()
        self._state = {k: jnp.asarray(v) for k, v in state.items()}
        self._meta = meta
        self._program = program
        self._load_error = load_error
        self._runner = None

    @property
    def runnable(self):
        return self._program is not None

    def _build_runner(self):
        pnames = self._meta.get("param_names")
        bnames = self._meta.get("buffer_names", [])
        if pnames is None:
            pnames = sorted(self._state)
        params = {n: self._state[n] for n in pnames}
        bufs = {n: self._state[n] for n in bnames}
        program = self._program
        call = jax.jit(lambda p, b, *args: program.call(p, b, *args))
        self._runner = lambda *args: call(params, bufs, *args)

    def forward(self, *args):
        if self._program is None:
            if self._load_error is not None:
                raise RuntimeError(
                    "the saved program could not be deserialized "
                    f"({self._load_error}); re-export the artifact with "
                    "the current jax version")
            raise NotImplementedError(
                "this artifact was saved without input_spec (no executable "
                "program): rebuild the python Layer and set_state_dict"
                "(layer.state_dict()), or re-save with input_spec")
        if self._runner is None:
            self._build_runner()
        out = self._runner(*[a._value if isinstance(a, Tensor)
                             else jnp.asarray(a) for a in args])
        if isinstance(out, (list, tuple)):
            return type(out)(Tensor(o) for o in out)
        return Tensor(out)

    def state_dict(self, *a, **k):
        return {k: Tensor(v) for k, v in self._state.items()}


def load(path, **configs):
    with open(path + ".pdiparams", "rb") as f:
        state = pickle.load(f)
    meta = {}
    if os.path.exists(path + ".pdmodel"):
        with open(path + ".pdmodel", "rb") as f:
            meta = pickle.load(f)
    program, load_error = None, None
    if os.path.exists(path + ".jaxprog"):
        try:
            from jax import export as jax_export
            with open(path + ".jaxprog", "rb") as f:
                program = jax_export.deserialize(f.read())
        except Exception as e:
            program = None
            load_error = f"{type(e).__name__}: {str(e)[:300]}"
    return TranslatedLayer(state, meta, program, load_error)


def set_code_level(level=100, also_to_stdout=False):
    """Dy2static debug verbosity — no bytecode translation stage here
    (jax.jit traces Python directly), accepted for parity."""
    return None


def set_verbosity(level=0, also_to_stdout=False):
    return None


class ProgramTranslator:
    """Reference dy2static ProgramTranslator singleton (reference
    dygraph_to_static/program_translator.py): the entry point for the AST
    control-flow conversion.  `get_func` returns the converted (but
    unjitted) function; `enable(False)` makes every _StaticFunction run
    its converted dygraph path instead of the compiled one."""
    _instance = None
    enable_to_static = True

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def enable(self, enable_to_static=True):
        ProgramTranslator.enable_to_static = bool(enable_to_static)

    def get_func(self, dygraph_func):
        """The dy2static-converted function (control flow routed through
        lax primitives), without jit."""
        return convert_to_static(
            getattr(dygraph_func, "__func__", dygraph_func))

    @staticmethod
    def conversion_error(fn):
        return conversion_error(getattr(fn, "__func__", fn))


class TracedLayer:
    """Reference fluid.dygraph.TracedLayer: trace a layer once, replay the
    jitted program."""

    def __init__(self, fn, example_inputs):
        self._fn = fn
        self._example = example_inputs

    @staticmethod
    def trace(layer, inputs):
        import jax
        from ..nn.layer_base import functional_call, state_pytree
        params = state_pytree(layer)

        def pure(p, *xs):
            return functional_call(layer, p, *xs)
        jitted = jax.jit(lambda *xs: pure(params, *xs))
        traced = TracedLayer(jitted, inputs)
        outs = layer(*inputs)
        return outs, traced

    def __call__(self, *inputs):
        return self._fn(*inputs)

    def save_inference_model(self, path, feed=None, fetch=None):
        import pickle
        with open(path + ".traced", "wb") as f:
            pickle.dump({"note": "use jit.save for StableHLO export"}, f)
