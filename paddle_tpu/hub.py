"""paddle_tpu.hub — reference python/paddle/hapi/hub.py. Zero-egress
environment: only `source="local"` works; github/gitee sources raise
(they would download archives). The local protocol is the reference's:
a repo dir with hubconf.py whose public callables are the entrypoints,
with an optional `dependencies = ["module", ...]` list checked for
importability right after hubconf itself imports (a hubconf that
imports a missing module at top level raises that ImportError
directly)."""
import importlib
import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]


def _check_dependencies(mod):
    deps = getattr(mod, "dependencies", None)
    if not deps:
        return
    missing = []
    for d in deps:
        try:
            importlib.import_module(d)
        except ImportError:
            missing.append(d)
    if missing:
        raise RuntimeError(
            f"hubconf.py declares missing dependencies: {missing}")


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, "hubconf.py")
    if not os.path.exists(path):
        raise FileNotFoundError(f"no hubconf.py in {repo_dir}")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["hubconf"] = mod
    spec.loader.exec_module(mod)
    _check_dependencies(mod)
    return mod


def _check_source(source):
    if source not in ("github", "gitee", "local"):
        raise ValueError(
            f'Unknown source: "{source}". Allowed values: "github" | '
            '"gitee" | "local".')
    if source != "local":
        raise NotImplementedError(
            "zero-egress environment: only source='local' is supported")


def list(repo_dir, source="github", force_reload=False):  # noqa: A001
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return [n for n in dir(mod)
            if callable(getattr(mod, n)) and not n.startswith("_")]


def _entrypoint(mod, model):
    fn = getattr(mod, model, None)
    if fn is None or not callable(fn) or model.startswith("_"):
        raise RuntimeError(f"hubconf.py has no entrypoint {model!r}")
    return fn


def help(repo_dir, model, source="github", force_reload=False):  # noqa: A001
    _check_source(source)
    return _entrypoint(_load_hubconf(repo_dir), model).__doc__


def load(repo_dir, model, source="github", force_reload=False, **kwargs):
    _check_source(source)
    return _entrypoint(_load_hubconf(repo_dir), model)(**kwargs)
