// Native image pipeline for the DataLoader — the TPU-side equivalent of the
// reference's C++ data feeding ops (paddle/fluid/operators/data_norm_op,
// image decode in paddle/fluid/operators/reader). All entry points are
// plain-C ABI for ctypes and run entirely off the GIL; the Python wrapper
// (paddle_tpu/runtime/image.py) falls back to PIL/numpy when this .so is
// unavailable.
//
//   pti_jpeg_info        — parse header: height/width/channels
//   pti_decode_jpeg      — decode into caller-provided HWC uint8 buffer
//   pti_resize_bilinear  — HWC uint8 bilinear resize
//   pti_normalize_chw    — HWC uint8 -> CHW float32 (x/255 - mean)/std
//   pti_pipeline         — fused decode -> resize -> normalize, one call
//
// Build: g++ -O3 -shared -fPIC -std=c++17 image_ops.cpp -ljpeg

#include <cstdint>
#include <cstdio>  // jpeglib.h needs FILE declared
#include <cstring>
#include <vector>

#include <jpeglib.h>
#include <csetjmp>

extern "C" {

struct PtiErrMgr {
  jpeg_error_mgr pub;
  jmp_buf jump;
};

static void pti_error_exit(j_common_ptr cinfo) {
  PtiErrMgr* err = reinterpret_cast<PtiErrMgr*>(cinfo->err);
  longjmp(err->jump, 1);
}

int pti_jpeg_info(const uint8_t* buf, int64_t len, int* h, int* w, int* c) {
  jpeg_decompress_struct cinfo;
  PtiErrMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = pti_error_exit;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, buf, static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  *h = cinfo.image_height;
  *w = cinfo.image_width;
  *c = cinfo.num_components >= 3 ? 3 : 1;
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

// out must hold h*w*c bytes (c from pti_jpeg_info: 3 for color, 1 for gray).
int pti_decode_jpeg(const uint8_t* buf, int64_t len, uint8_t* out) {
  jpeg_decompress_struct cinfo;
  PtiErrMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = pti_error_exit;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, buf, static_cast<unsigned long>(len));
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = cinfo.num_components >= 3 ? JCS_RGB : JCS_GRAYSCALE;
  jpeg_start_decompress(&cinfo);
  const int stride = cinfo.output_width * cinfo.output_components;
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = out + static_cast<size_t>(cinfo.output_scanline) * stride;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

// HWC uint8 bilinear resize (align_corners=false, pixel-center sampling —
// matches PIL/torchvision antialias=off semantics closely enough for
// training pipelines).
void pti_resize_bilinear(const uint8_t* src, int h, int w, int c,
                         uint8_t* dst, int oh, int ow) {
  const float sy = static_cast<float>(h) / oh;
  const float sx = static_cast<float>(w) / ow;
  for (int y = 0; y < oh; ++y) {
    float fy = (y + 0.5f) * sy - 0.5f;
    if (fy < 0) fy = 0;
    int y0 = static_cast<int>(fy);
    int y1 = y0 + 1 < h ? y0 + 1 : h - 1;
    const float wy = fy - y0;
    for (int x = 0; x < ow; ++x) {
      float fx = (x + 0.5f) * sx - 0.5f;
      if (fx < 0) fx = 0;
      int x0 = static_cast<int>(fx);
      int x1 = x0 + 1 < w ? x0 + 1 : w - 1;
      const float wx = fx - x0;
      const uint8_t* p00 = src + (static_cast<size_t>(y0) * w + x0) * c;
      const uint8_t* p01 = src + (static_cast<size_t>(y0) * w + x1) * c;
      const uint8_t* p10 = src + (static_cast<size_t>(y1) * w + x0) * c;
      const uint8_t* p11 = src + (static_cast<size_t>(y1) * w + x1) * c;
      uint8_t* out = dst + (static_cast<size_t>(y) * ow + x) * c;
      for (int ch = 0; ch < c; ++ch) {
        const float top = p00[ch] + (p01[ch] - p00[ch]) * wx;
        const float bot = p10[ch] + (p11[ch] - p10[ch]) * wx;
        const float val = top + (bot - top) * wy;
        out[ch] = static_cast<uint8_t>(val + 0.5f);
      }
    }
  }
}

// HWC uint8 -> CHW float32, (x*scale - mean[ch]) / std[ch]. scale is
// typically 1/255; pass mean/std in the scaled domain.
void pti_normalize_chw(const uint8_t* src, int h, int w, int c,
                       const float* mean, const float* stddev, float scale,
                       float* out) {
  const size_t plane = static_cast<size_t>(h) * w;
  for (int ch = 0; ch < c; ++ch) {
    const float m = mean[ch];
    const float inv = 1.0f / stddev[ch];
    float* dst = out + ch * plane;
    const uint8_t* s = src + ch;
    for (size_t i = 0; i < plane; ++i) {
      dst[i] = (s[i * c] * scale - m) * inv;
    }
  }
}

// Fused decode -> resize -> normalize. out is CHW float32 [c, oh, ow]
// (c resolved from the JPEG: 3 or 1). Returns the channel count, or -1.
int pti_pipeline(const uint8_t* buf, int64_t len, int oh, int ow,
                 const float* mean, const float* stddev, float scale,
                 float* out) {
  int h, w, c;
  if (pti_jpeg_info(buf, len, &h, &w, &c) != 0) return -1;
  std::vector<uint8_t> decoded(static_cast<size_t>(h) * w * c);
  if (pti_decode_jpeg(buf, len, decoded.data()) != 0) return -1;
  if (h == oh && w == ow) {
    pti_normalize_chw(decoded.data(), oh, ow, c, mean, stddev, scale, out);
    return c;
  }
  std::vector<uint8_t> resized(static_cast<size_t>(oh) * ow * c);
  pti_resize_bilinear(decoded.data(), h, w, c, resized.data(), oh, ow);
  pti_normalize_chw(resized.data(), oh, ow, c, mean, stddev, scale, out);
  return c;
}

}  // extern "C"
