// Native data-loader runtime.
//
// Role parity: the reference's C++ reader/feeder stack
// (paddle/fluid/operators/reader + DoubleBufferReader) — host-side batch
// assembly off the Python GIL. TPU-native twist: the hot pretraining input is
// a flat token stream; this library mmaps the token file, and a worker pool
// fills a lock-guarded ring of ready [batch, seq+1] int32 batches that the
// Python side copies out and device_puts while workers run ahead.
//
// C ABI (ctypes): ptl_open / ptl_start / ptl_next / ptl_stop / ptl_close.
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Loader {
  // mmap'd token file
  int fd = -1;
  const int32_t* tokens = nullptr;
  size_t n_tokens = 0;
  size_t map_len = 0;

  // batch geometry
  int64_t batch = 0;
  int64_t seq = 0;

  // prefetch ring
  std::deque<std::vector<int32_t>> ready;
  size_t capacity = 0;
  std::mutex mu;
  std::condition_variable cv_ready;   // consumer waits
  std::condition_variable cv_space;   // producers wait
  std::vector<std::thread> workers;
  std::atomic<bool> running{false};
  uint64_t seed = 0;
  std::atomic<uint64_t> batch_counter{0};
};

void worker_main(Loader* L, int wid) {
  std::mt19937_64 rng(L->seed + 0x9e3779b97f4a7c15ULL * (wid + 1));
  const int64_t sample_len = L->seq + 1;
  while (L->running.load(std::memory_order_relaxed)) {
    std::vector<int32_t> buf(static_cast<size_t>(L->batch) * sample_len);
    const size_t max_start = L->n_tokens - sample_len;
    for (int64_t b = 0; b < L->batch; ++b) {
      size_t start = rng() % max_start;
      std::memcpy(buf.data() + b * sample_len, L->tokens + start,
                  sample_len * sizeof(int32_t));
    }
    std::unique_lock<std::mutex> lk(L->mu);
    L->cv_space.wait(lk, [L] {
      return !L->running.load() || L->ready.size() < L->capacity;
    });
    if (!L->running.load()) return;
    L->ready.emplace_back(std::move(buf));
    L->cv_ready.notify_one();
  }
}

}  // namespace

extern "C" {

void* ptl_open(const char* path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (::fstat(fd, &st) != 0 || st.st_size < (long)sizeof(int32_t)) {
    ::close(fd);
    return nullptr;
  }
  void* mapped = ::mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (mapped == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  ::madvise(mapped, st.st_size, MADV_RANDOM);
  auto* L = new Loader();
  L->fd = fd;
  L->tokens = static_cast<const int32_t*>(mapped);
  L->n_tokens = st.st_size / sizeof(int32_t);
  L->map_len = st.st_size;
  return L;
}

int64_t ptl_num_tokens(void* handle) {
  return static_cast<Loader*>(handle)->n_tokens;
}

int ptl_start(void* handle, int64_t batch, int64_t seq, int n_workers,
              int prefetch_depth, uint64_t seed) {
  auto* L = static_cast<Loader*>(handle);
  if (L->running.load()) return -1;
  if ((size_t)(seq + 1) > L->n_tokens) return -2;
  L->batch = batch;
  L->seq = seq;
  L->capacity = prefetch_depth > 0 ? prefetch_depth : 2;
  L->seed = seed;
  L->running.store(true);
  for (int i = 0; i < (n_workers > 0 ? n_workers : 1); ++i)
    L->workers.emplace_back(worker_main, L, i);
  return 0;
}

// Copies one ready batch ([batch, seq+1] int32, row-major) into out.
int ptl_next(void* handle, int32_t* out) {
  auto* L = static_cast<Loader*>(handle);
  std::unique_lock<std::mutex> lk(L->mu);
  L->cv_ready.wait(lk, [L] { return !L->running.load() || !L->ready.empty(); });
  if (L->ready.empty()) return -1;
  std::vector<int32_t> buf = std::move(L->ready.front());
  L->ready.pop_front();
  L->cv_space.notify_one();
  lk.unlock();
  std::memcpy(out, buf.data(), buf.size() * sizeof(int32_t));
  L->batch_counter.fetch_add(1);
  return 0;
}

void ptl_stop(void* handle) {
  auto* L = static_cast<Loader*>(handle);
  L->running.store(false);
  L->cv_space.notify_all();
  L->cv_ready.notify_all();
  for (auto& t : L->workers)
    if (t.joinable()) t.join();
  L->workers.clear();
  std::lock_guard<std::mutex> lk(L->mu);
  L->ready.clear();
}

void ptl_close(void* handle) {
  auto* L = static_cast<Loader*>(handle);
  if (L->running.load()) ptl_stop(handle);
  if (L->tokens) ::munmap(const_cast<int32_t*>(L->tokens), L->map_len);
  if (L->fd >= 0) ::close(L->fd);
  delete L;
}

}  // extern "C"
