// Native WordPiece tokenizer — batch encoder with an off-GIL thread pool.
//
// Reference counterpart: the reference framework tokenizes in Python
// (PaddleNLP tokenizers) and hides the cost behind multiprocess DataLoader
// workers; here the hot path (greedy longest-match WordPiece over a vocab
// hash map) is C++ so one process saturates text preprocessing without
// worker processes. Semantics: BERT WordPiece — whitespace pre-split,
// per-word greedy longest prefix match, continuation pieces prefixed
// "##", unknown words -> [UNK]. All matching is on raw UTF-8 bytes; the
// Python fallback (runtime/tokenizer.py) implements the identical
// byte-level algorithm so outputs are bit-identical either way.
//
// C ABI (ctypes):
//   ptk_create(vocab_blob, blob_len) -> handle
//       vocab_blob: '\n'-joined UTF-8 tokens; token id == line index.
//   ptk_encode_batch(handle, text_blob, offsets, n_texts,
//                    out_ids, out_lens, max_len, n_threads,
//                    unk_id, cls_id, sep_id) -> 0/err
//       text_blob: concatenated UTF-8 texts, offsets[i]..offsets[i+1].
//       out_ids: int32 [n_texts, max_len] (padded with 0);
//       emits [CLS] ... [SEP] when cls_id/sep_id >= 0.
//   ptk_free(handle)

#include <cstdint>
#include <cstring>
#include <string>
#include <string_view>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

struct Tokenizer {
  std::string storage;                       // owns the vocab bytes
  std::unordered_map<std::string_view, int32_t> vocab;
  size_t max_token_bytes = 1;
};

bool is_space(unsigned char c) {
  return c == ' ' || c == '\t' || c == '\n' || c == '\r';
}

void encode_one(const Tokenizer& tk, std::string_view text, int32_t* out,
                int32_t* out_len, int64_t max_len, int32_t unk_id,
                int32_t cls_id, int32_t sep_id) {
  int64_t n = 0;
  if (cls_id >= 0 && n < max_len) out[n++] = cls_id;
  size_t i = 0;
  const size_t N = text.size();
  while (i < N && n < max_len) {
    while (i < N && is_space(text[i])) ++i;
    if (i >= N) break;
    size_t j = i;
    while (j < N && !is_space(text[j])) ++j;
    std::string_view word = text.substr(i, j - i);
    i = j;
    // greedy longest-match over the word's bytes
    size_t pos = 0;
    bool bad = false;
    std::vector<int32_t> pieces;
    std::string cont;                        // "##" + piece scratch
    while (pos < word.size()) {
      size_t take = std::min(word.size() - pos, tk.max_token_bytes);
      int32_t id = -1;
      size_t used = 0;
      for (; take > 0; --take) {
        std::string_view cand = word.substr(pos, take);
        if (pos == 0) {
          auto it = tk.vocab.find(cand);
          if (it != tk.vocab.end()) { id = it->second; used = take; break; }
        } else {
          cont.assign("##");
          cont.append(cand.data(), cand.size());
          auto it = tk.vocab.find(std::string_view(cont));
          if (it != tk.vocab.end()) { id = it->second; used = take; break; }
        }
      }
      if (id < 0) { bad = true; break; }
      pieces.push_back(id);
      pos += used;
    }
    if (bad) {
      if (n < max_len) out[n++] = unk_id;
    } else {
      for (int32_t id : pieces) {
        if (n >= max_len) break;
        out[n++] = id;
      }
    }
  }
  if (sep_id >= 0) {
    if (n < max_len) out[n++] = sep_id;
    else out[max_len - 1] = sep_id;
  }
  *out_len = static_cast<int32_t>(n);
}

}  // namespace

extern "C" {

void* ptk_create(const char* vocab_blob, int64_t blob_len) {
  auto* tk = new Tokenizer();
  tk->storage.assign(vocab_blob, static_cast<size_t>(blob_len));
  size_t start = 0;
  int32_t id = 0;
  const std::string& s = tk->storage;
  for (size_t i = 0; i <= s.size(); ++i) {
    if (i == s.size() || s[i] == '\n') {
      if (i > start) {
        std::string_view tok(&s[start], i - start);
        tk->vocab.emplace(tok, id);
        size_t body = tok.size();
        if (tok.size() > 2 && tok[0] == '#' && tok[1] == '#') body -= 2;
        if (body > tk->max_token_bytes) tk->max_token_bytes = body;
      }
      ++id;
      start = i + 1;
    }
  }
  return tk;
}

int ptk_encode_batch(void* handle, const char* text_blob,
                     const int64_t* offsets, int64_t n_texts,
                     int32_t* out_ids, int32_t* out_lens, int64_t max_len,
                     int n_threads, int32_t unk_id, int32_t cls_id,
                     int32_t sep_id) {
  auto* tk = static_cast<Tokenizer*>(handle);
  if (!tk || n_texts < 0 || max_len <= 0) return 1;
  std::memset(out_ids, 0, sizeof(int32_t) * n_texts * max_len);
  int nt = n_threads > 0 ? n_threads : 1;
  if (nt > n_texts) nt = static_cast<int>(n_texts > 0 ? n_texts : 1);
  auto work = [&](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; ++r) {
      std::string_view text(text_blob + offsets[r],
                            static_cast<size_t>(offsets[r + 1] - offsets[r]));
      encode_one(*tk, text, out_ids + r * max_len, out_lens + r, max_len,
                 unk_id, cls_id, sep_id);
    }
  };
  if (nt <= 1) {
    work(0, n_texts);
  } else {
    std::vector<std::thread> threads;
    int64_t chunk = (n_texts + nt - 1) / nt;
    for (int t = 0; t < nt; ++t) {
      int64_t lo = t * chunk;
      int64_t hi = std::min<int64_t>(lo + chunk, n_texts);
      if (lo >= hi) break;
      threads.emplace_back(work, lo, hi);
    }
    for (auto& th : threads) th.join();
  }
  return 0;
}

void ptk_free(void* handle) { delete static_cast<Tokenizer*>(handle); }

}  // extern "C"
