"""WordPiece tokenizer: native C++ batch encoder with a bit-identical
Python fallback.

Reference counterpart: PaddleNLP's BertTokenizer feeding the reference
BERT/ERNIE recipes (Python, hidden behind multiprocess DataLoader
workers). Here the greedy longest-match runs in C++ with an off-GIL
thread pool (runtime/cxx/tokenizer.cpp), so text preprocessing keeps up
with the device without worker processes; `use_native=False` (or a
failed toolchain) falls back to the same byte-level algorithm in Python.

    tok = WordPieceTokenizer(vocab)          # list of tokens or a file path
    ids, lens = tok.encode_batch(["a test"], max_len=16)

Matching is on raw UTF-8 bytes (continuation pieces prefixed '##',
unknown words -> unk token), so native and Python agree byte-for-byte.
"""
import ctypes
import os
import re

import numpy as np

from ._build import load_native

__all__ = ["WordPieceTokenizer", "native_tokenizer_available"]


def _register(lib):
    lib.ptk_create.restype = ctypes.c_void_p
    lib.ptk_create.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.ptk_encode_batch.restype = ctypes.c_int
    lib.ptk_encode_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
        ctypes.c_int, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int32]
    lib.ptk_free.restype = None
    lib.ptk_free.argtypes = [ctypes.c_void_p]


def _get_lib():
    return load_native("libptk_tokenizer.so", "tokenizer.cpp", _register)


def native_tokenizer_available():
    return _get_lib() is not None


class WordPieceTokenizer:
    def __init__(self, vocab, unk_token="[UNK]", cls_token="[CLS]",
                 sep_token="[SEP]", add_special_tokens=True,
                 lowercase=False, use_native=True):
        if isinstance(vocab, str):
            with open(vocab, "r", encoding="utf-8") as f:
                vocab = [line.rstrip("\n") for line in f if line.rstrip("\n")]
        self.tokens = list(vocab)
        bad = [t for t in self.tokens if "\n" in t or not t]
        if bad:
            raise ValueError(
                f"vocab tokens must be non-empty and newline-free "
                f"(the native blob is line-delimited): {bad[:3]!r}")
        # first occurrence wins on duplicates — same rule as the C++ map
        self.vocab = {}
        for i, t in enumerate(self.tokens):
            self.vocab.setdefault(t, i)
        self.lowercase = lowercase
        if unk_token not in self.vocab:
            raise ValueError(
                f"unk_token {unk_token!r} is not in the vocab — out-of-vocab "
                "words would silently map to id 0; add it or pass the "
                "correct unk_token=")
        self.unk_id = self.vocab[unk_token]
        self.cls_id = self.vocab.get(cls_token, -1) if add_special_tokens else -1
        self.sep_id = self.vocab.get(sep_token, -1) if add_special_tokens else -1
        # decode() strips the cls/sep tokens wherever they appear in the
        # vocab, even when THIS tokenizer doesn't emit them
        # (add_special_tokens=False) — ids may come from another encoder
        self._special_ids = {self.vocab.get(cls_token, -1),
                             self.vocab.get(sep_token, -1)} - {-1}
        self._bvocab = {}
        for i, t in enumerate(self.tokens):      # first-wins, like C++
            self._bvocab.setdefault(t.encode("utf-8"), i)
        self._max_body = max(
            (len(t.encode("utf-8")) - (2 if t.startswith("##") else 0)
             for t in self.tokens), default=1)
        self._handle = None
        self._lib = None           # kept on self: __del__ must not re-enter
        if use_native and native_tokenizer_available():    # the build lock
            self._lib = _get_lib()
            blob = "\n".join(self.tokens).encode("utf-8")
            self._handle = self._lib.ptk_create(blob, len(blob))

    @property
    def vocab_size(self):
        return len(self.tokens)

    def __len__(self):
        return len(self.tokens)

    # -- encoding ---------------------------------------------------------

    def encode_batch(self, texts, max_len=128, n_threads=0):
        """-> (ids int32 [N, max_len] zero-padded, lens int32 [N])."""
        if self.lowercase:
            texts = [t.lower() for t in texts]
        if self._handle is not None:
            return self._encode_native(texts, max_len, n_threads)
        return self._encode_py(texts, max_len)

    def encode(self, text, max_len=128):
        ids, lens = self.encode_batch([text], max_len)
        return ids[0, :lens[0]].tolist()

    def decode(self, ids):
        out = []
        for i in ids:
            i = int(i)
            if not 0 <= i < len(self.tokens) or i in self._special_ids:
                continue
            t = self.tokens[i]
            if t.startswith("##") and out:
                out[-1] += t[2:]
            elif t != "[PAD]":      # id-0 padding convention
                out.append(t)
        return " ".join(out)

    def _encode_native(self, texts, max_len, n_threads):
        lib = _get_lib()
        blobs = [t.encode("utf-8") for t in texts]
        offsets = np.zeros(len(blobs) + 1, np.int64)
        np.cumsum([len(b) for b in blobs], out=offsets[1:])
        blob = b"".join(blobs)
        n = len(texts)
        ids = np.zeros((n, max_len), np.int32)
        lens = np.zeros(n, np.int32)
        nt = n_threads or min(8, os.cpu_count() or 1)
        rc = lib.ptk_encode_batch(
            self._handle, blob, offsets.ctypes.data_as(
                ctypes.POINTER(ctypes.c_int64)), n,
            ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            max_len, nt, self.unk_id, self.cls_id, self.sep_id)
        if rc != 0:
            raise RuntimeError(f"native tokenizer failed (rc={rc})")
        return ids, lens

    def _encode_py(self, texts, max_len):
        n = len(texts)
        ids = np.zeros((n, max_len), np.int32)
        lens = np.zeros(n, np.int32)
        for r, text in enumerate(texts):
            row = []
            if self.cls_id >= 0:
                row.append(self.cls_id)
            # same whitespace set as the C++ is_space (space/tab/nl/cr)
            for word in re.split(rb"[ \t\n\r]+", text.encode("utf-8")):
                if not word:
                    continue
                pieces, pos, bad = [], 0, False
                while pos < len(word):
                    take = min(len(word) - pos, self._max_body)
                    pid = -1
                    while take > 0:
                        cand = word[pos:pos + take]
                        key = cand if pos == 0 else b"##" + cand
                        if key in self._bvocab:
                            pid = self._bvocab[key]
                            break
                        take -= 1
                    if pid < 0:
                        bad = True
                        break
                    pieces.append(pid)
                    pos += take
                row.extend([self.unk_id] if bad else pieces)
                if len(row) >= max_len:
                    break
            row = row[:max_len]
            if self.sep_id >= 0:
                if len(row) < max_len:
                    row.append(self.sep_id)
                else:
                    row[-1] = self.sep_id
            ids[r, :len(row)] = row
            lens[r] = len(row)
        return ids, lens

    def __del__(self):
        if getattr(self, "_handle", None) is not None and \
                getattr(self, "_lib", None) is not None:
            try:
                self._lib.ptk_free(self._handle)
            except Exception:
                pass
