"""Shared lazy g++ build/load for the native runtime components.

One implementation of the lock / stale-check / compile / dlopen pattern so
data_loader, image_ops and tokenizer can't drift: a component calls
`load_native("libx.so", "x.cpp", register)` and gets the CDLL (cached) or
None if the toolchain/compile fails — callers always keep a pure-Python
fallback.
"""
import ctypes
import hashlib
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_LIB_DIR = os.path.join(_HERE, "lib")
_CXX_DIR = os.path.join(_HERE, "cxx")
_lock = threading.Lock()
_cache = {}          # so_name -> (lib or None)
_errors = {}         # so_name -> exception from a failed build/load


def compile_so(sources, so_path, extra_flags=(), verbose=False):
    """Compile C++ sources into `so_path` atomically: g++ writes to a
    tmp path, then os.replace() publishes — a concurrent reader never
    dlopens a half-written library (shared by runtime components and
    utils.cpp_extension so the build flow can't drift)."""
    tmp = f"{so_path}.tmp.{os.getpid()}"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
           # libraries (-ljpeg etc.) must FOLLOW the sources for the
           # linker to resolve their undefined symbols
           *sources, "-o", tmp, *extra_flags]
    if verbose:
        print("[paddle_tpu build]", " ".join(cmd))
    try:
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(tmp, so_path)
    except subprocess.CalledProcessError as e:
        raise RuntimeError(
            f"{' '.join(cmd)} failed:\n"
            + e.stderr.decode(errors="replace")[-2000:]) from None
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def build_error(so_name):
    """The exception that made load_native return None for this
    component, or None (for error messages / debugging)."""
    return _errors.get(so_name)


def load_native(so_name, src_name, register, extra_flags=()):
    """Build (if stale) + dlopen a native component; returns the CDLL or
    None. `register(lib)` sets restype/argtypes once after loading.

    A prebuilt .so with no source alongside (e.g. a wheel that ships
    binaries only) is loaded as-is.  When the source IS present, staleness
    is decided by a recorded sha256 of the source (a `.srchash` stamp next
    to the .so) — mtimes are unreliable after a fresh git checkout, and a
    hash also rejects a foreign binary that happens to be newer."""
    with _lock:
        if so_name in _cache:
            return _cache[so_name]
        so_path = os.path.join(_LIB_DIR, so_name)
        src_path = os.path.join(_CXX_DIR, src_name)
        stamp_path = so_path + ".srchash"
        lib = None
        try:
            if os.path.exists(src_path):
                with open(src_path, "rb") as f:
                    src_hash = hashlib.sha256(f.read()).hexdigest()
                stamp = None
                if os.path.exists(stamp_path):
                    with open(stamp_path) as f:
                        stamp = f.read().strip()
                needs_build = not os.path.exists(so_path) or stamp != src_hash
            else:
                src_hash = None
                needs_build = not os.path.exists(so_path)
            if needs_build:
                os.makedirs(_LIB_DIR, exist_ok=True)
                compile_so([src_path], so_path, extra_flags)
                if src_hash is not None:
                    with open(stamp_path, "w") as f:
                        f.write(src_hash)
            lib = ctypes.CDLL(so_path)
            register(lib)
        except Exception as e:
            # keep the cause (incl. captured g++ stderr) for diagnostics;
            # consumers fall back to Python but can surface build_error()
            if isinstance(e, subprocess.CalledProcessError) and e.stderr:
                e = RuntimeError(
                    f"{' '.join(e.cmd)} failed:\n"
                    + e.stderr.decode(errors='replace')[-2000:])
            _errors[so_name] = e
            lib = None
        _cache[so_name] = lib
        return lib
