"""Native image ops behind ctypes — decode/resize/normalize off the GIL.

Reference counterpart: the C++ image preprocessing inside the reference's
DataLoader worker processes (fluid/operators/reader + PIL in workers). Here
the hot per-image path (JPEG decode -> bilinear resize -> CHW normalize) is
one C call per image, so thread-pool DataLoader workers scale past the GIL
even without process workers. Pure-Python (PIL/numpy) fallback throughout.
"""
import ctypes

import numpy as np

from ._build import load_native

__all__ = ["native_available", "decode_jpeg", "resize_bilinear",
           "normalize_chw", "decode_resize_normalize"]

_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")


def _register(lib):
    lib.pti_jpeg_info.restype = ctypes.c_int
    lib.pti_jpeg_info.argtypes = [
        ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int)]
    lib.pti_decode_jpeg.restype = ctypes.c_int
    lib.pti_decode_jpeg.argtypes = [ctypes.c_char_p, ctypes.c_int64, _u8p]
    lib.pti_resize_bilinear.restype = None
    lib.pti_resize_bilinear.argtypes = [
        _u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        _u8p, ctypes.c_int, ctypes.c_int]
    lib.pti_normalize_chw.restype = None
    lib.pti_normalize_chw.argtypes = [
        _u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        _f32p, _f32p, ctypes.c_float, _f32p]
    lib.pti_pipeline.restype = ctypes.c_int
    lib.pti_pipeline.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, ctypes.c_int,
        _f32p, _f32p, ctypes.c_float, _f32p]


def _get_lib():
    return load_native("libpti_image.so", "image_ops.cpp", _register,
                       extra_flags=("-ljpeg",))


def native_available():
    return _get_lib() is not None


def decode_jpeg(data):
    """JPEG bytes -> HWC uint8 ndarray (RGB or grayscale)."""
    lib = _get_lib()
    if lib is not None:
        h, w, c = ctypes.c_int(), ctypes.c_int(), ctypes.c_int()
        if lib.pti_jpeg_info(data, len(data), ctypes.byref(h), ctypes.byref(w),
                             ctypes.byref(c)) == 0:
            out = np.empty((h.value, w.value, c.value), np.uint8)
            if lib.pti_decode_jpeg(data, len(data), out) == 0:
                return out
    import io as _io

    from PIL import Image
    img = Image.open(_io.BytesIO(data))
    if img.mode not in ("RGB", "L"):
        img = img.convert("RGB")
    arr = np.asarray(img, np.uint8)
    return arr if arr.ndim == 3 else arr[:, :, None]


def resize_bilinear(img, size):
    """HWC uint8 -> HWC uint8, size=(oh, ow)."""
    oh, ow = size
    img = np.ascontiguousarray(img, np.uint8)
    if img.ndim == 2:
        img = img[:, :, None]
    h, w, c = img.shape
    if (h, w) == (oh, ow):
        return img
    lib = _get_lib()
    if lib is not None:
        out = np.empty((oh, ow, c), np.uint8)
        lib.pti_resize_bilinear(img, h, w, c, out, oh, ow)
        return out
    from PIL import Image
    pil = Image.fromarray(img if c > 1 else img[:, :, 0])
    out = np.asarray(pil.resize((ow, oh), Image.BILINEAR), np.uint8)
    return out if out.ndim == 3 else out[:, :, None]


def normalize_chw(img, mean, std, scale=1.0 / 255.0):
    """HWC uint8 -> CHW float32: (x*scale - mean) / std."""
    img = np.ascontiguousarray(img, np.uint8)
    if img.ndim == 2:
        img = img[:, :, None]
    h, w, c = img.shape
    mean = np.ascontiguousarray(np.broadcast_to(np.asarray(mean, np.float32), (c,)))
    std = np.ascontiguousarray(np.broadcast_to(np.asarray(std, np.float32), (c,)))
    lib = _get_lib()
    if lib is not None:
        out = np.empty((c, h, w), np.float32)
        lib.pti_normalize_chw(img, h, w, c, mean, std, np.float32(scale), out)
        return out
    return ((img.astype(np.float32) * scale
             - mean[None, None]) / std[None, None]).transpose(2, 0, 1)


def decode_resize_normalize(data, size, mean, std, scale=1.0 / 255.0):
    """Fused JPEG bytes -> CHW float32 (single C call when native)."""
    oh, ow = size
    mean = np.ascontiguousarray(np.broadcast_to(np.asarray(mean, np.float32), (3,)))
    std = np.ascontiguousarray(np.broadcast_to(np.asarray(std, np.float32), (3,)))
    lib = _get_lib()
    if lib is not None:
        out = np.empty((3, oh, ow), np.float32)
        c = lib.pti_pipeline(data, len(data), oh, ow, mean, std,
                             np.float32(scale), out)
        if c == 3:
            return out
        if c == 1:
            return out[:1]
    img = decode_jpeg(data)
    img = resize_bilinear(img, size)
    return normalize_chw(img, mean[:img.shape[2]], std[:img.shape[2]], scale)
