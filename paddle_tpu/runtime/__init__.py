"""Native runtime — C++ components behind ctypes, with pure-Python fallbacks.

Reference counterpart: paddle/fluid's C++ reader/feeder machinery. First
component: the token-stream loader feeding GPT pretraining (mmap + worker
pool + prefetch ring, all off-GIL).
"""
import ctypes
import os

import numpy as np

__all__ = ["NativeTokenLoader", "PyTokenLoader", "TokenLoader", "native_available"]

from ._build import load_native  # noqa: E402


def _register(lib):
    lib.ptl_open.restype = ctypes.c_void_p
    lib.ptl_open.argtypes = [ctypes.c_char_p]
    lib.ptl_num_tokens.restype = ctypes.c_int64
    lib.ptl_num_tokens.argtypes = [ctypes.c_void_p]
    lib.ptl_start.restype = ctypes.c_int
    lib.ptl_start.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                              ctypes.c_int64, ctypes.c_int, ctypes.c_int,
                              ctypes.c_uint64]
    lib.ptl_next.restype = ctypes.c_int
    lib.ptl_next.argtypes = [ctypes.c_void_p,
                             np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")]
    lib.ptl_stop.argtypes = [ctypes.c_void_p]
    lib.ptl_close.argtypes = [ctypes.c_void_p]


def _get_lib():
    return load_native("libptl_loader.so", "data_loader.cpp", _register)


def native_available():
    return _get_lib() is not None


class NativeTokenLoader:
    """Endless sampler of [batch, seq+1] windows from a flat int32 token file
    (C++ mmap + worker pool; batches appear without touching the GIL)."""

    def __init__(self, path, batch_size, seq_len, num_workers=2,
                 prefetch_depth=4, seed=0):
        lib = _get_lib()
        if lib is None:
            from ._build import build_error
            raise RuntimeError(
                f"native loader unavailable: "
                f"{build_error('libptl_loader.so')}")
        self._lib = lib
        self._h = lib.ptl_open(os.fsencode(path))
        if not self._h:
            raise IOError(f"cannot open token file {path}")
        self.batch_size = batch_size
        self.seq_len = seq_len
        rc = lib.ptl_start(self._h, batch_size, seq_len, num_workers,
                           prefetch_depth, seed)
        if rc != 0:
            raise RuntimeError(f"ptl_start failed rc={rc}")

    @property
    def num_tokens(self):
        return self._lib.ptl_num_tokens(self._h)

    def next(self):
        out = np.empty((self.batch_size, self.seq_len + 1), np.int32)
        rc = self._lib.ptl_next(self._h, out)
        if rc != 0:
            raise RuntimeError("loader stopped")
        return out

    def __iter__(self):
        while True:
            yield self.next()

    def close(self):
        if self._h:
            self._lib.ptl_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class PyTokenLoader:
    """numpy.memmap fallback with identical semantics."""

    def __init__(self, path, batch_size, seq_len, num_workers=0,
                 prefetch_depth=0, seed=0):
        self.tokens = np.memmap(path, np.int32, "r")
        self.batch_size = batch_size
        self.seq_len = seq_len
        self.rng = np.random.default_rng(seed)

    @property
    def num_tokens(self):
        return self.tokens.shape[0]

    def next(self):
        n = self.seq_len + 1
        starts = self.rng.integers(0, self.num_tokens - n, self.batch_size)
        return np.stack([np.asarray(self.tokens[s:s + n]) for s in starts])

    def __iter__(self):
        while True:
            yield self.next()

    def close(self):
        pass


def TokenLoader(path, batch_size, seq_len, **kw):
    """Native if the toolchain built the .so, else the python fallback."""
    if native_available():
        return NativeTokenLoader(path, batch_size, seq_len, **kw)
    return PyTokenLoader(path, batch_size, seq_len, **kw)


from .tokenizer import (  # noqa: E402,F401
    WordPieceTokenizer,
    native_tokenizer_available,
)

__all__ += ["WordPieceTokenizer", "native_tokenizer_available"]
