"""Sparse tensors — reference python/paddle/sparse (COO/CSR basics).
XLA has no native sparse layout; COO here is (indices, values, shape) with
dense fallbacks — correct semantics, dense-speed compute (fine for the
API-parity tier; TPU-efficient block-sparse lives in the Pallas kernel set).
"""
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor", "SparseCsrTensor",
           "matmul", "addmm", "relu", "tanh", "to_dense", "is_same_shape"]


class SparseCooTensor:
    def __init__(self, indices, values, shape):
        self.indices = indices if isinstance(indices, Tensor) else Tensor(jnp.asarray(indices))
        self.values = values if isinstance(values, Tensor) else Tensor(jnp.asarray(values))
        self.shape = list(shape)

    def to_dense(self):
        idx = np.asarray(self.indices._value)
        vals = self.values._value
        out = jnp.zeros(tuple(self.shape), vals.dtype)
        out = out.at[tuple(idx)].add(vals)
        return Tensor(out)

    def nnz(self):
        return self.values.shape[0]

    def coalesce(self):
        return self

    def __repr__(self):
        return f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()})"


class SparseCsrTensor:
    def __init__(self, crows, cols, values, shape):
        self.crows = crows if isinstance(crows, Tensor) else Tensor(jnp.asarray(crows))
        self.cols = cols if isinstance(cols, Tensor) else Tensor(jnp.asarray(cols))
        self.values = values if isinstance(values, Tensor) else Tensor(jnp.asarray(values))
        self.shape = list(shape)

    def to_dense(self):
        crows = np.asarray(self.crows._value)
        cols = np.asarray(self.cols._value)
        vals = self.values._value
        rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
        out = jnp.zeros(tuple(self.shape), vals.dtype)
        out = out.at[rows, cols].add(vals)
        return Tensor(out)

    def __repr__(self):
        return f"SparseCsrTensor(shape={self.shape}, nnz={self.values.shape[0]})"


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None, stop_gradient=True):
    if shape is None:
        idx = np.asarray(indices.numpy() if isinstance(indices, Tensor) else indices)
        shape = (idx.max(axis=1) + 1).tolist()
    return SparseCooTensor(indices, values, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None, stop_gradient=True):
    return SparseCsrTensor(crows, cols, values, shape)


def to_dense(x):
    return x.to_dense() if hasattr(x, "to_dense") else x


def matmul(x, y, name=None):
    xd = to_dense(x)
    yd = to_dense(y)
    from ..tensor.math import matmul as dense_matmul
    return dense_matmul(xd, yd)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    from ..tensor.math import addmm as dense_addmm
    return dense_addmm(to_dense(input), to_dense(x), to_dense(y), beta, alpha)


def relu(x, name=None):
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor(x.indices, Tensor(jnp.maximum(x.values._value, 0)), x.shape)
    from ..nn.functional import relu as dense_relu
    return dense_relu(x)


def tanh(x, name=None):
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor(x.indices, Tensor(jnp.tanh(x.values._value)), x.shape)
    from ..tensor.math import tanh as dense_tanh
    return dense_tanh(x)


def is_same_shape(x, y):
    return list(x.shape) == list(y.shape)
