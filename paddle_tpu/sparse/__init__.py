"""Sparse tensors — reference python/paddle/sparse (COO/CSR, phi sparse
kernels). XLA has no native sparse layout; compute here is index-based:

- matmul/addmm: gather + segment_sum over the nonzero pattern (O(nnz·N)),
  never materializing the dense operand — reference phi/kernels/sparse/
  matmul_kernel semantics.
- masked_matmul: SDDMM — dot products only at the mask's nonzeros.
- Conv3D/SubmConv3D: rulebook gather-GEMM-scatter (reference
  phi/kernels/sparse/conv_kernel), O(nnz·K³·C) instead of O(volume).

Zero-preserving unary ops act on the value array directly.
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply_op

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor", "SparseCsrTensor",
           "matmul", "addmm", "relu", "tanh", "to_dense", "is_same_shape"]


class SparseCooTensor:
    def __init__(self, indices, values, shape):
        self.indices = indices if isinstance(indices, Tensor) else Tensor(jnp.asarray(indices))
        self.values = values if isinstance(values, Tensor) else Tensor(jnp.asarray(values))
        self.shape = list(shape)

    def to_dense(self):
        idx = np.asarray(self.indices._value)
        vals = self.values._value
        out = jnp.zeros(tuple(self.shape), vals.dtype)
        out = out.at[tuple(idx)].add(vals)
        return Tensor(out)

    def nnz(self):
        return self.values.shape[0]

    def coalesce(self):
        return self

    def __repr__(self):
        return f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()})"


class SparseCsrTensor:
    def __init__(self, crows, cols, values, shape):
        self.crows = crows if isinstance(crows, Tensor) else Tensor(jnp.asarray(crows))
        self.cols = cols if isinstance(cols, Tensor) else Tensor(jnp.asarray(cols))
        self.values = values if isinstance(values, Tensor) else Tensor(jnp.asarray(values))
        self.shape = list(shape)

    def to_dense(self):
        crows = np.asarray(self.crows._value)
        cols = np.asarray(self.cols._value)
        vals = self.values._value
        rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
        out = jnp.zeros(tuple(self.shape), vals.dtype)
        out = out.at[rows, cols].add(vals)
        return Tensor(out)

    def __repr__(self):
        return f"SparseCsrTensor(shape={self.shape}, nnz={self.values.shape[0]})"


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None, stop_gradient=True):
    if shape is None:
        idx = np.asarray(indices.numpy() if isinstance(indices, Tensor) else indices)
        shape = (idx.max(axis=1) + 1).tolist()
    return SparseCooTensor(indices, values, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None, stop_gradient=True):
    return SparseCsrTensor(crows, cols, values, shape)


def to_dense(x):
    return x.to_dense() if hasattr(x, "to_dense") else x


def _coo_rows_cols(x):
    idx = x.indices._value
    return idx[0], idx[1]


def _csr_rows_cols(x):
    crows = x.crows._value
    nnz = x.cols.shape[0]
    rows = jnp.searchsorted(crows, jnp.arange(nnz), side="right") - 1
    return rows, x.cols._value


def _spmm(x, dense_t):
    """sparse[M,K] @ dense[K,N] via gather + segment_sum — no densify.
    Differentiable in both the sparse values and the dense operand."""
    if isinstance(x, SparseCooTensor):
        rows, cols = _coo_rows_cols(x)
    else:
        rows, cols = _csr_rows_cols(x)
    m = int(x.shape[0])
    rows = jnp.asarray(rows, jnp.int32)
    cols = jnp.asarray(cols, jnp.int32)

    def f(vals, d):
        contrib = vals[:, None] * d[cols] if d.ndim == 2 else vals * d[cols]
        return jax.ops.segment_sum(contrib, rows, num_segments=m)

    return apply_op(f, x.values, dense_t)


def matmul(x, y, name=None):
    """sparse @ dense (COO or CSR left operand) — reference
    python/paddle/sparse/functional/math.py:matmul backed by phi spmm."""
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)) and \
            not isinstance(y, (SparseCooTensor, SparseCsrTensor)) and \
            len(x.shape) == 2:
        return _spmm(x, y if isinstance(y, Tensor) else Tensor(jnp.asarray(y)))
    xd = to_dense(x)
    yd = to_dense(y)
    from ..tensor.math import matmul as dense_matmul
    return dense_matmul(xd, yd)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta*input + alpha*(x@y) with sparse x — spmm-based."""
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)) and \
            not isinstance(y, (SparseCooTensor, SparseCsrTensor)) and \
            len(x.shape) == 2:
        prod = _spmm(x, y if isinstance(y, Tensor) else Tensor(jnp.asarray(y)))
        return apply_op(lambda i, p: beta * i + alpha * p, to_dense(input), prod)
    from ..tensor.math import addmm as dense_addmm
    return dense_addmm(to_dense(input), to_dense(x), to_dense(y), beta, alpha)


def relu(x, name=None):
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor(x.indices, Tensor(jnp.maximum(x.values._value, 0)), x.shape)
    from ..nn.functional import relu as dense_relu
    return dense_relu(x)


def tanh(x, name=None):
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor(x.indices, Tensor(jnp.tanh(x.values._value)), x.shape)
    from ..tensor.math import tanh as dense_tanh
    return dense_tanh(x)


def is_same_shape(x, y):
    return list(x.shape) == list(y.shape)


def _unary_coo(fn):
    def op(x, name=None):
        if isinstance(x, SparseCooTensor):
            return SparseCooTensor(x.indices, Tensor(fn(x.values._value)), x.shape)
        if isinstance(x, SparseCsrTensor):
            return SparseCsrTensor(x.crows, x.cols, Tensor(fn(x.values._value)), x.shape)
        from ..framework.core import apply_op
        return apply_op(fn, x)
    return op


# zero-preserving unary ops on sparse values (reference sparse/functional)
sqrt = _unary_coo(jnp.sqrt)
sin = _unary_coo(jnp.sin)
square = _unary_coo(jnp.square)
abs = _unary_coo(jnp.abs)  # noqa: A001
neg = _unary_coo(jnp.negative)
expm1 = _unary_coo(jnp.expm1)
log1p = _unary_coo(jnp.log1p)
asin = _unary_coo(jnp.arcsin)
atan = _unary_coo(jnp.arctan)
sinh = _unary_coo(jnp.sinh)
asinh = _unary_coo(jnp.arcsinh)
atanh = _unary_coo(jnp.arctanh)
pow = _unary_coo(None)  # replaced below  # noqa: A001


def pow(x, factor, name=None):  # noqa: F811,A001
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor(x.indices, Tensor(jnp.power(x.values._value, factor)), x.shape)
    if isinstance(x, SparseCsrTensor):
        return SparseCsrTensor(x.crows, x.cols, Tensor(jnp.power(x.values._value, factor)), x.shape)
    from ..framework.core import apply_op
    return apply_op(lambda v: jnp.power(v, factor), x)


def cast(x, index_dtype=None, value_dtype=None):
    if isinstance(x, SparseCooTensor):
        idx = x.indices.astype(index_dtype) if index_dtype else x.indices
        vals = x.values.astype(value_dtype) if value_dtype else x.values
        return SparseCooTensor(idx, vals, x.shape)
    if isinstance(x, SparseCsrTensor):
        vals = x.values.astype(value_dtype) if value_dtype else x.values
        return SparseCsrTensor(x.crows, x.cols, vals, x.shape)
    return x.astype(value_dtype)


def add(x, y, name=None):
    return _ewise(x, y, jnp.add)


def subtract(x, y, name=None):
    return _ewise(x, y, jnp.subtract)


def multiply(x, y, name=None):
    return _ewise(x, y, jnp.multiply)


def divide(x, y, name=None):
    return _ewise(x, y, jnp.divide)


def _ewise(x, y, fn):
    """Elementwise over two same-pattern sparse tensors (dense fallback
    when patterns differ — correct, not compressed)."""
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        if np.array_equal(np.asarray(x.indices._value), np.asarray(y.indices._value)):
            return SparseCooTensor(x.indices, Tensor(fn(x.values._value, y.values._value)), x.shape)
        d = fn(x.to_dense()._value, y.to_dense()._value)
        return dense_to_coo(Tensor(d))
    from ..framework.core import apply_op
    return apply_op(fn, to_dense(x), to_dense(y))


def dense_to_coo(x, sparse_dim=None):
    """Tensor -> SparseCooTensor (reference Tensor.to_sparse_coo). With
    sparse_dim < ndim, indices cover the leading sparse_dim axes and values
    keep the trailing dense axes (e.g. NDHWC with sparse_dim=4 -> per-site
    channel vectors)."""
    arr = np.asarray(x._value if isinstance(x, Tensor) else x)
    if sparse_dim is None or sparse_dim == arr.ndim:
        idx = np.stack(np.nonzero(arr))
        vals = arr[tuple(idx)]
        return SparseCooTensor(idx, vals, arr.shape)
    lead = arr.reshape(arr.shape[:sparse_dim] + (-1,))
    active = np.abs(lead).sum(axis=-1) != 0
    idx = np.stack(np.nonzero(active))
    vals = arr[tuple(idx)]
    return SparseCooTensor(idx, vals, arr.shape)


def coo_to_csr(x):
    """2-D COO -> CSR."""
    if len(x.shape) != 2:
        raise ValueError("CSR requires 2-D")
    idx = np.asarray(x.indices._value)
    order = np.lexsort((idx[1], idx[0]))
    rows, cols = idx[0][order], idx[1][order]
    vals = np.asarray(x.values._value)[order]
    crows = np.zeros(x.shape[0] + 1, np.int64)
    np.add.at(crows, rows + 1, 1)
    crows = np.cumsum(crows)
    return SparseCsrTensor(crows, cols, vals, x.shape)


def masked_matmul(x, y, mask, name=None):
    """SDDMM — dense@dense sampled at mask's sparsity pattern (reference
    sparse.masked_matmul / phi sddmm): computes ONLY the nnz dot products,
    O(nnz·K) instead of O(M·N·K)."""
    if isinstance(mask, SparseCooTensor) and len(mask.shape) == 2:
        idx = np.asarray(mask.indices._value)
        rows, cols = jnp.asarray(idx[0]), jnp.asarray(idx[1])
        xt = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
        yt = y if isinstance(y, Tensor) else Tensor(jnp.asarray(y))
        vals = apply_op(
            lambda a, b: jnp.einsum("nk,nk->n", a[rows], b.T[cols]), xt, yt)
        return SparseCooTensor(idx, vals, mask.shape)
    from ..tensor.math import matmul as dense_matmul
    d = dense_matmul(to_dense(x), to_dense(y))
    if isinstance(mask, SparseCooTensor):   # N-D mask: dense-then-sample
        idx = np.asarray(mask.indices._value)
        vals = d._value[tuple(idx)]
        return SparseCooTensor(idx, vals, mask.shape)
    return d


# -- sparse.nn layer namespace (reference python/paddle/sparse/layer) -------
class _SparseNN:
    class ReLU:
        def __call__(self, x):
            return relu(x)

    class BatchNorm:
        """BatchNorm over sparse values (reference sparse/layer/norm.py):
        normalizes the value array channel-wise."""

        def __init__(self, num_features, momentum=0.9, epsilon=1e-5):
            self.num_features = num_features
            self.eps = epsilon

        def __call__(self, x):
            vals = x.values._value
            mean = vals.mean(axis=0, keepdims=True)
            var = vals.var(axis=0, keepdims=True)
            out = (vals - mean) / jnp.sqrt(var + self.eps)
            return SparseCooTensor(x.indices, Tensor(out), x.shape)

    class MaxPool3D:
        def __init__(self, kernel_size, stride=None, padding=0):
            self.kernel_size = kernel_size
            self.stride = stride or kernel_size
            self.padding = padding

        def __call__(self, x):
            from ..nn.functional.pooling import max_pool3d
            dense = to_dense(x)   # NDHWC (reference sparse pooling is channel-last)
            out = max_pool3d(dense, self.kernel_size, self.stride, self.padding,
                             data_format="NDHWC")
            return dense_to_coo(out, sparse_dim=4)


def _triple(v):
    return list(v) if isinstance(v, (list, tuple)) else [v] * 3


def _conv3d_rulebook(idx, in_shape, ks, stride, pad, dil, subm):
    """Build the gather-GEMM-scatter rulebook (reference
    phi/kernels/sparse/gpu/conv_kernel.cu rulebook construction, done
    host-side in numpy): for every kernel offset, the (input_row,
    output_row) pairs it contributes, plus the output index set."""
    n, d, h, w = (a.astype(np.int64) for a in idx)
    D, H, W = (int(s) for s in in_shape[1:4])
    st, pd, dl = _triple(stride), _triple(pad), _triple(dil)
    if subm:
        od, oh, ow = D, H, W
    else:
        od = (D + 2 * pd[0] - dl[0] * (ks[0] - 1) - 1) // st[0] + 1
        oh = (H + 2 * pd[1] - dl[1] * (ks[1] - 1) - 1) // st[1] + 1
        ow = (W + 2 * pd[2] - dl[2] * (ks[2] - 1) - 1) // st[2] + 1

    def lid(nn, dd, hh, ww):
        return ((nn * od + dd) * oh + hh) * ow + ww

    per_offset = []          # (k_linear, in_rows, out_lids)
    all_lids = []
    for kd in range(ks[0]):
        for kh in range(ks[1]):
            for kw in range(ks[2]):
                zd = d + pd[0] - kd * dl[0]
                zh = h + pd[1] - kh * dl[1]
                zw = w + pd[2] - kw * dl[2]
                ok = ((zd % st[0] == 0) & (zh % st[1] == 0) & (zw % st[2] == 0))
                zd, zh, zw = zd // st[0], zh // st[1], zw // st[2]
                ok &= ((zd >= 0) & (zd < od) & (zh >= 0) & (zh < oh)
                       & (zw >= 0) & (zw < ow))
                rows = np.nonzero(ok)[0]
                if rows.size == 0:
                    continue
                lids = lid(n[rows], zd[rows], zh[rows], zw[rows])
                k_lin = (kd * ks[1] + kh) * ks[2] + kw
                per_offset.append((k_lin, rows, lids))
                if not subm:
                    all_lids.append(lids)

    if subm:
        # outputs restricted to the input's active sites, in input order
        in_lids = lid(n, d, h, w)
        uniq_sorted = np.sort(in_lids)
        order = np.argsort(in_lids, kind="stable")
        rules = []
        for k_lin, rows, lids in per_offset:
            pos = np.searchsorted(uniq_sorted, lids)
            hit = (pos < uniq_sorted.size) & (uniq_sorted[np.minimum(
                pos, uniq_sorted.size - 1)] == lids)
            rows, pos = rows[hit], pos[hit]
            rules.append((k_lin, rows, order[pos]))
        out_idx = idx.copy()
        n_out = idx.shape[1]
    else:
        uniq = (np.unique(np.concatenate(all_lids)) if all_lids
                else np.zeros(0, np.int64))
        rules = [(k_lin, rows, np.searchsorted(uniq, lids))
                 for k_lin, rows, lids in per_offset]
        n_out = uniq.size
        rem, ww_ = np.divmod(uniq, ow)
        rem, hh_ = np.divmod(rem, oh)
        nn_, dd_ = np.divmod(rem, od)
        out_idx = np.stack([nn_, dd_, hh_, ww_])
    return rules, out_idx, (od, oh, ow), n_out


class _SparseConv3DBase:
    """Sparse 3-D convolution over NDHWC COO tensors — reference
    python/paddle/sparse/layer/conv.py:_Conv3D backed by phi sparse conv
    kernels. Computes gather-GEMM-scatter over a host-built rulebook:
    O(nnz·K³·C·C') work regardless of volume, with the per-offset GEMMs
    on the MXU. groups>1 falls back to the dense XLA conv."""

    _subm = False

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        from ..nn.initializer import XavierUniform
        from ..framework.core import Parameter
        from ..framework.random import next_key
        import jax
        if data_format != "NDHWC":
            raise ValueError("sparse Conv3D only supports NDHWC")
        self.in_channels = in_channels
        self.out_channels = out_channels
        ks = kernel_size if isinstance(kernel_size, (list, tuple)) else [kernel_size] * 3
        self.kernel_size = list(ks)
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        fan_in = in_channels * int(np.prod(self.kernel_size))
        bound = float(np.sqrt(6.0 / max(fan_in + out_channels * int(np.prod(self.kernel_size)), 1)))
        # weight layout matches reference: (kd, kh, kw, in_c/groups, out_c)
        wshape = self.kernel_size + [in_channels // groups, out_channels]
        self.weight = Parameter(jax.random.uniform(next_key(), wshape, jnp.float32,
                                                   -bound, bound))
        self.bias = Parameter(jnp.zeros([out_channels], jnp.float32))             if bias_attr is not False else None

    def parameters(self):
        return [self.weight] + ([self.bias] if self.bias is not None else [])

    def __call__(self, x):
        return self.forward(x)

    def forward(self, x):
        if self._subm and any(s != 1 for s in _triple(self.stride)):
            raise ValueError("SubmConv3D requires stride=1 (submanifold "
                             "outputs live on the input's active sites)")
        if self.groups != 1:
            return self._forward_dense(x)
        idx = np.asarray(x.indices._value)
        rules, out_idx, (od, oh, ow), n_out = _conv3d_rulebook(
            idx, x.shape, self.kernel_size, self.stride, self.padding,
            self.dilation, self._subm)
        out_c = self.out_channels
        k_total = int(np.prod(self.kernel_size))
        in_rows = [jnp.asarray(r, jnp.int32) for _, r, _ in rules]
        out_rows = [jnp.asarray(o, jnp.int32) for _, _, o in rules]
        k_ids = [k for k, _, _ in rules]

        def compute(vals, w, *maybe_b):
            wk = w.reshape(k_total, self.in_channels, out_c)
            out = jnp.zeros((n_out, out_c), vals.dtype)
            for k, ir, orow in zip(k_ids, in_rows, out_rows):
                out = out.at[orow].add(vals[ir] @ wk[k])
            if maybe_b:
                out = out + maybe_b[0]
            return out

        args = (x.values, self.weight) + ((self.bias,) if self.bias is not None else ())
        out_vals = apply_op(compute, *args)
        out_shape = [x.shape[0], od, oh, ow, out_c]
        return SparseCooTensor(out_idx, out_vals, out_shape)

    def _forward_dense(self, x):
        from ..nn.functional.conv import conv3d
        dense = to_dense(x)                           # (N, D, H, W, C)
        # our conv weights are (out_c, in_c/groups, kd, kh, kw)
        w = Tensor(jnp.transpose(self.weight._value, (4, 3, 0, 1, 2)))
        out = conv3d(dense, w, self.bias, stride=self.stride, padding=self.padding,
                     dilation=self.dilation, groups=self.groups, data_format="NDHWC")
        if self._subm:
            # submanifold: keep only the input's active sites
            mask_vals = jnp.ones((x.indices.shape[1], 1), jnp.float32)
            mask = SparseCooTensor(x.indices, Tensor(mask_vals),
                                   list(x.shape[:-1]) + [1])
            dm = to_dense(mask)._value
            out = Tensor(out._value * (dm > 0))
        return dense_to_coo(out, sparse_dim=4)


class Conv3D(_SparseConv3DBase):
    _subm = False


class SubmConv3D(_SparseConv3DBase):
    _subm = True


_SparseNN.Conv3D = Conv3D
_SparseNN.SubmConv3D = SubmConv3D

nn = _SparseNN()

# v2.3 exposes the sparse layers at paddle.sparse top level too
ReLU = _SparseNN.ReLU
BatchNorm = _SparseNN.BatchNorm
MaxPool3D = _SparseNN.MaxPool3D

__all__ += ["sqrt", "sin", "square", "abs", "neg", "expm1", "log1p", "asin",
            "atan", "sinh", "asinh", "atanh", "pow", "cast", "add", "subtract",
            "multiply", "divide", "masked_matmul", "dense_to_coo", "coo_to_csr",
            "nn"]
