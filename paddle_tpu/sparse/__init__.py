"""Sparse tensors — reference python/paddle/sparse (COO/CSR basics).
XLA has no native sparse layout; COO here is (indices, values, shape) with
dense fallbacks — correct semantics, dense-speed compute (fine for the
API-parity tier; TPU-efficient block-sparse lives in the Pallas kernel set).
"""
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor", "SparseCsrTensor",
           "matmul", "addmm", "relu", "tanh", "to_dense", "is_same_shape"]


class SparseCooTensor:
    def __init__(self, indices, values, shape):
        self.indices = indices if isinstance(indices, Tensor) else Tensor(jnp.asarray(indices))
        self.values = values if isinstance(values, Tensor) else Tensor(jnp.asarray(values))
        self.shape = list(shape)

    def to_dense(self):
        idx = np.asarray(self.indices._value)
        vals = self.values._value
        out = jnp.zeros(tuple(self.shape), vals.dtype)
        out = out.at[tuple(idx)].add(vals)
        return Tensor(out)

    def nnz(self):
        return self.values.shape[0]

    def coalesce(self):
        return self

    def __repr__(self):
        return f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()})"


class SparseCsrTensor:
    def __init__(self, crows, cols, values, shape):
        self.crows = crows if isinstance(crows, Tensor) else Tensor(jnp.asarray(crows))
        self.cols = cols if isinstance(cols, Tensor) else Tensor(jnp.asarray(cols))
        self.values = values if isinstance(values, Tensor) else Tensor(jnp.asarray(values))
        self.shape = list(shape)

    def to_dense(self):
        crows = np.asarray(self.crows._value)
        cols = np.asarray(self.cols._value)
        vals = self.values._value
        rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
        out = jnp.zeros(tuple(self.shape), vals.dtype)
        out = out.at[rows, cols].add(vals)
        return Tensor(out)

    def __repr__(self):
        return f"SparseCsrTensor(shape={self.shape}, nnz={self.values.shape[0]})"


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None, stop_gradient=True):
    if shape is None:
        idx = np.asarray(indices.numpy() if isinstance(indices, Tensor) else indices)
        shape = (idx.max(axis=1) + 1).tolist()
    return SparseCooTensor(indices, values, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None, stop_gradient=True):
    return SparseCsrTensor(crows, cols, values, shape)


def to_dense(x):
    return x.to_dense() if hasattr(x, "to_dense") else x


def matmul(x, y, name=None):
    xd = to_dense(x)
    yd = to_dense(y)
    from ..tensor.math import matmul as dense_matmul
    return dense_matmul(xd, yd)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    from ..tensor.math import addmm as dense_addmm
    return dense_addmm(to_dense(input), to_dense(x), to_dense(y), beta, alpha)


def relu(x, name=None):
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor(x.indices, Tensor(jnp.maximum(x.values._value, 0)), x.shape)
    from ..nn.functional import relu as dense_relu
    return dense_relu(x)


def tanh(x, name=None):
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor(x.indices, Tensor(jnp.tanh(x.values._value)), x.shape)
    from ..tensor.math import tanh as dense_tanh
    return dense_tanh(x)


def is_same_shape(x, y):
    return list(x.shape) == list(y.shape)


def _unary_coo(fn):
    def op(x, name=None):
        if isinstance(x, SparseCooTensor):
            return SparseCooTensor(x.indices, Tensor(fn(x.values._value)), x.shape)
        if isinstance(x, SparseCsrTensor):
            return SparseCsrTensor(x.crows, x.cols, Tensor(fn(x.values._value)), x.shape)
        from ..framework.core import apply_op
        return apply_op(fn, x)
    return op


# zero-preserving unary ops on sparse values (reference sparse/functional)
sqrt = _unary_coo(jnp.sqrt)
sin = _unary_coo(jnp.sin)
square = _unary_coo(jnp.square)
abs = _unary_coo(jnp.abs)  # noqa: A001
neg = _unary_coo(jnp.negative)
expm1 = _unary_coo(jnp.expm1)
log1p = _unary_coo(jnp.log1p)
asin = _unary_coo(jnp.arcsin)
atan = _unary_coo(jnp.arctan)
sinh = _unary_coo(jnp.sinh)
asinh = _unary_coo(jnp.arcsinh)
atanh = _unary_coo(jnp.arctanh)
pow = _unary_coo(None)  # replaced below  # noqa: A001


def pow(x, factor, name=None):  # noqa: F811,A001
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor(x.indices, Tensor(jnp.power(x.values._value, factor)), x.shape)
    if isinstance(x, SparseCsrTensor):
        return SparseCsrTensor(x.crows, x.cols, Tensor(jnp.power(x.values._value, factor)), x.shape)
    from ..framework.core import apply_op
    return apply_op(lambda v: jnp.power(v, factor), x)


def cast(x, index_dtype=None, value_dtype=None):
    if isinstance(x, SparseCooTensor):
        idx = x.indices.astype(index_dtype) if index_dtype else x.indices
        vals = x.values.astype(value_dtype) if value_dtype else x.values
        return SparseCooTensor(idx, vals, x.shape)
    if isinstance(x, SparseCsrTensor):
        vals = x.values.astype(value_dtype) if value_dtype else x.values
        return SparseCsrTensor(x.crows, x.cols, vals, x.shape)
    return x.astype(value_dtype)


def add(x, y, name=None):
    return _ewise(x, y, jnp.add)


def subtract(x, y, name=None):
    return _ewise(x, y, jnp.subtract)


def multiply(x, y, name=None):
    return _ewise(x, y, jnp.multiply)


def divide(x, y, name=None):
    return _ewise(x, y, jnp.divide)


def _ewise(x, y, fn):
    """Elementwise over two same-pattern sparse tensors (dense fallback
    when patterns differ — correct, not compressed)."""
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        if np.array_equal(np.asarray(x.indices._value), np.asarray(y.indices._value)):
            return SparseCooTensor(x.indices, Tensor(fn(x.values._value, y.values._value)), x.shape)
        d = fn(x.to_dense()._value, y.to_dense()._value)
        return dense_to_coo(Tensor(d))
    from ..framework.core import apply_op
    return apply_op(fn, to_dense(x), to_dense(y))


def dense_to_coo(x, sparse_dim=None):
    """Tensor -> SparseCooTensor (reference Tensor.to_sparse_coo). With
    sparse_dim < ndim, indices cover the leading sparse_dim axes and values
    keep the trailing dense axes (e.g. NDHWC with sparse_dim=4 -> per-site
    channel vectors)."""
    arr = np.asarray(x._value if isinstance(x, Tensor) else x)
    if sparse_dim is None or sparse_dim == arr.ndim:
        idx = np.stack(np.nonzero(arr))
        vals = arr[tuple(idx)]
        return SparseCooTensor(idx, vals, arr.shape)
    lead = arr.reshape(arr.shape[:sparse_dim] + (-1,))
    active = np.abs(lead).sum(axis=-1) != 0
    idx = np.stack(np.nonzero(active))
    vals = arr[tuple(idx)]
    return SparseCooTensor(idx, vals, arr.shape)


def coo_to_csr(x):
    """2-D COO -> CSR."""
    if len(x.shape) != 2:
        raise ValueError("CSR requires 2-D")
    idx = np.asarray(x.indices._value)
    order = np.lexsort((idx[1], idx[0]))
    rows, cols = idx[0][order], idx[1][order]
    vals = np.asarray(x.values._value)[order]
    crows = np.zeros(x.shape[0] + 1, np.int64)
    np.add.at(crows, rows + 1, 1)
    crows = np.cumsum(crows)
    return SparseCsrTensor(crows, cols, vals, x.shape)


def masked_matmul(x, y, mask, name=None):
    """Dense@dense restricted to mask's sparsity pattern (reference
    sparse.masked_matmul): compute dense then sample — XLA fuses."""
    from ..tensor.math import matmul as dense_matmul
    d = dense_matmul(to_dense(x), to_dense(y))
    if isinstance(mask, SparseCooTensor):
        idx = np.asarray(mask.indices._value)
        vals = d._value[tuple(idx)]
        return SparseCooTensor(idx, vals, mask.shape)
    return d


# -- sparse.nn layer namespace (reference python/paddle/sparse/layer) -------
class _SparseNN:
    class ReLU:
        def __call__(self, x):
            return relu(x)

    class BatchNorm:
        """BatchNorm over sparse values (reference sparse/layer/norm.py):
        normalizes the value array channel-wise."""

        def __init__(self, num_features, momentum=0.9, epsilon=1e-5):
            self.num_features = num_features
            self.eps = epsilon

        def __call__(self, x):
            vals = x.values._value
            mean = vals.mean(axis=0, keepdims=True)
            var = vals.var(axis=0, keepdims=True)
            out = (vals - mean) / jnp.sqrt(var + self.eps)
            return SparseCooTensor(x.indices, Tensor(out), x.shape)

    class MaxPool3D:
        def __init__(self, kernel_size, stride=None, padding=0):
            self.kernel_size = kernel_size
            self.stride = stride or kernel_size
            self.padding = padding

        def __call__(self, x):
            from ..nn.functional.pooling import max_pool3d
            dense = to_dense(x)   # NDHWC (reference sparse pooling is channel-last)
            out = max_pool3d(dense, self.kernel_size, self.stride, self.padding,
                             data_format="NDHWC")
            return dense_to_coo(out, sparse_dim=4)


class _SparseConv3DBase:
    """Sparse 3-D convolution over NDHWC COO tensors — reference
    python/paddle/sparse/layer/conv.py:_Conv3D. Computes via densify →
    XLA conv → re-sparsify; on TPU the dense conv IS the fast path (MXU),
    gather/scatter sparse kernels are not."""

    _subm = False

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        from ..nn.initializer import XavierUniform
        from ..framework.core import Parameter
        from ..framework.random import next_key
        import jax
        if data_format != "NDHWC":
            raise ValueError("sparse Conv3D only supports NDHWC")
        self.in_channels = in_channels
        self.out_channels = out_channels
        ks = kernel_size if isinstance(kernel_size, (list, tuple)) else [kernel_size] * 3
        self.kernel_size = list(ks)
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        fan_in = in_channels * int(np.prod(self.kernel_size))
        bound = float(np.sqrt(6.0 / max(fan_in + out_channels * int(np.prod(self.kernel_size)), 1)))
        # weight layout matches reference: (kd, kh, kw, in_c/groups, out_c)
        wshape = self.kernel_size + [in_channels // groups, out_channels]
        self.weight = Parameter(jax.random.uniform(next_key(), wshape, jnp.float32,
                                                   -bound, bound))
        self.bias = Parameter(jnp.zeros([out_channels], jnp.float32))             if bias_attr is not False else None

    def parameters(self):
        return [self.weight] + ([self.bias] if self.bias is not None else [])

    def __call__(self, x):
        return self.forward(x)

    def forward(self, x):
        from ..nn.functional.conv import conv3d
        dense = to_dense(x)                           # (N, D, H, W, C)
        # our conv weights are (out_c, in_c/groups, kd, kh, kw)
        w = Tensor(jnp.transpose(self.weight._value, (4, 3, 0, 1, 2)))
        out = conv3d(dense, w, self.bias, stride=self.stride, padding=self.padding,
                     dilation=self.dilation, groups=self.groups, data_format="NDHWC")
        if self._subm:
            # submanifold: keep only the input's active sites
            mask_vals = jnp.ones((x.indices.shape[1], 1), jnp.float32)
            mask = SparseCooTensor(x.indices, Tensor(mask_vals),
                                   list(x.shape[:-1]) + [1])
            dm = to_dense(mask)._value
            out = Tensor(out._value * (dm > 0))
        return dense_to_coo(out, sparse_dim=4)


class Conv3D(_SparseConv3DBase):
    _subm = False


class SubmConv3D(_SparseConv3DBase):
    _subm = True


_SparseNN.Conv3D = Conv3D
_SparseNN.SubmConv3D = SubmConv3D

nn = _SparseNN()

# v2.3 exposes the sparse layers at paddle.sparse top level too
ReLU = _SparseNN.ReLU
BatchNorm = _SparseNN.BatchNorm
MaxPool3D = _SparseNN.MaxPool3D

__all__ += ["sqrt", "sin", "square", "abs", "neg", "expm1", "log1p", "asin",
            "atan", "sinh", "asinh", "atanh", "pow", "cast", "add", "subtract",
            "multiply", "divide", "masked_matmul", "dense_to_coo", "coo_to_csr",
            "nn"]
