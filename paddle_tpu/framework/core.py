"""Core Tensor type, op dispatch, and the eager autograd tape.

Reference architecture being replaced (not ported):
  - paddle/fluid/eager/* — C++ eager autograd graph with per-op GradNodes
  - python/paddle/fluid/dygraph/varbase_patch_methods.py — Tensor methods
Here the accelerator compute path is XLA: every op is a pure function on
jax.Array values. `Tensor` is a thin mutable handle around a jax.Array (or a
tracer when inside jax.jit tracing). Eager autograd is a Wengert tape over
the op dispatch point `_apply`: each recorded node re-derives its VJP with
jax.vjp at backward time. The high-performance training path does NOT use the
tape — it uses jax.value_and_grad over `functional_call` (see nn/layers.py)
so the whole step compiles to one XLA program.
"""
from __future__ import annotations

import threading
import weakref

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as dtypes

__all__ = [
    "Tensor",
    "Parameter",
    "to_tensor",
    "no_grad",
    "enable_grad",
    "is_grad_enabled",
    "apply_op",
    "backward",
    "TapeState",
]

_tls = threading.local()


def _tape():
    if not hasattr(_tls, "tape"):
        _tls.tape = TapeState()
    return _tls.tape


class TapeState:
    __slots__ = ("nodes", "enabled", "paused")

    def __init__(self):
        self.nodes = []
        self.enabled = True
        self.paused = 0

    @property
    def recording(self):
        return self.enabled and self.paused == 0

    def clear(self):
        self.nodes = []


class _TapeNode:
    """One recorded eager op: enough to rebuild its VJP with jax.vjp."""

    __slots__ = ("fn", "raw_args", "kwargs", "diff_idx", "in_tensors", "outputs")

    def __init__(self, fn, raw_args, kwargs, diff_idx, in_tensors, outputs):
        self.fn = fn
        self.raw_args = raw_args      # positional args with Tensors unwrapped
        self.kwargs = kwargs          # static kwargs
        self.diff_idx = diff_idx      # positions of differentiable inputs
        self.in_tensors = in_tensors  # Tensor at each diff position
        self.outputs = outputs        # list[Tensor] produced


class no_grad:
    """Context manager + decorator disabling tape recording (paddle.no_grad)."""

    def __enter__(self):
        t = _tape()
        self._prev = t.enabled
        t.enabled = False
        return self

    def __exit__(self, *exc):
        _tape().enabled = self._prev
        return False

    def __call__(self, fn):
        def wrapper(*a, **k):
            with no_grad():
                return fn(*a, **k)

        wrapper.__name__ = getattr(fn, "__name__", "wrapped")
        return wrapper


class enable_grad:
    def __enter__(self):
        t = _tape()
        self._prev = t.enabled
        t.enabled = True
        return self

    def __exit__(self, *exc):
        _tape().enabled = self._prev
        return False


def is_grad_enabled():
    return _tape().recording


class _pause_tape:
    """Internal: used by functional_call / jitted paths where jax.grad is the
    differentiation mechanism and tape recording would be pure overhead."""

    def __enter__(self):
        _tape().paused += 1

    def __exit__(self, *exc):
        _tape().paused -= 1


def _is_jax_value(v):
    """jax.Array or any tracer (tracer classes moved across jax versions)."""
    return isinstance(v, jax.Array) or hasattr(v, "aval")


def _is_diff_dtype(v):
    d = jnp.result_type(v)
    return jnp.issubdtype(d, np.inexact) or d == dtypes.bfloat16


# set by paddle_tpu.profiler.Profiler.start() to time eager op dispatch
_op_profiler = None


def apply_op(fn, *args, **kwargs):
    """Central eager dispatch: unwrap Tensors, run `fn`, wrap outputs, and
    record a tape node when gradients are being tracked.

    `fn` must be pure: positional args may be arrays (differentiable),
    kwargs are static configuration. Multi-output fns return tuples.

    If any input is a SymbolicVar (static-graph mode), the op is deferred
    into the graph instead of executed.
    """
    if any(type(a) is SymbolicVar for a in args):
        return _defer_symbolic(fn, args, kwargs)
    tape = _tape()
    raw = []
    diff_idx = []
    in_tensors = []
    track = tape.recording
    for i, a in enumerate(args):
        if isinstance(a, Tensor):
            raw.append(a._value)
            if track and not a.stop_gradient and _is_diff_dtype(a._value):
                diff_idx.append(i)
                in_tensors.append(a)
        else:
            raw.append(a)
    if _op_profiler is None:
        out = fn(*raw, **kwargs)
    else:
        import time as _time
        _t0 = _time.perf_counter()
        out = fn(*raw, **kwargs)
        jax.block_until_ready(out)   # honest host timing while profiling
        _name = getattr(fn, "__qualname__", None) or getattr(fn, "__name__", "op")
        # lambdas carry their defining fn in __qualname__: "linear.<locals>.<lambda>"
        _name = _name.replace(".<locals>.<lambda>", "").replace(".<locals>", ".")
        _op_profiler._record_op(_name, _t0, _time.perf_counter())
    requires = bool(diff_idx)
    if isinstance(out, (tuple, list)):
        outs = [Tensor(o, stop_gradient=not requires) for o in out]
        if requires:
            node = _TapeNode(fn, raw, kwargs, diff_idx, in_tensors, outs)
            for t in outs:
                t._producer = node
            tape.nodes.append(node)
        return type(out)(outs) if isinstance(out, tuple) else outs
    t = Tensor(out, stop_gradient=not requires)
    if requires:
        node = _TapeNode(fn, raw, kwargs, diff_idx, in_tensors, [t])
        t._producer = node
        tape.nodes.append(node)
    return t


def _zero_ct(val):
    d = jnp.result_type(val)
    if jnp.issubdtype(d, np.inexact) or d == dtypes.bfloat16:
        return jnp.zeros(jnp.shape(val), d)
    return np.zeros(jnp.shape(val), jax.dtypes.float0)


def backward(loss: "Tensor", grad_tensor=None, retain_graph: bool = False):
    """Reverse-mode sweep over the eager tape; accumulates into leaf `.grad`.

    Mirrors paddle.autograd.backward semantics: only leaf tensors (not
    produced by a recorded op) retain `.grad`.
    """
    tape = _tape()
    if loss._producer is None:
        if not retain_graph:
            tape.clear()
        return
    cts: dict[int, jax.Array] = {}
    if grad_tensor is None:
        seed = jnp.ones(loss.shape, jnp.result_type(loss._value))
    else:
        seed = grad_tensor._value if isinstance(grad_tensor, Tensor) else jnp.asarray(grad_tensor)
    cts[id(loss)] = seed

    with _pause_tape():
        for node in reversed(tape.nodes):
            out_cts = [cts.get(id(o)) for o in node.outputs]
            if all(c is None for c in out_cts):
                continue

            def closed(*dvals, _node=node):
                full = list(_node.raw_args)
                for j, v in zip(_node.diff_idx, dvals):
                    full[j] = v
                return _node.fn(*full, **_node.kwargs)

            primals = [node.raw_args[j] for j in node.diff_idx]
            out_val, vjp_fn = jax.vjp(closed, *primals)
            if isinstance(out_val, (tuple, list)):
                ct = type(out_val)(
                    c if c is not None else _zero_ct(v)
                    for c, v in zip(out_cts, out_val)
                )
            else:
                ct = out_cts[0] if out_cts[0] is not None else _zero_ct(out_val)
            in_cts = vjp_fn(ct)
            for t, g in zip(node.in_tensors, in_cts):
                if g is None or (hasattr(g, "dtype") and g.dtype == jax.dtypes.float0):
                    continue
                key = id(t)
                cts[key] = g if key not in cts else cts[key] + g

    leaves = {}
    for node in tape.nodes:
        for t in node.in_tensors:
            if t._producer is None and id(t) in cts:
                leaves[id(t)] = t
    for t in leaves.values():
        g = cts[id(t)]
        t.grad = Tensor(g if t.grad is None else t.grad._value + g, stop_gradient=True)
    if not retain_graph:
        tape.clear()


class Tensor:
    """Paddle-compatible tensor handle over a jax.Array.

    Mutable wrapper (supports `x[i] = v`, `add_`, parameter updates) around
    immutable device buffers; functional-update under the hood (`.at[].set`).
    """

    __slots__ = ("_value", "stop_gradient", "grad", "_producer", "name",
                 "persistable", "partition_spec", "_deferred_shape",
                 "__weakref__")

    def __init__(self, value, dtype=None, stop_gradient=True, name=None):
        if isinstance(value, Tensor):
            value = value._value
        if dtype is not None:
            value = jnp.asarray(value, dtypes.dtype(dtype))
        elif not _is_jax_value(value):
            value = _np_default(value)
        self._value = value
        self.stop_gradient = stop_gradient
        self.grad = None
        self._producer = None
        self.name = name
        self.persistable = False
        self.partition_spec = None  # GSPMD mesh axes (auto_parallel/fleet)

    # -- basic properties -------------------------------------------------
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def dtype(self):
        return jnp.dtype(self._value.dtype)

    @property
    def ndim(self):
        return self._value.ndim

    dim = ndim

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def place(self):
        from .device import CPUPlace, TPUPlace

        try:
            dev = list(self._value.devices())[0]
            return CPUPlace() if dev.platform == "cpu" else TPUPlace(dev.id)
        except Exception:
            return TPUPlace(0)

    @property
    def T(self):
        return apply_op(jnp.transpose, self)

    @property
    def is_leaf(self):
        return self._producer is None

    def numel(self):
        return self.size

    # -- conversion -------------------------------------------------------
    def numpy(self):
        return np.asarray(self._value)

    def __array__(self, dtype=None):
        return np.asarray(self._value, dtype=dtype)

    def item(self, *idx):
        v = self._value
        if idx:
            v = v[idx if len(idx) > 1 else idx[0]]
        return v.item()

    def tolist(self):
        return np.asarray(self._value).tolist()

    def gradient(self):
        """Numpy value of this tensor's gradient, or None (reference
        varbase_patch_methods.py:306; the reference itself steers users
        toward `.grad`, which we also provide)."""
        return None if self.grad is None else np.asarray(self.grad._value)

    def to_sparse_coo(self, sparse_dim):
        """Dense -> SparseCooTensor over the leading `sparse_dim` dims
        (reference varbase_patch_methods.py:949); conversion itself lives
        in sparse.dense_to_coo, shared with the sparse-conv paths."""
        from ..sparse import dense_to_coo
        ndim = len(self.shape)
        if not 0 < sparse_dim <= ndim:
            raise ValueError(f"sparse_dim must be in [1, {ndim}], "
                             f"got {sparse_dim}")
        return dense_to_coo(self, sparse_dim)

    def to_dense(self):
        """Already dense — identity (parity with SparseCooTensor.to_dense
        so generic code can call .to_dense() on either)."""
        return self

    def set_value(self, value):
        """In-place value assignment (reference
        fluid/dygraph/varbase_patch_methods.py:132 set_value): the shape
        must match; the new value is cast to this tensor's dtype (the
        reference asserts dtype equality, but with x64 disabled a
        silently-f64 numpy literal would then never be assignable).
        Works on Parameters held by Layers — the Layer keeps this
        object, only its buffer is replaced."""
        v = value._value if isinstance(value, Tensor) else \
            jnp.asarray(value)   # handles list/np/jax without a host hop
        if getattr(self, "_deferred_shape", False):
            # a Layer.create_tensor placeholder takes its shape from the
            # first assignment (like the reference's uninitialized
            # Variables); ordinary empty tensors keep strict validation
            self._value = v.astype(self._value.dtype)
            self._deferred_shape = False
            return self
        if tuple(v.shape) != tuple(self._value.shape):
            raise ValueError(
                f"set_value: shape mismatch — tensor is "
                f"{tuple(self._value.shape)}, new value is "
                f"{tuple(v.shape)}")
        self._value = v.astype(self._value.dtype)
        return self

    def astype(self, d):
        return apply_op(lambda x, _d=dtypes.dtype(d): x.astype(_d), self)

    def cast(self, d):
        return self.astype(d)

    def clone(self):
        return apply_op(lambda x: x + 0 if x.dtype != jnp.bool_ else x, self)

    def detach(self):
        t = Tensor(self._value, stop_gradient=True)
        return t

    def cpu(self):
        return Tensor(jax.device_get(self._value), stop_gradient=self.stop_gradient)

    def to(self, *args, **kwargs):
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, str) and a in ("cpu", "tpu", "gpu"):
                continue
            try:
                return self.astype(a)
            except TypeError:
                continue
        return self

    def backward(self, grad_tensor=None, retain_graph=False):
        backward(self, grad_tensor, retain_graph)

    def clear_grad(self):
        self.grad = None

    def clear_gradient(self, set_to_zero=False):
        if set_to_zero and self.grad is not None:
            self.grad = Tensor(jnp.zeros_like(self.grad._value))
        else:
            self.grad = None

    def register_hook(self, hook):  # minimal parity; tape-level hooks
        return hook

    # -- python protocol --------------------------------------------------
    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __bool__(self):
        return bool(self._value)

    def __int__(self):
        return int(self._value)

    def __float__(self):
        return float(self._value)

    def __index__(self):
        return int(self._value)

    def __format__(self, spec):
        if self.ndim == 0:
            return format(self.item(), spec)
        return format(str(self), spec)

    def __repr__(self):
        sg = self.stop_gradient
        body = np.array2string(np.asarray(jax.device_get(self._value)), separator=", ", prefix="       ")
        return (
            f"Tensor(shape={self.shape}, dtype={self.dtype.name}, place={self.place}, "
            f"stop_gradient={sg},\n       {body})"
        )

    def __getitem__(self, idx):
        idx = _unwrap_index(idx)
        return apply_op(lambda x, _i=idx: x[_i], self)

    def __setitem__(self, idx, value):
        idx = _unwrap_index(idx)
        v = value._value if isinstance(value, Tensor) else value
        self._inplace_update(lambda x, _i=idx, _v=v: x.at[_i].set(jnp.asarray(_v, x.dtype)))

    def _inplace_update(self, fn):
        """In-place op: rebinds the handle to the new value, tape-consistently."""
        out = apply_op(fn, self)
        self._value = out._value
        self._producer = out._producer
        if out._producer is not None:
            out._producer.outputs[out._producer.outputs.index(out)] = self
            self.stop_gradient = out.stop_gradient
        return self

    __hash__ = object.__hash__  # identity hash; __eq__ is elementwise (torch-style)

    # arithmetic operators are monkey-patched in tensor/math.py, mirroring
    # reference python/paddle/fluid/dygraph/math_op_patch.py


class Parameter(Tensor):
    """Trainable tensor (paddle.framework.Parameter / fluid ParamBase)."""

    __slots__ = ("optimize_attr", "regularizer", "is_distributed",
                 "need_clip", "is_sparse_grad")

    def __init__(self, value, dtype=None, name=None, trainable=True):
        super().__init__(value, dtype=dtype, stop_gradient=not trainable, name=name)
        self.persistable = True
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.is_distributed = False
        self.need_clip = True
        from . import _static_mode
        if _static_mode[0]:
            # static mode: register with the default Program so
            # Program.all_parameters() reports real parameters
            from ..static import _register_parameter
            _register_parameter(self)
        self.partition_spec = None  # GSPMD mesh axes, set by parallel layers

    @property
    def trainable(self):
        return not self.stop_gradient

    @trainable.setter
    def trainable(self, v):
        self.stop_gradient = not v

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


def _np_default(value):
    """numpy-style coercion with paddle defaults (float data → default dtype)."""
    arr = np.asarray(value)
    if arr.dtype == np.float64:
        arr = arr.astype(np.dtype(dtypes.get_default_dtype()))
    return jnp.asarray(arr)


def _unwrap_index(idx):
    if isinstance(idx, Tensor):
        return idx._value
    if isinstance(idx, tuple):
        return tuple(i._value if isinstance(i, Tensor) else i for i in idx)
    return idx


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor — ref python/paddle/tensor/creation.py:to_tensor."""
    if isinstance(data, Tensor):
        v = data._value
        if dtype is not None:
            v = v.astype(dtypes.dtype(dtype))
        return Tensor(v, stop_gradient=stop_gradient)
    if dtype is not None:
        v = jnp.asarray(data, dtypes.dtype(dtype))
    elif _is_jax_value(data):
        v = data
    else:
        v = _np_default(data)
    return Tensor(v, stop_gradient=stop_gradient)


# -- pytree registration: Tensors flow through jax.jit / grad boundaries ----
def _flatten(t):
    return (t._value,), (type(t), t.stop_gradient)


def _unflatten(aux, children):
    cls, sg = aux
    obj = Tensor.__new__(cls)
    v = children[0]
    if _is_jax_value(v):
        Tensor.__init__(obj, v, stop_gradient=sg)
    else:
        # abstract leaf (ShapeDtypeStruct under eval_shape, aval in
        # tree_map diagnostics, ...): carry it through unnormalized so
        # Tensor pytrees survive shape-only tree rebuilds
        obj._value = v
        obj.stop_gradient = sg
        obj.grad = None
        obj._producer = None
        obj.name = None
        obj.persistable = False
        obj.partition_spec = None
    return obj


jax.tree_util.register_pytree_node(Tensor, _flatten, _unflatten)
jax.tree_util.register_pytree_node(Parameter, _flatten, _unflatten)


class _SymOp:
    """One deferred op in a static graph (symbolic trace node)."""

    __slots__ = ("fn", "args", "kwargs", "n_out")

    def __init__(self, fn, args, kwargs, n_out):
        self.fn = fn
        self.args = args      # mix of SymbolicVar / Tensor (captured) / consts
        self.kwargs = kwargs
        self.n_out = n_out    # None for single output, else tuple arity


class SymbolicVar(Tensor):
    """Static-graph variable (≈ reference fluid.framework.Variable).

    Holds no data — only a ShapeDtypeStruct aval plus either a feed name
    (placeholder from static.data) or the _SymOp that produces it. The
    Executor evaluates the op DAG under jax.jit; see paddle_tpu/static.
    """

    __slots__ = ("_feed_name", "_sym_op", "_out_index", "_declared_shape")

    def __init__(self, aval, feed_name=None, op=None, out_index=None, name=None):
        self._value = aval  # ShapeDtypeStruct: .shape/.dtype/.ndim still work
        self.stop_gradient = True
        self.grad = None
        self._producer = None
        self.name = name or feed_name
        self.persistable = False
        self._feed_name = feed_name
        self._sym_op = op
        self._out_index = out_index
        self._declared_shape = None  # holds -1 dynamic dims for .shape parity

    @property
    def shape(self):
        if self._declared_shape is not None:
            return list(self._declared_shape)
        return list(self._value.shape)

    def numpy(self):
        raise RuntimeError(
            f"Variable '{self.name}' is symbolic (static mode); fetch it via "
            "Executor.run(feed=..., fetch_list=[...]) to get a value")

    item = numpy
    tolist = numpy

    def __repr__(self):
        return (f"SymbolicVar(name={self.name}, shape={list(self._value.shape)}, "
                f"dtype={self._value.dtype})")


def _defer_symbolic(fn, args, kwargs):
    """apply_op path when any input is symbolic: record, don't execute."""
    avals = [a._value if isinstance(a, Tensor) else a for a in args]
    out_aval = jax.eval_shape(lambda *xs: fn(*xs, **kwargs), *avals)
    if isinstance(out_aval, (tuple, list)):
        op = _SymOp(fn, args, kwargs, len(out_aval))
        outs = [SymbolicVar(av, op=op, out_index=i) for i, av in enumerate(out_aval)]
        return type(out_aval)(outs) if isinstance(out_aval, tuple) else outs
    return SymbolicVar(out_aval, op=_SymOp(fn, args, kwargs, None))
