"""Global RNG state mirroring paddle.seed / get_rng_state semantics.

Reference: /root/reference/python/paddle/framework/random.py. Paddle keeps a
global generator per device; the TPU-native equivalent is a root
`jax.random.key` plus a fold-in counter, so eager ops get fresh keys while a
single `seed(n)` reproduces an entire run. Inside jitted code users pass keys
explicitly (idiomatic JAX); eager creation ops draw from this state.
"""
import contextlib
import threading

import jax

_state = threading.local()


def _ensure():
    if not hasattr(_state, "key"):
        _state.key = jax.random.PRNGKey(0)
        _state.counter = 0
        _state.traced_salt = None
    return _state


@contextlib.contextmanager
def traced_salt(value):
    """Fold a TRACED value (e.g. the training-step counter) into every
    next_key() drawn inside the context. Without this, keys drawn while
    tracing a jitted train step are baked in as compile-time constants —
    the same dropout/gate-noise draw would repeat every step. The salt is
    a step argument, so randomness is fresh per step with no retrace."""
    if value is None:
        yield
        return
    s = _ensure()
    old = s.traced_salt
    s.traced_salt = value
    try:
        yield
    finally:
        s.traced_salt = old


def seed(value: int):
    """Reset the global RNG. Returns None (paddle returns the generator)."""
    s = _ensure()
    s.key = jax.random.PRNGKey(int(value))
    s.counter = 0


def next_key():
    """Fresh PRNG key for one eager random op (deterministic given seed())."""
    s = _ensure()
    s.counter += 1
    k = jax.random.fold_in(s.key, s.counter)
    if getattr(s, "traced_salt", None) is not None:
        k = jax.random.fold_in(k, s.traced_salt)
    return k


def get_rng_state():
    s = _ensure()
    return (s.key, s.counter)


def set_rng_state(state):
    s = _ensure()
    s.key, s.counter = state
