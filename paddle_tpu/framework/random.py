"""Global RNG state mirroring paddle.seed / get_rng_state semantics.

Reference: /root/reference/python/paddle/framework/random.py. Paddle keeps a
global generator per device; the TPU-native equivalent is a root
`jax.random.key` plus a fold-in counter, so eager ops get fresh keys while a
single `seed(n)` reproduces an entire run. Inside jitted code users pass keys
explicitly (idiomatic JAX); eager creation ops draw from this state.
"""
import threading

import jax

_state = threading.local()


def _ensure():
    if not hasattr(_state, "key"):
        _state.key = jax.random.PRNGKey(0)
        _state.counter = 0
    return _state


def seed(value: int):
    """Reset the global RNG. Returns None (paddle returns the generator)."""
    s = _ensure()
    s.key = jax.random.PRNGKey(int(value))
    s.counter = 0


def next_key():
    """Fresh PRNG key for one eager random op (deterministic given seed())."""
    s = _ensure()
    s.counter += 1
    return jax.random.fold_in(s.key, s.counter)


def get_rng_state():
    s = _ensure()
    return (s.key, s.counter)


def set_rng_state(state):
    s = _ensure()
    s.key, s.counter = state
