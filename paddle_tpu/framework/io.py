"""paddle.save / paddle.load — reference python/paddle/framework/io.py.
Pickle-based state persistence (numpy payloads); for sharded/async
checkpoints of big models use paddle_tpu.incubate.checkpoint (orbax)."""
import os
import pickle

import jax.numpy as jnp
import numpy as np

from .core import Tensor

__all__ = ["save", "load"]


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj._value))
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_saveable(v) for v in obj)
    return obj


def _from_saveable(obj, return_numpy=False):
    if isinstance(obj, _TensorPayload):
        return obj.array if return_numpy else Tensor(jnp.asarray(obj.array))
    if isinstance(obj, dict):
        return {k: _from_saveable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_saveable(v, return_numpy) for v in obj)
    return obj


class _TensorPayload:
    def __init__(self, array):
        self.array = array


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


def load(path, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _from_saveable(obj, return_numpy=configs.get("return_numpy", False))
