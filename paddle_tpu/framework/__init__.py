"""paddle_tpu.framework — core runtime state (≈ python/paddle/framework in
the reference, minus the static-graph Program machinery which lives in
paddle_tpu.static)."""
from . import device, dtype, random
from .core import (
    Parameter,
    Tensor,
    apply_op,
    backward,
    enable_grad,
    is_grad_enabled,
    no_grad,
    to_tensor,
)
from .device import CPUPlace, CUDAPlace, TPUPlace, get_device, set_device
from .dtype import (
    bfloat16,
    bool,  # noqa: A004
    complex64,
    complex128,
    dtype,
    float16,
    float32,
    float64,
    get_default_dtype,
    int8,
    int16,
    int32,
    int64,
    set_default_dtype,
    uint8,
)
from .random import get_rng_state, seed, set_rng_state

__all__ = [
    "Tensor", "Parameter", "to_tensor", "no_grad", "enable_grad",
    "is_grad_enabled", "apply_op", "backward", "seed", "get_rng_state",
    "set_rng_state", "set_device", "get_device", "TPUPlace", "CPUPlace",
    "CUDAPlace", "dtype", "set_default_dtype", "get_default_dtype",
]


def in_dynamic_mode():
    return True


def disable_static():
    pass


def enable_static():
    raise NotImplementedError(
        "paddle_tpu is eager-first; use paddle_tpu.jit.to_static for compiled execution")
