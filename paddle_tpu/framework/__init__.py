"""paddle_tpu.framework — core runtime state (≈ python/paddle/framework in
the reference, minus the static-graph Program machinery which lives in
paddle_tpu.static)."""
from . import device, dtype, random
from .core import (
    Parameter,
    Tensor,
    apply_op,
    backward,
    enable_grad,
    is_grad_enabled,
    no_grad,
    to_tensor,
)
from .device import CPUPlace, CUDAPlace, TPUPlace, get_device, set_device
from .dtype import (
    bfloat16,
    bool,  # noqa: A004
    complex64,
    complex128,
    dtype,
    float16,
    float32,
    float64,
    get_default_dtype,
    int8,
    int16,
    int32,
    int64,
    set_default_dtype,
    uint8,
)
from .random import get_rng_state, seed, set_rng_state

__all__ = [
    "Tensor", "Parameter", "to_tensor", "no_grad", "enable_grad",
    "is_grad_enabled", "apply_op", "backward", "seed", "get_rng_state",
    "set_rng_state", "set_device", "get_device", "TPUPlace", "CPUPlace",
    "CUDAPlace", "dtype", "set_default_dtype", "get_default_dtype",
]


_static_mode = [False]


def in_dynamic_mode():
    return not _static_mode[0]


def in_dygraph_mode():
    return not _static_mode[0]


def disable_static():
    _static_mode[0] = False


def enable_static():
    """Switch to static-graph mode: paddle_tpu.static.data placeholders +
    Executor.run compile the whole fetched graph as one XLA program."""
    _static_mode[0] = True


class set_grad_enabled:
    """Mirror paddle.set_grad_enabled(mode): applies immediately on call
    (statement form) AND works as a context manager that restores the
    previous mode on exit."""

    def __init__(self, mode):
        from .core import _tape
        self.mode = True if mode else False  # builtin bool is shadowed by the dtype
        t = _tape()
        self._prev = t.enabled
        t.enabled = self.mode

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        from .core import _tape
        _tape().enabled = self._prev
        return False


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """Top-level parameter factory (reference python/paddle/framework →
    fluid layers.create_parameter)."""
    import jax.numpy as jnp
    from .core import Parameter
    from .dtype import dtype as _dt
    import numpy as _np
    shape = tuple(int(s) for s in shape)
    if default_initializer is not None:
        p = Parameter(jnp.zeros(shape, _dt(dtype)), name=name)
        default_initializer(p)
        return p
    if is_bias:
        val = jnp.zeros(shape, _dt(dtype))
    else:
        fan_in = shape[0] if shape else 1
        limit = float(_np.sqrt(6.0 / max(1, fan_in)))
        from .random import next_key
        import jax as _jax
        val = _jax.random.uniform(next_key(), shape, _dt(dtype), -limit, limit)
    return Parameter(val, name=name)


_printoptions = {"precision": 8, "threshold": 1000, "edgeitems": 3,
                 "linewidth": 80, "sci_mode": None}


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Mirror paddle.set_printoptions by configuring numpy's printer (our
    Tensor repr prints via numpy)."""
    import numpy as _np
    kw = {}
    if precision is not None:
        _printoptions["precision"] = kw["precision"] = int(precision)
    if threshold is not None:
        _printoptions["threshold"] = kw["threshold"] = int(threshold)
    if edgeitems is not None:
        _printoptions["edgeitems"] = kw["edgeitems"] = int(edgeitems)
    if linewidth is not None:
        _printoptions["linewidth"] = kw["linewidth"] = int(linewidth)
    if sci_mode is not None:
        _printoptions["sci_mode"] = sci_mode
        kw["suppress"] = not sci_mode
    _np.set_printoptions(**kw)
