"""Dtype registry mirroring paddle.framework.dtype.

Reference: /root/reference/python/paddle/framework/dtype.py — paddle exposes
named dtype singletons (paddle.float32, ...). Here each is a thin alias of a
numpy/jax dtype so they interop directly with jnp.
"""
import jax.numpy as jnp
import numpy as np

# Canonical dtypes (jnp dtype objects compare equal to numpy dtypes/strings).
uint8 = jnp.dtype("uint8")
int8 = jnp.dtype("int8")
int16 = jnp.dtype("int16")
int32 = jnp.dtype("int32")
int64 = jnp.dtype("int64")
float16 = jnp.dtype("float16")
float32 = jnp.dtype("float32")
float64 = jnp.dtype("float64")
bfloat16 = jnp.dtype(jnp.bfloat16)
bool = jnp.dtype("bool")  # noqa: A001 - paddle exposes `paddle.bool`
complex64 = jnp.dtype("complex64")
complex128 = jnp.dtype("complex128")

_ALIASES = {
    "float": float32,
    "double": float64,
    "half": float16,
    "int": int32,
    "long": int64,
    "bfloat": bfloat16,
}

_DEFAULT_DTYPE = [float32]


def dtype(name):
    """Coerce a paddle-style dtype spec (str / np dtype / jnp dtype) to jnp dtype."""
    if name is None:
        return None
    if isinstance(name, str) and name in _ALIASES:
        return _ALIASES[name]
    return jnp.dtype(name)


def canonical(d):
    """Map 64-bit dtypes to their 32-bit forms when x64 is disabled (the TPU
    default) so paddle's int64/float64 defaults don't spam truncation
    warnings — values are identical for framework-internal uses."""
    import jax

    d = dtype(d)
    if not jax.config.jax_enable_x64:
        if d == int64:
            return int32
        if d == float64:
            return float32
        if d == complex128:
            return complex64
    return d


def set_default_dtype(d):
    d = dtype(d)
    if d not in (float16, float32, float64, bfloat16):
        raise TypeError(f"set_default_dtype only supports floating dtypes, got {d}")
    _DEFAULT_DTYPE[0] = d


def get_default_dtype():
    return _DEFAULT_DTYPE[0]


def is_floating_point_dtype(d):
    return jnp.issubdtype(jnp.dtype(d), np.floating) or jnp.dtype(d) == bfloat16


def is_integer_dtype(d):
    return jnp.issubdtype(jnp.dtype(d), np.integer)


def is_complex_dtype(d):
    return jnp.issubdtype(jnp.dtype(d), np.complexfloating)
