"""Device management mirroring paddle.device.

Reference: /root/reference/python/paddle/device/__init__.py exposes
set_device/get_device with "gpu:0"-style strings backed by Place objects.
Here devices are jax.Device handles; "tpu"/"cpu" strings select platform.
"""
import jax


class TPUPlace:
    """Paddle-style Place handle for a TPU chip (≈ CUDAPlace in reference)."""

    def __init__(self, device_id: int = 0):
        self.device_id = int(device_id)

    def __repr__(self):
        return f"TPUPlace({self.device_id})"

    def __eq__(self, other):
        return isinstance(other, TPUPlace) and other.device_id == self.device_id


class CPUPlace:
    def __repr__(self):
        return "CPUPlace()"

    def __eq__(self, other):
        return isinstance(other, CPUPlace)


# Aliases so code written against the CUDA reference maps over.
CUDAPlace = TPUPlace
XPUPlace = TPUPlace
NPUPlace = TPUPlace
IPUPlace = TPUPlace
MLUPlace = TPUPlace


class CustomPlace:
    """Place for a custom device type (reference core.CustomPlace)."""

    def __init__(self, device_type="tpu", device_id=0):
        self.device_type = device_type
        self.device_id = device_id

    def __repr__(self):
        return f"Place({self.device_type}:{self.device_id})"


class CUDAPinnedPlace:
    """Host-pinned staging memory place. On TPU, host buffers handed to
    jax.device_put are already staged through pinned memory; this is an
    API-parity handle (reference fluid CUDAPinnedPlace)."""

    def __repr__(self):
        return "CUDAPinnedPlace()"

    def __eq__(self, other):
        return isinstance(other, CUDAPinnedPlace)

_current = [None]  # lazily resolved default device string


def _platform():
    return jax.default_backend()


def set_device(device: str):
    """Accepts "tpu", "tpu:0", "cpu". Returns the jax.Device selected."""
    name, _, idx = device.partition(":")
    idx = int(idx) if idx else 0
    if name in ("gpu", "cuda", "xpu"):  # compat: reference device names
        name = "tpu"
    devs = jax.devices() if name in ("tpu", "axon") else jax.devices(name)
    if idx >= len(devs):
        raise ValueError(f"device index {idx} out of range for {name} ({len(devs)} present)")
    jax.config.update("jax_default_device", devs[idx])
    _current[0] = f"{name}:{idx}"
    return devs[idx]


def get_device() -> str:
    if _current[0] is None:
        plat = _platform()
        plat = "tpu" if plat not in ("cpu",) else plat
        _current[0] = f"{plat}:0"
    return _current[0]


def is_compiled_with_cuda() -> bool:  # reference API parity; always False on TPU build
    return False


def is_compiled_with_tpu() -> bool:
    return True


def device_count() -> int:
    return len(jax.devices())
