"""paddle_tpu.autograd — eager tape + functional transforms.

Reference: python/paddle/autograd (backward, PyLayer) over the C++ eager
graph. Here: the tape lives in framework/core.py; functional grad/vjp/jvp
are direct jax transforms — the idiomatic TPU path.
"""
import jax

from ..framework.core import Tensor, _pause_tape, apply_op, backward, is_grad_enabled, no_grad
from . import functional  # noqa: F401
from .functional import (  # noqa: F401
    Hessian,
    Jacobian,
    batch_hessian,
    batch_jacobian,
    hessian,
    jacobian,
    vhp,
)

__all__ = ["PyLayerContext", "backward", "grad", "no_grad", "is_grad_enabled",
           "PyLayer", "value_and_grad", "vjp", "jvp", "Jacobian", "Hessian",
           "jacobian", "batch_jacobian", "hessian", "batch_hessian", "vhp",
           "functional"]


def grad(outputs, inputs, grad_outputs=None, retain_graph=False, create_graph=False,
         only_inputs=True, allow_unused=False, no_grad_vars=None):
    """paddle.grad: gradients of `outputs` wrt `inputs` via the eager tape."""
    outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    saved = [(t, t.grad) for t in ins]
    for t in ins:
        t.grad = None
    for i, o in enumerate(outs):
        go = None
        if grad_outputs is not None:
            gos = grad_outputs if isinstance(grad_outputs, (list, tuple)) else [grad_outputs]
            go = gos[i]
        backward(o, go, retain_graph=True if i < len(outs) - 1 else retain_graph)
    result = []
    for t, _ in saved:
        if t.grad is None and not allow_unused:
            import jax.numpy as jnp
            result.append(Tensor(jnp.zeros(t.shape, t.dtype)))
        else:
            result.append(t.grad)
    for t, g in saved:
        t.grad = g
    return result


def _fnize(func):
    """Lift a Tensor->Tensor python function to jax arrays for transforms."""
    def wrapped(*arrs):
        with _pause_tape():
            tens = [Tensor(a, stop_gradient=False) for a in arrs]
            out = func(*tens)
            return out._value if isinstance(out, Tensor) else out
    return wrapped


def value_and_grad(func, argnums=0, has_aux=False):
    vg = jax.value_and_grad(_fnize(func), argnums=argnums, has_aux=has_aux)

    def run(*tensors):
        arrs = [t._value if isinstance(t, Tensor) else t for t in tensors]
        val, g = vg(*arrs)
        wrap = lambda v: Tensor(v) if not isinstance(v, Tensor) else v
        g = jax.tree_util.tree_map(wrap, g)
        return jax.tree_util.tree_map(wrap, val), g
    return run


def vjp(func, xs, v=None):
    arrs = [t._value if isinstance(t, Tensor) else t for t in (xs if isinstance(xs, (list, tuple)) else [xs])]
    out, f_vjp = jax.vjp(_fnize(func), *arrs)
    if v is None:
        import jax.numpy as jnp
        v = jnp.ones_like(out)
    else:
        v = v._value if isinstance(v, Tensor) else v
    grads = f_vjp(v)
    gt = [Tensor(g) for g in grads]
    return Tensor(out), gt if len(gt) > 1 else gt[0]


def jvp(func, xs, v=None):
    arrs = [t._value if isinstance(t, Tensor) else t for t in (xs if isinstance(xs, (list, tuple)) else [xs])]
    if v is None:
        import jax.numpy as jnp
        tangents = [jnp.ones_like(a) for a in arrs]
    else:
        vs = v if isinstance(v, (list, tuple)) else [v]
        tangents = [t._value if isinstance(t, Tensor) else t for t in vs]
    out, tangent_out = jax.jvp(_fnize(func), arrs, tangents)
    return Tensor(out), Tensor(tangent_out)


class PyLayerContext:
    """Context passed to PyLayer.forward/backward (reference
    python/paddle/autograd/py_layer.py:PyLayerContext)."""

    def __init__(self):
        self._saved = ()

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved


class PyLayer:
    """Custom autograd op (reference python/paddle/autograd/py_layer.py).

    Subclass with static `forward(ctx, *args)` and `backward(ctx, *grads)`.
    Works with the eager tape: the pair is registered as one tape node whose
    VJP calls the user's backward.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    _Ctx = PyLayerContext

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = cls._Ctx()

        @jax.custom_vjp
        def op(*arrs):
            tens = [Tensor(a) for a in arrs]
            out = cls.forward(ctx, *tens, **kwargs)
            return out._value if isinstance(out, Tensor) else tuple(o._value for o in out)

        def fwd(*arrs):
            return op(*arrs), None

        def bwd(_, ct):
            cts = ct if isinstance(ct, tuple) else (ct,)
            gin = cls.backward(ctx, *[Tensor(c) for c in cts])
            gin = gin if isinstance(gin, (tuple, list)) else (gin,)
            return tuple(g._value if isinstance(g, Tensor) else g for g in gin)

        op.defvjp(fwd, bwd)
        return apply_op(op, *args)
