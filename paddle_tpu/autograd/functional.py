"""Functional higher-order autograd — Jacobian / Hessian / vhp.

Reference: python/paddle/autograd/functional.py:165 (Jacobian), :255
(Hessian), :698 (legacy jacobian), :842 (batch_jacobian), :992
(batch_hessian), :1137 (legacy hessian), :1262 (vhp).

TPU-native: instead of the reference's row-by-row double-grad loops over the
eager graph, everything lowers to jax.jacrev / jax.jacfwd / jax.hessian on a
flattened wrapper function — one traced XLA program, vmapped over the batch
axis for the batched variants. Matrices are computed on first access and
cached (the reference evaluates lazily per row; one fused XLA call is the
idiomatic equivalent here).
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, _pause_tape

__all__ = ["Jacobian", "Hessian", "jacobian", "batch_jacobian", "hessian",
           "batch_hessian", "vhp"]


def _as_list(xs):
    return list(xs) if isinstance(xs, (list, tuple)) else [xs]


def _arr(t):
    return t._value if isinstance(t, Tensor) else jnp.asarray(t)


def _flat_func(func, arrs, batched):
    """Build g(flat) -> flat_out over concatenated flattened inputs.

    batched=False: flat is [N] (all inputs raveled + concatenated), output
    is [M]. batched=True: per-sample flattening — flat is [B, N], output
    [B, M]; the batch (first) axis of every input/output is preserved.
    """
    shapes = [a.shape for a in arrs]
    if batched:
        sizes = [int(np.prod(s[1:], dtype=np.int64)) for s in shapes]
    else:
        sizes = [int(np.prod(s, dtype=np.int64)) for s in shapes]
    offsets = np.cumsum([0] + sizes)

    def g(flat):
        pieces = []
        for i, s in enumerate(shapes):
            seg = flat[..., offsets[i]:offsets[i + 1]]
            pieces.append(seg.reshape(s if not batched else (flat.shape[0],) + tuple(s[1:])))
        with _pause_tape():
            outs = func(*[Tensor(p, stop_gradient=False) for p in pieces])
        outs = [_arr(o) for o in _as_list(outs)]
        if batched:
            return jnp.concatenate([o.reshape(o.shape[0], -1) for o in outs], axis=-1)
        return jnp.concatenate([o.reshape(-1) for o in outs])

    if batched:
        flat0 = jnp.concatenate([a.reshape(a.shape[0], -1) for a in arrs], axis=-1)
    else:
        flat0 = jnp.concatenate([a.reshape(-1) for a in arrs])
    return g, flat0


class Jacobian:
    """Flattened Jacobian matrix of ``func`` at ``xs`` (reference
    python/paddle/autograd/functional.py:165).

    Shape is [M, N] (is_batched=False) or [B, M, N] (is_batched=True, first
    axis of every input/output is the batch). Supports tensor-style
    indexing; the full matrix is materialized lazily on first access.
    """

    def __init__(self, func, xs, is_batched=False):
        self._arrs = [_arr(t) for t in _as_list(xs)]
        self._func = func
        self._batched = is_batched
        self._g, self._flat0 = _flat_func(func, self._arrs, is_batched)
        self._mat = None
        m = jax.eval_shape(self._g, self._flat0).shape[-1]
        if is_batched:
            self._shape = (self._arrs[0].shape[0], m, self._flat0.shape[-1])
        else:
            self._shape = (m, self._flat0.shape[0])

    @property
    def shape(self):
        return self._shape

    def _evaluate(self):
        if self._mat is None:
            if self._batched:
                self._mat = jax.vmap(jax.jacrev(lambda f: self._g(f[None])[0]))(self._flat0)
            else:
                self._mat = jax.jacrev(self._g)(self._flat0)
        return self._mat

    def __getitem__(self, indexes):
        return Tensor(self._evaluate()[indexes])

    def __array__(self, dtype=None):
        return np.asarray(self._evaluate(), dtype=dtype)


class Hessian:
    """Flattened Hessian of a scalar-output ``func`` at ``xs`` (reference
    python/paddle/autograd/functional.py:255). Shape [N, N] or [B, N, N]."""

    def __init__(self, func, xs, is_batched=False):
        self._arrs = [_arr(t) for t in _as_list(xs)]
        self._batched = is_batched
        self._g, self._flat0 = _flat_func(func, self._arrs, is_batched)
        self._mat = None
        n = self._flat0.shape[-1]
        self._shape = (self._arrs[0].shape[0], n, n) if is_batched else (n, n)

    @property
    def shape(self):
        return self._shape

    def _evaluate(self):
        if self._mat is None:
            if self._batched:
                scalar = lambda f: self._g(f[None]).reshape(())
                self._mat = jax.vmap(jax.hessian(scalar))(self._flat0)
            else:
                self._mat = jax.hessian(lambda f: self._g(f).reshape(()))(self._flat0)
        return self._mat

    def __getitem__(self, indexes):
        return Tensor(self._evaluate()[indexes])

    def __array__(self, dtype=None):
        return np.asarray(self._evaluate(), dtype=dtype)


def _maybe_tuple(items, was_seq):
    return tuple(items) if was_seq or len(items) > 1 else items[0]


def jacobian(func, inputs, create_graph=False, allow_unused=False):
    """Legacy full Jacobian (reference functional.py:698): returns
    J[i][j] of shape [m_i, n_j] per (output i, input j); tuple structure
    collapses when either side is a single Tensor."""
    arrs = [_arr(t) for t in _as_list(inputs)]
    in_seq = isinstance(inputs, (list, tuple))

    out_is_seq = [False]

    def raw(*xs):
        with _pause_tape():
            res = func(*[Tensor(x, stop_gradient=False) for x in xs])
        out_is_seq[0] = isinstance(res, (list, tuple))
        return [_arr(o) for o in _as_list(res)]

    outs = jax.eval_shape(raw, *arrs)   # abstract: also records out_is_seq
    out_seq = out_is_seq[0]
    jacs = jax.jacrev(raw, argnums=tuple(range(len(arrs))))(*arrs)
    rows = []
    for i, oshape in enumerate(outs):
        m = int(np.prod(oshape.shape, dtype=np.int64))
        row = [Tensor(jacs[i][j].reshape(m, -1)) for j in range(len(arrs))]
        rows.append(_maybe_tuple(row, in_seq))
    return _maybe_tuple(rows, out_seq)


def batch_jacobian(func, inputs, create_graph=False, allow_unused=False):
    """Legacy batched Jacobian (reference functional.py:842): per-sample
    jacobians laid out [num_out, B * num_in] per (output, input) pair."""
    arrs = [_arr(t) for t in _as_list(inputs)]
    in_seq = isinstance(inputs, (list, tuple))
    b = arrs[0].shape[0]

    out_is_seq = [False]

    def raw(*xs):
        with _pause_tape():
            res = func(*[Tensor(x, stop_gradient=False) for x in xs])
        out_is_seq[0] = isinstance(res, (list, tuple))
        return [_arr(o) for o in _as_list(res)]

    jax.eval_shape(raw, *arrs)          # abstract: records out_is_seq
    out_seq = out_is_seq[0]

    def per_sample(*xs):
        # xs are single samples; run func on a size-1 batch
        outs = raw(*[x[None] for x in xs])
        return [o[0] for o in outs]

    jacs = jax.vmap(jax.jacrev(per_sample, argnums=tuple(range(len(arrs)))))(*arrs)
    rows = []
    n_out = len(jacs)
    for i in range(n_out):
        row = []
        for j in range(len(arrs)):
            jb = jacs[i][j]  # [B, *out_shape, *in_shape]
            o_nd = jb.ndim - 1 - (arrs[j].ndim - 1)
            mo = int(np.prod(jb.shape[1:1 + o_nd], dtype=np.int64))
            ni = int(np.prod(jb.shape[1 + o_nd:], dtype=np.int64))
            # [B, mo, ni] -> [mo, B*ni]
            row.append(Tensor(jnp.transpose(jb.reshape(b, mo, ni), (1, 0, 2)).reshape(mo, b * ni)))
        rows.append(_maybe_tuple(row, in_seq))
    return _maybe_tuple(rows, out_seq)


def hessian(func, inputs, create_graph=False, allow_unused=False):
    """Legacy Hessian of a scalar func (reference functional.py:1137):
    H[i][j] shape [n_i, n_j]."""
    arrs = [_arr(t) for t in _as_list(inputs)]
    in_seq = isinstance(inputs, (list, tuple))

    def scalar(*xs):
        with _pause_tape():
            out = func(*[Tensor(x, stop_gradient=False) for x in xs])
        return _arr(out).reshape(())

    h = jax.hessian(scalar, argnums=tuple(range(len(arrs))))(*arrs)
    rows = []
    for i in range(len(arrs)):
        ni = int(np.prod(arrs[i].shape, dtype=np.int64))
        row = [Tensor(h[i][j].reshape(ni, -1)) for j in range(len(arrs))]
        rows.append(_maybe_tuple(row, in_seq))
    return _maybe_tuple(rows, in_seq)


def batch_hessian(func, inputs, create_graph=False, allow_unused=False):
    """Legacy batched Hessian (reference functional.py:992): func returns
    [B, 1]; result per (i, j) is [num_in_i, B * num_in_j]."""
    arrs = [_arr(t) for t in _as_list(inputs)]
    in_seq = isinstance(inputs, (list, tuple))
    b = arrs[0].shape[0]

    def per_sample(*xs):
        with _pause_tape():
            out = func(*[Tensor(x[None], stop_gradient=False) for x in xs])
        return _arr(out).reshape(())

    h = jax.vmap(jax.hessian(per_sample, argnums=tuple(range(len(arrs)))))(*arrs)
    rows = []
    for i in range(len(arrs)):
        ni = int(np.prod(arrs[i].shape[1:], dtype=np.int64))
        row = []
        for j in range(len(arrs)):
            nj = int(np.prod(arrs[j].shape[1:], dtype=np.int64))
            hb = h[i][j].reshape(b, ni, nj)
            row.append(Tensor(jnp.transpose(hb, (1, 0, 2)).reshape(ni, b * nj)))
        rows.append(_maybe_tuple(row, in_seq))
    return _maybe_tuple(rows, in_seq)


def vhp(func, inputs, v=None, create_graph=False, allow_unused=False):
    """Vector-Hessian product (reference functional.py:1262): returns
    (func(inputs), v·H) with v defaulting to ones."""
    arrs = [_arr(t) for t in _as_list(inputs)]
    in_seq = isinstance(inputs, (list, tuple))

    def scalar(*xs):
        with _pause_tape():
            out = func(*[Tensor(x, stop_gradient=False) for x in xs])
        return _arr(out).reshape(())

    if v is None:
        vs = [jnp.ones_like(a) for a in arrs]
    else:
        vs = [_arr(t) for t in _as_list(v)]
    grad_fn = jax.grad(scalar, argnums=tuple(range(len(arrs))))
    _, hvp = jax.jvp(lambda *xs: grad_fn(*xs), tuple(arrs), tuple(vs))
    out = scalar(*arrs)
    hv = [Tensor(h) for h in hvp]
    return Tensor(out), _maybe_tuple(hv, in_seq)
