"""paddle_tpu.incubate.autotune — reference
python/paddle/incubate/autotune.py (set_config: kernel / layout /
dataloader autotuning toggles routed to the C++ autotune cache).

TPU-native rendering: the tunable hot kernel is the Pallas flash-attention
tile shape (ops/attention._BLOCK_Q/_BLOCK_K — the MXU/VMEM trade-off).
`tune_flash_attention` times candidate tiles ON DEVICE for a concrete
workload shape and installs the fastest; `set_config({"kernel":
{"enable": True}})` records the intent and tunes lazily from the given
shapes. Measured on GPT-1.3B bs4/seq1024: (512, 512) beats the (256, 256)
default by ~4% step time on v5e.
"""
import sys
import time
import types
import warnings

__all__ = ["set_config", "tune_flash_attention", "tune_w4_matmul",
           "get_tuned_blocks"]

_state = {"kernel_enabled": False, "tuned": {}}

_DEFAULT_CANDIDATES = [(256, 256), (256, 512), (512, 256), (512, 512),
                       (512, 1024), (1024, 512)]


def set_config(config=None):
    """Parity entry. config = {"kernel": {"enable": bool,
    "tuning_range": [[bq, bk], ...]}}; other sections accepted, ignored."""
    config = config or {}
    k = config.get("kernel", {})
    _state["kernel_enabled"] = bool(k.get("enable", False))
    rng = k.get("tuning_range")
    if rng:
        _state["candidates"] = [tuple(map(int, p)) for p in rng]
    return None


def get_tuned_blocks(shape_key=None):
    """Tuned (block_q, block_k) for a workload key (or all)."""
    if shape_key is None:
        return dict(_state["tuned"])
    return _state["tuned"].get(shape_key)


def tune_flash_attention(batch, seq_len, num_heads, head_dim,
                         candidates=None, steps=3, causal=True,
                         install=True, dtype="bfloat16"):
    """Time flash-attention fwd+bwd per candidate tile on the attached
    device; install the fastest into ops.attention. Returns
    {(bq, bk): seconds} over the EFFECTIVE (seq-clamped, deduplicated)
    tiles. Meaningful on TPU; on the CPU backend the jnp fallback path
    runs instead, so timings don't differentiate tiles — tune on the
    device you train on."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..ops import attention as A

    candidates = [tuple(map(int, c)) for c in
                  (candidates or _state.get("candidates",
                                            _DEFAULT_CANDIDATES))]
    rng = np.random.RandomState(0)
    shape = (batch, seq_len, num_heads, head_dim)
    q = jnp.asarray(rng.randn(*shape), jnp.dtype(dtype))
    k = jnp.asarray(rng.randn(*shape), jnp.dtype(dtype))
    v = jnp.asarray(rng.randn(*shape), jnp.dtype(dtype))

    def run(qv, kv, vv):
        from ..framework.core import Tensor
        out = A.flash_attention(Tensor(qv), Tensor(kv), Tensor(vv),
                                causal=causal)
        return jnp.sum(out._value.astype(jnp.float32) ** 2)

    timings = {}
    orig = (A._BLOCK_Q, A._BLOCK_K)
    seen_effective = set()
    for bq, bk in candidates:
        # time each EFFECTIVE tile once: _block clamps oversize prefs, so
        # (1024, 512) and (512, 512) are the same kernel at seq_len 512
        eff = (A._block(seq_len, bq), A._block(seq_len, bk))
        if eff in seen_effective:
            continue
        seen_effective.add(eff)
        bq, bk = eff
        A._BLOCK_Q, A._BLOCK_K = bq, bk
        try:
            g = jax.jit(jax.grad(run, argnums=(0, 1, 2)))
            jax.block_until_ready(g(q, k, v))          # compile
            t0 = time.perf_counter()
            for _ in range(steps):
                out = g(q, k, v)
            jax.block_until_ready(out)
            timings[(bq, bk)] = (time.perf_counter() - t0) / steps
        except Exception:
            continue
    A._BLOCK_Q, A._BLOCK_K = orig
    if timings and install:
        best = min(timings, key=timings.get)
        A._BLOCK_Q, A._BLOCK_K = best
        _state["tuned"][(batch, seq_len, num_heads, head_dim)] = best
    return timings


def tune_w4_matmul(S, K, N, candidates=(128, 256, 512), steps=5,
                   dtype="bfloat16"):
    """Time the int4 dequant-matmul per block_n on the attached device
    (decode shapes: S = decode batch, K = in-dim, N = out-dim). Returns
    {block_n: seconds}; pass the winner as w4_matmul(..., block_n=...).
    On CPU the interpret path runs — tune on the device you serve on."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..ops.w4_matmul import quantize_w4, w4_matmul

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(S, K), jnp.dtype(dtype))
    packed, scale = quantize_w4(rng.randn(K, N).astype("float32"))
    timings = {}
    for bn in candidates:
        if N % bn:
            continue
        try:
            f = jax.jit(lambda xv, bn=bn: w4_matmul(xv, packed, scale,
                                                    K, block_n=bn))
            jax.block_until_ready(f(x))               # compile
            t0 = time.perf_counter()
            for _ in range(steps):
                out = f(x)
            jax.block_until_ready(out)
            timings[bn] = (time.perf_counter() - t0) / steps
        except Exception:
            continue
    return timings


class _CallableModule(types.ModuleType):
    """Back-compat: earlier releases exposed incubate.autotune as a bare
    function; calling the module forwards to set_config with a warning."""

    def __call__(self, config=None):
        warnings.warn(
            "calling paddle_tpu.incubate.autotune(config) is deprecated; "
            "use incubate.autotune.set_config(config)",
            DeprecationWarning, stacklevel=2)
        return set_config(config)


sys.modules[__name__].__class__ = _CallableModule
