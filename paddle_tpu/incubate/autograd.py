"""paddle_tpu.incubate.autograd — reference
python/paddle/incubate/autograd/__init__.py:14-17 (re-exports the functional
higher-order autograd surface: vjp, jvp, Jacobian, Hessian)."""
from ..autograd import Hessian, Jacobian, jvp, vjp  # noqa: F401

__all__ = ["vjp", "jvp", "Jacobian", "Hessian"]


def enable_prim():
    """Reference's primitive-op (prim2orig) switch; jax transforms are
    already composable primitives, so this is a parity no-op."""
    return None


def disable_prim():
    return None


def prim_enabled():
    return False
