"""Graph message passing + fused helper ops — reference
python/paddle/incubate/operators/{graph_send_recv,softmax_mask_fuse}.py.

graph_send_recv gathers source-node features along edges and
scatter-reduces them at destinations: on TPU this is take() + one XLA
scatter-reduce (segment op), fusing under jit.
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply_op

__all__ = ["graph_send_recv", "softmax_mask_fuse", "softmax_mask_fuse_upper_triangle"]

_POOLS = {"sum": jax.ops.segment_sum, "mean": None,
          "max": jax.ops.segment_max, "min": jax.ops.segment_min}


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    pool_type = pool_type.lower()
    if pool_type not in _POOLS:
        raise ValueError(f"pool_type must be one of {list(_POOLS)}, got {pool_type}")
    dst = dst_index._value if isinstance(dst_index, Tensor) else np.asarray(dst_index)
    n = int(out_size) if out_size is not None else (
        x.shape[0] if hasattr(x, "shape") else None)
    if out_size is None:
        # reference semantics: output has as many rows as x (node count)
        n = x.shape[0]

    def f(xv, si, di):
        gathered = jnp.take(xv, si, axis=0)
        if pool_type == "mean":
            s = jax.ops.segment_sum(gathered, di, num_segments=n)
            cnt = jax.ops.segment_sum(jnp.ones_like(di, xv.dtype), di, num_segments=n)
            return s / jnp.maximum(cnt.reshape((-1,) + (1,) * (xv.ndim - 1)), 1)
        out = _POOLS[pool_type](gathered, di, num_segments=n)
        if pool_type in ("max", "min"):
            # empty segments come back +/-inf from XLA; reference returns 0
            return jnp.where(jnp.isfinite(out), out, 0)
        return out
    return apply_op(f, x, src_index, dst_index)


def softmax_mask_fuse(x, mask, name=None):
    """Masked softmax (reference fused_softmax_mask CUDA op): mask is added
    to the logits before softmax — XLA fuses this chain into one kernel."""
    return apply_op(lambda v, m: jax.nn.softmax(
        v.astype(jnp.float32) + m.astype(jnp.float32), axis=-1).astype(v.dtype),
        x, mask)


def softmax_mask_fuse_upper_triangle(x):
    """Causal-masked softmax (reference fused_softmax_mask_upper_triangle)."""
    def f(v):
        L, S = v.shape[-2], v.shape[-1]
        mask = jnp.tril(jnp.ones((L, S), bool))
        logits = jnp.where(mask, v.astype(jnp.float32), -1e30)
        return jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return apply_op(f, x)
